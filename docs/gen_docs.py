"""API-docs build: pydoc HTML for every raft_tpu module.

The reference ships a Doxygen target (cpp/Doxyfile.in, cmake/doxygen.cmake,
`build.sh cppdocs`); this is its analog for the TPU build using only the
stdlib (pdoc/sphinx are not in the baked image).  Output: docs/html/.

Run via ./docs.sh (or: python docs/gen_docs.py).
"""

import importlib
import os
import pkgutil
import pydoc
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
OUT = os.path.join(REPO, "docs", "html")
sys.path.insert(0, REPO)

# the environment may pre-register an accelerator backend; docs must
# build hardware-free
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")


def iter_modules():
    import raft_tpu

    yield "raft_tpu"
    # onerror: walk_packages imports subpackages itself and re-raises
    # non-ImportErrors without a handler — a gated optional dep must
    # skip that subpackage, not abort the build
    for m in pkgutil.walk_packages(
            raft_tpu.__path__, prefix="raft_tpu.",
            onerror=lambda name: print(f"skip {name}", file=sys.stderr)):
        yield m.name


def main():
    os.makedirs(OUT, exist_ok=True)
    os.chdir(OUT)
    names = []
    for name in iter_modules():
        try:
            importlib.import_module(name)
        except Exception as e:  # pragma: no cover - gated optional deps
            print(f"skip {name}: {e}", file=sys.stderr)
            continue
        pydoc.writedoc(name)
        names.append(name)
    with open("index.html", "w") as f:
        f.write("<html><head><title>raft_tpu API</title></head><body>\n"
                "<h1>raft_tpu API documentation</h1>\n<ul>\n")
        for n in sorted(names):
            f.write(f'<li><a href="{n}.html">{n}</a></li>\n')
        f.write("</ul></body></html>\n")
    print(f"wrote {len(names)} module pages to {OUT}")
    return 0 if names else 1


if __name__ == "__main__":
    sys.exit(main())
