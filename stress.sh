#!/usr/bin/env bash
# Scale-stress suite — the tests too slow for every CI run (currently
# the 50k single-linkage; the 100k spectral partition dropped to ~10 s
# with the r5 single-jit Lanczos and moved into the DEFAULT suite,
# tests/test_scale_stress.py).  Opt-in, separate from run_tests.sh.
#
# `./stress.sh faults [N]` instead loops the comms resilience suite N
# times (default 10) with a rotating fault seed (RAFT_TPU_FAULT_SEED),
# shaking nondeterminism out of the retry/abort/recovery paths — the
# injection harness is fully seeded, so any failure reproduces with the
# printed seed.
#
# `./stress.sh chaos [N]` loops the serving chaos scenario N times
# (default 10) with a rotating seed: tools/loadgen.py --chaos injects
# seeded serve-seam faults plus a mid-run simulated device loss and
# asserts every submitted request resolves exactly once with a result
# or typed error (docs/FAULT_MODEL.md "Serving failure model"); a
# failure reproduces with the printed seed.
#
# `./stress.sh serve [N]` loops the serving-layer suite N times
# (default 10) with a rotating data/submit-order seed
# (RAFT_TPU_SERVE_SEED) — the concurrent-submitter tests (including
# test_serve_ann.py's insert/compaction-under-traffic interleavings,
# same `serve` marker) are the only genuinely nondeterministic
# scheduling in the library, so the loop is what shakes out
# batching/drain/compaction races; a failure reproduces with the
# printed seed.
set -euo pipefail
cd "$(dirname "$0")"
export XLA_FLAGS="${XLA_FLAGS:-} --xla_force_host_platform_device_count=8"
export RAFT_TPU_TEST_PLATFORM="${RAFT_TPU_TEST_PLATFORM:-cpu}"
if [[ "${1:-}" == "faults" ]]; then
    n="${2:-10}"
    for i in $(seq 1 "$n"); do
        echo "== faults stress $i/$n (RAFT_TPU_FAULT_SEED=$i) =="
        RAFT_TPU_FAULT_SEED="$i" python -m pytest tests/ -q -m faults
    done
    exit 0
fi
if [[ "${1:-}" == "chaos" ]]; then
    n="${2:-10}"
    for i in $(seq 1 "$n"); do
        echo "== serve chaos $i/$n (seed=$i) =="
        python tools/loadgen.py --chaos --seed "$i" --duration 3 \
            --concurrency 4 --index-rows 3000 --dim 16 --k 5 \
            --max-batch-rows 64 --max-wait-ms 1
        # every other round runs the SHARDED variant with a permanent
        # shard kill: recovery must re-partition over the survivors
        # with exactly-once resolution and exact post-heal results
        if (( i % 2 == 0 )); then
            echo "== serve chaos shard-kill $i/$n (seed=$i) =="
            python tools/loadgen.py --chaos --kill-shard --mesh 4 \
                --seed "$i" --duration 3 --concurrency 4 \
                --index-rows 3000 --dim 16 --k 5 \
                --max-batch-rows 64 --max-wait-ms 1
        fi
    done
    exit 0
fi
if [[ "${1:-}" == "serve" ]]; then
    n="${2:-10}"
    for i in $(seq 1 "$n"); do
        echo "== serve stress $i/$n (RAFT_TPU_SERVE_SEED=$i) =="
        RAFT_TPU_SERVE_SEED="$i" python -m pytest tests/ -q -m serve
    done
    exit 0
fi
exec python -m pytest tests/ -q -m slow "$@"
