#!/usr/bin/env bash
# Scale-stress suite — the tests too slow for every CI run (currently
# the 50k single-linkage; the 100k spectral partition dropped to ~10 s
# with the r5 single-jit Lanczos and moved into the DEFAULT suite,
# tests/test_scale_stress.py).  Opt-in, separate from run_tests.sh.
#
# `./stress.sh faults [N]` instead loops the comms resilience suite N
# times (default 10) with a rotating fault seed (RAFT_TPU_FAULT_SEED),
# shaking nondeterminism out of the retry/abort/recovery paths — the
# injection harness is fully seeded, so any failure reproduces with the
# printed seed.
#
# `./stress.sh chaos [N]` loops the serving chaos scenario N times
# (default 10) with a rotating seed: tools/loadgen.py --chaos injects
# seeded serve-seam faults plus a mid-run simulated device loss and
# asserts every submitted request resolves exactly once with a result
# or typed error (docs/FAULT_MODEL.md "Serving failure model"); a
# failure reproduces with the printed seed.  Every other round also
# runs the sharded shard-kill variant and the hedged-dispatch variant
# (--hedge-chaos: one replica straggles under a persistent Delay;
# hedges must fire and win with exactly-once resolution).
#
# `./stress.sh tenants [N]` loops the mixed-tenant traffic-shaping
# scenario N times with rotating seeds: closed-loop interactive
# clients + an open-loop bulk flood through weighted-fair admission;
# exits non-zero if any shed was untyped (missing retry_after_s).
#
# Tuning note: the bench-driven autotuner (tools/autotune.py,
# docs/TUNING.md) is deterministic best-of-N timing, not a stress
# scenario — its rot guard is the bench ladder's `autotune_smoke` rung
# and `./run_tests.sh --tuning`; loop those if a tuning flake is ever
# suspected (the sweep is seed-free by design: same cells, same
# candidates, winner = measured min).
#
# `./stress.sh serve [N]` loops the serving-layer suite N times
# (default 10) with a rotating data/submit-order seed
# (RAFT_TPU_SERVE_SEED) — the concurrent-submitter tests (including
# test_serve_ann.py's insert/compaction-under-traffic interleavings,
# same `serve` marker) are the only genuinely nondeterministic
# scheduling in the library, so the loop is what shakes out
# batching/drain/compaction races; a failure reproduces with the
# printed seed.
set -euo pipefail
cd "$(dirname "$0")"
export XLA_FLAGS="${XLA_FLAGS:-} --xla_force_host_platform_device_count=8"
export RAFT_TPU_TEST_PLATFORM="${RAFT_TPU_TEST_PLATFORM:-cpu}"
if [[ "${1:-}" == "faults" ]]; then
    n="${2:-10}"
    for i in $(seq 1 "$n"); do
        echo "== faults stress $i/$n (RAFT_TPU_FAULT_SEED=$i) =="
        RAFT_TPU_FAULT_SEED="$i" python -m pytest tests/ -q -m faults
    done
    exit 0
fi
if [[ "${1:-}" == "chaos" ]]; then
    n="${2:-10}"
    for i in $(seq 1 "$n"); do
        echo "== serve chaos $i/$n (seed=$i) =="
        python tools/loadgen.py --chaos --seed "$i" --duration 3 \
            --concurrency 4 --index-rows 3000 --dim 16 --k 5 \
            --max-batch-rows 64 --max-wait-ms 1
        # every third round runs the chaos scenario against the
        # OUT-OF-CORE ANN tier (host-streamed slot store under a 1/4
        # device budget): breaker/recovery/exactly-once must hold while
        # tiles stream (docs/SERVING.md "Out-of-core serving")
        if (( i % 3 == 0 )); then
            echo "== serve chaos ooc $i/$n (seed=$i) =="
            python tools/loadgen.py --chaos --service ann --ooc \
                --clusters 32 --nlist 64 --seed "$i" --duration 3 \
                --concurrency 3 --index-rows 8000 --dim 16 --k 5 \
                --max-batch-rows 64 --max-wait-ms 1
        fi
        # every round also runs the crash-restart durability arm
        # (docs/PERSISTENCE.md): simulated process death mid-run (no
        # final snapshot), rebuild from the persist dir — zero
        # acknowledged-insert loss, bit-identical post-restore search,
        # typed-only errors, 0 post-warmup compiles after restore
        echo "== serve chaos crash-restart $i/$n (seed=$i) =="
        python tools/loadgen.py --crash-restart --service ann \
            --seed "$i" --duration 3 --concurrency 3 \
            --index-rows 4000 --dim 16 --k 5 --nlist 32 \
            --max-batch-rows 64 --max-wait-ms 1
        # every other round runs the SHARDED variant with a permanent
        # shard kill: recovery must re-partition over the survivors
        # with exactly-once resolution and exact post-heal results
        if (( i % 2 == 0 )); then
            echo "== serve chaos shard-kill $i/$n (seed=$i) =="
            python tools/loadgen.py --chaos --kill-shard --mesh 4 \
                --seed "$i" --duration 3 --concurrency 4 \
                --index-rows 3000 --dim 16 --k 5 \
                --max-batch-rows 64 --max-wait-ms 1
        else
            # hedged-dispatch variant: one replica straggles under a
            # persistent Delay; hedges fire+win, losers cancel, every
            # admitted request resolves exactly once, 0 compiles
            echo "== serve chaos hedge $i/$n (seed=$i) =="
            python tools/loadgen.py --hedge-chaos --replicas 2 \
                --hedge-ms 60 --seed "$i" --duration 3 \
                --concurrency 4 --index-rows 3000 --dim 16 --k 5 \
                --max-batch-rows 64 --max-wait-ms 1
        fi
    done
    exit 0
fi
if [[ "${1:-}" == "ops" ]]; then
    # ops-scrape-under-load loop (docs/OBSERVABILITY.md "Ops plane"):
    # an embedded ops plane scraped at 1 Hz mid-load; every scrape
    # must succeed, the scraped window must perform 0 post-warmup
    # compiles, and QPS must stay within noise of the unscraped
    # baseline — plus the concurrent-scrape test suite (`ops` marker)
    # shaking handler/worker interleavings with a rotating seed
    n="${2:-10}"
    for i in $(seq 1 "$n"); do
        echo "== ops scrape stress $i/$n (seed=$i) =="
        python tools/loadgen.py --ops-port 0 --seed "$i" --duration 4 \
            --concurrency 4 --index-rows 3000 --dim 16 --k 5 \
            --max-batch-rows 64 --max-wait-ms 1
        RAFT_TPU_SERVE_SEED="$i" python -m pytest tests/ -q -m ops
    done
    exit 0
fi
if [[ "${1:-}" == "tenants" ]]; then
    n="${2:-10}"
    for i in $(seq 1 "$n"); do
        echo "== mixed-tenant stress $i/$n (seed=$i) =="
        python tools/loadgen.py --tenants --seed "$i" --duration 3 \
            --concurrency 4 --bulk-qps 150 --bulk-rows 16 \
            --index-rows 5000 --dim 32 --k 10 --max-batch-rows 64 \
            --max-wait-ms 1 --queue-cap 64
    done
    exit 0
fi
if [[ "${1:-}" == "fleet" ]]; then
    # fleet chaos loop (docs/FAULT_MODEL.md "Fleet fault domains"):
    # a router + N worker PROCESSES under concurrent search+insert
    # traffic while a seeded ChaosSchedule injects process faults
    # (SIGKILL mid-WAL-append, hang, slow rejoin, dropped/garbled
    # frames, fsync stall).  Assertions per round: zero acknowledged
    # rows lost across the kill, every admitted request gets exactly
    # one typed terminal flight event, no untyped errors, the router
    # never crashes.  A failure reproduces with the printed seed.
    n="${2:-10}"
    for i in $(seq 1 "$n"); do
        echo "== fleet chaos $i/$n (seed=$i) =="
        python tools/loadgen.py --fleet --fleet-workers 2 \
            --seed "$i" --duration 6 --concurrency 4 \
            --index-rows 2000 --dim 16 --k 5 --nlist 16 \
            --max-batch-rows 64 --max-wait-ms 1
    done
    exit 0
fi
if [[ "${1:-}" == "serve" ]]; then
    n="${2:-10}"
    for i in $(seq 1 "$n"); do
        echo "== serve stress $i/$n (RAFT_TPU_SERVE_SEED=$i) =="
        RAFT_TPU_SERVE_SEED="$i" python -m pytest tests/ -q -m serve
    done
    exit 0
fi
exec python -m pytest tests/ -q -m slow "$@"
