#!/usr/bin/env bash
# Scale-stress suite (50k single-linkage, 100k spectral partition) —
# minutes, not seconds, so opt-in and separate from run_tests.sh.
set -euo pipefail
cd "$(dirname "$0")"
export XLA_FLAGS="${XLA_FLAGS:-} --xla_force_host_platform_device_count=8"
export RAFT_TPU_TEST_PLATFORM="${RAFT_TPU_TEST_PLATFORM:-cpu}"
exec python -m pytest tests/ -q -m slow "$@"
