#!/usr/bin/env bash
# Scale-stress suite — the tests too slow for every CI run (currently
# the 50k single-linkage; the 100k spectral partition dropped to ~10 s
# with the r5 single-jit Lanczos and moved into the DEFAULT suite,
# tests/test_scale_stress.py).  Opt-in, separate from run_tests.sh.
set -euo pipefail
cd "$(dirname "$0")"
export XLA_FLAGS="${XLA_FLAGS:-} --xla_force_host_platform_device_count=8"
export RAFT_TPU_TEST_PLATFORM="${RAFT_TPU_TEST_PLATFORM:-cpu}"
exec python -m pytest tests/ -q -m slow "$@"
