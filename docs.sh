#!/usr/bin/env bash
# API-docs build target (reference analog: `build.sh cppdocs` ->
# cmake/doxygen.cmake).  Writes HTML to docs/html/.
set -euo pipefail
cd "$(dirname "$0")"
exec python docs/gen_docs.py "$@"
