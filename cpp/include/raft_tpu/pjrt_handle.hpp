// raft_tpu::pjrt::Handle — the C++-consumable resource handle over the
// PJRT C API.
//
// Reference role: raft::handle_t (cpp/include/raft/handle.hpp:49) is the
// C++ entry point every reference primitive takes first; C++ consumers
// (cuML, cuGraph) own one and thread it everywhere.  The TPU analog of
// the *device runtime* behind that handle is a PJRT plugin (libtpu.so or
// any other PJRT_Api provider), and the stable, header-only,
// ABI-versioned way for C++ code to own it is the PJRT C API
// (cpp/third_party/xla/pjrt/c/pjrt_c_api.h, vendored from openxla/xla,
// Apache-2.0).
//
// Scope (deliberate): plugin loading, API-version negotiation, client
// lifecycle, platform/device introspection, and error plumbing — the
// resource-management slice of handle.hpp (streams/pools/comms live in
// the Python/JAX layer where XLA owns scheduling; see SURVEY.md §7.1
// amendment).  Compilation/execution through this handle is possible via
// the same PJRT_Api table but out of scope until a C++ consumer needs it.
//
// Threading: the PJRT C API is thread-safe; this wrapper adds no locks.
// Error model: every failing PJRT call surfaces as raft_tpu::pjrt::Error
// carrying the plugin's human-readable message (the analog of
// raft::exception / RAFT_EXPECTS in cpp/include/raft/error.hpp).

#pragma once

#include <memory>
#include <stdexcept>
#include <string>
#include <vector>

namespace raft_tpu {
namespace pjrt {

class Error : public std::runtime_error {
 public:
  explicit Error(const std::string& what) : std::runtime_error(what) {}
};

struct ApiVersion {
  int major_version = 0;
  int minor_version = 0;
};

struct DeviceInfo {
  int id = 0;
  std::string kind;         // e.g. "TPU v5 lite"
  std::string debug_string;
  bool addressable = false;
};

class Handle {
 public:
  // dlopens the plugin, resolves GetPjrtApi, runs PJRT_Plugin_Initialize,
  // and records the API version.  Does NOT create a client (backend/device
  // init is the expensive, environment-dependent step — keep construction
  // cheap the way handle_t construction is).
  explicit Handle(const std::string& plugin_path);
  ~Handle();
  Handle(const Handle&) = delete;
  Handle& operator=(const Handle&) = delete;

  ApiVersion api_version() const;
  const std::string& plugin_path() const;

  // Creates the PJRT client (device bring-up).  Throws Error with the
  // plugin's message when the environment has no device.
  void create_client();
  bool has_client() const;

  // Introspection (require a live client).
  std::string platform_name() const;
  std::string platform_version() const;
  std::vector<DeviceInfo> devices() const;

 private:
  struct Impl;
  std::unique_ptr<Impl> impl_;
};

}  // namespace pjrt
}  // namespace raft_tpu
