/*
 * Host memory arena: aligned allocations with pooling.
 *
 * The role of the reference's mr/ layer (base_allocator mr/allocator.hpp:35,
 * buffer_base mr/buffer_base.hpp:39) for the TPU build's host side: staging
 * buffers handed to PJRT host-to-device transfers want 64-byte alignment
 * and reuse; free blocks are kept in power-of-two size classes.
 */
#pragma once

#include <cstddef>
#include <cstdlib>
#include <map>
#include <mutex>
#include <vector>

#include "error.hpp"

namespace raft_tpu {

class host_arena {
 public:
  static constexpr std::size_t kAlignment = 64;

  void* allocate(std::size_t n)
  {
    if (n == 0) n = 1;
    std::size_t cls = size_class(n);
    {
      std::lock_guard<std::mutex> lock(mu_);
      auto& pool = free_[cls];
      if (!pool.empty()) {
        void* p = pool.back();
        pool.pop_back();
        pooled_size_.erase(p);
        size_of_[p] = cls;
        in_use_ += cls;
        return p;
      }
    }
    void* p = nullptr;
    if (posix_memalign(&p, kAlignment, cls) != 0 || p == nullptr) {
      RAFT_TPU_FAIL("host_arena: allocation of %zu bytes failed", cls);
    }
    std::lock_guard<std::mutex> lock(mu_);
    total_ += cls;
    in_use_ += cls;
    size_of_[p] = cls;
    return p;
  }

  /** Return a block to the pool.  Throws on unknown pointers AND on
   * double-free: a live block is tracked in size_of_, a pooled one only
   * in pooled_size_, so freeing twice cannot re-pool the same block. */
  void deallocate(void* p)
  {
    if (p == nullptr) return;
    std::lock_guard<std::mutex> lock(mu_);
    auto it = size_of_.find(p);
    RAFT_TPU_EXPECTS(it != size_of_.end(),
                     "host_arena: deallocate of unknown or already-freed "
                     "pointer");
    std::size_t cls = it->second;
    size_of_.erase(it);
    pooled_size_[p] = cls;
    in_use_ -= cls;
    free_[cls].push_back(p);
  }

  /** Release all pooled blocks back to the OS. */
  void trim()
  {
    std::lock_guard<std::mutex> lock(mu_);
    for (auto& kv : free_) {
      for (void* p : kv.second) {
        total_ -= kv.first;
        pooled_size_.erase(p);
        std::free(p);
      }
      kv.second.clear();
    }
  }

  std::size_t total_bytes() const { return total_; }
  std::size_t in_use_bytes() const { return in_use_; }

  ~host_arena()
  {
    for (auto& kv : size_of_) std::free(kv.first);
    for (auto& kv : pooled_size_) std::free(kv.first);
  }

 private:
  static std::size_t size_class(std::size_t n)
  {
    std::size_t c = kAlignment;
    while (c < n) c <<= 1;
    return c;
  }

  std::mutex mu_;
  std::map<std::size_t, std::vector<void*>> free_;
  std::map<void*, std::size_t> size_of_;       // live blocks
  std::map<void*, std::size_t> pooled_size_;   // pooled (freed) blocks
  std::size_t total_ = 0;
  std::size_t in_use_ = 0;
};

}  // namespace raft_tpu
