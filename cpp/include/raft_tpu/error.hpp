/*
 * Host-side error types for the raft_tpu native runtime.
 *
 * Mirrors the reference's raft::exception with collected stack trace and
 * the THROW / RAFT_EXPECTS / RAFT_FAIL macro family
 * (reference: cpp/include/raft/error.hpp:28,94-148) for the TPU build's
 * C++ host layer.  Device errors surface through XLA/PJRT on the Python
 * side; this covers the native host components (arena, packers).
 */
#pragma once

#include <cstdarg>
#include <cstdio>
#include <cstdlib>
#include <exception>
#include <execinfo.h>
#include <sstream>
#include <string>

namespace raft_tpu {

/** Exception carrying a message and a collected call stack. */
class exception : public std::exception {
 public:
  explicit exception(std::string const& message) : msg_(message)
  {
    collect_call_stack();
  }

  char const* what() const noexcept override { return msg_.c_str(); }

 private:
  std::string msg_;

  /** Append the current call stack to the message (reference
   * error.hpp:57-87 collectCallStack). */
  void collect_call_stack()
  {
#ifdef __GNUC__
    constexpr int kMaxStackDepth = 64;
    void* stack[kMaxStackDepth];
    int depth = backtrace(stack, kMaxStackDepth);
    std::ostringstream oss;
    oss << std::endl << "Obtained " << depth << " stack frames" << std::endl;
    char** strings = backtrace_symbols(stack, depth);
    if (strings == nullptr) return;
    for (int i = 0; i < depth; ++i) {
      oss << "#" << i << " in " << strings[i] << std::endl;
    }
    free(strings);
    msg_ += oss.str();
#endif
  }
};

}  // namespace raft_tpu

/** Macro family (reference error.hpp:94-148). */
#define RAFT_TPU_STRINGIFY_DETAIL(x) #x
#define RAFT_TPU_STRINGIFY(x) RAFT_TPU_STRINGIFY_DETAIL(x)

#define RAFT_TPU_THROW(fmt, ...)                                          \
  do {                                                                    \
    char msg[2048];                                                       \
    std::snprintf(msg, sizeof(msg),                                       \
                  "exception occurred! file=" __FILE__                    \
                  " line=" RAFT_TPU_STRINGIFY(__LINE__) ": " fmt,         \
                  ##__VA_ARGS__);                                         \
    throw raft_tpu::exception(msg);                                       \
  } while (0)

#define RAFT_TPU_EXPECTS(cond, fmt, ...)                                  \
  do {                                                                    \
    if (!(cond)) { RAFT_TPU_THROW(fmt, ##__VA_ARGS__); }                  \
  } while (0)

#define RAFT_TPU_FAIL(fmt, ...) RAFT_TPU_THROW(fmt, ##__VA_ARGS__)
