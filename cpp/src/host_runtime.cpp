/*
 * raft_tpu native host runtime: C ABI exported to Python via ctypes.
 *
 * The TPU build's analog of the reference's precompiled native layer
 * (cpp/src/ → libraft_distance.so / libraft_nn.so): device math lives in
 * XLA/Pallas, so what earns native code on a TPU host is the genuinely
 * sequential host-side work the Python layer would otherwise do in
 * interpreted loops:
 *
 *  - union-find dendrogram construction (reference build_dendrogram_host,
 *    sparse/hierarchy/detail/agglomerative.cuh:101) and flattened-cluster
 *    extraction (:237);
 *  - inverted-list packing for the IVF index builders (the role of FAISS's
 *    list assignment);
 *  - ball-cover group packing sorted by owner distance
 *    (reference detail/ball_cover.cuh:113-191 sort-by-landmark stage);
 *  - an aligned pooling host arena (reference mr/ layer).
 *
 * All functions use a plain C ABI (int64/double buffers the caller owns) so
 * the Python side binds with ctypes — no pybind11 dependency needed.
 */

#include <algorithm>
#include <cstdint>
#include <cstring>
#include <numeric>
#include <vector>

#include "raft_tpu/arena.hpp"
#include "raft_tpu/error.hpp"

extern "C" {

// ------------------------------------------------------------------ //
// version / arena
// ------------------------------------------------------------------ //
const char* rt_version() { return "raft_tpu_host 0.1.0"; }

static raft_tpu::host_arena g_arena;

void* rt_alloc(std::size_t n)
{
  try {
    return g_arena.allocate(n);
  } catch (...) {
    return nullptr;
  }
}

/** 0 on success, 1 on unknown pointer / double-free — exceptions must not
 * cross the C ABI into ctypes (std::terminate otherwise). */
int rt_free(void* p)
{
  try {
    g_arena.deallocate(p);
    return 0;
  } catch (...) {
    return 1;
  }
}

void rt_trim()
{
  try {
    g_arena.trim();
  } catch (...) {
  }
}
std::size_t rt_arena_total() { return g_arena.total_bytes(); }
std::size_t rt_arena_in_use() { return g_arena.in_use_bytes(); }

// ------------------------------------------------------------------ //
// union-find dendrogram (agglomerative.cuh:101 analog)
// ------------------------------------------------------------------ //
namespace {

struct UnionFind {
  std::vector<int64_t> parent;
  std::vector<int64_t> size;
  int64_t next_id;

  explicit UnionFind(int64_t n)
    : parent(2 * n - 1, -1), size(2 * n - 1, 0), next_id(n)
  {
    std::fill(size.begin(), size.begin() + n, 1);
  }

  int64_t find(int64_t x)
  {
    int64_t root = x;
    while (parent[root] != -1) root = parent[root];
    while (parent[x] != -1) {  // path compression
      int64_t next = parent[x];
      parent[x] = root;
      x = next;
    }
    return root;
  }

  void unite(int64_t a, int64_t b)
  {
    parent[a] = next_id;
    parent[b] = next_id;
    size[next_id] = size[a] + size[b];
    ++next_id;
  }
};

}  // namespace

/**
 * Build a scipy-convention dendrogram from m-1 MST edges.
 * Inputs: src/dst (m-1), weights (m-1), m.  The function sorts by weight
 * (stable) internally.  Outputs (caller-allocated): children (2*(m-1)),
 * out_delta (m-1), out_size (m-1).  Returns 0 on success.
 */
int rt_build_dendrogram(const int64_t* src, const int64_t* dst,
                        const double* weights, int64_t m,
                        int64_t* children, double* out_delta,
                        int64_t* out_size)
{
  if (m < 2) return 1;
  int64_t n_edges = m - 1;
  for (int64_t e = 0; e < n_edges; ++e) {  // leaf ids must be in [0, m)
    if (src[e] < 0 || src[e] >= m || dst[e] < 0 || dst[e] >= m) return 1;
  }
  std::vector<int64_t> order(n_edges);
  std::iota(order.begin(), order.end(), 0);
  std::stable_sort(order.begin(), order.end(),
                   [&](int64_t a, int64_t b) { return weights[a] < weights[b]; });

  UnionFind uf(m);
  for (int64_t i = 0; i < n_edges; ++i) {
    int64_t e = order[i];
    int64_t aa = uf.find(src[e]);
    int64_t bb = uf.find(dst[e]);
    children[2 * i] = aa;
    children[2 * i + 1] = bb;
    out_delta[i] = weights[e];
    out_size[i] = uf.size[aa] + uf.size[bb];
    uf.unite(aa, bb);
  }
  return 0;
}

/**
 * Cut a dendrogram into n_clusters monotonic labels
 * (agglomerative.cuh:237 analog).  labels: caller-allocated (n_leaves).
 */
int rt_extract_clusters(const int64_t* children, int64_t n_clusters,
                        int64_t n_leaves, int64_t* labels)
{
  if (n_leaves < 1 || n_clusters < 1 || n_clusters > n_leaves) return 1;
  if (n_clusters == 1) {
    std::fill(labels, labels + n_leaves, 0);
    return 0;
  }
  std::vector<int64_t> parent(2 * n_leaves - 1, -1);
  for (int64_t i = 0; i < n_leaves - n_clusters; ++i) {
    int64_t nid = n_leaves + i;
    parent[children[2 * i]] = nid;
    parent[children[2 * i + 1]] = nid;
  }
  // root per leaf, then monotonic relabel by first appearance of sorted
  // root ids (matches np.unique(..., return_inverse=True))
  std::vector<int64_t> roots(n_leaves);
  for (int64_t i = 0; i < n_leaves; ++i) {
    int64_t x = roots[i] = [&] {
      int64_t r = i;
      while (parent[r] != -1) r = parent[r];
      return r;
    }();
    (void)x;
  }
  std::vector<int64_t> uniq(roots);
  std::sort(uniq.begin(), uniq.end());
  uniq.erase(std::unique(uniq.begin(), uniq.end()), uniq.end());
  for (int64_t i = 0; i < n_leaves; ++i) {
    labels[i] = std::lower_bound(uniq.begin(), uniq.end(), roots[i]) -
                uniq.begin();
  }
  return 0;
}

// ------------------------------------------------------------------ //
// inverted-list packing (IVF builders)
// ------------------------------------------------------------------ //
/**
 * Pack per-row list assignments into a padded (nlist, max_len) table of
 * row ids (-1 pad).  max_len == 0 → computed from the largest list and
 * written back through *out_max_len.  table must hold nlist * max_len
 * entries (call once with max_len==0 and table==nullptr to size it).
 */
int rt_build_lists(const int64_t* labels, int64_t m, int64_t nlist,
                   int64_t* table, int64_t max_len, int64_t* out_max_len)
{
  std::vector<int64_t> counts(nlist, 0);
  for (int64_t i = 0; i < m; ++i) {
    if (labels[i] < 0 || labels[i] >= nlist) return 1;
    ++counts[labels[i]];
  }
  int64_t widest = *std::max_element(counts.begin(), counts.end());
  if (widest < 1) widest = 1;
  if (out_max_len != nullptr) *out_max_len = (max_len == 0) ? widest : max_len;
  if (table == nullptr) return 0;
  int64_t ml = (max_len == 0) ? widest : max_len;

  std::fill(table, table + nlist * ml, int64_t{-1});
  std::vector<int64_t> fill(nlist, 0);
  for (int64_t i = 0; i < m; ++i) {
    int64_t l = labels[i];
    if (fill[l] < ml) table[l * ml + fill[l]++] = i;
  }
  return 0;
}

/**
 * Ball-cover group packing: members of each landmark ordered by descending
 * owner distance (reference sorts 1-NN members by distance,
 * detail/ball_cover.cuh:113-191).  groups: (L, gmax) int64, -1 pad;
 * radius: (L,) double out.
 */
int rt_pack_groups(const int64_t* owner, const double* dist, int64_t m,
                   int64_t L, int64_t* groups, int64_t gmax, double* radius)
{
  std::vector<int64_t> order(m);
  std::iota(order.begin(), order.end(), 0);
  std::stable_sort(order.begin(), order.end(),
                   [&](int64_t a, int64_t b) { return dist[a] > dist[b]; });
  std::fill(groups, groups + L * gmax, int64_t{-1});
  std::fill(radius, radius + L, 0.0);
  std::vector<int64_t> fill(L, 0);
  for (int64_t idx : order) {
    int64_t l = owner[idx];
    if (l < 0 || l >= L) return 1;
    if (fill[l] < gmax) groups[l * gmax + fill[l]++] = idx;
    radius[l] = std::max(radius[l], dist[idx]);
  }
  return 0;
}

}  // extern "C"
