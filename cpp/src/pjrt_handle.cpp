// Implementation of raft_tpu::pjrt::Handle (see pjrt_handle.hpp) plus a
// plain C ABI for ctypes consumers (raft_tpu/core/pjrt.py) — the same
// binding style as host_runtime.cpp (the reference's Cython layer role,
// python/raft/common/handle.pyx).

#include "raft_tpu/pjrt_handle.hpp"

#include <dlfcn.h>

#include <cstddef>
#include <cstdio>
#include <cstring>
#include <mutex>
#include <set>

#include "xla/pjrt/c/pjrt_c_api.h"

namespace raft_tpu {
namespace pjrt {

namespace {

// The PJRT_Api is an append-only table gated by struct_size: a plugin
// built against an older API allocates a SHORTER struct, so reading a
// function pointer past its reported struct_size is undefined behavior
// (the header: callers must check struct_size to learn which fields
// exist).  Every table access after construction goes through this
// guard; the error-path functions (the first three table entries,
// present since API 0.1) are exempt so error rendering can't throw.
template <typename Fn>
Fn* require_fn(const PJRT_Api* api, size_t offset, Fn* PJRT_Api::*member,
               const char* name) {
  if (offset + sizeof(Fn*) > api->struct_size) {
    throw Error(std::string("plugin PJRT_Api (struct_size=") +
                std::to_string(api->struct_size) +
                ") predates required function " + name);
  }
  Fn* fn = api->*member;
  if (fn == nullptr) {
    throw Error(std::string("plugin PJRT_Api exports null ") + name);
  }
  return fn;
}

#define RT_PJRT_FN(api, Name) \
  require_fn((api), offsetof(PJRT_Api, Name), &PJRT_Api::Name, #Name)

// Render and free a PJRT_Error.  Returns empty string when err is null.
std::string consume_error(const PJRT_Api* api, PJRT_Error* err) {
  if (err == nullptr) return {};
  PJRT_Error_Message_Args msg;
  msg.struct_size = PJRT_Error_Message_Args_STRUCT_SIZE;
  msg.extension_start = nullptr;
  msg.error = err;
  api->PJRT_Error_Message(&msg);
  std::string out(msg.message, msg.message_size);
  PJRT_Error_Destroy_Args destroy;
  destroy.struct_size = PJRT_Error_Destroy_Args_STRUCT_SIZE;
  destroy.extension_start = nullptr;
  destroy.error = err;
  api->PJRT_Error_Destroy(&destroy);
  return out;
}

void check(const PJRT_Api* api, PJRT_Error* err, const char* what) {
  if (err == nullptr) return;
  throw Error(std::string(what) + ": " + consume_error(api, err));
}

}  // namespace

struct Handle::Impl {
  void* dso = nullptr;
  const PJRT_Api* api = nullptr;
  PJRT_Client* client = nullptr;
  std::string path;

  ~Impl() {
    if (api != nullptr && client != nullptr) {
      PJRT_Client_Destroy_Args args;
      args.struct_size = PJRT_Client_Destroy_Args_STRUCT_SIZE;
      args.extension_start = nullptr;
      args.client = client;
      consume_error(api, RT_PJRT_FN(api, PJRT_Client_Destroy)(&args));
    }
    // The dso is intentionally never dlclosed: PJRT plugins register
    // global state (XLA flags, runtime singletons) that does not survive
    // unload; leaking the library handle at process end is the correct
    // lifetime (same policy as jax's xla_bridge).
  }
};

Handle::Handle(const std::string& plugin_path) : impl_(new Impl) {
  impl_->path = plugin_path;
  impl_->dso = dlopen(plugin_path.c_str(), RTLD_NOW | RTLD_LOCAL);
  if (impl_->dso == nullptr) {
    throw Error(std::string("dlopen failed: ") + dlerror());
  }
  using GetPjrtApiFn = const PJRT_Api* (*)();
  auto get_api =
      reinterpret_cast<GetPjrtApiFn>(dlsym(impl_->dso, "GetPjrtApi"));
  if (get_api == nullptr) {
    throw Error(plugin_path + " exports no GetPjrtApi symbol");
  }
  impl_->api = get_api();
  if (impl_->api == nullptr) {
    throw Error("GetPjrtApi returned null");
  }
  // "One-time plugin setup" (pjrt_c_api.h): a second Handle over the
  // same plugin (dlopen refcounts to the same PJRT_Api) must not
  // re-initialize global state.  Keyed by the api pointer, which is
  // stable per loaded plugin.
  static std::mutex init_mu;
  static std::set<const PJRT_Api*>* initialized =
      new std::set<const PJRT_Api*>();
  std::lock_guard<std::mutex> lock(init_mu);
  if (initialized->count(impl_->api) == 0) {
    PJRT_Plugin_Initialize_Args init;
    init.struct_size = PJRT_Plugin_Initialize_Args_STRUCT_SIZE;
    init.extension_start = nullptr;
    check(impl_->api, RT_PJRT_FN(impl_->api, PJRT_Plugin_Initialize)(&init),
          "PJRT_Plugin_Initialize");
    initialized->insert(impl_->api);  // only a SUCCESSFUL init is final
  }
}

Handle::~Handle() = default;

ApiVersion Handle::api_version() const {
  ApiVersion v;
  v.major_version = impl_->api->pjrt_api_version.major_version;
  v.minor_version = impl_->api->pjrt_api_version.minor_version;
  return v;
}

const std::string& Handle::plugin_path() const { return impl_->path; }

void Handle::create_client() {
  if (impl_->client != nullptr) return;
  PJRT_Client_Create_Args args;
  std::memset(&args, 0, sizeof(args));
  args.struct_size = PJRT_Client_Create_Args_STRUCT_SIZE;
  check(impl_->api, RT_PJRT_FN(impl_->api, PJRT_Client_Create)(&args),
        "PJRT_Client_Create");
  impl_->client = args.client;
}

bool Handle::has_client() const { return impl_->client != nullptr; }

std::string Handle::platform_name() const {
  if (!has_client()) throw Error("platform_name: no client");
  PJRT_Client_PlatformName_Args args;
  args.struct_size = PJRT_Client_PlatformName_Args_STRUCT_SIZE;
  args.extension_start = nullptr;
  args.client = impl_->client;
  check(impl_->api, RT_PJRT_FN(impl_->api, PJRT_Client_PlatformName)(&args),
        "PJRT_Client_PlatformName");
  return std::string(args.platform_name, args.platform_name_size);
}

std::string Handle::platform_version() const {
  if (!has_client()) throw Error("platform_version: no client");
  PJRT_Client_PlatformVersion_Args args;
  args.struct_size = PJRT_Client_PlatformVersion_Args_STRUCT_SIZE;
  args.extension_start = nullptr;
  args.client = impl_->client;
  check(impl_->api, RT_PJRT_FN(impl_->api, PJRT_Client_PlatformVersion)(&args),
        "PJRT_Client_PlatformVersion");
  return std::string(args.platform_version, args.platform_version_size);
}

std::vector<DeviceInfo> Handle::devices() const {
  if (!has_client()) throw Error("devices: no client");
  PJRT_Client_Devices_Args args;
  args.struct_size = PJRT_Client_Devices_Args_STRUCT_SIZE;
  args.extension_start = nullptr;
  args.client = impl_->client;
  check(impl_->api, RT_PJRT_FN(impl_->api, PJRT_Client_Devices)(&args),
        "PJRT_Client_Devices");
  std::vector<DeviceInfo> out;
  out.reserve(args.num_devices);
  for (size_t i = 0; i < args.num_devices; ++i) {
    DeviceInfo info;
    PJRT_Device_GetDescription_Args desc;
    desc.struct_size = PJRT_Device_GetDescription_Args_STRUCT_SIZE;
    desc.extension_start = nullptr;
    desc.device = args.devices[i];
    check(impl_->api, RT_PJRT_FN(impl_->api, PJRT_Device_GetDescription)(&desc),
          "PJRT_Device_GetDescription");
    // global PJRT device id, NOT the enumeration index: on a multi-host
    // slice PJRT_Client_Devices interleaves remote devices and ids are
    // globally unique across hosts
    PJRT_DeviceDescription_Id_Args id_args;
    id_args.struct_size = PJRT_DeviceDescription_Id_Args_STRUCT_SIZE;
    id_args.extension_start = nullptr;
    id_args.device_description = desc.device_description;
    check(impl_->api, RT_PJRT_FN(impl_->api, PJRT_DeviceDescription_Id)(&id_args),
          "PJRT_DeviceDescription_Id");
    info.id = id_args.id;
    PJRT_DeviceDescription_Kind_Args kind;
    kind.struct_size = PJRT_DeviceDescription_Kind_Args_STRUCT_SIZE;
    kind.extension_start = nullptr;
    kind.device_description = desc.device_description;
    check(impl_->api, RT_PJRT_FN(impl_->api, PJRT_DeviceDescription_Kind)(&kind),
          "PJRT_DeviceDescription_Kind");
    info.kind.assign(kind.device_kind, kind.device_kind_size);
    PJRT_DeviceDescription_DebugString_Args dbg;
    dbg.struct_size = PJRT_DeviceDescription_DebugString_Args_STRUCT_SIZE;
    dbg.extension_start = nullptr;
    dbg.device_description = desc.device_description;
    check(impl_->api, RT_PJRT_FN(impl_->api, PJRT_DeviceDescription_DebugString)(&dbg),
          "PJRT_DeviceDescription_DebugString");
    info.debug_string.assign(dbg.debug_string, dbg.debug_string_size);
    PJRT_Device_IsAddressable_Args addr;
    addr.struct_size = PJRT_Device_IsAddressable_Args_STRUCT_SIZE;
    addr.extension_start = nullptr;
    addr.device = args.devices[i];
    check(impl_->api, RT_PJRT_FN(impl_->api, PJRT_Device_IsAddressable)(&addr),
          "PJRT_Device_IsAddressable");
    info.addressable = addr.is_addressable;
    out.push_back(std::move(info));
  }
  return out;
}

}  // namespace pjrt
}  // namespace raft_tpu

// ---------------------------------------------------------------------------
// C ABI for ctypes (raft_tpu/core/pjrt.py).  Every function writes a
// result or error message into (out, out_len) and returns 0 on success.
// ---------------------------------------------------------------------------

namespace {

// 0 = written whole; 2 = truncated (caller's buffer too small) — a
// truncated JSON payload must NOT be reported as success, or the Python
// side json.loads()es garbage.
int fill(char* out, size_t out_len, const std::string& s) {
  if (out == nullptr || out_len == 0) return 1;
  std::snprintf(out, out_len, "%s", s.c_str());
  if (s.size() + 1 > out_len) {
    std::snprintf(out, out_len, "result truncated: needs %zu bytes",
                  s.size() + 1);
    return 2;
  }
  return 0;
}

// JSON string escaping for plugin-reported free-form strings (platform
// name/version, device kind): without it a quote or backslash in a
// plugin string breaks json.loads on the Python side.
std::string jstr(const std::string& s) {
  std::string out = "\"";
  for (char c : s) {
    switch (c) {
      case '"': out += "\\\""; break;
      case '\\': out += "\\\\"; break;
      case '\n': out += "\\n"; break;
      case '\r': out += "\\r"; break;
      case '\t': out += "\\t"; break;
      default:
        if (static_cast<unsigned char>(c) < 0x20) {
          char buf[8];
          std::snprintf(buf, sizeof(buf), "\\u%04x", c);
          out += buf;
        } else {
          out += c;
        }
    }
  }
  out += "\"";
  return out;
}

}  // namespace

extern "C" {

// API-version probe: dlopen + GetPjrtApi + Plugin_Initialize only — no
// device bring-up, safe on machines without the accelerator.
int raft_tpu_pjrt_probe(const char* plugin_path, char* out, size_t out_len) {
  try {
    raft_tpu::pjrt::Handle h(plugin_path);
    auto v = h.api_version();
    return fill(out, out_len,
                "{\"api_version\": [" + std::to_string(v.major_version) +
                    ", " + std::to_string(v.minor_version) + "]}");
  } catch (const std::exception& e) {
    fill(out, out_len, e.what());
    return 1;
  }
}

// Full client bring-up + device enumeration.  Expensive; may fail where
// the process has no device access (the message says why).
int raft_tpu_pjrt_client_info(const char* plugin_path, char* out,
                              size_t out_len) {
  try {
    raft_tpu::pjrt::Handle h(plugin_path);
    h.create_client();
    std::string json = "{\"platform\": " + jstr(h.platform_name()) +
                       ", \"version\": " + jstr(h.platform_version()) +
                       ", \"devices\": [";
    bool first = true;
    for (const auto& d : h.devices()) {
      if (!first) json += ", ";
      first = false;
      json += "{\"id\": " + std::to_string(d.id) + ", \"kind\": " +
              jstr(d.kind) + ", \"addressable\": " +
              (d.addressable ? "true" : "false") + "}";
    }
    json += "]}";
    return fill(out, out_len, json);
  } catch (const std::exception& e) {
    fill(out, out_len, e.what());
    return 1;
  }
}

}  // extern "C"
