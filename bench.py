#!/usr/bin/env python
"""Headline benchmark — prints ONE JSON line, always, within a hard budget.

Measures the BASELINE.md configs as a *ladder*, banking each rung as it
completes: pairwise-L2 Gpairs/s (config #1/#2) at 2k then 8k, brute-force
kNN QPS (config #3) at 100k then the 1M x 128 k=100 north star, the
compiled-Pallas fused-kNN comparison, and a small spectral embedding
(config #4).  The headline metric is the best kNN rung completed.

Architecture (round-2 postmortem: the bench was killed by the harness
timeout before printing anything — rc=124):

- the PARENT process never imports JAX.  It owns a hard wall-clock budget
  (``RAFT_TPU_BENCH_BUDGET`` seconds, default 420) and a deadline loop;
  nothing the backend does (hung PJRT init, hung Mosaic compile) can keep
  it from printing the best JSON assembled so far and exiting 0.
- ONE measuring CHILD process does all JAX work (a single backend init —
  round 2 measured >180 s per init in this environment, so extra probe
  subprocesses are unaffordable).  It streams ``PARTIAL <json>`` lines
  after every rung; the parent folds them into the final result.
- the child sees the same deadline (env) and skips rungs that don't fit,
  recording them as skipped; the parent kills it at the deadline.
- if the child dies or produces nothing with enough budget left, the
  parent retries once on CPU (``JAX_PLATFORMS=cpu``) with scaled shapes
  and reports honestly (``fallback: "cpu"``).

Timing methodology: the device can sit behind a high-latency transport
where per-call host timing is unreliable, so each rung runs ITERS
data-dependent iterations inside ONE compiled ``fori_loop`` program
(single compile), fetches a scalar to force completion, and differences
an n-iteration call against a 1-iteration call of the *same* executable
to cancel fixed dispatch/fetch latency.

vs_baseline: the reference publishes no numbers (BASELINE.md), so the
baseline constant is an A100 estimate for the same op derived from the
north-star target ("within 1.5x of A100 wall-clock"):
- brute-force kNN 1M x 128 k=100: FAISS-class A100 throughput ~20k QPS.
  vs_baseline = ours / 20000 (smaller-index rungs normalized to their
  1M-index equivalent: per-query work scales with n_index).
- pairwise L2 f32: A100 sustains ~50 Gpairs/s at d=128.
"""

import json
import os
import subprocess
import sys
import threading
import time
import traceback

REPO = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, REPO)

KNN_BASELINE_QPS = 20000.0
PAIRWISE_BASELINE_GPAIRS = 50.0

_BUDGET_ENV = "RAFT_TPU_BENCH_BUDGET"
_DEADLINE_ENV = "RAFT_TPU_BENCH_DEADLINE"
_CPU_ENV = "RAFT_TPU_BENCH_CPU"
_SAFETY = 12.0          # parent prints this many seconds before the budget
_CPU_RETRY_COST = 100.0  # min budget left to bother starting a CPU child

# operator pins of the fused-kNN / selection impls, captured before any
# rung mutates the env (a pinned env var must win over the ladder AND be
# reported truthfully)
_OPERATOR_IMPL = os.environ.get("RAFT_TPU_FUSED_KNN_IMPL")
_OPERATOR_SELECT = os.environ.get("RAFT_TPU_SELECT_IMPL")


# --------------------------------------------------------------------------
# result assembly (shared by parent and child)
# --------------------------------------------------------------------------

def assemble(state):
    """Fold rung results into the single headline JSON object."""
    def best(*names):
        cands = [state.get(n) for n in names]
        return max((c for c in cands if c and c.get("qps")),
                   key=lambda c: c["qps"], default=None)

    detail = dict(state)
    knn_1m = best("knn_1m", "knn_1m_pallas")
    knn_100k = best("knn_100k", "knn_100k_approx")
    fallback = state.get("fallback") == "cpu"
    if knn_1m:
        metric = "knn_qps_1M_128d_k100"
        value = knn_1m["qps"]
        equiv = knn_1m["qps"]
    elif knn_100k and knn_100k.get("qps"):
        n_index = knn_100k["n_index"]
        metric = "knn_qps_%dk_128d_k100%s" % (
            n_index // 1000, "_cpu_fallback" if fallback else "")
        value = knn_100k["qps"]
        equiv = knn_100k["qps"] * (n_index / 1_000_000)
    else:
        metric = "knn_qps_1M_128d_k100"
        value = 0.0
        equiv = 0.0
    return {
        "metric": metric,
        "value": round(value, 1),
        "unit": "queries/s",
        "vs_baseline": round(equiv / KNN_BASELINE_QPS, 4),
        "detail": detail,
    }


# --------------------------------------------------------------------------
# child: the only process that imports JAX
# --------------------------------------------------------------------------

def _remaining():
    return float(os.environ[_DEADLINE_ENV]) - time.time()


def _emit(name, payload):
    print("PARTIAL " + json.dumps({name: payload}), flush=True)


def _time_chained(step, x, iters):
    """Seconds per call of ``step(x) -> array`` via one compiled fori_loop.

    A single executable taking the iteration count as a traced scalar is
    compiled once and called at n=iters and n=1; the difference cancels
    fixed dispatch/fetch latency without paying a second compile.
    """
    import jax
    import jax.numpy as jnp

    @jax.jit
    def run(x0, n):
        def body(_, carry):
            out = step(carry)
            # data dependency without changing the value: adds 0.0 derived
            # from a FULL reduction of the output, so XLA cannot
            # slice-narrow the benchmarked op
            return carry + jnp.sum(out) * 0.0
        return jax.lax.fori_loop(0, n, body, x0).ravel()[0]

    float(run(x, 1))  # compile + warm
    t0 = time.perf_counter()
    float(run(x, iters + 1))
    t_n = time.perf_counter() - t0
    t0 = time.perf_counter()
    float(run(x, 1))
    t_1 = time.perf_counter() - t0
    return max((t_n - t_1) / iters, 1e-9)


def _rand(shape, seed):
    """Device-side normal data — avoids shipping 100s of MB over a
    potentially slow host<->device transport."""
    import jax
    import jax.numpy as jnp

    return jax.jit(
        lambda: jax.random.normal(jax.random.PRNGKey(seed), shape, jnp.float32)
    )()


def _rung_init():
    t0 = time.time()
    import jax
    import jax.numpy as jnp

    if os.environ.get(_CPU_ENV) == "1":
        # env-var JAX_PLATFORMS is NOT enough: a sitecustomize-registered
        # accelerator plugin may force jax_platforms via jax.config at
        # interpreter startup; backend init is lazy, so re-pinning here
        # (before any device op) wins
        jax.config.update("jax_platforms", "cpu")
    dev = jax.devices()[0]
    x = jnp.ones((128, 128), jnp.float32)
    v = float((x @ x)[0, 0])
    assert v == 128.0, v
    from raft_tpu.core.utils import is_tpu_backend

    return {
        "seconds": round(time.time() - t0, 1),
        "device": str(dev.device_kind),
        "platform": str(dev.platform),
        "is_tpu": bool(is_tpu_backend()),
    }


def _bench_pairwise(m, iters):
    from raft_tpu.distance import DistanceType, pairwise_distance

    dim = 128
    x = _rand((m, dim), 1)
    y = _rand((m, dim), 2)

    def step(a):
        return pairwise_distance(a, y, DistanceType.L2Expanded)

    dt = _time_chained(step, x, iters)
    gpairs = m * m / dt / 1e9
    return {
        "gpairs_per_sec": round(gpairs, 2),
        "seconds_per_call": round(dt, 5),
        "shape": [m, m, dim],
        "vs_a100_estimate": round(gpairs / PAIRWISE_BASELINE_GPAIRS, 3),
    }


def _bench_knn(n_index, n_query, iters, impl, select_impl=None):
    from raft_tpu.spatial import brute_force_knn

    dim, k = 128, 100
    index = _rand((n_index, dim), 3)
    queries = _rand((n_query, dim), 4)
    impl = _OPERATOR_IMPL or impl  # operator env pins win over the ladder
    select_impl = _OPERATOR_SELECT or select_impl
    prev = {v: os.environ.get(v) for v in
            ("RAFT_TPU_FUSED_KNN_IMPL", "RAFT_TPU_SELECT_IMPL")}
    if impl:
        os.environ["RAFT_TPU_FUSED_KNN_IMPL"] = impl
    if select_impl:
        os.environ["RAFT_TPU_SELECT_IMPL"] = select_impl

    def step(q):
        dists, _ = brute_force_knn([index], q, k)
        return dists

    try:
        dt = _time_chained(step, queries, iters)
    finally:
        for var, val in prev.items():
            if val is None:
                os.environ.pop(var, None)
            else:
                os.environ[var] = val
    qps = n_query / dt
    return {
        "qps": round(qps, 1),
        "qps_1m_equiv": round(qps * n_index / 1_000_000, 1),
        "seconds_per_batch": round(dt, 4),
        "n_index": n_index, "n_query": n_query, "dim": dim, "k": k,
        "impl": impl or "xla", "select_impl": select_impl or "topk",
    }


def _bench_pallas(state):
    """Compiled (interpret=False) Pallas fused kNN: correctness vs the XLA
    impl, then a timed comparison at 100k.  Loud status either way —
    this is the kernel that must not ship unmeasured silently."""
    import numpy as np

    if not state.get("init", {}).get("is_tpu"):
        return {"status": "skipped_backend"}
    from raft_tpu.spatial.fused_l2_knn import fused_l2_knn

    x = _rand((4096, 128), 5)
    q = _rand((256, 128), 6)
    d_p, i_p = fused_l2_knn(x, q, 64, impl="pallas")
    d_r, i_r = fused_l2_knn(x, q, 64, impl="xla")
    ok_d = bool(np.allclose(np.asarray(d_p), np.asarray(d_r), atol=1e-2))
    ok_i = bool(np.mean(np.asarray(i_p) == np.asarray(i_r)) > 0.999)
    out = {"status": "ok" if (ok_d and ok_i) else "mismatch",
           "dist_close": ok_d, "idx_match": ok_i}
    if _remaining() > 90:
        index = _rand((100_000, 128), 3)
        queries = _rand((1024, 128), 4)
        for impl in ("pallas", "xla"):
            def step(qq, impl=impl):
                d, _ = fused_l2_knn(index, qq, 100, impl=impl)
                return d
            dt = _time_chained(step, queries, 2)
            out[impl + "_seconds_per_batch"] = round(dt, 4)
            out[impl + "_qps_100k"] = round(1024 / dt, 1)
    return out


def _bench_spectral():
    import numpy as np

    from raft_tpu.sparse.formats import COO
    from raft_tpu.sparse.spectral import fit_embedding

    n = 2048
    rng = np.random.default_rng(0)
    src = np.arange(n, dtype=np.int64)
    dst = (src + 1) % n
    extra = rng.integers(0, n, size=(2 * n, 2), dtype=np.int64)
    extra = extra[extra[:, 0] != extra[:, 1]]
    rows = np.concatenate([src, dst, extra[:, 0], extra[:, 1]])
    cols = np.concatenate([dst, src, extra[:, 1], extra[:, 0]])
    vals = np.ones(rows.shape[0], dtype=np.float32)
    coo = COO(rows.astype(np.int32), cols.astype(np.int32), vals, shape=(n, n))
    np.asarray(fit_embedding(coo, n_components=4))  # warmup: trace+compile
    t0 = time.perf_counter()
    np.asarray(fit_embedding(coo, n_components=4))
    dt = time.perf_counter() - t0
    return {"seconds": round(dt, 3), "n_vertices": n, "n_components": 4,
            "note": "steady-state (compile excluded by warmup call)"}


def child_main():
    cpu = os.environ.get(_CPU_ENV) == "1"
    state = {"fallback": "cpu" if cpu else None}
    skipped = []

    state["init"] = _rung_init()
    if not cpu and not state["init"]["is_tpu"]:
        # init succeeded but on a non-accelerator backend (e.g. a CPU-only
        # dev box): the full ladder would run for hours — use the scaled
        # shapes and say so in the metric name
        cpu = True
        state["fallback"] = "cpu"
        state["init"]["note"] = "non-TPU backend; scaled ladder"
    _emit("init", state["init"])
    _emit("fallback", state["fallback"])

    def knn_pallas_1m():
        """Re-run the north star with the Pallas kernel only once it has
        proven correct AND faster at 100k; assemble() picks the best."""
        p = state.get("pallas_check", {})
        if (p.get("status") == "ok"
                and p.get("pallas_seconds_per_batch", 1e9)
                < p.get("xla_seconds_per_batch", 0.0)):
            return _bench_knn(1_000_000, 10_000, 3, "pallas")
        return {"status": "skipped_pallas_not_faster"}

    if cpu:
        rungs = [
            ("pairwise_2k", 40, lambda: _bench_pairwise(2048, 4)),
            ("knn_100k", 70, lambda: _bench_knn(100_000, 512, 2, "xla")),
            ("spectral", 40, _bench_spectral),
        ]
    else:
        def best_select():
            """approx_max_k (TPU PartialReduce) vs top_k, per measurement
            at 100k — the winner drives the 1M rung."""
            a = state.get("knn_100k_approx", {})
            b = state.get("knn_100k", {})
            if a.get("qps", 0) > b.get("qps", 0):
                return "approx"
            return None

        # knn_1m (the headline, proven XLA impl) runs BEFORE pallas_check:
        # a Mosaic compile hang in this process must not forfeit the
        # north-star number (the parent can only kill the whole child)
        rungs = [
            ("pairwise_2k", 45, lambda: _bench_pairwise(2048, 8)),
            ("knn_100k", 80, lambda: _bench_knn(100_000, 4096, 4, "xla")),
            # gate = its own cost (60) PLUS the 1M rung's (140): the
            # comparison rung must never consume the budget that would
            # otherwise let the north-star headline run
            ("knn_100k_approx", 60 + 140,
             lambda: _bench_knn(100_000, 4096, 4, "xla",
                                select_impl="approx")),
            ("knn_1m", 140,
             lambda: _bench_knn(1_000_000, 10_000, 3, "xla",
                                select_impl=best_select())),
            ("pallas_check", 100, lambda: _bench_pallas(state)),
            ("knn_1m_pallas", 120, knn_pallas_1m),
            ("pairwise_8k", 50, lambda: _bench_pairwise(8192, 16)),
            ("spectral", 60, _bench_spectral),
        ]

    for name, est, fn in rungs:
        if _remaining() < est:
            skipped.append(name)
            _emit("skipped", skipped)
            continue
        try:
            state[name] = fn()
        except Exception:
            state.setdefault("errors", {})[name] = \
                traceback.format_exc()[-600:]
            _emit("errors", state["errors"])
            continue
        _emit(name, state[name])
    if skipped:
        state["skipped"] = skipped
    print("FINAL " + json.dumps(assemble(state)), flush=True)


# --------------------------------------------------------------------------
# parent: watchdog + orchestration, no JAX
# --------------------------------------------------------------------------

class _Child:
    def __init__(self, deadline, cpu):
        env = dict(os.environ)
        env[_DEADLINE_ENV] = repr(deadline)
        if cpu:
            env[_CPU_ENV] = "1"
            env["JAX_PLATFORMS"] = "cpu"
        self.proc = subprocess.Popen(
            [sys.executable, os.path.abspath(__file__), "--child"],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
            text=True)
        self.state = {}
        self.final = None
        self.stderr_tail = ""
        threading.Thread(target=self._read_out, daemon=True).start()
        threading.Thread(target=self._read_err, daemon=True).start()

    def _read_out(self):
        for line in self.proc.stdout:
            line = line.strip()
            if line.startswith("PARTIAL "):
                try:
                    self.state.update(json.loads(line[8:]))
                except ValueError:
                    pass
            elif line.startswith("FINAL "):
                try:
                    self.final = json.loads(line[6:])
                except ValueError:
                    pass

    def _read_err(self):
        tail = []
        for line in self.proc.stderr:
            tail.append(line)
            tail = tail[-8:]
        self.stderr_tail = "".join(tail)[-600:]

    def kill(self):
        try:
            self.proc.kill()
        except OSError:
            pass


def _result_of(child, note=None):
    """Best result extractable from a child: FINAL line, else assembled
    partials (None if it never even initialized a backend)."""
    if child is None:
        return None
    if child.final is not None:
        return child.final
    if not child.state.get("init"):
        return None
    state = dict(child.state)
    if note:
        state["watchdog"] = note
    return assemble(state)


def parent_main():
    t_start = time.time()
    budget = float(os.environ.get(_BUDGET_ENV, "420"))
    deadline = t_start + budget - _SAFETY

    tpu = _Child(deadline, cpu=False)
    cpu = None
    while time.time() < deadline:
        if tpu.final is not None:
            break
        tpu_dead = tpu.proc.poll() is not None
        if tpu_dead:
            # grace: the reader thread may not have consumed a FINAL line
            t_grace = time.time() + 2.0
            while time.time() < min(t_grace, deadline) and tpu.final is None:
                time.sleep(0.1)
            if tpu.final is not None:
                break
        no_backend = not tpu.state.get("init")
        want_cpu = cpu is None and no_backend and (
            tpu_dead or deadline - time.time() < _CPU_RETRY_COST)
        if want_cpu and deadline - time.time() > 20:
            # the accelerator never came up and the window to bank ANY
            # number is closing: start the CPU child *in parallel* — a
            # hung PJRT init burns no CPU, and if it completes late its
            # numbers still supersede the fallback's
            cpu = _Child(deadline, cpu=True)
        if tpu_dead and (cpu is None or cpu.proc.poll() is not None):
            t_grace = time.time() + 2.0
            while (time.time() < min(t_grace, deadline)
                   and cpu is not None and cpu.final is None):
                time.sleep(0.1)
            break
        time.sleep(0.5)

    if time.time() >= deadline:
        note = "deadline reached; reporting completed rungs"
    else:
        note = "child exited before FINAL; reporting completed rungs"
    result = _result_of(tpu, note)
    if result is not None and result.get("value"):
        if cpu is not None:
            result["detail"]["cpu_fallback_superseded"] = True
    else:
        cpu_result = _result_of(cpu, note)
        if cpu_result is not None:
            cpu_result["detail"]["tpu_attempt"] = (
                result["detail"] if result is not None
                else "backend init did not complete within budget")
            result = cpu_result
    if result is None:
        state = {"watchdog": note,
                 "child_error": tpu.stderr_tail or "backend init never "
                 "completed and no CPU fallback result"}
        result = assemble(state)
    tpu.kill()
    if cpu is not None:
        cpu.kill()
    print(json.dumps(result), flush=True)


def main():
    if "--child" in sys.argv:
        child_main()
    else:
        parent_main()


if __name__ == "__main__":
    try:
        main()
    except Exception:
        print(json.dumps({
            "metric": "knn_qps_1M_128d_k100",
            "value": 0.0,
            "unit": "queries/s",
            "vs_baseline": 0.0,
            "detail": {"error": traceback.format_exc()[-1200:]},
        }))
    sys.exit(0)
