#!/usr/bin/env python
"""Headline benchmark — prints ONE JSON line, always, within a hard budget.

Measures the BASELINE.md configs as a *ladder*, banking each rung as it
completes.  Rungs are ordered by compile cost so the first hardware
number banks as early as possible: the README config (pairwise
L2SqrtExpanded 1k x 64, BASELINE.md #1) first, then pairwise 2k, kNN
100k, the 1M x 128 k=100 north star (#3), the compiled-Pallas fused
kernel comparison, and a small spectral embedding (#4).  The headline
metric is the best accelerator kNN rung, falling back to an accelerator
pairwise rung, then to CPU kNN.

Architecture (round-3 verdict: three rounds of CPU fallbacks because
backend init ate the budget sequentially):

- the PARENT process never imports JAX.  It owns a hard wall-clock
  budget (``RAFT_TPU_BENCH_BUDGET`` seconds, default 420) and a
  deadline loop; nothing the backend does (hung PJRT init, hung Mosaic
  compile) can keep it from printing the best JSON assembled so far and
  exiting 0.
- TWO children start at t=0 *in parallel*: the TPU child gets the
  entire budget minus safety (a hung PJRT init burns no CPU), and the
  CPU child banks scaled fallback rungs for free from the first second
  instead of being a sequential retry.  Accelerator partials always
  supersede CPU ones in the headline.
- both children stream ``PARTIAL <json>`` lines after every rung, each
  rung carrying a ``device`` field; the TPU child additionally streams
  a timestamped ``init_log`` so a budget-eating backend init is
  *provable* from the report rather than inferred.
- the parent distinguishes "child died before init" (exit status +
  stderr tail) from "killed at deadline during init" (init_log shows
  where it sat) from "init ok but no rung fit" — the three look
  identical in a bare fallback note but need different fixes.

Timing methodology: the device can sit behind a high-latency transport
where per-call host timing is unreliable, so each rung runs ITERS
data-dependent iterations inside ONE compiled ``fori_loop`` program
(single compile), fetches a scalar to force completion, and differences
an n-iteration call against a 1-iteration call of the *same* executable
to cancel fixed dispatch/fetch latency.

Perf accounting: every accelerator rung reports an ``mfu`` block —
analytic FLOPs (2*m*n*d for distance-shaped ops), achieved FLOP/s, and
the fraction of the chip's nominal bf16 MXU peak (generation detected
from ``device_kind``).  This replaces "vs an A100 guess" as the basis
for the perf verdict; ``vs_baseline`` keeps the A100-derived constants
only because BASELINE.md defines the north star that way (the reference
publishes no numbers).
"""

import contextlib
import json
import os
import subprocess
import sys
import threading
import time
import traceback

REPO = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, REPO)

KNN_BASELINE_QPS = 20000.0
PAIRWISE_BASELINE_GPAIRS = 50.0

# Nominal dense bf16 MXU peak FLOP/s per chip, by generation.  f32
# inputs (our benchmarked dtype) run below this (bf16x3 passes or
# conversion), so mfu is a conservative fraction of the chip's
# *headline* peak — honest accounting, not marketing.  Sources: public
# TPU spec sheets.
TPU_PEAK_BF16 = {
    "v2": 46e12,
    "v3": 123e12,
    "v4": 275e12,
    "v5e": 197e12,
    "v5p": 459e12,
    "v6e": 918e12,
}

_BUDGET_ENV = "RAFT_TPU_BENCH_BUDGET"
_DEADLINE_ENV = "RAFT_TPU_BENCH_DEADLINE"
_CPU_ENV = "RAFT_TPU_BENCH_CPU"
_SAFETY = 12.0          # parent prints this many seconds before the budget

# operator pins of the fused-kNN / selection impls, captured before any
# rung mutates the env (a pinned env var must win over the ladder AND be
# reported truthfully)
_OPERATOR_IMPL = os.environ.get("RAFT_TPU_FUSED_KNN_IMPL")
_OPERATOR_SELECT = os.environ.get("RAFT_TPU_SELECT_IMPL")
_OPERATOR_MERGE = os.environ.get("RAFT_TPU_TILE_MERGE")


# gRPC-status tokens of a dead/hung device — matched against the
# exception MESSAGE only (a full traceback mentions benign words
# like "backend" in rendered source lines of ordinary bugs)
_DEAD_SIGNS = ("UNAVAILABLE", "DEADLINE_EXCEEDED",
               "Unable to initialize backend")


@contextlib.contextmanager
def _env_pins(pins):
    """Temporarily set env vars (None values = leave unset), restoring
    previous values on exit.  Single owner of the save/mutate/restore
    dance — exceptions propagate (a dead-device error must reach
    child_main's consecutive_dead abort, not be swallowed mid-pin)."""
    prev = {v: os.environ.get(v) for v in pins}
    for var, val in pins.items():
        if val is not None:
            os.environ[var] = val
    try:
        yield
    finally:
        for var, val in prev.items():
            if val is None:
                os.environ.pop(var, None)
            else:
                os.environ[var] = val


def chip_peak_flops(device_kind, platform):
    """(peak_flops, generation) from a PJRT device_kind string, or
    (None, None) when unrecognized / not an accelerator.  Generation
    number is matched before the lite/p suffix so 'TPU v6 lite' maps to
    v6e, not v5e."""
    s = (device_kind or "").lower().replace(" ", "")
    hint = (os.environ.get("PALLAS_AXON_TPU_GEN") or "").lower()
    gen = None
    if "v6" in s or "trillium" in s:
        gen = "v6e"
    elif "v5" in s:
        gen = "v5e" if ("lite" in s or "5e" in s) else "v5p"
    elif "v4" in s:
        gen = "v4"
    elif "v3" in s:
        gen = "v3"
    elif "v2" in s:
        gen = "v2"
    if gen:
        return TPU_PEAK_BF16[gen], gen
    if platform != "cpu" and hint in TPU_PEAK_BF16:
        return TPU_PEAK_BF16[hint], hint + "(env)"
    return None, None


# --------------------------------------------------------------------------
# result assembly (shared by parent and child)
# --------------------------------------------------------------------------

def _best_knn(state, *names):
    cands = [state.get(n) for n in names]
    return max((c for c in cands if c and c.get("qps")),
               key=lambda c: c["qps"], default=None)


def assemble(tpu_state, cpu_state):
    """Fold both children's rung results into the headline JSON object.

    Preference order for the headline: accelerator kNN > accelerator
    pairwise > CPU-fallback kNN > zero.
    """
    tpu_state = tpu_state or {}
    cpu_state = cpu_state or {}
    detail = dict(tpu_state)
    if cpu_state:
        detail["cpu_fallback"] = cpu_state

    knn_1m = _best_knn(tpu_state, "knn_1m", "knn_1m_pallas",
                       "knn_1m_twophase")
    knn_100k = _best_knn(tpu_state, "knn_100k", "knn_100k_chunked",
                         "knn_100k_pselect", "knn_100k_direct")
    pw = None
    for name in ("pairwise_8k", "pairwise_2k", "pairwise_1k"):
        cand = tpu_state.get(name)
        if cand and cand.get("gpairs_per_sec"):
            pw = cand
            break
    cpu_knn = _best_knn(cpu_state, "knn_100k")

    if knn_1m:
        metric, value = "knn_qps_1M_128d_k100", knn_1m["qps"]
        unit, vs = "queries/s", knn_1m["qps"] / KNN_BASELINE_QPS
    elif knn_100k:
        n_index = knn_100k["n_index"]
        metric = "knn_qps_%dk_128d_k100" % (n_index // 1000)
        value = knn_100k["qps"]
        unit = "queries/s"
        vs = value * (n_index / 1_000_000) / KNN_BASELINE_QPS
    elif pw:
        m, _, d = pw["shape"]
        metric = "pairwise_l2_gpairs_%dx%d" % (m, d)
        value = pw["gpairs_per_sec"]
        unit = "Gpairs/s"
        # the 50 Gpairs/s A100 constant is defined at d=128: normalize
        # this rung's pair rate to its d=128 FLOP equivalent
        vs = value * (d / 128.0) / PAIRWISE_BASELINE_GPAIRS
    elif cpu_knn:
        # CPU-fallback headlines report vs_baseline = 0 with a note: a
        # CPU rate divided by an A100 guess is cross-hardware noise (r4
        # verdict item 5); the per-rung mfu blocks are the honest perf
        # basis there
        n_index = cpu_knn["n_index"]
        metric = "knn_qps_%dk_128d_k100_cpu_fallback" % (n_index // 1000)
        value = cpu_knn["qps"]
        unit = "queries/s"
        vs = 0.0
    elif (cpw := next((cpu_state[n] for n in ("pairwise_2k", "pairwise_1k")
                       if cpu_state.get(n, {}).get("gpairs_per_sec")),
                      None)):
        # a very short budget can bank CPU pairwise rungs but not the
        # (costlier) CPU kNN rung — report the largest banked shape
        # (same order as the TPU pw chain above) instead of a flat
        # zero (r4: a 70 s smoke budget hit exactly this)
        m, _, d = cpw["shape"]
        metric = "pairwise_l2_gpairs_%dx%d_cpu_fallback" % (m, d)
        value = cpw["gpairs_per_sec"]
        unit = "Gpairs/s"
        vs = 0.0
    else:
        metric, value, unit, vs = "knn_qps_1M_128d_k100", 0.0, "queries/s", 0.0
    out = {
        "metric": metric,
        # 4 decimals: a 1-decimal round would flatten sub-1 Gpairs/s
        # fallback values (0.25 -> 0.2)
        "value": round(value, 4),
        "unit": unit,
        "vs_baseline": round(vs, 4),
        "detail": detail,
    }
    if metric.endswith("_cpu_fallback"):
        out["vs_baseline_note"] = (
            "cpu_fallback: vs_baseline suppressed (A100 comparison is "
            "cross-hardware noise; see per-rung mfu)")
    return out


# --------------------------------------------------------------------------
# child: the only process kind that imports JAX
# --------------------------------------------------------------------------

_CHILD_T0 = time.time()
_INIT_LOG = []


def _remaining():
    return float(os.environ[_DEADLINE_ENV]) - time.time()


def _emit(name, payload):
    print("PARTIAL " + json.dumps({name: payload}), flush=True)


def _log_init(event):
    _INIT_LOG.append({"t": round(time.time() - _CHILD_T0, 1), "event": event})
    _emit("init_log", _INIT_LOG)


_DEVICE_INFO = {}


def _tag(payload):
    """Attach the measured device to a rung result."""
    if isinstance(payload, dict) and _DEVICE_INFO:
        payload.setdefault("device", _DEVICE_INFO.get("device"))
        payload.setdefault("platform", _DEVICE_INFO.get("platform"))
        payload.setdefault("n_devices", _DEVICE_INFO.get("n_devices"))
    return payload


def _mfu(flops_per_call, seconds_per_call):
    achieved = flops_per_call / seconds_per_call
    out = {"flops_per_call": flops_per_call,
           "achieved_tflops": round(achieved / 1e12, 3)}
    peak, gen = chip_peak_flops(_DEVICE_INFO.get("device"),
                                _DEVICE_INFO.get("platform"))
    if peak:
        out["chip_gen"] = gen
        out["peak_tflops_bf16"] = round(peak / 1e12, 1)
        out["mfu"] = round(achieved / peak, 4)
        out["peak_basis"] = "bf16 MXU peak; inputs are f32"
    return out


def _time_chained(step, x, iters):
    """Seconds per call of ``step(x) -> array`` via one compiled fori_loop.

    A single executable taking the iteration count as a traced scalar is
    compiled once and called at n=iters and n=1; the difference cancels
    fixed dispatch/fetch latency without paying a second compile.

    CALLER CONTRACT: only the array ``step`` RETURNS is kept live —
    everything not feeding it is dead code inside the loop and XLA
    deletes it.  A step that computes (distances, indices) but returns
    only distances times the kernel *without* index tracking (~10x
    under the honest number at the 100k kNN shape, observed r4 on
    v5e).  Fold every contract output into the returned array, e.g.
    ``d + i.astype(d.dtype)``.
    """
    import jax
    import jax.numpy as jnp

    @jax.jit
    def run(x0, n, eps):
        def body(_, carry):
            out = step(carry)
            # data dependency without changing the value: adds eps * a
            # FULL reduction of the output, so XLA cannot slice-narrow
            # the benchmarked op.  eps is a TRACED argument (0.0 at every
            # call site), not a literal: a 0.0 literal lets the algebraic
            # simplifier fold the product, turn the body into identity,
            # and delete the whole loop — observed on the TPU backend as
            # seconds_per_call == 0 (r4).
            return carry + jnp.sum(out) * eps
        return jax.lax.fori_loop(0, n, body, x0).ravel()[0]

    float(run(x, 1, 0.0))  # compile + warm
    while True:
        t0 = time.perf_counter()
        float(run(x, iters + 1, 0.0))
        t_n = time.perf_counter() - t0
        t0 = time.perf_counter()
        float(run(x, 1, 0.0))
        t_1 = time.perf_counter() - t0
        diff = t_n - t_1
        # resolvable above host/transport jitter, or past the point of
        # cheap retries: accept.  Otherwise quadruple the chain (no
        # recompile: n is traced) so per-call cost integrates upward.
        if diff > 0.25 or iters >= 4096 or _remaining() < 4 * t_n + 10:
            break
        iters *= 4
    return max(diff / iters, 1e-9)


def _rand(shape, seed):
    """Device-side normal data — avoids shipping 100s of MB over a
    potentially slow host<->device transport."""
    import jax
    import jax.numpy as jnp

    return jax.jit(
        lambda: jax.random.normal(jax.random.PRNGKey(seed), shape, jnp.float32)
    )()


def _enable_compile_cache(jax_mod=None):
    """Persistent compile cache via EXPLICIT config: this environment's
    JAX does not read JAX_COMPILATION_CACHE_DIR from the env (measured
    r4: config stayed None and .jax_cache was never created, so every
    'warm cache' across sessions was a no-op).  Delegates to the single
    config owner (core.specializations.enable_persistent_cache) with a
    5 s threshold — only real accelerator compiles are worth disk."""
    cache_dir = os.environ.get("JAX_COMPILATION_CACHE_DIR")
    if not cache_dir:
        return
    try:
        from raft_tpu.core.specializations import enable_persistent_cache

        enable_persistent_cache(cache_dir, min_compile_secs=5.0)
    except Exception:
        pass  # older config names; cache stays off rather than crashing


def _rung_init():
    t0 = time.time()
    _log_init("backend_init_start")
    import jax
    import jax.numpy as jnp

    _log_init("jax_imported")
    _enable_compile_cache(jax)
    if os.environ.get(_CPU_ENV) == "1":
        # env-var JAX_PLATFORMS is NOT enough: a sitecustomize-registered
        # accelerator plugin may force jax_platforms via jax.config at
        # interpreter startup; backend init is lazy, so re-pinning here
        # (before any device op) wins
        jax.config.update("jax_platforms", "cpu")
    while True:
        try:
            dev = jax.devices()[0]
            break
        except Exception as e:
            # a flapping tunnel endpoint (observed r4: UNAVAILABLE for
            # ~20-40 min, then healthy) must not kill the child while
            # budget remains — clear the cached init failure and retry
            if _remaining() < 120:
                raise
            _log_init("init_failed_retrying: %s" % str(e)[-120:])
            time.sleep(45)
            try:
                from jax._src import xla_bridge as _xb

                _xb._clear_backends()
            except Exception:
                pass
    _log_init("devices_ready")
    x = jnp.ones((128, 128), jnp.float32)
    v = float((x @ x)[0, 0])
    assert v == 128.0, v
    _log_init("first_matmul_done")
    from raft_tpu.core.utils import is_tpu_backend

    _DEVICE_INFO.update({
        "device": str(dev.device_kind),
        "platform": str(dev.platform),
        # recorded so ladder comparisons can see a backend-shape
        # change: the CPU child now forces an 8-device virtual mesh
        # (for the comms_p2p rung), where earlier rounds ran 1-device —
        # a cross-round delta on a non-comms rung must be read against
        # this field before being called a regression
        "n_devices": len(jax.devices()),
    })
    return {
        "seconds": round(time.time() - t0, 1),
        "device": str(dev.device_kind),
        "platform": str(dev.platform),
        "n_devices": len(jax.devices()),
        "is_tpu": bool(is_tpu_backend()),
    }


def _wall_check(step, queries):
    """Wall-clock cross-check: one plain timed call of the jitted step.

    After the r4 dead-code findings, chained and wall must agree within
    dispatch overhead — a large ratio in a report is the red flag that
    something is being optimized away again.  Headline rungs only: the
    check costs one extra compile.  One owner so every headline rung
    measures under the same bar.
    """
    import jax

    jstep = jax.jit(step)
    jax.block_until_ready(jstep(queries))    # compile + warm
    t0 = time.perf_counter()
    jax.block_until_ready(jstep(queries))
    return time.perf_counter() - t0


def _bench_micro():
    """<10 s first rung (warm cache): one 512³ matmul, chain-timed.

    Exists so the report banks a hardware-tagged rung within seconds of
    a successful backend init — in a hostile-endpoint round the
    difference between "zero TPU rungs" and "TPU proven up + measured"
    is exactly this rung (VERDICT r4 item 4)."""
    import jax.numpy as jnp

    n = 512
    x = _rand((n, n), 7)

    def step(a):
        return jnp.matmul(a, x, precision="highest")

    dt = _time_chained(step, x, 4)
    fl = 2.0 * n ** 3
    return {
        "tflops": round(fl / dt / 1e12, 4),
        "seconds_per_call": round(dt, 6),
        "shape": [n, n, n],
        "mfu": _mfu(fl, dt),
    }


def _bench_pairwise(m, dim, iters, sqrt=False):
    from raft_tpu.distance import DistanceType, pairwise_distance

    metric = (DistanceType.L2SqrtExpanded if sqrt
              else DistanceType.L2Expanded)
    x = _rand((m, dim), 1)
    y = _rand((m, dim), 2)

    def step(a):
        return pairwise_distance(a, y, metric)

    dt = _time_chained(step, x, iters)
    gpairs = m * m / dt / 1e9
    out = {
        "gpairs_per_sec": round(gpairs, 2),
        "seconds_per_call": round(dt, 5),
        "shape": [m, m, dim],
        "metric": "L2SqrtExpanded" if sqrt else "L2Expanded",
        "mfu": _mfu(2.0 * m * m * dim, dt),
    }
    # cross-hardware estimate only where it means something: comparing
    # a CPU-fallback rung against a GPU guess is noise (r4 verdict);
    # accelerator rungs carry it, CPU rungs stand on their mfu block
    if _DEVICE_INFO.get("platform") not in (None, "cpu"):
        # A100 constant is at d=128: normalize to the d=128 equivalent
        out["vs_a100_estimate"] = round(
            gpairs * (dim / 128.0) / PAIRWISE_BASELINE_GPAIRS, 3)
    return out


def _bench_knn(n_index, n_query, iters, impl, select_impl=None,
               merge=None, wall_check=False):
    from raft_tpu.spatial import brute_force_knn

    dim, k = 128, 100
    index = _rand((n_index, dim), 3)
    queries = _rand((n_query, dim), 4)
    impl = _OPERATOR_IMPL or impl  # operator env pins win over the ladder
    select_impl = _OPERATOR_SELECT or select_impl
    merge = _OPERATOR_MERGE or merge
    def step(q):
        # BOTH outputs folded into the returned array: the chained
        # timing loop keeps only what the step returns live, and XLA
        # dead-codes the rest — a distances-only step measured the kNN
        # *without* its index tracking, ~10x faster than the honest
        # contract (observed r4 on v5e)
        dists, idx = brute_force_knn([index], q, k)
        return dists + idx.astype(dists.dtype)

    with _env_pins({"RAFT_TPU_FUSED_KNN_IMPL": impl or None,
                    "RAFT_TPU_SELECT_IMPL": select_impl or None,
                    "RAFT_TPU_TILE_MERGE": merge or None}):
        dt = _time_chained(step, queries, iters)
        wall = _wall_check(step, queries) if wall_check else None
    qps = n_query / dt
    out = {
        "qps": round(qps, 1),
        "qps_1m_equiv": round(qps * n_index / 1_000_000, 1),
        "seconds_per_batch": round(dt, 4),
        "n_index": n_index, "n_query": n_query, "dim": dim, "k": k,
        "impl": impl or "xla", "select_impl": select_impl or "topk",
        "merge": merge or "tile_topk",
        "mfu": _mfu(2.0 * n_query * n_index * dim, dt),
    }
    if wall is not None:
        out["wall_seconds_per_batch"] = round(wall, 4)
    return out


def _bench_pallas(state):
    """Compiled (interpret=False) Pallas kernels: correctness of BOTH
    the fused kNN kernel (vs the XLA impl) and the pairwise tile kernel
    (vs host numpy), then a timed kNN comparison at 100k.  Loud status
    either way — these are the kernels that must not ship unmeasured
    silently."""
    import numpy as np

    if not state.get("init", {}).get("is_tpu"):
        return {"status": "skipped_backend"}
    from raft_tpu.spatial.fused_l2_knn import fused_l2_knn

    x = _rand((4096, 128), 5)
    q = _rand((256, 128), 6)
    d_p, i_p = fused_l2_knn(x, q, 64, impl="pallas")
    d_r, i_r = fused_l2_knn(x, q, 64, impl="xla")
    ok_d = bool(np.allclose(np.asarray(d_p), np.asarray(d_r), atol=1e-2))
    ok_i = bool(np.mean(np.asarray(i_p) == np.asarray(i_r)) > 0.999)
    out = {"status": "ok" if (ok_d and ok_i) else "mismatch",
           "dist_close": ok_d, "idx_match": ok_i}

    # two-phase no-carry kernel (r5): same cross-check before timing.
    # Guarded: a compile failure in the NEW kernel must not forfeit the
    # established pallas/xla comparison (r4 lesson)
    from raft_tpu.ops.knn_tile import fused_knn_twophase

    try:
        d_t, i_t = fused_knn_twophase(x, q, 64)
        out["twophase_dist_close"] = bool(
            np.allclose(np.asarray(d_t), np.asarray(d_r), atol=1e-2))
        out["twophase_idx_match"] = bool(
            np.mean(np.asarray(i_t) == np.asarray(i_r)) > 0.999)
        # verdict recorded ONLY in its own fields: the shared "status"
        # gates knn_1m_pallas, and a defect in the NEW kernel must not
        # forfeit the established pallas/xla candidates
    except Exception:
        out["twophase_error"] = traceback.format_exc()[-400:]

    # pairwise_tile (the unexpanded-metric kernel): compiled L1 at a
    # host-checkable shape, plus a timed 2k x 2k call
    try:
        from raft_tpu.distance import DistanceType, pairwise_distance

        xs = _rand((512, 128), 9)
        ys = _rand((384, 128), 10)
        got = np.asarray(pairwise_distance(xs, ys, DistanceType.L1))
        ref = np.abs(np.asarray(xs)[:, None, :]
                     - np.asarray(ys)[None, :, :]).sum(-1)
        out["pairwise_tile_l1_ok"] = bool(
            np.allclose(got, ref, rtol=2e-4, atol=2e-3))
        xt = _rand((2048, 128), 11)
        yt = _rand((2048, 128), 12)

        def pstep(a):
            return pairwise_distance(a, yt, DistanceType.L1)

        dt = _time_chained(pstep, xt, 4)
        out["pairwise_tile_l1_gpairs"] = round(2048 * 2048 / dt / 1e9, 3)
        # VPU elementwise kernel (never touches the MXU): report the
        # achieved elementwise rate only — an MXU-peak mfu here would be
        # meaningless
        out["pairwise_tile_l1_gops"] = round(
            3.0 * 2048 * 2048 * 128 / dt / 1e9, 1)  # sub+abs+add / elt
        if not out["pairwise_tile_l1_ok"]:
            out["status"] = "mismatch"
    except Exception:
        out["pairwise_tile_error"] = traceback.format_exc()[-400:]
        if out["status"] == "ok":  # never mask a fused-kNN mismatch
            out["status"] = "pairwise_tile_error"
    if _remaining() > 90:
        index = _rand((100_000, 128), 3)
        queries = _rand((1024, 128), 4)
        for impl in ("pallas", "xla", "twophase"):
            def step(qq, impl=impl):
                # indices folded in: see _bench_knn on dead-coding
                if impl == "twophase":
                    d, i = fused_knn_twophase(index, qq, 100)
                else:
                    d, i = fused_l2_knn(index, qq, 100, impl=impl)
                return d + i.astype(d.dtype)
            try:
                dt = _time_chained(step, queries, 2)
            except Exception as e:
                # one impl's failure must not forfeit the others'
                # banked numbers; a dead device fails them all anyway
                out[impl + "_error"] = str(e)[-300:]
                if any(s in str(e) for s in _DEAD_SIGNS):
                    raise
                continue
            out[impl + "_seconds_per_batch"] = round(dt, 4)
            out[impl + "_qps_100k"] = round(1024 / dt, 1)
            out[impl + "_mfu"] = _mfu(2.0 * 1024 * 100_000 * 128, dt)
    return out


def _bench_knn_twophase_1m(state):
    """North-star shape on the two-phase kernel — only once it has
    proven correct AND fastest at 100k (pallas_check); assemble() picks
    the best 1M rung, so this can only improve the headline."""
    p = state.get("pallas_check", {})
    if not (p.get("twophase_dist_close") and p.get("twophase_idx_match")):
        return {"status": "skipped_twophase_not_validated"}
    t_qps = p.get("twophase_qps_100k", 0)
    if not (t_qps > p.get("xla_qps_100k", 0)
            and t_qps > p.get("pallas_qps_100k", 0)):
        return {"status": "skipped_twophase_not_faster"}
    from raft_tpu.ops.knn_tile import fused_knn_twophase

    # 1024-query batches, block_n=2048: the candidate buffer is
    # (n_query, n_tiles*kpad) — at 10k queries x 977 tiles it would be
    # ~10 GB + sort copies, past v5e HBM.  At 1024 x 489 tiles it is
    # ~0.5 GB; qps extrapolates per batch exactly like the 100k rungs.
    n_index, n_query, dim, k = 1_000_000, 1024, 128, 100
    index = _rand((n_index, dim), 3)
    queries = _rand((n_query, dim), 4)

    def step(q):
        d, i = fused_knn_twophase(  # block-shape-ok: attribution probe
            index, q, k, block_n=2048)
        return d + i.astype(d.dtype)

    dt = _time_chained(step, queries, 2)
    # same bar as the headline knn_1m rung: a NEW kernel path must
    # never set the headline on chained timing alone
    wall = _wall_check(step, queries)
    qps = n_query / dt
    return {
        "qps": round(qps, 1),
        "seconds_per_batch": round(dt, 4),
        "wall_seconds_per_batch": round(wall, 4),
        "n_index": n_index, "n_query": n_query, "dim": dim, "k": k,
        "impl": "twophase", "block_n": 2048,
        "mfu": _mfu(2.0 * n_query * n_index * dim, dt),
    }


def _bench_knn_bf16(n_index, n_query, iters):
    """Informational rung: kNN with single-pass bf16 MXU matmuls
    (precision='default') — the apples-to-apples mode against TF32-class
    GPU tensor-core paths.  The headline stays f32-'highest'; this rung
    reports the speed headroom AND the recall cost so the trade is
    visible, not hidden."""
    import numpy as np

    from raft_tpu.spatial.fused_l2_knn import fused_l2_knn

    from raft_tpu.spatial import brute_force_knn

    dim, k = 128, 100
    index = _rand((n_index, dim), 3)
    queries = _rand((n_query, dim), 4)

    def step(q):
        # indices folded in: see _bench_knn on dead-coding
        d, i = brute_force_knn([index], q, k, precision="default")
        return d + i.astype(d.dtype)

    dt = _time_chained(step, queries, iters)
    # recall@k of bf16 vs exact through the SAME public path as the
    # timing (auto impl: pallas on TPU) — speed and accuracy must
    # describe one kernel, not two
    probe = queries[:256]
    _, i_fast = brute_force_knn([index], probe, k, precision="default")
    _, i_ref = brute_force_knn([index], probe, k)
    i_fast, i_ref = np.asarray(i_fast), np.asarray(i_ref)
    recall = float(np.mean([
        len(set(i_fast[r]) & set(i_ref[r])) / k
        for r in range(i_fast.shape[0])]))
    qps = n_query / dt
    return {
        "qps": round(qps, 1),
        "qps_1m_equiv": round(qps * n_index / 1_000_000, 1),
        "seconds_per_batch": round(dt, 4),
        "n_index": n_index, "n_query": n_query, "dim": dim, "k": k,
        "precision": "default(bf16)",
        "impl": "auto (pallas on TPU, xla elsewhere)",
        "recall_at_k_vs_f32": round(recall, 4),
        "mfu": _mfu(2.0 * n_query * n_index * dim, dt),
        "note": "informational; headline rungs are f32-highest",
    }


def _bench_knn_rerank(n_index, n_query, iters, ratio=4):
    """bf16 scan + exact f32 re-rank (brute_force_knn rerank_ratio):
    the bf16 rung's speed with the candidate-set safety net.  Reports
    measured recall vs the f32 path; exact whenever the true top-k
    survive the bf16 stage-1."""
    import numpy as np

    from raft_tpu.spatial import brute_force_knn

    dim, k = 128, 100
    index = _rand((n_index, dim), 3)
    queries = _rand((n_query, dim), 4)

    def step(q):
        # indices folded in: see _bench_knn on dead-coding
        d, i = brute_force_knn([index], q, k, rerank_ratio=ratio)
        return d + i.astype(d.dtype)

    dt = _time_chained(step, queries, iters)
    probe = queries[:256]
    _, i_fast = brute_force_knn([index], probe, k, rerank_ratio=ratio)
    _, i_ref = brute_force_knn([index], probe, k)
    i_fast, i_ref = np.asarray(i_fast), np.asarray(i_ref)
    recall = float(np.mean([
        len(set(i_fast[r]) & set(i_ref[r])) / k
        for r in range(i_fast.shape[0])]))
    qps = n_query / dt
    return {
        "qps": round(qps, 1),
        "qps_1m_equiv": round(qps * n_index / 1_000_000, 1),
        "seconds_per_batch": round(dt, 4),
        "n_index": n_index, "n_query": n_query, "dim": dim, "k": k,
        "rerank_ratio": ratio,
        "recall_at_k_vs_f32": round(recall, 4),
        "mfu": _mfu(2.0 * n_query * n_index * dim, dt),
        "note": "bf16 stage-1 + exact f32 re-rank",
    }


def _bench_knn_recall95(n_index, n_query, iters):
    """Informational rung: kNN with the ``approx95`` selection impl
    (``approx_max_k`` at recall_target 0.95) — unlike ``approx``/recall
    1.0, whose partial reduce cannot drop anything and degenerates to
    the same sort as top_k (measured identical QPS), this genuinely
    shrinks the PartialReduce width.  Reports measured recall so the
    speed/accuracy trade is visible; headline rungs stay exact."""
    import numpy as np

    from raft_tpu.spatial import brute_force_knn

    out = _bench_knn(n_index, n_query, iters, "xla",
                     select_impl="approx95")
    # recall probe traced with the same impls as the timing: BOTH env
    # pins — on TPU the fused-kNN auto-dispatch otherwise resolves to
    # the Pallas kernel, which never consults the select impl, and the
    # probe would measure the exact kernel against itself (recall ~1.0
    # regardless — r4 code-review finding)
    index = _rand((n_index, 128), 3)
    probe = _rand((n_query, 128), 4)[:256]
    with _env_pins({"RAFT_TPU_FUSED_KNN_IMPL": "xla",
                    "RAFT_TPU_SELECT_IMPL": "approx95"}):
        _, i_fast = brute_force_knn([index], probe, 100)
    _, i_ref = brute_force_knn([index], probe, 100)
    i_fast, i_ref = np.asarray(i_fast), np.asarray(i_ref)
    out["recall_at_k_vs_exact"] = round(float(np.mean([
        len(set(i_fast[r]) & set(i_ref[r])) / 100
        for r in range(i_fast.shape[0])])), 4)
    out["note"] = "informational; headline rungs are exact"
    return out


def _bench_fused_nn(n, n_centroids, dim, iters):
    """Fused 1-NN (fusedL2NN analog) at the IVF coarse-assign scale:
    n points against n_centroids, the kmeans-assignment inner op."""
    from raft_tpu.distance import fused_l2_nn

    x = _rand((n, dim), 13)
    c = _rand((n_centroids, dim), 14)

    def make_step(impl):
        def step(a):
            # tile_n=512: the exact configuration the kmeans large-k
            # assignment runs (kmeans.py assign), so this rung measures
            # the real IVF coarse-assign op, not a different block
            # size.  argmin ids folded in: see _bench_knn.
            vals, ids = fused_l2_nn(a, c, tile_n=512, impl=impl)
            return vals + ids.astype(vals.dtype)
        return step

    dt = _time_chained(make_step(None), x, iters)
    out = {
        "seconds_per_call": round(dt, 4),
        "n": n, "n_centroids": n_centroids, "dim": dim,
        "assigns_per_sec": round(n / dt, 1),
        "impl": "auto (pallas on TPU, xla elsewhere)",
        "mfu": _mfu(2.0 * n * n_centroids * dim, dt),
    }
    # both impls timed ON TPU only (elsewhere auto IS xla and the
    # second chain would time the same impl twice): the 1-NN kernel has
    # no steady-state comparison yet (the kNN kernel's r4 lesson:
    # measure, don't assume)
    from raft_tpu.core.utils import is_tpu_backend

    if is_tpu_backend():
        try:
            dt_x = _time_chained(make_step("xla"), x, iters)
            out["xla_seconds_per_call"] = round(dt_x, 4)
            out["xla_assigns_per_sec"] = round(n / dt_x, 1)
        except Exception as e:
            if any(s in str(e) for s in _DEAD_SIGNS):
                raise
            out["xla_error"] = traceback.format_exc()[-300:]
    return out


def _bench_ivf(n_index, n_query, iters, build, search, params,
               alt_env=None):
    """Shared IVF rung driver: build once (untimed), timed search, and
    recall@10 against brute force on a probe slice — throughput without
    recall is not an ANN benchmark.  Index and queries split from ONE
    make_blobs call so both draw from the same 256 centers (the
    realistic in-distribution ANN regime; pure random Gaussian has no
    neighbor structure and understates every IVF index's recall —
    measured 0.37 vs 1.0)."""
    import numpy as np
    import jax.numpy as jnp

    from raft_tpu.spatial import brute_force_knn

    dim, k, nprobe = 128, 10, 32
    X_all, _ = make_blobs(np.random.default_rng(15), n_index + n_query,
                          dim, 256, spread=0.35)
    index_data = jnp.asarray(X_all[:n_index])
    queries = jnp.asarray(X_all[n_index:])
    idx = build(index_data)

    def step(q):
        # ids folded in: see _bench_knn on dead-coding
        d, i = search(idx, q, k=k, nprobe=nprobe)
        return d + i.astype(d.dtype)

    dt = _time_chained(step, queries, iters)
    probe = queries[:256]
    _, ii = search(idx, probe, k=k, nprobe=nprobe)
    _, ri = brute_force_knn([index_data], probe, k)
    ii, ri = np.asarray(ii), np.asarray(ri)
    recall = float(np.mean([
        len(set(ii[r]) & set(ri[r])) / k for r in range(ii.shape[0])]))
    out = {
        "qps": round(n_query / dt, 1),
        "seconds_per_batch": round(dt, 4),
        "n_index": n_index, "n_query": n_query, "dim": dim,
        "k": k, "nprobe": nprobe,
        "recall_at_10_vs_exact": round(recall, 4),
    }
    if alt_env:
        # re-time the SAME built index under alternative env pins (e.g.
        # the PQ ADC impls) — the hardware picks defaults, not
        # intuition.  A failed alt pass is recorded without forfeiting
        # the rung's headline result — EXCEPT dead-device errors, which
        # must propagate to child_main's consecutive_dead abort, not be
        # recorded as a note while later rungs burn the budget against
        # a dead channel.
        for tag, pins in alt_env.items():
            try:
                with _env_pins(pins):
                    dt_a = _time_chained(step, queries, iters)
                out[tag + "_qps"] = round(n_query / dt_a, 1)
            except Exception as e:
                if any(s in str(e) for s in _DEAD_SIGNS):
                    raise
                out[tag + "_error"] = traceback.format_exc()[-300:]
    out.update(params)
    return out


def _bench_serve(index_rows, dim, k, duration, concurrency):
    """Serving-layer rung: closed-loop clients against a warmed
    KNNService (docs/SERVING.md).  Unlike the raw-primitive rungs this
    measures the whole request path — queueing, coalescing, padding,
    split — so its QPS is the number the north star ("serves heavy
    traffic") is actually about; the raw kNN rungs bound it from above.
    Client-observed latency percentiles ride along, plus the padding
    waste the bucket ladder cost."""
    from tools.loadgen import build_service, run_load

    svc = build_service("knn", index_rows, dim, k,
                        max_batch_rows=256, max_wait_ms=1.0,
                        queue_cap=4096)
    t0 = time.time()
    svc.warmup()
    warmup_s = time.time() - t0
    try:
        rep = run_load(svc, mode="closed", duration=duration,
                       concurrency=concurrency, rows=4)
    finally:
        svc.close()
    return {
        "qps": rep["qps"],
        "p50_ms": rep["p50_ms"],
        "p95_ms": rep["p95_ms"],
        "p99_ms": rep["p99_ms"],
        "requests_ok": rep["requests_ok"],
        "rejected": rep["rejected"],
        "errors": rep["errors"],
        "mean_batch_rows": round(rep["mean_batch_rows"], 2),
        "padding_waste": round(rep["padding_waste"], 4),
        "warmup_s": round(warmup_s, 3),
        "config": {"index_rows": index_rows, "dim": dim, "k": k,
                   "concurrency": concurrency, "rows_per_request": 4,
                   "max_batch_rows": 256},
    }


def _bench_serve_trace_overhead(index_rows, dim, k, duration,
                                concurrency):
    """Flight-recorder cost rung (docs/OBSERVABILITY.md "Flight
    recorder & request tracing"): the observability layer must prove
    its own price.  Runs the serve_knn closed-loop workload three
    times per arm — recorder+tracing ON vs disabled (the
    RAFT_TPU_FLIGHT=0 baseline) — interleaved A/B with best-of-three
    per arm to damp scheduler noise, and asserts the qps overhead
    ≤ 3% with 0 post-warmup compiles and the recorder ring within its
    configured bound (the always-on claim is only honest with all
    three)."""
    from raft_tpu.core import flight
    from tools.loadgen import build_service, run_load

    # ONE service, warmed once, shared by every run: arm-to-arm
    # variance from index synthesis / warmup / allocator state would
    # otherwise swamp the few-percent effect under measurement
    svc = build_service("knn", index_rows, dim, k,
                        max_batch_rows=256, max_wait_ms=1.0,
                        queue_cap=4096)
    svc.warmup()
    per_run = max(1.0, duration / 3)
    offs, ons = [], []
    was_enabled = flight.is_enabled()
    try:
        # discarded priming run: the first seconds of closed-loop
        # traffic in a fresh process run ~15% slow regardless of arm
        # (thread pools, allocator, dispatch caches warming) — measured
        # windows must start from the plateau or the first arm eats
        # the warm-in as fake overhead
        run_load(svc, mode="closed", duration=max(2.0, per_run),
                 concurrency=concurrency, rows=4)
        # 3 interleaved runs per arm, best-of: scheduler/thermal drift
        # hits both arms alike, the max reports each arm's capability
        # rather than its unluckiest window
        for _ in range(3):
            flight.set_enabled(False)
            offs.append(run_load(svc, mode="closed", duration=per_run,
                                 concurrency=concurrency, rows=4))
            flight.set_enabled(True)
            ons.append(run_load(svc, mode="closed", duration=per_run,
                                concurrency=concurrency, rows=4))
    finally:
        # restore the CALLER's recording state — a RAFT_TPU_FLIGHT=0
        # run must not have this rung force recording back on for
        # every later rung in the same child process
        flight.set_enabled(was_enabled)
        svc.close()
    qps_off = max(r["qps"] for r in offs)
    qps_on = max(r["qps"] for r in ons)
    overhead = 1.0 - qps_on / qps_off if qps_off else 0.0
    rec = flight.default_recorder()
    best_on = max(ons, key=lambda r: r["qps"])
    from raft_tpu import config as _rt_config
    configured_cap = int(_rt_config.get("flight_events"))
    return {
        "qps_on": qps_on,
        "qps_off": qps_off,
        "overhead_frac": round(overhead, 4),
        # the acceptance bound: tracing on costs <= 3% qps
        "overhead_ok": overhead <= 0.03,
        "post_warmup_compiles": best_on["post_warmup_compiles"],
        "recorder_events": len(rec),
        "recorder_capacity": rec.capacity,
        # retained events vs the CONFIGURED bound (not the deque's own
        # maxlen, which would be true by construction): a recorder
        # built without the bound, or sized off-knob, fails here
        "recorder_bounded": len(rec) <= configured_cap,
        "p99_on_ms": best_on["p99_ms"],
        "p99_off_ms": max(offs, key=lambda r: r["qps"])["p99_ms"],
        "config": {"index_rows": index_rows, "dim": dim, "k": k,
                   "concurrency": concurrency, "rows_per_request": 4,
                   "runs_per_arm": 3, "shared_service": True},
    }


def _bench_ops_scrape_overhead(index_rows, dim, k, duration,
                               concurrency):
    """Ops-plane cost + completeness rung (docs/OBSERVABILITY.md "Ops
    plane").  Four claims, all on one shared warmed service:

    1. **Scrape price**: closed-loop QPS with a 1 Hz scraper pulling
       /metrics + /statusz + /healthz vs the same load unscraped —
       interleaved A/B, best-of-3 per arm (the serve_trace_overhead
       discipline), overhead must hold <= 3% with every scrape
       succeeding and 0 post-warmup compiles (the handlers' no-jax
       ban made real).
    2. **Program inventory completeness**: after warmup the cost
       inventory must list the service's cached search program at
       every bucket rung, each entry with nonzero cost-model
       flops/bytes — the device-capacity picture is only a picture if
       it is complete.
    3. **Anomaly sentinel**: a serve-seam Delay fault (the injected
       latency regression) must trip the exec_latency rule after a
       healthy baseline, flip /healthz degraded, and
    4. the automatic black-box dump must contain the breaching batch
       (an execute bracket whose exec_s carries the delay).
    """
    import urllib.error
    import urllib.request

    from raft_tpu.comms import faults
    from raft_tpu.core import flight, inventory
    from raft_tpu.core.metrics import parse_prometheus
    from raft_tpu.serve.opsplane import OpsPlane
    from raft_tpu.serve.resilience import inject_worker
    from tools.loadgen import build_service, run_load

    svc = build_service("knn", index_rows, dim, k,
                        max_batch_rows=256, max_wait_ms=1.0,
                        queue_cap=4096)
    svc.warmup()

    # -- 2: inventory completeness (before any fault noise) ---------- #
    inv = inventory.snapshot()
    # the serve path compiles the scan's donating twin by default —
    # count every tiled_knn-family executable against the rung ladder.
    # The nonzero check is scoped to THIS rung's program family: the
    # inventory is process-global and other rungs' programs (or a
    # backend that cannot answer cost_analysis) may legitimately
    # record zeros without invalidating the knn completeness claim
    knn_entries = {k: e for fn, keys in inv.items()
                   if fn.startswith("tiled_knn")
                   for k, e in keys.items()}
    inventory_complete = (
        len(knn_entries) >= len(svc.policy.rungs)
        and all(e["flops"] > 0 and e["bytes_accessed"] > 0
                for e in knn_entries.values()))

    plane = OpsPlane(services={svc.name: svc}, port=0,
                     sentinel_interval_s=0.25)
    url = plane.url
    scrape = {"n": 0, "failures": 0}
    scraping = threading.Event()
    stop = threading.Event()

    def scraper():
        while not stop.is_set():
            if not scraping.is_set():
                stop.wait(timeout=0.05)
                continue
            try:
                with urllib.request.urlopen(url + "/metrics",
                                            timeout=5) as resp:
                    parsed = parse_prometheus(
                        resp.read().decode("utf-8"))
                if "raft_tpu_serve_requests_total" not in parsed:
                    raise ValueError("scrape missing serve families")
                urllib.request.urlopen(url + "/statusz",
                                       timeout=5).close()
                try:
                    urllib.request.urlopen(url + "/healthz",
                                           timeout=5).close()
                except urllib.error.HTTPError:
                    pass  # 503-degraded is still a served scrape
            except Exception:
                scrape["failures"] += 1
            scrape["n"] += 1
            stop.wait(timeout=1.0)

    thread = threading.Thread(target=scraper, daemon=True)
    thread.start()
    per_run = max(1.0, duration / 3)
    offs, ons = [], []
    try:
        # discarded priming run (thread pools / allocator warm-in —
        # the serve_trace_overhead lesson); also feeds the sentinel
        # its healthy latency baseline
        run_load(svc, mode="closed", duration=max(2.0, per_run),
                 concurrency=concurrency, rows=4)
        for _ in range(3):
            scraping.clear()
            offs.append(run_load(svc, mode="closed",
                                 duration=per_run,
                                 concurrency=concurrency, rows=4))
            scraping.set()
            ons.append(run_load(svc, mode="closed",
                                duration=per_run,
                                concurrency=concurrency, rows=4))
        scraping.clear()

        # -- 3 + 4: injected latency fault trips the sentinel ------- #
        plane.sentinel.tick(force=True)   # settle the baseline
        delay_s = 0.3
        with inject_worker(svc.worker, faults.Delay(delay_s)):
            for _ in range(3):
                for f in svc.submit_many([svc.index[:4],
                                          svc.index[4:8]]):
                    f.result(timeout=60)
                plane.sentinel.tick(force=True)
        tripped_rules = [a["rule"] for a in plane.sentinel.active()]
        try:
            urllib.request.urlopen(url + "/healthz", timeout=5)
            healthz_degraded = False
        except urllib.error.HTTPError as e:
            healthz_degraded = e.code == 503
        boxes = [b for b in flight.default_recorder().blackboxes()
                 if b["reason"].startswith("anomaly_")]
        blackbox_has_batch = any(
            ev.get("kind") == "execute_ready"
            and ev.get("exec_s", 0.0) >= delay_s
            for b in boxes for ev in b["events"])
    finally:
        stop.set()
        thread.join(timeout=10.0)
        plane.close()
        svc.close()
    qps_off = max(r["qps"] for r in offs)
    qps_on = max(r["qps"] for r in ons)
    overhead = 1.0 - qps_on / qps_off if qps_off else 0.0
    best_on = max(ons, key=lambda r: r["qps"])
    sentinel_tripped = "exec_latency" in tripped_rules
    return {
        "qps_scraped": qps_on,
        "qps_unscraped": qps_off,
        "overhead_frac": round(overhead, 4),
        "overhead_ok": overhead <= 0.03,
        "scrapes": scrape["n"],
        "scrape_failures": scrape["failures"],
        "post_warmup_compiles": best_on["post_warmup_compiles"],
        "inventory_programs": inventory.entry_count(),
        "inventory_rung_entries": len(knn_entries),
        "inventory_complete": inventory_complete,
        "sentinel_tripped": sentinel_tripped,
        "sentinel_rules": sorted(set(tripped_rules)),
        "healthz_degraded": healthz_degraded,
        "blackbox_has_breaching_batch": blackbox_has_batch,
        "ops_ok": (overhead <= 0.03 and scrape["n"] > 0
                   and scrape["failures"] == 0
                   and best_on["post_warmup_compiles"] == 0
                   and inventory_complete and sentinel_tripped
                   and healthz_degraded and blackbox_has_batch),
        "config": {"index_rows": index_rows, "dim": dim, "k": k,
                   "concurrency": concurrency, "rows_per_request": 4,
                   "runs_per_arm": 3, "scrape_hz": 1.0,
                   "delay_s": 0.3, "shared_service": True},
    }


def _bench_serve_sharded(index_rows, dim, k, duration, concurrency,
                         rows=16, merge="hierarchical",
                         sizes=(1, 2, 4, 8)):
    """Sharded SPMD serving rung (docs/SERVING.md "Sharded serving"):
    the same KNNService workload served over a mesh-sharded index at
    1/2/4/8 devices — the capacity axis measured, not asserted.  Each
    mesh size serves the IDENTICAL index/k/query pool through the
    pjit'd per-shard search + on-device top-k merge, so the scaling
    table isolates what the mesh buys (per-shard scan is 1/N of the
    rows; the merge is the price).  Virtual-CPU-mesh caveat: the 8
    "devices" share this host's cores, so compute-bound scaling here
    is bounded by core count — the table still proves per-device work
    drops with N, executables stay per-rung-cached (0 post-warmup
    compiles) and the data path stays device-resident (0 host-staged
    bytes); ICI-real speedups need hardware.  A quick per-topology A/B
    (allgather / ring / hierarchical) at the top size rides along."""
    import jax
    import jax.numpy as jnp

    from raft_tpu.comms.host_comms import default_mesh
    from raft_tpu.serve import KNNService
    from tools.loadgen import make_query_pool, run_load, synth_data

    ref = jnp.asarray(synth_data(index_rows, dim, seed=0))
    pool = make_query_pool(ref, rows, seed=1)
    n_avail = len(jax.devices())
    mbr = 128

    def one(n_dev, topo, dur):
        mesh = default_mesh(n_dev)
        t0 = time.time()
        svc = KNNService(ref, k=k, mesh=mesh, axis=mesh.axis_names[0],
                         merge=topo, max_batch_rows=mbr,
                         bucket_rungs=(8, 32, 64, mbr),
                         max_wait_ms=2.0, queue_cap=4096)
        svc.warmup()
        warm = time.time() - t0
        try:
            rep = run_load(svc, mode="closed", duration=dur,
                           concurrency=concurrency, rows=rows,
                           query_pool=pool)
        finally:
            svc.close()
        return {
            "n_devices": n_dev,
            "qps": rep["qps"],
            "query_qps": rep["query_qps"],
            "query_qps_per_device": round(rep["query_qps"] / n_dev, 1),
            "p50_ms": rep["p50_ms"],
            "p99_ms": rep["p99_ms"],
            "post_warmup_compiles": rep["post_warmup_compiles"],
            "host_staged_bytes": rep["host_staged_bytes"],
            "warmup_s": round(warm, 2),
        }

    table = [one(n, merge, duration) for n in sizes if n <= n_avail]
    top = table[-1]
    out = {
        "qps": top["qps"],
        "query_qps": top["query_qps"],
        "n_devices": top["n_devices"],
        "merge": merge,
        "post_warmup_compiles": top["post_warmup_compiles"],
        "host_staged_bytes": top["host_staged_bytes"],
        "scaling": table,
        "config": {"index_rows": index_rows, "dim": dim, "k": k,
                   "concurrency": concurrency, "rows_per_request": rows,
                   "max_batch_rows": mbr, "merge": merge},
    }
    if len(table) > 1:
        out["speedup_%dx_vs_1x" % top["n_devices"]] = round(
            top["query_qps"] / table[0]["query_qps"], 2)
    # merge-topology A/B at the top size (short runs: the knob choice,
    # not the headline).  The default topology's number is already in
    # the scaling table — don't pay its warmup/run twice.
    out["merge_topologies"] = {
        topo: (top["query_qps"] if topo == merge
               else one(top["n_devices"], topo,
                        max(1.0, duration / 2))["query_qps"])
        for topo in ("allgather", "ring", "hierarchical")}
    return out


def _bench_serve_mixed_tenant(index_rows, dim, k, duration,
                              interactive_conc, bulk_qps,
                              bulk_rows=16, queue_cap=64,
                              max_batch_rows=64):
    """Traffic-shaping rung (docs/SERVING.md "Traffic shaping"): the
    multi-tenant isolation claim, measured.  One weighted-fair
    KNNService (interactive:4, bulk:1) takes closed-loop interactive
    clients and an open-loop bulk flood AT ONCE; the rung first runs
    the interactive class SOLO for its baseline p99, then the mixed
    scenario, and reports the ratio — ``isolation_ok`` asserts the
    interactive p99 stayed within 2x of its solo run while the bulk
    tenant saturated its quota (sheds > 0 proves saturation, and every
    shed is typed with a retry_after_s hint).  Without weighted-fair
    admission the bulk flood owns the whole queue cap and the
    interactive class starves — the single global cap this rung
    replaces."""
    from tools.loadgen import build_service, run_load, run_mixed_tenants

    # window sized so a mixed batch (interactive rows + bulk's DRR
    # quota) lands on a rung NEAR the solo batch's rung: exec time
    # scales with the padded rung, and the quota — not backfill — is
    # what bounds the mixed rung (docs/SERVING.md "Traffic shaping")
    svc = build_service("knn", index_rows, dim, k,
                        max_batch_rows=max_batch_rows, max_wait_ms=1.0,
                        queue_cap=queue_cap,
                        tenant_weights={"interactive": 4, "bulk": 1})
    t0 = time.time()
    svc.warmup()
    warmup_s = time.time() - t0
    try:
        solo = run_load(svc, mode="closed", duration=max(1.5,
                                                         duration / 2),
                        concurrency=interactive_conc, rows=4,
                        tenant="interactive")
        mixed = run_mixed_tenants(
            svc, duration=duration,
            interactive_concurrency=interactive_conc,
            bulk_qps=bulk_qps, interactive_rows=4, bulk_rows=bulk_rows)
    finally:
        svc.close()
    inter = mixed["tenants"]["interactive"]
    bulk = mixed["tenants"]["bulk"]
    solo_p99 = max(solo["p99_ms"], 1e-3)
    ratio = inter["p99_ms"] / solo_p99
    return {
        "interactive_solo_p99_ms": solo["p99_ms"],
        "interactive_mixed_p99_ms": inter["p99_ms"],
        "interactive_p99_ratio": round(ratio, 2),
        "interactive_qps": inter["qps"],
        "bulk_qps": bulk["qps"],
        "bulk_sheds": bulk["rejected"],
        "bulk_saturated": bulk["rejected"] > 0,
        "untyped_sheds": mixed["untyped_sheds"],
        # the acceptance statement: interactive p99 within 2x solo
        # while the bulk tenant saturates its quota, all sheds typed
        "isolation_ok": (ratio <= 2.0 and bulk["rejected"] > 0
                         and mixed["untyped_sheds"] == 0),
        "post_warmup_compiles": mixed["post_warmup_compiles"],
        "warmup_s": round(warmup_s, 3),
        "config": {"index_rows": index_rows, "dim": dim, "k": k,
                   "interactive_concurrency": interactive_conc,
                   "bulk_qps": bulk_qps, "bulk_rows": bulk_rows,
                   "queue_cap": queue_cap,
                   "max_batch_rows": max_batch_rows,
                   "tenant_weights": {"interactive": 4, "bulk": 1}},
    }


def _bench_serve_ann(index_rows, dim, k, duration, concurrency, nlist,
                     train_rows, target_recall, state=None, rows=16):
    """ANN serving rung (docs/SERVING.md): the whole request path
    against a warmed ANNService fronting an IVF-Flat index at the
    north-star scale, with nprobe CALIBRATED to a recall target rather
    than hand-pinned, and recall@k measured against brute-force ground
    truth during the load run — the QPS claim and its quality number
    are one measurement.  Data is a gaussian mixture (the shape real
    embedding workloads have; ground truth is brute force over the same
    data, so the recall number stays honest) and queries are drawn near
    the data.  Reports the speedup over the knn_1m brute-force rung
    when that rung has run in this session."""
    import jax.numpy as jnp

    from tools.loadgen import build_service, make_query_pool, run_load

    t_build = time.time()
    # shape choices are measured, not guessed (the CUDA-L2 stance):
    # few clients x 16-row requests beat many x 4-row at equal
    # in-flight rows (per-request split/score overhead rides the GIL),
    # and the rung ladder tops out at 128 so a half-full batch pads to
    # 64, not 256
    mbr = 128
    svc = build_service(
        "ann", index_rows, dim, k, clusters=256,
        nlist=nlist, train_rows=train_rows,
        max_batch_rows=mbr,
        bucket_rungs=(8, 32, 64, mbr),
        max_wait_ms=2.0, queue_cap=4096,
        nprobe_ladder=(4, 6, 8, 16),
        # membership-exact approx top-k: measured ~2x the whole-scan
        # throughput of the full-sort payload path at k=100 (CPU); the
        # recall number in this report is measured THROUGH it
        select_impl="approx")
    build_s = time.time() - t_build
    t0 = time.time()
    svc.warmup()
    warmup_s = time.time() - t0
    pool = make_query_pool(svc.loadgen_ref, rows, seed=1)
    cal = svc.calibrate(jnp.concatenate(pool[:2], axis=0),
                        target_recall, measure_all=True)
    try:
        rep = run_load(svc, mode="closed", duration=duration,
                       concurrency=concurrency, rows=rows, recall=True,
                       query_pool=pool)
    finally:
        svc.close()
    out = {
        "qps": rep["qps"],
        "query_qps": rep["query_qps"],
        "recall_at_k": rep.get("recall_at_k"),
        "p50_ms": rep["p50_ms"],
        "p95_ms": rep["p95_ms"],
        "p99_ms": rep["p99_ms"],
        "requests_ok": rep["requests_ok"],
        "rejected": rep["rejected"],
        "errors": rep["errors"],
        "post_warmup_compiles": rep["post_warmup_compiles"],
        "host_staged_bytes": rep["host_staged_bytes"],
        "nprobe": svc.nprobe,
        "calibration": cal,
        "mean_batch_rows": round(rep["mean_batch_rows"], 2),
        "build_s": round(build_s, 2),
        "warmup_s": round(warmup_s, 3),
        "config": {"index_rows": index_rows, "dim": dim, "k": k,
                   "nlist": nlist, "train_rows": train_rows,
                   "target_recall": target_recall,
                   "concurrency": concurrency, "rows_per_request": rows,
                   "max_batch_rows": mbr, "select_impl": "approx",
                   "clusters": 256},
    }
    base = (state or {}).get("knn_1m", {}).get("qps")
    if base:
        # the brute-force baseline this rung exists to beat (same
        # 1Mx128 content scale, same k; knn_1m counts query rows, so
        # the ratio uses row-level throughput)
        out["baseline_knn_1m_qps"] = base
        out["speedup_vs_knn_1m"] = round(rep["query_qps"] / base, 1)
    return out


def _bench_serve_ann_ooc(index_rows, dim, k, duration, concurrency,
                         nlist, train_rows, state=None, rows=16,
                         budget_frac=0.25):
    """Out-of-core ANN serving rung (docs/SERVING.md "Out-of-core
    serving"): the SAME 1M x 128 k=100 workload as ``serve_ann_1m``,
    but served under a device budget of ``budget_frac`` of the slot
    store (~4x oversubscription) — the host-resident store streams
    through the hot set + double-buffered TilePool.  Three arms over
    one built index:

    - **resident** — the fully device-resident ANNService at the same
      fixed nprobe: the recall-equality reference and the
      ``qps_vs_resident`` denominator;
    - **ooc (double-buffered)** — the tier under test: recall@k must
      EQUAL the resident arm (same candidates, same arithmetic — the
      spatial/ooc.py identity contract), 0 post-warmup compiles, and
      the hidden-transfer fraction reports how much of the H2D wall
      the prefetch buried under the scans;
    - **ooc (synchronous prefetch)** — the same tier with the double
      buffer disabled: ``overlap_speedup`` is the measured win of
      issuing tile N+1's transfer before tile N's scan blocks, the
      number the whole design argument rests on.
    """
    import jax.numpy as jnp
    import numpy as np

    from raft_tpu.core.metrics import default_registry
    from raft_tpu.serve.ann_service import ANNService
    from raft_tpu.spatial.ann import IVFFlatParams, ivf_flat_build
    from raft_tpu.spatial.ooc import ivf_flat_to_ooc
    from tools.loadgen import make_query_pool, run_load, synth_data

    t_build = time.time()
    ref = jnp.asarray(synth_data(index_rows, dim, seed=0, clusters=256))
    index = ivf_flat_build(ref, IVFFlatParams(nlist=nlist, nprobe=8),
                           train_rows=train_rows)
    build_s = time.time() - t_build
    store_bytes = int(np.asarray(index.slot_vecs).nbytes)
    budget = max(1, int(store_bytes * budget_frac))
    mbr = 128
    svc_opts = dict(max_batch_rows=mbr, bucket_rungs=(8, 32, 64, mbr),
                    max_wait_ms=2.0, queue_cap=4096,
                    nprobe_ladder=(4, 8), nprobe=8,
                    select_impl="approx", compact_rows=0)
    pool = make_query_pool(ref, rows, n=8, seed=1)

    def pool_stat(name, svc_name, attr="value"):
        fam = default_registry().get(name)
        if fam is None:
            return 0.0
        for labels, series in fam.series():
            if labels.get("pool") == svc_name:
                return float(getattr(series, attr))
        return 0.0

    def run_arm(svc, dur, recall):
        svc.loadgen_ref = ref
        t0 = time.time()
        svc.warmup()
        warm = time.time() - t0
        base = {n: pool_stat(n, svc.name) for n in
                ("raft_tpu_tile_hits_total", "raft_tpu_tile_misses_total",
                 "raft_tpu_h2d_bytes_total")}
        h2d0 = pool_stat("raft_tpu_h2d_seconds", svc.name, "total")
        stall0 = pool_stat("raft_tpu_h2d_stall_seconds", svc.name,
                           "total")
        try:
            rep = run_load(svc, mode="closed", duration=dur,
                           concurrency=concurrency, rows=rows,
                           recall=recall, query_pool=pool)
        finally:
            svc.close()
        hits = pool_stat("raft_tpu_tile_hits_total", svc.name) \
            - base["raft_tpu_tile_hits_total"]
        miss = pool_stat("raft_tpu_tile_misses_total", svc.name) \
            - base["raft_tpu_tile_misses_total"]
        h2d_t = pool_stat("raft_tpu_h2d_seconds", svc.name,
                          "total") - h2d0
        stall_t = pool_stat("raft_tpu_h2d_stall_seconds", svc.name,
                            "total") - stall0
        rep["warmup_s"] = round(warm, 2)
        if hits or miss:
            # load-window deltas (warmup streams tiles too)
            rep["tile_hit_rate"] = round(hits / (hits + miss), 4) \
                if hits + miss else 0.0
            rep["h2d_mb"] = round(
                (pool_stat("raft_tpu_h2d_bytes_total", svc.name)
                 - base["raft_tpu_h2d_bytes_total"]) / 1e6, 1)
            rep["hidden_transfer_frac"] = round(
                1.0 - stall_t / h2d_t, 4) if h2d_t else 0.0
        return rep

    # resident reference arm (same fixed nprobe -> same candidates)
    resident = run_arm(ANNService(index, k=k, **svc_opts),
                       max(1.5, duration / 2), recall=True)
    ooc_index = ivf_flat_to_ooc(index)
    del index  # frees the device slot store before the streamed arms
    ooc = run_arm(ANNService(ooc_index, k=k,
                             device_budget_bytes=budget, **svc_opts),
                  duration, recall=True)
    # same duration as the overlapped arm: the A/B must compare equal
    # sample sizes (a 2-3-batch window on the CPU venue is noise)
    sync = run_arm(ANNService(ooc_index, k=k,
                              device_budget_bytes=budget,
                              ooc_overlap=False, **svc_opts),
                   duration, recall=False)
    out = {
        "query_qps": ooc["query_qps"],
        "qps": ooc["qps"],
        "recall_at_k": ooc.get("recall_at_k"),
        "resident_query_qps": resident["query_qps"],
        "resident_recall_at_k": resident.get("recall_at_k"),
        "recall_equal": (ooc.get("recall_at_k")
                         == resident.get("recall_at_k")),
        "qps_vs_resident": round(
            ooc["query_qps"] / max(resident["query_qps"], 1e-9), 3),
        "sync_query_qps": sync["query_qps"],
        "overlap_speedup": round(
            ooc["query_qps"] / max(sync["query_qps"], 1e-9), 3),
        "tile_hit_rate": ooc.get("tile_hit_rate"),
        "h2d_mb": ooc.get("h2d_mb"),
        "hidden_transfer_frac": ooc.get("hidden_transfer_frac"),
        "sync_hidden_transfer_frac": sync.get("hidden_transfer_frac"),
        "store_mb": round(store_bytes / 1e6, 1),
        "budget_mb": round(budget / 1e6, 1),
        "oversubscription": round(store_bytes / budget, 2),
        "p50_ms": ooc["p50_ms"],
        "p95_ms": ooc["p95_ms"],
        "p99_ms": ooc["p99_ms"],
        "post_warmup_compiles": ooc["post_warmup_compiles"],
        "host_staged_bytes": ooc["host_staged_bytes"],
        "build_s": round(build_s, 2),
        "warmup_s": ooc["warmup_s"],
        "config": {"index_rows": index_rows, "dim": dim, "k": k,
                   "nlist": nlist, "train_rows": train_rows,
                   "nprobe": 8, "budget_frac": budget_frac,
                   "concurrency": concurrency,
                   "rows_per_request": rows, "max_batch_rows": mbr,
                   "select_impl": "approx", "clusters": 256},
    }
    base_ann = (state or {}).get("serve_ann_1m", {}).get("query_qps")
    if base_ann:
        out["serve_ann_1m_query_qps"] = base_ann
    import jax

    if jax.default_backend() == "cpu":
        # the honest-venue caveat (the serve_knn_sharded precedent):
        # on the virtual CPU device "H2D" is a memcpy competing for
        # the same cores as the scan, so hiding it buys little wall
        # clock — hidden_transfer_frac still proves the transfers ride
        # behind the scans; the wall-clock overlap_speedup is the TPU
        # ladder's to prove, where the copy is a DMA the host does not
        # pay for
        out["note"] = ("virtual-CPU venue: transfer and scan share "
                       "the cores, so overlap_speedup ~1.0 here; "
                       "hidden_transfer_frac is the mechanism proof")
    return out


def _bench_serve_ann_persist(index_rows, dim, k, duration, concurrency,
                             nlist, train_rows, rows=16):
    """Durability rung (docs/PERSISTENCE.md): the cost of durable
    serving state, measured.  Two arms over ONE built IVF-Flat index,
    each driving closed-loop queries plus a steady insert stream:

    - **OFF** — the plain in-memory ANNService (the baseline);
    - **ON** — ``persist_dir`` + WAL ``fsync="always"`` (every insert
      durable before acknowledge) + periodic snapshots on the
      maintenance seam.

    ``persist_overhead_ok`` asserts the ON arm holds ≥ 70% of the OFF
    arm's steady-state QPS (the query path shares nothing with the
    WAL; the overhead is the insert fsyncs plus snapshot writes riding
    the maintenance seam).  Two restore rows follow: snapshot-only
    restore time (clean shutdown) and WAL-replay rate (simulated crash
    with a 2048-row WAL tail and only the bootstrap snapshot)."""
    import shutil
    import tempfile
    import threading as _threading

    import jax.numpy as jnp
    import numpy as np

    from raft_tpu.core.error import RaftError
    from raft_tpu.serve.ann_service import ANNService
    from raft_tpu.spatial.ann import IVFFlatParams, ivf_flat_build
    from tools.loadgen import make_query_pool, run_load, synth_data

    t_build = time.time()
    ref = jnp.asarray(synth_data(index_rows, dim, seed=0, clusters=256))
    index = ivf_flat_build(ref, IVFFlatParams(nlist=nlist, nprobe=8),
                           train_rows=train_rows)
    build_s = time.time() - t_build
    mbr = 128
    svc_opts = dict(max_batch_rows=mbr, bucket_rungs=(8, 32, 64, mbr),
                    max_wait_ms=2.0, queue_cap=4096,
                    nprobe_ladder=(4, 8), nprobe=8,
                    select_impl="approx", delta_cap=8192,
                    compact_rows=0)
    pool = make_query_pool(ref, rows, n=8, seed=1)

    def run_arm(persist_dir, dur):
        kw = dict(svc_opts)
        if persist_dir is not None:
            kw.update(persist_dir=persist_dir, persist_fsync="always",
                      snapshot_interval_s=max(1.0, dur / 3))
        svc = ANNService(index, k=k, **kw)
        svc.loadgen_ref = ref
        t0 = time.time()
        svc.warmup()
        warm = time.time() - t0
        stop = _threading.Event()
        inserted = {"n": 0}
        rng = np.random.default_rng(7)

        def inserter():
            base = 10_000_000
            while not stop.is_set():
                ids = np.arange(base + inserted["n"],
                                base + inserted["n"] + 16)
                try:
                    svc.insert(ids, rng.standard_normal(
                        (16, dim)).astype(np.float32))
                    inserted["n"] += 16
                except RaftError:
                    pass  # a full delta sheds in both arms alike
                time.sleep(0.01)

        th = _threading.Thread(target=inserter, daemon=True)
        th.start()
        persist_stats = None
        try:
            rep = run_load(svc, mode="closed", duration=dur,
                           concurrency=concurrency, rows=rows,
                           query_pool=pool)
        finally:
            stop.set()
            th.join(timeout=10.0)
            if persist_dir is not None:
                persist_stats = svc.stats().get("persist")
            svc.close()    # the ON arm's clean-shutdown final snapshot
        rep["warmup_s"] = round(warm, 2)
        rep["inserted_rows"] = inserted["n"]
        rep["persist"] = persist_stats
        return rep

    off = run_arm(None, duration)
    pdir = tempfile.mkdtemp(prefix="raft_tpu_bench_persist_")
    pdir2 = tempfile.mkdtemp(prefix="raft_tpu_bench_persist_wal_")
    try:
        on = run_arm(pdir, duration)
        # restore row 1: snapshot-only restore (the clean shutdown
        # above left an empty WAL — restart never pays replay)
        t0 = time.time()
        svc_r = ANNService(None, k=k,
                           **dict(svc_opts, persist_dir=pdir))
        restore_snapshot_s = time.time() - t0
        r_stats = svc_r._persist.stats()
        svc_r.close(snapshot=False)
        # restore row 2: WAL-replay rate — bootstrap snapshot only,
        # 2048 acknowledged rows living in the WAL, simulated crash
        svc_w = ANNService(index, k=k,
                           **dict(svc_opts, persist_dir=pdir2,
                                  persist_fsync="always",
                                  snapshot_interval_s=1e9))
        rngw = np.random.default_rng(11)
        wal_rows = 0
        for _ in range(16):
            ids = np.arange(20_000_000 + wal_rows,
                            20_000_000 + wal_rows + 128)
            svc_w.insert(ids, rngw.standard_normal(
                (128, dim)).astype(np.float32))
            wal_rows += 128
        svc_w.close(snapshot=False)
        t0 = time.time()
        svc_w2 = ANNService(None, k=k,
                            **dict(svc_opts, persist_dir=pdir2))
        restore_replay_s = time.time() - t0
        replayed = svc_w2._persist.stats()["replayed_records"]
        svc_w2.close(snapshot=False)
    finally:
        shutil.rmtree(pdir, ignore_errors=True)
        shutil.rmtree(pdir2, ignore_errors=True)
    ratio = on["qps"] / max(off["qps"], 1e-9)
    return {
        "query_qps_on": on["query_qps"],
        "query_qps_off": off["query_qps"],
        "qps_on": on["qps"],
        "qps_off": off["qps"],
        "persist_overhead_ratio": round(ratio, 3),
        "persist_overhead_ok": ratio >= 0.7,
        "p99_ms_on": on["p99_ms"],
        "p99_ms_off": off["p99_ms"],
        "inserted_rows_on": on["inserted_rows"],
        "inserted_rows_off": off["inserted_rows"],
        "snapshots_taken": (on["persist"] or {}).get("snapshot_seq"),
        "snapshot_bytes": (on["persist"] or {}).get("snapshot_bytes"),
        "restore_snapshot_s": round(restore_snapshot_s, 3),
        "restored_snapshot_seq": r_stats["snapshot_seq"],
        "restore_replay_s": round(restore_replay_s, 3),
        "wal_replay_rows": wal_rows,
        "wal_replay_records": replayed,
        "wal_replay_rows_per_s": round(
            wal_rows / max(restore_replay_s, 1e-9), 1),
        "post_warmup_compiles_on": on["post_warmup_compiles"],
        "build_s": round(build_s, 2),
        "config": {"index_rows": index_rows, "dim": dim, "k": k,
                   "nlist": nlist, "train_rows": train_rows,
                   "concurrency": concurrency,
                   "rows_per_request": rows, "fsync": "always",
                   "max_batch_rows": mbr},
    }


def _bench_serve_fleet(index_rows, dim, k, duration, concurrency,
                       nlist=16):
    """Fault-domain fleet rung (docs/FAULT_MODEL.md "Fleet fault
    domains"): the serving fleet measured end-to-end through the
    router process boundary, then put through the kill-one-worker
    drill.  Two parts:

    - **scaling table** — closed-loop router QPS with 1 worker vs 2.
      Informational on this box: the worker PROCESSES share the same
      host cores (the serve_knn_sharded virtual-mesh caveat applies
      verbatim), so wall-clock scaling is bounded by the core count,
      not the fleet protocol.
    - **chaos arm** (the hard gates) — steady query traffic plus a
      live WAL-acked insert stream against the 2-worker fleet;
      SIGKILL one worker mid-ingestion.  ``/fleet/healthz`` must read
      degraded during the outage and healthy again after the
      crash-restored rejoin; ZERO acknowledged rows may be lost
      (every acked id must answer under its exact vector from the
      healed fleet); every admitted request must carry exactly one
      typed terminal flight event; and the recovered QPS window must
      hold >= 0.9x the pre-kill window."""
    import shutil
    import tempfile
    import threading as _threading

    import numpy as np

    from raft_tpu.core import flight as _flight
    from raft_tpu.core.error import RaftError
    from raft_tpu.fleet import Fleet
    from raft_tpu.fleet.worker import _synth

    def note(msg):
        if os.environ.get("RAFT_TPU_BENCH_DEBUG"):
            print("[serve_fleet +%.1fs] %s"
                  % (time.time() - note.t0, msg),
                  file=sys.stderr, flush=True)
    note.t0 = time.time()

    data = _synth(index_rows, dim, 5, 8)

    def drive(router, dur):
        stop = _threading.Event()
        lock = _threading.Lock()
        counts = {"calls": 0, "errors": 0}

        def client(idx):
            rng = np.random.default_rng(100 + idx)
            while not stop.is_set():
                picks = rng.integers(0, index_rows, 4)
                try:
                    router.search([data[i].tolist() for i in picks],
                                  timeout_s=10.0)
                except RaftError:
                    with lock:
                        counts["errors"] += 1
                    continue
                with lock:
                    counts["calls"] += 1

        threads = [_threading.Thread(target=client, args=(i,),
                                     daemon=True)
                   for i in range(concurrency)]
        t0 = time.time()
        for t in threads:
            t.start()
        time.sleep(dur)
        stop.set()
        for t in threads:
            t.join(timeout=10.0)
        el = max(time.time() - t0, 1e-9)
        return {"qps": round(4 * counts["calls"] / el, 1),
                "requests_s": round(counts["calls"] / el, 1),
                "errors": counts["errors"]}

    fleet_kw = dict(index_rows=index_rows, dim=dim, k=k, seed=5,
                    clusters=8, nlist=nlist,
                    service_opts={"delta_cap": 8192})
    roots = [tempfile.mkdtemp(prefix="raft_tpu_bench_fleet%d_" % n)
             for n in (1, 2)]
    try:
        t0 = time.time()
        with Fleet(1, root=roots[0], **fleet_kw) as f1:
            f1.wait_ready(timeout=180.0)
            boot1_s = time.time() - t0
            note("fleet(1) ready in %.1fs" % boot1_s)
            one = drive(f1.router, duration)
            note("drive(1) %s" % one)

        t0 = time.time()
        with Fleet(2, root=roots[1], **fleet_kw) as f2:
            router = f2.router
            f2.wait_ready(timeout=180.0)
            boot2_s = time.time() - t0
            note("fleet(2) ready in %.1fs" % boot2_s)
            two = drive(router, duration)
            note("drive(2) %s" % two)

            # ---------------- chaos arm ---------------- #
            _flight.reset()
            acked = {}
            attempted = {}
            ilock = _threading.Lock()
            istop = _threading.Event()
            irng = np.random.default_rng(17)

            def inserter():
                n = 0
                while not istop.is_set():
                    ids = list(range(10_000_000 + n,
                                     10_000_000 + n + 8))
                    vecs = irng.standard_normal(
                        (8, dim)).astype(np.float32)
                    with ilock:
                        for j, i in enumerate(ids):
                            attempted[i] = vecs[j]
                    try:
                        rep = router.insert(
                            ids, [v.tolist() for v in vecs],
                            timeout_s=6.0)
                    except RaftError:
                        time.sleep(0.02)
                        continue
                    ok_ids = set(rep["acked_ids"])
                    with ilock:
                        for j, i in enumerate(ids):
                            if i in ok_ids:
                                acked[i] = vecs[j]
                    n += 8
                    # throttled: the gate is zero acked-row LOSS, not
                    # ingest volume — an unthrottled stream acks tens
                    # of thousands of rows and the verification scan
                    # dominates the rung's wall clock
                    time.sleep(0.05)

            it = _threading.Thread(target=inserter, daemon=True)
            it.start()
            pre = drive(router, duration)
            note("pre-kill drive %s" % pre)
            gen_before = router.registry()["w1"]["generation"]
            f2.kill("w1")
            degraded_seen = False
            deadline = time.time() + 20.0
            while time.time() < deadline:
                ok, payload = router.fleet_health()
                if ok and payload["degraded"]:
                    degraded_seen = True
                    break
                time.sleep(0.1)
            note("degraded_seen=%s" % degraded_seen)
            f2.restart("w1")
            # wait for the rejoin itself (generation bump), not for a
            # merely-active state: the restart can land before the
            # lease eviction, while w1 still reads active under its
            # stale registration
            deadline = time.time() + 150.0
            while time.time() < deadline:
                pub = router.registry()["w1"]
                if (pub["state"] == "active"
                        and pub["generation"] > gen_before):
                    break
                time.sleep(0.1)
            rejoined = (router.registry()["w1"]["generation"]
                        > gen_before)
            note("rejoined=%s" % rejoined)
            healthy_after = False
            deadline = time.time() + 30.0
            while rejoined and time.time() < deadline:
                ok, payload = router.fleet_health()
                if ok and not payload["degraded"]:
                    healthy_after = True
                    break
                time.sleep(0.2)
            note("healthy_after=%s" % healthy_after)
            # settle window (discarded): "recovered" means the healed
            # steady state, not the first second after rejoin while
            # the worker is still folding its replayed delta
            drive(router, 1.5)
            rec = drive(router, duration)
            note("recovered drive %s" % rec)
            istop.set()
            it.join(timeout=30.0)

            # zero acked-row loss: every acked id answers under its
            # exact vector from the healed fleet
            lost = 0
            items = sorted(acked.items())
            note("loss scan over %d acked rows" % len(items))
            for off in range(0, len(items), 128):
                chunk = items[off:off + 128]
                try:
                    out = router.search(
                        [v.tolist() for _, v in chunk],
                        timeout_s=15.0)
                except RaftError:
                    lost += len(chunk)
                    continue
                for (i, _v), row in zip(chunk, out["ids"]):
                    if row[0] != i:
                        lost += 1
            note("loss scan done: lost=%d" % lost)

            # exactly one typed terminal per admitted request (the
            # flight ring is FIFO: a surviving admitted event's
            # terminal is newer, so the pairing is overflow-safe)
            rec_fl = _flight.default_recorder()
            admitted = [e.attrs.get("rid")
                        for e in rec_fl.events(kind="fleet_admitted")]
            terminals = {}
            for kind in ("fleet_resolved", "fleet_failed",
                         "fleet_expired"):
                for e in rec_fl.events(kind=kind):
                    rid = e.attrs.get("rid")
                    terminals[rid] = terminals.get(rid, 0) + 1
            exactly_once = bool(admitted) and all(
                terminals.get(rid, 0) == 1 for rid in admitted)
            rejoin_stats = (router.fleet_stats().get("last_rejoin")
                            or {})
    finally:
        for root in roots:
            shutil.rmtree(root, ignore_errors=True)

    ratio = rec["qps"] / max(pre["qps"], 1e-9)
    gates = {
        "degraded_during_outage": degraded_seen,
        "healthy_after_rejoin": healthy_after,
        "zero_acked_loss": lost == 0,
        "exactly_once_terminals": exactly_once,
        "recovered_qps_ok": ratio >= 0.9,
    }
    return {
        "qps_workers_1": one["qps"],
        "qps_workers_2": two["qps"],
        "scaling_x": round(two["qps"] / max(one["qps"], 1e-9), 2),
        "boot_s_workers_1": round(boot1_s, 1),
        "boot_s_workers_2": round(boot2_s, 1),
        "prekill_qps": pre["qps"],
        "recovered_qps": rec["qps"],
        "recovered_ratio": round(ratio, 3),
        "acked_rows": len(acked),
        "attempted_rows": len(attempted),
        "lost_rows": lost,
        "admitted_requests": len(admitted),
        "rejoin_replayed_records": rejoin_stats.get(
            "replayed_records"),
        "rejoin_restore_s": rejoin_stats.get("restore_s"),
        **gates,
        "fleet_ok": all(gates.values()),
        "note": ("scaling_x is informational on shared cores; the "
                 "chaos-arm gates are the rung's claim"),
        "config": {"index_rows": index_rows, "dim": dim, "k": k,
                   "nlist": nlist, "concurrency": concurrency,
                   "duration_s": duration},
    }


def _bench_fleet_trace_overhead(index_rows, dim, k, duration,
                                concurrency, nlist=16):
    """Fleet tracing cost rung (docs/OBSERVABILITY.md "Fleet
    tracing"): the distributed-tracing layer — context propagation on
    every RPC, router hop spans, worker-side trace binding and fleet
    indexing — must prove its own price end-to-end through the
    process boundary, exactly as the single-process
    serve_trace_overhead rung does for the flight recorder.

    One 2-worker sharded fleet, warmed once and shared by every run
    (worker boot = a jax import each; arm-to-arm fleet rebuilds would
    swamp the few-percent effect).  A discarded priming run, then 3
    interleaved runs per arm — recording ON fleet-wide vs OFF
    (router toggles locally, workers via ``POST /debug/flight``) —
    best-of-three per arm.  Gates: qps overhead <= 3%, ZERO
    post-warmup compiles across both worker processes (from the
    aggregated ``raft_tpu_jit_compile_seconds_count``), and the
    joined waterfall for a traced request validates clean."""
    import shutil
    import tempfile
    import threading as _threading

    import numpy as np

    from raft_tpu.core import flight as _flight
    from raft_tpu.core.error import RaftError
    from raft_tpu.core.metrics import parse_prometheus
    from raft_tpu.fleet import Fleet, protocol as _fproto
    from raft_tpu.fleet import tracing as _ftracing
    from raft_tpu.fleet.worker import _synth

    data = _synth(index_rows, dim, 5, 8)
    rid_seq = iter(range(1, 1_000_000))

    def drive(router, dur, keep_rids=None):
        stop = _threading.Event()
        lock = _threading.Lock()
        counts = {"calls": 0, "errors": 0}

        def client(idx):
            rng = np.random.default_rng(200 + idx)
            while not stop.is_set():
                picks = rng.integers(0, index_rows, 4)
                rid = "flt-ovh-%06d" % next(rid_seq)
                try:
                    router.search([data[i].tolist() for i in picks],
                                  timeout_s=10.0, request_id=rid)
                except RaftError:
                    with lock:
                        counts["errors"] += 1
                    continue
                with lock:
                    counts["calls"] += 1
                    if keep_rids is not None:
                        keep_rids.append(rid)

        threads = [_threading.Thread(target=client, args=(i,),
                                     daemon=True)
                   for i in range(concurrency)]
        t0 = time.time()
        for t in threads:
            t.start()
        time.sleep(dur)
        stop.set()
        for t in threads:
            t.join(timeout=10.0)
        el = max(time.time() - t0, 1e-9)
        return {"qps": round(4 * counts["calls"] / el, 1),
                "errors": counts["errors"]}

    def set_tracing(router, on):
        _flight.set_enabled(on)  # router-side hop spans
        for wid, pub in sorted(router.registry().items()):
            _fproto.post_json(
                "http://127.0.0.1:%d/debug/flight"
                % pub["data_port"], {"on": on}, timeout=5.0)

    def worker_compiles(router):
        parsed = parse_prometheus(router.fleet_metrics_text())
        return int(sum(parsed.get(
            "raft_tpu_jit_compile_seconds_count", {}).values()))

    fleet_kw = dict(index_rows=index_rows, dim=dim, k=k, seed=5,
                    clusters=8, nlist=nlist,
                    service_opts={"delta_cap": 8192})
    root = tempfile.mkdtemp(prefix="raft_tpu_bench_ftrace_")
    per_run = max(1.0, duration / 3)
    offs, ons = [], []
    on_rids = []
    try:
        with Fleet(2, root=root, **fleet_kw) as fl:
            router = fl.router
            fl.wait_ready(timeout=180.0)
            # discarded priming run from the plateau (same rationale
            # as serve_trace_overhead: the first closed-loop seconds
            # run slow regardless of arm)
            drive(router, max(2.0, per_run))
            compiles0 = worker_compiles(router)
            try:
                for _ in range(3):
                    set_tracing(router, False)
                    offs.append(drive(router, per_run))
                    set_tracing(router, True)
                    ons.append(drive(router, per_run,
                                     keep_rids=on_rids))
            finally:
                # the fleet (and this process) must not leave
                # recording off for later rungs
                set_tracing(router, True)
            post_compiles = worker_compiles(router) - compiles0
            # the traced arm's spans must join into a clean
            # waterfall — overhead numbers for a broken trace pipe
            # would be measuring nothing
            problems = ["no traced request joined"]
            for rid in reversed(on_rids[-8:]):
                status, joined = router.fleet_trace(rid)
                if status == 200:
                    problems = (joined.get("problems")
                                or _ftracing.validate(joined))
                    break
    finally:
        shutil.rmtree(root, ignore_errors=True)
    qps_off = max(r["qps"] for r in offs)
    qps_on = max(r["qps"] for r in ons)
    overhead = 1.0 - qps_on / qps_off if qps_off else 0.0
    gates = {
        # the acceptance bound: fleet tracing on costs <= 3% qps
        "overhead_ok": overhead <= 0.03,
        "zero_post_warmup_compiles": post_compiles == 0,
        "joined_trace_clean": problems == [],
    }
    return {
        "qps_on": qps_on,
        "qps_off": qps_off,
        "overhead_frac": round(overhead, 4),
        "post_warmup_compiles": post_compiles,
        "join_problems": problems,
        **gates,
        "fleet_trace_ok": all(gates.values()),
        "config": {"index_rows": index_rows, "dim": dim, "k": k,
                   "nlist": nlist, "concurrency": concurrency,
                   "rows_per_request": 4, "runs_per_arm": 3,
                   "shared_fleet": True},
    }


def _bench_comms_p2p(rows, dim, iters):
    """Tagged-p2p staging A/B (docs/ZERO_COPY.md): one full ring
    (every rank sends a (rows, dim) f32 block to its neighbor) per
    ``waitall``, device-resident assembly vs the historical host-numpy
    staging.  The host-staged-bytes counter rides along as the proof
    the device path moved zero payload bytes through numpy — the perf
    claim and the zero-copy claim are the same measurement."""
    import jax
    import jax.numpy as jnp

    from raft_tpu.comms.host_comms import HostComms, default_mesh
    from raft_tpu.core.metrics import default_registry

    comms = HostComms(default_mesh())
    size = comms.get_size()
    if size < 2:
        return {"status": "skipped_single_device"}
    payloads = [jnp.asarray(_rand((rows, dim), seed=100 + r))
                for r in range(size)]
    jax.block_until_ready(payloads)

    def staged_bytes():
        return default_registry().family_total(
            "raft_tpu_comms_host_staged_bytes")

    def ring(staging):
        recvs = []
        for r in range(size):
            comms.isend(payloads[r], rank=r, dest=(r + 1) % size, tag=0)
            recvs.append(comms.irecv(rank=r, source=(r - 1) % size,
                                     tag=0))
        comms.waitall(staging=staging)
        # block per waitall: the rung measures the eager verb's
        # round-trip (dispatch + collective + result ready), and
        # overlapping successive collective executions deadlocks the
        # CPU backend's rendezvous (8 virtual devices share one pool)
        return jax.block_until_ready([rq.result for rq in recvs])

    out = {"config": {"rows": rows, "dim": dim, "iters": iters,
                      "ranks": size}}
    payload_bytes = size * rows * dim * 4
    # all three arms: "device" (per-pair direct moves, no collective),
    # "ppermute" (same collective program as "host" but with on-device
    # assembly — the apples-to-apples staging A/B, and the path taken
    # on multi-process/multi-axis meshes or under a fault injector),
    # "host" (numpy-staged baseline)
    for staging in ("device", "ppermute", "host"):
        ring(staging)                            # compile warmup
        b0 = staged_bytes()
        t0 = time.time()
        for _ in range(iters):
            ring(staging)
        dt = (time.time() - t0) / iters
        out["%s_seconds_per_waitall" % staging] = round(dt, 6)
        out["%s_gb_per_sec" % staging] = round(
            payload_bytes / dt / 1e9, 3)
        out["%s_host_staged_bytes_per_waitall" % staging] = int(
            (staged_bytes() - b0) / iters)
    out["payload_mb_per_waitall"] = round(payload_bytes / 1e6, 2)
    out["device_speedup"] = round(
        out["host_seconds_per_waitall"]
        / out["device_seconds_per_waitall"], 3)
    # same collective, staging isolated: the zero-copy win net of
    # dropping the collective program
    out["ppermute_speedup"] = round(
        out["host_seconds_per_waitall"]
        / out["ppermute_seconds_per_waitall"], 3)
    return out


def _bench_sparse_pairwise(m, n_cols, nnz_row, iters, batch_size_k):
    """Sparse CSR pairwise L2 on the column-tiled engine (the
    load-balanced-SpMV-regime analog, sparse/distance/detail/
    coo_spmv.cuh:49,106) — the engine landed in r4 with correctness
    tests but no perf evidence.  ``batch_size_k`` is passed EXPLICITLY
    (n_cols/batch_size_k col tiles) so the multi-tile accumulation path
    is what gets timed — the auto heuristic at this shape would pick a
    single full-width tile and certify a path that never ran."""
    import numpy as np

    from raft_tpu.distance import DistanceType
    from raft_tpu.sparse.distance import pairwise_distance as spd
    from raft_tpu.sparse.formats import CSR

    def make(rows, seed):
        r = np.random.default_rng(seed)
        # stratified columns: unique + sorted per row by construction
        stride = n_cols // nnz_row
        cols = (np.arange(nnz_row)[None, :] * stride
                + r.integers(0, stride, (rows, nnz_row))).ravel()
        indptr = (np.arange(rows + 1) * nnz_row).astype(np.int32)
        data = r.random(rows * nnz_row).astype(np.float32) + 0.1
        return CSR(indptr, cols.astype(np.int32), data, (rows, n_cols))

    ca = make(m, 22)
    cb = make(m, 23)

    def step(dat):
        return spd(CSR(ca.indptr, ca.indices, dat, ca.shape), cb,
                   DistanceType.L2Expanded, batch_size_k=batch_size_k)

    dt = _time_chained(step, ca.data, iters)
    return {
        "gpairs_per_sec": round(m * m / dt / 1e9, 4),
        "seconds_per_call": round(dt, 4),
        "m": m, "n_cols": n_cols, "nnz_per_row": nnz_row,
        "n_col_tiles": -(-n_cols // batch_size_k),
        "engine": "column-tiled (explicit batch_size_k=%d)"
                  % batch_size_k,
    }


def _import_autotune():
    """Load tools/autotune.py as a module (bench reuses its cell
    runners so the sweep and the rung can never time different
    workloads)."""
    import importlib.util

    path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "tools", "autotune.py")
    spec = importlib.util.spec_from_file_location("raft_tpu_autotune",
                                                  path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _bench_autotune_smoke():
    """Sweep-path rot guard: one tiny cell per op through the FULL
    timed sweep (registry enumeration, profiled_jit warmup, best-of-N,
    post-warmup-compile assertion) — if tools/autotune.py breaks, this
    rung breaks the same round, not the next tuning day."""
    at = _import_autotune()
    table = at.run_sweep(smoke=True, log=lambda *_: None)
    exact = [e for e in table["entries"]
             if e.get("shape_class") != "*"]
    return {
        "cells": len(exact),
        "winners": {"%s/%s" % (e["op"], e["knob"]): e["winner"]
                    for e in exact},
        "post_warmup_compiles": sum(
            n for e in exact
            for n in e.get("post_warmup_compiles", {}).values()),
        "note": "smoke cells are tiny; winners here prove the sweep "
                "path, not the venue",
    }


def _bench_tuned_vs_default():
    """What is the tuning table worth on this venue?  Loads the
    checked-in table matching this backend's fingerprint (CPU ladder),
    or sweeps a fresh smoke table in-process when no venue table is
    checked in yet (the TPU ladder until its first tuned round) — then
    re-times winner vs config-default for every swept cell through the
    same runners the sweep used.  Every ratio must hold >= 1.0 (the
    autotuner's min-margin conservatism is exactly this rung's
    guarantee); post_warmup_compiles must be 0."""
    from raft_tpu import config
    from raft_tpu.core import metrics as _metrics

    at = _import_autotune()
    # the table install is scoped to THIS rung (try/finally below):
    # every other rung must keep measuring the documented defaults, or
    # round-over-round comparability silently dies the first tuned
    # round
    path = config.discover_tuning_table()
    try:
        if path is not None:
            with open(path, encoding="utf-8") as f:
                table = json.load(f)
            config.load_tuning_table(path)
            source = os.path.basename(path)
        else:
            table = at.run_sweep(smoke=True, log=lambda *_: None)
            config.install_tuning_table(table)
            source = "fresh-smoke-sweep (no checked-in table for this "
            source += "fingerprint; persist one with tools/autotune.py)"
        res = at.tuned_vs_default(table, iters=3, log=lambda *_: None)
    finally:
        config.clear_tuning_table()
    gauge = _metrics.default_registry().gauge(
        "raft_tpu_tuning_tuned_vs_default_ratio",
        help="tuned-vs-default speedup per swept cell",
        labels=("op", "cell"))
    for c in res["cells"]:
        gauge.labels(op=c["op"], cell=c["cell"]).set(c["ratio"])
    return {
        "table": source,
        "fingerprint": table.get("fingerprint"),
        "cells": res["cells"],
        "min_ratio": res["min_ratio"],
        "max_ratio": res["max_ratio"],
        "post_warmup_compiles": res["post_warmup_compiles"],
        "all_cells_at_least_1x": (res["min_ratio"] is not None
                                  and res["min_ratio"] >= 1.0),
    }


def _bench_roofline_closure(n_index, n_query, k, iters, fused_impl):
    """A/B the shipped brute-force pipeline (impl="xla": the tiled_knn
    scan program with per-tile re-selection) against the ONE-program
    fused path at a serving shape, then join the warmed executables
    against the venue's measured matmul ceiling: how much of the
    roofline does each achieve?

    fused_impl is "pallas" on the TPU ladder (the VMEM-resident kernel,
    ops/knn_tile.py) and "xla_fused" on the CPU ladder (the kernel's
    XLA-composed twin — same tile geometry and distance arithmetic,
    exact per-tile top_k running merge; interpreted Pallas is ~15 s/call
    flat and is never timed).  The checked-in tuning table for this
    venue's fingerprint is installed for the rung's scope so the fused
    arm runs at its SWEPT block shapes — knn_block_q/knn_block_n come
    out of the registry at the kernel call site, no literals here
    (ci/style_check.py bans them).

    Contract fields: fused_speedup = baseline_s / fused_s must hold
    >= 1.0 within noise (fused_at_least_baseline uses a 5% band);
    post_warmup_compiles must be 0; roofline.programs reports achieved
    GFLOP/s and closure = achieved / ceiling per warmed arm."""
    import jax

    from raft_tpu import config
    from raft_tpu.core import inventory, profiler
    from raft_tpu.core import metrics as _metrics
    from raft_tpu.spatial.fused_l2_knn import fused_l2_knn

    if fused_impl == "pallas" and _DEVICE_INFO.get("platform") != "tpu":
        return {"status": "skipped_backend",
                "note": "compiled Pallas arm is TPU-only; the CPU "
                        "ladder runs fused_impl='xla_fused'"}

    dim = 64
    index = _rand((n_index, dim), 31)
    queries = _rand((n_query, dim), 32)
    flops = 2.0 * n_query * n_index * dim  # the distance matmul bound

    def fused_body(q):
        d, i = fused_l2_knn(index, q, k, impl=fused_impl)
        # ids folded in: see _bench_knn on dead-coding
        return d + i.astype(d.dtype)

    fused_fn = profiler.profiled_jit(name="roofline_fused")(fused_body)

    def fused_arm():
        return jax.block_until_ready(fused_fn(queries))

    def base_arm():
        # the shipped eager entry point, dispatching its own
        # profiled_jit program ("tiled_knn"); both contract outputs are
        # program outputs, nothing to fold
        return jax.block_until_ready(
            fused_l2_knn(index, queries, k, impl="xla"))

    def misses():
        return sum(st.get("misses", 0)
                   for keys in profiler.compile_cache_stats().values()
                   for st in keys.values())

    # scoped table install, the _bench_tuned_vs_default discipline:
    # every other rung keeps measuring documented defaults
    path = config.discover_tuning_table()
    inv_before = {fn: set(keys)
                  for fn, keys in inventory.snapshot().items()}
    try:
        if path is not None:
            config.load_tuning_table(path)
        base_arm()
        fused_arm()  # both arms warmed; compiles after this are a bug
        m0 = misses()
        best_base = best_fused = float("inf")
        for _ in range(iters):  # interleaved best-of-N: drift-fair A/B
            t0 = time.perf_counter()
            base_arm()
            best_base = min(best_base, time.perf_counter() - t0)
            t0 = time.perf_counter()
            fused_arm()
            best_fused = min(best_fused, time.perf_counter() - t0)
        post_warmup = misses() - m0
    finally:
        if path is not None:
            config.clear_tuning_table()

    # the venue ceiling: one measured 512-cube matmul (the _bench_micro
    # program), not a spec sheet — closure is achieved/measured-peak
    nmm = 512
    a = _rand((nmm, nmm), 33)

    def mm_step(z):
        import jax.numpy as jnp
        return jnp.matmul(z, a, precision="highest")

    mm_dt = _time_chained(mm_step, a, 4)
    ceiling = 2.0 * nmm ** 3 / mm_dt

    # join this rung's freshly inventoried executables (cost-model
    # FLOPs/footprint from the AOT compile seam) against the measured
    # seconds; a Pallas custom call prices at 0 in the XLA cost model,
    # so "achieved" always uses the analytic distance-matmul bound
    progs = {}
    for fn, secs in (("tiled_knn", best_base),
                     ("roofline_fused", best_fused)):
        fresh = [e for kk, e in inventory.snapshot().get(fn, {}).items()
                 if kk not in inv_before.get(fn, set())]
        progs[fn] = {
            "seconds_per_call": round(secs, 5),
            "achieved_gflops": round(flops / secs / 1e9, 2),
            "roofline_closure": round((flops / secs) / ceiling, 4),
            "cost_model_flops": sum(e["flops"] for e in fresh),
            "hbm_bytes": sum(e["hbm_bytes"] for e in fresh),
        }
    gauge = _metrics.default_registry().gauge(
        "raft_tpu_roofline_closure",
        help="achieved/ceiling FLOP fraction per warmed brute-force "
             "program (roofline_closure bench rung)",
        labels=("program",))
    for fn, p in progs.items():
        gauge.labels(program=fn).set(p["roofline_closure"])

    ratio = best_base / best_fused
    out = {
        "fused_impl": fused_impl,
        "n_index": n_index, "n_query": n_query, "dim": dim, "k": k,
        "tuning_table": os.path.basename(path) if path else None,
        "baseline_seconds": round(best_base, 5),
        "fused_seconds": round(best_fused, 5),
        "fused_speedup": round(ratio, 4),
        "fused_at_least_baseline": bool(ratio >= 0.95),
        "post_warmup_compiles": post_warmup,
        "ceiling_gflops": round(ceiling / 1e9, 2),
        "programs": progs,
        "mfu_fused": _mfu(flops, best_fused),
    }
    return out


def _bench_ivf_flat(n_index, n_query, iters):
    """IVF-Flat ANN (reference approx_knn IVFFlat path)."""
    from raft_tpu.spatial.ann import (IVFFlatParams, ivf_flat_build,
                                      ivf_flat_search)

    nlist = 1024
    return _bench_ivf(
        n_index, n_query, iters,
        build=lambda X: ivf_flat_build(X, IVFFlatParams(nlist=nlist)),
        search=ivf_flat_search,
        params={"nlist": nlist})


def _bench_ivf_pq(n_index, n_query, iters):
    """IVF-PQ with exact refinement (the FAISS IndexRefineFlat analog):
    memory-compressed codes + re-rank."""
    from raft_tpu.spatial.ann import (IVFPQParams, ivf_pq_build,
                                      ivf_pq_search)

    nlist, M, refine = 1024, 16, 4
    return _bench_ivf(
        n_index, n_query, iters,
        build=lambda X: ivf_pq_build(
            X, IVFPQParams(nlist=nlist, M=M, refine_ratio=refine)),
        search=ivf_pq_search,
        params={"nlist": nlist, "M": M, "refine_ratio": refine},
        # same built index re-timed under the one-hot ADC contraction
        alt_env={"onehot_adc": {"RAFT_TPU_PQ_ADC": "onehot"}})


def _bench_ivf_sq(n_index, n_query, iters):
    """IVF-SQ (8-bit scalar-quantized residuals): the memory/speed
    middle ground of the ANN trio."""
    from raft_tpu.spatial.ann import (IVFSQParams, ivf_sq_build,
                                      ivf_sq_search)

    nlist = 1024
    return _bench_ivf(
        n_index, n_query, iters,
        build=lambda X: ivf_sq_build(X, IVFSQParams(nlist=nlist)),
        search=ivf_sq_search,
        params={"nlist": nlist, "qtype": "QT_8bit"})


def _bench_linalg_bundle(n, iters):
    """BASELINE.md config #2: gemm + rowNorm + colReduce + transpose on
    dense f32 (linalg/gemm.cuh:46, norm.cuh:48, reduce.cuh:61,
    transpose.h:36) as one chained step; FLOPs dominated by the gemm."""
    from raft_tpu.linalg import gemm, row_norm, strided_reduction, transpose

    x = _rand((n, n), 7)
    y = _rand((n, n), 8)

    def make_step(precision):
        def step(a):
            g = gemm(a, y, precision=precision)
            rn = row_norm(g)
            cs = strided_reduction(g)      # column sums (reduce.cuh:61)
            t = transpose(g)
            return t + rn[None, :] + cs[None, :]
        return step

    # headline = "highest" (the cuBLAS-SGEMM-faithful default contract);
    # single-pass bf16 reported alongside as the opt-out headroom
    dt = _time_chained(make_step("highest"), x, iters)
    flops = 2.0 * n * n * n
    out = {
        "seconds_per_call": round(dt, 5), "n": n,
        "precision": "highest (f32-faithful, the library default)",
        "gemm_tflops": round(flops / dt / 1e12, 3),
        "mfu": _mfu(flops, dt),
    }
    dt_fast = _time_chained(make_step("default"), x, iters)
    out["bf16_singlepass"] = {
        "seconds_per_call": round(dt_fast, 5),
        "gemm_tflops": round(flops / dt_fast / 1e12, 3),
        "mfu": _mfu(flops, dt_fast),
        "note": "precision='default' opt-out (TF32-math-mode analog)",
    }
    return out


def make_blobs(rng, m, d, n_blobs, spread=0.15):
    """(X, labels) Gaussian blobs — the canonical workload generator
    shared by the linkage bench rung and tests/test_scale_stress.py
    (single source so bench and stress test measure the same data)."""
    import numpy as np

    centers = rng.standard_normal((n_blobs, d)) * 4.0
    labels = rng.integers(0, n_blobs, m)
    X = (centers[labels]
         + rng.standard_normal((m, d)) * spread).astype(np.float32)
    return X, labels


def two_community_graph(n_half, n_cross, rng):
    """Symmetric deduped CSR of two ring communities + random intra
    edges + ``n_cross`` planted bridges; shared by the spectral bench
    rung and tests/test_scale_stress.py."""
    import numpy as np

    from raft_tpu.sparse.convert import coo_to_csr
    from raft_tpu.sparse.formats import COO
    from raft_tpu.sparse.op import max_duplicates

    n = 2 * n_half
    src = np.concatenate([
        np.arange(n_half), n_half + np.arange(n_half),
        rng.integers(0, n_half, 2 * n_half),
        n_half + rng.integers(0, n_half, 2 * n_half),
        rng.integers(0, n_half, n_cross)])
    dst = np.concatenate([
        (np.arange(n_half) + 1) % n_half,
        n_half + (np.arange(n_half) + 1) % n_half,
        rng.integers(0, n_half, 2 * n_half),
        n_half + rng.integers(0, n_half, 2 * n_half),
        n_half + rng.integers(0, n_half, n_cross)])
    keep = src != dst
    src, dst = src[keep], dst[keep]
    rows = np.concatenate([src, dst]).astype(np.int32)
    cols = np.concatenate([dst, src]).astype(np.int32)
    coo = max_duplicates(COO(rows, cols, np.ones(rows.size, np.float32),
                             shape=(n, n)))
    return coo_to_csr(coo, assume_sorted=True)


def _bench_linkage_50k():
    """m=50k single-linkage end-to-end (single_linkage.hpp:48 at bench
    scale): kNN graph + MST + host dendrogram + cluster extraction.
    Wall-clock includes compile (one-shot pipeline, not a steady-state
    op); label quality asserted against the planted blobs."""
    import numpy as np

    from raft_tpu.sparse.hierarchy import single_linkage

    m, d, blobs = 50_000, 2, 3
    X, truth = make_blobs(np.random.default_rng(0), m, d, blobs)
    t0 = time.perf_counter()
    res = single_linkage(X, n_clusters=blobs)
    labels = np.asarray(res.labels)
    dt = time.perf_counter() - t0
    # purity against the planted labels via majority vote per cluster
    correct = sum(np.bincount(truth[labels == c]).max()
                  for c in range(blobs) if (labels == c).any())
    return {"seconds_incl_compile": round(dt, 2), "m": m,
            "n_clusters": blobs, "purity": round(float(correct) / m, 4)}


def _bench_spectral_100k():
    """100k-vertex spectral partition (partition.hpp:65 at bench scale):
    two ring communities + planted bridges; wall-clock incl compile and
    the recovered-community accuracy."""
    import numpy as np

    from raft_tpu.spectral import partition
    from raft_tpu.spectral.eigen_solvers import (EigenSolverConfig,
                                                 LanczosSolver)

    n_half = 50_000
    n = 2 * n_half
    csr = two_community_graph(n_half, 40, np.random.default_rng(0))
    solver = LanczosSolver(EigenSolverConfig(n_eig_vecs=2, max_iter=6000,
                                             restart_iter=80, tol=1e-3,
                                             seed=42))
    t0 = time.perf_counter()
    res = partition(csr, eigen_solver=solver, n_clusters=2)
    clusters = np.asarray(res.clusters)
    dt = time.perf_counter() - t0
    truth = np.arange(n) >= n_half
    acc = max((clusters == truth).mean(), (clusters != truth).mean())
    return {"seconds_incl_compile": round(dt, 2), "n_vertices": n,
            "community_accuracy": round(float(acc), 4)}


def _bench_spectral():
    import numpy as np

    from raft_tpu.sparse.formats import COO
    from raft_tpu.sparse.spectral import fit_embedding

    n = 2048
    rng = np.random.default_rng(0)
    src = np.arange(n, dtype=np.int64)
    dst = (src + 1) % n
    extra = rng.integers(0, n, size=(2 * n, 2), dtype=np.int64)
    extra = extra[extra[:, 0] != extra[:, 1]]
    rows = np.concatenate([src, dst, extra[:, 0], extra[:, 1]])
    cols = np.concatenate([dst, src, extra[:, 1], extra[:, 0]])
    vals = np.ones(rows.shape[0], dtype=np.float32)
    coo = COO(rows.astype(np.int32), cols.astype(np.int32), vals, shape=(n, n))
    np.asarray(fit_embedding(coo, n_components=4))  # warmup: trace+compile
    t0 = time.perf_counter()
    np.asarray(fit_embedding(coo, n_components=4))
    dt = time.perf_counter() - t0
    return {"seconds": round(dt, 3), "n_vertices": n, "n_components": 4,
            "note": "steady-state (compile excluded by warmup call)"}


def child_main():
    cpu = os.environ.get(_CPU_ENV) == "1"
    state = {"fallback": "cpu" if cpu else None}
    skipped = []

    state["init"] = _rung_init()
    if not cpu and not state["init"]["is_tpu"]:
        # init succeeded but on a non-accelerator backend (e.g. a CPU-only
        # dev box): the full ladder would run for hours — use the scaled
        # shapes and say so in the metric name
        cpu = True
        state["fallback"] = "cpu"
        state["init"]["note"] = "non-TPU backend; scaled ladder"
    _emit("init", state["init"])
    _emit("fallback", state["fallback"])

    def knn_pallas_1m():
        """Re-run the north star with the Pallas kernel only once it has
        proven correct AND faster at 100k; assemble() picks the best."""
        p = state.get("pallas_check", {})
        if (p.get("status") == "ok"
                and p.get("pallas_seconds_per_batch", 1e9)
                < p.get("xla_seconds_per_batch", 0.0)):
            return _bench_knn(1_000_000, 10_000, 3, "pallas")
        return {"status": "skipped_pallas_not_faster"}

    if cpu:
        rungs = [
            ("pairwise_1k", 25, lambda: _bench_pairwise(1024, 64, 4,
                                                        sqrt=True)),
            ("pairwise_2k", 40, lambda: _bench_pairwise(2048, 128, 4)),
            ("linalg_bundle", 30, lambda: _bench_linalg_bundle(1024, 2)),
            ("knn_100k", 70, lambda: _bench_knn(100_000, 512, 2, "xla")),
            # what the checked-in tuning table is worth on this venue:
            # tuned-vs-default A/B per swept cell (>= 1.0x everywhere
            # by the autotuner's min-margin conservatism)
            ("tuned_vs_default", 150, _bench_tuned_vs_default),
            # sweep-path rot guard: tools/autotune.py --smoke inline
            ("autotune_smoke", 90, _bench_autotune_smoke),
            # one-program fused brute-force vs the shipped tiled-scan
            # pipeline + roofline closure per warmed executable; the
            # CPU arm is the kernel's XLA-composed twin (interpreted
            # Pallas is never timed), at the swept-cell geometry
            ("roofline_closure", 60,
             lambda: _bench_roofline_closure(20_000, 128, 32, 5,
                                             "xla_fused")),
            ("spectral", 40, _bench_spectral),
            # scaled-down column-tiled sparse engine evidence even on a
            # no-hardware round
            ("sparse_pairwise", 40,
             lambda: _bench_sparse_pairwise(512, 32768, 16, 2, 8192)),
            # serving-layer evidence (queue→coalesce→padded call→split):
            # scaled index, whole-request-path QPS + latency percentiles
            ("serve_knn", 45,
             lambda: _bench_serve(20_000, 64, 10, 3.0, 8)),
            # flight-recorder cost proof: same workload with tracing
            # on vs RAFT_TPU_FLIGHT=0, overhead must hold <= 3%
            ("serve_trace_overhead", 90,
             lambda: _bench_serve_trace_overhead(20_000, 64, 10,
                                                 6.0, 8)),
            # ops-plane cost + completeness proof: 1 Hz scraper <= 3%
            # qps, 0 compiles, inventory lists every warmed rung,
            # sentinel trips on an injected serve-seam Delay with the
            # breaching batch on the black-box tape
            ("ops_scrape_overhead", 110,
             lambda: _bench_ops_scrape_overhead(20_000, 64, 10,
                                                6.0, 8)),
            # multi-tenant isolation (DRR weighted-fair admission):
            # interactive p99 must hold within 2x its solo baseline
            # while an open-loop bulk flood saturates its quota.  Bulk
            # arrival rate is sized for this box: the open-loop
            # generator's own thread churn shares the 2 cores with the
            # virtual devices, so a crushing arrival rate measures
            # loadgen contention, not admission isolation
            ("serve_mixed_tenant", 70,
             lambda: _bench_serve_mixed_tenant(20_000, 64, 10, 4.0,
                                               4, 60.0)),
            # sharded SPMD serving scaling table (1/2/4/8 virtual
            # devices over the forced 8-device CPU mesh): the capacity
            # axis with its zero-copy/zero-compile proof riding along.
            # Virtual-mesh caveat (rung docstring): the 8 "devices"
            # share this host's 2 cores, so wall-clock scaling
            # saturates at ~2x (r6 measured 1.5x at 2 devices,
            # hierarchical the fastest topology); ICI-real scaling is
            # the TPU ladder's to prove
            ("serve_knn_sharded", 180,
             lambda: _bench_serve_sharded(50_000, 64, 100, 2.5, 8)),
            # zero-copy p2p staging A/B on the 8-device virtual mesh:
            # device-resident assembly vs host-numpy staging, with the
            # host-staged-bytes counter as the zero-copy proof
            ("comms_p2p", 40, lambda: _bench_comms_p2p(512, 1024, 8)),
            # affordable on CPU since the r5 single-jit Lanczos (~12 s
            # incl the graph build; was hours-scale retrace before)
            ("spectral_100k", 40, _bench_spectral_100k),
            # r5: retrace fixes made the 50k linkage pipeline ~60 s on
            # CPU; banked when budget remains so a no-hardware round
            # still carries HAC evidence
            ("linkage_50k", 150, _bench_linkage_50k),
            ("knn_100k_rerank", 90,
             lambda: _bench_knn_rerank(100_000, 512, 2)),
            # the TRUE north-star config on CPU (generous budgets only):
            # r5 measured 79.5 QPS wall-verified — notably faster than
            # r4's honest TPU number (~59 QPS 1M-equiv), the cleanest
            # statement of how selection-bound the chip path was
            ("knn_1m", 160,
             lambda: _bench_knn(1_000_000, 1024, 2, "xla",
                                wall_check=True)),
            # the ANN answer to the rung above: same 1M x 128 content
            # scale through the serving layer, nprobe calibrated to
            # recall@100 >= 0.9 — QPS and recall in one report
            # (runs after knn_1m so the speedup ratio can be computed)
            ("serve_ann_1m", 280,
             lambda: _bench_serve_ann(1_000_000, 128, 100, 4.0, 12,
                                      nlist=2048, train_rows=65536,
                                      target_recall=0.9, state=state)),
            # durability cost + recovery speed: WAL + periodic
            # snapshots ON vs OFF at a scaled shape, plus restore-time
            # and WAL-replay-rate rows (docs/PERSISTENCE.md)
            ("serve_ann_persist", 200,
             lambda: _bench_serve_ann_persist(200_000, 64, 10, 3.0, 6,
                                              nlist=512,
                                              train_rows=65536)),
            # fault-domain fleet drill (docs/FAULT_MODEL.md "Fleet
            # fault domains"): router QPS with 1 vs 2 worker
            # processes (informational on shared cores), then the
            # kill-one-worker chaos arm's hard gates — zero acked-row
            # loss across SIGKILL + crash-restore, exactly-once typed
            # terminals, /fleet/healthz degraded during the outage
            # and healthy after rejoin, recovered QPS >= 0.9x pre-kill
            ("serve_fleet", 280,
             lambda: _bench_serve_fleet(2_000, 16, 5, 3.0, 4)),
            # fleet tracing cost proof (docs/OBSERVABILITY.md "Fleet
            # tracing"): recording ON fleet-wide vs OFF on one warmed
            # 2-worker fleet — overhead <= 3% qps, zero post-warmup
            # compiles across workers, joined waterfall validates
            ("fleet_trace_overhead", 200,
             lambda: _bench_fleet_trace_overhead(2_000, 16, 5,
                                                 6.0, 4)),
            # the out-of-core tier at the same 1M x 128 scale: device
            # budget = 1/4 of the slot store (~4x oversubscription),
            # recall must EQUAL the resident arm, and the double-
            # buffered vs synchronous-prefetch A/B measures the
            # overlap win (docs/SERVING.md "Out-of-core serving")
            ("serve_ann_ooc", 320,
             lambda: _bench_serve_ann_ooc(1_000_000, 128, 100, 4.0, 8,
                                          nlist=2048, train_rows=65536,
                                          state=state)),
        ]
    else:
        def best_select():
            """chunked merge-tree vs fused pallas select vs top_k vs
            the direct single-sort merge, per measurement at 100k — the
            winner drives the 1M rung.  Returns (select_impl, merge).
            (approx@recall-1.0 was a fifth candidate in r4; measured
            identical to top_k, so the rung was retired for the
            genuinely different formulations.)"""
            base = state.get("knn_100k", {}).get("qps", 0)
            best, best_qps = (None, None), base
            for rung, cfg in (("knn_100k_chunked", ("chunked", None)),
                              ("knn_100k_pselect", ("pallas", None)),
                              ("knn_100k_direct", (None, "direct"))):
                qps = state.get(rung, {}).get("qps", 0)
                if qps > best_qps:
                    best, best_qps = cfg, qps
            return best

        # ladder ordered by compile cost: the README 1k x 64 config
        # (BASELINE.md #1) is the smallest possible program — bank ONE
        # hardware number before attempting anything hungrier.
        # knn_1m (the headline, proven XLA impl) runs BEFORE
        # pallas_check: a Mosaic compile hang in this process must not
        # forfeit the north-star number (the parent can only kill the
        # whole child).
        rungs = [
            # hardware-tagged rung within seconds of init (module doc)
            ("micro_matmul", 10, _bench_micro),
            ("pairwise_1k", 30, lambda: _bench_pairwise(1024, 64, 8,
                                                        sqrt=True)),
            ("pairwise_2k", 40, lambda: _bench_pairwise(2048, 128, 8)),
            ("linalg_bundle", 40, lambda: _bench_linalg_bundle(4096, 8)),
            ("knn_100k", 80 + 40,
             lambda: _bench_knn(100_000, 4096, 4, "xla",
                                wall_check=True)),
            # gate = its own cost (60) PLUS the 1M rung's (140): the
            # comparison rungs must never consume the budget that would
            # otherwise let the north-star headline run
            ("knn_100k_chunked", 60 + 140,
             lambda: _bench_knn(100_000, 4096, 4, "xla",
                                select_impl="chunked")),
            ("knn_100k_pselect", 80 + 140,
             lambda: _bench_knn(100_000, 4096, 4, "xla",
                                select_impl="pallas")),
            ("knn_100k_direct", 60 + 140,
             lambda: _bench_knn(100_000, 4096, 4, "xla",
                                merge="direct")),
            ("knn_1m", 140 + 60,
             lambda: _bench_knn(1_000_000, 10_000, 3, "xla",
                                *best_select(), wall_check=True)),
            ("pallas_check", 100, lambda: _bench_pallas(state)),
            ("knn_1m_pallas", 120, knn_pallas_1m),
            # est = chained timing (120) + the wall cross-check's extra
            # compile + executions (60), the knn_1m convention
            ("knn_1m_twophase", 120 + 60,
             lambda: _bench_knn_twophase_1m(state)),
            ("pairwise_8k", 50, lambda: _bench_pairwise(8192, 128, 16)),
            # zero-copy p2p staging A/B over ICI (docs/ZERO_COPY.md)
            ("comms_p2p", 50,
             lambda: _bench_comms_p2p(2048, 1024, 8)),
            ("knn_100k_bf16", 60,
             lambda: _bench_knn_bf16(100_000, 4096, 4)),
            ("knn_100k_rerank", 70,
             lambda: _bench_knn_rerank(100_000, 4096, 4)),
            ("knn_100k_recall95", 60,
             lambda: _bench_knn_recall95(100_000, 4096, 4)),
            # est covers the TPU-only xla comparison chain too
            ("fused_nn_1m", 120,
             lambda: _bench_fused_nn(1_000_000, 1024, 64, 4)),
            ("ivf_flat_100k", 90,
             lambda: _bench_ivf_flat(100_000, 4096, 4)),
            # est covers the onehot-ADC alt pass too (second compile +
            # timing chain on the same built index)
            ("ivf_pq_100k", 170,
             lambda: _bench_ivf_pq(100_000, 4096, 4)),
            ("ivf_sq_100k", 90,
             lambda: _bench_ivf_sq(100_000, 4096, 4)),
            # tuning-table value on the TPU venue: no checked-in table
            # until the first tuned TPU round, so this sweeps a fresh
            # smoke table in-process and reports tuned-vs-default on
            # it (est covers the smoke sweep's kernel compiles)
            ("tuned_vs_default", 180, _bench_tuned_vs_default),
            ("autotune_smoke", 120, _bench_autotune_smoke),
            # fused VMEM-resident kernel vs the shipped tiled-scan
            # pipeline + roofline closure per warmed executable (est
            # covers the Mosaic compile of the fused arm)
            ("roofline_closure", 120,
             lambda: _bench_roofline_closure(100_000, 1024, 64, 5,
                                             "pallas")),
            # the serving-layer number the north star is about: whole
            # request path (queue→coalesce→padded call→split) against a
            # warmed service; est covers the per-bucket warmup compiles
            ("serve_knn", 90,
             lambda: _bench_serve(100_000, 64, 10, 5.0, 16)),
            # flight-recorder cost proof at hardware scale (<= 3%)
            ("serve_trace_overhead", 120,
             lambda: _bench_serve_trace_overhead(100_000, 64, 10,
                                                 8.0, 16)),
            # ops-plane cost + completeness proof at hardware scale
            # (scraper <= 3% qps, complete inventory, sentinel trip)
            ("ops_scrape_overhead", 140,
             lambda: _bench_ops_scrape_overhead(100_000, 64, 10,
                                                8.0, 16)),
            # multi-tenant isolation at hardware scale: interactive
            # p99 within 2x solo while the bulk flood saturates
            ("serve_mixed_tenant", 90,
             lambda: _bench_serve_mixed_tenant(100_000, 64, 10, 5.0,
                                               8, 150.0)),
            # sharded SPMD serving over the real mesh: the QPS-scales-
            # with-mesh-size claim measured on hardware (1/2/4/8-device
            # scaling table + merge-topology A/B)
            ("serve_knn_sharded", 260,
             lambda: _bench_serve_sharded(500_000, 128, 100, 4.0, 16)),
            # ANN serving at the north-star scale: IVF-Flat 1M x 128,
            # k=100, nprobe calibrated to recall@100 >= 0.9; est covers
            # the subsampled build + rungs x nprobe-cell warmup
            ("serve_ann_1m", 220,
             lambda: _bench_serve_ann(1_000_000, 128, 100, 5.0, 16,
                                      nlist=1024, train_rows=131072,
                                      target_recall=0.9, state=state)),
            # durability cost + recovery speed at hardware scale:
            # WAL-fsync'd inserts + periodic snapshots ON vs OFF,
            # restore-time and WAL-replay-rate rows
            # (docs/PERSISTENCE.md)
            ("serve_ann_persist", 200,
             lambda: _bench_serve_ann_persist(500_000, 64, 10, 4.0, 8,
                                              nlist=1024,
                                              train_rows=131072)),
            # out-of-core tier on hardware: index bigger than the
            # budget by 4x, host-streamed tiles double-buffered against
            # the scans — where H2D is a real interconnect, the
            # hidden-transfer fraction and overlap_speedup are the
            # honest version of the CPU ladder's numbers
            ("serve_ann_ooc", 260,
             lambda: _bench_serve_ann_ooc(1_000_000, 128, 100, 5.0, 12,
                                          nlist=1024,
                                          train_rows=131072,
                                          state=state)),
            ("spectral", 60, _bench_spectral),
            ("linkage_50k", 130, _bench_linkage_50k),
            ("spectral_100k", 80, _bench_spectral_100k),
            # 2*2048^2*32768 = 0.27 Tflop per call (~10 ms-scale on
            # chip) — est covers compile + the chained timing, not the
            # math; 4 real col tiles
            ("sparse_pairwise", 60,
             lambda: _bench_sparse_pairwise(2048, 32768, 16, 2, 8192)),
            # scale headroom: 10x the north star (5 GB index in HBM),
            # informational tail rung on the measured winner config
            ("knn_10m", 200,
             lambda: _bench_knn(10_000_000, 2048, 2, "xla",
                                *best_select())),
        ]

    dead_signs = _DEAD_SIGNS
    consecutive_dead = 0
    for idx, (name, est, fn) in enumerate(rungs):
        if _remaining() < est:
            skipped.append(name)
            _emit("skipped", skipped)
            continue
        t_rung = time.time()
        try:
            state[name] = _tag(fn())
            if isinstance(state[name], dict):
                # wall seconds the rung consumed (compile + warmup +
                # timing chains): makes budget forensics readable from
                # the report itself
                state[name]["t_rung_s"] = round(time.time() - t_rung, 1)
        except Exception as e:
            state.setdefault("errors", {})[name] = \
                traceback.format_exc()[-600:]
            _emit("errors", state["errors"])
            # a dead/hung device fails every later rung too (observed:
            # tunnel died mid-session after a healthy init) — after two
            # consecutive device-level failures, stop burning the budget
            # on timeouts and emit what's banked
            if any(s in str(e) for s in dead_signs):
                consecutive_dead += 1
                if consecutive_dead >= 2:
                    state["aborted"] = "device_unavailable_mid_ladder"
                    skipped.extend(n for n, _, _ in rungs[idx + 1:])
                    _emit("skipped", skipped)
                    _emit("aborted", state["aborted"])
                    break
            else:
                consecutive_dead = 0
            continue
        consecutive_dead = 0
        _emit(name, state[name])
    if skipped:
        state["skipped"] = skipped
    # attach the observability artifact (ISSUE 2): the same snapshot
    # Session.metrics_snapshot() / tools/metrics_report.py produce, so
    # bench JSON carries per-primitive timings, jit compile-cache
    # attribution, comms bytes/latency, and memory peaks alongside the
    # rung numbers.  Emitted as a PARTIAL too — the parent assembles
    # its report from streamed state, not the child's FINAL line.  The
    # human-readable report is dropped (it duplicates profiler_tree).
    try:
        from raft_tpu.session import metrics_snapshot

        snap = metrics_snapshot()
        snap.pop("profiler_report", None)
        state["metrics_snapshot"] = snap
    except Exception as e:  # never let observability sink the bench
        state["metrics_snapshot"] = {"error": repr(e)[:200]}
    _emit("metrics_snapshot", state["metrics_snapshot"])
    final = (assemble(None, state) if cpu else assemble(state, None))
    print("FINAL " + json.dumps(final), flush=True)


# --------------------------------------------------------------------------
# parent: watchdog + orchestration, no JAX
# --------------------------------------------------------------------------

class _Child:
    def __init__(self, deadline, cpu):
        env = dict(os.environ)
        env[_DEADLINE_ENV] = repr(deadline)
        # persistent compilation cache: in-session compiles (and prior
        # bench runs) pre-pay the driver's compile cost where the
        # backend supports executable serialization
        env.setdefault("JAX_COMPILATION_CACHE_DIR",
                       os.path.join(REPO, ".jax_cache"))
        if cpu:
            env[_CPU_ENV] = "1"
            env["JAX_PLATFORMS"] = "cpu"
            # 8-device virtual mesh (the tests/conftest.py convention):
            # the comms_p2p rung A/Bs p2p staging across ranks, which a
            # 1-device CPU backend cannot exercise
            flags = env.get("XLA_FLAGS", "")
            if "xla_force_host_platform_device_count" not in flags:
                env["XLA_FLAGS"] = (
                    flags + " --xla_force_host_platform_device_count=8"
                ).strip()
        self.t_spawn = time.time()
        self.proc = subprocess.Popen(
            [sys.executable, os.path.abspath(__file__), "--child"],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
            text=True)
        self.state = {}
        self.final = None
        self.stderr_tail = ""
        # any streamed line counts as liveness: the stall watchdog keys
        # off this (a hung first-op RPC emits nothing for the rest of
        # the budget — observed r4)
        self.t_last_progress = time.time()
        threading.Thread(target=self._read_out, daemon=True).start()
        threading.Thread(target=self._read_err, daemon=True).start()

    def _read_out(self):
        for line in self.proc.stdout:
            line = line.strip()
            self.t_last_progress = time.time()
            if line.startswith("PARTIAL "):
                try:
                    self.state.update(json.loads(line[8:]))
                except ValueError:
                    pass
            elif line.startswith("FINAL "):
                try:
                    self.final = json.loads(line[6:])
                except ValueError:
                    pass

    def _read_err(self):
        tail = []
        for line in self.proc.stderr:
            # stderr counts as liveness too: a long compile with
            # continuous XLA logging but no PARTIAL yet is progressing,
            # not stalled
            self.t_last_progress = time.time()
            tail.append(line)
            tail = tail[-8:]
            # published incrementally, not at stream EOF: the stall
            # watchdog builds its attempt note while the child is still
            # alive, and a note without the gRPC/XLA stderr evidence is
            # exactly the diagnostic loss it exists to prevent
            self.stderr_tail = "".join(tail)[-600:]

    def kill(self):
        try:
            self.proc.kill()
        except OSError:
            pass


def _has_rung(state):
    return any(_rung_metric(v) for v in state.values())


def _partition_attempt_states(states):
    """Merge rungs banked by every attempt (a stalled attempt may have
    banked rungs before its channel died); later attempts win ties.
    PARTITIONED BY THE BACKEND THAT MEASURED THEM: when one attempt ran
    on the accelerator and another fell back to CPU (wedged endpoint),
    a blind merge would let the later init overwrite the earlier one —
    relabeling TPU-measured rungs as CPU fallback or, worse, CPU-speed
    rungs as accelerator numbers (r4 review).  Returns
    (accel_state, fallback_state, tpu_is_accel)."""
    accel_state, fb_state = {}, {}
    for s in states:
        dst = (accel_state if s.get("init", {}).get("is_tpu")
               else fb_state)
        dst.update(s)
    accel_state.pop("fallback", None)
    fb_state.pop("fallback", None)
    tpu_is_accel = bool(accel_state.get("init", {}).get("is_tpu"))
    return accel_state, fb_state, tpu_is_accel


def _rung_metric(v):
    if not isinstance(v, dict):
        return None
    return v.get("qps") or v.get("gpairs_per_sec") or v.get("tflops")


def _merge_best_rungs(base, other):
    """Fold `other`'s rungs into `base`, keeping the better metric per
    rung (never wholesale replacement: a fallback attempt that banked
    one fast kNN rung must not discard the CPU child's other rungs)."""
    merged = dict(base)
    for k, v in other.items():
        m = _rung_metric(v)
        if m is None:
            continue
        cur = _rung_metric(merged.get(k))
        if cur is None or m > cur:
            merged[k] = v
    return merged


def _tpu_attempt_note(tpu, deadline):
    """Honest status of the accelerator child (round-3 advisor: a child
    killed mid-import must not be labeled 'init did not complete')."""
    rc = tpu.proc.poll()
    init_log = tpu.state.get("init_log") or []
    note = {
        "init_log": init_log,
        "elapsed_at_report": round(time.time() - tpu.t_spawn, 1),
    }
    if tpu.state.get("init"):
        note["status"] = (
            "init_ok_but_no_accelerator_rung_completed"
            if tpu.state["init"].get("is_tpu")
            else "init_on_non_accelerator_backend")
        # keep the child's evidence: which rungs errored/skipped/aborted
        # and anything it did bank — 'init ok, all rungs died' must stay
        # diagnosable from the report alone
        for key in ("init", "errors", "skipped", "aborted"):
            if tpu.state.get(key) is not None:
                note[key] = tpu.state[key]
    elif rc is None:
        where = init_log[-1]["event"] if init_log else "spawn"
        note["status"] = ("killed_at_deadline_during_backend_init"
                          if time.time() >= deadline else "still_running")
        note["stuck_after"] = where
    elif rc != 0:
        note["status"] = "child_died_rc=%d_before_init" % rc
    else:
        note["status"] = "child_exited_rc=0_before_init"
    if tpu.stderr_tail:
        note["stderr_tail"] = tpu.stderr_tail
    return note


def parent_main():
    t_start = time.time()
    budget = float(os.environ.get(_BUDGET_ENV, "420"))
    deadline = t_start + budget - _SAFETY

    if os.environ.get("RAFT_TPU_BENCH_NO_TPU") == "1":
        # CPU-only evidence run: never spawns the accelerator child, so
        # it cannot collide with a recovery pipeline probing a wedged
        # endpoint (the r4 policy: the driver's bench must find a free
        # endpoint, never a competing client)
        cpu = _Child(deadline, cpu=True)
        while (time.time() < deadline and cpu.final is None
               and cpu.proc.poll() is None):
            time.sleep(0.5)
        t_grace = time.time() + 1.0
        while time.time() < t_grace:
            time.sleep(0.1)
        cpu_state = dict(cpu.state)
        cpu_state.pop("fallback", None)
        cpu_state.pop("init_log", None)
        cpu_state["tpu_attempt"] = {"status": "skipped_by_env_no_tpu"}
        if not _has_rung(cpu_state):
            # an "evidence run" must never report zeros without saying
            # why: the generic attempt note distinguishes died-early /
            # killed-at-deadline / init-only, with stderr + init_log
            cpu_state["cpu_attempt"] = _tpu_attempt_note(cpu, deadline)
        cpu.kill()
        print(json.dumps(assemble(None, cpu_state)), flush=True)
        return

    # BOTH children at t=0: the TPU child owns the whole budget (hung
    # init costs nothing), the CPU child banks fallback rungs for free.
    tpu = _Child(deadline, cpu=False)
    cpu = _Child(deadline, cpu=True)
    tpu_graced = False
    # stall watchdog: one hung RPC must not burn the whole TPU budget
    # on a dead gRPC channel (observed r4: first op after devices_ready
    # hung for the entire 2400 s).  No streamed line for STALL_S —
    # comfortably above any legitimate compile gap; rungs and init
    # retries all emit PARTIALs — kills the child and respawns on a
    # fresh channel, keeping each attempt's evidence and banked rungs.
    stall_s = float(os.environ.get("RAFT_TPU_BENCH_STALL_S", "420"))
    # stage-aware stall: BEFORE the child's "init" PARTIAL (backend up)
    # the only legitimate silence is a healthy backend init, measured at
    # 0.1-14 s whenever the endpoint was up (r4 sessions) — a silent
    # 150 s there is a hung init RPC, and a fresh child on a fresh
    # channel is the only probe that can ever bank a rung.  AFTER init,
    # long compiles justify the full stall_s.
    init_stall_s = float(os.environ.get("RAFT_TPU_BENCH_INIT_STALL_S",
                                        "150"))
    stalled_attempts = []
    banked_states = []
    while time.time() < deadline:
        if tpu.final is not None:
            break
        tpu_dead = tpu.proc.poll() is not None
        cpu_done = cpu.final is not None or cpu.proc.poll() is not None
        if tpu_dead and not tpu_graced:
            # one-time grace: the reader thread may not have consumed a
            # FINAL line yet
            tpu_graced = True
            t_grace = time.time() + 2.0
            while time.time() < min(t_grace, deadline) and tpu.final is None:
                time.sleep(0.1)
            if tpu.final is not None:
                break
        cur_stall = (stall_s if tpu.state.get("init")
                     else init_stall_s)
        # a fresh child can init in ~15 s and bank the micro rung in a
        # few more, so re-probing stays worthwhile until nearly the end
        min_left = 120 if tpu.state.get("init") else 45
        if (not tpu_dead and tpu.final is None
                and time.time() - tpu.t_last_progress > cur_stall
                and deadline - time.time() > min_left):
            note = _tpu_attempt_note(tpu, deadline)
            note["status"] = "killed_stalled_no_progress"
            note["stalled_s"] = round(time.time() - tpu.t_last_progress, 1)
            stalled_attempts.append(note)
            # bank only RUNG results: per-attempt bookkeeping
            # (skipped/errors/aborted/init_log) lives in the attempt
            # note and must not contradict a later attempt's outcome
            banked_states.append({
                k: v for k, v in tpu.state.items()
                if k not in ("skipped", "errors", "aborted", "init_log")})
            tpu.kill()
            tpu = _Child(deadline, cpu=False)
            tpu_graced = False
        if tpu_dead and cpu_done:
            break
        time.sleep(0.5)

    # small drain so reader threads catch trailing PARTIAL lines
    t_grace = time.time() + 1.0
    while time.time() < t_grace:
        time.sleep(0.1)

    has_rung = _has_rung
    accel_state, fb_state, tpu_is_accel = _partition_attempt_states(
        banked_states + [dict(tpu.state)])
    tpu_state = accel_state if tpu_is_accel else fb_state
    cpu_state = dict(cpu.state)
    cpu_state.pop("fallback", None)
    cpu_state.pop("init_log", None)
    if tpu_is_accel and has_rung(fb_state):
        # a CPU-fallback attempt's rungs compete with the CPU child's,
        # never with the accelerator's; per-rung best-of, not wholesale
        # (bookkeeping keys never propagate: _merge_best_rungs copies
        # only metric-bearing rungs)
        cpu_state = _merge_best_rungs(cpu_state, fb_state)
    if tpu_is_accel and has_rung(tpu_state):
        if stalled_attempts:
            tpu_state["stalled_attempts"] = stalled_attempts
        result = assemble(tpu_state, cpu_state)
    else:
        # no hardware number: both children (at best) ran CPU ladders —
        # report whichever banked the better kNN rung, with an honest
        # account of what happened to the accelerator attempt
        if not tpu_is_accel and has_rung(tpu_state):
            cpu_state = _merge_best_rungs(cpu_state, tpu_state)
        note = _tpu_attempt_note(tpu, deadline)
        if stalled_attempts:
            note["stalled_attempts"] = stalled_attempts
        cpu_state["tpu_attempt"] = note
        result = assemble(None, cpu_state)
    tpu.kill()
    cpu.kill()
    print(json.dumps(result), flush=True)


def main():
    if "--child" in sys.argv:
        child_main()
    else:
        parent_main()


if __name__ == "__main__":
    try:
        main()
    except Exception:
        print(json.dumps({
            "metric": "knn_qps_1M_128d_k100",
            "value": 0.0,
            "unit": "queries/s",
            "vs_baseline": 0.0,
            "detail": {"error": traceback.format_exc()[-1200:]},
        }))
    sys.exit(0)
