#!/usr/bin/env python
"""Headline benchmark — prints ONE JSON line, always.

Measures the BASELINE.md configs: the north-star brute-force kNN QPS at
1M x 128d k=100 (config #3) as the headline metric, with pairwise-L2
Gpairs/s (config #1/#2 family) and a small spectral-partition run
(config #4) in ``detail``.

Robustness (round-1 postmortem: the TPU backend failed to initialize and
the bench emitted nothing):

- the backend is probed in a SUBPROCESS with a timeout + retries before
  any in-process JAX work, so a hung PJRT init cannot hang the bench;
- if the probe fails, the bench re-execs itself pinned to CPU with
  scaled-down shapes and reports honestly (``fallback`` in detail);
- every section and the whole main are wrapped so any failure still
  prints a JSON line (with an ``error`` field) and exits 0.

Timing methodology: the device may sit behind a high-latency transport
where per-call host timing (and even block_until_ready) is unreliable, so
each measurement chains ITERS data-dependent iterations inside ONE
compiled program, fetches a scalar to force completion, and subtracts the
single-iteration run to cancel fixed dispatch/fetch latency.

vs_baseline: the reference publishes no numbers (BASELINE.md), so the
baseline constant is an A100 estimate for the same op derived from the
north-star target ("within 1.5x of A100 wall-clock"):
- brute-force kNN 1M x 128 k=100: FAISS-class A100 throughput ~20k QPS.
  vs_baseline = ours / 20000.
- pairwise L2 f32: A100 sustains ~50 Gpairs/s at k=128.
"""

import json
import os
import subprocess
import sys
import time
import traceback

REPO = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, REPO)

KNN_BASELINE_QPS = 20000.0
PAIRWISE_BASELINE_GPAIRS = 50.0
_FALLBACK_ENV = "RAFT_TPU_BENCH_CPU_FALLBACK"

PROBE_SRC = """
import jax, jax.numpy as jnp
x = jnp.ones((128, 128), jnp.float32)
v = float((x @ x)[0, 0])
assert v == 128.0, v
print("PROBE_OK", jax.devices()[0].device_kind)
"""


PALLAS_CHECK_SRC = f"""
import sys
sys.path.insert(0, {REPO!r})
import numpy as np, jax.numpy as jnp
from raft_tpu.spatial.fused_l2_knn import fused_l2_knn
x = jnp.asarray(np.random.default_rng(0).standard_normal((512, 128)),
                dtype=jnp.float32)
d_p, i_p = fused_l2_knn(x, x[:32], 8, impl="pallas")
d_r, i_r = fused_l2_knn(x, x[:32], 8, impl="xla")
assert np.allclose(np.asarray(d_p), np.asarray(d_r), atol=1e-3)
assert np.array_equal(np.asarray(i_p), np.asarray(i_r))
print("PALLAS_OK")
"""


def probe_backend(timeout=180, attempts=2):
    """Run a tiny matmul in a subprocess; returns (ok, info-string).

    A subprocess is the only safe way to test PJRT init: round 1 showed
    it can either raise UNAVAILABLE or hang indefinitely, and a hang in
    the bench process itself would produce no JSON at all.  Worst case
    here is ~6 min of probing before the CPU fallback kicks in — kept
    well under any plausible harness timeout.
    """
    last = ""
    for i in range(attempts):
        try:
            r = subprocess.run(
                [sys.executable, "-c", PROBE_SRC],
                capture_output=True, text=True, timeout=timeout,
            )
            out = (r.stdout or "") + (r.stderr or "")
            if r.returncode == 0 and "PROBE_OK" in r.stdout:
                kind = r.stdout.split("PROBE_OK", 1)[1].strip()
                return True, kind
            last = out[-500:]
        except subprocess.TimeoutExpired:
            last = f"probe timed out after {timeout}s"
        if i + 1 < attempts:
            time.sleep(5)
    return False, last


def time_chained(step, x, iters):
    """Seconds per call of ``step(x) -> array``, measured by chaining
    ``iters`` data-dependent calls in one jit and differencing against a
    1-iteration run to cancel fixed latency."""
    import jax
    import jax.numpy as jnp

    def chained(n):
        @jax.jit
        def run(x0):
            def body(carry, _):
                out = step(carry)
                # data dependency without changing the value: adds 0.0
                # derived from a FULL reduction of the output, so XLA
                # cannot slice-narrow the benchmarked op
                return carry + jnp.sum(out) * 0.0, None

            final, _ = jax.lax.scan(body, x0, None, length=n)
            return final.ravel()[0]

        return run

    run_n = chained(iters)
    run_1 = chained(1)
    float(run_n(x))  # compile n
    float(run_1(x))  # compile 1
    t0 = time.perf_counter()
    float(run_n(x))
    t_n = time.perf_counter() - t0
    t0 = time.perf_counter()
    float(run_1(x))
    t_1 = time.perf_counter() - t0
    return max((t_n - t_1) / (iters - 1), 1e-9)


def bench_knn(fallback):
    """North star (BASELINE.md config #3): brute-force kNN 1M x 128 k=100."""
    import jax.numpy as jnp
    import numpy as np

    from raft_tpu.spatial import brute_force_knn

    if fallback:  # CPU can't sustain the 2.56-TFLOP batch; scale honestly
        n_index, n_query, dim, k, iters = 100_000, 512, 128, 100, 2
    else:
        n_index, n_query, dim, k, iters = 1_000_000, 10_000, 128, 100, 4

    # Validate the compiled Pallas fused-kNN path before the headline run —
    # in a SUBPROCESS with a timeout (a Mosaic compile/runtime hang in this
    # process would break the one-JSON-line-always contract), and only on a
    # real TPU backend (anywhere else "pallas" means the interpreter, which
    # is orders of magnitude slower than the XLA impl).  On any failure,
    # pin the proven XLA tile-scan impl.
    impl_used = os.environ.get("RAFT_TPU_FUSED_KNN_IMPL")
    if impl_used is None and not fallback:
        from raft_tpu.core.utils import is_tpu_backend

        impl_used = "xla"
        if is_tpu_backend():
            try:
                r = subprocess.run(
                    [sys.executable, "-c", PALLAS_CHECK_SRC],
                    capture_output=True, text=True, timeout=300,
                )
                if r.returncode == 0 and "PALLAS_OK" in r.stdout:
                    impl_used = "pallas"
            except subprocess.TimeoutExpired:
                pass
        os.environ["RAFT_TPU_FUSED_KNN_IMPL"] = impl_used

    rng = np.random.default_rng(42)
    index = jnp.array(rng.standard_normal((n_index, dim)), dtype=jnp.float32)
    queries = jnp.array(rng.standard_normal((n_query, dim)), dtype=jnp.float32)

    def step(q):
        dists, _ = brute_force_knn([index], q, k)
        return dists

    dt = time_chained(step, queries, iters=iters)
    qps = n_query / dt
    # per-query work scales with n_index, so normalize the scaled-down
    # fallback config to its 1M-index equivalent before comparing against
    # the 1M-config A100 baseline constant
    qps_1m_equiv = qps * (n_index / 1_000_000)
    return qps, qps_1m_equiv, {
        "seconds_per_batch": round(dt, 4),
        "n_index": n_index, "n_query": n_query, "dim": dim, "k": k,
        "fused_knn_impl": impl_used or "xla",
    }


def bench_pairwise(fallback):
    """BASELINE.md config #1 family: pairwise L2 throughput."""
    import jax.numpy as jnp
    import numpy as np

    from raft_tpu.distance import DistanceType, pairwise_distance

    m = n = 2048 if fallback else 8192
    dim = 128
    rng = np.random.default_rng(42)
    x = jnp.array(rng.standard_normal((m, dim)), dtype=jnp.float32)
    y = jnp.array(rng.standard_normal((n, dim)), dtype=jnp.float32)

    def step(a):
        return pairwise_distance(a, y, DistanceType.L2Expanded)

    dt = time_chained(step, x, iters=4 if fallback else 16)
    gpairs = m * n / dt / 1e9
    return {
        "gpairs_per_sec": round(gpairs, 2),
        "shape": [m, n, dim],
        "vs_a100_estimate": round(gpairs / PAIRWISE_BASELINE_GPAIRS, 3),
    }


def bench_spectral(fallback):
    """BASELINE.md config #4: Lanczos -> spectral partition on a CSR graph."""
    import numpy as np

    from raft_tpu.sparse.formats import COO
    from raft_tpu.sparse.spectral import fit_embedding

    n = 512 if fallback else 2048
    rng = np.random.default_rng(0)
    # ring + random chords: connected, sparse
    src = np.arange(n, dtype=np.int64)
    dst = (src + 1) % n
    extra = rng.integers(0, n, size=(2 * n, 2), dtype=np.int64)
    extra = extra[extra[:, 0] != extra[:, 1]]
    rows = np.concatenate([src, dst, extra[:, 0], extra[:, 1]])
    cols = np.concatenate([dst, src, extra[:, 1], extra[:, 0]])
    vals = np.ones(rows.shape[0], dtype=np.float32)
    coo = COO(rows.astype(np.int32), cols.astype(np.int32), vals, shape=(n, n))
    t0 = time.perf_counter()
    emb = fit_embedding(coo, n_components=4)
    np.asarray(emb)
    dt = time.perf_counter() - t0
    return {"seconds": round(dt, 3), "n_vertices": n, "n_components": 4}


def run_benches(fallback, device_kind):
    detail = {"fallback": "cpu" if fallback else None, "device": device_kind}
    errors = {}

    qps = qps_1m_equiv = 0.0
    try:
        qps, qps_1m_equiv, knn_detail = bench_knn(fallback)
        detail["knn"] = knn_detail
    except Exception:
        errors["knn"] = traceback.format_exc()[-800:]
    for name, fn in (("pairwise", bench_pairwise), ("spectral", bench_spectral)):
        try:
            detail[name] = fn(fallback)
        except Exception:
            errors[name] = traceback.format_exc()[-800:]
    if errors:
        detail["errors"] = errors

    return {
        "metric": "knn_qps_1M_128d_k100" if not fallback
        else "knn_qps_100k_128d_k100_cpu_fallback",
        "value": round(qps, 1),
        "unit": "queries/s",
        "vs_baseline": round(qps_1m_equiv / KNN_BASELINE_QPS, 4),
        "detail": detail,
    }


def main():
    fallback = os.environ.get(_FALLBACK_ENV) == "1"
    if not fallback:
        ok, info = probe_backend()
        if not ok:
            # backend dead: re-exec pinned to CPU so this process never
            # touches the broken backend (in-process platform switching
            # after a failed init is not reliable)
            env = dict(os.environ)
            env[_FALLBACK_ENV] = "1"
            env["JAX_PLATFORMS"] = "cpu"
            env["RAFT_TPU_PROBE_ERROR"] = info[-400:]
            os.execve(sys.executable, [sys.executable, __file__], env)
    else:
        os.environ["JAX_PLATFORMS"] = "cpu"

    import jax

    jax.config.update("jax_platforms", os.environ.get("JAX_PLATFORMS") or None)
    device_kind = str(jax.devices()[0].device_kind)

    from raft_tpu.core.utils import is_tpu_backend

    if not fallback and not is_tpu_backend():
        # probe succeeded but on a non-TPU backend (e.g. a CPU-only dev
        # box): the full 1M-point config would run for hours — use the
        # scaled shapes and say so in the metric name
        fallback = True
    result = run_benches(fallback, device_kind)
    if fallback and os.environ.get("RAFT_TPU_PROBE_ERROR"):
        result["detail"]["probe_error"] = os.environ["RAFT_TPU_PROBE_ERROR"]
    print(json.dumps(result))


if __name__ == "__main__":
    try:
        main()
    except Exception:
        print(json.dumps({
            "metric": "knn_qps_1M_128d_k100",
            "value": 0.0,
            "unit": "queries/s",
            "vs_baseline": 0.0,
            "error": traceback.format_exc()[-1500:],
        }))
        sys.exit(0)
