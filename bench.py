#!/usr/bin/env python
"""Headline benchmark — prints ONE JSON line.

Measures the BASELINE.md configs that exist so far, and reports the
north-star metric: brute-force kNN QPS at 1M x 128d k=100 when the spatial
module is available, else pairwise-L2 Gpairs/sec/chip.

Timing methodology: the device may sit behind a high-latency transport
where per-call host timing (and even block_until_ready) is unreliable, so
each measurement chains ITERS data-dependent iterations inside ONE
compiled program, fetches a scalar to force completion, and subtracts the
single-iteration run to cancel fixed dispatch/fetch latency.

vs_baseline: the reference publishes no numbers (BASELINE.md), so the
baseline constant is an A100 estimate for the same op derived from the
north-star target ("within 1.5x of A100 wall-clock"):
- pairwise L2 f32: A100 sustains ~50 Gpairs/s at k=128 (19.5 TF/s fp32 FMA
  with the fused kernel ~65% efficient).  vs_baseline = ours / 50.
- brute-force kNN 1M x 128 k=100: FAISS-class A100 throughput ~20k QPS.
  vs_baseline = ours / 20000.
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import jax
import jax.numpy as jnp
import numpy as np


def time_chained(step, x, iters):
    """Seconds per call of ``step(x) -> array``, measured by chaining
    ``iters`` data-dependent calls in one jit and differencing against a
    1-iteration run to cancel fixed latency."""

    def chained(n):
        @jax.jit
        def run(x0):
            def body(carry, _):
                out = step(carry)
                # data dependency without changing the value: adds 0.0
                # derived from a FULL reduction of the output — every
                # element feeds the carry, so XLA cannot slice-narrow the
                # benchmarked op to a sub-computation (and the sum is not
                # constant-foldable since the output could be non-finite)
                return carry + jnp.sum(out) * 0.0, None

            final, _ = jax.lax.scan(body, x0, None, length=n)
            return final.ravel()[0]

        return run

    run_n = chained(iters)
    run_1 = chained(1)
    float(run_n(x))  # compile n
    float(run_1(x))  # compile 1
    t0 = time.perf_counter()
    float(run_n(x))
    t_n = time.perf_counter() - t0
    t0 = time.perf_counter()
    float(run_1(x))
    t_1 = time.perf_counter() - t0
    return max((t_n - t_1) / (iters - 1), 1e-9)


def bench_knn():
    from raft_tpu.spatial import brute_force_knn

    n_index, n_query, k_dim, k = 1_000_000, 10_000, 128, 100
    rng = np.random.default_rng(42)
    index = jnp.array(rng.standard_normal((n_index, k_dim)), dtype=jnp.float32)
    queries = jnp.array(rng.standard_normal((n_query, k_dim)), dtype=jnp.float32)

    def step(q):
        dists, idx = brute_force_knn([index], q, k)
        return dists

    dt = time_chained(step, queries, iters=4)
    qps = n_query / dt
    return {
        "metric": "knn_qps_1M_128d_k100",
        "value": round(qps, 1),
        "unit": "queries/s",
        "vs_baseline": round(qps / 20000.0, 3),
        "detail": {"seconds_per_batch": round(dt, 4), "n_query": n_query},
    }


def bench_pairwise():
    from raft_tpu.distance import DistanceType, pairwise_distance

    m = n = 8192
    k = 128
    rng = np.random.default_rng(42)
    x = jnp.array(rng.standard_normal((m, k)), dtype=jnp.float32)
    y = jnp.array(rng.standard_normal((n, k)), dtype=jnp.float32)

    def step(a):
        return pairwise_distance(a, y, DistanceType.L2Expanded)

    dt = time_chained(step, x, iters=16)
    gpairs = m * n / dt / 1e9
    return {
        "metric": "pairwise_l2_gpairs_per_sec",
        "value": round(gpairs, 2),
        "unit": "Gpairs/s (m=n=8192, k=128, f32)",
        "vs_baseline": round(gpairs / 50.0, 3),
    }


def main():
    import importlib.util

    # explicit existence check: a broken import inside raft_tpu.spatial must
    # surface as an error, not silently fall back to the wrong metric
    if importlib.util.find_spec("raft_tpu.spatial") is not None:
        result = bench_knn()
    else:
        result = bench_pairwise()
    result["device"] = str(jax.devices()[0].device_kind)
    print(json.dumps(result))


if __name__ == "__main__":
    main()
