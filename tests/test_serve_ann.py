"""ANNService (raft_tpu.serve.ann_service): served-vs-direct identity,
warmup compile-cache proof across rungs x nprobe cells, streaming
ingestion (insert visibility, delta overflow shed), compaction (manual,
automatic under concurrent traffic, drain ordering), recall-targeted
calibration, session integration, and the loadgen recall@k scoring.

Deterministic halves run threadless services (``start=False``) stepped
through ``worker.run_once()`` / explicit ``compact()`` calls; the
concurrency half runs real workers with tiny windows and thresholds
(``./stress.sh serve N`` rotates RAFT_TPU_SERVE_SEED over this file
too — same ``serve`` marker).
"""

import os
import threading
import time

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from raft_tpu.core.error import (
    LogicError,
    ServiceOverloadError,
)
from raft_tpu.core.metrics import default_registry
from raft_tpu.core.profiler import (
    compile_cache_stats,
    reset_compile_cache_stats,
)
from raft_tpu.serve import ANNService
from raft_tpu.spatial import ann
from raft_tpu.spatial.knn import brute_force_knn

pytestmark = pytest.mark.serve

SEED = int(os.environ.get("RAFT_TPU_SERVE_SEED", "1234"))


@pytest.fixture
def rng():
    return np.random.default_rng(SEED)


@pytest.fixture
def flat_index(rng):
    X = jnp.asarray(rng.standard_normal((2000, 24)), jnp.float32)
    return ann.ivf_flat_build(X, ann.IVFFlatParams(nlist=16, nprobe=8),
                              seed=SEED)


def _total_misses():
    return sum(s["misses"] for fn in compile_cache_stats().values()
               for s in fn.values())


def make_ann(index, *, start=False, **kw):
    kw.setdefault("max_batch_rows", 32)
    kw.setdefault("bucket_rungs", (8, 32))
    kw.setdefault("max_wait_ms", 1.0)
    kw.setdefault("nprobe_ladder", (4, 8))
    kw.setdefault("delta_cap", 64)
    kw.setdefault("compact_rows", 0)   # manual compaction by default
    return ANNService(index, k=10, start=start, **kw)


def _step(svc, fut, timeout=5.0):
    """Drive a threadless worker until ``fut`` resolves (the window is
    wall-clock; poll run_once until the batcher releases the batch).
    The timeout only fires while the future is genuinely unresolved —
    a ``run_once`` whose first dispatch pays a long compile must not
    trip it after the fact."""
    t0 = time.monotonic()
    while not fut.done():
        svc.worker.run_once()
        if fut.done():
            break
        if time.monotonic() - t0 > timeout:
            raise AssertionError("future did not resolve")
        time.sleep(0.002)
    return fut.result(timeout=0)


class TestServedVsDirect:
    def test_bit_identity_no_donate(self, flat_index, rng):
        svc = make_ann(flat_index, donate=False)
        q = jnp.asarray(rng.standard_normal((6, 24)), jnp.float32)
        d, i = _step(svc, svc.submit(q))
        d0, i0 = ann.ivf_flat_search(flat_index, q, 10)
        # same profiled_jit executable (empty delta, no donation):
        # bitwise equality, not closeness
        assert bool((np.asarray(d) == np.asarray(d0)).all())
        assert bool((np.asarray(i) == np.asarray(i0)).all())
        svc.close()

    def test_bit_identity_donating_default(self, flat_index, rng):
        svc = make_ann(flat_index)
        assert svc.donate    # default on without a retry policy
        q = jnp.asarray(rng.standard_normal((6, 24)), jnp.float32)
        d, i = _step(svc, svc.submit(q))
        d0, i0 = ann.ivf_flat_search(flat_index, q, 10)
        # the donating twin runs the same HLO; donation only recycles
        # the input buffer (docs/ZERO_COPY.md)
        assert bool((np.asarray(d) == np.asarray(d0)).all())
        assert bool((np.asarray(i) == np.asarray(i0)).all())
        # the caller's array survives (the worker pads/copies)
        assert q.shape == (6, 24)
        np.asarray(q)
        svc.close()

    def test_pq_and_sq_served(self, rng):
        X = jnp.asarray(rng.standard_normal((1500, 16)), jnp.float32)
        for build, params in (
                (ann.ivf_pq_build, ann.IVFPQParams(nlist=8, nprobe=8,
                                                   M=4)),
                (ann.ivf_sq_build, ann.IVFSQParams(nlist=8, nprobe=8))):
            idx = build(X, params, seed=SEED)
            svc = make_ann(idx, nprobe_ladder=(8,))
            q = jnp.asarray(rng.standard_normal((4, 16)), jnp.float32)
            d, i = _step(svc, svc.submit(q))
            d0, i0 = ann.approx_knn_search(idx, q, 10)
            assert bool((np.asarray(i) == np.asarray(i0)).all())
            assert np.allclose(np.asarray(d), np.asarray(d0))
            # PQ/SQ stores hold codes: compaction is flat-only
            assert svc.stats()["compact_rows"] == 0
            with pytest.raises(LogicError):
                svc.compact()
            svc.close()


class TestWarmupCompileCache:
    def test_rungs_times_nprobe_zero_steady_state_compiles(self, rng):
        # uniquely-shaped index: compiled executables persist across
        # reset_compile_cache_stats, so the miss-count proof needs
        # cache keys no earlier test in this process can have compiled
        X = jnp.asarray(rng.standard_normal((2161, 24)), jnp.float32)
        index = ann.ivf_flat_build(
            X, ann.IVFFlatParams(nlist=16, nprobe=8), seed=SEED)
        svc = make_ann(index, delta_cap=48)
        reset_compile_cache_stats()
        assert svc.warmed_rungs == ()
        svc.warmup()
        assert svc.warmed_rungs == (8, 32)
        m_warm = _total_misses()
        # at least one compile per (rung x cell x {plain, delta} arm)
        assert m_warm >= len(svc.policy.rungs) * len(svc.nprobe_ladder)
        # steady state: every admissible shape x every ladder cell x
        # both delta arms lands on a warmed executable
        for cell in svc.nprobe_ladder:
            svc.set_nprobe(cell)
            for r in (1, 7, 8, 31):
                q = jnp.asarray(rng.standard_normal((r, 24)),
                                jnp.float32)
                _step(svc, svc.submit(q))
        svc.insert([41000], rng.standard_normal((1, 24)))
        for cell in svc.nprobe_ladder:
            svc.set_nprobe(cell)
            _step(svc, svc.submit(
                jnp.asarray(rng.standard_normal((5, 24)), jnp.float32)))
        assert _total_misses() == m_warm
        svc.close()


class TestStreamingIngestion:
    def test_insert_then_query_sees_vector(self, flat_index, rng):
        svc = make_ann(flat_index)
        probe = jnp.asarray(rng.standard_normal((1, 24)), jnp.float32)
        d0, i0 = _step(svc, svc.submit(probe))
        assert 77777 not in set(np.asarray(i0).ravel())
        svc.insert([77777], probe)
        assert svc.delta_rows == 1
        d1, i1 = _step(svc, svc.submit(probe))
        # the inserted vector IS the query: exact match at distance ~0,
        # visible before any compaction (the visibility point is the
        # next formed batch)
        assert int(np.asarray(i1)[0, 0]) == 77777
        assert float(np.asarray(d1)[0, 0]) <= 1e-5
        svc.close()

    def test_insert_validation_and_overflow_shed(self, flat_index, rng):
        svc = make_ann(flat_index, delta_cap=8)
        with pytest.raises(LogicError):
            svc.insert([-1], rng.standard_normal((1, 24)))
        with pytest.raises(LogicError):
            svc.insert([1, 2], rng.standard_normal((1, 24)))
        with pytest.raises(LogicError):   # single block beyond capacity
            svc.insert(np.arange(9), rng.standard_normal((9, 24)))
        svc.insert(np.arange(6), rng.standard_normal((6, 24)))
        with pytest.raises(ServiceOverloadError):
            svc.insert([6, 7, 8], rng.standard_normal((3, 24)))
        # shed, not corrupted: the first six rows are still there
        assert svc.delta_rows == 6
        svc.close()

    def test_results_unchanged_across_compaction_swap(self, flat_index,
                                                      rng):
        # full probe: the brute-force cross-check below needs the scan
        # to be exact (nprobe < nlist legitimately misses neighbors)
        svc = make_ann(flat_index, nprobe=16, nprobe_ladder=(16,))
        new_v = jnp.asarray(rng.standard_normal((12, 24)), jnp.float32)
        svc.insert(np.arange(50000, 50012), new_v)
        q = jnp.asarray(rng.standard_normal((7, 24)), jnp.float32)
        d_pre, i_pre = _step(svc, svc.submit(q))
        assert svc.compact()
        assert svc.delta_rows == 0
        assert svc.index is not flat_index      # atomic swap happened
        d_post, i_post = _step(svc, svc.submit(q))
        # the exact result set survives the swap: same neighbor ids in
        # the same order; distances agree to float tolerance (the same
        # row is now computed by the slot scan instead of the delta
        # merge)
        assert bool((np.asarray(i_pre) == np.asarray(i_post)).all())
        assert np.allclose(np.asarray(d_pre), np.asarray(d_post),
                           atol=1e-4)
        # and the compacted index agrees with brute force over the
        # reconstructed store (sets per row: near-equal distances at
        # the rank boundary may order differently across formulations)
        vecs, ids = svc.ground_truth_store()
        bd, bi = brute_force_knn(jnp.asarray(vecs), q, 10)
        want = ids[np.asarray(bi)]
        got = np.asarray(i_post)
        for r in range(got.shape[0]):
            assert set(got[r]) == set(want[r]), (r, got[r], want[r])
        svc.close()

    def test_compact_noop_on_empty_delta(self, flat_index):
        svc = make_ann(flat_index)
        assert svc.compact() is False
        svc.close()


class TestCompactionUnderTraffic:
    def test_auto_compaction_with_concurrent_submitters(self, rng):
        X = jnp.asarray(rng.standard_normal((3000, 24)), jnp.float32)
        index = ann.ivf_flat_build(
            X, ann.IVFFlatParams(nlist=16, nprobe=16), seed=SEED)
        svc = ANNService(index, k=10, max_batch_rows=32,
                         bucket_rungs=(8, 32), max_wait_ms=0.5,
                         nprobe_ladder=(16,), nprobe=16,
                         delta_cap=256, compact_rows=24,
                         maintenance_interval_s=0.005, start=True)
        stop = threading.Event()
        errors = []
        results = []
        q_fixed = jnp.asarray(rng.standard_normal((3, 24)), jnp.float32)

        def submitter(tid):
            g = np.random.default_rng(SEED + tid)
            while not stop.is_set():
                try:
                    fut = svc.submit(jnp.asarray(
                        g.standard_normal((2, 24)), jnp.float32))
                    fut.result(timeout=10.0)
                    fut2 = svc.submit(q_fixed)
                    results.append(fut2.result(timeout=10.0))
                except Exception as e:  # noqa: BLE001
                    errors.append(e)

        threads = [threading.Thread(target=submitter, args=(t,),
                                    daemon=True) for t in range(4)]
        for t in threads:
            t.start()
        inserted = 0
        for round_ in range(8):
            svc.insert(np.arange(60000 + inserted,
                                 60000 + inserted + 16),
                       rng.standard_normal((16, 24)))
            inserted += 16
            time.sleep(0.05)
        # wait for the worker-loop maintenance to compact below the
        # threshold (it may legitimately keep a small tail)
        t0 = time.monotonic()
        while svc.delta_rows >= 24 and time.monotonic() - t0 < 10:
            time.sleep(0.02)
        stop.set()
        for t in threads:
            t.join(timeout=10.0)
        assert not errors, errors[:3]
        fam = default_registry().get(
            "raft_tpu_serve_ann_compactions_total")
        compactions = 0.0
        if fam is not None:
            for labels, series in fam.series():
                if labels.get("service") == svc.name:
                    compactions = series.value
        assert compactions >= 1, "auto-compaction never ran under load"
        assert svc.delta_rows < 24
        # every mid-flight answer for the fixed query matches one of
        # the legal snapshots; the FINAL state must contain all
        # inserted rows exactly once — verify against brute force
        d_fin, i_fin = _step_live(svc, q_fixed)
        vecs, ids = svc.ground_truth_store()
        assert len(np.unique(ids)) == len(ids)
        bd, bi = brute_force_knn(jnp.asarray(vecs), q_fixed, 10)
        assert bool((ids[np.asarray(bi)] == np.asarray(i_fin)).all())
        assert results, "no fixed-query results collected"
        svc.close()
        assert not svc.worker.is_alive()


def _step_live(svc, q):
    """Submit against a live (threaded) worker and wait."""
    return svc.submit(q).result(timeout=10.0)


class TestDrainAndSession:
    def test_drain_closes_compaction_cleanly(self, flat_index, rng):
        svc = ANNService(flat_index, k=10, max_batch_rows=32,
                         bucket_rungs=(8, 32), max_wait_ms=0.5,
                         nprobe_ladder=(8,), delta_cap=64,
                         compact_rows=4,
                         maintenance_interval_s=0.005, start=True)
        svc.insert(np.arange(70000, 70010),
                   rng.standard_normal((10, 24)))
        svc.close()          # drain -> join: no compaction mid-flight
        assert not svc.worker.is_alive()
        # whether the tick fired before the drain or not, no row was
        # lost: index content + delta = base + inserted
        vecs, ids = svc.ground_truth_store()
        assert vecs.shape[0] == 2000 + 10
        assert set(range(70000, 70010)) <= set(ids.tolist())
        # a second close is a no-op
        svc.close()

    def test_overload_shed_on_submit(self, flat_index, rng):
        svc = make_ann(flat_index, queue_cap=2)
        q = jnp.asarray(rng.standard_normal((1, 24)), jnp.float32)
        svc.submit(q)
        svc.submit(q)
        with pytest.raises(ServiceOverloadError):
            svc.submit(q)
        svc.close(drain=False)

    def test_session_serve_ann_registers_and_drains(self, flat_index,
                                                    rng):
        from raft_tpu.session import Comms

        with Comms() as sess:
            svc = sess.serve(kind="ann", index=flat_index, k=10,
                             max_batch_rows=32, bucket_rungs=(8, 32),
                             nprobe_ladder=(8,), delta_cap=32,
                             compact_rows=0)
            assert svc.name in sess.services
            hc = sess.health_check()
            assert svc.name in hc["services"]
            assert hc["services"][svc.name]["kind"] == "IVFFlatIndex"
            q = jnp.asarray(rng.standard_normal((2, 24)), jnp.float32)
            d, i = _step_live(svc, q)
            assert d.shape == (2, 10)
        assert not svc.is_open()
        assert not svc.worker.is_alive()


class TestCalibration:
    def test_calibrate_picks_cheapest_cell_meeting_target(self, rng):
        # well-clustered data: tiny nprobe already reaches the target,
        # so calibration must stop at the FIRST (cheapest) cell
        centers = rng.standard_normal((16, 24)).astype(np.float32) * 8
        assign = rng.integers(0, 16, 4000)
        X = jnp.asarray(centers[assign]
                        + 0.1 * rng.standard_normal((4000, 24)),
                        jnp.float32)
        index = ann.ivf_flat_build(
            X, ann.IVFFlatParams(nlist=16, nprobe=8), seed=SEED)
        svc = make_ann(index, nprobe_ladder=(1, 2, 4, 16))
        q = jnp.asarray(np.asarray(X)[:32]
                        + 0.05 * rng.standard_normal((32, 24)),
                        jnp.float32)
        rep = svc.calibrate(q, target_recall=0.9)
        assert rep["met_target"]
        assert rep["chosen_nprobe"] == rep["table"][-1]["nprobe"]
        assert svc.nprobe == rep["chosen_nprobe"]
        # full-probe cell is exact: recall 1.0 by construction
        rep_all = svc.calibrate(q, target_recall=2.0 - 1.0,
                                measure_all=True, set_default=False)
        assert rep_all["table"][-1]["nprobe"] == 16
        # full probe is an exact scan; allow rank-boundary tie flips
        # between the slot-scan and brute-force formulations
        assert rep_all["table"][-1]["recall_at_k"] >= 0.99
        svc.close()

    def test_set_nprobe_clamps_and_retargets(self, flat_index):
        svc = make_ann(flat_index)
        assert svc.set_nprobe(999) == 16   # clamped to nlist
        with pytest.raises(LogicError):
            svc.set_nprobe(0)
        svc.close()


class TestLoadgenRecall:
    def test_run_load_reports_recall_one_for_exact_service(self, rng):
        from raft_tpu.serve import KNNService
        from tools.loadgen import make_query_pool, run_load

        ref = jnp.asarray(rng.standard_normal((500, 16)), jnp.float32)
        svc = KNNService(ref, k=5, max_batch_rows=16, max_wait_ms=0.5)
        svc.loadgen_ref = ref
        pool = make_query_pool(ref, 2, n=4, seed=SEED)
        rep = run_load(svc, mode="closed", duration=0.5, concurrency=2,
                       recall=True, query_pool=pool)
        svc.close()
        assert rep["requests_ok"] > 0
        assert rep["recall_k"] == 5
        # exact service: recall@k is 1.0 by definition
        assert rep["recall_at_k"] == 1.0

    def test_run_load_recall_for_ann_service(self, flat_index):
        from tools.loadgen import make_query_pool, run_load

        svc = make_ann(flat_index, nprobe_ladder=(16,), nprobe=16,
                       start=True)
        ref, _ = svc.ground_truth_store()
        pool = make_query_pool(ref, 2, n=4, seed=SEED)
        rep = run_load(svc, mode="closed", duration=0.5, concurrency=2,
                       recall=True, query_pool=pool)
        svc.close()
        assert rep["requests_ok"] > 0
        # full probe (nprobe == nlist) is exact for IVF-Flat (modulo
        # rank-boundary tie flips vs the brute-force formulation)
        assert rep["recall_at_k"] >= 0.99
