"""Interpreted-Pallas vs XLA-reference parity for the fused kernels.

The contract under test (ISSUE r6): every fused kernel ships with an
XLA-composed companion selected through the tuning registry —

- an op-for-op ORACLE that replays the kernel's exact op order at the
  jnp level, so interpreted kernel and oracle agree BITWISE on one
  backend (``fused_knn_xla_oracle``, ``fused_ivf_scan_xla``); and
- for brute-force kNN, a FAST production twin (``fused_knn_xla``) with
  the same tile geometry and distance arithmetic but an exact
  ``lax.top_k`` running merge: distance VALUES match the kernel
  bitwise, ids agree wherever distances are distinct.

COST DISCIPLINE: one interpret-mode execution of a while-loop
running-select kernel costs ~15 s FLAT on CPU (the gate loop's lane
networks dispatch eagerly — not compile-cached), and the op-for-op
oracles pay the same per tile.  Tier-1 keeps at most a couple of
interpret executions; the full rung x k x dtype matrix is
``@pytest.mark.slow`` (run with ``-m slow``).
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from raft_tpu.core import tuning
from raft_tpu.core.error import LogicError
from raft_tpu.ops.ivf_tile import fused_ivf_scan, fused_ivf_scan_xla
from raft_tpu.ops.knn_tile import (fused_knn_tile, fused_knn_xla,
                                   fused_knn_xla_oracle)
from raft_tpu.spatial.fused_l2_knn import fused_l2_knn


def _np_knn(x, q, k):
    """Full-sort host reference: squared L2, ascending, stable ids."""
    d = ((q[:, None, :] - x[None, :, :]) ** 2).sum(-1)
    ids = np.argsort(d, axis=1, kind="stable")[:, :k]
    return np.take_along_axis(d, ids, axis=1), ids


def _rand(shape, seed):
    return np.random.RandomState(seed).random(shape).astype(np.float32)


def _slot_store(S, cap, d, seed, vacancy_rows=0):
    """Synthetic slotted IVF store: (S, cap, d) vectors, squared norms,
    global ids with ``vacancy_rows`` trailing -1 vacancies per slot."""
    rng = np.random.RandomState(seed)
    sv = rng.random((S, cap, d)).astype(np.float32)
    sn = (sv * sv).sum(-1).astype(np.float32)
    si = np.arange(S * cap, dtype=np.int32).reshape(S, cap)
    if vacancy_rows:
        si[:, cap - vacancy_rows:] = -1
        sv[:, cap - vacancy_rows:] = 0.0
        sn[:, cap - vacancy_rows:] = 0.0
    return sv, sn, si


# --------------------------------------------------------------------- #
# fast twin: exactness + tie-break (cheap, tier-1)
# --------------------------------------------------------------------- #
class TestFusedKnnXlaTwin:
    @pytest.mark.parametrize("n,d,nq,k", [
        (96, 8, 16, 5),
        (700, 24, 33, 11),
        (2048, 64, 32, 128),   # k at the kpad cap
    ])
    def test_exact_vs_full_sort(self, n, d, nq, k):
        x, q = _rand((n, d), 1), _rand((nq, d), 2)
        dd, ii = fused_knn_xla(jnp.asarray(x), jnp.asarray(q), k)
        rd, _ = _np_knn(x, q, k)
        dd, ii = np.asarray(dd), np.asarray(ii)
        np.testing.assert_allclose(dd, rd, atol=1e-4)
        # id contract: every returned id really has the distance at
        # its rank (expanded-form rounding may swap near-ties, so ids
        # are checked through their distances, not positionally), and
        # no id repeats within a row
        for r in range(nq):
            assert len(set(ii[r].tolist())) == k
            np.testing.assert_allclose(
                ((q[r] - x[ii[r]]) ** 2).sum(-1), rd[r], atol=1e-4)

    def test_tie_break_at_k_boundary(self):
        # duplicate index rows straddle the k boundary: the running
        # merge must keep exactly k of the tied distance and never
        # emit a duplicate or out-of-range id
        base = _rand((8, 16), 3)
        x = np.concatenate([base] * 6, axis=0)        # 48 rows, 6-way ties
        q = base[:3] + 0.0
        k = 9                                         # ties cross k=9
        dd, ii = fused_knn_xla(jnp.asarray(x), jnp.asarray(q), k)
        dd, ii = np.asarray(dd), np.asarray(ii)
        rd, _ = _np_knn(x, q, k)
        np.testing.assert_allclose(dd, rd, atol=1e-5)
        for r in range(q.shape[0]):
            assert len(set(ii[r].tolist())) == k      # no id reuse
            assert ((ii[r] >= 0) & (ii[r] < x.shape[0])).all()
            # every returned id really has the reported distance
            np.testing.assert_allclose(
                ((q[r] - x[ii[r]]) ** 2).sum(-1), dd[r], atol=1e-5)

    def test_k_cap(self):
        x, q = _rand((512, 8), 4), _rand((4, 8), 5)
        with pytest.raises(LogicError):
            fused_knn_xla(jnp.asarray(x), jnp.asarray(q), 129)

    def test_dispatch_through_fused_l2_knn(self):
        # impl="xla_fused" must route the public entry point to the
        # twin and agree with the shipped tiled-scan pipeline
        x, q = _rand((600, 32), 6), _rand((24, 32), 7)
        df, jf = fused_l2_knn(jnp.asarray(x), jnp.asarray(q), 10,
                              impl="xla_fused")
        dr, jr = fused_l2_knn(jnp.asarray(x), jnp.asarray(q), 10,
                              impl="xla")
        np.testing.assert_allclose(np.asarray(df), np.asarray(dr),
                                   atol=1e-4)
        assert np.array_equal(np.asarray(jf), np.asarray(jr))


# --------------------------------------------------------------------- #
# block-shape knob legality (registry predicates; no kernel runs)
# --------------------------------------------------------------------- #
class TestBlockKnobLegality:
    def test_ladder_values_resolve(self):
        for v in ("256", "512", "1024", "2048", "4096"):
            got = tuning.resolve("knn_block_n", v, site="t",
                                 n=4096, k=16, d=32)
            assert got == v

    def test_off_ladder_rejected(self):
        with pytest.raises(LogicError):
            tuning.resolve("knn_block_n", "300", site="t",
                           n=4096, k=16, d=32)

    def test_lane_multiple_enforced(self):
        # 64 is sublane-legal for block_q but NOT lane-legal for block_n
        assert tuning.check("knn_block_q", "64", n=4096, k=16,
                            d=32) == "64"
        with pytest.raises(LogicError, match="multiple"):
            tuning.check("knn_block_n", "8", n=4096, k=16, d=32)

    def test_vmem_fit_rejects_wide_blocks_at_depth(self):
        # (block_n=4096, d=4096): the index tile alone is 64 MiB —
        # far past the 12 MiB kernel budget
        with pytest.raises(LogicError, match="VMEM"):
            tuning.check("knn_block_n", "4096", n=100_000, k=64,
                         d=4096)

    def test_twin_resolves_blocks_from_registry(self, monkeypatch):
        # the twin's call-site geometry comes from the knobs: pinning
        # knn_block_n via env must change the tile split without
        # changing results
        x, q = _rand((600, 16), 8), _rand((8, 16), 9)
        d0, i0 = fused_knn_xla(jnp.asarray(x), jnp.asarray(q), 4)
        monkeypatch.setenv("RAFT_TPU_KNN_BLOCK_N", "256")
        d1, i1 = fused_knn_xla(jnp.asarray(x), jnp.asarray(q), 4)
        np.testing.assert_array_equal(np.asarray(d0), np.asarray(d1))
        np.testing.assert_array_equal(np.asarray(i0), np.asarray(i1))


# --------------------------------------------------------------------- #
# interpreted kernel vs references — ONE small interpret execution per
# test (~15 s each); the matrix lives in the slow block below
# --------------------------------------------------------------------- #
class TestKernelParityTier1:
    def test_knn_kernel_bitwise_vs_fast_twin(self):
        # ragged n (tail mask), ragged nq (row padding), k off the
        # lane width — distances must match the twin BITWISE; ids agree
        # on distinct distances (random floats: ties improbable)
        x, q = _rand((700, 24), 10), _rand((33, 24), 11)
        k = 11
        dk, ik = fused_knn_tile(jnp.asarray(x), jnp.asarray(q), k,
                                interpret=True)
        dx, ix = fused_knn_xla(jnp.asarray(x), jnp.asarray(q), k)
        np.testing.assert_array_equal(np.asarray(dk), np.asarray(dx))
        assert np.array_equal(np.asarray(ik), np.asarray(ix))

    def test_ivf_kernel_bitwise_vs_oracle(self):
        # vacancies + short (-1-padded) scan lists in one shot
        S, cap, d, k, nq, n_steps = 6, 24, 10, 5, 7, 4
        sv, sn, si = _slot_store(S, cap, d, 12, vacancy_rows=3)
        q = _rand((nq, d), 13)
        rng = np.random.RandomState(14)
        slots = np.stack([rng.permutation(S)[:n_steps]
                          for _ in range(nq)]).astype(np.int32)
        slots[0, 2:] = -1                             # short scan list
        args = (jnp.asarray(q), jnp.asarray(sv), jnp.asarray(sn),
                jnp.asarray(si), jnp.asarray(slots), k)
        dk, ik = fused_ivf_scan(*args, interpret=True)
        dx, ix = fused_ivf_scan_xla(*args)
        np.testing.assert_array_equal(np.asarray(dk), np.asarray(dx))
        np.testing.assert_array_equal(np.asarray(ik), np.asarray(ix))


# --------------------------------------------------------------------- #
# the full parity matrix: rung x k x dtype (slow; ~15 s per case)
# --------------------------------------------------------------------- #
@pytest.mark.slow
class TestKernelParityMatrix:
    @pytest.mark.parametrize("n,k", [(300, 1), (700, 11), (1500, 100)])
    def test_knn_oracle_bitwise(self, n, k):
        x, q = _rand((n, 24), 20), _rand((17, 24), 21)
        dk, ik = fused_knn_tile(jnp.asarray(x), jnp.asarray(q), k,
                                interpret=True)
        do, io = fused_knn_xla_oracle(jnp.asarray(x), jnp.asarray(q), k)
        np.testing.assert_array_equal(np.asarray(dk), np.asarray(do))
        np.testing.assert_array_equal(np.asarray(ik), np.asarray(io))

    def test_knn_kernel_tie_break_at_k_boundary(self):
        # duplicate rows 6-way at k=9: the kernel's running bitonic
        # merge and the twin must agree on the tied distance multiset
        base = _rand((8, 16), 22)
        x = np.concatenate([base] * 6, axis=0)
        q = base[:3] + 0.0
        k = 9
        dk, ik = fused_knn_tile(jnp.asarray(x), jnp.asarray(q), k,
                                interpret=True)
        dk, ik = np.asarray(dk), np.asarray(ik)
        rd, _ = _np_knn(x, q, k)
        np.testing.assert_allclose(dk, rd, atol=1e-5)
        for r in range(q.shape[0]):
            assert len(set(ik[r].tolist())) == k
            np.testing.assert_allclose(
                ((q[r] - x[ik[r]]) ** 2).sum(-1), dk[r], atol=1e-5)

    @pytest.mark.parametrize("accum_bf16", [False, True])
    def test_ivf_oracle_bitwise_by_dtype(self, accum_bf16):
        S, cap, d, k, nq, n_steps = 8, 40, 18, 13, 9, 5
        sv, sn, si = _slot_store(S, cap, d, 23, vacancy_rows=2)
        q = _rand((nq, d), 24)
        rng = np.random.RandomState(25)
        slots = np.stack([rng.permutation(S)[:n_steps]
                          for _ in range(nq)]).astype(np.int32)
        args = (jnp.asarray(q), jnp.asarray(sv), jnp.asarray(sn),
                jnp.asarray(si), jnp.asarray(slots), k)
        dk, ik = fused_ivf_scan(*args, accum_bf16=accum_bf16,
                                interpret=True)
        dx, ix = fused_ivf_scan_xla(*args, accum_bf16=accum_bf16)
        # kernel vs oracle is bitwise in BOTH dtypes (same op order);
        # bf16 accuracy vs f32 truth is a separate, tolerance question
        np.testing.assert_array_equal(np.asarray(dk), np.asarray(dx))
        np.testing.assert_array_equal(np.asarray(ik), np.asarray(ix))
        if accum_bf16:
            df, _ = fused_ivf_scan_xla(*args)  # f32 truth
            np.testing.assert_allclose(np.asarray(dk), np.asarray(df),
                                       atol=5e-2)
