"""Guard: the simulated multi-device environment is actually in effect.

All multi-device tests assume an 8-device CPU mesh (see conftest.py); if the
platform override silently fails (e.g. an environment pre-imports jax with a
different backend), every mesh test would "pass" single-device.  Fail loudly
here instead.
"""

import os

import jax


def test_virtual_device_mesh_active():
    expected = os.environ.get("RAFT_TPU_TEST_PLATFORM", "cpu")
    assert jax.devices()[0].platform == expected
    if expected == "cpu":
        assert len(jax.devices()) == 8, jax.devices()
