"""Multi-process comms bootstrap: the DCN-role test.

Reference: python/raft/test/test_comms.py runs the comms self-tests on a
live multi-worker cluster bootstrapped by out-of-band NCCL-uid exchange
(ucp_helper.hpp:92 provides the cross-host p2p transport).  Here two real
OS processes bootstrap through ``jax.distributed`` (coordination service
= the uid-exchange analog, session.py Comms(coordinator_address=...)) and
run every comms selftest over the spanning mesh.
"""

import socket
import subprocess
import sys
from pathlib import Path

WORKER = Path(__file__).parent / "helpers" / "mp_comms_worker.py"


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("localhost", 0))
        return s.getsockname()[1]


def test_two_process_bootstrap_and_selftests():
    port = _free_port()
    procs = [
        subprocess.Popen([sys.executable, str(WORKER), str(i), "2", str(port)],
                         stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
                         text=True)
        for i in range(2)
    ]
    outs = []
    for p in procs:
        try:
            out, _ = p.communicate(timeout=240)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            raise
        outs.append(out)
    for i, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"worker {i} failed:\n{out[-3000:]}"
        assert f"WORKER_RESULT {i} failures={{}}" in out, out[-3000:]
