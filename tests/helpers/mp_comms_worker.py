"""Worker for the multi-process comms bootstrap test.

Usage: python mp_comms_worker.py <process_id> <num_processes> <port>

Each process exposes 2 virtual CPU devices; the global mesh spans
2 * num_processes devices across the jax.distributed cluster — the
reference's LocalCUDACluster-driven comms test topology
(python/raft/test/conftest.py:17-48) without hardware.
"""

import os
import sys

pid, nprocs, port = int(sys.argv[1]), int(sys.argv[2]), sys.argv[3]
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

from raft_tpu.comms import selftest  # noqa: E402
from raft_tpu.session import Comms, get_raft_comm_state, local_handle  # noqa: E402

sess = Comms(coordinator_address=f"localhost:{port}", num_processes=nprocs,
             process_id=pid).init()
assert jax.process_count() == nprocs
assert jax.device_count() == 2 * nprocs

# the reference drives every comms/test.hpp function from pytest on a live
# cluster (test_comms.py); same here, across real processes
failures = {}
for name in sorted(dir(selftest)):
    if name.startswith("test_"):
        try:
            ok = getattr(selftest, name)(sess.comms)
        except Exception as e:  # noqa: BLE001
            ok = f"{type(e).__name__}: {e}"
        if ok is not True:
            failures[name] = ok

# session-registry API parity checks (comms.py:247,266)
assert local_handle(sess.sessionId) is sess.handle
assert get_raft_comm_state(sess.sessionId)["nworkers"] == 2 * nprocs


def _mnmg_knn_cross_process():
    """Run the flagship MNMG algorithm across the real process boundary
    (reference: the Dask-driven MNMG kNN of python/raft — here the global
    mesh spans both OS processes, so the all_gather merge rides the
    jax.distributed cluster) and check it against a host numpy reference.
    """
    import numpy as np
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    from raft_tpu.comms.host_comms import default_mesh
    from raft_tpu.spatial import mnmg_knn

    rng = np.random.default_rng(7)  # identical data on every process
    n, d, nq, k = 103, 16, 8, 10
    index = rng.standard_normal((n, d)).astype(np.float32)
    queries = rng.standard_normal((nq, d)).astype(np.float32)
    mesh = default_mesh()
    assert mesh.devices.size == 2 * nprocs, mesh
    # replicated global placement; mnmg_knn row-shards over the axis
    repl = NamedSharding(mesh, P(None, None))
    ix = jax.device_put(jnp.asarray(index), repl)
    q = jax.device_put(jnp.asarray(queries), repl)
    sq = ((queries[:, None, :] - index[None, :, :]) ** 2).sum(-1)
    order = np.argsort(sq, axis=1, kind="stable")[:, :k]
    d_ref = np.take_along_axis(sq, order, axis=1)
    # both merge modes must cross the process boundary: allgather is the
    # default collective; ring sends ppermute hops over the same wire
    for merge in ("allgather", "ring"):
        d_got, i_got = mnmg_knn(ix, q, k, mesh=mesh,
                                axis=mesh.axis_names[0], merge=merge)
        d_got, i_got = np.asarray(d_got), np.asarray(i_got)
        np.testing.assert_allclose(d_got, d_ref, rtol=1e-4, atol=1e-4,
                                   err_msg=merge)
        # ids must agree except where the k-th boundary distance ties
        mism = i_got != order
        assert np.allclose(d_got[mism], d_ref[mism],
                           rtol=1e-4, atol=1e-4), (merge, i_got, order)
    return True


try:
    ok = _mnmg_knn_cross_process()
except Exception as e:  # noqa: BLE001
    ok = f"{type(e).__name__}: {e}"
if ok is not True:
    failures["mnmg_knn_cross_process"] = ok

print(f"WORKER_RESULT {pid} failures={failures}", flush=True)
sess.destroy()
sys.exit(0 if not failures else 1)
