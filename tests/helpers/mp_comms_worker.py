"""Worker for the multi-process comms bootstrap test.

Usage: python mp_comms_worker.py <process_id> <num_processes> <port>

Each process exposes 2 virtual CPU devices; the global mesh spans
2 * num_processes devices across the jax.distributed cluster — the
reference's LocalCUDACluster-driven comms test topology
(python/raft/test/conftest.py:17-48) without hardware.
"""

import os
import sys

pid, nprocs, port = int(sys.argv[1]), int(sys.argv[2]), sys.argv[3]
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

from raft_tpu.comms import selftest  # noqa: E402
from raft_tpu.session import Comms, get_raft_comm_state, local_handle  # noqa: E402

sess = Comms(coordinator_address=f"localhost:{port}", num_processes=nprocs,
             process_id=pid).init()
assert jax.process_count() == nprocs
assert jax.device_count() == 2 * nprocs

# the reference drives every comms/test.hpp function from pytest on a live
# cluster (test_comms.py); same here, across real processes
failures = {}
for name in sorted(dir(selftest)):
    if name.startswith("test_"):
        try:
            ok = getattr(selftest, name)(sess.comms)
        except Exception as e:  # noqa: BLE001
            ok = f"{type(e).__name__}: {e}"
        if ok is not True:
            failures[name] = ok

# session-registry API parity checks (comms.py:247,266)
assert local_handle(sess.sessionId) is sess.handle
assert get_raft_comm_state(sess.sessionId)["nworkers"] == 2 * nprocs

print(f"WORKER_RESULT {pid} failures={failures}", flush=True)
sess.destroy()
sys.exit(0 if not failures else 1)
