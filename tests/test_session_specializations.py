"""Session lifecycle + AOT specialization tests.

Mirrors python/raft/test/test_comms.py's session bring-up pattern and the
role of the reference's precompiled specializations.
"""

import numpy as np
import pytest

from raft_tpu.comms import Op
from raft_tpu.core.specializations import (
    aot_compile,
    default_specializations,
    enable_persistent_cache,
    warmup,
)
from raft_tpu.session import Comms, get_raft_comm_state, local_handle


class TestSession:
    def test_lifecycle(self):
        c = Comms().init()
        assert c.initialized
        st = get_raft_comm_state(c.sessionId)
        assert st["nworkers"] == 8
        h = local_handle(c.sessionId)
        assert h.comms_initialized()
        c.destroy()
        assert get_raft_comm_state(c.sessionId) == {}

    def test_context_manager(self):
        with Comms() as c:
            assert c.initialized
            # run a collective through the session's injected comms
            comms = local_handle(c.sessionId).get_comms()
            x = np.arange(8, dtype=np.float32).reshape(8, 1)
            out = np.asarray(comms.allreduce(x, Op.SUM))
            np.testing.assert_allclose(out, np.full((8, 1), x.sum()))
        assert not c.initialized

    def test_local_handle_missing(self):
        with pytest.raises(Exception):
            local_handle("nope")

    def test_worker_info(self):
        """Reference Comms.worker_info (comms.py:154): rank/placement
        map per worker; here per mesh device."""
        with Comms() as c:
            info = c.worker_info()
            assert len(info) == 8
            assert sorted(v["rank"] for v in info.values()) == list(range(8))
            some_id = next(iter(info))
            only = c.worker_info(workers=[some_id])
            assert list(only) == [some_id]
            assert all("process_index" in v and "device_kind" in v
                       for v in info.values())

    def test_worker_info_2d_mesh_ranks_in_comms_space(self):
        """On a 2-D mesh the rank must be the device's coordinate along
        the COMMS axis (HostComms rank space), not flat enumeration."""
        import jax
        from jax.sharding import Mesh

        devs = np.array(jax.devices()[:8]).reshape(2, 4)
        with Comms(mesh=Mesh(devs, ("ranks", "aux"))) as c:
            info = c.worker_info()
            ranks = sorted(v["rank"] for v in info.values())
            assert ranks == [0] * 4 + [1] * 4          # comm size 2
            assert all(v["mesh_coords"]["ranks"] == v["rank"]
                       for v in info.values())


class TestSpecializations:
    def test_cache_dir(self, tmp_path):
        d = enable_persistent_cache(str(tmp_path / "cache"))
        assert (tmp_path / "cache").exists()
        assert enable_persistent_cache(d) == d  # idempotent

    def test_aot_compile_runs(self):
        import jax.numpy as jnp

        compiled = aot_compile(lambda a, b: a @ b,
                               jnp.zeros((8, 4)), jnp.zeros((4, 2)))
        out = compiled(jnp.ones((8, 4)), jnp.ones((4, 2)))
        np.testing.assert_allclose(np.asarray(out), 4.0)

    def test_warmup_registry(self, tmp_path):
        specs = default_specializations()
        assert "pairwise_l2sqrt_1k_64" in specs
        # compile one small spec end-to-end into a fresh cache
        out = warmup(["pairwise_l2sqrt_1k_64"],
                     cache_dir=str(tmp_path / "c2"))
        import jax
        import jax.numpy as jnp

        fn = out["pairwise_l2sqrt_1k_64"]
        rng = np.random.default_rng(0)
        x = jnp.asarray(rng.random((1024, 64)), jnp.float32)
        y = jnp.asarray(rng.random((1024, 64)), jnp.float32)
        d = np.asarray(fn(x, y))
        assert d.shape == (1024, 1024)
        assert np.isfinite(d).all()
