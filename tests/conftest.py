"""Test configuration: run everything on a simulated 8-device CPU mesh.

The reference tests multi-GPU comms on a real LocalCUDACluster
(python/raft/test/conftest.py:17-48); we instead force the JAX host
platform to expose 8 virtual CPU devices, which lets every multi-device
code path (mesh sharding, collectives, comm_split) run hardware-free.
"""

import os

# The environment may pre-set JAX_PLATFORMS to a real accelerator and even
# import jax at interpreter startup (sitecustomize), so an env-var-only
# override is too late.  Backend *initialization* is lazy, though: setting
# XLA_FLAGS now and switching platforms via jax.config still works as long
# as no backend has been touched yet.  RAFT_TPU_TEST_PLATFORM overrides the
# CPU default for running tests on real hardware.
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()
_platform = os.environ.get("RAFT_TPU_TEST_PLATFORM", "cpu")
os.environ["JAX_PLATFORMS"] = _platform

import jax  # noqa: E402

jax.config.update("jax_platforms", _platform)
jax.config.update("jax_enable_x64", True)

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture
def handle():
    from raft_tpu import Handle

    return Handle(n_streams=4)


@pytest.fixture
def rng():
    return np.random.default_rng(1234)
