"""Pairwise distance tests vs naive O(mnk) numpy references.

Mirrors the reference's strategy: every metric checked against a naive
reference kernel over parameterized sizes/seeds
(cpp/test/distance/distance_base.cuh:30-110, dist_*.cu).
"""

import jax.numpy as jnp
import numpy as np
import pytest

from raft_tpu import RaftError
from raft_tpu.distance import DistanceType as D
from raft_tpu.distance import fused_l2_nn, pairwise_distance, get_workspace_size

# last case has k > 128 to exercise the tiled kernel's multi-k-tile
# accumulation path (bk=128 chunks)
SIZES = [(40, 32, 17), (65, 33, 8), (128, 128, 64), (33, 40, 300)]


def naive(x, y, metric, p=2.0):
    m, n = x.shape[0], y.shape[0]
    out = np.zeros((m, n))
    for i in range(m):
        for j in range(n):
            a, b = x[i], y[j]
            if metric == D.L2Expanded or metric == D.L2Unexpanded:
                out[i, j] = ((a - b) ** 2).sum()
            elif metric == D.L2SqrtExpanded or metric == D.L2SqrtUnexpanded:
                out[i, j] = np.sqrt(((a - b) ** 2).sum())
            elif metric == D.CosineExpanded:
                out[i, j] = 1 - (a * b).sum() / (np.linalg.norm(a) * np.linalg.norm(b))
            elif metric == D.CorrelationExpanded:
                out[i, j] = 1 - np.corrcoef(a, b)[0, 1]
            elif metric == D.InnerProduct:
                out[i, j] = (a * b).sum()
            elif metric == D.L1:
                out[i, j] = np.abs(a - b).sum()
            elif metric == D.Linf:
                out[i, j] = np.abs(a - b).max()
            elif metric == D.Canberra:
                s = np.abs(a) + np.abs(b)
                d = np.abs(a - b)
                out[i, j] = np.where(s == 0, 0.0, d / np.where(s == 0, 1, s)).sum()
            elif metric == D.LpUnexpanded:
                out[i, j] = (np.abs(a - b) ** p).sum() ** (1 / p)
            elif metric == D.HellingerExpanded:
                acc = (np.sqrt(a) * np.sqrt(b)).sum()
                out[i, j] = np.sqrt(max(0.0, 1 - acc))
            elif metric == D.RusselRaoExpanded:
                k = len(a)
                out[i, j] = (k - (a * b).sum()) / k
            elif metric == D.KLDivergence:
                t = np.where(a > 0, a * (np.log(np.where(a > 0, a, 1))
                                         - np.where(b > 0, np.log(np.where(b > 0, b, 1)), 0)), 0)
                out[i, j] = 0.5 * t.sum()
            elif metric == D.HammingUnexpanded:
                out[i, j] = (a != b).mean()
            elif metric == D.JensenShannon:
                mm = 0.5 * (a + b)
                def kl(u, v):
                    return np.where(u > 0, u * (np.log(np.where(u > 0, u, 1))
                                                - np.log(np.where(v > 0, v, 1))), 0).sum()
                out[i, j] = np.sqrt(0.5 * (kl(a, mm) + kl(b, mm)))
            elif metric == D.BrayCurtis:
                den = (a + b).sum()
                out[i, j] = np.abs(a - b).sum() / den if den != 0 else 0.0
            else:
                raise ValueError(metric)
    return out


GENERAL_METRICS = [
    D.L2Expanded, D.L2SqrtExpanded, D.CosineExpanded, D.CorrelationExpanded,
    D.InnerProduct, D.L1, D.L2Unexpanded, D.L2SqrtUnexpanded, D.Linf,
    D.Canberra, D.LpUnexpanded, D.HammingUnexpanded, D.BrayCurtis,
]
# probability-simplex metrics (inputs must be distributions)
PROB_METRICS = [D.HellingerExpanded, D.KLDivergence, D.JensenShannon, D.RusselRaoExpanded]


@pytest.mark.parametrize("m,n,k", SIZES)
@pytest.mark.parametrize("metric", GENERAL_METRICS)
def test_pairwise_general(rng, m, n, k, metric):
    x = rng.standard_normal((m, k)).astype(np.float32)
    y = rng.standard_normal((n, k)).astype(np.float32)
    got = np.asarray(pairwise_distance(jnp.array(x), jnp.array(y), metric))
    want = naive(x.astype(np.float64), y.astype(np.float64), metric)
    atol = 2e-3 if metric in (D.L2Expanded, D.L2SqrtExpanded) else 1e-4
    np.testing.assert_allclose(got, want, atol=atol, rtol=2e-3)


@pytest.mark.parametrize("m,n,k", [(30, 25, 16), (64, 64, 32)])
@pytest.mark.parametrize("metric", PROB_METRICS)
def test_pairwise_probability(rng, m, n, k, metric):
    x = rng.uniform(0.01, 1.0, (m, k))
    y = rng.uniform(0.01, 1.0, (n, k))
    x = (x / x.sum(1, keepdims=True)).astype(np.float32)
    y = (y / y.sum(1, keepdims=True)).astype(np.float32)
    got = np.asarray(pairwise_distance(jnp.array(x), jnp.array(y), metric))
    want = naive(x.astype(np.float64), y.astype(np.float64), metric)
    np.testing.assert_allclose(got, want, atol=1e-4, rtol=1e-3)


def test_kl_with_zeros(rng):
    # exercise the zero-guard branches (kl_divergence.cuh:95-99)
    x = rng.uniform(0, 1, (10, 8))
    y = rng.uniform(0, 1, (12, 8))
    x[x < 0.3] = 0.0
    y[y < 0.3] = 0.0
    got = np.asarray(pairwise_distance(jnp.array(x, dtype=jnp.float32),
                                       jnp.array(y, dtype=jnp.float32), D.KLDivergence))
    want = naive(x, y, D.KLDivergence)
    np.testing.assert_allclose(got, want, atol=1e-4)


def test_hamming_int_inputs(rng):
    x = rng.integers(0, 3, (20, 16)).astype(np.float32)
    y = rng.integers(0, 3, (15, 16)).astype(np.float32)
    got = np.asarray(pairwise_distance(jnp.array(x), jnp.array(y), D.HammingUnexpanded))
    np.testing.assert_allclose(got, naive(x, y, D.HammingUnexpanded), atol=1e-6)


def test_minkowski_p3(rng):
    x = rng.standard_normal((12, 9)).astype(np.float32)
    y = rng.standard_normal((11, 9)).astype(np.float32)
    got = np.asarray(pairwise_distance(jnp.array(x), jnp.array(y), D.LpUnexpanded, metric_arg=3.0))
    np.testing.assert_allclose(got, naive(x.astype(np.float64), y.astype(np.float64),
                                          D.LpUnexpanded, p=3.0), rtol=1e-3, atol=1e-4)


def test_fin_op(rng):
    x = rng.standard_normal((5, 4)).astype(np.float32)
    got = np.asarray(pairwise_distance(jnp.array(x), jnp.array(x), D.L2Expanded,
                                       fin_op=lambda d: d + 1.0))
    np.testing.assert_allclose(np.diag(got), 1.0, atol=1e-5)


def test_fin_op_adjacency(rng):
    """Epsilon-neighborhood adjacency via fin_op (reference dist_adj.cu:
    the distance kernel's FinalLambda thresholds into a bool matrix)."""
    x = rng.standard_normal((20, 6)).astype(np.float32)
    y = rng.standard_normal((15, 6)).astype(np.float32)
    eps = 6.0
    adj = np.asarray(pairwise_distance(
        jnp.array(x), jnp.array(y), D.L2Expanded,
        fin_op=lambda d: d <= eps))
    ref = naive(x.astype(np.float64), y.astype(np.float64),
                D.L2Expanded) <= eps
    assert adj.dtype == np.bool_
    np.testing.assert_array_equal(adj, ref)


def test_unsupported_metric(rng):
    x = jnp.zeros((4, 4))
    with pytest.raises(RaftError, match="Unknown or unsupported"):
        pairwise_distance(x, x, D.Haversine)
    with pytest.raises(RaftError):
        pairwise_distance(x, x, D.Precomputed)
    with pytest.raises(RaftError):
        pairwise_distance(x, jnp.zeros((4, 5)), D.L1)


def test_workspace_size():
    x, y = jnp.zeros((10, 4), jnp.float32), jnp.zeros((20, 4), jnp.float32)
    assert get_workspace_size(x, y, D.L2Expanded) == 30 * 4
    assert get_workspace_size(x, y, D.CorrelationExpanded) == 60 * 4
    assert get_workspace_size(x, y, D.L1) == 0


class TestFusedL2NN:
    @pytest.mark.parametrize("m,n,k", [(50, 37, 8), (200, 513, 16)])
    @pytest.mark.parametrize("sqrt", [False, True])
    def test_matches_naive(self, rng, m, n, k, sqrt):
        x = rng.standard_normal((m, k)).astype(np.float32)
        y = rng.standard_normal((n, k)).astype(np.float32)
        vals, idx = fused_l2_nn(jnp.array(x), jnp.array(y), sqrt=sqrt, tile_n=64)
        d = ((x[:, None, :] - y[None, :, :]) ** 2).sum(-1)
        ref_idx = d.argmin(axis=1)
        ref_val = d.min(axis=1)
        if sqrt:
            ref_val = np.sqrt(ref_val)
        np.testing.assert_array_equal(np.asarray(idx), ref_idx)
        np.testing.assert_allclose(np.asarray(vals), ref_val, atol=1e-3)

    def test_mask_excludes(self, rng):
        x = rng.standard_normal((10, 4)).astype(np.float32)
        # nearest neighbor of each point in itself-set is itself; mask the
        # diagonal to get second-nearest
        mask = ~np.eye(10, dtype=bool)
        vals, idx = fused_l2_nn(jnp.array(x), jnp.array(x), mask=jnp.array(mask), tile_n=4)
        assert np.all(np.asarray(idx) != np.arange(10))
        d = ((x[:, None, :] - x[None, :, :]) ** 2).sum(-1)
        np.fill_diagonal(d, np.inf)
        np.testing.assert_array_equal(np.asarray(idx), d.argmin(axis=1))

    def test_tie_breaks_to_smaller_index(self):
        x = jnp.zeros((3, 2))
        y = jnp.zeros((5, 2))  # all distances equal (0)
        _, idx = fused_l2_nn(x, y, tile_n=2)
        np.testing.assert_array_equal(np.asarray(idx), 0)


class TestReviewRegressions:
    def test_integer_inputs_not_truncated(self, rng):
        # Hamming on int-coded categories must return fractional means
        x = jnp.array(rng.integers(0, 3, (6, 8)), dtype=jnp.int32)
        out = np.asarray(pairwise_distance(x, x, D.HammingUnexpanded))
        assert out.dtype == np.float32
        assert np.any((out > 0) & (out < 1))
        np.testing.assert_allclose(np.diag(out), 0.0)

    def test_fully_masked_row_keeps_sentinel(self, rng):
        from raft_tpu.distance.fused_l2_nn import IDX_SENTINEL

        x = jnp.array(rng.standard_normal((4, 3)), dtype=jnp.float32)
        mask = np.ones((4, 4), dtype=bool)
        mask[2, :] = False  # row 2 has no admissible pair
        vals, idx = fused_l2_nn(x, x, mask=jnp.array(mask), tile_n=2)
        assert np.isinf(np.asarray(vals)[2])
        assert np.asarray(idx)[2] == IDX_SENTINEL
        assert np.all(np.asarray(idx)[[0, 1, 3]] != IDX_SENTINEL)

    def test_mask_with_custom_reduce_op(self, rng):
        from raft_tpu.distance import fused_l2_nn_min_reduce

        x = jnp.array(rng.standard_normal((6, 3)), dtype=jnp.float32)

        def max_reduce(best, cand):  # deliberately invert: keep the farthest
            bv, bi = best
            cv, ci = cand
            take = jnp.isfinite(cv) & ((cv > bv) | ~jnp.isfinite(bv))
            return jnp.where(take, cv, bv), jnp.where(take, ci, bi)

        mask = jnp.array(~np.eye(6, dtype=bool))
        init = (jnp.full((6,), -np.inf, jnp.float32), jnp.zeros((6,), jnp.int32))
        vals, idx = fused_l2_nn_min_reduce(x, x, reduce_op=max_reduce,
                                           init_val=init, mask=mask, tile_n=2)
        d = ((np.asarray(x)[:, None, :] - np.asarray(x)[None, :, :]) ** 2).sum(-1)
        np.fill_diagonal(d, -np.inf)
        # per-tile argmin feeding a max-reduce doesn't give the global max,
        # but every reported pair must be admissible and finite
        assert np.all(np.asarray(idx) != np.arange(6))
        assert np.all(np.isfinite(np.asarray(vals)))

    def test_block_k_honored(self, rng):
        from raft_tpu.ops import pairwise_tile

        x = rng.standard_normal((10, 300)).astype(np.float32)
        out = pairwise_tile(jnp.array(x), jnp.array(x),
                            lambda a, b: jnp.abs(a - b), block_k=256)
        ref = np.abs(x[:, None, :] - x[None, :, :]).sum(-1)
        np.testing.assert_allclose(np.asarray(out), ref, rtol=1e-4, atol=1e-4)


class TestFusedNnTile:
    """The Pallas fused 1-NN kernel (ops/nn_tile.py) vs the XLA scan —
    the fused_l2_nn.cuh:134 analog, interpret-mode on CPU."""

    def _check(self, rng, m, n, d, block_n=1024):
        from raft_tpu.ops.nn_tile import fused_nn_tile

        x = jnp.asarray(rng.standard_normal((m, d)).astype(np.float32))
        y = jnp.asarray(rng.standard_normal((n, d)).astype(np.float32))
        v_p, i_p = fused_nn_tile(x, y, block_n=block_n)
        v_r, i_r = fused_l2_nn(x, y, impl="xla")
        np.testing.assert_allclose(np.asarray(v_p), np.asarray(v_r),
                                   rtol=1e-5, atol=1e-4)
        np.testing.assert_array_equal(np.asarray(i_p), np.asarray(i_r))

    def test_aligned(self, rng):
        self._check(rng, 64, 512, 32)

    def test_ragged(self, rng):
        self._check(rng, 57, 1000, 17, block_n=256)

    def test_wide_d(self, rng):
        self._check(rng, 32, 300, 200)

    def test_multi_tile(self, rng):
        self._check(rng, 40, 5000, 8, block_n=512)

    def test_tie_breaks_to_smaller_index(self):
        from raft_tpu.ops.nn_tile import fused_nn_tile

        # duplicate rows of y: the nearest is at distance 0 twice; the
        # kernel must report the smaller id like the XLA reduce
        y = jnp.asarray(np.array([[1.0, 0.0], [3.0, 0.0], [1.0, 0.0],
                                  [5.0, 1.0]], np.float32))
        x = y[:1]
        v, i = fused_nn_tile(x, y)
        assert float(v[0]) == 0.0 and int(i[0]) == 0

    def test_dispatch_sqrt(self, rng):
        x = jnp.asarray(rng.standard_normal((20, 8)).astype(np.float32))
        y = jnp.asarray(rng.standard_normal((50, 8)).astype(np.float32))
        v_p, i_p = fused_l2_nn(x, y, sqrt=True, impl="pallas")
        v_r, i_r = fused_l2_nn(x, y, sqrt=True, impl="xla")
        np.testing.assert_allclose(np.asarray(v_p), np.asarray(v_r),
                                   rtol=1e-5, atol=1e-4)
        np.testing.assert_array_equal(np.asarray(i_p), np.asarray(i_r))
