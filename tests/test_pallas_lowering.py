"""Compiled-path (interpret=False) Pallas kernel lowering for TPU.

Every unit test runs the kernels under the Pallas interpreter (CPU), but
the interpreter accepts constructs Mosaic rejects — round 3 found
exactly that: ``pltpu.roll`` rejects the negative lane shifts
``jnp.roll`` accepts, so the compiled kernel failed TPU lowering while
all interpret-mode tests passed.  ``jax.export`` with
``platforms=["tpu"]`` runs the full Pallas→Mosaic kernel lowering
WITHOUT TPU hardware, so this guards the compiled path hardware-free;
actual on-chip execution + timing is bench.py's pallas_check rung.

Reference contrast: the CUDA kernels are themselves the tested artifact
(detail/fused_l2_knn.cuh:196); this is the TPU build's equivalent
compile-level guard.
"""

import jax
import jax.numpy as jnp
import pytest


def _export_tpu(fn, *shapes):
    args = [jax.ShapeDtypeStruct(s, jnp.float32) for s in shapes]
    exp = jax.export.export(jax.jit(fn), platforms=["tpu"])(*args)
    blob = exp.mlir_module_serialized
    # the Pallas kernel must actually be in the module as a Mosaic
    # custom call — an accidental interpret/XLA fallback would "pass"
    # this test while shipping no kernel at all
    assert b"tpu_custom_call" in blob
    return blob


class TestFusedKnnTileLowersForTPU:
    @pytest.mark.parametrize("k", [8, 64, 100, 256])
    def test_k_sweep(self, k):
        from raft_tpu.ops.knn_tile import fused_knn_tile

        _export_tpu(
            lambda x, q: fused_knn_tile(x, q, k, block_n=1024,
                                        interpret=False),
            (8192, 128), (256, 128))

    def test_north_star_shape(self):
        """1M x 128 k=100 (BASELINE.md config #3), the bench headline."""
        from raft_tpu.ops.knn_tile import fused_knn_tile

        _export_tpu(
            lambda x, q: fused_knn_tile(x, q, 100, interpret=False),
            (1_000_000, 128), (1024, 128))

    @pytest.mark.parametrize("merge_impl", ["merge", "fullsort", "sorttile"])
    def test_merge_impls(self, merge_impl):
        """Every running-top-k merge network must lower for TPU."""
        from raft_tpu.ops.knn_tile import fused_knn_tile

        _export_tpu(
            lambda x, q: fused_knn_tile(x, q, 100, interpret=False,
                                        merge_impl=merge_impl),
            (8192, 128), (256, 128))

    def test_ragged_tail(self):
        """n not a multiple of the block: padding path must lower too."""
        from raft_tpu.ops.knn_tile import fused_knn_tile

        _export_tpu(
            lambda x, q: fused_knn_tile(x, q, 10, block_n=1024,
                                        interpret=False),
            (5000, 64), (96, 64))


class TestSelectTileLowersForTPU:
    @pytest.mark.parametrize("k", [8, 100, 128])
    def test_k_sweep(self, k):
        from raft_tpu.ops.select_tile import select_tile

        _export_tpu(
            lambda keys: select_tile(keys, k, interpret=False),
            (4096, 8192))

    def test_ragged_and_merge_impls(self):
        from raft_tpu.ops.select_tile import select_tile

        _export_tpu(
            lambda keys: select_tile(keys, 100, interpret=False,
                                     merge_impl="fullsort"),
            (1000, 5000))


class TestFusedNnTileLowersForTPU:
    def test_default_and_ragged(self):
        from raft_tpu.ops.nn_tile import fused_nn_tile

        _export_tpu(lambda x, y: fused_nn_tile(x, y, interpret=False),
                    (4096, 128), (100_000, 128))
        _export_tpu(lambda x, y: fused_nn_tile(x, y, block_n=256,
                                               interpret=False),
                    (57, 33), (1000, 33))


class TestPairwiseTileLowersForTPU:
    @pytest.mark.parametrize("reduce_kind", ["add", "max"])
    def test_unexpanded_tile(self, reduce_kind):
        from raft_tpu.ops.pairwise_tile import pairwise_tile

        def f(x, y):
            return pairwise_tile(
                x, y, lambda a, b: jnp.abs(a - b),
                reduce_kind=reduce_kind, interpret=False)

        _export_tpu(f, (1024, 128), (2048, 128))

    def test_epilog(self):
        from raft_tpu.ops.pairwise_tile import pairwise_tile

        def f(x, y):
            return pairwise_tile(x, y, lambda a, b: (a - b) ** 2,
                                 epilog=jnp.sqrt, interpret=False)

        _export_tpu(f, (512, 64), (512, 64))

    @pytest.mark.parametrize("metric_name", [
        "L1", "L2SqrtUnexpanded", "Linf", "Canberra", "LpUnexpanded",
        "HammingUnexpanded", "JensenShannon", "BrayCurtis",
    ])
    def test_every_unexpanded_metric_combine_lowers(self, metric_name):
        """Each metric's combine lambda is a different elementwise
        program inside the kernel (where-guards, pow, log, != casts) —
        any one of them can hit a Mosaic-unsupported op even when the
        L1/L2 combines lower fine.  Export the PUBLIC dispatch so the
        exact shipped kernel is what lowers."""
        from raft_tpu.distance import DistanceType, pairwise_distance

        metric = getattr(DistanceType, metric_name)

        def f(x, y):
            return pairwise_distance(x, y, metric, metric_arg=1.5,
                                     interpret=False)

        _export_tpu(f, (256, 96), (192, 96))


class TestXlaPathsExportForTPU:
    """The XLA-path entry points can hide TPU-hostile dtypes too (f64
    promotions under x64 have no TPU lowering); export them for the tpu
    platform hardware-free.  No Mosaic assert — these are plain XLA."""

    def _export(self, fn, *shapes, dtypes=None):
        dtypes = dtypes or [jnp.float32] * len(shapes)
        args = [jax.ShapeDtypeStruct(s, d) for s, d in zip(shapes, dtypes)]
        jax.export.export(jax.jit(fn), platforms=["tpu"])(*args)

    def test_brute_force_knn_xla(self):
        from raft_tpu.spatial import brute_force_knn

        self._export(
            lambda x, q: brute_force_knn([x], q, 100),
            (100_000, 128), (1024, 128))

    def test_fused_l2_nn(self):
        from raft_tpu.distance import fused_l2_nn

        self._export(lambda x, y: fused_l2_nn(x, y), (4096, 64), (4096, 64))

    def test_sortscan_spmv(self):
        """Gather-free SpMV (r5): variadic sort + tuple
        associative_scan must lower for TPU."""
        from raft_tpu.sparse.formats import CSR
        from raft_tpu.sparse.linalg import csr_spmv

        def f(indptr, indices, data, x):
            a = CSR(indptr, indices, data, shape=(512, 400))
            return csr_spmv(a, x, impl="sortscan")

        self._export(f, (513,), (4096,), (4096,), (400,),
                     dtypes=[jnp.int32, jnp.int32, jnp.float32,
                             jnp.float32])

    def test_tiled_knn_direct_merge(self):
        """The r4 'direct' merge mode (single (k+tile_n)-wide variadic
        sort per tile) must lower for tpu."""
        def fn(x, q):
            from raft_tpu.spatial.tiled_knn import tiled_knn

            qn = jnp.sum(q * q, axis=1)

            def tile_dist(qq, xt):
                xn = jnp.sum(xt * xt, axis=1)
                return (qn[:, None] + xn[None, :]
                        - 2.0 * qq @ xt.T)

            return tiled_knn(x, q, 100, tile_dist, merge="direct")

        self._export(fn, (100_000, 128), (1024, 128))

    def test_ivf_pq_adc_onehot(self):
        """The r4 one-hot ADC formulation must lower for tpu (the
        one_hot + einsum chain can promote under x64)."""
        from raft_tpu.spatial.ann import _ivf_pq_search_jit
        from raft_tpu.distance import DistanceType

        nlist, M, ksub, dsub, cap, n_slots, nq = 16, 8, 256, 4, 64, 32, 64
        d = M * dsub

        def fn(centroids, codebooks, q):
            slot_codes = jnp.zeros((n_slots, cap, M), jnp.int32)
            slot_ids = jnp.zeros((n_slots, cap), jnp.int32)
            slot_centroid = jnp.zeros((n_slots,), jnp.int32)
            cent_slots = jnp.zeros((nlist, 2), jnp.int32)
            return _ivf_pq_search_jit(
                centroids, codebooks, slot_codes, slot_ids,
                slot_centroid, cent_slots, q, 10, 4,
                DistanceType.L2Expanded, adc="onehot")

        self._export(fn, (nlist, d), (M, ksub, dsub), (nq, d))

    def test_select_k_approx(self):
        from raft_tpu.spatial.select_k import select_k

        self._export(lambda d: select_k(d, 100, impl="approx"),
                     (512, 8192))

    def test_sparse_coltiled_distance(self):
        """The column-tiled sparse engine (round-4 scalability fix) must
        export for tpu — its densify/segment-sum drivers are the most
        scatter-heavy programs in the package."""
        from raft_tpu.distance import DistanceType
        from raft_tpu.sparse.distance import pairwise_distance as spw
        from raft_tpu.sparse.formats import CSR

        def f(aip, ai, ad, bip, bi, bd):
            ca = CSR(aip, ai, ad, shape=(64, 4096))
            cb = CSR(bip, bi, bd, shape=(48, 4096))
            return spw(ca, cb, DistanceType.L2Expanded, batch_size_k=512)

        self._export(f, (65,), (640,), (640,), (49,), (480,), (480,),
                     dtypes=[jnp.int32, jnp.int32, jnp.float32,
                             jnp.int32, jnp.int32, jnp.float32])

    def test_mnmg_knn_single_axis(self):
        """The SPMD program (shard_map + all_gather + reselect) must
        export for tpu; uses a 1-device mesh (the program is the same
        module for any axis size)."""
        import numpy as np
        from jax.sharding import Mesh

        from raft_tpu.spatial.mnmg_knn import mnmg_knn

        mesh = Mesh(np.array(jax.devices()[:1]), ("ranks",))

        def f(x, q):
            return mnmg_knn(x, q, 10, mesh=mesh, axis="ranks")

        self._export(f, (1000, 32), (64, 32))


class TestTwophaseLowersForTPU:
    """No-carry two-phase kernel (r5): per-tile select, parallel grid."""

    @pytest.mark.parametrize("k", [8, 100])
    def test_k_sweep(self, k):
        from raft_tpu.ops.knn_tile import fused_knn_twophase

        _export_tpu(
            lambda x, q: fused_knn_twophase(x, q, k, block_n=1024,
                                            interpret=False),
            (8192, 128), (256, 128))

    def test_ragged_tail(self):
        from raft_tpu.ops.knn_tile import fused_knn_twophase

        _export_tpu(
            lambda x, q: fused_knn_twophase(x, q, 10, block_n=1024,
                                            interpret=False),
            (5000, 96), (100, 96))



