"""Sharded SPMD serving (docs/SERVING.md "Sharded serving").

One service over a mesh-sharded index on the virtual 8-device mesh:
KNNService(axis=...) / ANNService(axis=...) dispatch each padded bucket
batch into a pjit'd per-shard search + on-device top-k merge.  The
contract tested here:

- served results match the single-device primitive across all three
  merge topologies and both donation arms (ids exact on tie-free
  random data — the merge is documented tie-break-stable, not
  bit-order-stable, on exact distance ties);
- warmup precompiles every per-rung sharded executable, steady state
  performs zero compiles, and the data path stays device-resident
  (0 host-staged bytes);
- shard loss re-partitions the lost shard's rows/slots across the
  surviving sub-mesh exactly (session health_check flags the stale
  mesh first, RecoveryManager heals it).
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from raft_tpu.comms.host_comms import default_mesh
from raft_tpu.core.metrics import default_registry
from raft_tpu.core.profiler import compile_cache_stats
from raft_tpu.serve import ANNService, KNNService
from raft_tpu.spatial.ann import (IVFFlatParams, ivf_flat_build,
                                  ivf_flat_search)
from raft_tpu.spatial.knn import brute_force_knn

pytestmark = pytest.mark.serve

RUNGS = (8, 32)


def _misses():
    return sum(s["misses"] for fn in compile_cache_stats().values()
               for s in fn.values())


@pytest.fixture(scope="module")
def data():
    rng = np.random.default_rng(42)
    ref = jnp.asarray(rng.standard_normal((1200, 24)).astype(np.float32))
    queries = jnp.asarray(
        rng.standard_normal((12, 24)).astype(np.float32))
    return ref, queries


@pytest.fixture(scope="module")
def ivf(data):
    ref, _ = data
    return ivf_flat_build(ref, IVFFlatParams(nlist=24, nprobe=6))


# ---------------------------------------------------------------------- #
# KNNService(axis=...): served == single device, every topology x arm
# ---------------------------------------------------------------------- #
class TestShardedKNN:
    @pytest.mark.parametrize("merge", ["allgather", "ring",
                                       "hierarchical"])
    @pytest.mark.parametrize("donate", [True, False])
    def test_matches_single_device(self, data, merge, donate):
        ref, queries = data
        d_ref, i_ref = brute_force_knn(ref, queries, 7)
        svc = KNNService(ref, k=7, axis="ranks", merge=merge,
                         donate=donate, max_batch_rows=RUNGS[-1],
                         bucket_rungs=RUNGS)
        try:
            out = svc.submit(jnp.copy(queries)).result(timeout=60)
            np.testing.assert_array_equal(np.asarray(out[1]),
                                          np.asarray(i_ref))
            np.testing.assert_allclose(np.asarray(out[0]),
                                       np.asarray(d_ref),
                                       rtol=1e-4, atol=1e-4)
            st = svc.stats()
            assert st["sharded"] is True
            assert st["axis"] == "ranks"
            assert st["shard_devices"] == 8
            assert st["merge"] == merge
        finally:
            svc.close()

    def test_warmup_then_zero_steady_state_compiles(self, data):
        ref, queries = data
        svc = KNNService(ref, k=5, axis="ranks",
                         max_batch_rows=RUNGS[-1], bucket_rungs=RUNGS)
        try:
            svc.warmup()
            m0 = _misses()
            for _ in range(3):
                svc.submit(jnp.copy(queries)).result(timeout=60)
            assert _misses() - m0 == 0
            # the zero-copy proof: nothing staged through host numpy
            assert default_registry().family_total(
                "raft_tpu_comms_host_staged_bytes") == 0
        finally:
            svc.close()

    def test_explicit_submesh(self, data):
        """mesh= pins the shard span (here: 4 of the 8 devices)."""
        ref, queries = data
        mesh = default_mesh(4)
        _, i_ref = brute_force_knn(ref, queries, 5)
        svc = KNNService(ref, k=5, mesh=mesh, max_batch_rows=RUNGS[-1],
                         bucket_rungs=RUNGS)
        try:
            out = svc.submit(jnp.copy(queries)).result(timeout=60)
            np.testing.assert_array_equal(np.asarray(out[1]),
                                          np.asarray(i_ref))
            assert svc.stats()["shard_devices"] == 4
        finally:
            svc.close()

    def test_bad_axis_raises(self, data):
        from raft_tpu.core.error import RaftError

        ref, _ = data
        with pytest.raises(RaftError):
            KNNService(ref, k=3, mesh=default_mesh(), axis="nope",
                       start=False)

    def test_shard_devices_gauge(self, data):
        ref, _ = data
        svc = KNNService(ref, k=3, axis="ranks", start=False,
                         name="gauge-knn")
        try:
            fam = default_registry().get("raft_tpu_serve_shard_devices")
            vals = {labels.get("service"): series.value
                    for labels, series in fam.series()}
            assert vals["gauge-knn"] == 8
        finally:
            svc.close()


# ---------------------------------------------------------------------- #
# ANNService(axis=...): slot-sharded dispatch + ingestion + compaction
# ---------------------------------------------------------------------- #
class TestShardedANN:
    @pytest.mark.parametrize("merge", ["allgather", "hierarchical"])
    def test_matches_single_device(self, data, ivf, merge):
        ref, queries = data
        d_ref, i_ref = ivf_flat_search(ivf, queries, 6, nprobe=6)
        svc = ANNService(ivf, k=6, axis="ranks", merge=merge,
                         nprobe=6, nprobe_ladder=(6,),
                         max_batch_rows=RUNGS[-1], bucket_rungs=RUNGS)
        try:
            out = svc.submit(jnp.copy(queries)).result(timeout=60)
            np.testing.assert_array_equal(np.asarray(out[1]),
                                          np.asarray(i_ref))
            np.testing.assert_allclose(np.asarray(out[0]),
                                       np.asarray(d_ref),
                                       rtol=1e-4, atol=1e-4)
            assert svc.stats()["sharded"] is True
        finally:
            svc.close()

    def test_warmup_covers_sharded_cells(self, data, ivf):
        ref, queries = data
        svc = ANNService(ivf, k=4, axis="ranks", nprobe=6,
                         nprobe_ladder=(3, 6),
                         max_batch_rows=RUNGS[-1], bucket_rungs=RUNGS)
        try:
            svc.warmup()
            m0 = _misses()
            for cell in (3, 6):
                svc.set_nprobe(cell)
                svc.submit(jnp.copy(queries)).result(timeout=60)
            assert _misses() - m0 == 0
        finally:
            svc.close()

    def test_insert_visible_and_compaction_exact(self, data, ivf):
        """Streaming ingestion through the sharded path: inserted rows
        are queryable (delta merge), and compaction re-shards the
        swapped index — full-probe results stay exact vs brute force
        over base + inserted content."""
        ref, queries = data
        rng = np.random.default_rng(3)
        svc = ANNService(ivf, k=4, axis="ranks",
                         nprobe=24, nprobe_ladder=(24,),
                         compact_rows=0,   # manual compaction only
                         max_batch_rows=RUNGS[-1], bucket_rungs=RUNGS)
        try:
            new = rng.standard_normal((16, 24)).astype(np.float32)
            ids = np.arange(5000, 5016, dtype=np.int32)
            svc.insert(ids, new)
            assert svc.delta_rows == 16
            full = jnp.concatenate([ref, jnp.asarray(new)])
            _, i_ref = brute_force_knn(full, queries, 4)
            want = np.asarray(i_ref)
            want = np.where(want >= ref.shape[0],
                            want - ref.shape[0] + 5000, want)
            out = svc.submit(jnp.copy(queries)).result(timeout=60)
            np.testing.assert_array_equal(np.asarray(out[1]), want)
            # compact: delta folds into slots, sharded mirror re-cut
            assert svc.compact() is True
            assert svc.delta_rows == 0
            out2 = svc.submit(jnp.copy(queries)).result(timeout=60)
            np.testing.assert_array_equal(np.asarray(out2[1]), want)
        finally:
            svc.close()

    def test_sharded_requires_flat(self, data):
        from raft_tpu.core.error import RaftError
        from raft_tpu.spatial.ann import IVFSQParams, ivf_sq_build

        ref, _ = data
        sq = ivf_sq_build(ref, IVFSQParams(nlist=16, nprobe=4))
        with pytest.raises(RaftError):
            ANNService(sq, k=3, axis="ranks", start=False)


# ---------------------------------------------------------------------- #
# shard loss -> health flag -> re-partition -> exact results
# ---------------------------------------------------------------------- #
class TestShardLossRecovery:
    def test_health_flags_then_repartition_heals(self, data):
        from raft_tpu.serve.resilience import RecoveryManager
        from raft_tpu.session import Comms

        ref, queries = data
        _, i_ref = brute_force_knn(ref, queries, 6)
        s = Comms().init()
        try:
            svc = s.serve("knn", index=ref, k=6, axis="ranks",
                          merge="hierarchical",
                          max_batch_rows=RUNGS[-1], bucket_rungs=RUNGS)
            out = svc.submit(jnp.copy(queries)).result(timeout=60)
            np.testing.assert_array_equal(np.asarray(out[1]),
                                          np.asarray(i_ref))
            assert svc.stats()["shard_devices"] == 8
            # shard loss: the session rebuilds comms on 4 survivors;
            # the service still spans the old 8-device mesh
            survivors = [int(d.id)
                         for d in s.comms.mesh.devices.ravel()[:4]]
            s.recover(devices=survivors)
            report = s.health_check()
            assert report["services"][svc.name]["mesh_ok"] is False
            assert report["ok"] is False
            # orchestrated heal: post_recover re-partitions the full
            # index over the survivors, warmup rebuilds executables
            RecoveryManager(s).recover(recover_comms=False)
            assert svc.stats()["shard_devices"] == 4
            out = svc.submit(jnp.copy(queries)).result(timeout=60)
            np.testing.assert_array_equal(np.asarray(out[1]),
                                          np.asarray(i_ref))
            report = s.health_check()
            assert report["services"][svc.name]["mesh_ok"] is True
            assert report["ok"] is True
            # the repair is counted
            assert default_registry().family_total(
                "raft_tpu_serve_repartitions_total") >= 1
        finally:
            s.destroy()

    def test_ann_repartition_carries_delta(self, data, ivf):
        """ANN shard loss: slots re-cut over the survivors AND the
        delta segment (inserted rows) survives — full-probe exactness
        against base + inserted content on the shrunken mesh."""
        ref, queries = data
        rng = np.random.default_rng(5)
        svc = ANNService(ivf, k=4, axis="ranks", nprobe=24,
                         nprobe_ladder=(24,), compact_rows=0,
                         max_batch_rows=RUNGS[-1], bucket_rungs=RUNGS)
        try:
            new = rng.standard_normal((8, 24)).astype(np.float32)
            ids = np.arange(7000, 7008, dtype=np.int32)
            svc.insert(ids, new)
            assert svc.repartition(mesh=default_mesh(4)) is True
            assert svc.stats()["shard_devices"] == 4
            assert svc.delta_rows == 8
            full = jnp.concatenate([ref, jnp.asarray(new)])
            _, i_ref = brute_force_knn(full, queries, 4)
            want = np.asarray(i_ref)
            want = np.where(want >= ref.shape[0],
                            want - ref.shape[0] + 7000, want)
            out = svc.submit(jnp.copy(queries)).result(timeout=60)
            np.testing.assert_array_equal(np.asarray(out[1]), want)
        finally:
            svc.close()

    def test_repartition_drops_undivisible_group_size(self, data):
        """A constructor-pinned hierarchical group_size that does not
        divide the survivor mesh must not brick the service: the pin
        drops and the group re-resolves per mesh (regression — every
        post-recovery dispatch used to raise)."""
        ref, queries = data
        _, i_ref = brute_force_knn(ref, queries, 5)
        svc = KNNService(ref, k=5, mesh=default_mesh(4),
                         merge="hierarchical", group_size=2,
                         max_batch_rows=RUNGS[-1], bucket_rungs=RUNGS)
        try:
            out = svc.submit(jnp.copy(queries)).result(timeout=60)
            np.testing.assert_array_equal(np.asarray(out[1]),
                                          np.asarray(i_ref))
            # shard loss to a 3-device mesh: 2 does not divide 3
            assert svc.repartition(mesh=default_mesh(3)) is True
            svc.warmup()
            out = svc.submit(jnp.copy(queries)).result(timeout=60)
            np.testing.assert_array_equal(np.asarray(out[1]),
                                          np.asarray(i_ref))
            assert svc.stats()["shard_devices"] == 3
        finally:
            svc.close()

    def test_repartition_on_unsharded_raises(self, data):
        from raft_tpu.core.error import RaftError

        ref, _ = data
        svc = KNNService(ref, k=3, start=False)
        try:
            with pytest.raises(RaftError):
                svc.repartition()
        finally:
            svc.close()


# ---------------------------------------------------------------------- #
# loadgen integration (the --mesh lever) and chaos shard-kill
# ---------------------------------------------------------------------- #
class TestLoadgenMesh:
    def test_build_service_mesh_devices(self):
        from tools.loadgen import build_service, run_load

        svc = build_service("knn", 800, 16, 5, mesh_devices=2,
                            max_batch_rows=32, merge="ring")
        try:
            assert svc.stats()["shard_devices"] == 2
            rep = run_load(svc, mode="closed", duration=0.5,
                           concurrency=2, rows=4, recall=True)
            assert rep["recall_at_k"] == 1.0   # exact service
            assert rep["host_staged_bytes"] == 0
        finally:
            svc.close()

    def test_chaos_kill_shard_heals_exactly(self):
        from raft_tpu.serve.resilience import RecoveryManager
        from tools.loadgen import build_service, run_chaos

        svc = build_service("knn", 800, 16, 5, mesh_devices=4,
                            max_batch_rows=32)
        svc.warmup()
        manager = RecoveryManager(services=[svc])
        try:
            rep = run_chaos(svc, duration=2.0, concurrency=2, rows=4,
                            seed=11, transient_p=0.02, outage_s=0.4,
                            manager=manager, kill_shard=True)
        finally:
            svc.close()
        assert rep["chaos_ok"] is True
        assert rep["exactly_once"] is True
        assert rep["shard_devices"] == 3
        assert rep["post_recovery_exact"] is True


# ---------------------------------------------------------------------- #
# CI hygiene: the direct-jax.jit ban in mnmg_knn.py
# ---------------------------------------------------------------------- #
class TestMnmgJitBan:
    def _check(self, tmp_path, relpath, src, monkeypatch):
        import importlib.util
        import os

        spec = importlib.util.spec_from_file_location(
            "style_check_mnmg", os.path.join(
                os.path.dirname(__file__), "..", "ci",
                "style_check.py"))
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        monkeypatch.setattr(mod, "REPO", str(tmp_path))
        path = tmp_path / relpath
        path.parent.mkdir(parents=True, exist_ok=True)
        path.write_text(src)
        return mod.check_file(str(path))

    def test_direct_jit_flagged(self, tmp_path, monkeypatch):
        src = "import jax\nf = jax.jit(lambda x: x)\n"
        probs = self._check(tmp_path, "raft_tpu/spatial/mnmg_knn.py",
                            src, monkeypatch)
        assert any("jax.jit" in p for p in probs)
        probs = self._check(tmp_path, "raft_tpu/spatial/mnmg_knn.py",
                            "from jax import jit\n", monkeypatch)
        assert any("jax.jit" in p for p in probs)
        # the bare decorator form (an Attribute, not a Call) must be
        # caught too
        src = ("import jax\n"
               "@jax.jit\n"
               "def f(x):\n"
               "    return x\n")
        probs = self._check(tmp_path, "raft_tpu/spatial/mnmg_knn.py",
                            src, monkeypatch)
        assert any("jax.jit" in p for p in probs)

    def test_marker_and_other_files_pass(self, tmp_path, monkeypatch):
        src = ("import jax\n"
               "f = jax.jit(lambda x: x)  # mnmg-jit-ok: probe\n")
        assert self._check(tmp_path, "raft_tpu/spatial/mnmg_knn.py",
                           src, monkeypatch) == []
        src = "import jax\nf = jax.jit(lambda x: x)\n"
        assert self._check(tmp_path, "raft_tpu/spatial/other.py", src,
                           monkeypatch) == []

    def test_live_tree_clean(self):
        import os
        import subprocess
        import sys

        repo = os.path.dirname(os.path.dirname(
            os.path.abspath(__file__)))
        out = subprocess.run(
            [sys.executable, os.path.join(repo, "ci",
                                          "style_check.py")],
            capture_output=True, text=True)
        assert out.returncode == 0, out.stdout + out.stderr
