"""Label utilities, vector cache, and LAP solver tests.

Mirrors cpp/test/label/label.cu, cpp/test/label/merge_labels.cu,
cpp/test/cache/*.cu, cpp/test/lap/lap.cu (vs scipy ground truth).
"""

import jax.numpy as jnp
import numpy as np
import pytest
from scipy.optimize import linear_sum_assignment

from raft_tpu.cache import VecCache
from raft_tpu.label import (
    get_ovr_labels,
    get_unique_labels,
    make_monotonic,
    merge_labels,
)
from raft_tpu.lap import LinearAssignmentProblem, solve_lap


class TestLabels:
    def test_unique(self):
        labels = jnp.asarray([5, 3, 5, 9, 3, 3], jnp.int32)
        uniq, n = get_unique_labels(labels)
        assert int(n) == 3
        np.testing.assert_array_equal(np.asarray(uniq)[:3], [3, 5, 9])

    def test_make_monotonic(self):
        labels = jnp.asarray([10, 20, 10, 30], jnp.int32)
        out = np.asarray(make_monotonic(labels))
        np.testing.assert_array_equal(out, [1, 2, 1, 3])
        out0 = np.asarray(make_monotonic(labels, zero_based=True))
        np.testing.assert_array_equal(out0, [0, 1, 0, 2])

    def test_make_monotonic_filter(self):
        labels = jnp.asarray([-1, 7, 7, 2], jnp.int32)
        out = np.asarray(make_monotonic(
            labels, zero_based=True, filter_op=lambda v: v == -1))
        assert out[0] == -1
        # remaining labels relabeled by rank in unique {-1, 2, 7}
        assert out[3] < out[1] and out[1] == out[2]

    def test_ovr(self):
        labels = jnp.asarray([1, 2, 1, 3], jnp.int32)
        uniq, _ = get_unique_labels(labels)
        out = np.asarray(get_ovr_labels(labels, uniq, 0))
        np.testing.assert_array_equal(out, [1, -1, 1, -1])

    def test_merge_labels(self):
        # batch A says {1,1,3,3,5}; batch B says {1,2,2,4,4}; masked points
        # connect label groups: expect min-label components
        la = jnp.asarray([1, 1, 3, 3, 5], jnp.int32)
        lb = jnp.asarray([1, 2, 2, 4, 4], jnp.int32)
        mask = jnp.asarray([True, True, True, False, False])
        out = np.asarray(merge_labels(la, lb, mask))
        # groups {1,2,3} merge into 1; 5 stays (mask False on its links)
        np.testing.assert_array_equal(out, [1, 1, 1, 1, 5])


class TestCache:
    def test_store_and_get(self):
        rng = np.random.default_rng(0)
        cache = VecCache(n_dim=4, n_vecs=16, associativity=4)
        st = cache.init()
        keys = jnp.asarray([3, 7, 11], jnp.int32)
        vecs = jnp.asarray(rng.random((3, 4)), jnp.float32)
        st = cache.store_vecs(st, keys, vecs)
        got, found, st = cache.get_vecs(st, keys)
        assert bool(found.all())
        np.testing.assert_allclose(np.asarray(got), np.asarray(vecs))
        _, found2, _ = cache.get_vecs(st, jnp.asarray([99], jnp.int32))
        assert not bool(found2.any())

    def test_lru_eviction(self):
        cache = VecCache(n_dim=2, n_vecs=4, associativity=2)  # 2 sets × 2
        st = cache.init()
        # keys 0, 2, 4 all map to set 0; capacity 2 → oldest evicted
        for k in [0, 2]:
            st = cache.store_vecs(st, jnp.asarray([k], jnp.int32),
                                  jnp.full((1, 2), float(k), jnp.float32))
        _, f, st = cache.get_vecs(st, jnp.asarray([0], jnp.int32))  # touch 0
        st = cache.store_vecs(st, jnp.asarray([4], jnp.int32),
                              jnp.full((1, 2), 4.0, jnp.float32))
        _, f0, st = cache.get_vecs(st, jnp.asarray([0], jnp.int32))
        _, f2, st = cache.get_vecs(st, jnp.asarray([2], jnp.int32))
        assert bool(f0.all())        # recently used → kept
        assert not bool(f2.any())    # LRU → evicted

    def test_update_existing(self):
        cache = VecCache(n_dim=2, n_vecs=8, associativity=2)
        st = cache.init()
        st = cache.store_vecs(st, jnp.asarray([5], jnp.int32),
                              jnp.ones((1, 2), jnp.float32))
        st = cache.store_vecs(st, jnp.asarray([5], jnp.int32),
                              2 * jnp.ones((1, 2), jnp.float32))
        got, found, _ = cache.get_vecs(st, jnp.asarray([5], jnp.int32))
        assert bool(found.all())
        np.testing.assert_allclose(np.asarray(got), 2.0)


class TestLAP:
    @pytest.mark.parametrize("n", [4, 16, 48])
    @pytest.mark.parametrize("seed", [0, 1])
    def test_matches_scipy(self, n, seed):
        rng = np.random.default_rng(seed)
        cost = rng.random((n, n)).astype(np.float32)
        res = solve_lap(jnp.asarray(cost))
        rows, cols = linear_sum_assignment(cost)
        ref_obj = cost[rows, cols].sum()
        got = np.asarray(res.row_assignment)
        assert sorted(got) == list(range(n)), "not a permutation"
        np.testing.assert_allclose(float(res.obj_val), ref_obj,
                                   rtol=1e-4, atol=1e-4)

    def test_known(self):
        cost = jnp.asarray([[4.0, 1, 3], [2, 0, 5], [3, 2, 2]])
        res = solve_lap(cost)
        np.testing.assert_array_equal(np.asarray(res.row_assignment),
                                      [1, 0, 2])
        assert float(res.obj_val) == 5.0

    def test_batched(self):
        rng = np.random.default_rng(2)
        costs = rng.random((3, 8, 8)).astype(np.float32)
        res = LinearAssignmentProblem().solve(jnp.asarray(costs))
        for b in range(3):
            r, c = linear_sum_assignment(costs[b])
            np.testing.assert_allclose(float(res.obj_val[b]),
                                       costs[b][r, c].sum(),
                                       rtol=1e-4, atol=1e-4)
