"""Scale-stress for the graph pipeline (round-4 item 7).

The reference stresses single_linkage / spectral at real sizes
(cpp/test/sparse/linkage.cu end-to-end, cpp/bench/spatial/knn.cu);
until round 3 ours were only exercised at m ~ 2k.  These run the same
algorithms at 50k / 100k vertices on the virtual CPU mesh.  The 50k
linkage still takes minutes and keeps the ``slow`` marker (deselect
with ``-m "not slow"``); the 100k spectral partition dropped to ~10 s
with the r5 single-jit Lanczos and now runs by default.
"""

import time

import numpy as np
import pytest


def _adjusted_rand_index(a: np.ndarray, b: np.ndarray) -> float:
    """ARI between two label vectors (standard contingency formula)."""
    a = np.asarray(a).ravel()
    b = np.asarray(b).ravel()
    n = a.size
    _, ai = np.unique(a, return_inverse=True)
    _, bi = np.unique(b, return_inverse=True)
    c = np.zeros((ai.max() + 1, bi.max() + 1), np.int64)
    np.add.at(c, (ai, bi), 1)

    def comb2(x):
        return x * (x - 1) / 2.0

    sum_ij = comb2(c.astype(np.float64)).sum()
    sum_a = comb2(c.sum(axis=1).astype(np.float64)).sum()
    sum_b = comb2(c.sum(axis=0).astype(np.float64)).sum()
    expected = sum_a * sum_b / comb2(float(n))
    max_index = 0.5 * (sum_a + sum_b)
    if max_index == expected:
        return 1.0
    return (sum_ij - expected) / (max_index - expected)


@pytest.mark.slow
def test_single_linkage_50k(rng):
    """m=50k single-linkage: full-size run recovers the blob structure,
    and agrees with scipy single linkage on a subsample (the reference's
    linkage.cu expected-cluster methodology at bench scale)."""
    import scipy.cluster.hierarchy as sch

    from bench import make_blobs
    from raft_tpu.sparse.hierarchy import single_linkage

    m, d, n_blobs = 50_000, 2, 3
    X, truth = make_blobs(rng, m, d, n_blobs)
    t0 = time.perf_counter()
    res = single_linkage(X, n_clusters=n_blobs)
    dt = time.perf_counter() - t0
    labels = np.asarray(res.labels)
    assert labels.shape == (m,)
    assert len(np.unique(labels)) == n_blobs
    ari_truth = _adjusted_rand_index(labels, truth)
    assert ari_truth > 0.99, ari_truth

    # subsample cross-check vs scipy: cluster quality, not just shape
    sub = rng.choice(m, 2000, replace=False)
    Z = sch.linkage(X[sub], method="single")
    scipy_labels = sch.fcluster(Z, t=n_blobs, criterion="maxclust")
    ari_scipy = _adjusted_rand_index(labels[sub], scipy_labels)
    assert ari_scipy > 0.99, ari_scipy
    print(f"single_linkage 50k: {dt:.1f}s, ARI(truth)={ari_truth:.4f}, "
          f"ARI(scipy@2k)={ari_scipy:.4f}")


def test_spectral_partition_100k(rng):
    """100k-vertex spectral partition of a two-community graph: the
    partition must recover the communities and the edge cut must match
    the number of planted cross edges (partition.hpp:65,133 at scale)."""
    from bench import two_community_graph
    from raft_tpu.spectral import analyze_partition, partition
    from raft_tpu.spectral.eigen_solvers import EigenSolverConfig, LanczosSolver

    n_half, n_cross = 50_000, 40
    n = 2 * n_half
    csr = two_community_graph(n_half, n_cross, rng)

    t0 = time.perf_counter()
    solver = LanczosSolver(EigenSolverConfig(n_eig_vecs=2, max_iter=6000,
                                             restart_iter=80, tol=1e-3,
                                             seed=42))
    res = partition(csr, eigen_solver=solver, n_clusters=2)
    dt = time.perf_counter() - t0
    clusters = np.asarray(res.clusters)
    truth = (np.arange(n) >= n_half).astype(np.int32)
    ari = _adjusted_rand_index(clusters, truth)
    assert ari > 0.95, ari
    edge_cut, cost = analyze_partition(csr, 2, res.clusters)
    # a perfect split cuts exactly the planted bridges (minus any that
    # were deduped); imperfect splits cut community edges too
    assert float(edge_cut) <= 3 * n_cross, float(edge_cut)
    print(f"spectral partition 100k: {dt:.1f}s, ARI={ari:.4f}, "
          f"edge_cut={float(edge_cut):.0f}, cost={float(cost):.4f}")
