"""Metrics registry + profiler subsystem tests (docs/OBSERVABILITY.md).

Covers: registry semantics (labels, histogram quantiles, snapshot
isolation, thread-safety under a hammer thread), Prometheus round-trip,
compile-cache hit/miss attribution across shapes, profiler report
nesting, memory gauge tracking, the AllocationError contract, the two
tracing fixes (thread-local range stack; range_push entering
jax.named_scope), comms verb bytes/latency, the session snapshot
surface, and the style-check timing ban.

Global-state convention: the default registry/profiler are
process-global and shared with every other test in the session, so
integration tests assert *deltas*, never absolutes; pure registry
semantics run on private ``MetricsRegistry`` instances.
"""

import json
import threading

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from raft_tpu.core import metrics, profiler, tracing
from raft_tpu.core.error import AllocationError, LogicError


# ---------------------------------------------------------------------- #
# registry semantics
# ---------------------------------------------------------------------- #
class TestRegistry:
    def test_counter_inc_and_value(self):
        reg = metrics.MetricsRegistry()
        c = reg.counter("raft_tpu_test_ops_total")
        c.inc()
        c.inc(41)
        assert c.value == 42

    def test_counter_rejects_negative(self):
        reg = metrics.MetricsRegistry()
        with pytest.raises(ValueError, match="negative"):
            reg.counter("raft_tpu_test_neg_total").inc(-1)

    def test_labeled_series_are_independent(self):
        reg = metrics.MetricsRegistry()
        fam = reg.counter("raft_tpu_test_bytes_total", labels=("verb",))
        fam.labels(verb="allreduce").inc(100)
        fam.labels(verb="bcast").inc(7)
        assert fam.labels(verb="allreduce").value == 100
        assert fam.labels(verb="bcast").value == 7

    def test_label_schema_enforced(self):
        reg = metrics.MetricsRegistry()
        fam = reg.counter("raft_tpu_test_labeled_total", labels=("verb",))
        with pytest.raises(ValueError, match="do not match"):
            fam.labels(wrong="x")
        # a labeled family cannot be used as its own series
        with pytest.raises(ValueError, match="labels"):
            fam.inc()

    def test_kind_conflict_raises(self):
        reg = metrics.MetricsRegistry()
        reg.counter("raft_tpu_test_conflict")
        with pytest.raises(ValueError, match="already registered"):
            reg.gauge("raft_tpu_test_conflict")

    def test_get_or_create_returns_same_family(self):
        reg = metrics.MetricsRegistry()
        assert (reg.counter("raft_tpu_test_same")
                is reg.counter("raft_tpu_test_same"))

    def test_gauge_set_inc_dec_high_water(self):
        reg = metrics.MetricsRegistry()
        g = reg.gauge("raft_tpu_test_live_bytes")
        g.set(100)
        g.inc(50)
        g.dec(120)
        assert g.value == 30
        assert g.high_water == 150

    def test_timer_quantiles_and_extrema(self):
        reg = metrics.MetricsRegistry()
        t = reg.timer("raft_tpu_test_lat_seconds")
        for ms in range(1, 101):  # 1ms..100ms
            t.observe(ms / 1000.0)
        snap = reg.snapshot()["raft_tpu_test_lat_seconds"]["series"][0]
        assert snap["count"] == 100
        assert snap["min"] == pytest.approx(0.001)
        assert snap["max"] == pytest.approx(0.100)
        assert 0.045 <= snap["p50"] <= 0.055
        assert 0.090 <= snap["p95"] <= 0.100
        assert snap["total"] == pytest.approx(sum(range(1, 101)) / 1000.0)

    def test_quantile_nearest_rank_low_counts(self):
        """Review regression: the rank was off by one, so p50 of two
        samples reported the max instead of the lower sample."""
        reg = metrics.MetricsRegistry()
        t = reg.timer("raft_tpu_test_rank_seconds")
        t.observe(0.001)
        t.observe(27.0)
        assert t.quantile(0.5) == pytest.approx(0.001)
        assert t.quantile(0.95) == pytest.approx(27.0)
        assert t.quantile(0.0) == pytest.approx(0.001)
        assert t.quantile(1.0) == pytest.approx(27.0)
        t2 = reg.timer("raft_tpu_test_rank100_seconds")
        for ms in range(1, 101):
            t2.observe(ms / 1000.0)
        assert t2.quantile(0.95) == pytest.approx(0.095)
        assert t2.quantile(0.5) == pytest.approx(0.050)

    def test_timer_scope_observes(self):
        reg = metrics.MetricsRegistry()
        t = reg.timer("raft_tpu_test_scope_seconds")
        with t.time():
            pass
        assert (reg.snapshot()["raft_tpu_test_scope_seconds"]
                ["series"][0]["count"] == 1)

    def test_snapshot_isolation(self):
        reg = metrics.MetricsRegistry()
        c = reg.counter("raft_tpu_test_iso_total")
        c.inc(5)
        snap = reg.snapshot()
        c.inc(100)
        assert snap["raft_tpu_test_iso_total"]["series"][0]["value"] == 5
        # the later snapshot sees the new value
        assert (reg.snapshot()["raft_tpu_test_iso_total"]["series"][0]
                ["value"] == 105)

    def test_thread_safety_hammer(self):
        reg = metrics.MetricsRegistry()
        c = reg.counter("raft_tpu_test_hammer_total")
        t = reg.timer("raft_tpu_test_hammer_seconds")
        n_threads, n_iter = 8, 2000

        def hammer():
            for _ in range(n_iter):
                c.inc()
                t.observe(0.001)

        threads = [threading.Thread(target=hammer)
                   for _ in range(n_threads)]
        for th in threads:
            th.start()
        for th in threads:
            th.join()
        assert c.value == n_threads * n_iter
        assert (reg.snapshot()["raft_tpu_test_hammer_seconds"]
                ["series"][0]["count"] == n_threads * n_iter)

    def test_disable_enable(self):
        reg = metrics.MetricsRegistry()
        c = reg.counter("raft_tpu_test_disabled_total")
        metrics.set_enabled(False)
        try:
            c.inc(10)
        finally:
            metrics.set_enabled(True)
        assert c.value == 0
        c.inc(1)
        assert c.value == 1

    def test_metric_name_helper(self):
        assert (metrics.metric_name("linalg", "gemm_seconds")
                == "raft_tpu_linalg_gemm_seconds")
        with pytest.raises(ValueError):
            metrics.metric_name("bad layer", "x")

    def test_reset(self):
        reg = metrics.MetricsRegistry()
        reg.counter("raft_tpu_test_gone_total").inc()
        reg.reset()
        assert reg.snapshot() == {}


# ---------------------------------------------------------------------- #
# Prometheus text format
# ---------------------------------------------------------------------- #
class TestPrometheus:
    def _populated(self):
        reg = metrics.MetricsRegistry()
        reg.counter("raft_tpu_test_bytes_total",
                    labels=("verb",)).labels(verb="allreduce").inc(4096)
        g = reg.gauge("raft_tpu_test_live_bytes")
        g.set(100)
        g.set(40)
        t = reg.timer("raft_tpu_test_lat_seconds")
        for ms in (1, 2, 3, 4, 100):
            t.observe(ms / 1000.0)
        return reg

    def test_round_trip(self):
        reg = self._populated()
        parsed = metrics.parse_prometheus(reg.to_prometheus())
        assert (parsed["raft_tpu_test_bytes_total"]
                [(("verb", "allreduce"),)] == 4096)
        assert parsed["raft_tpu_test_live_bytes"][()] == 40
        # gauge peaks export as a _peak-suffixed series (the JSON
        # snapshot's high_water field, scraper-visible)
        assert parsed["raft_tpu_test_live_bytes_peak"][()] == 100
        assert "raft_tpu_test_live_bytes_high_water" not in parsed
        assert parsed["raft_tpu_test_lat_seconds_count"][()] == 5
        assert parsed["raft_tpu_test_lat_seconds_sum"][()] == (
            pytest.approx(0.110))
        assert parsed["raft_tpu_test_lat_seconds_max"][()] == (
            pytest.approx(0.100))
        # quantile samples carry the quantile label
        q = parsed["raft_tpu_test_lat_seconds"]
        assert (("quantile", "0.5"),) in q
        assert (("quantile", "0.95"),) in q

    def test_label_escaping_round_trips(self):
        reg = metrics.MetricsRegistry()
        fam = reg.counter("raft_tpu_test_esc_total", labels=("what",))
        fam.labels(what='a"b\\c').inc(3)
        parsed = metrics.parse_prometheus(reg.to_prometheus())
        assert parsed["raft_tpu_test_esc_total"][
            (("what", 'a"b\\c'),)] == 3

    def test_brace_in_label_value_round_trips(self):
        """Review regression: [^}]* label matching choked on '}' inside
        a quoted label value."""
        reg = metrics.MetricsRegistry()
        fam = reg.counter("raft_tpu_test_brace_total", labels=("what",))
        fam.labels(what="a}b{c").inc(2)
        parsed = metrics.parse_prometheus(reg.to_prometheus())
        assert parsed["raft_tpu_test_brace_total"][
            (("what", "a}b{c"),)] == 2

    def test_backslash_n_sequence_round_trips(self):
        """Review regression: sequential unescape replaces turned a
        literal backslash-then-n into a newline; must be one pass."""
        reg = metrics.MetricsRegistry()
        fam = reg.counter("raft_tpu_test_esc2_total", labels=("what",))
        for value in ("a\\nb", "a\nb", "end\\"):
            fam.labels(what=value).inc(1)
        parsed = metrics.parse_prometheus(reg.to_prometheus())
        keys = set(parsed["raft_tpu_test_esc2_total"])
        assert keys == {(("what", "a\\nb"),), (("what", "a\nb"),),
                        (("what", "end\\"),)}


# ---------------------------------------------------------------------- #
# instrumented jit: compile-cache attribution
# ---------------------------------------------------------------------- #
class TestProfiledJit:
    def _stats(self, name):
        return profiler.compile_cache_stats().get(name, {})

    def test_hit_miss_attribution_across_two_shapes(self):
        calls = []

        @profiler.profiled_jit(name="t_two_shapes",
                               static_argnames=("k",))
        def f(x, k):
            calls.append(1)
            return x * k

        a = jnp.ones((4, 4), jnp.float32)
        b = jnp.ones((8, 2), jnp.float32)
        f(a, k=2)
        assert sum(s["misses"] for s in
                   self._stats("t_two_shapes").values()) == 1
        f(a, k=2)  # same shape: hit, no retrace
        st = self._stats("t_two_shapes")
        assert sum(s["misses"] for s in st.values()) == 1
        assert sum(s["hits"] for s in st.values()) == 1
        f(b, k=2)  # second shape: second miss
        st = self._stats("t_two_shapes")
        assert len(st) == 2
        assert sum(s["misses"] for s in st.values()) == 2
        assert sum(s["compile_s"] for s in st.values()) > 0
        # first and second call at the same shape differ: miss then hit
        np.testing.assert_allclose(np.asarray(f(a, k=2)), 2.0)

    def test_static_passed_positionally(self):
        # mirrors _kmeans_jit(X, k, ...): static arg in the middle,
        # passed positionally — the wrapper must normalize by name
        @profiler.profiled_jit(name="t_positional_static",
                               static_argnames=("k",))
        def f(x, k, t):
            return x * k + t

        out = f(jnp.ones((3,), jnp.float32), 3, jnp.zeros((3,),
                                                          jnp.float32))
        np.testing.assert_allclose(np.asarray(out), 3.0)
        out = f(jnp.ones((3,), jnp.float32), 3,
                jnp.zeros((3,), jnp.float32))
        st = self._stats("t_positional_static")
        assert sum(s["hits"] for s in st.values()) == 1

    def test_distinct_static_values_are_distinct_keys(self):
        @profiler.profiled_jit(name="t_static_key",
                               static_argnames=("k",))
        def f(x, k):
            return x * k

        x = jnp.ones((2,), jnp.float32)
        f(x, k=2)
        f(x, k=3)
        assert len(self._stats("t_static_key")) == 2

    def test_jit_counters_in_default_registry(self):
        reg = metrics.default_registry()

        @profiler.profiled_jit(name="t_registry_counters")
        def f(x):
            return x + 1

        x = jnp.ones((5,), jnp.float32)
        miss_fam = reg.counter("raft_tpu_jit_cache_misses_total",
                               labels=("fn",))
        hit_fam = reg.counter("raft_tpu_jit_cache_hits_total",
                              labels=("fn",))
        f(x)
        f(x)
        assert miss_fam.labels(fn="t_registry_counters").value == 1
        assert hit_fam.labels(fn="t_registry_counters").value == 1
        tsnap = (reg.get("raft_tpu_jit_compile_seconds")
                 .labels(fn="t_registry_counters")._snapshot())
        assert tsnap["count"] == 1 and tsnap["total"] > 0

    def test_pytree_and_dtype_in_key(self):
        @profiler.profiled_jit(name="t_dtype_key")
        def f(x):
            return x.sum()

        f(jnp.ones((4,), jnp.float32))
        f(jnp.ones((4,), jnp.int32))
        assert len(self._stats("t_dtype_key")) == 2

    def test_defaulted_and_explicit_args_share_key(self):
        """Review regression: sig.bind without apply_defaults() gave
        f(x) and f(x, k=<default>) distinct keys — duplicate compiles
        of one program and false misses."""
        @profiler.profiled_jit(name="t_default_key",
                               static_argnames=("k",))
        def f(x, k=2, scale=1.0):
            return x * k * scale

        x = jnp.ones((4,), jnp.float32)
        f(x)
        f(x, k=2)
        f(x, k=2, scale=1.0)
        st = self._stats("t_default_key")
        assert len(st) == 1
        assert sum(s["misses"] for s in st.values()) == 1
        assert sum(s["hits"] for s in st.values()) == 2

    def test_device_placement_in_key(self):
        """Review regression: same-shape arrays on different devices
        must not replay one AOT executable (jax raises on a sharding
        mismatch); they key separately, like jax.jit's cache."""
        @profiler.profiled_jit(name="t_device_key")
        def f(x):
            return x + 1

        devs = jax.devices()
        x = jnp.ones((4,), jnp.float32)
        f(jax.device_put(x, devs[0]))
        out = f(jax.device_put(x, devs[-1]))  # 8-dev mesh in conftest
        np.testing.assert_allclose(np.asarray(out), 2.0)
        expected = 1 if len(devs) == 1 else 2
        assert len(self._stats("t_device_key")) == expected

    def test_disable_jit_falls_back_to_eager(self):
        """Review regression: the AOT Compiled path raised under
        jax.disable_jit(); it must route through the plain jit, which
        honors the flag (eager step/print debugging)."""
        @profiler.profiled_jit(name="t_disable_jit")
        def f(x):
            return x * 3

        x = jnp.ones((4,), jnp.float32)
        np.testing.assert_allclose(np.asarray(f(x)), 3.0)  # AOT cached
        with jax.disable_jit():
            np.testing.assert_allclose(np.asarray(f(x)), 3.0)
        np.testing.assert_allclose(np.asarray(f(x)), 3.0)  # cache again

    def test_static_objects_kept_alive_and_equality_keyed(self):
        """Review regression: statics were keyed by repr(v), which for
        id()-repr objects can alias a recycled address onto a stale
        executable; they now key (and stay alive) by the object."""
        @profiler.profiled_jit(name="t_static_alive",
                               static_argnames=("mode",))
        def f(x, mode):
            return x + 1 if mode == "inc" else x - 1

        x = jnp.ones((3,), jnp.float32)
        np.testing.assert_allclose(np.asarray(f(x, "inc")), 2.0)
        # equal-but-distinct string objects share the key (hit)
        np.testing.assert_allclose(np.asarray(f(x, "in" + "c")), 2.0)
        np.testing.assert_allclose(np.asarray(f(x, "dec")), 0.0)
        st = self._stats("t_static_alive")
        assert len(st) == 2
        assert sum(s["hits"] for s in st.values()) == 1

    def test_dynamic_scalars_key_by_type_not_value(self):
        """Review regression: keying dynamic Python scalars on their
        value reported a fresh miss (and compiled a fresh executable)
        for every distinct tol/seed, where plain jax.jit aval-keys
        them and hits."""
        @profiler.profiled_jit(name="t_scalar_key",
                               static_argnames=("k",))
        def f(x, k, seed):
            return x * k + seed

        x = jnp.ones((4,), jnp.float32)
        for seed in range(5):
            out = f(x, 2, float(seed))
            np.testing.assert_allclose(np.asarray(out), 2.0 + seed)
        st = self._stats("t_scalar_key")
        assert sum(s["misses"] for s in st.values()) == 1
        assert sum(s["hits"] for s in st.values()) == 4
        # a different scalar *type* is a different key
        f(x, 2, 7)
        assert len(self._stats("t_scalar_key")) == 2


# ---------------------------------------------------------------------- #
# profiler spans / report
# ---------------------------------------------------------------------- #
class TestProfilerReport:
    def test_nesting_and_counts(self):
        prof = profiler.Profiler(registry=metrics.MetricsRegistry())
        with prof.span("outer"):
            with prof.span("inner"):
                pass
            with prof.span("inner"):
                pass
        tree = prof.tree()
        assert tree["outer"]["count"] == 1
        assert tree["outer"]["children"]["inner"]["count"] == 2
        report = prof.report()
        out_line = [ln for ln in report.splitlines()
                    if "outer" in ln][0]
        in_line = [ln for ln in report.splitlines()
                   if "inner" in ln][0]
        # children render indented under their parent
        assert (len(in_line) - len(in_line.lstrip())
                > len(out_line) - len(out_line.lstrip()))
        assert "n=2" in in_line

    def test_span_feeds_layer_timer(self):
        reg = metrics.MetricsRegistry()
        prof = profiler.Profiler(registry=reg)
        with prof.span("linalg.fake_op", layer="linalg"):
            pass
        snap = reg.snapshot()
        assert ("raft_tpu_linalg_fake_op_seconds" in snap
                and snap["raft_tpu_linalg_fake_op_seconds"]["series"][0]
                ["count"] == 1)

    def test_threads_do_not_graft(self):
        prof = profiler.Profiler(registry=metrics.MetricsRegistry())
        done = threading.Event()

        def worker():
            with prof.span("from_thread"):
                pass
            done.set()

        with prof.span("main_scope"):
            t = threading.Thread(target=worker)
            t.start()
            t.join()
        assert done.is_set()
        tree = prof.tree()
        # the thread's span is a root, NOT a child of main_scope
        assert "from_thread" in tree
        assert "from_thread" not in (
            tree["main_scope"].get("children", {}))

    def test_exception_still_recorded(self):
        prof = profiler.Profiler(registry=metrics.MetricsRegistry())
        with pytest.raises(RuntimeError):
            with prof.span("exploding"):
                raise RuntimeError("boom")
        assert prof.tree()["exploding"]["count"] == 1

    def test_disabled_spans_are_noop(self):
        prof = profiler.Profiler(registry=metrics.MetricsRegistry())
        metrics.set_enabled(False)
        try:
            with prof.span("invisible"):
                pass
        finally:
            metrics.set_enabled(True)
        assert "invisible" not in prof.tree()

    def test_profiled_primitive_honors_handle_profiler(self):
        """Review regression: @profiled primitives hardwired the
        process profiler, dropping spans from a Handle carrying a
        scoped one."""
        from raft_tpu import Handle
        from raft_tpu.distance.pairwise import pairwise_distance

        scoped = profiler.Profiler(registry=metrics.MetricsRegistry())
        h = Handle(profiler=scoped)
        x = jnp.ones((8, 4), jnp.float32)
        pairwise_distance(x, x, handle=h)
        assert "distance.pairwise_distance" in scoped.tree()

    def test_jit_spans_follow_active_scoped_profiler(self):
        """Review regression: profiled_jit's 'jit.<fn>' spans landed on
        the process-default profiler even when the caller's span ran on
        a handle-scoped one, orphaning compile/execute children."""
        @profiler.profiled_jit(name="t_scoped_routing")
        def f(x):
            return x + 1

        scoped = profiler.Profiler(registry=metrics.MetricsRegistry())
        x = jnp.ones((4,), jnp.float32)
        with scoped.span("outer_scope"):
            f(x)
        tree = scoped.tree()
        assert ("jit.t_scoped_routing"
                in tree["outer_scope"].get("children", {}))
        default_tree = profiler.default_profiler().tree()
        assert "jit.t_scoped_routing" not in default_tree

    def test_takes_handle_primitives_report(self):
        from raft_tpu.linalg import gemm

        reg = metrics.default_registry()
        a = jnp.eye(8, dtype=jnp.float32)
        before = 0
        fam = reg.get("raft_tpu_linalg_gemm_seconds")
        if fam is not None:
            before = fam._default()._snapshot()["count"]
        gemm(a, a)
        after = (reg.get("raft_tpu_linalg_gemm_seconds")
                 ._default()._snapshot()["count"])
        assert after == before + 1


# ---------------------------------------------------------------------- #
# memory accounting
# ---------------------------------------------------------------------- #
class TestMemoryAccounting:
    def _live(self, space):
        return metrics.default_registry().gauge(
            "raft_tpu_mr_live_bytes", labels=("space",)).labels(space=space)

    def test_device_buffer_tracks_alloc_free(self):
        from raft_tpu.mr.buffer import DeviceBuffer

        g = self._live("device")
        before = g.value
        buf = DeviceBuffer((64, 64), jnp.float32)
        nbytes = 64 * 64 * 4
        assert g.value == before + nbytes
        assert g.high_water >= before + nbytes
        buf.deallocate()
        assert g.value == before
        buf.deallocate()  # idempotent: no double-free accounting
        assert g.value == before

    def test_peak_survives_free(self):
        from raft_tpu.mr.buffer import DeviceBuffer

        g = self._live("device")
        with DeviceBuffer((256, 256), jnp.float32):
            peak_during = g.high_water
        assert g.high_water == peak_during  # peak is sticky

    def test_host_buffer_space_label(self):
        from raft_tpu.mr.buffer import HostBuffer

        g = self._live("host")
        before = g.value
        buf = HostBuffer((32, 32), jnp.float32)
        assert g.value == before + 32 * 32 * 4
        buf.deallocate()
        assert g.value == before

    def test_allocation_error_carries_context(self, monkeypatch):
        from raft_tpu.mr import buffer as mr_buffer

        def explode(*a, **k):
            raise RuntimeError("RESOURCE_EXHAUSTED: out of memory")

        monkeypatch.setattr(mr_buffer.jax, "device_put", explode)
        with pytest.raises(AllocationError) as ei:
            mr_buffer.DeviceBuffer((128, 128), jnp.float32)
        err = ei.value
        assert err.requested_bytes == 128 * 128 * 4
        assert err.live_bytes >= 0
        assert "128" in str(err) and "live" in str(err)
        assert isinstance(err, Exception)
        # failed allocation must not leak into the live gauge
        g = self._live("device")
        assert g.value >= 0

    def test_gc_reclaims_accounting(self):
        """Review regression: buffers dropped without deallocate() (GC
        frees the HBM) must release their live-byte accounting too."""
        import gc

        from raft_tpu.mr.buffer import DeviceBuffer

        g = self._live("device")
        before = g.value
        bufs = [DeviceBuffer((32, 32), jnp.float32) for _ in range(3)]
        assert g.value == before + 3 * 32 * 32 * 4
        del bufs
        gc.collect()
        assert g.value == before

    def test_gc_does_not_delete_adopted_array(self):
        """Review regression: __del__ must release accounting only —
        an adopted array the caller still holds must survive the
        wrapper's GC."""
        import gc

        from raft_tpu.mr.buffer import DeviceBuffer

        x = jnp.ones((8, 8), jnp.float32)
        buf = DeviceBuffer.from_array(x)
        del buf
        gc.collect()
        np.testing.assert_allclose(np.asarray(x), 1.0)  # still alive

    def test_accounting_balances_across_disable(self):
        """Review regression: a free must balance its recorded alloc
        even if RAFT_TPU_METRICS is toggled off in between (and an
        alloc made while disabled must not be decremented later)."""
        from raft_tpu.mr.buffer import DeviceBuffer

        g = self._live("device")
        before = g.value
        buf = DeviceBuffer((64, 64), jnp.float32)  # recorded
        metrics.set_enabled(False)
        try:
            buf.deallocate()  # paired free applies despite the gate
            assert g.value == before
            buf2 = DeviceBuffer((32, 32), jnp.float32)  # NOT recorded
        finally:
            metrics.set_enabled(True)
        buf2.deallocate()  # no matching alloc: must not go negative
        assert g.value == before

    def test_free_after_registry_reset_does_not_go_negative(self):
        """Review regression: a registry reset between alloc and free
        recreates the gauge at 0 — the orphaned free must be dropped,
        not applied (which left live_bytes negative forever)."""
        from raft_tpu.mr.buffer import DeviceBuffer

        reg = metrics.default_registry()
        buf = DeviceBuffer((64, 64), jnp.float32)
        reg.reset()
        buf.deallocate()
        fam = reg.get("raft_tpu_mr_live_bytes")
        val = (fam.labels(space="device").value
               if fam is not None else 0)
        assert val == 0

    def test_zero_size_buffer_pairs_alloc_and_free_counters(self):
        """Review regression: a 0-byte buffer recorded its alloc
        counter but the falsy byte count skipped the free half."""
        from raft_tpu.mr.buffer import DeviceBuffer

        reg = metrics.default_registry()

        def count(name):
            fam = reg.get(name)
            if fam is None:
                return 0
            return fam.labels(space="device").value

        a0 = count("raft_tpu_mr_alloc_total")
        f0 = count("raft_tpu_mr_free_total")
        DeviceBuffer((0, 8), jnp.float32).deallocate()
        assert count("raft_tpu_mr_alloc_total") == a0 + 1
        assert count("raft_tpu_mr_free_total") == f0 + 1

    def test_pool_counters(self):
        from raft_tpu.mr.buffer import PoolAllocator

        reg = metrics.default_registry()
        hits = reg.counter("raft_tpu_mr_pool_hits_total")
        misses = reg.counter("raft_tpu_mr_pool_misses_total")
        h0, m0 = hits.value, misses.value
        pool = PoolAllocator()
        buf = pool.allocate((16, 16))
        pool.deallocate(buf)
        pool.allocate((16, 16))
        assert misses.value == m0 + 1
        assert hits.value == h0 + 1
        pool.release()


# ---------------------------------------------------------------------- #
# tracing regressions (ISSUE 2 satellites)
# ---------------------------------------------------------------------- #
class TestTracingThreadLocal:
    def test_thread_pop_does_not_touch_main_stack(self):
        """Regression: _range_stack was process-global, so a watchdog
        thread's range_pop popped the main thread's open range."""
        tracing.range_push("main_range")
        try:
            assert len(tracing._range_stack()) == 1

            def worker():
                # one matched pair, then an unmatched pop — under the
                # old global stack the extra pop closed main's range
                tracing.range_push("thread_range")
                tracing.range_pop()
                tracing.range_pop()

            t = threading.Thread(target=worker)
            t.start()
            t.join()
            assert len(tracing._range_stack()) == 1
        finally:
            tracing.range_pop()
        assert len(tracing._range_stack()) == 0


class TestRangePushNamedScope:
    """Regression: range_push only opened a TraceAnnotation, so
    imperative ranges (unlike `annotate`) put no name on the tracing
    name stack and therefore left no HLO names.  The observable is the
    name stack JAX stamps onto traced ops (the same stack
    ``jax.named_scope`` feeds); both range forms must now push it."""

    @staticmethod
    def _name_stack():
        from jax._src import source_info_util

        return str(source_info_util.current_name_stack())

    def test_imperative_range_enters_named_scope(self):
        assert "obsv_scope_regression" not in self._name_stack()
        tracing.range_push("obsv_scope_regression")
        try:
            assert "obsv_scope_regression" in self._name_stack()
        finally:
            tracing.range_pop()
        # and the scope is properly closed after pop
        assert "obsv_scope_regression" not in self._name_stack()

    def test_scoped_and_imperative_consistent(self):
        with tracing.annotate("consistency_probe"):
            a = self._name_stack()
        tracing.range_push("consistency_probe")
        try:
            b = self._name_stack()
        finally:
            tracing.range_pop()
        assert ("consistency_probe" in a) and (a == b)

    def test_named_scope_visible_to_tracing_in_range(self):
        """An op traced between push and pop carries the range name in
        its jaxpr source info — the HLO-name consistency the fix is
        about (scopes entered outside a ``jit`` boundary don't cross
        it in this JAX version; in-trace usage does, same as
        ``annotate``)."""
        from jax._src import source_info_util

        def f(x):
            tracing.range_push("in_trace_range")
            try:
                return x + 1
            finally:
                tracing.range_pop()

        jaxpr = jax.make_jaxpr(f)(0.0)
        stacks = [str(source_info_util.current_name_stack())]
        stacks += [str(e.source_info.name_stack) for e in jaxpr.eqns]
        assert any("in_trace_range" in s for s in stacks[1:])


# ---------------------------------------------------------------------- #
# comms verb metrics
# ---------------------------------------------------------------------- #
class TestCommsMetrics:
    def test_bytes_and_latency_per_verb(self):
        from raft_tpu.comms import HostComms
        from raft_tpu.comms.types import Op

        reg = metrics.default_registry()
        comms = HostComms()
        size = comms.get_size()
        x = jnp.ones((size, 8), jnp.float32)

        def bytes_now():
            fam = reg.get("raft_tpu_comms_bytes_total")
            if fam is None:
                return 0
            return fam.labels(verb="allreduce").value

        def lat_count():
            fam = reg.get("raft_tpu_comms_verb_seconds")
            if fam is None:
                return 0
            return fam.labels(verb="allreduce")._snapshot()["count"]

        b0, n0 = bytes_now(), lat_count()
        comms.allreduce(x, Op.SUM)
        comms.allreduce(x, Op.SUM)
        assert bytes_now() == b0 + 2 * x.nbytes
        assert lat_count() == n0 + 2

    def test_prog_cache_counters(self):
        from raft_tpu.comms import HostComms

        reg = metrics.default_registry()
        comms = HostComms()  # fresh communicator: its prog cache is empty
        size = comms.get_size()
        x = jnp.ones((size, 4), jnp.float32)

        def count(name):
            fam = reg.get(name)
            if fam is None:
                return 0
            return fam.labels(verb="bcast").value

        m0 = count("raft_tpu_comms_prog_cache_misses_total")
        h0 = count("raft_tpu_comms_prog_cache_hits_total")
        comms.bcast(x)
        comms.bcast(x)
        assert count("raft_tpu_comms_prog_cache_misses_total") == m0 + 1
        assert count("raft_tpu_comms_prog_cache_hits_total") == h0 + 1

    def test_failed_verb_counts_latency_not_bytes(self):
        from raft_tpu.comms import HostComms

        reg = metrics.default_registry()
        comms = HostComms()
        size = comms.get_size()
        bad = jnp.ones((size + 1, 2), jnp.float32)  # wrong leading axis

        def bytes_now():
            fam = reg.get("raft_tpu_comms_bytes_total")
            if fam is None:
                return 0
            return fam.labels(verb="allreduce").value

        b0 = bytes_now()
        with pytest.raises(LogicError):
            comms.allreduce(bad)
        assert bytes_now() == b0


# ---------------------------------------------------------------------- #
# session snapshot surface (the ISSUE acceptance shape)
# ---------------------------------------------------------------------- #
class TestSessionSnapshot:
    def test_bench_shaped_run_snapshot(self, tmp_path):
        """pairwise + knn (x2: miss then hit) + allreduce + a buffer —
        the snapshot must carry per-primitive histograms, differing jit
        miss/hit between first and second same-shape call, comms
        bytes/latency per verb, and a live-buffer peak."""
        from raft_tpu.comms import HostComms
        from raft_tpu.distance.pairwise import pairwise_distance
        from raft_tpu.mr.buffer import DeviceBuffer
        from raft_tpu.session import Session
        from raft_tpu.spatial.knn import brute_force_knn

        rng = np.random.default_rng(7)
        X = jnp.asarray(rng.standard_normal((128, 16)), jnp.float32)
        Q = jnp.asarray(rng.standard_normal((16, 16)), jnp.float32)

        st0 = profiler.compile_cache_stats().get("tiled_knn", {})
        h0 = sum(s["hits"] for s in st0.values())
        m0 = sum(s["misses"] for s in st0.values())

        pairwise_distance(Q, X)
        brute_force_knn(X, Q, k=3)   # first call at this shape
        brute_force_knn(X, Q, k=3)   # second call: cache hit
        comms = HostComms()
        comms.allreduce(jnp.ones((comms.get_size(), 4), jnp.float32))
        with DeviceBuffer((64, 64), jnp.float32):
            pass

        s = Session()
        snap = s.metrics_snapshot()
        m = snap["metrics"]

        # per-primitive timer histograms, counts > 0
        for name in ("raft_tpu_distance_pairwise_distance_seconds",
                     "raft_tpu_spatial_brute_force_knn_seconds",
                     "raft_tpu_spatial_tiled_knn_seconds"):
            assert m[name]["type"] == "timer"
            assert m[name]["series"][0]["count"] > 0

        # jit compile/hit counts differ between 1st and 2nd call
        st = snap["compile_cache"]["tiled_knn"]
        assert sum(s_["misses"] for s_ in st.values()) >= m0 + 1
        assert sum(s_["hits"] for s_ in st.values()) >= h0 + 1

        # comms bytes + latency per verb
        verbs = {s_["labels"]["verb"]
                 for s_ in m["raft_tpu_comms_verb_seconds"]["series"]}
        assert "allreduce" in verbs
        byts = {s_["labels"]["verb"]: s_["value"]
                for s_ in m["raft_tpu_comms_bytes_total"]["series"]}
        assert byts["allreduce"] > 0

        # peak live buffer bytes
        mr = {s_["labels"]["space"]: s_
              for s_ in m["raft_tpu_mr_live_bytes"]["series"]}
        assert mr["device"]["high_water"] >= 64 * 64 * 4

        # profiler tree shows the knn nesting
        tree = snap["profiler_tree"]
        assert "spatial.brute_force_knn" in tree
        assert ("spatial.tiled_knn"
                in tree["spatial.brute_force_knn"]["children"])
        assert "profiler report" in snap["profiler_report"]

    def test_dump_metrics_round_trips(self, tmp_path):
        from raft_tpu.session import Session

        path = tmp_path / "snap.json"
        s = Session()
        written = s.dump_metrics(str(path))
        loaded = json.loads(path.read_text())
        assert set(loaded) == {"metrics", "compile_cache",
                               "profiler_tree", "profiler_report",
                               "event_counters", "flight", "inventory"}
        assert loaded["metrics"].keys() == written["metrics"].keys()
        # the flight section (docs/OBSERVABILITY.md "Flight recorder &
        # request tracing") rides in every artifact
        assert {"enabled", "events", "capacity", "blackboxes", "slo",
                "exemplars"} <= set(loaded["flight"])
        # the program cost inventory (docs/OBSERVABILITY.md "Ops
        # plane") does too: {fn: {key: entry}} detail + the summary
        assert {"programs", "total_hbm_bytes", "per_fn",
                "detail"} <= set(loaded["inventory"])

    def test_module_level_snapshot_matches_session(self):
        from raft_tpu import session as session_mod

        a = session_mod.metrics_snapshot()
        b = session_mod.Session().metrics_snapshot()
        assert set(a) == set(b)


# ---------------------------------------------------------------------- #
# style check: ad-hoc timing ban
# ---------------------------------------------------------------------- #
class TestTimingBan:
    def _check(self, tmp_path, monkeypatch, rel, body):
        import importlib.util
        import os
        import sys

        spec = importlib.util.spec_from_file_location(
            "style_check_under_test",
            os.path.join(os.path.dirname(os.path.dirname(
                os.path.abspath(__file__))), "ci", "style_check.py"))
        sc = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(sc)
        monkeypatch.setattr(sc, "REPO", str(tmp_path))
        path = tmp_path / rel
        path.parent.mkdir(parents=True, exist_ok=True)
        path.write_text(body)
        sys.modules.pop("style_check_under_test", None)
        return sc.check_file(str(path))

    def test_time_time_rejected_in_library(self, tmp_path, monkeypatch):
        problems = self._check(
            tmp_path, monkeypatch, "raft_tpu/bad.py",
            "import time\nt0 = time.time()\n")
        assert any("ad-hoc time.time()" in p for p in problems)

    def test_perf_counter_rejected(self, tmp_path, monkeypatch):
        problems = self._check(
            tmp_path, monkeypatch, "raft_tpu/bad2.py",
            "import time\nt0 = time.perf_counter()\n")
        assert any("perf_counter" in p for p in problems)

    def test_aliased_import_rejected(self, tmp_path, monkeypatch):
        problems = self._check(
            tmp_path, monkeypatch, "raft_tpu/bad3.py",
            "import time as t\nt0 = t.monotonic()\n")
        assert any("monotonic" in p for p in problems)

    def test_from_import_rejected(self, tmp_path, monkeypatch):
        problems = self._check(
            tmp_path, monkeypatch, "raft_tpu/bad4.py",
            "from time import perf_counter\nt0 = perf_counter()\n")
        assert any("perf_counter" in p for p in problems)

    def test_sleep_allowed(self, tmp_path, monkeypatch):
        problems = self._check(
            tmp_path, monkeypatch, "raft_tpu/ok.py",
            "import time\ntime.sleep(0.1)\n")
        assert problems == []

    def test_metrics_module_allowlisted(self, tmp_path, monkeypatch):
        problems = self._check(
            tmp_path, monkeypatch, "raft_tpu/core/metrics.py",
            "import time\nt0 = time.perf_counter()\n")
        assert problems == []

    def test_outside_library_allowed(self, tmp_path, monkeypatch):
        problems = self._check(
            tmp_path, monkeypatch, "tests/timing_ok.py",
            "import time\nt0 = time.time()\n")
        assert problems == []

    def test_repo_is_clean(self):
        """The real tree passes its own timing ban."""
        import os
        import subprocess
        import sys

        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        proc = subprocess.run(
            [sys.executable, os.path.join(repo, "ci", "style_check.py")],
            capture_output=True, text=True)
        assert proc.returncode == 0, proc.stdout + proc.stderr
