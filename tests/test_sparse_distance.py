"""Sparse distance + sparse kNN tests vs dense/scipy naive references.

Mirrors cpp/test/sparse/dist_*.cu and cpp/test/sparse/knn.cu: sparse results
must match the dense metric computed on the densified operands.
"""

import numpy as np
import pytest
import scipy.spatial.distance as spd

from raft_tpu.distance.distance_type import DistanceType as D
from raft_tpu.sparse import CSR
from raft_tpu.sparse.distance import pairwise_distance
from raft_tpu.sparse.selection import brute_force_knn, knn_graph


@pytest.fixture
def data():
    rng = np.random.default_rng(7)
    a = (rng.random((23, 17)) * (rng.random((23, 17)) < 0.4)).astype(np.float32)
    b = (rng.random((19, 17)) * (rng.random((19, 17)) < 0.4)).astype(np.float32)
    return a, b


METRICS = [
    (D.L2Expanded, lambda a, b: spd.cdist(a, b, "sqeuclidean"), 2e-3),
    (D.L2SqrtExpanded, lambda a, b: spd.cdist(a, b, "euclidean"), 2e-3),
    (D.InnerProduct, lambda a, b: a @ b.T, 1e-4),
    (D.L1, lambda a, b: spd.cdist(a, b, "cityblock"), 1e-4),
    (D.Linf, lambda a, b: spd.cdist(a, b, "chebyshev"), 1e-4),
    (D.CosineExpanded, lambda a, b: spd.cdist(a, b, "cosine"), 1e-3),
    (D.JaccardExpanded,
     lambda a, b: spd.cdist(a != 0, b != 0, "jaccard"), 1e-4),
    (D.DiceExpanded, lambda a, b: spd.cdist(a != 0, b != 0, "dice"), 1e-4),
    (D.Canberra, lambda a, b: spd.cdist(a, b, "canberra"), 1e-3),
    (D.LpUnexpanded, lambda a, b: spd.cdist(a, b, "minkowski", p=3.0), 1e-3),
]


@pytest.mark.parametrize("metric,ref,tol", METRICS,
                         ids=[m[0].name for m in METRICS])
def test_sparse_pairwise(data, metric, ref, tol):
    a, b = data
    ca = CSR.from_dense(a, capacity=256)
    cb = CSR.from_dense(b, capacity=256)
    got = np.asarray(pairwise_distance(ca, cb, metric, metric_arg=3.0,
                                       batch_size_a=8, batch_size_b=8))
    expect = np.asarray(ref(a, b), dtype=np.float64)
    np.testing.assert_allclose(got, expect, rtol=tol, atol=tol)


def test_sparse_knn_matches_dense(data):
    a, b = data
    ca = CSR.from_dense(a, capacity=256)
    cb = CSR.from_dense(b, capacity=256)
    dists, inds = brute_force_knn(ca, cb, k=5, metric=D.L2Expanded,
                                  batch_size_index=8, batch_size_query=8)
    full = spd.cdist(b, a, "sqeuclidean")
    expect_i = np.argsort(full, axis=1, kind="stable")[:, :5]
    expect_d = np.take_along_axis(full, expect_i, axis=1)
    np.testing.assert_allclose(np.asarray(dists), expect_d, atol=2e-3)
    # indices may tie-swap; compare distances at chosen indices
    chosen = np.take_along_axis(full, np.asarray(inds), axis=1)
    np.testing.assert_allclose(chosen, expect_d, atol=2e-3)


def test_sparse_knn_inner_product(data):
    a, b = data
    ca = CSR.from_dense(a, capacity=256)
    cb = CSR.from_dense(b, capacity=256)
    dists, inds = brute_force_knn(ca, cb, k=3, metric=D.InnerProduct)
    full = b @ a.T
    expect_i = np.argsort(-full, axis=1, kind="stable")[:, :3]
    expect_d = np.take_along_axis(full, expect_i, axis=1)
    np.testing.assert_allclose(np.asarray(dists), expect_d, atol=1e-4)


def test_knn_graph_symmetric():
    rng = np.random.default_rng(0)
    X = rng.random((20, 4)).astype(np.float32)
    g = knn_graph(X, k=4)
    dense = np.asarray(g.to_dense())
    np.testing.assert_allclose(dense, dense.T, atol=1e-6)
    # every vertex keeps at least k-1 neighbors (self edge has weight 0)
    assert ((dense > 0).sum(axis=1) >= 3).all()


@pytest.mark.parametrize("metric,ref,tol", METRICS,
                         ids=[m[0].name for m in METRICS])
def test_coltiled_matches_fullwidth(data, metric, ref, tol):
    """Column-tiled engine == scipy on every metric (bk far below
    n_cols so multiple col tiles + row stats are really exercised)."""
    a, b = data
    ca = CSR.from_dense(a, capacity=256)
    cb = CSR.from_dense(b, capacity=256)
    got = np.asarray(pairwise_distance(ca, cb, metric, metric_arg=3.0,
                                       batch_size_a=8, batch_size_b=8,
                                       batch_size_k=5))
    expect = np.asarray(ref(a, b), dtype=np.float64)
    np.testing.assert_allclose(got, expect, rtol=tol, atol=tol)


@pytest.mark.parametrize("metric", [D.CorrelationExpanded, D.KLDivergence,
                                    D.HellingerExpanded, D.BrayCurtis,
                                    D.HammingUnexpanded, D.JensenShannon,
                                    D.L2SqrtUnexpanded, D.RusselRaoExpanded])
def test_coltiled_matches_fullwidth_extra_metrics(data, metric):
    """Metrics with row-stat decompositions (correlation's sums, KL's
    x·log x, BrayCurtis' denominators) vs the full-width engine."""
    a, b = data
    if metric in (D.KLDivergence, D.JensenShannon, D.HellingerExpanded):
        # probability-vector domain
        a = a / np.maximum(a.sum(1, keepdims=True), 1e-6)
        b = b / np.maximum(b.sum(1, keepdims=True), 1e-6)
    ca = CSR.from_dense(a, capacity=256)
    cb = CSR.from_dense(b, capacity=256)
    got = np.asarray(pairwise_distance(ca, cb, metric,
                                       batch_size_a=8, batch_size_b=8,
                                       batch_size_k=5))
    ref = np.asarray(pairwise_distance(ca, cb, metric,
                                       batch_size_a=32, batch_size_b=32))
    np.testing.assert_allclose(got, ref, rtol=2e-3, atol=2e-3)


def test_auto_heuristic_engages_for_tall_b():
    """A tall-but-narrow b must auto-engage the column-tiled engine: the
    full-width driver densifies ALL of b up front (b_tiles), so gating
    on a single block would let a 1M-row b through to a huge
    allocation.  Checked via the compiled program's own peak memory."""
    import jax

    n_cols, m, n = 256, 8, 300_000
    rng = np.random.default_rng(5)
    a_dense = (rng.random((m, n_cols)) * (rng.random((m, n_cols)) < 0.05)
               ).astype(np.float32)
    # b: sparse tall matrix, ~4 nnz/row
    nnz_row = 4
    rows = np.repeat(np.arange(n), nnz_row)
    cols = rng.integers(0, n_cols, n * nnz_row)
    vals = rng.random(n * nnz_row).astype(np.float32)
    import scipy.sparse as sp

    sb = sp.coo_matrix((vals, (rows, cols)), shape=(n, n_cols))
    sb.sum_duplicates()
    sb = sb.tocsr()
    sa = sp.csr_matrix(a_dense)

    def f(aip, ai, ad, bip, bi, bd):
        ca = CSR(aip, ai, ad, shape=(m, n_cols))
        cb = CSR(bip, bi, bd, shape=(n, n_cols))
        return pairwise_distance(ca, cb, D.L2Expanded)  # no batch_size_k

    fn = jax.jit(f)
    args = (sa.indptr.astype(np.int32), sa.indices.astype(np.int32),
            sa.data.astype(np.float32),
            sb.indptr.astype(np.int32), sb.indices.astype(np.int32),
            sb.data.astype(np.float32))
    mem = fn.lower(*args).compile().memory_analysis()
    peak = (mem.temp_size_in_bytes + mem.argument_size_in_bytes
            + mem.output_size_in_bytes)
    # full-width b_tiles alone would be n * n_cols * 4 = 307 MB; the
    # col-tiled engine keeps temps to tiles + the (m, n) output
    assert peak < 150 * 2**20, f"peak {peak/2**20:.0f} MB"
    got = np.asarray(fn(*args))
    # sparse expanded-form reference (dense cdist at 300k rows would
    # need a 1.2 GB f64 temp)
    sqa = np.asarray(sa.multiply(sa).sum(axis=1)).ravel()
    sqb = np.asarray(sb.multiply(sb).sum(axis=1)).ravel()
    ref = sqa[:, None] + sqb[None, :] - 2.0 * (sa @ sb.T).toarray()
    np.testing.assert_allclose(got, np.maximum(ref, 0.0), rtol=2e-3,
                               atol=2e-3)


def test_coltiled_wide_megacolumn():
    """The reference's load-balanced-SpMV regime (coo_spmv.cuh:49,106):
    n_cols = 2^20, nnz ~ 1e5.  A (block, n_cols) densification would
    allocate 4 GB/tile; the column-tiled engine must stay under 1 GB
    peak while matching scipy."""
    import jax
    import scipy.sparse as sp

    n_cols = 1 << 20
    m, n = 48, 40
    nnz_row = 1200                      # ~1e5 nnz total
    rng = np.random.default_rng(11)

    def make(nr):
        rows = np.repeat(np.arange(nr), nnz_row)
        cols = rng.integers(0, n_cols, nr * nnz_row)
        vals = rng.random(nr * nnz_row).astype(np.float32)
        M = sp.coo_matrix((vals, (rows, cols)), shape=(nr, n_cols))
        M.sum_duplicates()
        return M.tocsr()

    sa, sb = make(m), make(n)

    # raw-leaf wrapper: .lower() cannot pass ArgInfo through the CSR
    # pytree's coercing __init__, so the CSRs are built in-trace
    def f(aip, ai, ad, bip, bi, bd):
        ca = CSR(aip, ai, ad, shape=(m, n_cols))
        cb = CSR(bip, bi, bd, shape=(n, n_cols))
        return pairwise_distance(ca, cb, D.L2Expanded, batch_size_a=64,
                                 batch_size_b=64, batch_size_k=16384)

    fn = jax.jit(f)
    args = (sa.indptr.astype(np.int32), sa.indices.astype(np.int32),
            sa.data.astype(np.float32),
            sb.indptr.astype(np.int32), sb.indices.astype(np.int32),
            sb.data.astype(np.float32))
    # peak-memory assertion from the compiled program itself
    mem = fn.lower(*args).compile().memory_analysis()
    peak = (mem.temp_size_in_bytes + mem.argument_size_in_bytes
            + mem.output_size_in_bytes)
    assert peak < 1 << 30, f"peak {peak/2**30:.2f} GB"

    got = np.asarray(fn(*args))
    ref = spd.cdist(sa.toarray(), sb.toarray(), "sqeuclidean")
    np.testing.assert_allclose(got, ref, rtol=2e-3, atol=2e-3)


def test_sparse_pairwise_hlo_size_constant_in_tiles():
    """Compile-time scaling: the batched driver must emit O(1) HLO in the
    number of tiles (one fori_loop block program), not inline every
    (a-tile, b-tile) pair — reference engine is likewise a single kernel
    (detail/coo_spmv.cuh:49).  At 100k x 100k with 1k batches that is the
    difference between seconds and hours of compile."""
    import jax

    rng = np.random.default_rng(3)
    dense = (rng.random((256, 8)) * (rng.random((256, 8)) < 0.3)).astype(
        np.float32)
    c = CSR.from_dense(dense, capacity=1024)

    def hlo_len(batch):
        jaxpr = jax.make_jaxpr(
            lambda x, y: pairwise_distance(x, y, D.L2Expanded,
                                           batch_size_a=batch,
                                           batch_size_b=batch)
        )(c, c)
        return len(str(jaxpr))

    few_tiles = hlo_len(128)   # 2x2 tiles
    many_tiles = hlo_len(16)   # 16x16 tiles = 64x the block count
    assert many_tiles < 2 * few_tiles, (few_tiles, many_tiles)
