"""Serving resilience (raft_tpu.serve.resilience): serve-seam fault
injection, circuit breaker + failure classification, requeue-once,
degraded-mode ANN dispatch, recovery orchestration, session self_heal,
and the chaos acceptance scenario (docs/FAULT_MODEL.md "Serving failure
model").

Deterministic halves drive a FakeClock through the injectable-clock
seam and step workers manually; the orchestration/chaos halves use real
worker threads.  ``./stress.sh chaos N`` loops the loadgen chaos
scenario with rotating seeds on top of this file's fixed-seed version.
"""

import os
import time

import numpy as np
import pytest

import jax.numpy as jnp

from raft_tpu.comms import faults
from raft_tpu.core.error import (
    CommTimeoutError,
    LogicError,
    ServiceUnavailableError,
)
from raft_tpu.core.metrics import default_registry
from raft_tpu.serve import (
    ANNService,
    BreakerState,
    CircuitBreaker,
    KNNService,
    RecoveryManager,
    Service,
    inject_worker,
)
from raft_tpu.spatial.knn import brute_force_knn

pytestmark = pytest.mark.serve

SEED = int(os.environ.get("RAFT_TPU_SERVE_SEED", "1234"))


class FakeClock:
    def __init__(self, t=0.0):
        self.t = float(t)

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt


@pytest.fixture
def rng():
    return np.random.default_rng(SEED)


@pytest.fixture
def index(rng):
    return jnp.asarray(rng.standard_normal((300, 16)), jnp.float32)


def _echo_service(clock, **kw):
    return Service("echo", lambda p: p * 2.0, dim=4, start=False,
                   max_batch_rows=8, max_wait_ms=0.0, clock=clock, **kw)


# ---------------------------------------------------------------------- #
# circuit breaker state machine
# ---------------------------------------------------------------------- #
class TestCircuitBreaker:
    def test_consecutive_trip_cooldown_probe_close(self):
        clock = FakeClock()
        br = CircuitBreaker("b", failure_threshold=3, window_failures=0,
                            cooldown_s=1.0, clock=clock)
        boom = RuntimeError("device gone")
        assert not br.record_failure(boom)
        assert not br.record_failure(boom)
        assert br.state is BreakerState.CLOSED and br.allow()
        assert br.record_failure(boom)          # third strike trips
        assert br.state is BreakerState.OPEN
        assert not br.allow()
        assert br.retry_after() == pytest.approx(1.0)
        assert br.dispatch_hold() == pytest.approx(1.0)
        clock.advance(1.01)                     # cooldown elapses
        assert br.dispatch_hold() == 0.0
        assert br.state is BreakerState.HALF_OPEN
        assert br.allow()                       # probe admission
        br.record_success()
        assert br.state is BreakerState.CLOSED  # close_after=1

    def test_probe_failure_reopens_with_fresh_cooldown(self):
        clock = FakeClock()
        br = CircuitBreaker("b", failure_threshold=1, cooldown_s=2.0,
                            clock=clock)
        br.record_failure(RuntimeError("x"))
        clock.advance(2.5)
        assert br.state is BreakerState.HALF_OPEN
        assert br.record_failure(RuntimeError("probe failed"))
        assert br.state is BreakerState.OPEN
        assert br.retry_after() == pytest.approx(2.0)

    def test_windowed_trip_catches_flapping(self):
        clock = FakeClock()
        br = CircuitBreaker("b", failure_threshold=0, window=6,
                            window_failures=3, clock=clock)
        for _ in range(2):
            br.record_success()
            assert not br.record_failure(RuntimeError("flap"))
        br.record_success()
        assert br.record_failure(RuntimeError("flap"))  # 3rd in window
        assert br.state is BreakerState.OPEN

    def test_caller_bugs_classified_out(self):
        clock = FakeClock()
        br = CircuitBreaker("b", failure_threshold=1, clock=clock)
        for exc in (LogicError("bad shape", collect_stack=False),
                    ValueError("x"), TypeError("x")):
            assert not br.record_failure(exc)
        assert br.state is BreakerState.CLOSED
        assert br.describe()["consecutive_failures"] == 0

    def test_half_open_probe_budget(self):
        clock = FakeClock()
        br = CircuitBreaker("b", failure_threshold=1, cooldown_s=0.5,
                            half_open_probes=2, clock=clock)
        br.record_failure(RuntimeError("x"))
        clock.advance(0.6)
        assert br.allow() and br.allow()
        assert not br.allow()                   # budget exhausted
        br.record_success()
        assert br.state is BreakerState.CLOSED
        assert br.allow()

    def test_half_open_budget_refreshes_each_cooldown(self):
        """A probe that never produces a batch outcome (expired in
        queue, shed, malformed) must not wedge HALF_OPEN shut: each
        elapsed cooldown grants a fresh probe budget."""
        clock = FakeClock()
        br = CircuitBreaker("b", failure_threshold=1, cooldown_s=1.0,
                            half_open_probes=1, clock=clock)
        br.record_failure(RuntimeError("x"))
        clock.advance(1.1)
        assert br.allow()                       # the one probe slot
        assert not br.allow()                   # spent; no outcome ever
        clock.advance(1.1)                      # a cooldown later
        assert br.allow()                       # fresh budget, not wedged

    def test_both_conditions_disabled_rejected(self):
        with pytest.raises(LogicError):
            CircuitBreaker("b", failure_threshold=0, window_failures=0)

    def test_manual_trip_and_reset(self):
        clock = FakeClock()
        br = CircuitBreaker("b", clock=clock)
        br.trip()
        assert br.state is BreakerState.OPEN
        br.reset()
        assert br.state is BreakerState.CLOSED


# ---------------------------------------------------------------------- #
# serve-seam fault injection (the comms vocabulary, retargeted)
# ---------------------------------------------------------------------- #
class TestServeSeamInjection:
    def test_failnth_hits_the_seam_and_restores(self):
        clock = FakeClock()
        svc = _echo_service(clock, breaker=False)
        with inject_worker(svc.worker,
                           faults.FailNth(1, verb="serve.echo")) as log:
            f1 = svc.submit(jnp.ones((2, 4)))
            svc.worker.run_once()
            with pytest.raises(faults.InjectedError):
                f1.result(timeout=0)
            f2 = svc.submit(jnp.ones((2, 4)))
            svc.worker.run_once()               # second call passes
            assert f2.exception(timeout=0) is None
        assert len(log.injected) == 1
        assert log.injected[0].verb == "serve.echo"
        # key carries the padded bucket rows for assertions
        verb, key = log.calls[0]
        assert verb == "serve.echo"
        assert key[1] in svc.policy.rungs
        f3 = svc.submit(jnp.ones((1, 4)))       # seam restored
        svc.worker.run_once()
        assert f3.exception(timeout=0) is None
        svc.close()

    def test_random_fail_deterministic_per_seed(self):
        clock = FakeClock()

        def run(seed):
            svc = _echo_service(clock, breaker=False)
            outcomes = []
            with inject_worker(svc.worker,
                               faults.RandomFail(0.5, seed=seed)):
                for _ in range(12):
                    f = svc.submit(jnp.ones((1, 4)))
                    svc.worker.run_once()
                    outcomes.append(f.exception(timeout=0) is None)
            svc.close()
            return outcomes

        assert run(SEED) == run(SEED)           # seeded: replays

    def test_injection_sits_below_the_retry_layer(self):
        from raft_tpu.comms.resilience import RetryPolicy

        clock = FakeClock()
        svc = _echo_service(clock, retry_policy=RetryPolicy(
            max_retries=2, base_delay=0.0, sleep=lambda s: None))
        with inject_worker(svc.worker, faults.FailNth(1)) as log:
            f = svc.submit(jnp.ones((1, 4)))
            svc.worker.run_once()
        assert f.exception(timeout=0) is None   # retry won
        assert len(log.injected) == 1
        assert len(log.calls) == 2              # attempt + retry
        svc.close()


# ---------------------------------------------------------------------- #
# breaker wired through the worker: shed, hold, requeue-once
# ---------------------------------------------------------------------- #
class TestBreakerDispatch:
    def _tripping_service(self, clock, **kw):
        br = CircuitBreaker("echo", failure_threshold=1,
                            cooldown_s=1.0, clock=clock)
        return _echo_service(clock, breaker=br, **kw), br

    def test_trip_requeues_riders_once_then_relays(self):
        clock = FakeClock()
        svc, br = self._tripping_service(clock)
        with inject_worker(svc.worker,
                           faults.FailNth(1, persistent=True)):
            f = svc.submit(jnp.ones((2, 4)))
            svc.worker.run_once()
            # the tripping batch's riders are re-enqueued, not lost
            assert br.state is BreakerState.OPEN
            assert not f.done()
            assert svc.batcher.depth() == 1
            # dispatch held while open
            assert not svc.worker.run_once()
            clock.advance(1.1)                  # half-open probe
            svc.worker.run_once()
            # second strike: the error is relayed
            with pytest.raises(faults.InjectedError):
                f.result(timeout=0)
        svc.close()

    def test_trip_then_heal_serves_requeued_rider(self):
        clock = FakeClock()
        svc, br = self._tripping_service(clock)
        with inject_worker(svc.worker, faults.FailNth(1)):
            f = svc.submit(jnp.ones((2, 4)))
            svc.worker.run_once()               # trips + requeues
        assert not f.done()
        clock.advance(1.1)
        assert svc.worker.run_once()            # probe succeeds
        assert np.asarray(f.result(timeout=0)).shape == (2, 4)
        assert br.state is BreakerState.CLOSED
        # exactly-once: the rider resolved with its real result
        total = default_registry().family_total(
            "raft_tpu_serve_requeued_total")
        assert total >= 1
        svc.close()

    def test_open_breaker_sheds_admission_with_retry_after(self):
        clock = FakeClock()
        svc, br = self._tripping_service(clock)
        br.trip()
        with pytest.raises(ServiceUnavailableError) as ei:
            svc.submit(jnp.ones((1, 4)))
        assert ei.value.reason == "breaker_open"
        assert ei.value.service == "echo"
        assert ei.value.retry_after_s == pytest.approx(1.0)
        svc.close()

    def test_drain_overrides_the_hold(self):
        clock = FakeClock()
        svc, br = self._tripping_service(clock)
        with inject_worker(svc.worker,
                           faults.FailNth(1, persistent=True)):
            f = svc.submit(jnp.ones((1, 4)))
            svc.worker.run_once()               # trip + requeue
            assert br.state is BreakerState.OPEN
            # close must not hang behind an open breaker: drain
            # dispatches anyway and the second strike relays
            svc.close(timeout=5.0)
        with pytest.raises(faults.InjectedError):
            f.result(timeout=0)

    def test_caller_bug_batch_does_not_trip(self):
        clock = FakeClock()
        svc, br = self._tripping_service(clock)
        with inject_worker(
                svc.worker,
                _RaiseFault(LogicError("bad", collect_stack=False))):
            f = svc.submit(jnp.ones((1, 4)))
            svc.worker.run_once()
        with pytest.raises(LogicError):
            f.result(timeout=0)                 # relayed immediately
        assert br.state is BreakerState.CLOSED  # classified out
        svc.close()


class _RaiseFault(faults.Fault):
    """Raise a specific exception instance on every matching call."""

    def __init__(self, exc, verb=None):
        super().__init__(verb)
        self.exc = exc

    def apply(self, comms, verb, key, n_match):
        raise self.exc


# ---------------------------------------------------------------------- #
# satellites: dead worker, maintenance error, future taxonomy
# ---------------------------------------------------------------------- #
class TestFailFastSatellites:
    @pytest.mark.filterwarnings(
        "ignore::pytest.PytestUnhandledThreadExceptionWarning")
    def test_dead_worker_sheds_then_restart_serves(self):
        state = {"die": True}

        def exe(p):
            if state["die"]:
                raise SystemExit("loop killer")  # kills the thread
            return p * 2.0

        svc = Service("mort", exe, dim=4, max_batch_rows=8,
                      max_wait_ms=0.5)
        doomed = svc.submit(jnp.ones((1, 4)))
        deadline = time.monotonic() + 10.0
        while svc.worker.is_alive() and time.monotonic() < deadline:
            time.sleep(0.01)
        assert not svc.worker.is_alive()
        # even a worker-KILLING failure resolves its riders first (the
        # exactly-once guarantee): the future carries the error
        assert doomed.wait(timeout=5.0)
        assert doomed.exception(timeout=0) is not None
        with pytest.raises(ServiceUnavailableError) as ei:
            svc.submit(jnp.ones((1, 4)))
        assert ei.value.reason == "worker_dead"
        state["die"] = False
        assert svc.worker.restart()
        assert not svc.worker.restart()          # alive: no-op
        out = svc.submit(jnp.ones((1, 4))).result(timeout=10.0)
        assert bool((np.asarray(out) == 2.0).all())
        svc.close()

    def test_restart_raises_once_closed(self, index):
        svc = KNNService(index, k=3, start=False, max_batch_rows=8)
        svc.close()
        with pytest.raises(LogicError):
            svc.worker.restart()

    def test_maintenance_error_captured_and_cleared(self):
        state = {"fail": True}

        def maint():
            if state["fail"]:
                raise RuntimeError("compactor exploded")

        clock = FakeClock(t=42.0)
        svc = Service("m", lambda p: p, dim=4, start=False,
                      maintenance=maint, clock=clock)
        svc.worker.run_maintenance()
        err = svc.stats()["last_maintenance_error"]
        assert err["type"] == "RuntimeError"
        assert "compactor exploded" in err["message"]
        assert err["at"] == pytest.approx(42.0)
        state["fail"] = False
        svc.worker.run_maintenance()             # success clears it
        assert svc.stats()["last_maintenance_error"] is None
        svc.close()

    def test_future_timeout_is_typed_and_names_service(self):
        svc = Service("slowpoke", lambda p: p, dim=4, start=False)
        fut = svc.submit(jnp.ones((1, 4)))
        with pytest.raises(CommTimeoutError, match="slowpoke"):
            fut.result(timeout=0.01)
        with pytest.raises(CommTimeoutError, match="slowpoke"):
            fut.exception(timeout=0.01)
        svc.close(drain=False)

    def test_breaker_knob_defaults_resolve(self):
        clock = FakeClock()
        svc = _echo_service(clock)
        d = svc.breaker.describe()
        assert d["state"] == "closed"
        assert d["window"] == 16                 # serve_breaker_window
        assert d["cooldown_s"] == pytest.approx(0.25)
        assert svc.stats()["breaker"]["state"] == "closed"
        svc.close()

    def test_breaker_opt_out(self):
        clock = FakeClock()
        svc = _echo_service(clock, breaker=False)
        assert svc.breaker is None
        assert "breaker" not in svc.stats()
        svc.close()

    def test_breaker_knobs_both_zero_means_off(self):
        """The env-level opt-out: both trip conditions knobbed to 0
        disables the breaker instead of crashing construction."""
        from raft_tpu import config

        with config.override(serve_breaker_threshold="0",
                             serve_breaker_window_failures="0"):
            clock = FakeClock()
            svc = _echo_service(clock)
            assert svc.breaker is None
            svc.close()

    def test_half_open_exhausted_shed_reason_and_hint(self):
        clock = FakeClock()
        br = CircuitBreaker("echo", failure_threshold=1,
                            cooldown_s=1.0, half_open_probes=1,
                            clock=clock)
        svc = _echo_service(clock, breaker=br)
        br.record_failure(RuntimeError("x"))
        clock.advance(1.1)                       # OPEN -> HALF_OPEN
        svc.submit(jnp.ones((1, 4)))             # the one probe slot
        with pytest.raises(ServiceUnavailableError) as ei:
            svc.submit(jnp.ones((1, 4)))
        assert ei.value.reason == "breaker_half_open"
        assert ei.value.retry_after_s > 0.0      # budget refresh hint
        svc.close()


# ---------------------------------------------------------------------- #
# degraded-mode ANN dispatch (quality brownout)
# ---------------------------------------------------------------------- #
class TestDegradedDispatch:
    @pytest.fixture
    def ann(self, rng):
        from raft_tpu.spatial.ann import IVFFlatParams, ivf_flat_build

        ref = jnp.asarray(rng.standard_normal((2000, 16)), jnp.float32)
        idx = ivf_flat_build(ref, IVFFlatParams(nlist=32, nprobe=8))
        svc = ANNService(idx, k=5, nprobe=8, nprobe_ladder=(2, 4, 8),
                         start=False, max_batch_rows=16,
                         max_wait_ms=0.0, queue_cap=8,
                         degrade_queue_frac=0.5, name="deg")
        yield svc
        svc.close()

    def test_queue_pressure_steps_down_and_restores(self, ann):
        assert ann._effective_nprobe() == (8, False)
        for _ in range(4):                       # 4/8 >= 0.5: pressure
            ann.submit(jnp.ones((1, 16)))
        assert ann._effective_nprobe() == (4, True)
        while ann.worker.run_once():
            pass
        assert ann._effective_nprobe() == (8, False)  # pressure cleared
        # the formed batch drains the queue below the threshold before
        # dispatch, so the batch itself is usually served at full
        # quality — the live gauge family exists either way
        assert default_registry().get(
            "raft_tpu_serve_degraded_active") is not None

    def test_half_open_breaker_degrades(self, ann):
        ann.breaker.trip()
        # force the cooldown elapsed via the breaker's own clock
        ann.breaker._opened_t = -1e9
        assert ann.breaker.state is BreakerState.HALF_OPEN
        assert ann._effective_nprobe() == (4, True)
        ann.breaker.reset()
        assert ann._effective_nprobe() == (8, False)

    def test_manual_hold_walks_the_ladder(self, ann):
        ann.degrade(2)
        assert ann._effective_nprobe() == (2, True)
        ann.restore()
        assert ann._effective_nprobe() == (8, False)
        assert ann.stats()["degrade_queue_frac"] == pytest.approx(0.5)

    def test_degraded_batch_counted_and_results_sane(self, ann, rng):
        q = jnp.asarray(rng.standard_normal((2, 16)), jnp.float32)
        ann.degrade(2)                           # every batch browns out
        fut = ann.submit(q)
        ann.worker.run_once()
        d, i = fut.result(timeout=0)
        assert np.asarray(i).shape == (2, 5)
        fam = default_registry().get(
            "raft_tpu_serve_degraded_batches_total")
        vals = {labels["service"]: series.value
                for labels, series in fam.series()}
        assert vals.get("deg", 0) >= 1
        ann.restore()


# ---------------------------------------------------------------------- #
# recovery orchestration
# ---------------------------------------------------------------------- #
class TestRecoveryManager:
    def test_recover_carries_ann_snapshot_and_readmits(self, rng):
        from raft_tpu.spatial.ann import IVFFlatParams, ivf_flat_build

        ref = jnp.asarray(rng.standard_normal((1500, 8)), jnp.float32)
        idx = ivf_flat_build(ref, IVFFlatParams(nlist=16, nprobe=16))
        svc = ANNService(idx, k=3, nprobe=16, nprobe_ladder=(4, 16),
                         start=False, max_batch_rows=8,
                         max_wait_ms=0.0, compact_rows=0, name="rec")
        # streaming state that must survive the failure
        new_vec = jnp.asarray(rng.standard_normal((1, 8)), jnp.float32)
        svc.insert([99999], new_vec)
        mgr = RecoveryManager(services=[svc])
        report = mgr.recover()
        assert report["services"] == ["rec"]
        assert not report["comms_recovered"]
        assert svc.delta_rows == 1               # snapshot carried
        assert not svc.batcher.paused()          # re-admitted
        fut = svc.submit(new_vec)
        svc.worker.run_once()
        d, i = fut.result(timeout=0)
        assert 99999 in np.asarray(i)[0]         # inserted row found
        total = default_registry().family_total(
            "raft_tpu_serve_recoveries_total")
        assert total >= 1
        svc.close()

    def test_pause_sheds_recovering(self, index):
        svc = KNNService(index, k=3, start=False, max_batch_rows=8,
                         name="pz")
        svc.pause()
        with pytest.raises(ServiceUnavailableError) as ei:
            svc.submit(jnp.ones((1, 16)))
        assert ei.value.reason == "recovering"
        svc.resume()
        svc.submit(jnp.ones((1, 16)))            # admits again
        svc.close()

    def test_session_self_heal_after_abort(self, index, rng):
        from raft_tpu.session import Comms

        s = Comms().init()
        try:
            svc = s.serve("knn", index=index, k=3, max_batch_rows=16,
                          max_wait_ms=1.0, name="heal-knn")
            svc.warmup()
            q = jnp.asarray(rng.standard_normal((2, 16)), jnp.float32)
            ref = brute_force_knn(index, q, 3)
            s.comms.abort()                      # the device-loss latch
            healed = s.self_heal(devices=[0, 1, 2, 3])
            assert healed["recovered"]
            assert s.comms.get_size() == 4       # surviving sub-mesh
            # post-recovery serving is bit-identical to unbatched
            d, i = svc.submit(q).result(timeout=15.0)
            np.testing.assert_array_equal(np.asarray(i),
                                          np.asarray(ref[1]))
            np.testing.assert_array_equal(np.asarray(d),
                                          np.asarray(ref[0]))
            report = s.health_check()
            assert report["ok"]
        finally:
            s.destroy()

    def test_self_heal_cheap_path_for_breaker_only_trip(self, index):
        """A tripped breaker on a healthy mesh must NOT cost a
        communicator rebuild or a re-warmup — re-admit only."""
        from raft_tpu.session import Comms

        s = Comms().init()
        try:
            # long cooldown: the trip must still be OPEN when
            # health_check's battery (seconds) finishes
            svc = s.serve("knn", index=index, k=3, max_batch_rows=16,
                          max_wait_ms=1.0, name="cheap-knn",
                          breaker=CircuitBreaker(
                              "cheap-knn", failure_threshold=1,
                              cooldown_s=60.0))
            svc.warmup()
            n_dev = s.comms.get_size()
            svc.breaker.trip()
            healed = s.self_heal()
            assert healed["recovered"]
            assert not healed["recovery"]["comms_recovered"]
            assert s.comms.get_size() == n_dev   # no mesh rebuild
            assert svc.breaker.state is BreakerState.CLOSED
            assert s.health_check()["ok"]
        finally:
            s.destroy()

    def test_self_heal_noop_when_healthy(self, index):
        from raft_tpu.session import Comms

        s = Comms().init()
        try:
            s.serve("knn", index=index, k=3, max_batch_rows=16,
                    name="fine-knn")
            healed = s.self_heal()
            assert not healed["recovered"]
            assert healed["report"]["ok"]
        finally:
            s.destroy()


# ---------------------------------------------------------------------- #
# the chaos acceptance scenario (ISSUE 7 acceptance criterion)
# ---------------------------------------------------------------------- #
class TestChaosAcceptance:
    def test_chaos_exactly_once_with_recovery(self, rng):
        """Seeded serve-seam faults + mid-run simulated device loss:
        the service trips, recovers, re-admits; every submitted request
        resolves exactly once with a result or typed error; the
        recovery is visible in ``raft_tpu_serve_recoveries_total`` and
        the breaker state metric; post-recovery results are
        bit-identical to the unbatched call."""
        from tools.loadgen import run_chaos

        index = jnp.asarray(rng.standard_normal((1000, 16)),
                            jnp.float32)
        svc = KNNService(index, k=4, max_batch_rows=64,
                         max_wait_ms=1.0, name="chaos-knn")
        svc.warmup()
        mgr = RecoveryManager(services=[svc])
        report = run_chaos(svc, duration=2.5, concurrency=4, rows=2,
                           seed=SEED, transient_p=0.05, outage_at=0.3,
                           outage_s=0.5, manager=mgr)
        try:
            assert report["exactly_once"], report
            assert report["typed_only"], report
            assert report["lost"] == 0
            assert report["recoveries"] >= 1     # visible in metrics
            assert report["breaker_state"] is not None
            fam = default_registry().get("raft_tpu_serve_breaker_state")
            assert fam is not None
            # post-recovery: breaker closed again, served results exact
            assert svc.breaker.state is BreakerState.CLOSED
        finally:
            svc.close()

    def test_chaos_self_heals_without_manager(self, rng):
        """No RecoveryManager at all: the breaker's half-open probe
        alone re-closes the service once the outage clears — the
        transient-fault self-healing path."""
        from tools.loadgen import run_chaos

        index = jnp.asarray(rng.standard_normal((500, 8)), jnp.float32)
        svc = KNNService(index, k=3, max_batch_rows=32,
                         max_wait_ms=1.0, name="chaos-nomgr",
                         breaker=CircuitBreaker(
                             "chaos-nomgr", failure_threshold=2,
                             cooldown_s=0.1))
        svc.warmup()
        report = run_chaos(svc, duration=2.0, concurrency=3, rows=2,
                           seed=SEED + 1, transient_p=0.0,
                           outage_at=0.3, outage_s=0.4, manager=None)
        try:
            assert report["exactly_once"], report
            assert report["typed_only"], report
            assert report["breaker_trips"] >= 1
            assert report["recoveries"] == 0
            assert report["breaker_state"] == "closed"  # self-healed
        finally:
            svc.close()


# ---------------------------------------------------------------------- #
# CI hygiene: the serve except-Exception audit
# ---------------------------------------------------------------------- #
class TestServeExceptAudit:
    def _check(self, tmp_path, relpath, src, monkeypatch):
        import importlib.util

        spec = importlib.util.spec_from_file_location(
            "style_check", os.path.join(os.path.dirname(__file__),
                                        "..", "ci", "style_check.py"))
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        monkeypatch.setattr(mod, "REPO", str(tmp_path))
        path = tmp_path / relpath
        path.parent.mkdir(parents=True, exist_ok=True)
        path.write_text(src)
        return mod.check_file(str(path))

    def test_silent_swallow_flagged(self, tmp_path, monkeypatch):
        src = ("try:\n"
               "    x = 1\n"
               "except Exception:\n"
               "    pass\n")
        probs = self._check(tmp_path, "raft_tpu/serve/bad.py", src,
                            monkeypatch)
        assert any("except Exception" in p for p in probs)

    def test_relay_and_counter_and_marker_pass(self, tmp_path,
                                               monkeypatch):
        src = ("def f(req, counter):\n"
               "    try:\n"
               "        x = 1\n"
               "    except Exception as e:\n"
               "        req.future._set_exception(e)\n"
               "    try:\n"
               "        x = 2\n"
               "    except Exception:\n"
               "        counter.inc()\n"
               "    try:\n"
               "        x = 3\n"
               "    except Exception:  # serve-exc-ok: audited\n"
               "        pass\n"
               "    try:\n"
               "        x = 4\n"
               "    except Exception:\n"
               "        raise\n")
        probs = self._check(tmp_path, "raft_tpu/serve/good.py", src,
                            monkeypatch)
        assert probs == []

    def test_outside_serve_not_audited(self, tmp_path, monkeypatch):
        src = ("try:\n"
               "    x = 1\n"
               "except Exception:\n"
               "    pass\n")
        probs = self._check(tmp_path, "raft_tpu/spatial/ok.py", src,
                            monkeypatch)
        assert not any("except Exception" in p for p in probs)

    def test_repo_is_clean(self):
        import subprocess
        import sys

        out = subprocess.run(
            [sys.executable,
             os.path.join(os.path.dirname(__file__), "..", "ci",
                          "style_check.py")],
            capture_output=True, text=True)
        assert out.returncode == 0, out.stdout + out.stderr
