"""Traffic shaping (docs/SERVING.md "Traffic shaping"): multi-tenant
weighted-fair admission, EDF/tier ordering, replica groups with hedged
dispatch and loser cancellation.

Deterministic halves drive a FakeClock through the injectable-clock
seam and step the worker manually; the hedging halves use real worker
threads (the hedge race is inherently concurrent) with fixed hedge
thresholds so the straggler/winner roles are scripted by injected
``Delay``/``FailNth`` faults, not timing luck.
"""

import os
import time

import numpy as np
import pytest

import jax.numpy as jnp

from raft_tpu import config
from raft_tpu.comms import faults
from raft_tpu.core.error import (
    LogicError,
    RaftError,
    ServiceOverloadError,
    ServiceUnavailableError,
)
from raft_tpu.core.metrics import default_registry
from raft_tpu.core.profiler import compile_cache_stats
from raft_tpu.serve import (
    KNNService,
    MicroBatcher,
    inject_replica,
    split_mesh,
)
from raft_tpu.spatial.knn import brute_force_knn

pytestmark = pytest.mark.serve

SEED = int(os.environ.get("RAFT_TPU_SERVE_SEED", "1234"))


class FakeClock:
    def __init__(self, t=0.0):
        self.t = float(t)

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt


def _misses():
    return sum(s["misses"] for fn in compile_cache_stats().values()
               for s in fn.values())


def _reg_total(name):
    return int(default_registry().family_total(name))


@pytest.fixture
def rng():
    return np.random.default_rng(SEED)


@pytest.fixture
def index(rng):
    return jnp.asarray(rng.standard_normal((400, 16)), jnp.float32)


# ---------------------------------------------------------------------- #
# weighted-fair share math (fake clock, no threads)
# ---------------------------------------------------------------------- #
class TestWeightedFair:
    def make(self, weights, **kw):
        clock = FakeClock()
        kw.setdefault("max_batch_rows", 16)
        kw.setdefault("max_wait_s", 0.010)
        kw.setdefault("queue_cap", 16)
        return MicroBatcher(clock=clock, tenant_weights=weights,
                            **kw), clock

    def test_shares_split_by_weight(self):
        """Both tenants saturated: a 3:1 weight split of a 16-row
        window is 12 rows vs 4 rows, every window."""
        b, clock = self.make({"a": 3, "b": 1}, queue_cap=64)
        for i in range(14):
            b.submit(("a", i), 1, tenant="a")
        for i in range(4):
            b.submit(("b", i), 1, tenant="b")
        clock.advance(0.02)
        batch = b.take()
        tenants = [r.tenant for r in batch]
        assert tenants.count("a") == 12
        assert tenants.count("b") == 4

    def test_unused_share_redistributed_to_busy_tenant(self):
        """Only bulk queued: it gets the WHOLE window — an idle
        tenant's share is never wasted."""
        b, clock = self.make({"a": 3, "b": 1}, queue_cap=64)
        for i in range(16):
            b.submit(("b", i), 1, tenant="b")
        clock.advance(0.02)
        batch = b.take()
        assert len(batch) == 16
        assert all(r.tenant == "b" for r in batch)

    def test_active_backlog_bounded_by_quota(self):
        """THE isolation property: an active tenant's backlog cannot
        stuff the shared window past its quota — a's 2 rows ride with
        at most b's 4-row share, NOT a 14-row bulk backfill (backfill
        would inflate every batch's exec time and hand the bulk
        backlog to the interactive class as latency)."""
        b, clock = self.make({"a": 3, "b": 1}, queue_cap=64)
        for i in range(2):
            b.submit(("a", i), 1, tenant="a")
        for i in range(16):
            b.submit(("b", i), 1, tenant="b")
        clock.advance(0.02)
        batch = b.take()
        tenants = [r.tenant for r in batch]
        assert tenants.count("a") == 2
        assert tenants.count("b") == 4       # b's quota, no backfill

    def test_deficit_carries_big_request_across_windows(self):
        """A request bigger than one window's share accumulates
        deficit instead of starving — and requests never split."""
        b, clock = self.make({"a": 1, "b": 1})
        b.submit("a-big", 10, tenant="a")    # share is 8: waits once
        b.submit("b-ok", 6, tenant="b")
        clock.advance(0.02)
        assert [r.payload for r in b.take()] == ["b-ok"]
        b.submit("b-late", 6, tenant="b")
        clock.advance(0.02)
        # a's carried deficit (8 + 8 = 16 >= 10) admits the big
        # request; b serves its own share alongside
        payloads = [r.payload for r in b.take()]
        assert "a-big" in payloads and "b-late" in payloads
        b2, clock2 = self.make({"a": 1, "b": 1})
        b2.submit("a-big", 12, tenant="a")
        b2.submit("b1", 6, tenant="b")
        b2.submit("b2", 2, tenant="b")
        clock2.advance(0.02)
        payloads = [r.payload for r in b2.take()]
        # a's 12-row request exceeds its first-window share: it waits
        assert payloads == ["b1", "b2"]
        payloads = [r.payload for r in b2.take()]
        assert payloads == ["a-big"]

    def test_per_tenant_cap_sheds_typed_with_hint(self):
        b, _ = self.make({"a": 3, "b": 1}, queue_cap=8)
        assert b.tenant_cap("a") == 6
        assert b.tenant_cap("b") == 2
        for i in range(2):
            b.submit(("b", i), 1, tenant="b")
        with pytest.raises(ServiceOverloadError) as ei:
            b.submit(("b", 9), 1, tenant="b")
        assert ei.value.tenant == "b"
        assert ei.value.queue_cap == 2
        assert ei.value.retry_after_s > 0.0
        # the other tenant still admits: shed isolation
        b.submit(("a", 0), 1, tenant="a")

    def test_unknown_tenant_autoregisters_at_weight_one(self):
        b, clock = self.make({"a": 3})
        b.submit("x", 1, tenant="surprise")
        assert b.tenants()["surprise"] == 1.0
        clock.advance(0.02)
        assert len(b.take()) == 1

    def test_single_queue_service_unchanged(self):
        """No tenant_weights: one implicit default tenant with the
        full cap and the full window — the pre-tenancy behavior."""
        clock = FakeClock()
        b = MicroBatcher(max_batch_rows=16, max_wait_s=0.01,
                         queue_cap=4, clock=clock)
        assert b.tenant_cap("default") == 4
        for i in range(4):
            b.submit(i, 1)
        with pytest.raises(ServiceOverloadError) as ei:
            b.submit("over", 1)
        assert ei.value.queue_cap == 4
        assert ei.value.retry_after_s > 0.0

    def test_drain_estimate_tracks_batch_time(self):
        b, clock = self.make({"a": 1}, queue_cap=4)
        for i in range(4):
            b.submit(i, 1, tenant="a")
        with pytest.raises(ServiceOverloadError) as e1:
            b.submit("x", 1, tenant="a")
        b.note_batch_seconds(2.0)
        with pytest.raises(ServiceOverloadError) as e2:
            b.submit("x", 1, tenant="a")
        assert e2.value.retry_after_s > e1.value.retry_after_s


# ---------------------------------------------------------------------- #
# EDF + tiers (fake clock, no threads)
# ---------------------------------------------------------------------- #
class TestEDF:
    def make(self, **kw):
        clock = FakeClock()
        kw.setdefault("max_batch_rows", 4)
        kw.setdefault("max_wait_s", 0.010)
        kw.setdefault("queue_cap", 16)
        return MicroBatcher(clock=clock, **kw), clock

    def test_edf_beats_fifo_within_tenant(self):
        b, clock = self.make()
        b.submit("late", 1, deadline_t=10.0)
        b.submit("soon", 1, deadline_t=1.0)
        b.submit("mid", 1, deadline_t=5.0)
        clock.advance(0.02)
        assert [r.payload for r in b.take()] == ["soon", "mid", "late"]

    def test_no_deadline_sorts_after_deadlines_fifo(self):
        b, clock = self.make()
        b.submit("n1", 1)
        b.submit("d", 1, deadline_t=99.0)
        b.submit("n2", 1)
        clock.advance(0.02)
        assert [r.payload for r in b.take()] == ["d", "n1", "n2"]

    def test_tier_overrides_deadline(self):
        b, clock = self.make()
        b.submit("urgent-far", 1, deadline_t=100.0, tier=-1)
        b.submit("normal-soon", 1, deadline_t=1.0)
        clock.advance(0.02)
        assert [r.payload for r in b.take()] == ["urgent-far",
                                                 "normal-soon"]

    def test_fifo_preserved_without_deadlines(self):
        """Determinism regression: equal keys dispatch in submission
        order (the seq tie-break)."""
        b, clock = self.make()
        for name in ("a", "b", "c", "d"):
            b.submit(name, 1)
        clock.advance(0.02)
        assert [r.payload for r in b.take()] == ["a", "b", "c", "d"]

    def test_requeued_request_listed_once_at_shutdown(self):
        """A popped-then-requeued request leaves a stale entry in the
        arrival view; shutdown must list (and fail) it exactly once."""
        b, clock = self.make()
        b.submit("keep", 1)
        b.submit("ride", 1)
        clock.advance(0.02)
        batch = b.take()
        assert len(batch) == 2
        assert b.requeue(batch)
        leftovers = b.shutdown()
        assert [r.payload for r in leftovers] == ["keep", "ride"]

    def test_requeue_served_before_everything(self):
        b, clock = self.make()
        b.submit("fresh-soon", 1, deadline_t=0.5)
        clock.advance(0.02)
        batch = b.take()
        assert [r.payload for r in batch] == ["fresh-soon"]
        b.submit("newer", 1, deadline_t=0.1)
        assert b.requeue(batch)
        clock.advance(0.02)
        assert [r.payload for r in b.take()] == ["fresh-soon", "newer"]

    def test_service_submit_threads_tenant_and_tier(self, index, rng):
        clock = FakeClock()
        svc = KNNService(index, k=3, start=False, clock=clock,
                         max_batch_rows=32, max_wait_ms=10.0,
                         tenant_weights={"i": 2, "b": 1},
                         name="traffic%d" % SEED)
        q = jnp.asarray(rng.standard_normal((2, 16)), jnp.float32)
        svc.submit(q, tenant="i", tier=1)
        svc.submit(q, tenant="b")
        assert svc.batcher.tenant_depths() == {"i": 1, "b": 1}
        st = svc.stats()
        assert st["tenants"]["i"]["weight"] == 2.0
        assert st["tenants"]["b"]["depth"] == 1
        clock.advance(0.5)
        assert svc.worker.run_once()
        # per-tenant served counters flowed
        fam = default_registry().get("raft_tpu_serve_tenant_rows_total")
        vals = {(lbl["service"], lbl["tenant"]): s.value
                for lbl, s in fam.series()}
        assert vals[(svc.name, "i")] == 2
        assert vals[(svc.name, "b")] == 2
        svc.close()

    def test_tenant_weights_knob_resolves(self, index):
        config.configure(serve_tenant_weights="x:5,y:1")
        try:
            svc = KNNService(index, k=3, start=False, max_batch_rows=16)
            assert svc.batcher.tenants() == {"x": 5.0, "y": 1.0}
            svc.close()
        finally:
            config.configure(serve_tenant_weights=None)


# ---------------------------------------------------------------------- #
# replica groups: identity, rotation, warmup
# ---------------------------------------------------------------------- #
class TestReplicas:
    def test_split_mesh_disjoint(self):
        from raft_tpu.comms.host_comms import default_mesh

        mesh = default_mesh()
        groups = split_mesh(mesh, mesh.axis_names[0], 2)
        ids = [set(int(d.id) for d in g.devices.ravel())
               for g in groups]
        assert ids[0] & ids[1] == set()
        assert len(ids[0] | ids[1]) == mesh.devices.size
        with pytest.raises(RaftError):
            split_mesh(mesh, mesh.axis_names[0], 1)
        with pytest.raises(RaftError):
            split_mesh(mesh, mesh.axis_names[0], 99)

    def test_replicated_matches_unbatched(self, index, rng):
        q = jnp.asarray(rng.standard_normal((6, 16)), jnp.float32)
        d0, i0 = brute_force_knn(index, q, 5)
        svc = KNNService(index, k=5, replicas=2, hedge_ms=5000.0,
                         max_batch_rows=32, bucket_rungs=(8, 32),
                         max_wait_ms=1.0)
        try:
            assert svc.donate is False   # hedging forces donation off
            for _ in range(3):           # rotation covers both replicas
                out = svc.submit(jnp.copy(q)).result(timeout=60)
                np.testing.assert_array_equal(np.asarray(out[1]),
                                              np.asarray(i0))
                np.testing.assert_allclose(np.asarray(out[0]),
                                           np.asarray(d0),
                                           rtol=1e-4, atol=1e-4)
            st = svc.stats()["replicas"]
            assert len(st["replicas"]) == 2
            devs = [set(r["devices"]) for r in st["replicas"]]
            assert devs[0] & devs[1] == set()
        finally:
            svc.close()

    def test_zero_postwarmup_compiles_with_hedging(self, index, rng):
        svc = KNNService(index, k=5, replicas=2, hedge_ms=50.0,
                         max_batch_rows=32, bucket_rungs=(8, 32),
                         max_wait_ms=0.5)
        try:
            svc.warmup()
            m0 = _misses()
            # a hedged batch (replica 0 straggles) must hit only warmed
            # executables on the OTHER replica too
            with inject_replica(svc, 0, faults.Delay(0.6)):
                q = jnp.asarray(rng.standard_normal((4, 16)),
                                jnp.float32)
                for _ in range(3):
                    svc.submit(jnp.copy(q)).result(timeout=60)
            time.sleep(0.8)          # abandoned losers wake and bail
            assert _misses() == m0
            assert _reg_total("raft_tpu_comms_host_staged_bytes") == 0
        finally:
            svc.close()

    def test_hedge_fires_and_loser_cancels_exactly_once(self, index,
                                                        rng):
        """THE hedging acceptance: Delay on one replica -> the hedge
        resolves every future exactly once with the exact result, the
        win/cancel counters move, and the delayed loser never
        resolves anything."""
        q = jnp.asarray(rng.standard_normal((4, 16)), jnp.float32)
        _, i0 = brute_force_knn(index, q, 5)
        svc = KNNService(index, k=5, replicas=2, hedge_ms=60.0,
                         max_batch_rows=32, bucket_rungs=(8, 32),
                         max_wait_ms=0.5)
        try:
            svc.warmup()
            h0 = _reg_total("raft_tpu_serve_hedges_total")
            w0 = _reg_total("raft_tpu_serve_hedge_wins_total")
            c0 = _reg_total("raft_tpu_serve_hedge_cancelled_total")
            with inject_replica(svc, 0, faults.Delay(0.8)):
                futs = [svc.submit(jnp.copy(q)) for _ in range(4)]
                outs = [f.result(timeout=60) for f in futs]
            for out in outs:
                np.testing.assert_array_equal(np.asarray(out[1]),
                                              np.asarray(i0))
            fired = _reg_total("raft_tpu_serve_hedges_total") - h0
            wins = _reg_total("raft_tpu_serve_hedge_wins_total") - w0
            cancelled = _reg_total(
                "raft_tpu_serve_hedge_cancelled_total") - c0
            assert fired > 0 and wins > 0
            assert cancelled == fired   # exactly one loser per hedge
            # the loser wakes, sees the abandon mark, and bails; every
            # future is already resolved exactly once (result() above)
            time.sleep(1.0)
            for f in futs:
                assert f.done() and f.exception(timeout=0) is None
        finally:
            svc.close()

    def test_tripped_replica_drops_out_and_heals(self, index, rng):
        """Persistent failure on replica 0: its OWN breaker trips it
        out of rotation (failover keeps batches succeeding, the
        service breaker stays closed); after the fault clears a
        half-open probe re-closes it."""
        q = jnp.asarray(rng.standard_normal((4, 16)), jnp.float32)
        _, i0 = brute_force_knn(index, q, 5)
        with config.override(serve_breaker_threshold="1",
                             serve_breaker_cooldown_ms="1000"):
            svc = KNNService(index, k=5, replicas=2, hedge_ms=5000.0,
                             max_batch_rows=32, bucket_rungs=(8, 32),
                             max_wait_ms=0.5)
        try:
            svc.warmup()
            f0 = _reg_total("raft_tpu_serve_replica_failovers_total")
            with inject_replica(svc, 0,
                                faults.FailNth(1, persistent=True)):
                for _ in range(4):
                    out = svc.submit(jnp.copy(q)).result(timeout=60)
                    np.testing.assert_array_equal(np.asarray(out[1]),
                                                  np.asarray(i0))
                states = {r["idx"]: r["state"] for r in
                          svc.stats()["replicas"]["replicas"]}
                # tripped OUT of rotation (a slow run may already have
                # cooled into the half-open probe window — still out
                # of closed rotation, which is the contract)
                assert states[0] in ("open", "half_open")
                assert states[1] == "closed"
            assert (_reg_total("raft_tpu_serve_replica_failovers_total")
                    - f0) >= 1
            # the service-level breaker never saw a failure: every
            # batch succeeded via failover/rotation
            assert svc.breaker.describe()["state"] == "closed"
            time.sleep(1.05)         # cooldown: replica 0 half-opens
            for _ in range(4):
                svc.submit(jnp.copy(q)).result(timeout=60)
            states = {r["idx"]: r["state"] for r in
                      svc.stats()["replicas"]["replicas"]}
            assert states[0] == "closed"
        finally:
            svc.close()

    def test_all_replicas_tripped_sheds_typed(self, index, rng):
        q = jnp.asarray(rng.standard_normal((4, 16)), jnp.float32)
        with config.override(serve_breaker_threshold="1",
                             serve_breaker_cooldown_ms="60000"):
            svc = KNNService(index, k=5, replicas=2, hedge_ms=5000.0,
                             max_batch_rows=32, bucket_rungs=(8, 32),
                             max_wait_ms=0.5, breaker=False)
        try:
            with inject_replica(svc, 0,
                                faults.FailNth(1, persistent=True)):
                with inject_replica(svc, 1,
                                    faults.FailNth(1, persistent=True)):
                    errs = []
                    for _ in range(4):
                        fut = svc.submit(jnp.copy(q))
                        errs.append(fut.exception(timeout=60))
            # first failures relay the injected error; once both
            # breakers trip, batches shed replicas_exhausted — every
            # future resolves exactly once with a TYPED error
            assert all(isinstance(e, RaftError) for e in errs)
            assert any(isinstance(e, ServiceUnavailableError)
                       and e.reason == "replicas_exhausted"
                       for e in errs)
        finally:
            svc.close()

    def test_session_serve_replicas_and_health(self, index):
        from raft_tpu.session import Comms

        s = Comms().init()
        try:
            svc = s.serve("knn", index=index, k=3, replicas=2,
                          max_batch_rows=32, bucket_rungs=(8, 32),
                          name="rep-knn", retry_policy=None)
            assert svc.replica_device_ids() == set(
                int(d.id) for d in s.comms.mesh.devices.ravel())
            report = s.health_check()
            assert report["services"]["rep-knn"]["mesh_ok"] is True
            assert report["ok"]
        finally:
            s.destroy()

    def test_rebuild_replicas_on_shrunk_mesh(self, index, rng):
        """Replica-loss recovery: rebuild over a smaller mesh re-cuts
        the groups; a 1-device survivor degrades to plain sharded
        serving but keeps answering exactly."""
        from raft_tpu.comms.host_comms import default_mesh

        q = jnp.asarray(rng.standard_normal((4, 16)), jnp.float32)
        _, i0 = brute_force_knn(index, q, 5)
        svc = KNNService(index, k=5, replicas=2, hedge_ms=5000.0,
                         max_batch_rows=32, bucket_rungs=(8, 32),
                         max_wait_ms=0.5)
        try:
            assert svc.rebuild_replicas(default_mesh(4)) is True
            svc.warmup()
            st = svc.stats()["replicas"]
            assert len(st["replicas"]) == 2
            assert svc.replica_device_ids() == {0, 1, 2, 3}
            out = svc.submit(jnp.copy(q)).result(timeout=60)
            np.testing.assert_array_equal(np.asarray(out[1]),
                                          np.asarray(i0))
            # degrade path: 1 device cannot host 2 disjoint replicas
            assert svc.rebuild_replicas(default_mesh(1)) is True
            svc.warmup()
            assert svc._replica_set is None
            assert svc.axis is not None      # plain sharded fallback
            out = svc.submit(jnp.copy(q)).result(timeout=60)
            np.testing.assert_array_equal(np.asarray(out[1]),
                                          np.asarray(i0))
            # a regrown mesh RESTORES replication (post_recover keys
            # off the constructor's intent, not the degraded state)
            assert svc.rebuild_replicas(default_mesh(8)) is True
            svc.warmup()
            assert svc._replica_set is not None
            assert len(svc.stats()["replicas"]["replicas"]) == 2
            out = svc.submit(jnp.copy(q)).result(timeout=60)
            np.testing.assert_array_equal(np.asarray(out[1]),
                                          np.asarray(i0))
        finally:
            svc.close()

    def test_replicas_reject_bad_config(self, index):
        with pytest.raises(RaftError):
            KNNService(index, k=3, replicas=1, start=False)

    def test_adaptive_threshold_needs_samples(self, index):
        svc = KNNService(index, k=3, replicas=2, hedge_ms=0.0,
                         max_batch_rows=32, bucket_rungs=(8, 32),
                         start=False)
        try:
            rs = svc._replica_set
            assert rs.hedge_s is None
            assert rs.hedge_after(8) is None     # cold: never hedge
            for _ in range(5):
                rs.tracker.observe(8, 0.010)
            # adaptive: max(factor * p99, floor) with defaults
            # factor=1.5, min=10ms -> 15ms
            assert rs.hedge_after(8) == pytest.approx(0.015)
        finally:
            svc.close()


# ---------------------------------------------------------------------- #
# overload-taxonomy satellites
# ---------------------------------------------------------------------- #
class TestOverloadTaxonomy:
    def test_error_carries_tenant_and_hint(self):
        e = ServiceOverloadError("m", 4, 4, tenant="bulk",
                                 retry_after_s=1.5)
        assert e.tenant == "bulk"
        assert e.retry_after_s == 1.5
        assert "tenant=bulk" in str(e)
        e2 = ServiceOverloadError("m", 4, 4)
        assert e2.tenant is None and e2.retry_after_s == 0.0

    def test_ann_delta_shed_carries_hint(self, rng):
        from raft_tpu.serve import ANNService
        from raft_tpu.spatial.ann import IVFFlatParams, ivf_flat_build

        ref = jnp.asarray(rng.standard_normal((300, 8)), jnp.float32)
        idx = ivf_flat_build(ref, IVFFlatParams(nlist=8, nprobe=2))
        svc = ANNService(idx, k=3, delta_cap=4, compact_rows=0,
                         start=False)
        try:
            svc.insert([1, 2, 3, 4],
                       rng.standard_normal((4, 8)).astype(np.float32))
            with pytest.raises(ServiceOverloadError) as ei:
                svc.insert([5], rng.standard_normal((1, 8)).astype(
                    np.float32))
            assert ei.value.retry_after_s > 0.0
        finally:
            svc.close()


# ---------------------------------------------------------------------- #
# CI hygiene: the shed-hint audit
# ---------------------------------------------------------------------- #
class TestShedHintAudit:
    def _check(self, tmp_path, relpath, src, monkeypatch):
        import importlib.util

        spec = importlib.util.spec_from_file_location(
            "style_check", os.path.join(os.path.dirname(__file__),
                                        "..", "ci", "style_check.py"))
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        monkeypatch.setattr(mod, "REPO", str(tmp_path))
        path = tmp_path / relpath
        path.parent.mkdir(parents=True, exist_ok=True)
        path.write_text(src)
        return mod.check_file(str(path))

    def test_bare_shed_flagged(self, tmp_path, monkeypatch):
        src = "raise ServiceOverloadError('full', 4, 4)\n"
        probs = self._check(tmp_path, "raft_tpu/serve/bad.py", src,
                            monkeypatch)
        assert any("retry_after_s" in p for p in probs)

    def test_hinted_shed_passes(self, tmp_path, monkeypatch):
        src = ("raise ServiceOverloadError('full', 4, 4,\n"
               "                           retry_after_s=0.5)\n")
        assert self._check(tmp_path, "raft_tpu/serve/ok.py", src,
                           monkeypatch) == []

    def test_marker_exempts(self, tmp_path, monkeypatch):
        src = ("raise ServiceOverloadError('full', 4, 4)"
               "  # shed-hint-ok\n")
        assert self._check(tmp_path, "raft_tpu/serve/marked.py", src,
                           monkeypatch) == []

    def test_outside_serve_not_audited(self, tmp_path, monkeypatch):
        src = "raise ServiceOverloadError('full', 4, 4)\n"
        assert self._check(tmp_path, "raft_tpu/spatial/out.py", src,
                           monkeypatch) == []

    def test_library_shed_sites_all_hinted(self):
        """The audit holds on the real tree (the self-test above only
        proves the checker; this proves the library)."""
        import importlib.util

        spec = importlib.util.spec_from_file_location(
            "style_check", os.path.join(os.path.dirname(__file__),
                                        "..", "ci", "style_check.py"))
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        serve_dir = os.path.join(os.path.dirname(__file__), "..",
                                 "raft_tpu", "serve")
        problems = []
        for fn in os.listdir(serve_dir):
            if fn.endswith(".py"):
                problems += [p for p in mod.check_file(
                    os.path.join(serve_dir, fn))
                    if "retry_after_s" in p]
        assert problems == []


# ---------------------------------------------------------------------- #
# mixed-tenant loadgen scenario (threaded smoke)
# ---------------------------------------------------------------------- #
class TestMixedTenantLoadgen:
    def test_mixed_run_reports_per_tenant_and_typed_sheds(self, rng):
        from tools.loadgen import build_service, run_mixed_tenants

        svc = build_service("knn", 2000, 16, 5, seed=SEED,
                            max_batch_rows=64, max_wait_ms=1.0,
                            queue_cap=32,
                            tenant_weights={"interactive": 4,
                                            "bulk": 1})
        svc.warmup()
        try:
            rep = run_mixed_tenants(svc, duration=1.2,
                                    interactive_concurrency=2,
                                    bulk_qps=150.0, interactive_rows=2,
                                    bulk_rows=16, seed=SEED)
        finally:
            svc.close()
        assert set(rep["tenants"]) == {"interactive", "bulk"}
        assert rep["tenants"]["interactive"]["requests_ok"] > 0
        assert rep["untyped_sheds"] == 0
        # the bulk flood sheds against its own share, interactive
        # stays admitted (its closed loop can't exceed its cap)
        assert rep["tenants"]["interactive"]["rejected"] == 0

    def test_hedge_chaos_scenario(self, rng):
        from tools.loadgen import build_service, run_hedge_chaos

        svc = build_service("knn", 2000, 16, 5, seed=SEED,
                            max_batch_rows=64, max_wait_ms=1.0,
                            replicas=2, hedge_ms=60.0)
        svc.warmup()
        try:
            rep = run_hedge_chaos(svc, duration=2.5, concurrency=3,
                                  rows=4, seed=SEED, delay_s=0.4)
        finally:
            svc.close()
        assert rep["chaos_ok"], rep
        assert rep["hedge_wins"] > 0
        assert rep["exactly_once"] and rep["typed_only"]
        assert rep["post_warmup_compiles"] == 0
