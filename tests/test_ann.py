"""ANN tests: IVF-Flat / IVF-PQ / IVF-SQ recall, ball cover exactness.

Mirrors cpp/test/spatial/ann_base_kernel.cuh + ball_cover.cu (discrepancy
counts vs brute force).
"""

import numpy as np
import pytest
import scipy.spatial.distance as spd

from raft_tpu.distance.distance_type import DistanceType as D
from raft_tpu.spatial import (
    IVFFlatParams,
    IVFPQParams,
    IVFSQParams,
    approx_knn_build_index,
    approx_knn_search,
    rbc_all_knn_query,
    rbc_build_index,
    rbc_knn_query,
)


def recall(got_ids, ref_ids):
    hits = sum(len(set(g) & set(r)) for g, r in zip(got_ids, ref_ids))
    return hits / ref_ids.size


@pytest.fixture
def data():
    rng = np.random.default_rng(42)
    X = rng.random((1000, 16)).astype(np.float32)
    Q = rng.random((50, 16)).astype(np.float32)
    return X, Q


def brute(X, Q, k):
    full = spd.cdist(Q, X, "sqeuclidean")
    ids = np.argsort(full, axis=1, kind="stable")[:, :k]
    return np.take_along_axis(full, ids, axis=1), ids


class TestIVFFlat:
    def test_high_recall(self, data):
        X, Q = data
        idx = approx_knn_build_index(X, IVFFlatParams(nlist=20), D.L2Expanded)
        dd, ii = approx_knn_search(idx, Q, k=10, nprobe=8)
        _, ref = brute(X, Q, 10)
        assert recall(np.asarray(ii), ref) > 0.9

    def test_full_probe_exact(self, data):
        X, Q = data
        idx = approx_knn_build_index(X, IVFFlatParams(nlist=10), D.L2Expanded)
        dd, ii = approx_knn_search(idx, Q, k=5, nprobe=10)
        ref_d, ref = brute(X, Q, 5)
        assert recall(np.asarray(ii), ref) == 1.0
        np.testing.assert_allclose(np.asarray(dd), ref_d, rtol=1e-3, atol=1e-3)


class TestIVFPQ:
    @pytest.fixture
    def gauss(self):
        """Easy Gaussian data at M=8 x 8-bit (dsub=2: 256 codewords per
        2-d subspace — quantization error far below neighbor spacing).
        A correct ADC pipeline measures ~0.9 recall@10 unrefined here; a
        half-broken LUT cannot clear the 0.8 bar (reference quality bar
        = FAISS parity, ann_quantized_faiss.cuh:75)."""
        rng = np.random.default_rng(7)
        X = rng.normal(0, 1, (2000, 16)).astype(np.float32)
        Q = rng.normal(0, 1, (50, 16)).astype(np.float32)
        return X, Q

    def test_unrefined_recall(self, gauss):
        X, Q = gauss
        idx = approx_knn_build_index(
            X, IVFPQParams(nlist=10, M=8, n_bits=8), D.L2Expanded)
        dd, ii = approx_knn_search(idx, Q, k=10, nprobe=10)
        _, ref = brute(X, Q, 10)
        assert recall(np.asarray(ii), ref) >= 0.8

    def test_adc_onehot_matches_gather(self, gauss, monkeypatch):
        """The one-hot MXU formulation of the ADC scan must return the
        same distances and ids as the LUT gather (RAFT_TPU_PQ_ADC)."""
        X, Q = gauss
        idx = approx_knn_build_index(
            X, IVFPQParams(nlist=10, M=8, n_bits=8), D.L2Expanded)
        d_g, i_g = approx_knn_search(idx, Q, k=10, nprobe=10)
        monkeypatch.setenv("RAFT_TPU_PQ_ADC", "onehot")
        d_o, i_o = approx_knn_search(idx, Q, k=10, nprobe=10)
        np.testing.assert_allclose(np.asarray(d_g), np.asarray(d_o),
                                   rtol=1e-4, atol=1e-4)
        assert (np.asarray(i_g) == np.asarray(i_o)).mean() > 0.99

    def test_adc_onehot_padded_codebooks(self, monkeypatch):
        """m < 2**n_bits pads codebooks with inf rows; the one-hot ADC
        einsum must not turn those into 0*inf = NaN distances
        (code-review r4 finding)."""
        rng = np.random.default_rng(9)
        X = rng.normal(0, 1, (120, 16)).astype(np.float32)  # < 256 rows
        Q = rng.normal(0, 1, (20, 16)).astype(np.float32)
        idx = approx_knn_build_index(
            X, IVFPQParams(nlist=4, M=8, n_bits=8), D.L2Expanded)
        monkeypatch.setenv("RAFT_TPU_PQ_ADC", "onehot")
        dd, ii = approx_knn_search(idx, Q, k=5, nprobe=4)
        assert np.isfinite(np.asarray(dd)).all()
        _, ref = brute(X, Q, 5)
        assert recall(np.asarray(ii), ref) >= 0.8

    def test_refined_recall(self, gauss):
        X, Q = gauss
        idx = approx_knn_build_index(
            X, IVFPQParams(nlist=10, M=8, n_bits=8, refine_ratio=4),
            D.L2Expanded)
        dd, ii = approx_knn_search(idx, Q, k=10, nprobe=10)
        _, ref_i = brute(X, Q, 10)
        # exact re-rank of the top-40 ADC candidates: near-perfect
        assert recall(np.asarray(ii), ref_i) >= 0.99
        # refined distances are EXACT where the index matches the
        # brute-force reference at the same rank
        ref_d, _ = brute(X, Q, 10)
        hit = np.asarray(ii) == ref_i
        np.testing.assert_allclose(np.asarray(dd)[hit], ref_d[hit],
                                   rtol=1e-3, atol=1e-3)

    def test_refine_ratio_override(self, gauss):
        """Search-time refine_ratio=1 disables re-ranking even on an
        index built with vectors stored."""
        X, Q = gauss
        idx = approx_knn_build_index(
            X, IVFPQParams(nlist=10, M=8, n_bits=8, refine_ratio=4),
            D.L2Expanded)
        d_ref, i_ref = approx_knn_search(idx, Q, k=10, nprobe=10,
                                         refine_ratio=1)
        idx_plain = approx_knn_build_index(
            X, IVFPQParams(nlist=10, M=8, n_bits=8), D.L2Expanded)
        d_p, i_p = approx_knn_search(idx_plain, Q, k=10, nprobe=10)
        np.testing.assert_array_equal(np.asarray(i_ref), np.asarray(i_p))


class TestIVFSQ:
    def test_high_recall(self, data):
        X, Q = data
        idx = approx_knn_build_index(
            X, IVFSQParams(nlist=10), D.L2Expanded)
        dd, ii = approx_knn_search(idx, Q, k=10, nprobe=10)
        _, ref = brute(X, Q, 10)
        # 8-bit residual quantization ~ near-exact
        assert recall(np.asarray(ii), ref) > 0.95

    def test_no_residual_encoding(self, data):
        X, Q = data
        idx = approx_knn_build_index(
            X, IVFSQParams(nlist=10, nprobe=10, encode_residual=False),
            D.L2Expanded)
        dd, ii = approx_knn_search(idx, Q, k=10)  # nprobe from build params
        _, ref = brute(X, Q, 10)
        assert recall(np.asarray(ii), ref) > 0.95


class TestParams:
    def test_build_nprobe_honored(self, data):
        X, Q = data
        # nprobe=nlist at build → search without explicit nprobe is exact
        idx = approx_knn_build_index(X, IVFFlatParams(nlist=10, nprobe=10),
                                     D.L2Expanded)
        dd, ii = approx_knn_search(idx, Q, k=5)
        _, ref = brute(X, Q, 5)
        assert recall(np.asarray(ii), ref) == 1.0

    def test_metric_rejected(self, data):
        X, _ = data
        import pytest as _pytest
        from raft_tpu.core.error import RaftError
        with _pytest.raises(Exception):
            approx_knn_build_index(X, IVFFlatParams(nlist=10),
                                   D.InnerProduct)


class TestNprobeValidation:
    """ISSUE 6 satellite: nprobe edge regressions — non-positive raises
    LogicError, over-nlist clamps with a one-time warning instead of
    passing garbage into the probe scan."""

    @pytest.fixture
    def flat(self, data):
        X, Q = data
        return approx_knn_build_index(
            X, IVFFlatParams(nlist=10, nprobe=4), D.L2Expanded), Q

    @pytest.mark.parametrize("bad", [0, -1, -7])
    def test_nonpositive_nprobe_raises(self, flat, bad):
        from raft_tpu.core.error import LogicError

        idx, Q = flat
        with pytest.raises(LogicError):
            approx_knn_search(idx, Q, k=5, nprobe=bad)

    @pytest.mark.parametrize("params", [
        IVFFlatParams(nlist=10, nprobe=4),
        IVFPQParams(nlist=10, nprobe=4, M=4),
        IVFSQParams(nlist=10, nprobe=4),
    ])
    def test_nonpositive_nprobe_raises_all_kinds(self, data, params):
        from raft_tpu.core.error import LogicError

        X, Q = data
        idx = approx_knn_build_index(X, params, D.L2Expanded)
        with pytest.raises(LogicError):
            approx_knn_search(idx, Q, k=5, nprobe=0)

    def test_oversized_nprobe_clamps_with_one_time_warning(self, flat):
        import warnings

        from raft_tpu.spatial import ann as ann_mod

        idx, Q = flat
        ann_mod._NPROBE_CLAMP_WARNED.clear()
        with warnings.catch_warnings(record=True) as w:
            warnings.simplefilter("always")
            d_big, i_big = approx_knn_search(idx, Q, k=5, nprobe=999)
        clamp_w = [x for x in w if "clamping to nlist" in str(x.message)]
        assert len(clamp_w) == 1
        # clamped == explicit full probe, bitwise
        d_full, i_full = approx_knn_search(idx, Q, k=5, nprobe=10)
        assert (np.asarray(d_big) == np.asarray(d_full)).all()
        assert (np.asarray(i_big) == np.asarray(i_full)).all()
        # one-time: a second oversized call does not warn again
        with warnings.catch_warnings(record=True) as w2:
            warnings.simplefilter("always")
            approx_knn_search(idx, Q, k=5, nprobe=999)
        assert not [x for x in w2
                    if "clamping to nlist" in str(x.message)]


class TestDeltaSegment:
    """Delta-aware search entry points (streaming ingestion substrate):
    empty delta is a bitwise no-op, live delta rows merge exactly, and
    ivf_flat_extend folds them in losslessly."""

    def test_empty_delta_is_identity(self, data):
        import jax.numpy as jnp

        X, Q = data
        idx = approx_knn_build_index(
            X, IVFFlatParams(nlist=10, nprobe=4), D.L2Expanded)
        d0, i0 = approx_knn_search(idx, Q, k=5)
        blank = (jnp.zeros((16, 16), jnp.float32),
                 jnp.full((16,), -1, jnp.int32))
        d1, i1 = approx_knn_search(idx, Q, k=5, delta=blank)
        assert (np.asarray(d0) == np.asarray(d1)).all()
        assert (np.asarray(i0) == np.asarray(i1)).all()

    def test_delta_rows_merge_and_extend_matches(self, data):
        import jax.numpy as jnp

        X, Q = data
        idx = approx_knn_build_index(
            X, IVFFlatParams(nlist=10, nprobe=10), D.L2Expanded)
        # delta = 3 perturbed queries under fresh global ids
        dv = np.zeros((8, 16), np.float32)
        di = np.full(8, -1, np.int32)
        dv[:3] = Q[:3] + 1e-3
        di[:3] = [2000, 2001, 2002]
        d1, i1 = approx_knn_search(
            idx, Q, k=5, delta=(jnp.asarray(dv), jnp.asarray(di)))
        assert (np.asarray(i1)[:3, 0] == di[:3]).all()
        # brute force over X + delta rows agrees on the id sets
        X_aug = np.concatenate([X, dv[:3]])
        _, ref = brute(X_aug, Q, 5)
        ref_ids = np.where(ref >= 1000, ref + 1000, ref)
        assert recall(np.asarray(i1), ref_ids) == 1.0
        # compaction: extend produces the same answers from slot storage
        from raft_tpu.spatial.ann import ivf_flat_extend

        idx2 = ivf_flat_extend(idx, dv[:3], di[:3])
        d2, i2 = approx_knn_search(idx2, Q, k=5)
        assert (np.asarray(i2) == np.asarray(i1)).all()
        assert np.allclose(np.asarray(d2), np.asarray(d1), atol=1e-4)


class TestBallCover:
    @pytest.mark.parametrize("metric", [D.L2SqrtExpanded, D.L2Expanded])
    def test_exact_2d(self, metric):
        rng = np.random.default_rng(0)
        X = rng.random((800, 2)).astype(np.float32)
        Q = rng.random((60, 2)).astype(np.float32)
        idx = rbc_build_index(X, metric=metric)
        dd, ii = rbc_knn_query(idx, 7, Q)
        kind = "sqeuclidean" if metric == D.L2Expanded else "euclidean"
        full = spd.cdist(Q, X, kind)
        ref_i = np.argsort(full, axis=1, kind="stable")[:, :7]
        ref_d = np.take_along_axis(full, ref_i, axis=1)
        np.testing.assert_allclose(np.asarray(dd), ref_d, rtol=1e-3,
                                   atol=1e-4)
        # exactness as discrepancy count (reference ball_cover.cu style)
        assert recall(np.asarray(ii), ref_i) > 0.999

    def test_exact_haversine(self):
        rng = np.random.default_rng(1)
        lat = rng.uniform(-np.pi / 2, np.pi / 2, 500)
        lon = rng.uniform(-np.pi, np.pi, 500)
        X = np.stack([lat, lon], 1).astype(np.float32)
        idx = rbc_build_index(X, metric=D.Haversine)
        dd, ii = rbc_all_knn_query(idx, 5)
        # self is each point's nearest neighbor at distance 0
        np.testing.assert_array_equal(np.asarray(ii)[:, 0], np.arange(500))
        np.testing.assert_allclose(np.asarray(dd)[:, 0], 0.0, atol=1e-5)
        # check a handful of rows exhaustively
        from raft_tpu.spatial import haversine_distances
        import jax.numpy as jnp
        full = np.asarray(haversine_distances(jnp.asarray(X[:20]),
                                              jnp.asarray(X)))
        ref_i = np.argsort(full, axis=1, kind="stable")[:, :5]
        ref_d = np.take_along_axis(full, ref_i, axis=1)
        np.testing.assert_allclose(np.asarray(dd)[:20], ref_d, atol=1e-5)

    def test_3d(self):
        rng = np.random.default_rng(2)
        X = rng.random((600, 3)).astype(np.float32)
        idx = rbc_build_index(X, metric=D.L2SqrtExpanded)
        dd, ii = rbc_all_knn_query(idx, 4)
        full = spd.cdist(X, X, "euclidean")
        ref_i = np.argsort(full, axis=1, kind="stable")[:, :4]
        assert recall(np.asarray(ii), ref_i) > 0.999


class TestIVFSkew:
    """Slotted list storage under Zipf-skewed cluster sizes (the reference
    FAISS path keeps variable-length lists, ann_quantized_faiss.cuh:75;
    dense max_len padding would collapse here)."""

    def _zipf_blobs(self, m=20000, d=16, nlist=50):
        rng = np.random.default_rng(0)
        # cluster sizes ~ 1/rank: the hottest cluster holds ~20% of rows
        w = 1.0 / np.arange(1, nlist + 1)
        sizes = np.maximum((w / w.sum() * m).astype(int), 1)
        sizes[0] += m - sizes.sum()
        centers = rng.normal(0, 10, (nlist, d))
        X = np.concatenate([
            centers[c] + rng.normal(0, 0.5, (s, d))
            for c, s in enumerate(sizes)
        ]).astype(np.float32)
        return X[rng.permutation(len(X))]

    def test_build_memory_bounded(self):
        from raft_tpu.spatial.ann import IVFFlatParams, ivf_flat_build

        X = self._zipf_blobs()
        m = X.shape[0]
        idx = ivf_flat_build(X, IVFFlatParams(nlist=50), D.L2Expanded)
        n_slots, cap, d = idx.slot_vecs.shape
        # storage within ~2x of the unpadded ideal (m rows + per-list
        # rounding), however skewed the k-means assignment came out
        assert n_slots * cap <= 2 * m + 8 * 50, (n_slots, cap, m)
        # a dense (nlist, max_len, d) layout would need nlist*max_len:
        max_len = int(np.asarray(idx.list_sizes).max())
        assert n_slots * cap < 50 * max_len

    def test_skewed_recall(self):
        from raft_tpu.spatial.ann import IVFFlatParams, ivf_flat_build, \
            ivf_flat_search

        X = self._zipf_blobs(m=5000)
        Q = X[:64] + 0.01
        idx = ivf_flat_build(X, IVFFlatParams(nlist=20), D.L2Expanded)
        dd, ii = ivf_flat_search(idx, Q, k=10, nprobe=8)
        _, ref = brute(X, Q, 10)
        assert recall(np.asarray(ii), ref) > 0.9

    def test_explicit_cap_splits_hot_list(self):
        from raft_tpu.spatial.ann import _build_slots

        labels = np.array([0] * 100 + [1] * 3 + [2] * 5)
        slot_rows, slot_cent, cent_slots, cap, counts = _build_slots(
            labels, 3, cap=16)
        np.testing.assert_array_equal(counts, [100, 3, 5])
        assert cap == 16
        # list 0 split into ceil(100/16)=7 slots; others 1 each
        assert (slot_cent == 0).sum() == 7
        assert slot_rows.shape == (9, 16)
        assert (cent_slots[0] >= 0).sum() == 7
        # every row appears exactly once
        got = np.sort(slot_rows[slot_rows >= 0])
        np.testing.assert_array_equal(got, np.arange(108))


class TestHandleInjection:
    def test_ivf_search_records_on_handle(self, data):
        from raft_tpu import Handle
        from raft_tpu.spatial.ann import IVFFlatParams, ivf_flat_build, \
            ivf_flat_search

        X, Q = data
        h = Handle(n_streams=2)
        idx = ivf_flat_build(X, IVFFlatParams(nlist=10), D.L2Expanded,
                             handle=h)
        dd, ii = ivf_flat_search(idx, Q, k=5, nprobe=10, handle=h)
        assert len(h.get_stream()._pending) > 0
        h.sync_stream()
        assert len(h.get_stream()._pending) == 0
        _, ref = brute(X, Q, 5)
        assert recall(np.asarray(ii), ref) == 1.0


def test_ivf_float64(data):
    """x64 inputs must work (conftest enables jax_enable_x64; the scan
    carry must adopt the input dtype, not hard-code f32)."""
    from raft_tpu.spatial.ann import IVFFlatParams, ivf_flat_build, \
        ivf_flat_search

    X, Q = data
    X64, Q64 = X.astype(np.float64), Q.astype(np.float64)
    idx = ivf_flat_build(X64, IVFFlatParams(nlist=10), D.L2Expanded)
    dd, ii = ivf_flat_search(idx, Q64, k=5, nprobe=10)
    ref_d, ref = brute(X64, Q64, 5)
    assert recall(np.asarray(ii), ref) == 1.0
    np.testing.assert_allclose(np.asarray(dd), ref_d, rtol=1e-6, atol=1e-9)
