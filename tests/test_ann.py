"""ANN tests: IVF-Flat / IVF-PQ / IVF-SQ recall, ball cover exactness.

Mirrors cpp/test/spatial/ann_base_kernel.cuh + ball_cover.cu (discrepancy
counts vs brute force).
"""

import numpy as np
import pytest
import scipy.spatial.distance as spd

from raft_tpu.distance.distance_type import DistanceType as D
from raft_tpu.spatial import (
    IVFFlatParams,
    IVFPQParams,
    IVFSQParams,
    approx_knn_build_index,
    approx_knn_search,
    rbc_all_knn_query,
    rbc_build_index,
    rbc_knn_query,
)


def recall(got_ids, ref_ids):
    hits = sum(len(set(g) & set(r)) for g, r in zip(got_ids, ref_ids))
    return hits / ref_ids.size


@pytest.fixture
def data():
    rng = np.random.default_rng(42)
    X = rng.random((1000, 16)).astype(np.float32)
    Q = rng.random((50, 16)).astype(np.float32)
    return X, Q


def brute(X, Q, k):
    full = spd.cdist(Q, X, "sqeuclidean")
    ids = np.argsort(full, axis=1, kind="stable")[:, :k]
    return np.take_along_axis(full, ids, axis=1), ids


class TestIVFFlat:
    def test_high_recall(self, data):
        X, Q = data
        idx = approx_knn_build_index(X, IVFFlatParams(nlist=20), D.L2Expanded)
        dd, ii = approx_knn_search(idx, Q, k=10, nprobe=8)
        _, ref = brute(X, Q, 10)
        assert recall(np.asarray(ii), ref) > 0.9

    def test_full_probe_exact(self, data):
        X, Q = data
        idx = approx_knn_build_index(X, IVFFlatParams(nlist=10), D.L2Expanded)
        dd, ii = approx_knn_search(idx, Q, k=5, nprobe=10)
        ref_d, ref = brute(X, Q, 5)
        assert recall(np.asarray(ii), ref) == 1.0
        np.testing.assert_allclose(np.asarray(dd), ref_d, rtol=1e-3, atol=1e-3)


class TestIVFPQ:
    def test_reasonable_recall(self, data):
        X, Q = data
        idx = approx_knn_build_index(
            X, IVFPQParams(nlist=10, M=4, n_bits=6), D.L2Expanded)
        dd, ii = approx_knn_search(idx, Q, k=10, nprobe=10)
        _, ref = brute(X, Q, 10)
        # quantized distances: recall@10 well above chance (10/1000 = 1%)
        assert recall(np.asarray(ii), ref) > 0.5


class TestIVFSQ:
    def test_high_recall(self, data):
        X, Q = data
        idx = approx_knn_build_index(
            X, IVFSQParams(nlist=10), D.L2Expanded)
        dd, ii = approx_knn_search(idx, Q, k=10, nprobe=10)
        _, ref = brute(X, Q, 10)
        # 8-bit residual quantization ~ near-exact
        assert recall(np.asarray(ii), ref) > 0.95

    def test_no_residual_encoding(self, data):
        X, Q = data
        idx = approx_knn_build_index(
            X, IVFSQParams(nlist=10, nprobe=10, encode_residual=False),
            D.L2Expanded)
        dd, ii = approx_knn_search(idx, Q, k=10)  # nprobe from build params
        _, ref = brute(X, Q, 10)
        assert recall(np.asarray(ii), ref) > 0.95


class TestParams:
    def test_build_nprobe_honored(self, data):
        X, Q = data
        # nprobe=nlist at build → search without explicit nprobe is exact
        idx = approx_knn_build_index(X, IVFFlatParams(nlist=10, nprobe=10),
                                     D.L2Expanded)
        dd, ii = approx_knn_search(idx, Q, k=5)
        _, ref = brute(X, Q, 5)
        assert recall(np.asarray(ii), ref) == 1.0

    def test_metric_rejected(self, data):
        X, _ = data
        import pytest as _pytest
        from raft_tpu.core.error import RaftError
        with _pytest.raises(Exception):
            approx_knn_build_index(X, IVFFlatParams(nlist=10),
                                   D.InnerProduct)


class TestBallCover:
    @pytest.mark.parametrize("metric", [D.L2SqrtExpanded, D.L2Expanded])
    def test_exact_2d(self, metric):
        rng = np.random.default_rng(0)
        X = rng.random((800, 2)).astype(np.float32)
        Q = rng.random((60, 2)).astype(np.float32)
        idx = rbc_build_index(X, metric=metric)
        dd, ii = rbc_knn_query(idx, 7, Q)
        kind = "sqeuclidean" if metric == D.L2Expanded else "euclidean"
        full = spd.cdist(Q, X, kind)
        ref_i = np.argsort(full, axis=1, kind="stable")[:, :7]
        ref_d = np.take_along_axis(full, ref_i, axis=1)
        np.testing.assert_allclose(np.asarray(dd), ref_d, rtol=1e-3,
                                   atol=1e-4)
        # exactness as discrepancy count (reference ball_cover.cu style)
        assert recall(np.asarray(ii), ref_i) > 0.999

    def test_exact_haversine(self):
        rng = np.random.default_rng(1)
        lat = rng.uniform(-np.pi / 2, np.pi / 2, 500)
        lon = rng.uniform(-np.pi, np.pi, 500)
        X = np.stack([lat, lon], 1).astype(np.float32)
        idx = rbc_build_index(X, metric=D.Haversine)
        dd, ii = rbc_all_knn_query(idx, 5)
        # self is each point's nearest neighbor at distance 0
        np.testing.assert_array_equal(np.asarray(ii)[:, 0], np.arange(500))
        np.testing.assert_allclose(np.asarray(dd)[:, 0], 0.0, atol=1e-5)
        # check a handful of rows exhaustively
        from raft_tpu.spatial import haversine_distances
        import jax.numpy as jnp
        full = np.asarray(haversine_distances(jnp.asarray(X[:20]),
                                              jnp.asarray(X)))
        ref_i = np.argsort(full, axis=1, kind="stable")[:, :5]
        ref_d = np.take_along_axis(full, ref_i, axis=1)
        np.testing.assert_allclose(np.asarray(dd)[:20], ref_d, atol=1e-5)

    def test_3d(self):
        rng = np.random.default_rng(2)
        X = rng.random((600, 3)).astype(np.float32)
        idx = rbc_build_index(X, metric=D.L2SqrtExpanded)
        dd, ii = rbc_all_knn_query(idx, 4)
        full = spd.cdist(X, X, "euclidean")
        ref_i = np.argsort(full, axis=1, kind="stable")[:, :4]
        assert recall(np.asarray(ii), ref_i) > 0.999
