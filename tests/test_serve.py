"""Serving layer (raft_tpu.serve): bucketing math, micro-batching,
admission control, deadlines, warmup/compile-cache, drain/close
lifecycle, VecCache wiring, session integration.

Deterministic halves run a FakeClock through the injectable-clock seam
and step the worker manually (no threads); the concurrency halves use
real worker threads with tiny batching windows.  ``./stress.sh serve N``
loops this file with a rotating RAFT_TPU_SERVE_SEED to shake scheduling
nondeterminism out of the threaded tests.
"""

import os
import threading
import time

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from raft_tpu import config
from raft_tpu.core.error import (
    CommTimeoutError,
    LogicError,
    ServiceOverloadError,
)
from raft_tpu.core.metrics import default_registry
from raft_tpu.core.profiler import (
    compile_cache_stats,
    reset_compile_cache_stats,
)
from raft_tpu.comms.resilience import RetryPolicy
from raft_tpu.serve import (
    BucketPolicy,
    KNNService,
    MicroBatcher,
    PairwiseService,
    Service,
    coalesce,
    pad_rows,
    resolve_rungs,
    split_rows,
)
from raft_tpu.spatial.knn import brute_force_knn

pytestmark = pytest.mark.serve

SEED = int(os.environ.get("RAFT_TPU_SERVE_SEED", "1234"))


class FakeClock:
    def __init__(self, t=0.0):
        self.t = float(t)

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt


@pytest.fixture
def rng():
    return np.random.default_rng(SEED)


@pytest.fixture
def index(rng):
    return jnp.asarray(rng.standard_normal((300, 16)), jnp.float32)


def _total_misses():
    return sum(s["misses"] for fn in compile_cache_stats().values()
               for s in fn.values())


# ---------------------------------------------------------------------- #
# bucketing
# ---------------------------------------------------------------------- #
class TestBucketing:
    def test_pow2_rungs_end_at_max(self):
        assert resolve_rungs("pow2", 64) == (8, 16, 32, 64)
        assert resolve_rungs(None, 100) == (8, 16, 32, 64, 100)
        assert resolve_rungs("pow2", 4) == (4,)

    def test_explicit_rungs_sorted_dedup_and_capped(self):
        assert resolve_rungs("16,4,16", 32) == (4, 16, 32)
        assert resolve_rungs([32, 8], 32) == (8, 32)
        with pytest.raises(LogicError):
            resolve_rungs([64], 32)
        with pytest.raises(LogicError):
            resolve_rungs([0, 8], 32)
        with pytest.raises(ValueError):
            resolve_rungs("8,banana", 32)

    def test_bucket_for_boundaries(self):
        p = BucketPolicy((8, 16, 64))
        assert p.bucket_for(1) == 8
        assert p.bucket_for(8) == 8
        assert p.bucket_for(9) == 16
        assert p.bucket_for(17) == 64
        assert p.bucket_for(64) == 64
        assert p.padding_waste(9) == 7
        with pytest.raises(LogicError):
            p.bucket_for(65)
        with pytest.raises(LogicError):
            p.bucket_for(0)

    def test_policy_rejects_bad_ladders(self):
        with pytest.raises(LogicError):
            BucketPolicy((8, 8))
        with pytest.raises(LogicError):
            BucketPolicy(())

    def test_pad_rows(self):
        a = jnp.ones((3, 4))
        p = pad_rows(a, 8)
        assert p.shape == (8, 4)
        assert bool((np.asarray(p[3:]) == 0).all())
        assert pad_rows(a, 3) is a
        with pytest.raises(LogicError):
            pad_rows(a, 2)

    def test_coalesce_split_roundtrip(self, rng):
        blocks = [jnp.asarray(rng.standard_normal((r, 5)), jnp.float32)
                  for r in (3, 1, 7)]
        batch, spans = coalesce(blocks)
        assert batch.shape == (11, 5)
        assert spans == [(0, 3), (3, 4), (4, 11)]
        back = split_rows(batch, spans)
        for orig, rec in zip(blocks, back):
            assert bool((np.asarray(orig) == np.asarray(rec)).all())


# ---------------------------------------------------------------------- #
# batcher (deterministic: FakeClock, no threads)
# ---------------------------------------------------------------------- #
class TestMicroBatcher:
    def make(self, **kw):
        clock = FakeClock()
        kw.setdefault("max_batch_rows", 16)
        kw.setdefault("max_wait_s", 0.010)
        kw.setdefault("queue_cap", 4)
        return MicroBatcher(clock=clock, **kw), clock

    def test_window_holds_then_releases(self):
        b, clock = self.make()
        b.submit("a", 2)
        assert b.take() is None            # window still open
        clock.advance(0.011)
        batch = b.take()
        assert [r.payload for r in batch] == ["a"]
        assert b.empty()

    def test_rows_threshold_dispatches_immediately(self):
        b, _ = self.make()
        b.submit("a", 10)
        assert b.take() is None
        b.submit("b", 6)                   # 16 rows = max_batch_rows
        batch = b.take()
        assert [r.payload for r in batch] == ["a", "b"]

    def test_batch_never_splits_a_request(self):
        b, clock = self.make()
        b.submit("a", 10)
        b.submit("b", 10)                  # 20 rows > 16: b must wait
        clock.advance(0.011)
        assert [r.payload for r in b.take()] == ["a"]
        assert [r.payload for r in b.take()] == ["b"]

    def test_request_rows_capped(self):
        b, _ = self.make()
        with pytest.raises(LogicError):
            b.submit("too-big", 17)
        with pytest.raises(LogicError):
            b.submit("empty", 0)

    def test_admission_cap_sheds(self):
        b, _ = self.make()
        for i in range(4):
            b.submit(i, 1)
        with pytest.raises(ServiceOverloadError) as ei:
            b.submit("over", 1)
        assert ei.value.queue_depth == 4
        assert ei.value.queue_cap == 4

    def test_drain_flushes_and_rejects_new(self):
        b, _ = self.make()
        b.submit("a", 1)
        assert b.take() is None            # window open
        b.begin_drain()
        assert [r.payload for r in b.take()] == ["a"]   # flushed
        with pytest.raises(LogicError):
            b.submit("late", 1)

    def test_shutdown_returns_leftovers(self):
        b, _ = self.make()
        b.submit("a", 1)
        b.submit("b", 2)
        left = b.shutdown()
        assert [r.payload for r in left] == ["a", "b"]
        assert b.wait_for_batch() is None


# ---------------------------------------------------------------------- #
# service: deterministic (threadless) coalesce/split, deadlines, warmup
# ---------------------------------------------------------------------- #
class TestServiceManual:
    def make_knn(self, index, **kw):
        clock = FakeClock()
        kw.setdefault("max_batch_rows", 32)
        kw.setdefault("max_wait_ms", 10.0)
        svc = KNNService(index, k=5, start=False, clock=clock, **kw)
        return svc, clock

    def test_coalesce_split_matches_unbatched(self, index, rng):
        svc, clock = self.make_knn(index)
        blocks = [jnp.asarray(rng.standard_normal((r, 16)), jnp.float32)
                  for r in (3, 1, 9)]
        futs = svc.submit_many(blocks)
        assert not any(f.done() for f in futs)
        clock.advance(0.5)
        assert svc.worker.run_once()
        for q, f in zip(blocks, futs):
            d, i = f.result(timeout=0)
            d0, i0 = brute_force_knn(index, q, 5)
            assert bool((np.asarray(d) == np.asarray(d0)).all())
            assert bool((np.asarray(i) == np.asarray(i0)).all())
        svc.close()

    def test_deadline_expires_in_queue(self, index, rng):
        svc, clock = self.make_knn(index)
        q = jnp.asarray(rng.standard_normal((2, 16)), jnp.float32)
        doomed = svc.submit(q, timeout=0.05)
        alive = svc.submit(q)
        clock.advance(0.1)                 # past deadline AND window
        assert svc.worker.run_once()
        with pytest.raises(CommTimeoutError):
            doomed.result(timeout=0)
        d, i = alive.result(timeout=0)
        assert d.shape == (2, 5)
        svc.close()

    def test_warmup_populates_compile_cache(self, rng):
        # uniquely-shaped index: compiled executables persist across
        # reset_compile_cache_stats (its documented contract), so the
        # miss-counting assertion needs cache keys no earlier test in
        # this process can have compiled
        index = jnp.asarray(rng.standard_normal((317, 16)), jnp.float32)
        svc, clock = self.make_knn(index, bucket_rungs="8,32")
        reset_compile_cache_stats()
        assert svc.warmed_rungs == ()
        svc.warmup()
        assert svc.warmed_rungs == (8, 32)
        m_warm = _total_misses()
        assert m_warm >= len(svc.policy.rungs)
        # steady state: every admissible shape lands on a warmed bucket
        for r in (1, 8, 9, 30, 32):
            fut = svc.submit(
                jnp.asarray(rng.standard_normal((r, 16)), jnp.float32))
            clock.advance(0.5)
            assert svc.worker.run_once()
            fut.result(timeout=0)
        assert _total_misses() == m_warm
        svc.close()

    def test_payload_validation(self, index):
        svc, _ = self.make_knn(index)
        with pytest.raises(LogicError):
            svc.submit(jnp.zeros((2, 7)))  # wrong dim
        with pytest.raises(LogicError):
            svc.submit(jnp.zeros((40, 16)))  # > max_batch_rows
        one = svc.submit(jnp.zeros((16,)))   # 1-D promotes to one row
        svc.close()  # drains: resolves `one`
        assert one.done() and one.exception() is None

    def test_metrics_flow(self, index, rng):
        svc, clock = self.make_knn(index, name="mtest")
        svc.submit(jnp.asarray(rng.standard_normal((3, 16)), jnp.float32))
        clock.advance(0.5)
        svc.worker.run_once()
        reg = default_registry()
        req = reg.get("raft_tpu_serve_requests_total")
        assert req is not None
        vals = {lbl["service"]: s.value for lbl, s in req.series()}
        assert vals.get("mtest", 0) >= 1
        pay = reg.get("raft_tpu_serve_payload_rows_total")
        pad = reg.get("raft_tpu_serve_padded_rows_total")
        pay_v = {lbl["service"]: s.value for lbl, s in pay.series()}
        pad_v = {lbl["service"]: s.value for lbl, s in pad.series()}
        # 3 payload rows padded to the 8-rung: 5 pad rows
        assert pay_v["mtest"] == 3 and pad_v["mtest"] == 5
        bucket = reg.get("raft_tpu_serve_bucket_calls_total")
        bvals = {(lbl["service"], lbl["bucket"]): s.value
                 for lbl, s in bucket.series()}
        assert bvals.get(("mtest", "8")) == 1
        svc.close()


# ---------------------------------------------------------------------- #
# retry / watchdog reuse (PR 1 machinery around the device call)
# ---------------------------------------------------------------------- #
class TestRetryPolicyIntegration:
    def _echo_service(self, **kw):
        clock = FakeClock()
        svc = Service("echo", lambda p: p * 2.0, dim=4, start=False,
                      max_batch_rows=8, max_wait_ms=0.0, clock=clock,
                      **kw)
        return svc, clock

    def test_transient_failure_retried(self):
        calls = {"n": 0}

        def flaky(padded):
            calls["n"] += 1
            if calls["n"] == 1:
                raise RuntimeError("transient")
            return padded * 2.0

        clock = FakeClock()
        svc = Service("flaky", flaky, dim=4, start=False,
                      max_batch_rows=8, max_wait_ms=0.0, clock=clock,
                      retry_policy=RetryPolicy(max_retries=2,
                                               base_delay=0.0,
                                               sleep=lambda s: None))
        fut = svc.submit(jnp.ones((2, 4)))
        assert svc.worker.run_once()
        out = fut.result(timeout=0)
        assert calls["n"] == 2
        assert bool((np.asarray(out) == 2.0).all())
        svc.close()

    def test_failure_without_policy_fails_all_riders(self):
        def boom(padded):
            raise RuntimeError("device gone")

        clock = FakeClock()
        svc = Service("boom", boom, dim=4, start=False,
                      max_batch_rows=8, max_wait_ms=0.0, clock=clock)
        futs = [svc.submit(jnp.ones((1, 4))) for _ in range(2)]
        svc.worker.run_once()
        for f in futs:
            with pytest.raises(RuntimeError, match="device gone"):
                f.result(timeout=0)
        svc.close()

    def test_watchdog_deadline_on_device_call(self):
        def hang(padded):
            time.sleep(0.5)
            return padded

        svc = Service("hang", hang, dim=4, start=False,
                      max_batch_rows=8, max_wait_ms=0.0,
                      retry_policy=RetryPolicy(
                          max_retries=0, timeout=0.05,
                          retry_timeouts=False))
        fut = svc.submit(jnp.ones((1, 4)))
        svc.worker.run_once()
        with pytest.raises(CommTimeoutError):
            fut.result(timeout=0)
        svc.close(drain=False)


# ---------------------------------------------------------------------- #
# threaded: the acceptance scenario + lifecycle + stress
# ---------------------------------------------------------------------- #
class TestThreadedService:
    def test_acceptance_100_concurrent_mixed_shapes(self, index, rng):
        """ISSUE 4 acceptance: warmed service, 100 concurrent
        mixed-shape submits -> zero post-warmup compiles, bit-identical
        results, over-cap load sheds with ServiceOverloadError."""
        svc = KNNService(index, k=5, max_batch_rows=64,
                         max_wait_ms=1.0, queue_cap=256)
        rows = [int(r) for r in rng.integers(1, 33, size=100)]
        blocks = [jnp.asarray(rng.standard_normal((r, 16)), jnp.float32)
                  for r in rows]
        # baselines FIRST: they compile unbatched-shape executables
        # that must not count against the service's steady state
        baselines = [brute_force_knn(index, q, 5) for q in blocks]
        reset_compile_cache_stats()
        svc.warmup()
        m_warm = _total_misses()

        futs = [None] * len(blocks)
        errors = []

        def submitter(i):
            try:
                futs[i] = svc.submit(blocks[i])
            except Exception as e:  # noqa: BLE001 — collected
                errors.append(e)

        threads = [threading.Thread(target=submitter, args=(i,))
                   for i in range(len(blocks))]
        for t in threads:
            t.start()
        for t in threads:
            t.join(30)
        assert not errors
        for (d0, i0), fut in zip(baselines, futs):
            d, i = fut.result(timeout=30)
            assert bool((np.asarray(d) == np.asarray(d0)).all())
            assert bool((np.asarray(i) == np.asarray(i0)).all())
        assert _total_misses() == m_warm, \
            "post-warmup traffic must be compile-free"

        # over-cap load sheds: stall admission by flooding a tiny-cap
        # service whose worker never runs
        svc.close()
        stalled = KNNService(index, k=5, max_batch_rows=64,
                             max_wait_ms=1000.0, queue_cap=8,
                             start=False)
        for _ in range(8):
            stalled.submit(blocks[0])
        with pytest.raises(ServiceOverloadError):
            stalled.submit(blocks[0])
        stalled.close()

    def test_drain_then_close_idempotent(self, index, rng):
        svc = KNNService(index, k=5, max_batch_rows=64,
                         max_wait_ms=200.0)  # long window: drain flushes
        futs = svc.submit_many(
            [jnp.asarray(rng.standard_normal((3, 16)), jnp.float32)
             for _ in range(5)])
        assert svc.drain(timeout=30)
        for f in futs:
            assert f.done() and f.exception() is None
        with pytest.raises(LogicError):
            svc.submit(jnp.zeros((1, 16)))
        svc.close()
        svc.close()                        # idempotent
        assert not svc.is_open()

    def test_close_without_drain_fails_pending(self, index, rng):
        svc = KNNService(index, k=5, max_batch_rows=64,
                         max_wait_ms=60_000.0, start=False)
        fut = svc.submit(
            jnp.asarray(rng.standard_normal((2, 16)), jnp.float32))
        svc.close(drain=False)
        with pytest.raises(CommTimeoutError):
            fut.result(timeout=0)

    def test_concurrent_submitter_stress(self, index, rng):
        svc = KNNService(index, k=3, max_batch_rows=64,
                         max_wait_ms=0.5, queue_cap=2048)
        svc.warmup()
        n_threads, per_thread = 16, 20
        results = [[] for _ in range(n_threads)]
        errors = []

        def client(tid):
            trng = np.random.default_rng(SEED + tid)
            try:
                for _ in range(per_thread):
                    q = jnp.asarray(
                        trng.standard_normal((int(trng.integers(1, 9)),
                                              16)), jnp.float32)
                    results[tid].append(
                        (q, svc.submit(q).result(timeout=30)))
            except Exception as e:  # noqa: BLE001 — collected
                errors.append(e)

        threads = [threading.Thread(target=client, args=(t,))
                   for t in range(n_threads)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(60)
        assert not errors
        assert sum(len(r) for r in results) == n_threads * per_thread
        for tid in range(n_threads):
            for q, (d, i) in results[tid]:
                d0, i0 = brute_force_knn(index, q, 3)
                assert bool((np.asarray(d) == np.asarray(d0)).all())
        svc.close()


# ---------------------------------------------------------------------- #
# pairwise service
# ---------------------------------------------------------------------- #
class TestPairwiseService:
    def test_roundtrip(self, rng):
        from raft_tpu.distance.pairwise import pairwise_distance

        Y = jnp.asarray(rng.standard_normal((80, 8)), jnp.float32)
        svc = PairwiseService(Y, max_batch_rows=32, max_wait_ms=1.0)
        svc.warmup()
        x = jnp.asarray(rng.standard_normal((5, 8)), jnp.float32)
        out = svc.submit(x).result(timeout=30)
        assert out.shape == (5, 80)
        ref = pairwise_distance(x, Y)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=1e-5, atol=1e-5)
        svc.close()


# ---------------------------------------------------------------------- #
# query-vector cache (VecCache wiring)
# ---------------------------------------------------------------------- #
class TestQueryCache:
    def make(self, index):
        clock = FakeClock()
        svc = KNNService(index, k=5, start=False, clock=clock,
                         max_batch_rows=32, max_wait_ms=10.0,
                         query_cache_size=64, name="qc%d" % SEED)
        return svc, clock

    def test_put_lookup_counters(self, index, rng):
        svc, _ = self.make(index)
        keys = jnp.asarray([3, 9, 40], jnp.int32)
        vecs = jnp.asarray(rng.standard_normal((3, 16)), jnp.float32)
        svc.cache_put(keys, vecs)
        got, found = svc.cache_lookup(jnp.asarray([3, 9, 40, 7]))
        assert bool(found[:3].all()) and not bool(found[3])
        assert bool((np.asarray(got[:3]) == np.asarray(vecs)).all())
        reg = default_registry()
        hits = {lbl["service"]: s.value for lbl, s in reg.get(
            "raft_tpu_serve_query_cache_hits_total").series()}
        misses = {lbl["service"]: s.value for lbl, s in reg.get(
            "raft_tpu_serve_query_cache_misses_total").series()}
        assert hits[svc.name] == 3 and misses[svc.name] == 1
        svc.close()

    def test_submit_keys_equals_submit_vectors(self, index, rng):
        svc, clock = self.make(index)
        keys = jnp.asarray([1, 2, 5], jnp.int32)
        vecs = jnp.asarray(rng.standard_normal((3, 16)), jnp.float32)
        svc.cache_put(keys, vecs)
        fut = svc.submit_keys(keys)
        clock.advance(0.5)
        assert svc.worker.run_once()
        d, i = fut.result(timeout=0)
        d0, i0 = brute_force_knn(index, vecs, 5)
        assert bool((np.asarray(d) == np.asarray(d0)).all())
        assert bool((np.asarray(i) == np.asarray(i0)).all())
        svc.close()

    def test_missing_key_raises_naming_it(self, index, rng):
        svc, _ = self.make(index)
        svc.cache_put(jnp.asarray([1], jnp.int32),
                      jnp.asarray(rng.standard_normal((1, 16)),
                                  jnp.float32))
        with pytest.raises(LogicError, match="77"):
            svc.submit_keys(jnp.asarray([1, 77], jnp.int32))
        svc.close()

    def test_cache_requires_opt_in(self, index):
        clock = FakeClock()
        svc = KNNService(index, k=5, start=False, clock=clock,
                         max_batch_rows=32)
        with pytest.raises(LogicError):
            svc.submit_keys(jnp.asarray([1], jnp.int32))
        with pytest.raises(LogicError):
            svc.cache_put(jnp.asarray([-1], jnp.int32),
                          jnp.zeros((1, 16)))
        svc.close()


# ---------------------------------------------------------------------- #
# config knobs
# ---------------------------------------------------------------------- #
class TestServeKnobs:
    @pytest.fixture(autouse=True)
    def _reset(self):
        yield
        config.configure(serve_bucket_rungs=None, serve_max_wait_ms=None,
                         serve_queue_cap=None)

    def test_defaults_resolve(self):
        assert config.get("serve_bucket_rungs") == "pow2"
        assert float(config.get("serve_max_wait_ms")) == 2.0
        assert int(config.get("serve_queue_cap")) == 1024

    def test_knobs_feed_service_defaults(self, index):
        config.configure(serve_bucket_rungs="8,16",
                         serve_max_wait_ms="7.5", serve_queue_cap="2")
        svc = KNNService(index, k=5, start=False, max_batch_rows=16)
        assert svc.policy.rungs == (8, 16)
        assert svc.batcher.max_wait_s == pytest.approx(0.0075)
        assert svc.batcher.queue_cap == 2
        svc.submit(jnp.zeros((1, 16)))
        svc.submit(jnp.zeros((1, 16)))
        with pytest.raises(ServiceOverloadError):
            svc.submit(jnp.zeros((1, 16)))
        svc.close()

    def test_bad_numeric_knob_surfaces(self, index):
        # typed knob parse (config.get_float): LogicError naming the
        # knob AND its env var — was a bare ValueError before the
        # autotuner PR's typed-parse satellite
        from raft_tpu.core.error import LogicError

        config.configure(serve_max_wait_ms="fast")
        with pytest.raises(LogicError, match="serve_max_wait_ms"):
            KNNService(index, k=5, start=False)


# ---------------------------------------------------------------------- #
# session integration (incl. the destroy-drains-services bugfix)
# ---------------------------------------------------------------------- #
class TestSessionServe:
    def test_serve_requires_initialized(self):
        from raft_tpu.session import Comms

        s = Comms()
        with pytest.raises(LogicError):
            s.serve("knn", index=jnp.zeros((10, 4)), k=2)

    def test_serve_registers_and_destroy_drains(self, index, rng):
        from raft_tpu.session import Comms

        s = Comms().init()
        try:
            svc = s.serve("knn", index=index, k=5, max_batch_rows=64,
                          max_wait_ms=60_000.0, name="sess-knn")
            assert "sess-knn" in s.services
            with pytest.raises(LogicError):
                s.serve("knn", index=index, k=5, name="sess-knn")
            futs = svc.submit_many(
                [jnp.asarray(rng.standard_normal((2, 16)), jnp.float32)
                 for _ in range(4)])
            # the batching window is a minute long: only destroy's
            # drain-before-teardown can resolve these
            assert not any(f.done() for f in futs)
        finally:
            s.destroy()
        for f in futs:
            assert f.done() and f.exception() is None
        assert not svc.is_open()
        assert not svc.worker.is_alive()
        assert s.services == {}
        s.destroy()                        # idempotent

    def test_health_check_covers_services(self, index):
        from raft_tpu.session import Comms

        s = Comms().init()
        try:
            svc = s.serve("knn", index=index, k=5, max_batch_rows=32,
                          name="hc-knn")
            report = s.health_check()
            assert report["services"]["hc-knn"]["worker_alive"]
            assert report["services"]["hc-knn"]["open"]
            assert report["services"]["hc-knn"]["rungs"] == [8, 16, 32]
            assert report["ok"]
            svc.close()
            report2 = s.health_check()
            assert report2["services"]["hc-knn"]["open"] is False
            assert report2["ok"]           # closed-on-purpose passes
        finally:
            s.destroy()


# ---------------------------------------------------------------------- #
# CI hygiene: the raw-Thread ban
# ---------------------------------------------------------------------- #
class TestThreadBan:
    def _check(self, tmp_path, relpath, src, monkeypatch):
        import importlib.util

        spec = importlib.util.spec_from_file_location(
            "style_check", os.path.join(os.path.dirname(__file__),
                                        "..", "ci", "style_check.py"))
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        monkeypatch.setattr(mod, "REPO", str(tmp_path))
        path = tmp_path / relpath
        path.parent.mkdir(parents=True, exist_ok=True)
        path.write_text(src)
        return mod.check_file(str(path))

    def test_raw_thread_outside_serve_flagged(self, tmp_path,
                                              monkeypatch):
        src = ("import threading\n"
               "t = threading.Thread(target=print)\n")
        probs = self._check(tmp_path, "raft_tpu/spatial/bad.py", src,
                            monkeypatch)
        assert any("threading.Thread" in p for p in probs)
        probs = self._check(
            tmp_path, "raft_tpu/spatial/bad2.py",
            "from threading import Thread\n", monkeypatch)
        assert any("threading.Thread" in p for p in probs)

    def test_serve_and_resilience_allowlisted(self, tmp_path,
                                              monkeypatch):
        src = ("import threading\n"
               "t = threading.Thread(target=print)\n")
        assert self._check(tmp_path, "raft_tpu/serve/ok.py", src,
                           monkeypatch) == []
        assert self._check(tmp_path, "raft_tpu/comms/resilience.py",
                           src, monkeypatch) == []


# ---------------------------------------------------------------------- #
# zero-copy serve path: donation + overlapped dispatch (docs/ZERO_COPY.md)
# ---------------------------------------------------------------------- #
class TestZeroCopyServe:
    def test_donate_defaults_and_retry_forces_off(self, index):
        svc = KNNService(index, k=3, start=False)
        assert svc.donate is True            # on when no retry policy
        assert svc.worker.donate is True
        svc.close()
        policy = RetryPolicy(max_retries=1, timeout=30.0)
        svc = KNNService(index, k=3, start=False, retry_policy=policy)
        assert svc.donate is False           # a retry could replay a
        assert svc.worker.donate is False   # consumed buffer
        svc.close()
        svc = KNNService(index, k=3, start=False, donate=True,
                         retry_policy=policy)
        assert svc.donate is False           # explicit opt-in loses too
        svc.close()
        svc = KNNService(index, k=3, start=False, donate=False)
        assert svc.donate is False           # opt-out respected
        svc.close()

    def test_donating_batch_matches_unbatched_and_spares_callers(
            self, index, rng):
        """Donation consumes the PADDED buffer, never a caller's
        submitted array: every submitted block must survive the batch
        (resubmittable) and results stay bit-identical to unbatched."""
        clock = FakeClock()
        svc = KNNService(index, k=5, start=False, clock=clock,
                         max_batch_rows=32, max_wait_ms=10.0)
        assert svc.donate
        blocks = [jnp.asarray(rng.standard_normal((r, 16)), jnp.float32)
                  for r in (3, 7, 2)]
        futs = svc.submit_many(blocks)
        clock.advance(0.5)
        assert svc.worker.run_once()
        for q, f in zip(blocks, futs):
            assert not q.is_deleted()        # caller array survived
            d, i = f.result(timeout=0)
            d0, i0 = brute_force_knn(index, q, 5)
            assert bool((np.asarray(d) == np.asarray(d0)).all())
            assert bool((np.asarray(i) == np.asarray(i0)).all())
        # round 2 resubmits the SAME arrays — a consumed caller buffer
        # would throw here
        futs = svc.submit_many(blocks)
        clock.advance(0.5)
        assert svc.worker.run_once()
        for f in futs:
            f.result(timeout=0)
        svc.close()

    def test_donate_aliasing_rung_sized_request_copies(self, index,
                                                       rng):
        """The one case where pad/coalesce is the identity — a single
        request exactly rung-sized — must pay the defensive copy, not
        donate the caller's array out from under them."""
        clock = FakeClock()
        svc = KNNService(index, k=5, start=False, clock=clock,
                         bucket_rungs="8,32", max_batch_rows=32,
                         max_wait_ms=10.0)
        q = jnp.asarray(rng.standard_normal((8, 16)), jnp.float32)
        fut = svc.submit(q)                  # exactly the 8-rung
        clock.advance(0.5)
        assert svc.worker.run_once()
        fut.result(timeout=0)
        assert not q.is_deleted()
        d, i = brute_force_knn(index, q, 5)  # still readable
        assert np.asarray(d).shape == (8, 5)
        svc.close()

    def test_pad_tail_reuses_zeros_cache(self, rng):
        from raft_tpu.mr import default_zeros_pool

        pool = default_zeros_pool()
        a = jnp.asarray(rng.standard_normal((3, 16)), jnp.float32)
        p1 = pad_rows(a, 8)
        h0, m0 = pool.n_hits, pool.n_misses
        b = jnp.asarray(rng.standard_normal((3, 16)), jnp.float32)
        p2 = pad_rows(b, 8)                  # same (5, 16) tail shape
        assert pool.n_hits == h0 + 1 and pool.n_misses == m0
        # fresh storage out (the donation precondition), zero tails
        assert p2 is not b
        np.testing.assert_array_equal(np.asarray(p1[3:]),
                                      np.zeros((5, 16), np.float32))
        assert pad_rows(a, 3) is a           # no-pad identity unchanged

    def test_overlapped_loop_sustained_load_exact(self, index, rng):
        """The pipelined worker loop (batch N+1 forms while N runs on
        device) under sustained threaded load: every result exact,
        every future resolved, zero post-warmup compiles with the
        donating executables."""
        svc = KNNService(index, k=5, max_batch_rows=64, max_wait_ms=0.5,
                         queue_cap=4096)
        assert svc.donate
        rows = [int(r) for r in rng.integers(1, 33, size=60)]
        blocks = [jnp.asarray(rng.standard_normal((r, 16)), jnp.float32)
                  for r in rows]
        baselines = [brute_force_knn(index, q, 5) for q in blocks]
        reset_compile_cache_stats()
        svc.warmup()
        m_warm = _total_misses()
        # bursts keep the queue non-empty so the loop actually takes
        # the overlap branch (batcher.take() finds a ready batch while
        # one is in flight)
        futs = []
        for start in range(0, len(blocks), 12):
            futs.extend(svc.submit_many(blocks[start:start + 12]))
        for (d0, i0), fut in zip(baselines, futs):
            d, i = fut.result(timeout=30)
            assert bool((np.asarray(d) == np.asarray(d0)).all())
            assert bool((np.asarray(i) == np.asarray(i0)).all())
        assert _total_misses() == m_warm
        for q in blocks:
            assert not q.is_deleted()
        svc.close()
