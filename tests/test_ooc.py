"""Out-of-core index tier (docs/SERVING.md "Out-of-core serving",
docs/ZERO_COPY.md §6): streamed-search identity against the resident
path across every arm (hot/cold mix, cold-only, synchronous prefetch,
delta merge, sqrt metrics), host-side extend/reconstruct, the
``ANNService(ooc=...)`` integration (served identity, zero
post-warmup compiles, budget enforcement, hot-set promotion,
compaction, recovery), the loadgen ``--ooc`` report shape, and the
``ci/style_check.py`` whole-index ``jax.device_put`` ban self-tests.
"""

import os
import time

import numpy as np
import pytest

import jax.numpy as jnp

from raft_tpu.core.error import LogicError
from raft_tpu.core.metrics import default_registry
from raft_tpu.core.profiler import compile_cache_stats
from raft_tpu.mr import TilePool
from raft_tpu.serve import ANNService
from raft_tpu.spatial import ann
from raft_tpu.spatial.knn import brute_force_knn
from raft_tpu.spatial.ooc import (
    OocIVFFlat,
    ivf_flat_to_ooc,
    materialize_hot,
    ooc_extend,
    ooc_ivf_flat_search,
    ooc_reconstruct,
)

SEED = int(os.environ.get("RAFT_TPU_SERVE_SEED", "1234"))


@pytest.fixture
def rng():
    return np.random.default_rng(SEED)


@pytest.fixture
def flat_index(rng):
    X = jnp.asarray(rng.standard_normal((2500, 24)), jnp.float32)
    return ann.ivf_flat_build(X, ann.IVFFlatParams(nlist=24, nprobe=6),
                              seed=SEED)


def _total_misses():
    return sum(s["misses"] for fn in compile_cache_stats().values()
               for s in fn.values())


def _pool(ooc, name, tiles=10):
    return TilePool(4, tiles * 4 * (ooc.slot_bytes() + 4), name=name)


def _pool_total(name, pool_name, attr="value"):
    fam = default_registry().get(name)
    if fam is None:
        return 0.0
    for labels, series in fam.series():
        if labels.get("pool") == pool_name:
            return float(getattr(series, attr))
    return 0.0


# ---------------------------------------------------------------------- #
# search identity
# ---------------------------------------------------------------------- #
class TestOocSearchIdentity:
    def _assert_identical(self, got, want):
        assert bool((np.asarray(got[1]) == np.asarray(want[1])).all())
        assert bool((np.asarray(got[0]) == np.asarray(want[0])).all())

    def test_cold_only_matches_resident(self, flat_index, rng):
        ooc = ivf_flat_to_ooc(flat_index)
        q = jnp.asarray(rng.standard_normal((9, 24)), jnp.float32)
        want = ann.ivf_flat_search(flat_index, q, 10)
        got = ooc_ivf_flat_search(ooc, q, 10,
                                  pool=_pool(ooc, "id-cold"))
        self._assert_identical(got, want)

    def test_hot_plus_cold_matches_resident(self, flat_index, rng):
        ooc = ivf_flat_to_ooc(flat_index)
        hot = materialize_hot(ooc, np.arange(min(6, ooc.n_slots)),
                              pool_name="id-hot")
        q = jnp.asarray(rng.standard_normal((9, 24)), jnp.float32)
        want = ann.ivf_flat_search(flat_index, q, 10)
        got = ooc_ivf_flat_search(ooc, q, 10,
                                  pool=_pool(ooc, "id-hot"), hot=hot)
        self._assert_identical(got, want)

    def test_all_hot_no_streaming(self, flat_index, rng):
        """Budget >= store: everything hot, the pool never streams."""
        ooc = ivf_flat_to_ooc(flat_index)
        hot = materialize_hot(ooc, np.arange(ooc.n_slots),
                              pool_name="id-allhot")
        pool = _pool(ooc, "id-allhot")
        q = jnp.asarray(rng.standard_normal((5, 24)), jnp.float32)
        want = ann.ivf_flat_search(flat_index, q, 10)
        got = ooc_ivf_flat_search(ooc, q, 10, pool=pool, hot=hot)
        self._assert_identical(got, want)
        assert pool.n_staged == 0

    def test_sync_arm_matches_overlap(self, flat_index, rng):
        ooc = ivf_flat_to_ooc(flat_index)
        q = jnp.asarray(rng.standard_normal((7, 24)), jnp.float32)
        a = ooc_ivf_flat_search(ooc, q, 10, pool=_pool(ooc, "id-ov"),
                                overlap=True)
        b = ooc_ivf_flat_search(ooc, q, 10, pool=_pool(ooc, "id-sy"),
                                overlap=False)
        self._assert_identical(a, b)

    def test_delta_merge_matches_resident(self, flat_index, rng):
        ooc = ivf_flat_to_ooc(flat_index)
        dv = jnp.asarray(rng.standard_normal((8, 24)), jnp.float32)
        di = jnp.asarray(
            np.array([9000, 9001, 9002, -1, -1, -1, -1, -1], np.int32))
        q = jnp.asarray(rng.standard_normal((6, 24)), jnp.float32)
        want = ann.ivf_flat_search(flat_index, q, 10, delta=(dv, di))
        got = ooc_ivf_flat_search(ooc, q, 10,
                                  pool=_pool(ooc, "id-delta"),
                                  delta=(dv, di))
        self._assert_identical(got, want)

    def test_sqrt_metric(self, rng):
        from raft_tpu.distance.distance_type import DistanceType

        X = jnp.asarray(rng.standard_normal((1200, 16)), jnp.float32)
        idx = ann.ivf_flat_build(
            X, ann.IVFFlatParams(nlist=12, nprobe=4),
            metric=DistanceType.L2SqrtExpanded, seed=SEED)
        ooc = ivf_flat_to_ooc(idx)
        q = jnp.asarray(rng.standard_normal((4, 16)), jnp.float32)
        want = ann.ivf_flat_search(idx, q, 5)
        got = ooc_ivf_flat_search(ooc, q, 5, pool=_pool(ooc, "id-sq"))
        self._assert_identical(got, want)

    def test_select_impl_approx_membership(self, flat_index, rng):
        """The per-service approx select pin: membership-exact against
        the resident path under the same pin (the serve_ann config)."""
        ooc = ivf_flat_to_ooc(flat_index)
        q = jnp.asarray(rng.standard_normal((6, 24)), jnp.float32)
        want = ann.ivf_flat_search(flat_index, q, 10,
                                   select_impl="approx")
        got = ooc_ivf_flat_search(ooc, q, 10,
                                  pool=_pool(ooc, "id-ap"),
                                  select_impl="approx")
        assert (set(np.asarray(got[1]).ravel().tolist())
                == set(np.asarray(want[1]).ravel().tolist()))

    def test_force_rounds_is_result_noop(self, flat_index, rng):
        ooc = ivf_flat_to_ooc(flat_index)
        hot = materialize_hot(ooc, np.arange(ooc.n_slots),
                              pool_name="id-fr")
        pool = _pool(ooc, "id-fr")
        q = jnp.asarray(rng.standard_normal((5, 24)), jnp.float32)
        want = ooc_ivf_flat_search(ooc, q, 10, pool=pool, hot=hot)
        got = ooc_ivf_flat_search(ooc, q, 10, pool=pool, hot=hot,
                                  force_rounds=2)
        self._assert_identical(got, want)
        assert pool.n_staged == 2          # the forced empty tiles

    def test_nprobe_validation(self, flat_index):
        ooc = ivf_flat_to_ooc(flat_index)
        with pytest.raises(LogicError, match="nprobe"):
            ooc_ivf_flat_search(ooc, jnp.zeros((2, 24)), 5, nprobe=0,
                                pool=_pool(ooc, "id-np"))

    def test_tile_hit_miss_accounting(self, flat_index, rng):
        ooc = ivf_flat_to_ooc(flat_index)
        hot = materialize_hot(ooc, np.arange(ooc.n_slots // 2),
                              pool_name="id-acct")
        pool = _pool(ooc, "id-acct")
        h0 = _pool_total("raft_tpu_tile_hits_total", "id-acct")
        m0 = _pool_total("raft_tpu_tile_misses_total", "id-acct")
        q = jnp.asarray(rng.standard_normal((8, 24)), jnp.float32)
        ooc_ivf_flat_search(ooc, q, 10, pool=pool, hot=hot,
                            nprobe=int(ooc.centroids.shape[0]))
        hits = _pool_total("raft_tpu_tile_hits_total", "id-acct") - h0
        miss = _pool_total("raft_tpu_tile_misses_total",
                           "id-acct") - m0
        # full probe touches every non-empty slot exactly once
        n_live = int((np.asarray(ooc.slot_ids[:, 0]) >= 0).sum())
        assert hits + miss == n_live
        assert hits > 0 and miss > 0


# ---------------------------------------------------------------------- #
# host-side extend / reconstruct
# ---------------------------------------------------------------------- #
class TestOocExtend:
    def test_reconstruct_roundtrip(self, flat_index):
        ooc = ivf_flat_to_ooc(flat_index)
        vecs_r, ids_r = ann.ivf_flat_reconstruct(flat_index)
        vecs_o, ids_o = ooc_reconstruct(ooc)
        np.testing.assert_array_equal(ids_o, ids_r)
        np.testing.assert_array_equal(vecs_o, vecs_r)

    def test_extend_matches_resident_extend(self, flat_index, rng):
        """ooc_extend and ivf_flat_extend share the layout helper, so
        the rebuilt stores must hold the same rows in the same slots —
        checked content-wise through reconstruction and search."""
        new_v = rng.standard_normal((40, 24)).astype(np.float32)
        new_i = np.arange(50_000, 50_040)
        resident = ann.ivf_flat_extend(flat_index, new_v, new_i,
                                       slot_multiple=16)
        ooc = ooc_extend(ivf_flat_to_ooc(flat_index), new_v, new_i,
                         slot_multiple=16)
        np.testing.assert_array_equal(
            np.asarray(ooc.slot_ids), np.asarray(resident.slot_ids))
        np.testing.assert_array_equal(
            ooc.store, np.asarray(resident.slot_vecs))
        q = jnp.asarray(rng.standard_normal((6, 24)), jnp.float32)
        want = ann.ivf_flat_search(resident, q, 10)
        got = ooc_ivf_flat_search(ooc, q, 10,
                                  pool=_pool(ooc, "ex-search"))
        assert bool((np.asarray(got[1]) == np.asarray(want[1])).all())

    def test_extend_never_devices_the_store(self, flat_index, rng):
        ooc = ooc_extend(ivf_flat_to_ooc(flat_index),
                         rng.standard_normal((8, 24)).astype(np.float32),
                         np.arange(60_000, 60_008))
        assert isinstance(ooc.store, np.ndarray)
        assert isinstance(ooc, OocIVFFlat)


# ---------------------------------------------------------------------- #
# ANNService(ooc=...)
# ---------------------------------------------------------------------- #
def make_ooc_svc(index, *, budget_frac=0.3, start=False, **kw):
    store_bytes = int(np.asarray(index.slot_vecs).nbytes) \
        if isinstance(index, ann.IVFFlatIndex) else index.store_bytes()
    kw.setdefault("device_budget_bytes",
                  max(1, int(store_bytes * budget_frac)))
    kw.setdefault("max_batch_rows", 32)
    kw.setdefault("bucket_rungs", (8, 32))
    kw.setdefault("max_wait_ms", 1.0)
    kw.setdefault("nprobe_ladder", (4, 8))
    kw.setdefault("delta_cap", 64)
    kw.setdefault("compact_rows", 0)
    return ANNService(index, k=10, ooc=True, start=start, **kw)


def _step(svc, fut, timeout=10.0):
    t0 = time.monotonic()
    while not fut.done():
        svc.worker.run_once()
        if fut.done():
            break
        if time.monotonic() - t0 > timeout:
            raise AssertionError("future did not resolve")
        time.sleep(0.002)
    return fut.result(timeout=0)


@pytest.mark.serve
class TestOocService:
    def test_served_identity_and_zero_compiles(self, flat_index, rng):
        svc = make_ooc_svc(flat_index)
        svc.warmup()
        m0 = _total_misses()
        for _ in range(3):
            q = jnp.asarray(rng.standard_normal((6, 24)), jnp.float32)
            d, i = _step(svc, svc.submit(q))
            d0, i0 = ann.ivf_flat_search(flat_index, q, 10)
            assert bool((np.asarray(i) == np.asarray(i0)).all())
            assert bool((np.asarray(d) == np.asarray(d0)).all())
        assert _total_misses() == m0, "post-warmup compile on ooc path"
        svc.close()

    def test_budget_never_exceeded(self, flat_index, rng):
        svc = make_ooc_svc(flat_index)
        svc.warmup()
        for _ in range(4):
            q = jnp.asarray(rng.standard_normal((8, 24)), jnp.float32)
            _step(svc, svc.submit(q))
        st = svc.stats()["ooc"]
        hot_bytes = st["hot_slots"] * svc._ooc.slot_bytes()
        staged_hw = _pool_total("raft_tpu_tile_staged_bytes", svc.name,
                                "high_water")
        assert hot_bytes + staged_hw <= st["budget_bytes"] * 1.001
        assert st["store_bytes"] > st["budget_bytes"]  # oversubscribed
        svc.close()

    def test_insert_visible_and_compaction_exact(self, flat_index,
                                                 rng):
        svc = make_ooc_svc(flat_index)
        svc.warmup()
        probe = rng.standard_normal((2, 24)).astype(np.float32) * 0.01
        svc.insert([77000, 77001], probe)
        d, i = _step(svc, svc.submit(np.zeros((1, 24), np.float32)))
        assert 77000 in set(np.asarray(i).ravel().tolist())
        assert svc.compact() is True
        assert svc.delta_rows == 0
        d2, i2 = _step(svc, svc.submit(np.zeros((1, 24), np.float32)))
        assert 77000 in set(np.asarray(i2).ravel().tolist())
        # post-compaction exactness: full probe must equal brute force
        # over the reconstructed store (no rows lost in the swap)
        vecs, ids = svc.ground_truth_store()
        q = jnp.asarray(rng.standard_normal((4, 24)), jnp.float32)
        _, gt_rows = brute_force_knn(jnp.asarray(vecs), q, 10)
        gt = ids[np.asarray(gt_rows)]
        svc.set_nprobe(int(svc._nlist))
        _, i4 = _step(svc, svc.submit(q))
        assert bool((np.asarray(i4) == gt).all())
        svc.close()

    def test_promotion_moves_hot_set(self, flat_index, rng):
        svc = make_ooc_svc(flat_index, budget_frac=0.25,
                           ooc_promote_batches=2)
        svc.warmup()
        ev0 = _pool_total("raft_tpu_tile_evictions_total", svc.name)
        hot_before = svc._ooc_hot_ids.copy()
        # concentrate traffic on one region of the data so the
        # measured top-H diverges from the list-size seeding
        base = np.asarray(ann.ivf_flat_reconstruct(flat_index)[0][:4])
        q = jnp.asarray(base + 0.01, jnp.float32)
        for _ in range(8):
            _step(svc, svc.submit(q))
            svc.worker.run_maintenance()
        assert not np.array_equal(hot_before, svc._ooc_hot_ids)
        assert _pool_total("raft_tpu_tile_evictions_total",
                           svc.name) > ev0
        # promotion swapped content, not shape: still zero compiles
        m0 = _total_misses()
        d, i = _step(svc, svc.submit(q))
        assert _total_misses() == m0
        d0, i0 = ann.ivf_flat_search(flat_index, q, 10)
        assert bool((np.asarray(i) == np.asarray(i0)).all())
        svc.close()

    def test_ooc_rejects_bad_combinations(self, flat_index, rng):
        with pytest.raises(LogicError, match="budget"):
            ANNService(flat_index, k=5, ooc=True, start=False)
        with pytest.raises(LogicError, match="refine_ratio"):
            make_ooc_svc(flat_index, refine_ratio=4)
        X = jnp.asarray(rng.standard_normal((600, 16)), jnp.float32)
        pq = ann.ivf_pq_build(X, ann.IVFPQParams(nlist=8, M=4),
                              seed=SEED)
        with pytest.raises(LogicError, match="IVF-Flat"):
            ANNService(pq, k=5, ooc=True, device_budget_bytes=1 << 20,
                       start=False)
        # ooc-only knobs on a resident service: error, not silent no-op
        with pytest.raises(LogicError, match="out-of-core"):
            ANNService(flat_index, k=5,
                       device_budget_bytes=1 << 20, start=False)

    def test_ooc_index_object_implies_ooc(self, flat_index):
        ooc = ivf_flat_to_ooc(flat_index)
        svc = make_ooc_svc(ooc)
        assert svc.stats()["ooc"]["store_bytes"] == ooc.store_bytes()
        assert svc.stats()["kind"] == "OocIVFFlat"
        svc.close()

    def test_post_recover_republishes_hot_set(self, flat_index, rng):
        svc = make_ooc_svc(flat_index)
        svc.warmup()
        q = jnp.asarray(rng.standard_normal((4, 24)), jnp.float32)
        want = _step(svc, svc.submit(q))
        svc.post_recover()
        got = _step(svc, svc.submit(q))
        assert bool((np.asarray(got[1])
                     == np.asarray(want[1])).all())
        assert svc.stats()["ooc"]["hot_slots"] > 0
        svc.close()

    def test_calibrate_over_ooc_store(self, flat_index, rng):
        svc = make_ooc_svc(flat_index, nprobe_ladder=(2, 24))
        svc.warmup()
        q = jnp.asarray(rng.standard_normal((16, 24)), jnp.float32)
        cal = svc.calibrate(q, target_recall=1.0, measure_all=False)
        assert cal["met_target"]
        assert cal["chosen_nprobe"] <= 24
        svc.close()

    def test_loadgen_ooc_report(self, rng):
        from tools.loadgen import build_service, run_load

        svc = build_service("ann", 3000, 16, 10, seed=SEED,
                            clusters=16, nlist=16, ooc=True,
                            max_batch_rows=32,
                            bucket_rungs=(8, 32), nprobe=16)
        svc.warmup()
        try:
            rep = run_load(svc, mode="closed", duration=1.0,
                           concurrency=2, rows=4, recall=True)
        finally:
            svc.close()
        # full probe (nprobe == nlist): the streamed tier is exact
        assert rep["recall_at_k"] == 1.0
        assert rep["post_warmup_compiles"] == 0
        assert rep["host_staged_bytes"] == 0
        assert 0.0 <= rep["tile_hit_rate"] <= 1.0
        assert "hidden_transfer_frac" in rep and "h2d_mb" in rep


# ---------------------------------------------------------------------- #
# CI hygiene: the whole-index device_put ban
# ---------------------------------------------------------------------- #
class TestOocDevicePutBan:
    def _check(self, tmp_path, relpath, src, monkeypatch):
        import importlib.util

        spec = importlib.util.spec_from_file_location(
            "style_check", os.path.join(os.path.dirname(__file__),
                                        "..", "ci", "style_check.py"))
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        monkeypatch.setattr(mod, "REPO", str(tmp_path))
        path = tmp_path / relpath
        path.parent.mkdir(parents=True, exist_ok=True)
        path.write_text(src)
        return mod.check_file(str(path))

    def test_device_put_flagged_in_ooc_path(self, tmp_path,
                                            monkeypatch):
        src = ("import jax\n"
               "def f(store):\n"
               "    return jax.device_put(store)\n")
        for rel in ("raft_tpu/spatial/ooc.py",
                    "raft_tpu/mr/tile_pool.py"):
            probs = self._check(tmp_path, rel, src, monkeypatch)
            assert any("device_put" in p for p in probs), rel

    def test_marker_and_alias_and_from_import(self, tmp_path,
                                              monkeypatch):
        ok = ("import jax\n"
              "def f(tile):\n"
              "    return jax.device_put(tile)  # ooc-resident-ok\n")
        assert self._check(tmp_path, "raft_tpu/spatial/ooc.py", ok,
                           monkeypatch) == []
        alias = ("import jax as j\n"
                 "def f(store):\n"
                 "    return j.device_put(store)\n")
        assert any("device_put" in p for p in self._check(
            tmp_path, "raft_tpu/spatial/ooc.py", alias, monkeypatch))
        imp = "from jax import device_put\n"
        assert any("device_put" in p for p in self._check(
            tmp_path, "raft_tpu/mr/tile_pool.py", imp, monkeypatch))

    def test_outside_scope_not_flagged(self, tmp_path, monkeypatch):
        src = ("import jax\n"
               "def f(x):\n"
               "    return jax.device_put(x)\n")
        probs = self._check(tmp_path, "raft_tpu/spatial/knn.py", src,
                            monkeypatch)
        assert not any("device_put" in p for p in probs)
