"""Spectral module tests: operators vs dense numpy, k-means quality,
partition/modularity on planted graphs.

Mirrors cpp/test/eigen_solvers.cu (eigenvalue assertions),
cpp/test/cluster_solvers.cu (k-means cost sanity), cpp/test/spectral_matrix.cu.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
import scipy.sparse as sp

from raft_tpu.sparse import COO, CSR
from raft_tpu.sparse.spectral import fit_embedding
from raft_tpu.spectral import (
    ClusterSolverConfig,
    EigenSolverConfig,
    KmeansSolver,
    LanczosSolver,
    LaplacianMatrix,
    ModularityMatrix,
    SparseMatrix,
    analyze_modularity,
    analyze_partition,
    kmeans,
    modularity_maximization,
    partition,
)


def planted_two_blocks(rng, n_per=15, p_in=0.7, p_out=0.05):
    n = 2 * n_per
    adj = np.zeros((n, n), np.float32)
    for i in range(n):
        for j in range(i + 1, n):
            same = (i < n_per) == (j < n_per)
            p = p_in if same else p_out
            if rng.random() < p:
                adj[i, j] = adj[j, i] = 1.0
    return adj


@pytest.fixture
def rng():
    return np.random.default_rng(17)


class TestOperators:
    def test_sparse_mv(self, rng):
        d = (rng.random((12, 12)) * (rng.random((12, 12)) < 0.4)).astype(np.float32)
        x = rng.random(12).astype(np.float32)
        got = SparseMatrix(CSR.from_dense(d, capacity=80)).mv(x)
        np.testing.assert_allclose(np.asarray(got), d @ x, rtol=1e-5)

    def test_spmv_impl_typo_fails_at_construction(self, rng):
        """A typo'd spmv_impl pin must fail in __init__ against the knob
        whitelist, not surface later from inside a jitted solve."""
        from raft_tpu.core.error import RaftError
        from raft_tpu.sparse.linalg import SPMV_IMPLS

        d = (rng.random((8, 8)) * (rng.random((8, 8)) < 0.4)
             ).astype(np.float32)
        csr = CSR.from_dense(d, capacity=40)
        with pytest.raises(RaftError, match="spmv_impl"):
            SparseMatrix(csr, spmv_impl="segement")   # the typo
        with pytest.raises(RaftError, match="spmv_impl"):
            LaplacianMatrix(csr, spmv_impl="cusparse")
        # every whitelisted impl (and the None = knob default) is legal
        x = rng.random(8).astype(np.float32)
        for impl in SPMV_IMPLS + (None,):
            got = SparseMatrix(csr, spmv_impl=impl).mv(x)
            np.testing.assert_allclose(np.asarray(got), d @ x,
                                       rtol=1e-4, atol=1e-5)

    def test_laplacian_mv(self, rng):
        adj = planted_two_blocks(rng, 8)
        L_ref = np.diag(adj.sum(1)) - adj
        x = rng.random(16).astype(np.float32)
        L = LaplacianMatrix(CSR.from_dense(adj))
        np.testing.assert_allclose(np.asarray(L.mv(x)), L_ref @ x, rtol=1e-4,
                                   atol=1e-4)
        np.testing.assert_allclose(np.asarray(L.diagonal), adj.sum(1), rtol=1e-6)

    def test_modularity_mv(self, rng):
        adj = planted_two_blocks(rng, 8)
        d = adj.sum(1)
        B_ref = adj - np.outer(d, d) / d.sum()
        x = rng.random(16).astype(np.float32)
        B = ModularityMatrix(CSR.from_dense(adj))
        np.testing.assert_allclose(np.asarray(B.mv(x)), B_ref @ x, rtol=1e-4,
                                   atol=1e-4)


class TestKmeans:
    def test_blobs(self, rng):
        X = np.concatenate([
            rng.normal(0, 0.2, (30, 2)),
            rng.normal(3, 0.2, (30, 2)),
            rng.normal((6, 0), 0.2, (30, 2)),
        ]).astype(np.float32)
        res = kmeans(X, 3, seed=7)
        # perfect separation: each blob uniform label
        for s in range(0, 90, 30):
            blob = np.asarray(res.labels[s:s + 30])
            assert (blob == blob[0]).all()
        assert float(res.residual) < 30 * 3 * 0.2 ** 2 * 4

    def test_k_equals_n(self, rng):
        X = rng.random((5, 2)).astype(np.float32)
        res = kmeans(X, 5, seed=3)
        assert len(np.unique(np.asarray(res.labels))) == 5
        assert float(res.residual) < 1e-6

    def test_solver_facade(self, rng):
        X = rng.random((20, 3)).astype(np.float32)
        labels, residual, iters = KmeansSolver(
            ClusterSolverConfig(n_clusters=4)).solve(jnp.asarray(X))
        assert labels.shape == (20,)
        assert float(residual) >= 0


class TestEigenSolver:
    def test_laplacian_smallest(self, rng):
        adj = planted_two_blocks(rng, 10)
        L_ref = np.diag(adj.sum(1)) - adj
        ref_vals = np.linalg.eigvalsh(L_ref)
        L = LaplacianMatrix(CSR.from_dense(adj))
        solver = LanczosSolver(EigenSolverConfig(n_eig_vecs=3, tol=1e-9))
        vals, vecs, _ = solver.solve_smallest_eigenvectors(L, 20)
        np.testing.assert_allclose(np.asarray(vals), ref_vals[:3], atol=1e-3)
        assert vecs.shape == (20, 3)


class TestPartition:
    def test_two_blocks(self, rng):
        adj = planted_two_blocks(rng)
        res = partition(CSR.from_dense(adj), n_clusters=2)
        labels = np.asarray(res.clusters)
        # the two planted blocks separate
        assert (labels[:15] == labels[0]).all()
        assert (labels[15:] == labels[15]).all()
        assert labels[0] != labels[15]

        edge_cut, cost = analyze_partition(CSR.from_dense(adj), 2, res.clusters)
        # cut of planted partition == cross-block edges
        ref_cut = adj[:15, 15:].sum()
        np.testing.assert_allclose(float(edge_cut), ref_cut, rtol=1e-4)
        assert float(cost) > 0

    def test_modularity_two_blocks(self, rng):
        adj = planted_two_blocks(rng)
        res = modularity_maximization(CSR.from_dense(adj), n_clusters=2)
        labels = np.asarray(res.clusters)
        assert (labels[:15] == labels[0]).all()
        assert (labels[15:] == labels[15]).all()
        assert labels[0] != labels[15]

        q = analyze_modularity(CSR.from_dense(adj), 2, res.clusters)
        # reference formula vs dense computation
        d = adj.sum(1)
        B_ref = adj - np.outer(d, d) / d.sum()
        q_ref = sum(
            (labels == c).astype(float) @ B_ref @ (labels == c).astype(float)
            for c in range(2)) / d.sum()
        np.testing.assert_allclose(float(q), q_ref, atol=1e-4)
        assert float(q) > 0.2  # strong community structure


class TestFitEmbedding:
    def test_embedding_separates_components(self, rng):
        adj = planted_two_blocks(rng, 12, p_in=0.8, p_out=0.02)
        coo = COO.from_dense(adj)
        emb = np.asarray(fit_embedding(coo, n_components=2))
        assert emb.shape == (24, 2)
        # fiedler coordinate separates the blocks
        f = emb[:, 0]
        assert (np.sign(f[:12]) == np.sign(f[0])).all() or \
               (np.sign(f[12:]) == np.sign(f[12])).all()


def test_kmeans_large_k_fused_assignment(rng):
    """k >= 256 routes assignment through the fused 1-NN (kmeans.py
    assign) — labels and residual must match the dense argmin exactly."""
    from raft_tpu.spectral.kmeans import kmeans

    X = jnp.asarray(rng.standard_normal((2000, 8)).astype(np.float32))
    res = kmeans(X, 256, max_iter=2, seed=3)
    labels = np.asarray(res.labels)
    C = np.asarray(res.centroids)
    Xh = np.asarray(X, np.float64)
    dm = ((Xh[:, None, :] - C[None].astype(np.float64)) ** 2).sum(-1)
    ref = dm.argmin(axis=1)
    mism = labels != ref
    # any mismatch must be an exact distance tie
    assert np.allclose(dm[np.arange(2000), labels][mism],
                       dm[np.arange(2000), ref][mism], rtol=1e-6), \
        mism.sum()
    np.testing.assert_allclose(
        float(res.residual), dm.min(axis=1).sum(), rtol=1e-3)


class TestR5Regressions:
    """r5 spectral perf fixes: solver executable reuse, constant-column
    whitening, and kmeans multi-init (VERDICT r4 item 5)."""

    def test_lanczos_executable_reused_across_instances(self, rng):
        """The jitted solve must cache by (operator structure, shapes):
        a second LaplacianMatrix of the same shape may not retrace (the
        r4 pathology: ~7.4 s of per-call retrace on a 0.05 s solve)."""
        from raft_tpu.linalg import lanczos as lz

        solver = LanczosSolver(EigenSolverConfig(n_eig_vecs=2, tol=1e-3))
        base = lz._lanczos_run._cache_size()
        adj = planted_two_blocks(np.random.default_rng(0), 12)
        for _ in range(2):
            # fresh CSR + operator instances, identical shapes — the
            # second solve must be a pure executable-cache hit
            L = LaplacianMatrix(CSR.from_dense(adj.copy()))
            solver.solve_smallest_eigenvectors(L, 24)
        assert lz._lanczos_run._cache_size() == base + 1

    def test_whitening_zeroes_constant_column(self):
        from raft_tpu.spectral.spectral_util import transform_eigen_matrix

        n = 64
        const = np.full((n,), 1.0 / np.sqrt(n), np.float32)
        const += np.random.default_rng(0).normal(0, 1e-6, n).astype(
            np.float32)  # f32 eigensolver noise
        sig = np.concatenate([np.full(n // 2, -1.0), np.full(n // 2, 1.0)])
        vecs = jnp.asarray(np.stack([const, sig.astype(np.float32)], 1))
        emb = np.asarray(transform_eigen_matrix(vecs))
        # noise must NOT be amplified to unit variance
        assert np.abs(emb[:, 0]).max() < 1e-2
        # informative column still whitened
        np.testing.assert_allclose(np.abs(emb[:, 1]), 1.0, rtol=1e-5)

    def test_kmeans_multi_init_no_worse(self, rng):
        from raft_tpu.spectral.kmeans import kmeans

        X = jnp.asarray(rng.standard_normal((200, 2)).astype(np.float32))
        r1 = kmeans(X, 4, seed=5, n_init=1)
        r8 = kmeans(X, 4, seed=5, n_init=8)
        assert float(r8.residual) <= float(r1.residual) + 1e-5

    def test_kmeans_nan_solve_stays_visible(self):
        """A non-finite solve must surface as a non-finite residual, not
        as the zero-initialized best (r5 review finding)."""
        from raft_tpu.spectral.kmeans import kmeans

        X = jnp.asarray(np.full((32, 2), 1e20, np.float32))
        res = kmeans(X, 2, seed=1, n_init=3)
        assert not np.isfinite(float(res.residual)) or \
            float(res.residual) >= 0
        # the all-zero-centroid masquerade: centroids must not be the
        # untouched zeros sentinel while residual claims +inf
        if not np.isfinite(float(res.residual)):
            assert not np.all(np.asarray(res.centroids) == 0.0)

    def test_operator_densify_auto_and_override(self):
        """Small graphs auto-densify (dense MXU matvec instead of the
        nnz element gather — serial on TPU); large-graph behavior is
        forced via densify=False and must agree."""
        rng = np.random.default_rng(2)
        adj = planted_two_blocks(rng, 10)
        x = jnp.asarray(rng.random(20).astype(np.float32))
        # auto is backend-aware (dense only on TPU); force both paths
        Ld = LaplacianMatrix(CSR.from_dense(adj), densify=True)
        Ls = LaplacianMatrix(CSR.from_dense(adj), densify=False)
        assert Ld.dense is not None and Ls.dense is None
        from raft_tpu.core.utils import is_tpu_backend
        auto = LaplacianMatrix(CSR.from_dense(adj))
        # auto follows the backend: dense on TPU, sparse elsewhere
        assert (auto.dense is not None) == is_tpu_backend()
        np.testing.assert_allclose(np.asarray(Ld.mv(x)),
                                   np.asarray(Ls.mv(x)),
                                   rtol=1e-4, atol=1e-4)
        Bd = ModularityMatrix(CSR.from_dense(adj), densify=True)
        Bs = ModularityMatrix(CSR.from_dense(adj), densify=False)
        np.testing.assert_allclose(np.asarray(Bd.mv(x)),
                                   np.asarray(Bs.mv(x)),
                                   rtol=1e-4, atol=1e-4)
        # pytree round-trip preserves the dense leaf without recompute
        leaves, treedef = jax.tree_util.tree_flatten(Bd)
        Bd2 = jax.tree_util.tree_unflatten(treedef, leaves)
        assert Bd2.dense is not None
        np.testing.assert_allclose(np.asarray(Bd2.mv(x)),
                                   np.asarray(Bd.mv(x)), rtol=1e-6)

    def test_operator_spmv_impl_pin_keys_executables(self):
        """spmv_impl pinned on the operator is AUX data: operators
        pinned to different impls must produce different treedefs (so
        the jitted solver compiles each genuinely — the r5 spectral A/B
        initially timed one executable three times without this)."""
        rng = np.random.default_rng(3)
        adj = planted_two_blocks(rng, 8)
        x = jnp.asarray(rng.random(16).astype(np.float32))
        L_ref = np.diag(adj.sum(1)) - adj
        defs = set()
        for impl in ("segment", "cumsum", "sortscan"):
            L = LaplacianMatrix(CSR.from_dense(adj), spmv_impl=impl)
            _, treedef = jax.tree_util.tree_flatten(L)
            defs.add(str(treedef))
            np.testing.assert_allclose(np.asarray(L.mv(x)), L_ref @
                                       np.asarray(x), rtol=1e-3,
                                       atol=1e-3)
        assert len(defs) == 3
        # pin survives the round-trip
        L = ModularityMatrix(CSR.from_dense(adj), spmv_impl="sortscan")
        leaves, td = jax.tree_util.tree_flatten(L)
        assert jax.tree_util.tree_unflatten(td, leaves).spmv_impl == \
            "sortscan"
