"""RNG distribution tests (reference cpp/test/random/rng.cu — mean/stddev
moment checks per distribution; sample_without_replacement weight tests)."""

import numpy as np
import pytest

from raft_tpu.core.error import RaftError
from raft_tpu.random import GeneratorType, Rng

N = 40_000


@pytest.fixture
def r():
    return Rng(seed=42)


def _moments(x):
    x = np.asarray(x, dtype=np.float64)
    return x.mean(), x.std()


class TestDistributions:
    def test_uniform(self, r):
        x = np.asarray(r.uniform((N,), start=-1.0, end=3.0))
        assert -1.0 <= x.min() and x.max() < 3.0
        assert abs(x.mean() - 1.0) < 0.05

    def test_uniform_int(self, r):
        x = np.asarray(r.uniform_int((N,), 5, 10))
        assert set(np.unique(x)) <= {5, 6, 7, 8, 9}

    def test_normal(self, r):
        m, s = _moments(r.normal((N,), mu=2.0, sigma=3.0))
        assert abs(m - 2.0) < 0.1 and abs(s - 3.0) < 0.1

    def test_normal_int(self, r):
        x = np.asarray(r.normal_int((N,), 100, 10))
        assert np.issubdtype(x.dtype, np.integer)
        assert abs(x.mean() - 100) < 1.0

    def test_normal_table(self, r):
        import jax.numpy as jnp

        mu = jnp.array([0.0, 10.0, -5.0])
        x = np.asarray(r.normal_table(N, mu, sigma=2.0))
        np.testing.assert_allclose(x.mean(axis=0), [0.0, 10.0, -5.0], atol=0.2)

    def test_fill_bernoulli(self, r):
        assert np.all(np.asarray(r.fill((7,), 3.5)) == 3.5)
        b = np.asarray(r.bernoulli((N,), 0.3))
        assert abs(b.mean() - 0.3) < 0.02
        sb = np.asarray(r.scaled_bernoulli((N,), 0.3, 2.0))
        assert set(np.unique(sb)) == {-2.0, 2.0}
        # P(+scale) = P(u <= prob)? reference: val > prob ? -scale : scale
        assert abs((sb == 2.0).mean() - 0.3) < 0.02

    def test_gumbel(self, r):
        m, _ = _moments(r.gumbel((N,), mu=1.0, beta=2.0))
        assert abs(m - (1.0 + 2.0 * 0.5772)) < 0.1

    def test_lognormal(self, r):
        x = np.asarray(r.lognormal((N,), mu=0.0, sigma=0.5))
        assert abs(np.log(x).mean()) < 0.05

    def test_logistic(self, r):
        m, s = _moments(r.logistic((N,), mu=3.0, scale=1.0))
        assert abs(m - 3.0) < 0.1
        assert abs(s - np.pi / np.sqrt(3)) < 0.1

    def test_exponential(self, r):
        m, _ = _moments(r.exponential((N,), lam=2.0))
        assert abs(m - 0.5) < 0.02

    def test_rayleigh(self, r):
        m, _ = _moments(r.rayleigh((N,), sigma=2.0))
        assert abs(m - 2.0 * np.sqrt(np.pi / 2)) < 0.1

    def test_laplace(self, r):
        m, s = _moments(r.laplace((N,), mu=1.0, scale=2.0))
        assert abs(m - 1.0) < 0.1
        assert abs(s - 2.0 * np.sqrt(2)) < 0.15

    def test_reproducible(self):
        a = np.asarray(Rng(7).uniform((100,)))
        b = np.asarray(Rng(7).uniform((100,)))
        np.testing.assert_array_equal(a, b)
        c = np.asarray(Rng(8).uniform((100,)))
        assert not np.array_equal(a, c)

    def test_generator_types_accepted(self):
        for g in GeneratorType:
            Rng(1, gtype=g).uniform((4,))


class TestSampling:
    def test_without_replacement_unweighted(self, r):
        import jax.numpy as jnp

        items = jnp.arange(100)
        vals, idx = r.sample_without_replacement(items, 20)
        assert len(np.unique(np.asarray(idx))) == 20
        np.testing.assert_array_equal(np.asarray(vals), np.asarray(idx))

    def test_without_replacement_weighted(self):
        import jax.numpy as jnp

        # one item has overwhelming weight -> always sampled
        w = jnp.ones(50).at[13].set(1e6)
        hits = 0
        for seed in range(20):
            _, idx = Rng(seed).sample_without_replacement(jnp.arange(50), 5, weights=w)
            hits += int(13 in np.asarray(idx))
        assert hits == 20

    def test_bad_len(self, r):
        import jax.numpy as jnp

        with pytest.raises(RaftError):
            r.sample_without_replacement(jnp.arange(10), 11)

    def test_affine_params(self, r):
        import math

        a, b = r.affine_transform_params(100)
        assert math.gcd(a, 100) == 1
        assert 0 <= b < 100
