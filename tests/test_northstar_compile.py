"""North-star-shape AOT compile smoke (BASELINE.md config #3/#5).

The unit tests exercise <=1k-row shapes; nothing there catches scaling
bugs — HLO blow-ups, tiling mistakes, memory planning — that only appear
at the 1M x 128 k=100 regime the bench measures.  AOT lowering +
compilation (jax.jit(...).lower().compile()) exercises exactly that
without executing a single FLOP, so it runs fine on the CPU test mesh.

Reference contrast: RAFT runs its perf-shaped paths in test_raft
(cpp/test/CMakeLists.txt:18-113); this is the shape-only analog.
"""

import jax
import jax.numpy as jnp
import pytest

N_INDEX = 1_000_000
N_QUERY = 10_000
DIM = 128
K = 100


def _abstract(shape):
    return jax.ShapeDtypeStruct(shape, jnp.float32)


class TestNorthStarCompile:
    def test_brute_force_knn_1m_compiles(self):
        """Single-chip north star: lower + compile, no execution."""
        from raft_tpu.spatial import brute_force_knn

        def step(index, queries):
            return brute_force_knn([index], queries, K)

        lowered = jax.jit(step).lower(_abstract((N_INDEX, DIM)),
                                      _abstract((N_QUERY, DIM)))
        # the tile scan must keep HLO size independent of n_index: a
        # driver that unrolls 123 tiles would blow far past this bound
        hlo_lines = lowered.as_text().count("\n")
        assert hlo_lines < 4000, f"HLO blow-up: {hlo_lines} lines"
        compiled = lowered.compile()
        mem = compiled.memory_analysis()
        if mem is not None:  # backend-dependent availability
            # index (512MB) + queries + a (nq, tile) live tile — far
            # below a 16GB HBM; catches accidental (nq, n_index) temps,
            # which alone would need 40GB
            total = (mem.argument_size_in_bytes
                     + mem.temp_size_in_bytes + mem.output_size_in_bytes)
            assert total < 4 * 1024 ** 3, f"memory plan {total/2**30:.1f}GB"

    def test_mnmg_knn_sharded_equivalent_compiles(self):
        """Multi-chip north star: the same shape row-sharded over the
        8-device test mesh (BASELINE.md config #5)."""
        from raft_tpu.comms.host_comms import default_mesh
        from raft_tpu.spatial.mnmg_knn import mnmg_knn

        if len(jax.devices()) < 8:
            pytest.skip("needs the 8-device test mesh")
        mesh = default_mesh(8)

        def step(index, queries):
            return mnmg_knn(index, queries, K, mesh=mesh, axis="ranks")

        lowered = jax.jit(step).lower(_abstract((N_INDEX, DIM)),
                                      _abstract((N_QUERY, DIM)))
        hlo_lines = lowered.as_text().count("\n")
        assert hlo_lines < 6000, f"HLO blow-up: {hlo_lines} lines"
        lowered.compile()

    def test_select_k_at_scale_compiles(self):
        """k=100 selection over a 1M-wide candidate row (the k>64 regime
        the reference routes to FAISS block-select)."""
        from raft_tpu.spatial.select_k import select_k

        lowered = jax.jit(
            lambda d: select_k(d, K, select_min=True)
        ).lower(_abstract((64, N_INDEX)))
        lowered.compile()
