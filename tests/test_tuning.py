"""Bench-driven autotuner: candidate registry + tuning-table layer.

Covers the full resolution ladder with the table rung (override >
configure > env > table > default), table lifecycle (load / stale
fingerprint / corrupt / suspend), describe() layer attribution, the
registry's shared validation contract, the typed knob parsers, and the
sweep driver itself (tools/autotune.py --smoke in-process).
"""

import importlib.util
import json
import os
import warnings

import numpy as np
import pytest

import jax.numpy as jnp

from raft_tpu import config
from raft_tpu.core import tuning
from raft_tpu.core.error import LogicError, RaftError

pytestmark = pytest.mark.tuning


@pytest.fixture(autouse=True)
def _reset(monkeypatch):
    monkeypatch.setattr(config, "_values", {})
    monkeypatch.setattr(config, "_consumed", {})
    monkeypatch.setattr(config, "_table", None)
    monkeypatch.setattr(config, "_table_env_checked", True)
    monkeypatch.setattr(config, "_table_warned", set())
    for _, (env, _, _) in config._KNOBS.items():
        monkeypatch.delenv(env, raising=False)
    monkeypatch.delenv(config.TUNING_TABLE_ENV, raising=False)
    yield
    config.clear_tuning_table()


# select cell the fixtures key on: class of (n=4096, k=16)
DIMS = {"n": 4096, "k": 16}
CLS = tuning.shape_class(DIMS)


def make_table(entries=None, fp=None):
    return {
        "version": 1,
        "fingerprint": fp or tuning.backend_fingerprint(),
        "entries": entries if entries is not None else [
            {"op": "select_k", "knob": "select_impl",
             "shape_class": CLS, "dtype": "float32",
             "winner": "chunked", "margin": 2.0},
            {"op": "select_k", "knob": "select_impl",
             "shape_class": "*", "dtype": "*", "winner": "approx"},
        ],
    }


def resolve_select(**kw):
    kw.setdefault("dtype", jnp.float32)
    return tuning.resolve("select_impl", site="select_k", **DIMS, **kw)


# --------------------------------------------------------------------- #
# resolution ladder
# --------------------------------------------------------------------- #
class TestResolutionLadder:
    def test_table_answers_when_unset(self):
        assert resolve_select() == "topk"          # no table: default
        config.install_tuning_table(make_table())
        assert resolve_select() == "chunked"       # exact-class cell
        # unswept class falls through to the "*" rollup
        assert tuning.resolve("select_impl", site="select_k",
                              n=1 << 20, k=7,
                              dtype=jnp.float32) == "approx"

    def test_env_beats_table(self, monkeypatch):
        config.install_tuning_table(make_table())
        monkeypatch.setenv("RAFT_TPU_SELECT_IMPL", "approx")
        assert resolve_select() == "approx"
        assert config.tuned("select_impl")[1] == "env"

    def test_configure_beats_table_and_reverts_to_it(self):
        config.install_tuning_table(make_table())
        config.configure(select_impl="topk")
        assert resolve_select() == "topk"
        config.configure(select_impl=None)
        assert resolve_select() == "chunked"       # table, not default

    def test_override_beats_env_and_table(self, monkeypatch):
        config.install_tuning_table(make_table())
        monkeypatch.setenv("RAFT_TPU_SELECT_IMPL", "approx")
        with config.override(select_impl="topk"):
            assert resolve_select() == "topk"
        assert resolve_select() == "approx"

    def test_override_none_reverts_to_table_not_default(self):
        """The acceptance contract: a knob resolved from the table is
        overridable, and REVERTING the override restores the table's
        answer (not the built-in default)."""
        config.install_tuning_table(make_table())
        with config.override(select_impl="approx"):
            assert resolve_select() == "approx"
            with config.override(select_impl=None):
                assert resolve_select() == "chunked"
            assert resolve_select() == "approx"
        assert resolve_select() == "chunked"

    def test_suspend_tuning(self):
        config.install_tuning_table(make_table())
        assert resolve_select() == "chunked"
        with config.suspend_tuning():
            assert resolve_select() == "topk"
        assert resolve_select() == "chunked"

    def test_suspend_is_thread_local(self):
        """A suspension neither leaks into concurrent threads nor
        races their depth (review finding: the global += counter could
        lose an increment and latch the table off process-wide)."""
        import threading

        config.install_tuning_table(make_table())
        seen = []
        with config.suspend_tuning():
            t = threading.Thread(target=lambda:
                                 seen.append(resolve_select()))
            t.start()
            t.join()
            assert resolve_select() == "topk"      # suspended here
        assert seen == ["chunked"]                 # not over there
        assert resolve_select() == "chunked"

    def test_sweep_times_with_table_suspended(self):
        """Candidate timing must not resolve nested knobs through the
        incumbent table (review finding: re-sweeps on a tuned venue
        would persist winners measured under the OLD table's pins)."""
        at = _load_autotune()
        config.install_tuning_table(make_table())
        states = []
        best, compiles = at.time_candidate(
            lambda: states.append(config.tuning_table_info()),
            op="x", cell="c", cand="v", iters=1)
        assert states == [None, None]              # warmup + 1 iter
        assert config.tuning_table_info() is not None

    def test_illegal_table_winner_falls_back_to_default(self):
        """A table cell whose winner is illegal for the REAL call ctx
        (swept at a coarser class) must fall back to the default, not
        crash the call: the table is advisory."""
        t = make_table(entries=[
            {"op": "select_k", "knob": "select_impl",
             "shape_class": "*", "dtype": "*", "winner": "pallas"}])
        config.install_tuning_table(t)
        got = tuning.resolve("select_impl", site="select_k",
                             n=100000, k=500, dtype=jnp.float32)
        assert got == "topk"                       # pallas caps k at 128

    def test_consumer_dispatches_table_winner(self, monkeypatch):
        """Through the REAL consumer: select_k routes to the table's
        winner for the matching shape class."""
        import importlib

        sk = importlib.import_module("raft_tpu.spatial.select_k")
        calls = []
        real = sk.chunked_top_k
        monkeypatch.setattr(
            sk, "chunked_top_k",
            lambda *a, **k: calls.append(1) or real(*a, **k))
        keys = jnp.asarray(
            np.random.RandomState(0).random((4, DIMS["n"]))
            .astype("float32"))
        sk.select_k(keys, DIMS["k"])
        assert not calls                           # default: topk path
        config.install_tuning_table(make_table())
        sk.select_k(keys, DIMS["k"])
        assert calls                               # table: chunked


# --------------------------------------------------------------------- #
# table lifecycle
# --------------------------------------------------------------------- #
class TestTableLifecycle:
    def test_stale_fingerprint_ignored_with_one_warning(self, tmp_path):
        fp = dict(tuning.backend_fingerprint())
        fp["platform"] = "definitely-not-this-backend"
        path = tmp_path / "stale.json"
        path.write_text(json.dumps(make_table(fp=fp)))
        with pytest.warns(UserWarning, match="stale fingerprint"):
            assert config.load_tuning_table(str(path)) is False
        assert resolve_select() == "topk"          # untuned
        # one-time: the second load of the SAME table stays silent
        with warnings.catch_warnings():
            warnings.simplefilter("error")
            assert config.load_tuning_table(str(path)) is False

    def test_corrupt_table_fails_loudly(self, tmp_path):
        bad = tmp_path / "bad.json"
        bad.write_text("{not json")
        with pytest.raises(LogicError, match="corrupt"):
            config.load_tuning_table(str(bad))
        wrong = tmp_path / "wrong.json"
        wrong.write_text(json.dumps({"version": 999, "fingerprint": {},
                                     "entries": []}))
        with pytest.raises(LogicError, match="version"):
            config.load_tuning_table(str(wrong))
        missing = tmp_path / "missing.json"
        missing.write_text(json.dumps(
            {"version": 1,
             "fingerprint": tuning.backend_fingerprint(),
             "entries": [{"op": "x"}]}))
        with pytest.raises(LogicError, match="entry 0"):
            config.load_tuning_table(str(missing))
        with pytest.raises(LogicError, match="unreadable"):
            config.load_tuning_table(str(tmp_path / "nope.json"))

    def test_env_var_load(self, tmp_path, monkeypatch):
        path = tmp_path / "t.json"
        path.write_text(json.dumps(make_table()))
        monkeypatch.setenv(config.TUNING_TABLE_ENV, str(path))
        monkeypatch.setattr(config, "_table_env_checked", False)
        assert resolve_select() == "chunked"
        info = config.tuning_table_info()
        assert info["cells"] == 2
        assert info["knobs"] == {"select_impl": 2}

    def test_roundtrip_through_file(self, tmp_path):
        path = tmp_path / "t.json"
        path.write_text(json.dumps(make_table()))
        assert config.load_tuning_table(str(path)) is True
        assert resolve_select() == "chunked"
        config.clear_tuning_table()
        assert resolve_select() == "topk"

    def test_checked_in_table_is_valid(self):
        """Every checked-in table under raft_tpu/tuning/ parses and
        indexes (fingerprint match not required — other venues' tables
        ride the same tree)."""
        d = os.path.join(os.path.dirname(config.__file__), "tuning")
        found = 0
        for fname in os.listdir(d):
            if fname.endswith(".json"):
                with open(os.path.join(d, fname)) as f:
                    doc = json.load(f)
                t = config._index_table(doc, fname)
                assert t["index"], fname
                found += 1
        assert found >= 1                          # the CPU-ladder table


# --------------------------------------------------------------------- #
# describe() attribution
# --------------------------------------------------------------------- #
class TestDescribe:
    def test_layers(self, monkeypatch):
        config.install_tuning_table(make_table())
        monkeypatch.setenv("RAFT_TPU_TILE_MERGE", "direct")
        config.configure(spmv_impl="sortscan")
        with config.override(pq_adc="onehot"):
            d = config.describe(layers=True)
            assert d["pq_adc"] == {"value": "onehot",
                                   "layer": "override"}
            assert d["spmv_impl"] == {"value": "sortscan",
                                      "layer": "configure"}
            assert d["tile_merge"] == {"value": "direct",
                                       "layer": "env"}
            # two table cells with different winners -> "per-shape"
            assert d["select_impl"] == {"value": "per-shape",
                                        "layer": "table"}
            assert d["mnmg_merge"] == {"value": "allgather",
                                       "layer": "default"}
        # unanimous single-cell table reads its winner
        config.install_tuning_table(make_table(entries=[
            {"op": "select_k", "knob": "select_impl",
             "shape_class": CLS, "dtype": "float32",
             "winner": "chunked"}]))
        d = config.describe(layers=True)
        assert d["select_impl"] == {"value": "chunked",
                                    "layer": "table"}
        # plain describe() reports the EFFECTIVE value — the table's
        # winner, exactly what consumers receive (review finding: the
        # untabled _resolve here misled operators about the running
        # config)
        assert config.describe()["select_impl"] == "chunked"
        with config.suspend_tuning():
            assert config.describe()["select_impl"] == "topk"

    def test_override_none_attributes_to_table(self):
        config.install_tuning_table(make_table(entries=[
            {"op": "select_k", "knob": "select_impl",
             "shape_class": "*", "dtype": "*", "winner": "approx"}]))
        config.configure(select_impl="topk")
        with config.override(select_impl=None):
            d = config.describe(layers=True)
            assert d["select_impl"] == {"value": "approx",
                                        "layer": "table"}


# --------------------------------------------------------------------- #
# registry contract
# --------------------------------------------------------------------- #
class TestRegistry:
    def test_shared_message_shape(self):
        with pytest.raises(LogicError) as ei:
            tuning.check("spmv_impl", "cusparse", site="SparseMatrix")
        msg = str(ei.value)
        # names the site, knob, value, legal set, and why
        for frag in ("SparseMatrix", "spmv_impl", "cusparse",
                     "segment", "cumsum", "sortscan", "unknown impl"):
            assert frag in msg

    def test_arg_only_candidate(self):
        assert tuning.check("knn_tile_merge", "skip",
                            site="fused_knn_tile",
                            explicit=True) == "skip"
        with pytest.raises(LogicError, match="argument-only"):
            tuning.check("knn_tile_merge", "skip",
                         site="fused_knn_tile")
        # from the table layer: also rejected (falls back via resolve)
        t = make_table(entries=[
            {"op": "fused_knn_tile", "knob": "knn_tile_merge",
             "shape_class": "*", "dtype": "*", "winner": "skip"}])
        config.install_tuning_table(t)
        assert tuning.resolve("knn_tile_merge", site="fused_knn_tile",
                              n=1024, k=8) == "merge"

    def test_twophase_pin_ignores_config(self):
        """merge_select_impl is registry-only: a process-wide
        select_impl configure() must not reach it."""
        config.configure(select_impl="approx95")
        assert tuning.resolve("merge_select_impl") == "topk"
        assert tuning.resolve("merge_select_impl", "chunked") == \
            "chunked"

    def test_group_size_legality(self):
        with pytest.raises(LogicError, match="mnmg_group_size"):
            tuning.check("mnmg_group_size", 3, site="mnmg",
                         explicit=True, axis_size=8)
        assert tuning.check("mnmg_group_size", 4, site="mnmg",
                            explicit=True, axis_size=8) == 4

    def test_sparse_matrix_typo_via_registry(self):
        from raft_tpu.sparse.formats import CSR
        from raft_tpu.spectral.matrix_wrappers import SparseMatrix

        d = (np.random.RandomState(0).random((8, 8)) * 1).astype(
            "float32")
        csr = CSR.from_dense(d, capacity=80)
        with pytest.raises(RaftError, match="spmv_impl"):
            SparseMatrix(csr, spmv_impl="segement")

    def test_pallas_k_cap_legality(self):
        with pytest.raises(LogicError, match="128"):
            tuning.resolve("fused_knn_impl", "pallas",
                           site="fused_l2_knn", n=10000, k=500)

    def test_every_choices_knob_is_registered(self):
        """The lint's contract, asserted dynamically too: every config
        knob with a choices whitelist has a registry spec with the
        SAME candidate set."""
        for knob, (_, _, choices) in config._KNOBS.items():
            if choices is None:
                continue
            assert set(tuning.candidates(knob)) == set(choices), knob

    def test_shape_class_pow2_rounding(self):
        assert tuning.shape_class({"n": 100000, "k": 100}) == \
            "k=128,n=131072"
        assert tuning.shape_class({"n": 131072, "k": 128}) == \
            "k=128,n=131072"
        assert tuning.shape_class({}) == "*"
        assert tuning.shape_class({"n": 8192, "k": 100}) != \
            tuning.shape_class({"n": 131072, "k": 100})


# --------------------------------------------------------------------- #
# typed knob parsers
# --------------------------------------------------------------------- #
class TestTypedParsers:
    @pytest.mark.parametrize("fn,knob,env,bad", [
        (config.get_int, "serve_queue_cap",
         "RAFT_TPU_SERVE_QUEUE_CAP", "many"),
        (config.get_float, "serve_max_wait_ms",
         "RAFT_TPU_SERVE_MAX_WAIT_MS", "fast"),
        (config.get_float, "serve_hedge_factor",
         "RAFT_TPU_SERVE_HEDGE_FACTOR", "1.5x"),
        (config.get_int_list, "serve_ann_nprobe_ladder",
         "RAFT_TPU_SERVE_ANN_NPROBE_LADDER", "4,8,banana"),
        (config.get_float_list, "serve_slo_windows_s",
         "RAFT_TPU_SERVE_SLO_WINDOWS_S", "60,eternity"),
    ])
    def test_malformed_env_names_knob_and_env(self, monkeypatch, fn,
                                              knob, env, bad):
        monkeypatch.setenv(env, bad)
        with pytest.raises(LogicError) as ei:
            fn(knob)
        assert knob in str(ei.value)
        assert env in str(ei.value)

    def test_happy_paths(self, monkeypatch):
        assert config.get_int("serve_queue_cap") == 1024
        assert config.get_float("serve_max_wait_ms") == 2.0
        assert config.get_int_list("serve_ann_nprobe_ladder") == \
            (4, 8, 16, 32, 64)
        assert config.get_float_list("serve_slo_windows_s") == \
            (60.0, 300.0)

    def test_service_construction_surfaces_logic_error(self):
        """The serve layer reads through the typed helpers: a
        malformed configure()d value fails service construction with
        the knob-naming LogicError (was a bare ValueError)."""
        from raft_tpu.serve.service import KNNService

        idx = jnp.asarray(np.random.RandomState(0)
                          .random((64, 8)).astype("float32"))
        config.configure(serve_max_wait_ms="fast")
        try:
            with pytest.raises(LogicError, match="serve_max_wait_ms"):
                KNNService(idx, k=5, start=False)
        finally:
            config.configure(serve_max_wait_ms=None)


# --------------------------------------------------------------------- #
# the sweep driver (tools/autotune.py)
# --------------------------------------------------------------------- #
def _load_autotune():
    path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "tools", "autotune.py")
    spec = importlib.util.spec_from_file_location("_autotune_t", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


class TestAutotune:
    def test_smoke_sweep_produces_valid_table(self, tmp_path):
        at = _load_autotune()
        table = at.run_sweep(smoke=True, log=lambda *_: None)
        # valid per the config loader's own contract
        t = config._index_table(table, "<smoke>")
        exact = [e for e in table["entries"]
                 if e["shape_class"] != "*"]
        swept_knobs = {e["knob"] for e in exact}
        # every knob with >= 1 sweep-legal candidate on this backend
        assert {"select_impl", "tile_merge", "spmv_impl", "pq_adc",
                "mnmg_merge"} <= swept_knobs
        for e in exact:
            assert e["winner"] in e["timings_s"]
            assert all(n == 0 for n in
                       e["post_warmup_compiles"].values()), e
            # a reverted entry's margin is honestly < 1: the discarded
            # winner was faster, just inside the noise band
            assert e["margin"] >= 1.0 or e["reverted_from"] is not None, e
        # rollup entries cover shape-less lookups
        assert any(e["shape_class"] == "*" for e in table["entries"])
        assert t["index"]

    def test_smoke_table_installs_and_tuned_vs_default(self):
        at = _load_autotune()
        table = at.run_sweep(smoke=True, log=lambda *_: None)
        assert config.install_tuning_table(table) is True
        res = at.tuned_vs_default(table, iters=2, log=lambda *_: None)
        assert res["cells"]
        # smoke cells are ms-scale, so the re-timed ratio is noisy:
        # this asserts the MACHINERY (the >= 1.0 bar is the bench
        # rung's, over the real-size checked-in table)
        assert res["min_ratio"] is None or res["min_ratio"] >= 0.5
        assert res["post_warmup_compiles"] == 0

    def test_dry_run_and_filters(self, capsys):
        at = _load_autotune()
        assert at.main(["--dry-run", "--smoke"]) == 0
        out = capsys.readouterr().out
        assert "select_k/select_impl" in out
        assert "SWEEP" in out
        table = at.run_sweep(smoke=True, op_filter="select_impl",
                             log=lambda *_: None)
        assert {e["knob"] for e in table["entries"]} == \
            {"select_impl"}

    def test_diff_tables(self):
        at = _load_autotune()
        old = make_table()
        new = make_table(entries=[
            {"op": "select_k", "knob": "select_impl",
             "shape_class": CLS, "dtype": "float32",
             "winner": "topk", "margin": 1.2},
        ])
        logs = []
        changes = at.diff_tables(old, new, log=logs.append)
        assert changes == 2                        # 1 flip + 1 gone
        assert any("FLIP" in ln for ln in logs)
        assert at.diff_tables(old, old, log=logs.append) == 0


# --------------------------------------------------------------------- #
# the style lint (registry drift)
# --------------------------------------------------------------------- #
class TestStyleLint:
    def _sc(self):
        path = os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), "ci", "style_check.py")
        spec = importlib.util.spec_from_file_location("_sc_t", path)
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        return mod

    def test_real_tree_has_no_drift(self):
        sc = self._sc()
        assert sc.check_tuning_registry() == []

    def test_drift_detected(self):
        sc = self._sc()
        cfg = ('_KNOBS = {\n'
               '    "ghost_impl": ("E", "a", ("a", "b")),\n'
               '}\n')
        probs = sc.check_tuning_registry(config_src=cfg,
                                         tuning_src="\n")
        assert probs and "ghost_impl" in probs[0]

    def test_lint_selftest_green(self):
        sc = self._sc()
        assert sc.selftest() == 0
