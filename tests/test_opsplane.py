"""Ops plane (raft_tpu.serve.opsplane + sentinel + core.inventory):
embedded telemetry endpoint, XLA program cost inventory, anomaly
sentinel (docs/OBSERVABILITY.md "Ops plane").

Covers: inventory capture at profiled_jit's compile seam (nonzero
cost-model numbers, snapshot/summary shapes, the metrics_snapshot
section), every HTTP endpoint's contract (content, status codes,
_peak gauge series, 404/405/500 taxonomy, request accounting),
TTL-cached full health, sentinel rule state machines under a fake
clock (trip-once semantics, breach-frozen baselines, clearance),
the end-to-end injected-latency trip with its black-box tape,
16-thread scrape-under-traffic bit-identity, session serve_ops
lifecycle, the loadgen ops-scrape scenario, and the ops-jax-ban lint.
``./stress.sh ops N`` loops this file with rotating seeds.
"""

import itertools
import json
import os
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

import jax.numpy as jnp

from raft_tpu import config
from raft_tpu.comms import faults
from raft_tpu.core import flight
from raft_tpu.core import inventory
from raft_tpu.core.metrics import default_registry, parse_prometheus
from raft_tpu.core.profiler import compile_cache_stats, profiled_jit
from raft_tpu.serve import AnomalySentinel, KNNService, OpsPlane
from raft_tpu.serve import sentinel as sentinel_mod
from raft_tpu.serve.resilience import inject_worker
from raft_tpu.spatial.knn import brute_force_knn

pytestmark = pytest.mark.ops

SEED = int(os.environ.get("RAFT_TPU_SERVE_SEED", "1234"))
_uniq = itertools.count()


def _name(prefix="opsvc"):
    return "%s%d" % (prefix, next(_uniq))


@pytest.fixture(autouse=True)
def _flight_isolation():
    """Sentinel breaches capture black boxes into the process-global
    bounded deque (BLACKBOX_KEEP=8); left behind, a saturated deque
    breaks any later suite's grew-by-one assertion (test_persist's
    scrub test).  Clear flight state after every test here."""
    yield
    flight.reset()


@pytest.fixture
def rng():
    return np.random.default_rng(SEED)


@pytest.fixture
def index(rng):
    return jnp.asarray(rng.standard_normal((400, 16)), jnp.float32)


@pytest.fixture
def service(index):
    svc = KNNService(index, k=5, max_batch_rows=64, max_wait_ms=1.0,
                     name=_name())
    svc.warmup()
    yield svc
    svc.close()


@pytest.fixture
def plane(service):
    p = OpsPlane(services={service.name: service}, port=0,
                 sentinel_interval_s=0.05)
    yield p
    p.close()


def _get(url, timeout=10.0):
    """(status, parsed-json-or-text) tolerating non-2xx statuses."""
    try:
        with urllib.request.urlopen(url, timeout=timeout) as resp:
            body = resp.read().decode("utf-8")
            code = resp.status
    except urllib.error.HTTPError as e:
        body = e.read().decode("utf-8")
        code = e.code
    try:
        return code, json.loads(body)
    except ValueError:
        return code, body


def _total_misses():
    return sum(s["misses"] for fn in compile_cache_stats().values()
               for s in fn.values())


class FakeClock:
    def __init__(self, t=0.0):
        self.t = float(t)

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt


# ---------------------------------------------------------------------- #
# program cost inventory (raft_tpu/core/inventory.py)
# ---------------------------------------------------------------------- #
class TestInventory:
    def test_profiled_jit_populates_inventory(self, rng):
        fn_name = _name("inv_fn")

        @profiled_jit(name=fn_name)
        def f(x):
            return (x @ x.T).sum(axis=1)

        x = jnp.asarray(rng.standard_normal((32, 8)), jnp.float32)
        f(x)
        snap = inventory.snapshot()
        assert fn_name in snap
        assert len(snap[fn_name]) == 1
        entry = next(iter(snap[fn_name].values()))
        # the CPU backend answers the cost model: nonzero flops and
        # bytes, and memory_analysis footprints
        assert entry["flops"] > 0
        assert entry["bytes_accessed"] > 0
        assert entry["hbm_bytes"] > 0
        assert entry["hbm_bytes"] == pytest.approx(
            entry["argument_bytes"] + entry["output_bytes"]
            + entry["temp_bytes"])
        # a second shape = a second executable = a second entry;
        # a repeat call at a known shape adds nothing
        f(jnp.asarray(rng.standard_normal((64, 8)), jnp.float32))
        f(x)
        assert len(inventory.snapshot()[fn_name]) == 2

    def test_gauges_exported_per_entry(self, rng):
        fn_name = _name("inv_gauge")

        @profiled_jit(name=fn_name)
        def f(x):
            return x * 2.0

        f(jnp.asarray(rng.standard_normal((16, 4)), jnp.float32))
        entry = next(iter(inventory.snapshot()[fn_name].values()))
        for metric in ("raft_tpu_program_flops",
                       "raft_tpu_program_bytes",
                       "raft_tpu_program_hbm_bytes"):
            fam = default_registry().get(metric)
            assert fam is not None
            series = {lbls["fn"]: (lbls, s) for lbls, s in fam.series()}
            assert fn_name in series
            lbls, _ = series[fn_name]
            assert lbls["entry"] == entry["entry"]

    def test_summary_rolls_up(self, rng):
        fn_name = _name("inv_sum")

        @profiled_jit(name=fn_name)
        def f(x):
            return x.sum()

        for n in (8, 16, 32):
            f(jnp.asarray(rng.standard_normal((n, 4)), jnp.float32))
        s = inventory.summary()
        assert s["per_fn"][fn_name]["programs"] == 3
        detail = inventory.snapshot()[fn_name]
        assert s["per_fn"][fn_name]["total_hbm_bytes"] == pytest.approx(
            sum(e["hbm_bytes"] for e in detail.values()))
        assert s["programs"] == inventory.entry_count()

    def test_metrics_snapshot_carries_inventory(self):
        from raft_tpu.session import metrics_snapshot

        snap = metrics_snapshot()
        assert {"programs", "total_hbm_bytes", "per_fn",
                "detail"} <= set(snap["inventory"])

    def test_warmed_service_fully_inventoried(self, service):
        # the serve path's cached scan program (the donating twin by
        # default) must appear at every bucket rung with nonzero cost
        snap = inventory.snapshot()
        entries = [e for fn, keys in snap.items()
                   if fn.startswith("tiled_knn")
                   for e in keys.values()]
        assert len(entries) >= len(service.policy.rungs)
        assert all(e["flops"] > 0 and e["bytes_accessed"] > 0
                   for e in entries)


# ---------------------------------------------------------------------- #
# endpoints
# ---------------------------------------------------------------------- #
class TestEndpoints:
    def _traffic(self, service, index, n=3):
        for f in service.submit_many([index[:3], index[3:7]] * n):
            f.result(timeout=30)

    def test_metrics_prometheus(self, plane, service, index):
        self._traffic(service, index)
        code, body = _get(plane.url + "/metrics")
        assert code == 200
        parsed = parse_prometheus(body)
        # serve families, gauge peaks, and the program inventory all
        # ride one scrape
        assert "raft_tpu_serve_requests_total" in parsed
        assert any(k.endswith("_peak") for k in parsed)
        assert not any(k.endswith("_high_water") for k in parsed)
        assert "raft_tpu_program_flops" in parsed

    def test_healthz_ok(self, plane, service):
        code, body = _get(plane.url + "/healthz")
        assert code == 200
        assert body["ok"] is True
        assert body["degraded"] is False
        flags = body["services"][service.name]
        assert flags["worker_alive"] is True
        assert flags["breaker"] == "closed"

    def test_statusz(self, plane, service, index):
        self._traffic(service, index)
        code, body = _get(plane.url + "/statusz")
        assert code == 200
        assert service.name in body["services"]
        assert body["services"][service.name]["worker_alive"] is True
        assert body["inventory"]["programs"] > 0
        # the roofline join: a fn that has executed carries its
        # measured mean next to the cost-model numbers
        assert any("exec_mean_s" in st
                   for st in body["inventory"]["per_fn"].values())
        assert body["sentinel"]["degraded"] is False
        assert {"enabled", "events", "capacity"} <= set(body["flight"])
        assert body["uptime_s"] >= 0

    def test_debug_config_layers(self, plane):
        code, body = _get(plane.url + "/debug/config")
        assert code == 200
        knob = body["knobs"]["select_impl"]
        assert {"value", "layer"} <= set(knob)

    def test_debug_traces(self, plane, service, index):
        self._traffic(service, index)
        code, body = _get(plane.url + "/debug/traces?k=2")
        assert code == 200
        assert body["k"] == 2
        assert body["traces"], "exemplars should exist after traffic"
        tr = body["traces"][0]
        assert tr["service"] == service.name
        kinds = {e["kind"] for e in tr["events"]}
        assert {"batch_formed", "resolved"} <= kinds
        code, body = _get(plane.url + "/debug/traces?k=bogus")
        assert code == 400

    def test_debug_inventory_and_snapshot(self, plane, service):
        code, body = _get(plane.url + "/debug/inventory")
        assert code == 200
        assert body["summary"]["programs"] > 0
        code, snap = _get(plane.url + "/debug/snapshot")
        assert code == 200
        assert {"metrics", "compile_cache", "flight",
                "inventory"} <= set(snap)
        # the --watch source renders through the standard digest
        import tools.metrics_report as mr

        text = mr.render_report(snap)
        assert "program inventory" in text

    def test_blackbox_post_only(self, plane):
        before = len(flight.default_recorder().blackboxes())
        req = urllib.request.Request(
            plane.url + "/debug/blackbox?reason=test", method="POST")
        with urllib.request.urlopen(req, timeout=10) as resp:
            body = json.loads(resp.read().decode("utf-8"))
        assert body["reason"] == "ops_test"
        assert len(flight.default_recorder().blackboxes()) == before + 1
        code, _ = _get(plane.url + "/debug/blackbox")
        assert code == 405

    def test_unknown_endpoint_404_lists_routes(self, plane):
        code, body = _get(plane.url + "/nope")
        assert code == 404
        assert "/metrics" in body["endpoints"]
        # review regression: arbitrary probed paths must not mint one
        # registry series each — 404s land under one "unknown" label
        _get(plane.url + "/nope2")
        _get(plane.url + "/favicon.ico")
        fam = default_registry().get("raft_tpu_ops_requests_total")
        endpoints = {lbls["endpoint"] for lbls, _ in fam.series()}
        assert "unknown" in endpoints
        assert not {"/nope", "/nope2", "/favicon.ico"} & endpoints

    def test_request_accounting(self, plane):
        _get(plane.url + "/metrics")
        _get(plane.url + "/metrics")
        fam = default_registry().get("raft_tpu_ops_requests_total")
        total = sum(s.value for lbls, s in fam.series()
                    if lbls.get("endpoint") == "/metrics")
        assert total >= 2
        fam = default_registry().get("raft_tpu_ops_request_seconds")
        assert any(lbls.get("endpoint") == "/metrics"
                   for lbls, _ in fam.series())

    def test_lifecycle(self, service):
        with OpsPlane(services={service.name: service}, port=0) as p:
            url = p.url
            assert p.port > 0
            assert not p.closed
            assert _get(url + "/healthz")[0] == 200
        # closed: the socket is gone and close is idempotent
        assert p.closed
        p.close()
        with pytest.raises(Exception):
            urllib.request.urlopen(url + "/healthz", timeout=2)

    def test_bind_failure_leaks_no_sentinel(self, service):
        """Review regression: a failed bind (port in use) must not
        leave a permanently registered zombie sentinel behind."""
        import raft_tpu.serve.sentinel as smod

        with OpsPlane(services={service.name: service}, port=0) as p:
            with smod._reg_lock:
                before = list(smod._registered)
            with pytest.raises(OSError):
                OpsPlane(services={service.name: service},
                         host="127.0.0.1", port=p.port)
            with smod._reg_lock:
                assert list(smod._registered) == before


# ---------------------------------------------------------------------- #
# full health behind the TTL cache
# ---------------------------------------------------------------------- #
class _FakeSession:
    def __init__(self):
        self.calls = 0
        self.services = {}

    def health_check(self):
        self.calls += 1
        return {"ok": True, "tests": {}, "devices": {}}


class TestFullHealth:
    def test_ttl_caches_the_battery(self):
        fake = _FakeSession()
        with OpsPlane(session=fake, port=0, healthz_ttl_s=60.0,
                      sentinel=False) as p:
            code, body = _get(p.url + "/healthz?full=1")
            assert code == 200 and body["full"]["ok"] is True
            _get(p.url + "/healthz?full=1")
            _get(p.url + "/healthz?full=1")
            assert fake.calls == 1          # TTL shared one run
            _get(p.url + "/healthz")
            assert fake.calls == 1          # the cheap path never runs it

    def test_ttl_zero_reruns(self):
        fake = _FakeSession()
        with OpsPlane(session=fake, port=0, healthz_ttl_s=0.0,
                      sentinel=False) as p:
            _get(p.url + "/healthz?full=1")
            time.sleep(0.01)
            _get(p.url + "/healthz?full=1")
            assert fake.calls == 2


# ---------------------------------------------------------------------- #
# anomaly sentinel (unit, fake clock)
# ---------------------------------------------------------------------- #
class _Dummy:
    """Service-shaped nothing: the sentinel must cope with objects
    exposing none of the optional surfaces."""


class TestSentinelRules:
    def _sentinel(self, services, clock=None, **knobs):
        with config.override(**{k: str(v) for k, v in knobs.items()}):
            return AnomalySentinel(lambda: services, interval_s=0.0,
                                   clock=clock or FakeClock())

    def _exec_timer(self, svc_name):
        return default_registry().timer(
            "raft_tpu_serve_exec_seconds", labels=("service",)
        ).labels(service=svc_name)

    def test_exec_latency_trip_freeze_clear(self):
        name = _name("sent")
        clock = FakeClock()
        sent = self._sentinel({name: _Dummy()}, clock=clock,
                              ops_sentinel_min_samples=5,
                              ops_sentinel_latency_factor=3)
        t = self._exec_timer(name)
        counter0 = default_registry().family_total(
            "raft_tpu_anomaly_total")
        # window 1: cursor init; windows 2-3: healthy baseline
        sent.tick(force=True)
        for _ in range(2):
            for _ in range(5):
                t.observe(0.002)
            clock.advance(1.0)
            sent.tick(force=True)
        assert not sent.degraded()
        w = sent.status()["watches"]["exec_latency/%s" % name]
        assert w["baseline"] == pytest.approx(0.002, rel=0.5)
        # regression: 10x the baseline trips on ONE window
        t.observe(0.02)
        clock.advance(1.0)
        sent.tick(force=True)
        assert sent.degraded()
        active = sent.active()
        assert [a["rule"] for a in active] == ["exec_latency"]
        assert default_registry().family_total(
            "raft_tpu_anomaly_total") == counter0 + 1
        # breach persists: baseline FROZEN, counter NOT re-bumped
        base_before = sent.status()["watches"][
            "exec_latency/%s" % name]["baseline"]
        t.observe(0.02)
        clock.advance(1.0)
        sent.tick(force=True)
        assert sent.degraded()
        assert sent.status()["watches"][
            "exec_latency/%s" % name]["baseline"] == base_before
        assert default_registry().family_total(
            "raft_tpu_anomaly_total") == counter0 + 1
        # recovery clears and records the clearance event
        for _ in range(5):
            t.observe(0.002)
        clock.advance(1.0)
        sent.tick(force=True)
        assert not sent.degraded()
        cleared = flight.default_recorder().events(
            kind="anomaly_cleared", service=name)
        assert cleared and cleared[-1].attrs["rule"] == "exec_latency"

    def test_quiet_window_neither_trips_nor_learns(self):
        name = _name("sent")
        clock = FakeClock()
        sent = self._sentinel({name: _Dummy()}, clock=clock,
                              ops_sentinel_min_samples=2)
        t = self._exec_timer(name)
        sent.tick(force=True)
        for _ in range(3):
            t.observe(0.005)
        clock.advance(1.0)
        sent.tick(force=True)
        base = sent.status()["watches"]["exec_latency/%s" % name][
            "baseline"]
        clock.advance(1.0)
        sent.tick(force=True)   # no new batches
        assert sent.status()["watches"]["exec_latency/%s" % name][
            "baseline"] == base
        assert not sent.degraded()

    def test_queue_depth_rule(self):
        name = _name("sent")

        class Batcher:
            queue_cap = 100

            def __init__(self):
                self._depth = 0

            def depth(self):
                return self._depth

        svc = _Dummy()
        svc.batcher = Batcher()
        sent = self._sentinel({name: svc},
                              ops_sentinel_queue_frac=0.5)
        sent.tick(force=True)
        assert not sent.degraded()
        svc.batcher._depth = 80
        sent.tick(force=True)
        assert [a["rule"] for a in sent.active()] == ["queue_depth"]
        svc.batcher._depth = 3
        sent.tick(force=True)
        assert not sent.degraded()

    def test_persist_rules(self):
        name = _name("sent")

        class Persist:
            corruption_detected = False
            stats_dict = {"wal_records": 0, "snapshot_age_s": 1.0,
                          "snapshot_interval_s": 30.0,
                          "snapshot_stale": False,
                          "corruption_detected": False}

            def stats(self):
                return dict(self.stats_dict,
                            corruption_detected=self.corruption_detected)

        svc = _Dummy()
        svc._persist = Persist()
        sent = self._sentinel({name: svc},
                              ops_sentinel_wal_records=50)
        sent.tick(force=True)
        assert not sent.degraded()
        svc._persist.stats_dict["wal_records"] = 51
        svc._persist.corruption_detected = True
        svc._persist.stats_dict["snapshot_stale"] = True
        sent.tick(force=True)
        rules = sorted(a["rule"] for a in sent.active())
        assert rules == ["scrub_corruption", "snapshot_age",
                         "wal_depth"]

    def test_slo_burn_rule(self):
        name = _name("sent")
        clock = FakeClock(100.0)
        tracker = flight.slo_for(name, target_s=0.01, objective=0.9,
                                 windows_s=(60.0,), clock=clock)
        svc = _Dummy()
        svc.slo = tracker
        sent = self._sentinel({name: svc}, clock=clock,
                              ops_sentinel_min_samples=5,
                              ops_sentinel_burn=2)
        for _ in range(10):
            tracker.observe("default", 0.001)
        sent.tick(force=True)
        assert not sent.degraded()
        for _ in range(10):
            tracker.observe("default", 0.5)   # all misses: burn = 5
        sent.tick(force=True)
        assert [a["rule"] for a in sent.active()] == ["slo_burn"]

    def test_rate_limit_and_poke(self):
        name = _name("sent")
        clock = FakeClock()
        with config.override(ops_sentinel_interval_s="10"):
            sent = AnomalySentinel(lambda: {name: _Dummy()},
                                   clock=clock)
        assert sent.tick() is True
        assert sent.tick() is False          # inside the interval
        clock.advance(11.0)
        assert sent.tick() is True
        ticks = sent.status()["ticks"]
        sentinel_mod.register(sent)
        try:
            sentinel_mod.poke()              # rate-limited: no-op
            assert sent.status()["ticks"] == ticks
            clock.advance(11.0)
            sentinel_mod.poke()
            assert sent.status()["ticks"] == ticks + 1
        finally:
            sentinel_mod.unregister(sent)

    def test_tile_stall_first_sighting_not_judged(self):
        """Review regression: the first tick sees the pool's LIFETIME
        h2d/stall totals — warmup's inherently-unhidden streams must
        not trip tile_stall on a healthy freshly-watched service."""
        name = _name("sent")
        reg = default_registry()
        h2d = reg.timer("raft_tpu_h2d_seconds",
                        labels=("pool",)).labels(pool=name)
        stall = reg.timer("raft_tpu_h2d_stall_seconds",
                          labels=("pool",)).labels(pool=name)
        h2d.observe(1.0)
        stall.observe(0.9)     # lifetime fraction 0.9 > 0.5 threshold
        sent = self._sentinel({name: _Dummy()},
                              ops_sentinel_stall_frac=0.5)
        sent.tick(force=True)
        assert not sent.degraded()       # first sighting: cursor only
        h2d.observe(1.0)
        stall.observe(0.9)               # a genuinely stalled WINDOW
        sent.tick(force=True)
        assert [a["rule"] for a in sent.active()] == ["tile_stall"]

    def test_broken_services_fn_counted_not_raised(self):
        def boom():
            raise RuntimeError("broken registry")

        sent = AnomalySentinel(boom, interval_s=0.0,
                               clock=FakeClock())
        before = default_registry().family_total(
            "raft_tpu_ops_sentinel_errors_total")
        assert sent.tick(force=True) is True
        assert default_registry().family_total(
            "raft_tpu_ops_sentinel_errors_total") == before + 1


# ---------------------------------------------------------------------- #
# sentinel end to end: injected latency fault -> trip -> tape
# ---------------------------------------------------------------------- #
class TestSentinelIntegration:
    def test_delay_fault_trips_and_tapes(self, index):
        svc = KNNService(index, k=5, max_batch_rows=64,
                         max_wait_ms=0.2, name=_name("sint"))
        svc.warmup()
        plane = OpsPlane(services={svc.name: svc}, port=0,
                         sentinel_interval_s=0.02)
        sent = plane.sentinel
        try:
            for _ in range(30):
                for f in svc.submit_many([index[:3], index[3:7]]):
                    f.result(timeout=30)
                sent.tick(force=True)
            assert not sent.degraded()
            delay_s = 0.2
            with inject_worker(svc.worker, faults.Delay(delay_s)):
                for f in svc.submit_many([index[:3], index[3:7]]):
                    f.result(timeout=60)
                sent.tick(force=True)
            assert "exec_latency" in [a["rule"] for a in sent.active()]
            # /healthz flips degraded (503) while breached
            code, body = _get(plane.url + "/healthz")
            assert code == 503 and body["degraded"] is True
            assert any(a["rule"] == "exec_latency"
                       for a in body["anomalies"])
            # the automatic black box holds the breaching batch: an
            # execute bracket carrying the injected delay
            boxes = [b for b in flight.default_recorder().blackboxes()
                     if b["reason"] == "anomaly_exec_latency"
                     and b["service"] == svc.name]
            assert boxes
            assert any(ev.get("kind") == "execute_ready"
                       and ev.get("exec_s", 0.0) >= delay_s
                       for ev in boxes[-1]["events"])
            # healthy traffic clears the breach and /healthz recovers
            for _ in range(20):
                for f in svc.submit_many([index[:3], index[3:7]]):
                    f.result(timeout=30)
                sent.tick(force=True)
                if not sent.degraded():
                    break
            assert not sent.degraded()
            assert _get(plane.url + "/healthz")[0] == 200
        finally:
            plane.close()
            svc.close()


# ---------------------------------------------------------------------- #
# concurrent scrape under traffic: 16 threads, bit-identical results
# ---------------------------------------------------------------------- #
@pytest.mark.serve
class TestScrapeUnderTraffic:
    def test_sixteen_threads_with_scraper(self, rng):
        index = jnp.asarray(rng.standard_normal((600, 24)), jnp.float32)
        svc = KNNService(index, k=5, max_batch_rows=128,
                         max_wait_ms=0.5, name=_name("traffic"))
        svc.warmup()
        plane = OpsPlane(services={svc.name: svc}, port=0)
        queries = [jnp.asarray(rng.standard_normal((4, 24)),
                               jnp.float32) for _ in range(8)]
        expected = [tuple(np.asarray(a) for a in
                          brute_force_knn(index, q, 5))
                    for q in queries]
        misses0 = _total_misses()
        stop = threading.Event()
        errors = []
        scrape = {"n": 0, "failures": 0}

        def client(tid):
            i = tid
            while not stop.is_set():
                q = queries[i % len(queries)]
                want = expected[i % len(queries)]
                try:
                    d, ids = svc.submit(q).result(timeout=30)
                    if not (np.array_equal(np.asarray(d), want[0])
                            and np.array_equal(np.asarray(ids),
                                               want[1])):
                        errors.append("mismatch")
                        return
                except Exception as e:
                    errors.append(repr(e))
                    return
                i += 1

        def scraper():
            while not stop.is_set():
                try:
                    code, body = _get(plane.url + "/metrics",
                                      timeout=5)
                    assert code == 200
                    parse_prometheus(body)
                    code, _ = _get(plane.url + "/statusz", timeout=5)
                    assert code == 200
                except Exception:
                    scrape["failures"] += 1
                scrape["n"] += 1

        threads = [threading.Thread(target=client, args=(t,),
                                    daemon=True) for t in range(16)]
        threads.append(threading.Thread(target=scraper, daemon=True))
        try:
            for t in threads:
                t.start()
            time.sleep(1.5)
        finally:
            stop.set()
            for t in threads:
                t.join(timeout=30)
        try:
            assert not errors, errors[:3]
            assert scrape["n"] > 3
            assert scrape["failures"] == 0
            # served results stayed bit-identical, the worker loop
            # never stalled (alive + still serving), and the scrape
            # loop compiled NOTHING
            assert svc.worker.is_alive()
            assert _total_misses() == misses0
            # bounded handler latency even while hammered
            fam = default_registry().get("raft_tpu_ops_request_seconds")
            for lbls, series in fam.series():
                if lbls.get("endpoint") in ("/metrics", "/statusz"):
                    assert series.quantile(0.95) < 2.0
        finally:
            plane.close()
            svc.close()


# ---------------------------------------------------------------------- #
# session integration
# ---------------------------------------------------------------------- #
class TestSessionServeOps:
    def test_serve_ops_lifecycle(self, index):
        from raft_tpu.session import Session

        s = Session().init()
        try:
            svc = s.serve(kind="knn", index=index, k=3,
                          max_batch_rows=32, retry_policy=None)
            svc.warmup()
            plane = s.serve_ops(port=0)
            assert s.ops_plane is plane
            # the plane sees the SESSION's registry (live view)
            code, body = _get(plane.url + "/statusz")
            assert code == 200 and svc.name in body["services"]
            # one LIVE plane per session
            with pytest.raises(Exception):
                s.serve_ops(port=0)
            # review regression: manually closing the plane must not
            # brick the session — a fresh one can be started
            plane.close()
            plane2 = s.serve_ops(port=0)
            assert _get(plane2.url + "/healthz")[0] == 200
            url = plane2.url
        finally:
            s.destroy()
        # destroy closed the plane with the session
        assert s.ops_plane is None
        with pytest.raises(Exception):
            urllib.request.urlopen(url + "/healthz", timeout=2)


# ---------------------------------------------------------------------- #
# loadgen ops-scrape scenario
# ---------------------------------------------------------------------- #
class TestLoadgenOpsScrape:
    def test_scenario_report(self, rng):
        from tools.loadgen import build_service, run_ops_scrape

        svc = build_service("knn", 2000, 16, 5, seed=SEED,
                            max_batch_rows=64, max_wait_ms=1.0)
        svc.warmup()
        try:
            rep = run_ops_scrape(svc, port=0, duration=2.0,
                                 concurrency=4, rows=4, seed=SEED)
        finally:
            svc.close()
        assert rep["scrapes"] > 0
        assert rep["scrape_failures"] == 0
        assert rep["post_warmup_compiles"] == 0
        assert rep["ops_port"] > 0
        assert rep["baseline_qps"] > 0 and rep["scraped_qps"] > 0


# ---------------------------------------------------------------------- #
# CI hygiene: the ops-jax ban
# ---------------------------------------------------------------------- #
class TestOpsJaxBanLint:
    def _check(self, tmp_path, relpath, src, monkeypatch):
        import importlib.util

        spec = importlib.util.spec_from_file_location(
            "style_check", os.path.join(os.path.dirname(__file__),
                                        "..", "ci", "style_check.py"))
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        monkeypatch.setattr(mod, "REPO", str(tmp_path))
        path = tmp_path / relpath
        path.parent.mkdir(parents=True, exist_ok=True)
        path.write_text(src)
        return [p for p in mod.check_file(str(path))
                if "ops plane" in p]

    def test_jax_flagged_in_ops_modules(self, tmp_path, monkeypatch):
        for src in ("import jax\n", "from jax import jit\n",
                    "x = jax.devices()\n", "j = jax\n"):
            assert self._check(tmp_path, "raft_tpu/serve/opsplane.py",
                               src, monkeypatch), src
        assert self._check(tmp_path, "raft_tpu/serve/sentinel.py",
                           "import jax.numpy as jnp\n", monkeypatch)

    def test_marker_escapes_and_scope_is_tight(self, tmp_path,
                                               monkeypatch):
        assert not self._check(
            tmp_path, "raft_tpu/serve/opsplane.py",
            "import jax  # ops-jax-ok: fixture\n", monkeypatch)
        assert not self._check(tmp_path, "raft_tpu/serve/opsplane.py",
                               "import json\n", monkeypatch)
        # the rest of serve/ may use jax freely
        assert not self._check(tmp_path, "raft_tpu/serve/scheduler.py",
                               "import jax\n", monkeypatch)

    def test_real_modules_are_clean(self):
        import importlib.util

        spec = importlib.util.spec_from_file_location(
            "style_check", os.path.join(os.path.dirname(__file__),
                                        "..", "ci", "style_check.py"))
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        repo = os.path.join(os.path.dirname(__file__), "..")
        for rel in ("raft_tpu/serve/opsplane.py",
                    "raft_tpu/serve/sentinel.py"):
            assert mod.check_file(os.path.join(repo, rel)) == []
