"""Comms tests on the simulated 8-device CPU mesh.

Mirrors python/raft/test/test_comms.py: every collective / p2p /
comm_split self-test from the reference's test.hpp suite, parameterized,
plus the status-returning sync semantics — but hardware-free (SURVEY.md
§4: virtual-device meshes are strictly better than the reference's
GPU-required `mg` marks).
"""

import numpy as np
import pytest
import jax
import jax.numpy as jnp
from jax.sharding import Mesh

from raft_tpu import Handle
from raft_tpu.comms import (
    HostComms, MeshComms, Op, Status, build_comms, default_mesh, selftest,
)


@pytest.fixture(scope="module")
def comms():
    return HostComms(default_mesh())


def test_mesh_has_8_devices(comms):
    assert comms.get_size() == 8


@pytest.mark.parametrize("fn", selftest.ALL_TESTS, ids=lambda f: f.__name__)
def test_selftest(fn):
    # fresh comms per test: some tests (abort) poison the communicator
    assert fn(HostComms(default_mesh()))


def test_sync_stream_status():
    assert selftest.test_sync_stream_status(HostComms(default_mesh()))


def test_allreduce_ops(comms):
    size = comms.get_size()
    x = jnp.arange(1, size + 1, dtype=jnp.float32)[:, None]
    assert np.asarray(comms.allreduce(x, Op.SUM))[0, 0] == size * (size + 1) / 2
    assert np.asarray(comms.allreduce(x, Op.MAX))[0, 0] == size
    assert np.asarray(comms.allreduce(x, Op.MIN))[0, 0] == 1
    got = np.asarray(comms.allreduce(x, Op.PROD))[0, 0]
    assert got == float(np.prod(np.arange(1, size + 1, dtype=np.float64)))


def test_bcast_nonzero_root(comms):
    size = comms.get_size()
    x = jnp.zeros((size, 2)).at[3].set(7.0)
    out = comms.bcast(x, root=3)
    assert (np.asarray(out) == 7.0).all()


def test_allgatherv_roundtrip(comms):
    size = comms.get_size()
    counts = [(r % 3) + 1 for r in range(size)]
    maxc = max(counts)
    buf = np.zeros((size, maxc), np.float32)
    for r in range(size):
        buf[r, : counts[r]] = np.arange(counts[r]) + 10 * r
    out = np.asarray(comms.allgatherv(jnp.asarray(buf), counts))
    expected = np.concatenate(
        [np.arange(c) + 10 * r for r, c in enumerate(counts)])
    for r in range(size):
        np.testing.assert_allclose(out[r], expected)


def test_p2p_tags_do_not_cross(comms):
    """Two rings with different tags resolve independently."""
    size = comms.get_size()
    recv_a, recv_b = [], []
    for r in range(size):
        comms.isend(jnp.full((1,), float(r)), rank=r, dest=(r + 1) % size, tag=1)
        comms.isend(jnp.full((1,), float(100 + r)), rank=r, dest=(r - 1) % size, tag=2)
        recv_a.append(comms.irecv(rank=r, source=(r - 1) % size, tag=1))
        recv_b.append(comms.irecv(rank=r, source=(r + 1) % size, tag=2))
    comms.waitall()
    for r in range(size):
        assert float(recv_a[r].result[0]) == float((r - 1) % size)
        assert float(recv_b[r].result[0]) == float(100 + (r + 1) % size)


def test_allgather_wide_blocks(comms):
    """(size, n) -> (size, size*n) with n > 1 (regression: block passed
    un-squeezed produced (size, size, n))."""
    size = comms.get_size()
    x = jnp.arange(size * 3, dtype=jnp.float32).reshape(size, 3)
    out = np.asarray(comms.allgather(x))
    assert out.shape == (size, size * 3)
    for r in range(size):
        np.testing.assert_allclose(out[r], np.arange(size * 3))


def test_allgather_reducescatter_roundtrip(comms):
    size = comms.get_size()
    x = jnp.ones((size, 2), jnp.float32)
    gathered = comms.allgather(x)          # (size, size*2)
    back = comms.reducescatter(gathered)   # (size, 2), each summed size times
    assert np.asarray(back).shape == (size, 2)
    assert (np.asarray(back) == size).all()


def test_waitall_consecutive_phases():
    """Two p2p phases on one communicator (regression: waitall mutated
    its own queue while iterating, leaving stale requests)."""
    comms = HostComms(default_mesh())
    size = comms.get_size()
    for phase in range(2):
        recvs = []
        for r in range(size):
            comms.isend(jnp.full((2,), float(phase * 10 + r)), rank=r,
                        dest=(r + 1) % size, tag=phase)
            recvs.append(comms.irecv(rank=r, source=(r - 1) % size, tag=phase))
        comms.waitall()
        assert comms._requests == []
        for r in range(size):
            assert float(recvs[r].result[0]) == phase * 10 + (r - 1) % size


def test_waitall_fanout_same_tag():
    """One rank sends to two peers with the same tag: must split into
    disjoint ppermute layers, not crash."""
    comms = HostComms(default_mesh())
    comms.isend(jnp.full((1,), 1.0), rank=0, dest=1, tag=5)
    comms.isend(jnp.full((1,), 2.0), rank=0, dest=2, tag=5)
    r1 = comms.irecv(rank=1, source=0, tag=5)
    r2 = comms.irecv(rank=2, source=0, tag=5)
    comms.waitall()
    assert float(r1.result[0]) == 1.0 and float(r2.result[0]) == 2.0


def test_multicast_int_payload_exact(comms):
    """Integer payloads above 2^24 survive multicast exactly (regression:
    float32 routing matmul dropped low bits)."""
    size = comms.get_size()
    big = 2**24 + 1
    x = jnp.zeros((size, 1), jnp.int32).at[0, 0].set(big)
    out = np.asarray(comms.device_multicast_sendrecv(
        x, [(0, d) for d in range(size)]))
    assert (out == big).all()


def test_waitall_unmatched_raises(comms):
    comms.isend(jnp.ones((1,)), rank=0, dest=1, tag=99)
    with pytest.raises(Exception):
        comms.waitall()


def test_comm_split_keys_reorder():
    comms = HostComms(default_mesh())
    size = comms.get_size()
    # one color, reversed keys: rank order inside the subcomm flips
    subs = comms.comm_split([0] * size, keys=list(range(size))[::-1])
    assert subs[0].get_size() == size
    assert selftest.test_collective_allreduce(subs[0])


def test_subcomm_2d_grid():
    """2D subcommunicator pattern (reference handle.set_subcomm +
    test_subcomm_func in python/raft/test/test_comms.py): 8 ranks as a
    4x2 grid with row and column splits."""
    comms = HostComms(default_mesh())
    rows = comms.comm_split([r // 2 for r in range(8)])   # 4 row comms
    cols = comms.comm_split([r % 2 for r in range(8)])    # 2 col comms
    assert len(rows) == 4 and all(c.get_size() == 2 for c in rows.values())
    assert len(cols) == 2 and all(c.get_size() == 4 for c in cols.values())
    for c in list(rows.values()) + list(cols.values()):
        assert selftest.test_collective_allreduce(c)


def test_handle_injection():
    handle = Handle()
    comms = build_comms(handle)
    assert handle.comms_initialized()
    assert handle.get_comms() is comms
    handle.set_subcomm("rows", comms.comm_split([0] * 8)[0])
    assert handle.get_subcomm("rows").get_size() == 8


def test_mesh_comms_in_user_shard_map():
    """MeshComms used directly inside user shard_map code — the idiomatic
    in-trace path."""
    from raft_tpu.comms.host_comms import shard_map
    from jax.sharding import PartitionSpec as P

    mesh = default_mesh()
    mc = MeshComms("ranks", 8)

    def fn(x):
        local_sum = jnp.sum(x)
        total = mc.allreduce(local_sum)
        return (x / total)[None]  # keep a rank axis for out_specs

    x = jnp.arange(8.0 * 4).reshape(8, 4) + 1
    f = shard_map(fn, mesh=mesh, in_specs=P("ranks"), out_specs=P("ranks"),
                  check_rep=False)
    out = jax.jit(f)(x)
    np.testing.assert_allclose(np.asarray(out).sum(), 1.0, rtol=1e-6)
