"""Comms tests on the simulated 8-device CPU mesh.

Mirrors python/raft/test/test_comms.py: every collective / p2p /
comm_split self-test from the reference's test.hpp suite, parameterized,
plus the status-returning sync semantics — but hardware-free (SURVEY.md
§4: virtual-device meshes are strictly better than the reference's
GPU-required `mg` marks).
"""

import numpy as np
import pytest
import jax
import jax.numpy as jnp
from jax.sharding import Mesh

from raft_tpu import Handle
from raft_tpu.comms import (
    HostComms, MeshComms, Op, Status, build_comms, default_mesh, selftest,
)


@pytest.fixture(scope="module")
def comms():
    return HostComms(default_mesh())


def test_mesh_has_8_devices(comms):
    assert comms.get_size() == 8


@pytest.mark.parametrize("fn", selftest.ALL_TESTS, ids=lambda f: f.__name__)
def test_selftest(fn):
    # fresh comms per test: some tests (abort) poison the communicator
    assert fn(HostComms(default_mesh()))


def test_sync_stream_status():
    assert selftest.test_sync_stream_status(HostComms(default_mesh()))


def test_allreduce_ops(comms):
    size = comms.get_size()
    x = jnp.arange(1, size + 1, dtype=jnp.float32)[:, None]
    assert np.asarray(comms.allreduce(x, Op.SUM))[0, 0] == size * (size + 1) / 2
    assert np.asarray(comms.allreduce(x, Op.MAX))[0, 0] == size
    assert np.asarray(comms.allreduce(x, Op.MIN))[0, 0] == 1
    got = np.asarray(comms.allreduce(x, Op.PROD))[0, 0]
    assert got == float(np.prod(np.arange(1, size + 1, dtype=np.float64)))


def test_bcast_nonzero_root(comms):
    size = comms.get_size()
    x = jnp.zeros((size, 2)).at[3].set(7.0)
    out = comms.bcast(x, root=3)
    assert (np.asarray(out) == 7.0).all()


def test_allgatherv_roundtrip(comms):
    size = comms.get_size()
    counts = [(r % 3) + 1 for r in range(size)]
    maxc = max(counts)
    buf = np.zeros((size, maxc), np.float32)
    for r in range(size):
        buf[r, : counts[r]] = np.arange(counts[r]) + 10 * r
    out = np.asarray(comms.allgatherv(jnp.asarray(buf), counts))
    expected = np.concatenate(
        [np.arange(c) + 10 * r for r, c in enumerate(counts)])
    for r in range(size):
        np.testing.assert_allclose(out[r], expected)


def test_p2p_tags_do_not_cross(comms):
    """Two rings with different tags resolve independently."""
    size = comms.get_size()
    recv_a, recv_b = [], []
    for r in range(size):
        comms.isend(jnp.full((1,), float(r)), rank=r, dest=(r + 1) % size, tag=1)
        comms.isend(jnp.full((1,), float(100 + r)), rank=r, dest=(r - 1) % size, tag=2)
        recv_a.append(comms.irecv(rank=r, source=(r - 1) % size, tag=1))
        recv_b.append(comms.irecv(rank=r, source=(r + 1) % size, tag=2))
    comms.waitall()
    for r in range(size):
        assert float(recv_a[r].result[0]) == float((r - 1) % size)
        assert float(recv_b[r].result[0]) == float(100 + (r + 1) % size)


def test_allgather_wide_blocks(comms):
    """(size, n) -> (size, size*n) with n > 1 (regression: block passed
    un-squeezed produced (size, size, n))."""
    size = comms.get_size()
    x = jnp.arange(size * 3, dtype=jnp.float32).reshape(size, 3)
    out = np.asarray(comms.allgather(x))
    assert out.shape == (size, size * 3)
    for r in range(size):
        np.testing.assert_allclose(out[r], np.arange(size * 3))


def test_allgather_reducescatter_roundtrip(comms):
    size = comms.get_size()
    x = jnp.ones((size, 2), jnp.float32)
    gathered = comms.allgather(x)          # (size, size*2)
    back = comms.reducescatter(gathered)   # (size, 2), each summed size times
    assert np.asarray(back).shape == (size, 2)
    assert (np.asarray(back) == size).all()


def test_waitall_consecutive_phases():
    """Two p2p phases on one communicator (regression: waitall mutated
    its own queue while iterating, leaving stale requests)."""
    comms = HostComms(default_mesh())
    size = comms.get_size()
    for phase in range(2):
        recvs = []
        for r in range(size):
            comms.isend(jnp.full((2,), float(phase * 10 + r)), rank=r,
                        dest=(r + 1) % size, tag=phase)
            recvs.append(comms.irecv(rank=r, source=(r - 1) % size, tag=phase))
        comms.waitall()
        assert comms._requests == []
        for r in range(size):
            assert float(recvs[r].result[0]) == phase * 10 + (r - 1) % size


def test_waitall_fanout_same_tag():
    """One rank sends to two peers with the same tag: must split into
    disjoint ppermute layers, not crash."""
    comms = HostComms(default_mesh())
    comms.isend(jnp.full((1,), 1.0), rank=0, dest=1, tag=5)
    comms.isend(jnp.full((1,), 2.0), rank=0, dest=2, tag=5)
    r1 = comms.irecv(rank=1, source=0, tag=5)
    r2 = comms.irecv(rank=2, source=0, tag=5)
    comms.waitall()
    assert float(r1.result[0]) == 1.0 and float(r2.result[0]) == 2.0


def _host_staged_bytes(require=True):
    """Total of the raft_tpu_comms_host_staged_bytes counter (waitall
    always materializes the family, so a zero is a measurement —
    ``require=False`` for a baseline read before any waitall ran)."""
    from raft_tpu.core.metrics import default_registry
    reg = default_registry()
    if reg.get("raft_tpu_comms_host_staged_bytes") is None:
        assert not require, "waitall must materialize the counter"
    return reg.family_total("raft_tpu_comms_host_staged_bytes")


def test_waitall_mixed_shapes_device_path_zero_host_staged():
    """ONE waitall with heterogeneous shapes AND dtypes (the old
    uniform-shape restriction is gone) on the default device-resident
    path: every payload routes correctly and the host-staged-bytes
    counter stays at zero — no payload byte bounced through numpy.
    Measured as a DELTA: the counter is process-global, and an earlier
    host-staged waitall in the same process legitimately leaves it
    non-zero."""
    comms = HostComms(default_mesh())          # p2p_staging="device"
    size = comms.get_size()
    before = _host_staged_bytes(require=False)
    f32_recvs, i32_recvs = [], []
    for r in range(size):
        comms.isend(jnp.full((2, 3), float(10 * r), jnp.float32),
                    rank=r, dest=(r + 1) % size, tag=1)
        comms.isend(jnp.full((5,), 1000 + r, jnp.int32),
                    rank=r, dest=(r - 1) % size, tag=2)
        f32_recvs.append(comms.irecv(rank=r, source=(r - 1) % size, tag=1))
        i32_recvs.append(comms.irecv(rank=r, source=(r + 1) % size, tag=2))
    # plus a lone odd-shaped pair riding the same waitall
    comms.isend(jnp.arange(7, dtype=jnp.float32), rank=0, dest=3, tag=3)
    lone = comms.irecv(rank=3, source=0, tag=3)

    comms.waitall()
    assert _host_staged_bytes() - before == 0

    for r in range(size):
        got = np.asarray(f32_recvs[r].result)
        assert got.shape == (2, 3) and got.dtype == np.float32
        assert (got == 10 * ((r - 1) % size)).all()
        got = np.asarray(i32_recvs[r].result)
        assert got.shape == (5,) and got.dtype == np.int32
        assert (got == 1000 + (r + 1) % size).all()
    np.testing.assert_array_equal(np.asarray(lone.result),
                                  np.arange(7, dtype=np.float32))


def test_waitall_ppermute_committed_rows_mixed_devices():
    """Resending per-rank COMMITTED arrays (e.g. a prior round's direct
    p2p results, each living on its own device) through the ppermute
    staging path: the on-device assembly must normalize placements —
    a naive jnp.stack over rows committed to distinct devices raises
    "incompatible devices" (regression)."""
    import jax
    comms = HostComms(default_mesh(), p2p_staging="ppermute")
    size = comms.get_size()
    devs = list(comms.mesh.devices.ravel())
    before = _host_staged_bytes(require=False)
    sends = [jax.device_put(jnp.full((2,), float(r), jnp.float32),
                            devs[r]) for r in range(size)]
    recvs = []
    for r in range(size):
        comms.isend(sends[r], rank=r, dest=(r + 1) % size, tag=11)
        recvs.append(comms.irecv(rank=r, source=(r - 1) % size, tag=11))
    comms.waitall()
    assert _host_staged_bytes() - before == 0  # still zero-copy
    for r in range(size):
        assert float(recvs[r].result[0]) == float((r - 1) % size)


def test_waitall_host_staging_counts_bytes():
    """The staging="host" baseline routes identically but COUNTS its
    numpy bounce — the measurable contrast to the device path's zero."""
    comms = HostComms(default_mesh())
    size = comms.get_size()
    recvs = []
    for r in range(size):
        comms.isend(jnp.full((4,), float(r), jnp.float32), rank=r,
                    dest=(r + 1) % size, tag=0)
        recvs.append(comms.irecv(rank=r, source=(r - 1) % size, tag=0))
    before = _host_staged_bytes(require=False)
    comms.waitall(staging="host")
    # one (size, 4) f32 rank-major staging buffer bounced through host
    assert _host_staged_bytes() - before == size * 4 * 4
    for r in range(size):
        assert float(recvs[r].result[0]) == float((r - 1) % size)


def test_p2p_bytes_total_consistent_across_stagings():
    """raft_tpu_comms_bytes_total{verb=p2p} means the same thing on
    every staging arm: actual send-row bytes, NOT the rank-major
    staging buffer with its blank rows.  A sparse pattern (one matched
    pair on the full mesh) is the worst case — counting the staging
    buffer would inflate the collective arms by a factor of
    get_size() and break the bench rung's A/B comparison
    (regression)."""
    from raft_tpu.core.metrics import default_registry

    comms = HostComms(default_mesh())

    def p2p_bytes():
        fam = default_registry().get("raft_tpu_comms_bytes_total")
        if fam is None:
            return 0.0
        return sum(s.value for labels, s in fam.series()
                   if labels.get("verb") == "p2p")

    payload = jnp.arange(6, dtype=jnp.float32).reshape(2, 3)
    deltas = {}
    for staging in ("device", "ppermute", "host"):
        comms.isend(payload, rank=0, dest=1, tag=21)
        rq = comms.irecv(rank=1, source=0, tag=21)
        before = p2p_bytes()
        comms.waitall(staging=staging)
        deltas[staging] = p2p_bytes() - before
        np.testing.assert_array_equal(np.asarray(rq.result),
                                      np.asarray(payload))
    assert deltas["device"] == payload.nbytes, deltas
    assert deltas["ppermute"] == payload.nbytes, deltas
    assert deltas["host"] == payload.nbytes, deltas


def test_multicast_int_payload_exact(comms):
    """Integer payloads above 2^24 survive multicast exactly (regression:
    float32 routing matmul dropped low bits)."""
    size = comms.get_size()
    big = 2**24 + 1
    x = jnp.zeros((size, 1), jnp.int32).at[0, 0].set(big)
    out = np.asarray(comms.device_multicast_sendrecv(
        x, [(0, d) for d in range(size)]))
    assert (out == big).all()


def test_waitall_unmatched_raises(comms):
    comms.isend(jnp.ones((1,)), rank=0, dest=1, tag=99)
    with pytest.raises(Exception):
        comms.waitall()


def test_comm_split_keys_reorder():
    comms = HostComms(default_mesh())
    size = comms.get_size()
    # one color, reversed keys: rank order inside the subcomm flips
    subs = comms.comm_split([0] * size, keys=list(range(size))[::-1])
    assert subs[0].get_size() == size
    assert selftest.test_collective_allreduce(subs[0])


def test_subcomm_2d_grid():
    """2D subcommunicator pattern (reference handle.set_subcomm +
    test_subcomm_func in python/raft/test/test_comms.py): 8 ranks as a
    4x2 grid with row and column splits."""
    comms = HostComms(default_mesh())
    rows = comms.comm_split([r // 2 for r in range(8)])   # 4 row comms
    cols = comms.comm_split([r % 2 for r in range(8)])    # 2 col comms
    assert len(rows) == 4 and all(c.get_size() == 2 for c in rows.values())
    assert len(cols) == 2 and all(c.get_size() == 4 for c in cols.values())
    for c in list(rows.values()) + list(cols.values()):
        assert selftest.test_collective_allreduce(c)


def test_handle_injection():
    handle = Handle()
    comms = build_comms(handle)
    assert handle.comms_initialized()
    assert handle.get_comms() is comms
    handle.set_subcomm("rows", comms.comm_split([0] * 8)[0])
    assert handle.get_subcomm("rows").get_size() == 8


def test_mesh_comms_in_user_shard_map():
    """MeshComms used directly inside user shard_map code — the idiomatic
    in-trace path."""
    from raft_tpu.comms.host_comms import shard_map
    from jax.sharding import PartitionSpec as P

    mesh = default_mesh()
    mc = MeshComms("ranks", 8)

    def fn(x):
        local_sum = jnp.sum(x)
        total = mc.allreduce(local_sum)
        return (x / total)[None]  # keep a rank axis for out_specs

    x = jnp.arange(8.0 * 4).reshape(8, 4) + 1
    f = shard_map(fn, mesh=mesh, in_specs=P("ranks"), out_specs=P("ranks"),
                  check_rep=False)
    out = jax.jit(f)(x)
    np.testing.assert_allclose(np.asarray(out).sum(), 1.0, rtol=1e-6)


# ---------------------------------------------------------------------- #
# CI hygiene: the comms host-numpy payload ban (docs/ZERO_COPY.md)
# ---------------------------------------------------------------------- #
class TestCommsNumpyBan:
    def _check(self, tmp_path, relpath, src, monkeypatch):
        import importlib.util
        import os

        spec = importlib.util.spec_from_file_location(
            "style_check_np", os.path.join(os.path.dirname(__file__),
                                           "..", "ci", "style_check.py"))
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        monkeypatch.setattr(mod, "REPO", str(tmp_path))
        path = tmp_path / relpath
        path.parent.mkdir(parents=True, exist_ok=True)
        path.write_text(src)
        return mod.check_file(str(path))

    def test_np_asarray_in_comms_flagged(self, tmp_path, monkeypatch):
        src = ("import numpy as np\n"
               "def stage(x):\n"
               "    return np.asarray(x)\n")
        probs = self._check(tmp_path, "raft_tpu/comms/bad.py", src,
                            monkeypatch)
        assert any("np.asarray" in p for p in probs)
        probs = self._check(tmp_path, "raft_tpu/comms/bad2.py",
                            "from numpy import asarray\n", monkeypatch)
        assert any("array/asarray" in p for p in probs)

    def test_marker_and_allowlist_exempt(self, tmp_path, monkeypatch):
        marked = ("import numpy as np\n"
                  "def mesh(devs):\n"
                  "    return np.asarray(devs)  # comms-host-ok: handles\n")
        assert self._check(tmp_path, "raft_tpu/comms/ok.py", marked,
                           monkeypatch) == []
        # the marker the error message prescribes works on the
        # from-import form too (regression)
        marked_import = ("from numpy import asarray"
                         "  # comms-host-ok: device handles\n")
        assert self._check(tmp_path, "raft_tpu/comms/ok_imp.py",
                           marked_import, monkeypatch) == []
        unmarked = ("import numpy as np\n"
                    "def probe(x):\n"
                    "    return np.asarray(x)\n")
        assert self._check(tmp_path, "raft_tpu/comms/selftest.py",
                           unmarked, monkeypatch) == []
        assert self._check(tmp_path, "raft_tpu/comms/faults.py",
                           unmarked, monkeypatch) == []
        # outside comms/ the ban does not apply
        assert self._check(tmp_path, "raft_tpu/spatial/ok.py",
                           unmarked, monkeypatch) == []

    def test_real_comms_tree_is_clean(self):
        """The ACTUAL raft_tpu/comms/ files pass the ban (the zero-copy
        guarantee is enforced, not aspirational)."""
        import importlib.util
        import os

        repo = os.path.join(os.path.dirname(__file__), "..")
        spec = importlib.util.spec_from_file_location(
            "style_check_live", os.path.join(repo, "ci",
                                             "style_check.py"))
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        comms_dir = os.path.join(repo, "raft_tpu", "comms")
        problems = []
        for fname in sorted(os.listdir(comms_dir)):
            if fname.endswith(".py"):
                problems.extend(
                    mod.check_file(os.path.join(comms_dir, fname)))
        assert problems == []
