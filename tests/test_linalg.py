"""Dense linalg tests vs naive numpy references.

Mirrors the reference's parameterized-vs-naive-kernel strategy
(cpp/test/linalg/*.cu, e.g. test/linalg/norm.cu, reduce.cu, eig.cu).
"""

import jax.numpy as jnp
import numpy as np
import pytest

from raft_tpu import linalg

SIZES = [(16, 8), (64, 33), (128, 128)]


def _rand(rng, shape, dtype=np.float64):
    return rng.standard_normal(shape).astype(dtype)


class TestGemm:
    @pytest.mark.parametrize("ta,tb", [(False, False), (True, False), (False, True), (True, True)])
    def test_gemm_transposes(self, rng, ta, tb):
        a = _rand(rng, (12, 7) if not ta else (7, 12))
        b = _rand(rng, (7, 9) if not tb else (9, 7))
        out = linalg.gemm(a, b, trans_a=ta, trans_b=tb)
        ref = (a.T if ta else a) @ (b.T if tb else b)
        np.testing.assert_allclose(np.asarray(out), ref, rtol=1e-10)

    def test_gemm_alpha_beta(self, rng):
        a, b = _rand(rng, (5, 4)), _rand(rng, (4, 6))
        c = _rand(rng, (5, 6))
        out = linalg.gemm(a, b, alpha=2.5, beta=-0.5, c=c)
        np.testing.assert_allclose(np.asarray(out), 2.5 * a @ b - 0.5 * c, rtol=1e-10)

    def test_gemm_shape_error(self, rng):
        from raft_tpu import RaftError

        with pytest.raises(RaftError):
            linalg.gemm(_rand(rng, (3, 4)), _rand(rng, (5, 6)))

    def test_gemv(self, rng):
        a, x, y = _rand(rng, (8, 5)), _rand(rng, (5,)), _rand(rng, (8,))
        out = linalg.gemv(a, x, alpha=3.0, beta=1.0, y=y)
        np.testing.assert_allclose(np.asarray(out), 3.0 * a @ x + y, rtol=1e-10)
        out_t = linalg.gemv(a, y, trans_a=True)
        np.testing.assert_allclose(np.asarray(out_t), a.T @ y, rtol=1e-10)


class TestEig:
    def _sym(self, rng, n):
        a = _rand(rng, (n, n))
        return (a + a.T) / 2

    def test_eig_dc_reconstruction(self, rng):
        a = self._sym(rng, 20)
        v, w = linalg.eig_dc(a)
        np.testing.assert_allclose(
            np.asarray(v) @ np.diag(np.asarray(w)) @ np.asarray(v).T, a, atol=1e-8)
        assert np.all(np.diff(np.asarray(w)) >= -1e-12)

    @pytest.mark.parametrize("largest", [False, True])
    def test_eig_sel(self, rng, largest):
        a = self._sym(rng, 16)
        v, w = linalg.eig_sel_dc(a, 4, largest=largest)
        ref_w = np.linalg.eigvalsh(a)
        expect = ref_w[-4:] if largest else ref_w[:4]
        np.testing.assert_allclose(np.asarray(w), expect, atol=1e-8)
        assert v.shape == (16, 4)

    def test_eig_jacobi_matches_dc(self, rng):
        a = self._sym(rng, 10)
        _, w1 = linalg.eig_dc(a)
        _, w2 = linalg.eig_jacobi(a, tol=1e-8, sweeps=20)
        np.testing.assert_allclose(np.asarray(w1), np.asarray(w2), atol=1e-10)


class TestSvd:
    @pytest.mark.parametrize("m,n", [(20, 8), (16, 16)])
    def test_svd_qr(self, rng, m, n):
        a = _rand(rng, (m, n))
        u, s, v = linalg.svd_qr(a)
        np.testing.assert_allclose(
            np.asarray(linalg.svd_reconstruction(u, s, v)), a, atol=1e-8
        )
        assert linalg.svd.evaluate_svd_by_l2_norm(a, u, s, v, 1e-6)

    def test_svd_eig_matches_svd_qr_values(self, rng):
        a = _rand(rng, (30, 6))
        _, s_ref, _ = linalg.svd_qr(a)
        u, s, v = linalg.svd_eig(a)
        np.testing.assert_allclose(np.asarray(s), np.asarray(s_ref), rtol=1e-6)
        np.testing.assert_allclose(
            np.asarray(linalg.svd_reconstruction(u, s, v)), a, atol=1e-6
        )

    def test_svd_eig_requires_tall(self, rng):
        from raft_tpu import RaftError

        with pytest.raises(RaftError):
            linalg.svd_eig(_rand(rng, (4, 8)))


class TestQr:
    def test_qr(self, rng):
        a = _rand(rng, (12, 5))
        q, r = linalg.qr_get_qr(a)
        np.testing.assert_allclose(np.asarray(q) @ np.asarray(r), a, atol=1e-10)
        np.testing.assert_allclose(np.asarray(q).T @ np.asarray(q), np.eye(5), atol=1e-10)
        q2 = linalg.qr_get_q(a)
        np.testing.assert_allclose(np.abs(np.asarray(q2)), np.abs(np.asarray(q)), atol=1e-10)


class TestCholesky:
    def test_rank1_update_builds_full_factor(self, rng):
        n = 8
        b = _rand(rng, (n, n))
        a = b @ b.T + n * np.eye(n)
        ref_l = np.linalg.cholesky(a)
        # incrementally build the factor row by row like the SVM use case
        work = np.zeros((n, n))
        for k in range(1, n + 1):
            work[k - 1, :k] = a[k - 1, :k]
            work = np.array(linalg.cholesky_rank1_update(jnp.array(work), k))
        np.testing.assert_allclose(np.tril(work), ref_l, atol=1e-8)


class TestElementwise:
    def test_ops(self, rng):
        x, y = _rand(rng, (6, 6)), _rand(rng, (6, 6))
        np.testing.assert_allclose(np.asarray(linalg.eltwise_add(x, y)), x + y)
        np.testing.assert_allclose(np.asarray(linalg.eltwise_sub(x, y)), x - y)
        np.testing.assert_allclose(np.asarray(linalg.eltwise_multiply(x, y)), x * y)
        np.testing.assert_allclose(np.asarray(linalg.eltwise_divide(x, y)), x / y)
        np.testing.assert_allclose(np.asarray(linalg.add_scalar(x, 2.0)), x + 2)
        np.testing.assert_allclose(np.asarray(linalg.multiply_scalar(x, 3.0)), x * 3)
        np.testing.assert_allclose(
            np.asarray(linalg.unary_op(x, lambda v: v * v)), x * x
        )
        np.testing.assert_allclose(
            np.asarray(linalg.binary_op(x, y, lambda a, b: a * b + 1)), x * y + 1
        )

    def test_divide_check_zero(self):
        x = jnp.array([1.0, 2.0, 3.0])
        y = jnp.array([2.0, 0.0, 4.0])
        out = linalg.elementwise.eltwise_divide_check_zero(x, y)
        np.testing.assert_allclose(np.asarray(out), [0.5, 0.0, 0.75])


class TestReduce:
    @pytest.mark.parametrize("shape", SIZES)
    def test_coalesced_sum(self, rng, shape):
        x = _rand(rng, shape)
        out = linalg.coalesced_reduction(jnp.array(x))
        np.testing.assert_allclose(np.asarray(out), x.sum(axis=1), rtol=1e-10)

    def test_strided_sum(self, rng):
        x = _rand(rng, (32, 9))
        out = linalg.strided_reduction(jnp.array(x))
        np.testing.assert_allclose(np.asarray(out), x.sum(axis=0), rtol=1e-10)

    def test_reduce_lambdas(self, rng):
        # L2-norm built from lambdas like the reference's norm tests
        x = _rand(rng, (10, 7))
        out = linalg.reduce(
            jnp.array(x),
            along_rows=True,
            main_op=lambda v, i: v * v,
            final_op=jnp.sqrt,
        )
        np.testing.assert_allclose(np.asarray(out), np.linalg.norm(x, axis=1), rtol=1e-10)

    def test_reduce_custom_reduce_op(self, rng):
        x = np.abs(_rand(rng, (8, 5))) + 0.1
        out = linalg.reduce(
            jnp.array(x),
            along_rows=False,
            reduce_op=jnp.maximum,
            init=-np.inf,
        )
        np.testing.assert_allclose(np.asarray(out), x.max(axis=0), rtol=1e-10)

    def test_map_then_reduce(self, rng):
        x, y = _rand(rng, (40,)), _rand(rng, (40,))
        out = linalg.map_then_sum_reduce(lambda a, b: (a - b) ** 2, jnp.array(x), jnp.array(y))
        np.testing.assert_allclose(float(out), ((x - y) ** 2).sum(), rtol=1e-10)


class TestNorm:
    @pytest.mark.parametrize("shape", SIZES)
    def test_row_norms(self, rng, shape):
        x = _rand(rng, shape)
        np.testing.assert_allclose(
            np.asarray(linalg.row_norm(jnp.array(x), linalg.L1Norm)),
            np.abs(x).sum(axis=1), rtol=1e-10)
        np.testing.assert_allclose(
            np.asarray(linalg.row_norm(jnp.array(x), linalg.L2Norm)),
            (x * x).sum(axis=1), rtol=1e-10)
        np.testing.assert_allclose(
            np.asarray(linalg.row_norm(jnp.array(x), linalg.L2Norm, do_sqrt=True)),
            np.linalg.norm(x, axis=1), rtol=1e-10)
        np.testing.assert_allclose(
            np.asarray(linalg.row_norm(jnp.array(x), linalg.LinfNorm)),
            np.abs(x).max(axis=1), rtol=1e-10)

    def test_col_norm_fin_op(self, rng):
        x = _rand(rng, (20, 6))
        out = linalg.col_norm(jnp.array(x), linalg.L2Norm, do_sqrt=True, fin_op=lambda v: 1.0 / v)
        np.testing.assert_allclose(np.asarray(out), 1.0 / np.linalg.norm(x, axis=0), rtol=1e-10)

    def test_mse(self, rng):
        a, b = _rand(rng, (50,)), _rand(rng, (50,))
        out = linalg.mean_squared_error(jnp.array(a), jnp.array(b), weight=2.0)
        np.testing.assert_allclose(float(out), 2.0 * ((a - b) ** 2).mean(), rtol=1e-10)


class TestMatrixVectorOp:
    def test_bcast_rows(self, rng):
        m, v = _rand(rng, (6, 4)), _rand(rng, (4,))
        out = linalg.matrix_vector_op(jnp.array(m), jnp.array(v), lambda a, b: a + b)
        np.testing.assert_allclose(np.asarray(out), m + v[None, :], rtol=1e-10)

    def test_bcast_cols_two_vecs(self, rng):
        m, v1, v2 = _rand(rng, (6, 4)), _rand(rng, (6,)), _rand(rng, (6,))
        out = linalg.matrix_vector_op(
            jnp.array(m), jnp.array(v1), lambda a, b, c: (a - b) / c,
            bcast_along_rows=False, vec2=jnp.array(v2))
        np.testing.assert_allclose(np.asarray(out), (m - v1[:, None]) / v2[:, None], rtol=1e-10)

    def test_length_mismatch(self, rng):
        from raft_tpu import RaftError

        with pytest.raises(RaftError):
            linalg.matrix_vector_op(jnp.zeros((3, 4)), jnp.zeros(5), lambda a, b: a + b)


class TestMisc:
    def test_transpose(self, rng):
        x = _rand(rng, (5, 9))
        np.testing.assert_allclose(np.asarray(linalg.transpose(jnp.array(x))), x.T)

    def test_range_init(self):
        np.testing.assert_array_equal(np.asarray(linalg.range_init(3, 10)), np.arange(3, 10))


class TestLanczos:
    def test_smallest_dense(self, rng):
        n = 60
        b = _rand(rng, (n, n), np.float64)
        a = jnp.array((b + b.T) / 2)
        vals, vecs, iters = linalg.compute_smallest_eigenvectors(a, n, 3, tol=1e-9)
        ref = np.linalg.eigvalsh(np.asarray(a))[:3]
        np.testing.assert_allclose(np.asarray(vals), ref, atol=1e-6)
        # residual check: ||A v - lambda v|| small
        r = np.asarray(a) @ np.asarray(vecs) - np.asarray(vecs) * np.asarray(vals)[None, :]
        assert np.linalg.norm(r, axis=0).max() < 1e-5
        assert iters > 0

    def test_largest_matvec_operator(self, rng):
        n = 40
        b = _rand(rng, (n, n), np.float64)
        a = (b + b.T) / 2
        a_j = jnp.array(a)
        vals, vecs, _ = linalg.compute_largest_eigenvectors(lambda x: a_j @ x, n, 2, tol=1e-9)
        # operator path needs explicit float dtype handling
        ref = np.linalg.eigvalsh(a)[-2:][::-1]
        np.testing.assert_allclose(np.asarray(vals), ref, atol=1e-5)

    def test_k_out_of_range(self, rng):
        from raft_tpu import RaftError

        with pytest.raises(RaftError):
            linalg.compute_smallest_eigenvectors(jnp.eye(5), 5, 5)


class TestLanczosDegenerate:
    """Regression: Krylov exhaustion must not fabricate zero-residual pairs."""

    def test_identity(self):
        vals, vecs, _ = linalg.compute_smallest_eigenvectors(jnp.eye(60), 60, 3)
        np.testing.assert_allclose(np.asarray(vals), [1.0, 1.0, 1.0], atol=1e-8)
        norms = np.linalg.norm(np.asarray(vecs), axis=0)
        np.testing.assert_allclose(norms, 1.0, atol=1e-8)

    def test_low_rank_plus_shift(self, rng):
        n = 50
        u = rng.standard_normal((n, 2))
        a = jnp.array(u @ u.T + 5.0 * np.eye(n))
        vals, _, _ = linalg.compute_largest_eigenvectors(a, n, 2)
        ref = np.linalg.eigvalsh(np.asarray(a))[-2:][::-1]
        np.testing.assert_allclose(np.asarray(vals), ref, rtol=1e-6)


class TestCholeskyJit:
    def test_jit_compatible(self, rng):
        import jax

        b = rng.standard_normal((6, 6))
        a = b @ b.T + 6 * np.eye(6)
        work = np.zeros((6, 6))
        work[0, 0] = a[0, 0]
        f = jax.jit(lambda m: linalg.cholesky_rank1_update(m, 1, eps=1e-12))
        out = f(jnp.array(work))
        assert float(out[0, 0]) == pytest.approx(np.sqrt(a[0, 0]))

    def test_n1_eps_check(self):
        from raft_tpu import RaftError

        with pytest.raises(RaftError):
            linalg.cholesky_rank1_update(jnp.array([[-1.0]]), 1, eps=1e-12)
