"""Memory-resource tests (reference test/mr/device/buffer.cpp,
test/mr/host/buffer.cpp)."""

import jax.numpy as jnp
import numpy as np
import pytest

from raft_tpu import RaftError
from raft_tpu.mr import (DeviceBuffer, HostBuffer, PoolAllocator,
                         device_memory_stats)


class TestDeviceBuffer:
    def test_alloc_use_free(self):
        buf = DeviceBuffer((128, 64), jnp.float32)
        assert buf.data.shape == (128, 64)
        assert buf.size_bytes() == 128 * 64 * 4
        assert not buf.deallocated
        buf.deallocate()
        assert buf.deallocated
        with pytest.raises(RaftError, match="use after deallocate"):
            _ = buf.data
        buf.deallocate()  # idempotent

    def test_from_array_adopts(self):
        x = jnp.arange(16.0)
        buf = DeviceBuffer.from_array(x)
        assert float(buf.data[3]) == 3.0
        buf.deallocate()
        assert x.is_deleted()

    def test_context_manager(self):
        with DeviceBuffer((8,), jnp.int32) as buf:
            assert buf.data.dtype == jnp.int32
        assert buf.deallocated


class TestHostBuffer:
    def test_alloc_use_free(self):
        buf = HostBuffer((4, 4), jnp.float64)
        buf.data[1, 2] = 7.0
        assert buf.data[1, 2] == 7.0
        assert isinstance(buf.data, np.ndarray)
        buf.deallocate()
        assert buf.deallocated


class TestPoolAllocator:
    def test_reuse(self):
        pool = PoolAllocator()
        a = pool.allocate((256, 32))
        pool.deallocate(a)
        b = pool.allocate((256, 32))
        assert b is a                       # freelist hit
        assert pool.n_hits == 1 and pool.n_misses == 1
        c = pool.allocate((256, 32))
        assert c is not a                   # pool empty again
        assert pool.n_misses == 2

    def test_key_isolation(self):
        pool = PoolAllocator()
        a = pool.allocate((16,), jnp.float32)
        pool.deallocate(a)
        b = pool.allocate((16,), jnp.int32)
        assert b is not a

    def test_cap_and_release(self):
        pool = PoolAllocator(max_pooled_per_key=1)
        a, b = pool.allocate((8,)), pool.allocate((8,))
        pool.deallocate(a)
        pool.deallocate(b)                  # over cap: freed outright
        assert b.deallocated and not a.deallocated
        assert pool.pooled_bytes() == 8 * 4
        pool.release()
        assert a.deallocated and pool.pooled_bytes() == 0

    def test_rejects_dead_buffer(self):
        pool = PoolAllocator()
        a = pool.allocate((8,))
        a.deallocate()
        with pytest.raises(RaftError):
            pool.deallocate(a)


def test_memory_stats_shape():
    stats = device_memory_stats()
    assert isinstance(stats, dict)
    for v in stats.values():
        assert isinstance(v, int)
