"""Memory-resource tests (reference test/mr/device/buffer.cpp,
test/mr/host/buffer.cpp) — plus the out-of-core tier's TilePool
budget/streaming contract (docs/ZERO_COPY.md §6)."""

import threading

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from raft_tpu import RaftError
from raft_tpu.mr import (DeviceBuffer, HostBuffer, PoolAllocator,
                         TilePool, ZerosPool, default_zeros_pool,
                         device_memory_stats, zeros_cached)


class TestDeviceBuffer:
    def test_alloc_use_free(self):
        buf = DeviceBuffer((128, 64), jnp.float32)
        assert buf.data.shape == (128, 64)
        assert buf.size_bytes() == 128 * 64 * 4
        assert not buf.deallocated
        buf.deallocate()
        assert buf.deallocated
        with pytest.raises(RaftError, match="use after deallocate"):
            _ = buf.data
        buf.deallocate()  # idempotent

    def test_from_array_adopts(self):
        x = jnp.arange(16.0)
        buf = DeviceBuffer.from_array(x)
        assert float(buf.data[3]) == 3.0
        buf.deallocate()
        assert x.is_deleted()

    def test_context_manager(self):
        with DeviceBuffer((8,), jnp.int32) as buf:
            assert buf.data.dtype == jnp.int32
        assert buf.deallocated


class TestHostBuffer:
    def test_alloc_use_free(self):
        buf = HostBuffer((4, 4), jnp.float64)
        buf.data[1, 2] = 7.0
        assert buf.data[1, 2] == 7.0
        assert isinstance(buf.data, np.ndarray)
        buf.deallocate()
        assert buf.deallocated


class TestPoolAllocator:
    def test_reuse(self):
        pool = PoolAllocator()
        a = pool.allocate((256, 32))
        pool.deallocate(a)
        b = pool.allocate((256, 32))
        assert b is a                       # freelist hit
        assert pool.n_hits == 1 and pool.n_misses == 1
        c = pool.allocate((256, 32))
        assert c is not a                   # pool empty again
        assert pool.n_misses == 2

    def test_key_isolation(self):
        pool = PoolAllocator()
        a = pool.allocate((16,), jnp.float32)
        pool.deallocate(a)
        b = pool.allocate((16,), jnp.int32)
        assert b is not a

    def test_cap_and_release(self):
        pool = PoolAllocator(max_pooled_per_key=1)
        a, b = pool.allocate((8,)), pool.allocate((8,))
        pool.deallocate(a)
        pool.deallocate(b)                  # over cap: freed outright
        assert b.deallocated and not a.deallocated
        assert pool.pooled_bytes() == 8 * 4
        pool.release()
        assert a.deallocated and pool.pooled_bytes() == 0

    def test_rejects_dead_buffer(self):
        pool = PoolAllocator()
        a = pool.allocate((8,))
        a.deallocate()
        with pytest.raises(RaftError):
            pool.deallocate(a)

    def test_byte_budget_enforced(self):
        """Pooled bytes never exceed max_bytes; overflow evicts."""
        pool = PoolAllocator(max_pooled_per_key=8, max_bytes=64)
        bufs = [pool.allocate((4,), jnp.float32) for _ in range(6)]
        for b in bufs:                      # 6 * 16 bytes > 64
            pool.deallocate(b)
        assert pool.pooled_bytes() <= 64
        assert pool.n_evictions == 2
        assert sum(b.deallocated for b in bufs) == 2

    def test_eviction_order_oldest_pooled_first(self):
        """The byte bound frees the LEAST-RECENTLY-POOLED buffer first,
        across keys — a freshly returned buffer must never be the
        victim."""
        pool = PoolAllocator(max_pooled_per_key=8, max_bytes=40)
        a = pool.allocate((4,), jnp.float32)   # 16 bytes
        b = pool.allocate((2,), jnp.float32)   # 8 bytes
        c = pool.allocate((4,), jnp.float32)   # 16 bytes
        pool.deallocate(a)
        pool.deallocate(b)                     # 24 pooled
        pool.deallocate(c)                     # 40 pooled: fits
        d = pool.allocate((2, 2), jnp.float32)  # new key, 16 bytes
        pool.deallocate(d)          # 56 > 40: evict a (oldest) -> 40
        assert a.deallocated
        assert not b.deallocated and not c.deallocated \
            and not d.deallocated
        assert pool.pooled_bytes() == 40
        f = pool.allocate((8,), jnp.float32)    # new key, 32 bytes
        pool.deallocate(f)          # 72: evict b, c, d in pool order
        assert b.deallocated and c.deallocated and d.deallocated
        assert not f.deallocated
        assert pool.pooled_bytes() == 32

    def test_reuse_refreshes_nothing_but_removes_from_order(self):
        """An allocate() that hits the freelist must leave the byte
        accounting consistent (the buffer left the pool)."""
        pool = PoolAllocator(max_bytes=64)
        a = pool.allocate((4,), jnp.float32)
        pool.deallocate(a)
        assert pool.pooled_bytes() == 16
        b = pool.allocate((4,), jnp.float32)
        assert b is a and pool.pooled_bytes() == 0

    def test_single_oversize_buffer_never_pooled(self):
        pool = PoolAllocator(max_bytes=8)
        a = pool.allocate((4,), jnp.float32)   # 16 > 8
        pool.deallocate(a)
        assert a.deallocated and pool.pooled_bytes() == 0

    def test_release_resets_byte_accounting(self):
        pool = PoolAllocator(max_bytes=1024)
        pool.deallocate(pool.allocate((4,)))
        pool.release()
        assert pool.pooled_bytes() == 0
        pool.deallocate(pool.allocate((4,)))   # usable after release
        assert pool.pooled_bytes() == 16


class TestTilePool:
    """The out-of-core staging pool (docs/ZERO_COPY.md §6): gathered
    tiles, budget enforcement, stall accounting."""

    def _store(self, n_slots=16, cap=4, dim=3):
        rng = np.random.default_rng(7)
        return rng.standard_normal((n_slots, cap, dim)).astype(
            np.float32)

    def test_stage_take_round_trip(self):
        store = self._store()
        pool = TilePool(4, 1 << 20, name="t-rt")
        tile = pool.stage(store, np.array([3, 1, 5]))
        vecs, ids = pool.take(tile)
        assert vecs.shape == (4, 4, 3)          # padded to tile_slots
        np.testing.assert_array_equal(np.asarray(ids),
                                      [3, 1, 5, -1])
        np.testing.assert_allclose(np.asarray(vecs)[:3],
                                   store[[3, 1, 5]])
        assert pool.staged_bytes() == 0
        assert pool.n_staged == 1 and pool.n_taken == 1

    def test_double_take_rejected(self):
        pool = TilePool(2, 1 << 20, name="t-dt")
        tile = pool.stage(self._store(), np.array([0]))
        pool.take(tile)
        with pytest.raises(RaftError, match="already taken"):
            pool.take(tile)

    def test_budget_must_hold_two_tiles(self):
        store = self._store()
        tiny = TilePool(8, 64, name="t-tiny")   # one tile is 8*(48+4)
        with pytest.raises(RaftError, match="double-buffer"):
            tiny.stage(store, np.array([0]))

    def test_overstage_from_one_thread_fails_loudly(self):
        """A driver that stages past the budget without taking must get
        AllocationError after the bounded wait, not a deadlock."""
        from raft_tpu.core.error import AllocationError

        store = self._store()
        tile_b = 4 * (store.shape[1] * store.shape[2] * 4 + 4)
        pool = TilePool(4, 2 * tile_b, name="t-over",
                        stage_wait_s=0.2)
        a = pool.stage(store, np.array([0]))
        b = pool.stage(store, np.array([1]))
        with pytest.raises(AllocationError):
            pool.stage(store, np.array([2]))
        pool.take(a)
        pool.take(b)

    def test_budget_holds_under_concurrent_traffic(self):
        """The satellite acceptance: an oversubscribed pool shared by
        concurrent searchers never exceeds its budget — proven by the
        staged-bytes gauge's high-water, not asserted."""
        from raft_tpu.core.metrics import default_registry

        store = self._store(n_slots=64)
        pool = TilePool(4, 3 * (4 * (store.shape[1] * store.shape[2]
                                     * 4 + 4)),
                        name="t-conc", stage_wait_s=10.0)
        errors = []

        def worker(seed):
            rng = np.random.default_rng(seed)
            try:
                for _ in range(25):
                    ids = rng.integers(0, 64, 3)
                    pool.take(pool.stage(store, ids))
            except Exception as e:  # pragma: no cover
                errors.append(e)

        threads = [threading.Thread(target=worker, args=(s,))
                   for s in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=30)
        assert not errors
        assert pool.staged_bytes() == 0
        fam = default_registry().get("raft_tpu_tile_staged_bytes")
        assert fam is not None
        for labels, series in fam.series():
            if labels.get("pool") == "t-conc":
                assert series.high_water <= pool.budget_bytes
                break
        else:  # pragma: no cover
            pytest.fail("staged-bytes gauge missing")

    def test_discard_releases_budget(self):
        """The unwind path: a staged-not-taken tile (its scan failed)
        must give its budget charge back — a leaked reservation would
        shrink the pool until every stage stalls out."""
        store = self._store()
        pool = TilePool(2, 1 << 20, name="t-disc")
        tile = pool.stage(store, np.array([0]))
        assert pool.staged_bytes() > 0
        pool.discard(tile)
        assert pool.staged_bytes() == 0
        pool.discard(tile)                  # idempotent
        assert pool.staged_bytes() == 0
        with pytest.raises(RaftError, match="already taken"):
            pool.take(tile)

    def test_h2d_metrics_recorded(self):
        from raft_tpu.core.metrics import default_registry

        reg = default_registry()
        b0 = reg.family_total("raft_tpu_h2d_bytes_total")
        store = self._store()
        pool = TilePool(2, 1 << 20, name="t-met")
        pool.take(pool.stage(store, np.array([0, 1])))
        assert reg.family_total("raft_tpu_h2d_bytes_total") > b0

    def test_sync_stage_counts_exposed_stall(self):
        """hidden=False (the synchronous-prefetch arm) charges the
        stage-side host time to the stall timer; a fully hidden stage
        whose take overlapped compute charges ~nothing."""
        from raft_tpu.core.metrics import default_registry

        store = self._store()
        pool = TilePool(2, 1 << 20, name="t-stall")
        pool.take(pool.stage(store, np.array([0]), hidden=False))
        fam = default_registry().get("raft_tpu_h2d_stall_seconds")
        total_sync = None
        for labels, series in fam.series():
            if labels.get("pool") == "t-stall":
                total_sync = series.total
        assert total_sync is not None and total_sync > 0.0
        pool.take(pool.stage(store, np.array([1]), hidden=True),
                  busy=True)
        for labels, series in fam.series():
            if labels.get("pool") == "t-stall":
                assert series.total == total_sync  # hidden: no charge


class TestZerosPool:
    def test_shared_block_identity(self):
        pool = ZerosPool()
        a = pool.get((4, 3), jnp.float32)
        b = pool.get((4, 3), jnp.float32)
        assert b is a                       # ONE shared block, not a copy
        assert pool.n_hits == 1 and pool.n_misses == 1
        assert float(np.asarray(a).sum()) == 0.0

    def test_key_isolation_shape_and_dtype(self):
        pool = ZerosPool()
        a = pool.get((8,), jnp.float32)
        assert pool.get((8,), jnp.int32) is not a
        assert pool.get((9,), jnp.float32) is not a
        assert pool.n_misses == 3 and pool.n_hits == 0

    def test_lru_bound_evicts_oldest(self):
        pool = ZerosPool(max_entries=2)
        a = pool.get((1,))
        pool.get((2,))
        pool.get((1,))                      # refresh (1,): (2,) is now LRU
        pool.get((3,))                      # evicts (2,)
        assert len(pool) == 2
        assert pool.get((1,)) is a          # survived
        pool.get((2,))                      # re-created
        assert pool.n_misses == 4           # (1,) (2,) (3,) (2,)-again

    def test_pooled_bytes_and_release(self):
        pool = ZerosPool()
        blk = pool.get((16,), jnp.float32)
        assert pool.pooled_bytes() == 16 * 4
        pool.release()
        assert len(pool) == 0 and pool.pooled_bytes() == 0
        # released blocks stay valid for in-flight readers (no eager
        # delete — GC owns the device memory)
        assert float(np.asarray(blk).sum()) == 0.0

    def test_byte_bound_evicts_and_oversize_never_cached(self):
        """The LRU is bounded by BYTES as well as count: wide serve
        tails must not pin unbounded device memory for the process
        lifetime, and a single block larger than max_bytes is returned
        fresh, never cached (it would evict everything else)."""
        pool = ZerosPool(max_entries=64, max_bytes=4096)
        big = pool.get((2048,), jnp.float32)      # 8 KiB > max_bytes
        assert len(pool) == 0 and pool.pooled_bytes() == 0
        assert float(np.asarray(big).sum()) == 0.0  # still usable
        for i in range(1, 9):
            pool.get((256, i), jnp.float32)       # 1 KiB * i blocks
        assert pool.pooled_bytes() <= 4096
        assert len(pool) < 8                      # bytes bound, not count

    def test_deleted_block_is_replaced(self):
        pool = ZerosPool()
        a = pool.get((5,))
        a.delete()                          # a consumer broke the
        b = pool.get((5,))                  # read-only convention
        assert b is not a and not b.is_deleted()

    def test_zeros_cached_reads_default_pool(self):
        blk = zeros_cached((7, 2), jnp.int32)
        assert zeros_cached((7, 2), jnp.int32) is blk
        assert default_zeros_pool().get((7, 2), jnp.int32) is blk
        assert blk.dtype == jnp.int32 and blk.shape == (7, 2)

    def test_composition_yields_fresh_storage(self):
        """The documented consumption pattern (docs/ZERO_COPY.md):
        composing the shared block via concatenate produces FRESH
        storage — safe to donate — and never mutates the block."""
        tail = zeros_cached((3, 2), jnp.float32)
        rows = jnp.ones((2, 2), jnp.float32)
        out = jnp.concatenate([rows, tail], axis=0)
        assert out is not tail
        jax.block_until_ready(out)
        assert not tail.is_deleted()
        np.testing.assert_array_equal(np.asarray(out[2:]),
                                      np.zeros((3, 2), np.float32))


def test_memory_stats_shape():
    stats = device_memory_stats()
    assert isinstance(stats, dict)
    for v in stats.values():
        assert isinstance(v, int)
