"""Memory-resource tests (reference test/mr/device/buffer.cpp,
test/mr/host/buffer.cpp)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from raft_tpu import RaftError
from raft_tpu.mr import (DeviceBuffer, HostBuffer, PoolAllocator,
                         ZerosPool, default_zeros_pool,
                         device_memory_stats, zeros_cached)


class TestDeviceBuffer:
    def test_alloc_use_free(self):
        buf = DeviceBuffer((128, 64), jnp.float32)
        assert buf.data.shape == (128, 64)
        assert buf.size_bytes() == 128 * 64 * 4
        assert not buf.deallocated
        buf.deallocate()
        assert buf.deallocated
        with pytest.raises(RaftError, match="use after deallocate"):
            _ = buf.data
        buf.deallocate()  # idempotent

    def test_from_array_adopts(self):
        x = jnp.arange(16.0)
        buf = DeviceBuffer.from_array(x)
        assert float(buf.data[3]) == 3.0
        buf.deallocate()
        assert x.is_deleted()

    def test_context_manager(self):
        with DeviceBuffer((8,), jnp.int32) as buf:
            assert buf.data.dtype == jnp.int32
        assert buf.deallocated


class TestHostBuffer:
    def test_alloc_use_free(self):
        buf = HostBuffer((4, 4), jnp.float64)
        buf.data[1, 2] = 7.0
        assert buf.data[1, 2] == 7.0
        assert isinstance(buf.data, np.ndarray)
        buf.deallocate()
        assert buf.deallocated


class TestPoolAllocator:
    def test_reuse(self):
        pool = PoolAllocator()
        a = pool.allocate((256, 32))
        pool.deallocate(a)
        b = pool.allocate((256, 32))
        assert b is a                       # freelist hit
        assert pool.n_hits == 1 and pool.n_misses == 1
        c = pool.allocate((256, 32))
        assert c is not a                   # pool empty again
        assert pool.n_misses == 2

    def test_key_isolation(self):
        pool = PoolAllocator()
        a = pool.allocate((16,), jnp.float32)
        pool.deallocate(a)
        b = pool.allocate((16,), jnp.int32)
        assert b is not a

    def test_cap_and_release(self):
        pool = PoolAllocator(max_pooled_per_key=1)
        a, b = pool.allocate((8,)), pool.allocate((8,))
        pool.deallocate(a)
        pool.deallocate(b)                  # over cap: freed outright
        assert b.deallocated and not a.deallocated
        assert pool.pooled_bytes() == 8 * 4
        pool.release()
        assert a.deallocated and pool.pooled_bytes() == 0

    def test_rejects_dead_buffer(self):
        pool = PoolAllocator()
        a = pool.allocate((8,))
        a.deallocate()
        with pytest.raises(RaftError):
            pool.deallocate(a)


class TestZerosPool:
    def test_shared_block_identity(self):
        pool = ZerosPool()
        a = pool.get((4, 3), jnp.float32)
        b = pool.get((4, 3), jnp.float32)
        assert b is a                       # ONE shared block, not a copy
        assert pool.n_hits == 1 and pool.n_misses == 1
        assert float(np.asarray(a).sum()) == 0.0

    def test_key_isolation_shape_and_dtype(self):
        pool = ZerosPool()
        a = pool.get((8,), jnp.float32)
        assert pool.get((8,), jnp.int32) is not a
        assert pool.get((9,), jnp.float32) is not a
        assert pool.n_misses == 3 and pool.n_hits == 0

    def test_lru_bound_evicts_oldest(self):
        pool = ZerosPool(max_entries=2)
        a = pool.get((1,))
        pool.get((2,))
        pool.get((1,))                      # refresh (1,): (2,) is now LRU
        pool.get((3,))                      # evicts (2,)
        assert len(pool) == 2
        assert pool.get((1,)) is a          # survived
        pool.get((2,))                      # re-created
        assert pool.n_misses == 4           # (1,) (2,) (3,) (2,)-again

    def test_pooled_bytes_and_release(self):
        pool = ZerosPool()
        blk = pool.get((16,), jnp.float32)
        assert pool.pooled_bytes() == 16 * 4
        pool.release()
        assert len(pool) == 0 and pool.pooled_bytes() == 0
        # released blocks stay valid for in-flight readers (no eager
        # delete — GC owns the device memory)
        assert float(np.asarray(blk).sum()) == 0.0

    def test_byte_bound_evicts_and_oversize_never_cached(self):
        """The LRU is bounded by BYTES as well as count: wide serve
        tails must not pin unbounded device memory for the process
        lifetime, and a single block larger than max_bytes is returned
        fresh, never cached (it would evict everything else)."""
        pool = ZerosPool(max_entries=64, max_bytes=4096)
        big = pool.get((2048,), jnp.float32)      # 8 KiB > max_bytes
        assert len(pool) == 0 and pool.pooled_bytes() == 0
        assert float(np.asarray(big).sum()) == 0.0  # still usable
        for i in range(1, 9):
            pool.get((256, i), jnp.float32)       # 1 KiB * i blocks
        assert pool.pooled_bytes() <= 4096
        assert len(pool) < 8                      # bytes bound, not count

    def test_deleted_block_is_replaced(self):
        pool = ZerosPool()
        a = pool.get((5,))
        a.delete()                          # a consumer broke the
        b = pool.get((5,))                  # read-only convention
        assert b is not a and not b.is_deleted()

    def test_zeros_cached_reads_default_pool(self):
        blk = zeros_cached((7, 2), jnp.int32)
        assert zeros_cached((7, 2), jnp.int32) is blk
        assert default_zeros_pool().get((7, 2), jnp.int32) is blk
        assert blk.dtype == jnp.int32 and blk.shape == (7, 2)

    def test_composition_yields_fresh_storage(self):
        """The documented consumption pattern (docs/ZERO_COPY.md):
        composing the shared block via concatenate produces FRESH
        storage — safe to donate — and never mutates the block."""
        tail = zeros_cached((3, 2), jnp.float32)
        rows = jnp.ones((2, 2), jnp.float32)
        out = jnp.concatenate([rows, tail], axis=0)
        assert out is not tail
        jax.block_until_ready(out)
        assert not tail.is_deleted()
        np.testing.assert_array_equal(np.asarray(out[2:]),
                                      np.zeros((3, 2), np.float32))


def test_memory_stats_shape():
    stats = device_memory_stats()
    assert isinstance(stats, dict)
    for v in stats.values():
        assert isinstance(v, int)
