"""Matrix manipulation/math + stats tests vs numpy references
(reference cpp/test/matrix/{matrix.cu,math.cu}, test/stats/*.cu)."""

import jax.numpy as jnp
import numpy as np
import pytest

from raft_tpu import matrix, stats
from raft_tpu.core.error import RaftError


class TestMatrix:
    def test_copy_rows(self, rng):
        x = rng.standard_normal((10, 4))
        idx = jnp.array([7, 1, 3])
        np.testing.assert_allclose(np.asarray(matrix.copy_rows(jnp.array(x), idx)), x[[7, 1, 3]])

    def test_trunc_and_slice(self, rng):
        x = rng.standard_normal((8, 8))
        np.testing.assert_allclose(
            np.asarray(matrix.trunc_zero_origin(jnp.array(x), 3, 5)), x[:3, :5])
        np.testing.assert_allclose(
            np.asarray(matrix.slice_matrix(jnp.array(x), 2, 1, 6, 4)), x[2:6, 1:4])
        with pytest.raises(RaftError):
            matrix.slice_matrix(jnp.array(x), 5, 0, 3, 4)

    def test_reverses(self, rng):
        x = rng.standard_normal((5, 7))
        np.testing.assert_allclose(np.asarray(matrix.col_reverse(jnp.array(x))), x[:, ::-1])
        np.testing.assert_allclose(np.asarray(matrix.row_reverse(jnp.array(x))), x[::-1, :])

    def test_triangular_diag(self, rng):
        x = rng.standard_normal((6, 6))
        np.testing.assert_allclose(
            np.asarray(matrix.copy_upper_triangular(jnp.array(x))), np.triu(x))
        v = rng.standard_normal(4)
        np.testing.assert_allclose(
            np.asarray(matrix.initialize_diagonal_matrix(jnp.array(v))), np.diag(v))
        m = np.ones((3, 3))
        np.fill_diagonal(m, [2.0, 4.0, 0.0])
        out = np.asarray(matrix.get_diagonal_inverse_matrix(jnp.array(m)))
        np.testing.assert_allclose(np.diag(out), [0.5, 0.25, 0.0])
        assert out[0, 1] == 1.0  # off-diagonal preserved

    def test_l2norm_print(self, rng):
        x = rng.standard_normal((4, 4))
        np.testing.assert_allclose(
            float(matrix.get_l2_norm(jnp.array(x))), np.linalg.norm(x), rtol=1e-10)
        s = matrix.print_host(jnp.array([[1.0, 2.0], [3.0, 4.0]]))
        assert s == "1.0,2.0;3.0,4.0"


class TestMatrixMath:
    def test_power_seqroot(self, rng):
        x = np.abs(rng.standard_normal((5, 5))) + 0.1
        np.testing.assert_allclose(np.asarray(matrix.power(jnp.array(x))), x * x)
        np.testing.assert_allclose(np.asarray(matrix.power(jnp.array(x), 2.0)), 2 * x * x)
        np.testing.assert_allclose(np.asarray(matrix.seq_root(jnp.array(x))), np.sqrt(x), rtol=1e-7)
        neg = jnp.array([-1.0, 4.0])
        np.testing.assert_allclose(np.asarray(matrix.seq_root(neg, set_neg_zero=True)), [0.0, 2.0])

    def test_small_values_reciprocal(self):
        x = jnp.array([1e-20, 0.5, -1e-18, 2.0])
        np.testing.assert_allclose(
            np.asarray(matrix.set_small_values_zero(x)), [0.0, 0.5, 0.0, 2.0])
        np.testing.assert_allclose(
            np.asarray(matrix.reciprocal(x, setzero=True, thres=1e-10)), [0.0, 2.0, 0.0, 0.5])

    def test_ratio_argmax_signflip(self, rng):
        x = np.array([[1.0, 3.0], [4.0, 2.0]])
        np.testing.assert_allclose(np.asarray(matrix.ratio(jnp.array(x))), x / x.sum())
        np.testing.assert_array_equal(np.asarray(matrix.argmax(jnp.array(x))), [1, 0])
        m = np.array([[1.0, -5.0], [-3.0, 2.0]])
        out = np.asarray(matrix.sign_flip(jnp.array(m)))
        # col 0 pivot is -3 -> flipped; col 1 pivot is -5 -> flipped
        np.testing.assert_allclose(out, [[-1.0, 5.0], [3.0, -2.0]])

    def test_matrix_vector_binaries(self, rng):
        m = rng.standard_normal((4, 3))
        v = np.array([2.0, 0.0, 4.0])
        jm, jv = jnp.array(m), jnp.array(v)
        np.testing.assert_allclose(np.asarray(matrix.matrix_vector_binary_mult(jm, jv)), m * v)
        out = np.asarray(matrix.matrix_vector_binary_mult_skip_zero(jm, jv))
        np.testing.assert_allclose(out[:, 1], m[:, 1])  # zero col untouched
        out = np.asarray(matrix.matrix_vector_binary_div_skip_zero(jm, jv, return_zero=True))
        np.testing.assert_allclose(out[:, 1], 0.0)
        np.testing.assert_allclose(np.asarray(matrix.matrix_vector_binary_add(jm, jv)), m + v)
        np.testing.assert_allclose(np.asarray(matrix.matrix_vector_binary_sub(jm, jv)), m - v)


class TestStats:
    @pytest.mark.parametrize("n,d", [(100, 5), (1000, 32)])
    def test_mean_sum(self, rng, n, d):
        x = rng.standard_normal((n, d))
        np.testing.assert_allclose(np.asarray(stats.mean(jnp.array(x))), x.mean(axis=0), atol=1e-10)
        np.testing.assert_allclose(
            np.asarray(stats.sum_cols(jnp.array(x))), x.sum(axis=0), atol=1e-8)

    @pytest.mark.parametrize("sample", [True, False])
    def test_stddev_vars(self, rng, sample):
        x = rng.standard_normal((200, 4))
        ddof = 1 if sample else 0
        np.testing.assert_allclose(
            np.asarray(stats.vars_(jnp.array(x), sample=sample)),
            x.var(axis=0, ddof=ddof), rtol=1e-8)
        np.testing.assert_allclose(
            np.asarray(stats.stddev(jnp.array(x), sample=sample)),
            x.std(axis=0, ddof=ddof), rtol=1e-8)

    def test_mean_center_roundtrip(self, rng):
        x = rng.standard_normal((50, 3))
        mu = stats.mean(jnp.array(x))
        centered = stats.mean_center(jnp.array(x), mu)
        np.testing.assert_allclose(np.asarray(stats.mean(centered)), 0.0, atol=1e-12)
        back = stats.mean_add(centered, mu)
        np.testing.assert_allclose(np.asarray(back), x, atol=1e-12)
