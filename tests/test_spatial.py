"""Spatial / kNN tests vs naive numpy references.

Mirrors the reference's strategy (SURVEY.md §4): every fast path checked
against an O(mnk) naive implementation (reference
test/spatial/knn.cu:107,193 uses grouped-label fixtures;
test/spatial/selection.cu checks select_k against sorted copies).
"""

import numpy as np
import pytest
import jax.numpy as jnp

from raft_tpu.distance.distance_type import DistanceType as D
from raft_tpu.spatial import (
    brute_force_knn,
    fused_l2_knn,
    haversine_distances,
    haversine_knn,
    knn_merge_parts,
    select_k,
)


def naive_knn(index, queries, k, metric="sqeuclidean", p=2.0):
    if metric == "sqeuclidean":
        d = ((queries[:, None, :] - index[None, :, :]) ** 2).sum(-1)
    elif metric == "euclidean":
        d = np.sqrt(((queries[:, None, :] - index[None, :, :]) ** 2).sum(-1))
    elif metric == "l1":
        d = np.abs(queries[:, None, :] - index[None, :, :]).sum(-1)
    elif metric == "cosine":
        qn = queries / np.linalg.norm(queries, axis=1, keepdims=True)
        xn = index / np.linalg.norm(index, axis=1, keepdims=True)
        d = 1.0 - qn @ xn.T
    elif metric == "ip":
        d = -(queries @ index.T)  # min-select on negated ip
    else:
        raise ValueError(metric)
    idx = np.argsort(d, axis=1, kind="stable")[:, :k]
    return np.take_along_axis(d, idx, axis=1), idx


# --------------------------------------------------------------------- #
# select_k
# --------------------------------------------------------------------- #
@pytest.mark.parametrize("m,n,k", [(5, 17, 3), (32, 100, 10), (1, 8, 8)])
@pytest.mark.parametrize("select_min", [True, False])
def test_select_k(rng, m, n, k, select_min):
    keys = rng.standard_normal((m, n)).astype(np.float32)
    vals, idx = select_k(jnp.asarray(keys), k, select_min=select_min)
    order = np.argsort(keys if select_min else -keys, axis=1, kind="stable")[:, :k]
    np.testing.assert_array_equal(np.asarray(idx), order)
    np.testing.assert_allclose(
        np.asarray(vals), np.take_along_axis(keys, order, axis=1), rtol=1e-6)


def test_select_k_payload(rng):
    keys = rng.standard_normal((4, 20)).astype(np.float32)
    payload = rng.integers(0, 10**6, (4, 20)).astype(np.int32)
    vals, out_payload = select_k(jnp.asarray(keys), 5, values=jnp.asarray(payload))
    order = np.argsort(keys, axis=1, kind="stable")[:, :5]
    np.testing.assert_array_equal(np.asarray(out_payload),
                                  np.take_along_axis(payload, order, axis=1))


def test_select_k_ties_prefer_smaller_index():
    keys = jnp.asarray([[1.0, 0.0, 0.0, 2.0]])
    _, idx = select_k(keys, 2)
    np.testing.assert_array_equal(np.asarray(idx), [[1, 2]])


# --------------------------------------------------------------------- #
# fused_l2_knn
# --------------------------------------------------------------------- #
@pytest.mark.parametrize("n,nq,d,k,tile_n", [
    (100, 20, 8, 5, 32),      # multi-tile with remainder
    (50, 10, 16, 50, 64),     # k == n
    (257, 33, 4, 7, 100),
])
def test_fused_l2_knn(rng, n, nq, d, k, tile_n):
    index = rng.standard_normal((n, d)).astype(np.float32)
    queries = rng.standard_normal((nq, d)).astype(np.float32)
    dist, idx = fused_l2_knn(jnp.asarray(index), jnp.asarray(queries), k, tile_n=tile_n)
    ref_d, ref_i = naive_knn(index, queries, k)
    np.testing.assert_allclose(np.asarray(dist), ref_d, rtol=1e-4, atol=1e-4)
    np.testing.assert_array_equal(np.asarray(idx), ref_i)


# --------------------------------------------------------------------- #
# haversine
# --------------------------------------------------------------------- #
def naive_haversine(x, y):
    sin_lat = np.sin(0.5 * (x[:, None, 0] - y[None, :, 0]))
    sin_lon = np.sin(0.5 * (x[:, None, 1] - y[None, :, 1]))
    r = sin_lat**2 + np.cos(x[:, None, 0]) * np.cos(y[None, :, 0]) * sin_lon**2
    return 2 * np.arcsin(np.sqrt(r))


def test_haversine(rng):
    x = (rng.uniform(-1.2, 1.2, (20, 2))).astype(np.float64)
    y = (rng.uniform(-1.2, 1.2, (30, 2))).astype(np.float64)
    d = haversine_distances(jnp.asarray(x), jnp.asarray(y))
    np.testing.assert_allclose(np.asarray(d), naive_haversine(x, y), rtol=1e-6)


def test_haversine_knn(rng):
    index = rng.uniform(-1.2, 1.2, (73, 2)).astype(np.float64)
    queries = rng.uniform(-1.2, 1.2, (9, 2)).astype(np.float64)
    dist, idx = haversine_knn(jnp.asarray(index), jnp.asarray(queries), 4, tile_n=32)
    ref = naive_haversine(queries, index)
    ref_i = np.argsort(ref, axis=1, kind="stable")[:, :4]
    np.testing.assert_array_equal(np.asarray(idx), ref_i)
    np.testing.assert_allclose(
        np.asarray(dist), np.take_along_axis(ref, ref_i, axis=1), rtol=1e-6)


# --------------------------------------------------------------------- #
# knn_merge_parts
# --------------------------------------------------------------------- #
def test_knn_merge_parts(rng):
    n_parts, nq, k = 3, 6, 4
    part_d = rng.uniform(0, 10, (n_parts, nq, k)).astype(np.float32)
    part_d.sort(axis=2)
    part_i = rng.integers(0, 50, (n_parts, nq, k)).astype(np.int32)
    trans = [0, 100, 200]
    dist, idx = knn_merge_parts(jnp.asarray(part_d), jnp.asarray(part_i), k, trans)
    # naive merge
    all_d = part_d.transpose(1, 0, 2).reshape(nq, -1)
    all_i = (part_i + np.asarray(trans)[:, None, None]).transpose(1, 0, 2).reshape(nq, -1)
    order = np.argsort(all_d, axis=1, kind="stable")[:, :k]
    np.testing.assert_allclose(np.asarray(dist), np.take_along_axis(all_d, order, 1))
    np.testing.assert_array_equal(np.asarray(idx), np.take_along_axis(all_i, order, 1))


# --------------------------------------------------------------------- #
# brute_force_knn end-to-end
# --------------------------------------------------------------------- #
@pytest.mark.parametrize("metric,naive", [
    (D.L2Expanded, "sqeuclidean"),
    (D.L2SqrtExpanded, "euclidean"),
    (D.L1, "l1"),
    (D.CosineExpanded, "cosine"),
    (D.InnerProduct, "ip"),
])
def test_brute_force_knn_single(rng, metric, naive):
    index = rng.standard_normal((120, 12)).astype(np.float32)
    queries = rng.standard_normal((25, 12)).astype(np.float32)
    k = 6
    dist, idx = brute_force_knn(jnp.asarray(index), jnp.asarray(queries), k,
                                metric=metric, tile_n=48)
    ref_d, ref_i = naive_knn(index, queries, k, metric=naive)
    np.testing.assert_array_equal(np.asarray(idx), ref_i)
    got = np.asarray(dist)
    if naive == "ip":
        ref_d = -ref_d  # brute_force_knn reports raw inner products
    np.testing.assert_allclose(got, ref_d, rtol=1e-4, atol=1e-4)


def test_brute_force_knn_partitions(rng):
    """Partitioned input + translations == single concatenated index
    (reference multi-partition path, knn_brute_force_faiss.cuh:291-365)."""
    d, k = 10, 8
    parts_np = [rng.standard_normal((n, d)).astype(np.float32) for n in (40, 70, 25)]
    queries = rng.standard_normal((15, d)).astype(np.float32)
    dist_p, idx_p = brute_force_knn([jnp.asarray(p) for p in parts_np],
                                    jnp.asarray(queries), k, tile_n=32)
    full = np.concatenate(parts_np)
    ref_d, ref_i = naive_knn(full, queries, k)
    np.testing.assert_array_equal(np.asarray(idx_p), ref_i)
    np.testing.assert_allclose(np.asarray(dist_p), ref_d, rtol=1e-4, atol=1e-4)


def test_brute_force_knn_custom_translations(rng):
    index = rng.standard_normal((30, 5)).astype(np.float32)
    queries = rng.standard_normal((4, 5)).astype(np.float32)
    _, idx = brute_force_knn([jnp.asarray(index)], jnp.asarray(queries), 3,
                             translations=[1000])
    assert np.asarray(idx).min() >= 1000


def test_brute_force_knn_grouped_labels(rng):
    """Points in tight, well-separated clusters: every neighbor must share
    the query's cluster (reference test/spatial/knn.cu:107 pattern)."""
    centers = np.asarray([[0.0, 0.0], [50.0, 0.0], [0.0, 50.0], [50.0, 50.0]])
    n_per, k = 25, 10
    pts, labels = [], []
    for ci, c in enumerate(centers):
        pts.append(c + 0.5 * rng.standard_normal((n_per, 2)))
        labels.extend([ci] * n_per)
    pts = np.concatenate(pts).astype(np.float32)
    labels = np.asarray(labels)
    _, idx = brute_force_knn(jnp.asarray(pts), jnp.asarray(pts), k)
    neighbor_labels = labels[np.asarray(idx)]
    assert (neighbor_labels == labels[:, None]).all()


# --------------------------------------------------------------------- #
# fused distance+top-k Pallas kernel (interpret mode on CPU)
# --------------------------------------------------------------------- #
# interpret-mode executions of the while-loop running-select kernels
# cost ~15s per call flat (the gate loop dispatches its lane networks
# eagerly), so the full matrices are opt-in; the fast tier-1 parity
# coverage for these kernels lives in tests/test_fused_kernels.py
@pytest.mark.slow
@pytest.mark.parametrize("n,nq,d,k", [
    (300, 17, 13, 5),         # sub-tile everything, odd sizes
    (3000, 33, 128, 100),     # multi index tile, kpad==128, north-star k
    (130, 9, 2, 129),         # k > 128 -> kpad 256, tiny n
    (900, 7, 16, 300),        # kpad must round to a power of two (512)
    (2500, 24, 64, 10),
])
def test_fused_knn_tile_exact(rng, n, nq, d, k):
    from raft_tpu.ops.knn_tile import fused_knn_tile

    index = rng.standard_normal((n, d)).astype(np.float32)
    queries = rng.standard_normal((nq, d)).astype(np.float32)
    dist, idx = fused_knn_tile(jnp.asarray(index), jnp.asarray(queries), k)
    ref_d, ref_i = naive_knn(index, queries, k)
    np.testing.assert_allclose(np.asarray(dist), ref_d, rtol=1e-4, atol=1e-4)
    # ties may resolve to different ids of equal distance: compare the
    # distances at the chosen ids
    full = ((queries[:, None, :] - index[None, :, :]) ** 2).sum(-1)
    chosen = np.take_along_axis(full, np.asarray(idx), axis=1)
    np.testing.assert_allclose(chosen, ref_d, rtol=1e-4, atol=1e-4)
    assert (np.asarray(idx) >= 0).all() and (np.asarray(idx) < n).all()


@pytest.mark.slow
def test_fused_knn_tile_duplicate_rows(rng):
    """Duplicate points produce exact-tie distances; the selected set must
    still be a valid kNN set (no id duplicated within a row)."""
    from raft_tpu.ops.knn_tile import fused_knn_tile

    base = rng.standard_normal((40, 6)).astype(np.float32)
    index = np.concatenate([base, base, base])          # every row x3
    queries = base[:11]
    dist, idx = fused_knn_tile(jnp.asarray(index), jnp.asarray(queries), 5)
    idx = np.asarray(idx)
    for row in idx:
        assert len(set(row.tolist())) == len(row), row
    np.testing.assert_allclose(np.asarray(dist)[:, :3], 0.0, atol=1e-5)


@pytest.mark.slow
def test_fused_knn_tile_merge_impls_agree(rng):
    """The log2-stage bitonic-merge tail ("merge", default) and the
    full log^2 sort of the concatenation ("fullsort") are two networks
    for the same running-top-k update; they must produce identical
    distance sets — including on tie-heavy duplicated rows, where a
    broken merge shows up as a dropped or doubled id."""
    from raft_tpu.ops.knn_tile import fused_knn_tile

    base = rng.standard_normal((150, 24)).astype(np.float32)
    index = np.concatenate([base, base])          # exact ties everywhere
    queries = rng.standard_normal((33, 24)).astype(np.float32)
    for k in (5, 100):
        d_m, i_m = fused_knn_tile(jnp.asarray(index), jnp.asarray(queries),
                                  k, merge_impl="merge")
        for alt in ("fullsort", "sorttile"):
            d_f, i_f = fused_knn_tile(jnp.asarray(index),
                                      jnp.asarray(queries),
                                      k, merge_impl=alt)
            np.testing.assert_allclose(np.asarray(d_m), np.asarray(d_f),
                                       rtol=1e-5, atol=1e-6)
            for row_m, row_f in zip(np.asarray(i_m), np.asarray(i_f)):
                assert len(set(row_m.tolist())) == k
                assert len(set(row_f.tolist())) == k
                # same id SET up to tie partners (a and a+150 are the
                # same point): compare modulo the duplication
                assert sorted(r % 150 for r in row_m) == \
                    sorted(r % 150 for r in row_f)


@pytest.mark.slow
def test_fused_l2_knn_impl_dispatch(rng):
    """impl="pallas" and impl="xla" agree through the public entry
    (~15s: the pallas arm executes interpreted off-TPU; the fast
    xla_fused twin's dispatch is covered in tests/test_fused_kernels.py)."""
    index = rng.standard_normal((600, 32)).astype(np.float32)
    queries = rng.standard_normal((41, 32)).astype(np.float32)
    d_x, i_x = fused_l2_knn(jnp.asarray(index), jnp.asarray(queries), 9,
                            impl="xla")
    d_p, i_p = fused_l2_knn(jnp.asarray(index), jnp.asarray(queries), 9,
                            impl="pallas")
    np.testing.assert_allclose(np.asarray(d_p), np.asarray(d_x),
                               rtol=1e-4, atol=1e-4)
    np.testing.assert_array_equal(np.asarray(i_p), np.asarray(i_x))


class TestHandleThreading:
    """The reference threads one handle_t& through every primitive
    (handle.hpp:49) and forks partition searches across its stream pool
    (knn_brute_force_faiss.cuh:289-297); verify the TPU handle is
    functionally live, not ornamental."""

    def test_brute_force_knn_uses_stream_pool(self):
        from raft_tpu import Handle
        from raft_tpu.spatial import brute_force_knn

        rng = np.random.default_rng(0)
        X = rng.standard_normal((300, 8)).astype(np.float32)
        h = Handle(n_streams=3)
        parts = [X[:100], X[100:180], X[180:]]
        dd, ii = brute_force_knn(parts, X[:16], 4, handle=h)
        # each partition's search was recorded on a distinct pool stream
        busy = [s for s in h._stream_pool if s._pending]
        assert len(busy) == 3
        # and the merged result on the main stream
        assert len(h.get_stream()._pending) == 2
        h.sync_stream_pool()
        h.sync_stream()
        assert all(not s._pending for s in h._stream_pool)
        # results identical to the handle-free path
        dd0, ii0 = brute_force_knn(parts, X[:16], 4)
        np.testing.assert_array_equal(np.asarray(ii), np.asarray(ii0))

    def test_stream_syncer_scope(self):
        from raft_tpu import Handle
        from raft_tpu.core.handle import stream_syncer
        from raft_tpu.spatial import brute_force_knn

        rng = np.random.default_rng(1)
        X = rng.standard_normal((100, 4)).astype(np.float32)
        h = Handle(n_streams=2)
        with stream_syncer(h):
            brute_force_knn([X], X[:8], 3, handle=h)
        assert not h.get_stream()._pending

    def test_single_linkage_handle(self):
        from raft_tpu import Handle
        from raft_tpu.sparse.hierarchy import single_linkage
        from raft_tpu.distance.distance_type import DistanceType as D

        rng = np.random.default_rng(2)
        X = np.concatenate([rng.normal(0, .1, (30, 2)),
                            rng.normal(5, .1, (30, 2))]).astype(np.float32)
        h = Handle(n_streams=2)
        res = single_linkage(X, n_clusters=2, metric=D.L2SqrtExpanded,
                             handle=h)
        assert len(h.get_stream()._pending) > 0
        h.sync_stream()
        labels = np.asarray(res.labels)
        assert len(set(labels[:30])) == 1 and len(set(labels[30:])) == 1

    def test_spectral_partition_handle(self):
        from raft_tpu import Handle
        from raft_tpu.sparse.formats import CSR
        from raft_tpu.spectral import partition

        # two disjoint triangles + one weak bridge
        rows = np.array([0, 1, 0, 2, 1, 2, 3, 4, 3, 5, 4, 5, 2, 3])
        cols = np.array([1, 0, 2, 0, 2, 1, 4, 3, 5, 3, 5, 4, 3, 2])
        vals = np.ones(14, np.float32)
        dense = np.zeros((6, 6), np.float32)
        dense[rows, cols] = vals
        csr = CSR.from_dense(dense)
        h = Handle()
        res = partition(csr, n_clusters=2, handle=h)
        assert len(h.get_stream()._pending) > 0
        h.sync_stream()
        labels = np.asarray(res.clusters)
        assert len(set(labels[:3])) == 1 and len(set(labels[3:])) == 1


class TestSelectKImpl:
    """approx_max_k path (TPU PartialReduce; exact membership at
    recall_target=1.0) vs the default top_k."""

    def test_approx_matches_topk_membership(self):
        rng = np.random.default_rng(0)
        keys = jnp.asarray(rng.standard_normal((32, 4096)), jnp.float32)
        from raft_tpu.spatial.select_k import select_k

        d_t, i_t = select_k(keys, 16, select_min=True, impl="topk")
        d_a, i_a = select_k(keys, 16, select_min=True, impl="approx")
        # membership and sorted keys identical on distinct keys; tie
        # ORDER is not guaranteed by the approx path (module doc)
        np.testing.assert_allclose(np.sort(np.asarray(d_a), 1),
                                   np.sort(np.asarray(d_t), 1), atol=1e-6)
        for r in range(32):
            assert set(np.asarray(i_a)[r]) == set(np.asarray(i_t)[r])

    def test_payload_carried(self):
        rng = np.random.default_rng(1)
        keys = jnp.asarray(rng.standard_normal((4, 256)), jnp.float32)
        payload = jnp.asarray(rng.integers(0, 9999, (4, 256)), jnp.int32)
        from raft_tpu.spatial.select_k import select_k

        d, v = select_k(keys, 8, select_min=False, values=payload,
                        impl="approx")
        ref_d, ref_i = select_k(keys, 8, select_min=False)
        np.testing.assert_allclose(np.asarray(d), np.asarray(ref_d),
                                   atol=1e-6)

    def test_env_default(self, monkeypatch):
        from raft_tpu.spatial.select_k import select_k

        monkeypatch.setenv("RAFT_TPU_SELECT_IMPL", "bogus")
        with pytest.raises(Exception, match="unknown impl"):
            select_k(jnp.ones((2, 8)), 2)

    @pytest.mark.parametrize("m,n,k", [
        (32, 4096, 16), (7, 8192, 100), (5, 1000, 3),   # ragged width
        (3, 257, 100),                                   # pad + k>chunk/2
        (2, 100, 7),                                     # narrow fallback
        (4, 512, 256),                                   # k == chunk
    ])
    def test_chunked_matches_topk(self, m, n, k):
        """chunked_top_k: exact values and valid indices at every
        bracket shape (aligned, ragged, narrow fallback, k > chunk)."""
        rng = np.random.default_rng(2)
        keys = jnp.asarray(rng.standard_normal((m, n)), jnp.float32)
        from raft_tpu.spatial.select_k import select_k

        d_c, i_c = select_k(keys, k, select_min=True, impl="chunked")
        d_t, i_t = select_k(keys, k, select_min=True, impl="topk")
        np.testing.assert_allclose(np.asarray(d_c), np.asarray(d_t),
                                   atol=1e-6)
        # indices must point at rows holding exactly the selected value
        # (tie order is bracket-local, so compare gathered values)
        got = np.take_along_axis(np.asarray(keys), np.asarray(i_c), 1)
        np.testing.assert_allclose(got, np.asarray(d_c), atol=1e-6)

    def test_direct_merge_matches_tile_topk(self, monkeypatch):
        """tiled_knn merge='direct' (single (k+tile_n)-wide sort) must
        equal the default tile-topk merge exactly."""
        rng = np.random.default_rng(11)
        x = jnp.asarray(rng.standard_normal((3000, 32)), jnp.float32)
        q = jnp.asarray(rng.standard_normal((64, 32)), jnp.float32)
        from raft_tpu.spatial.fused_l2_knn import fused_l2_knn
        from raft_tpu.spatial.tiled_knn import tiled_knn

        def tile_dist(qq, xt):
            return jnp.sum((qq[:, None, :] - xt[None, :, :]) ** 2, -1)

        d_t, i_t = tiled_knn(x, q, 10, tile_dist, tile_n=512,
                             merge="tile_topk")
        d_d, i_d = tiled_knn(x, q, 10, tile_dist, tile_n=512,
                             merge="direct")
        np.testing.assert_allclose(np.asarray(d_t), np.asarray(d_d),
                                   rtol=1e-6)
        assert (np.asarray(i_t) == np.asarray(i_d)).mean() > 0.999
        # the env knob must reach the public entry: run BOTH settings
        # (fresh shapes aren't needed — fused_l2_knn is untraced here,
        # so each call re-reads the env)
        d_e, i_e = fused_l2_knn(x, q, 10, tile_n=512, impl="xla")
        monkeypatch.setenv("RAFT_TPU_TILE_MERGE", "direct")
        d_v, i_v = fused_l2_knn(x, q, 10, tile_n=512, impl="xla")
        np.testing.assert_allclose(np.asarray(d_e), np.asarray(d_v),
                                   atol=1e-4)
        assert (np.asarray(i_e) == np.asarray(i_v)).mean() > 0.999
        monkeypatch.setenv("RAFT_TPU_TILE_MERGE", "bogus")
        with pytest.raises(Exception):
            fused_l2_knn(x, q, 10, tile_n=512, impl="xla")

    def test_chunked_int_keys_odd_merge_round(self):
        """Integer keys through a merge tree with an ODD chunk count
        (w=768, chunk=256 -> c=3): the odd-round pad sentinel is
        iinfo.min, whose two's-complement negation wraps onto itself —
        the order flip must be overflow-free or pads outrank every
        genuine entry (code-review r4 finding)."""
        rng = np.random.default_rng(7)
        keys = rng.integers(-10**9, 10**9, (5, 768)).astype(np.int32)
        keys[0, :5] = np.iinfo(np.int32).min  # genuine INT_MIN entries
        from raft_tpu.spatial.select_k import chunked_top_k

        v_c, i_c = chunked_top_k(jnp.asarray(keys), 10)
        v_ref = np.sort(keys, axis=1)[:, ::-1][:, :10]
        np.testing.assert_array_equal(np.asarray(v_c), v_ref)
        got = np.take_along_axis(keys, np.asarray(i_c), 1)
        np.testing.assert_array_equal(got, v_ref)

    def test_select_k_int_payload_select_max_intmin(self):
        """select_k(select_min=False, values=payload) on int32 keys
        containing INT_MIN: the payload sort path must not negate
        integer keys (INT_MIN wraps onto itself and would be reported
        as the LARGEST key — code-review r4 finding)."""
        rng = np.random.default_rng(8)
        keys = rng.integers(-1000, 1000, (3, 40)).astype(np.int32)
        keys[:, 0] = np.iinfo(np.int32).min
        payload = rng.integers(0, 9999, (3, 40)).astype(np.int32)
        from raft_tpu.spatial.select_k import select_k

        d, v = select_k(jnp.asarray(keys), 5, select_min=False,
                        values=jnp.asarray(payload), impl="topk")
        order = np.argsort(-keys.astype(np.int64), axis=1)[:, :5]
        np.testing.assert_array_equal(
            np.asarray(d), np.take_along_axis(keys, order, 1))
        np.testing.assert_array_equal(
            np.asarray(v), np.take_along_axis(payload, order, 1))

    def test_chunked_masked_rows_match_topk(self):
        """Rows where most keys are +inf (the standard invalid-distance
        sentinel, -inf after negation): pad columns must not outrank
        genuine entries, values must equal lax.top_k, and indices stay
        in range (code-review r4 finding)."""
        rng = np.random.default_rng(3)
        keys = np.full((4, 1000), np.inf, np.float32)
        keys[:, :60] = rng.standard_normal((4, 60))  # < k finite entries
        keys = jnp.asarray(keys)
        from raft_tpu.spatial.select_k import select_k

        d_c, i_c = select_k(keys, 100, select_min=True, impl="chunked")
        d_t, _ = select_k(keys, 100, select_min=True, impl="topk")
        np.testing.assert_allclose(np.asarray(d_c), np.asarray(d_t),
                                   atol=1e-6)
        i_c = np.asarray(i_c)
        assert i_c.min() >= 0 and i_c.max() < 1000
        # the 60 finite entries are selected with correct indices
        got = np.take_along_axis(np.asarray(keys), i_c[:, :60], 1)
        np.testing.assert_allclose(got, np.asarray(d_c)[:, :60], atol=1e-6)

    # select_tile interpret-mode executions cost ~15s per call flat
    # (module comment at test_fused_knn_tile_exact); the tier-1 fast
    # coverage is tests/test_fused_kernels.py + the lowering suite
    @pytest.mark.slow
    @pytest.mark.parametrize("m,n,k", [
        (32, 4096, 16), (7, 8192, 100), (5, 1000, 3),   # ragged width
        (3, 257, 100),                                   # w barely > 2k
        (9, 300, 128),                                   # k == cap
    ])
    def test_pallas_matches_topk(self, m, n, k):
        """The fused select kernel (interpret mode on CPU): exact
        values; indices point at rows holding the selected value (tie
        ids may differ from top_k's smallest-index rule)."""
        rng = np.random.default_rng(4)
        keys = jnp.asarray(rng.standard_normal((m, n)), jnp.float32)
        from raft_tpu.spatial.select_k import select_k

        d_p, i_p = select_k(keys, k, select_min=True, impl="pallas")
        d_t, _ = select_k(keys, k, select_min=True, impl="topk")
        np.testing.assert_allclose(np.asarray(d_p), np.asarray(d_t),
                                   rtol=1e-6, atol=1e-6)
        got = np.take_along_axis(np.asarray(keys), np.asarray(i_p), 1)
        np.testing.assert_allclose(got, np.asarray(d_p), rtol=1e-6,
                                   atol=1e-6)
        assert np.asarray(i_p).min() >= 0

    @pytest.mark.slow
    def test_pallas_select_max_and_payload(self):
        rng = np.random.default_rng(5)
        keys = jnp.asarray(rng.standard_normal((6, 2000)), jnp.float32)
        payload = jnp.asarray(rng.integers(0, 9999, (6, 2000)), jnp.int32)
        from raft_tpu.spatial.select_k import select_k

        d_p, v_p = select_k(keys, 9, select_min=False, values=payload,
                            impl="pallas")
        d_t, v_t = select_k(keys, 9, select_min=False, values=payload,
                            impl="topk")
        np.testing.assert_allclose(np.asarray(d_p), np.asarray(d_t),
                                   atol=1e-6)
        np.testing.assert_array_equal(np.asarray(v_p), np.asarray(v_t))

    @pytest.mark.slow
    def test_pallas_deficit_rows_stay_in_range(self):
        """Rows with fewer than k finite keys: +inf fills the deficit
        and ids stay in range (the kernel's -1 sentinel must be
        clamped, mirroring the chunked pad contract)."""
        rng = np.random.default_rng(6)
        keys = np.full((3, 900), np.inf, np.float32)
        keys[:, :40] = rng.standard_normal((3, 40))
        from raft_tpu.spatial.select_k import select_k

        d_p, i_p = select_k(jnp.asarray(keys), 100, select_min=True,
                            impl="pallas")
        d_t, _ = select_k(jnp.asarray(keys), 100, select_min=True,
                          impl="topk")
        np.testing.assert_allclose(np.asarray(d_p), np.asarray(d_t),
                                   atol=1e-6)
        i_p = np.asarray(i_p)
        assert i_p.min() >= 0 and i_p.max() < 900
        got = np.take_along_axis(keys, i_p[:, :40], 1)
        np.testing.assert_allclose(got, np.asarray(d_p)[:, :40],
                                   atol=1e-6)

    @pytest.mark.slow
    def test_pallas_duplicate_ties_no_id_reuse(self):
        """Exact-tie keys: the selected id set must not repeat an id."""
        rng = np.random.default_rng(7)
        base = rng.standard_normal((1, 300)).astype(np.float32)
        keys = jnp.asarray(np.concatenate([base, base], axis=1))
        from raft_tpu.spatial.select_k import select_k

        _, i_p = select_k(keys, 50, select_min=True, impl="pallas")
        row = np.asarray(i_p)[0]
        assert len(set(row.tolist())) == 50

    def test_pallas_k_cap_errors(self):
        from raft_tpu.spatial.select_k import select_k

        with pytest.raises(Exception, match="128"):
            select_k(jnp.ones((2, 600)), 200, impl="pallas")

    @pytest.mark.slow
    def test_pallas_randomized_geometry_sweep(self):
        """Seeded fuzz over (m, w, k, block) geometry: the kernel's
        padding/grouping rules must hold at arbitrary ragged shapes,
        not only the hand-picked ones (the reference fuzzes select_k
        the same way: test/spatial/selection.cu random shape lists)."""
        rng = np.random.default_rng(42)
        from raft_tpu.ops.select_tile import select_tile

        for _ in range(10):
            m = int(rng.integers(1, 40))
            w = int(rng.integers(2, 1500))
            k = int(rng.integers(1, min(w, 128) + 1))
            bw = int(rng.choice([256, 512, 1024]))
            keys = rng.standard_normal((m, w)).astype(np.float32)
            d_p, i_p = select_tile(jnp.asarray(keys), k, block_w=bw)
            ref = np.sort(keys, axis=1)[:, :k]
            np.testing.assert_allclose(np.asarray(d_p), ref, rtol=1e-6,
                                       atol=1e-6,
                                       err_msg=f"{m}x{w} k={k} bw={bw}")
            got = np.take_along_axis(keys, np.asarray(i_p), 1)
            np.testing.assert_allclose(got, ref, rtol=1e-6, atol=1e-6)

    def test_chunked_int_keys(self):
        """Integer keys (e.g. vote counts) through the merge tree."""
        rng = np.random.default_rng(4)
        keys = jnp.asarray(rng.integers(-1000, 1000, (8, 2048)), jnp.int32)
        from raft_tpu.spatial.select_k import select_k

        d_c, i_c = select_k(keys, 50, select_min=False, impl="chunked")
        d_t, _ = select_k(keys, 50, select_min=False, impl="topk")
        np.testing.assert_array_equal(np.asarray(d_c), np.asarray(d_t))
        got = np.take_along_axis(np.asarray(keys), np.asarray(i_c), 1)
        np.testing.assert_array_equal(got, np.asarray(d_c))

    def test_chunked_duplicate_keys(self):
        """All-equal keys: every returned index must be in range and
        distinct (ties resolve to k different columns)."""
        keys = jnp.zeros((3, 2048), jnp.float32)
        from raft_tpu.spatial.select_k import select_k

        _, idx = select_k(keys, 32, impl="chunked")
        idx = np.asarray(idx)
        assert idx.min() >= 0 and idx.max() < 2048
        for r in range(3):
            assert len(set(idx[r])) == 32


def test_brute_force_knn_precision_kwarg(rng):
    """precision= threads through to the distance matmuls (the cublas
    math-mode analog); on CPU all precisions are exact f32, so results
    must match the default exactly."""
    from raft_tpu.spatial import brute_force_knn

    import jax

    x = jnp.asarray(rng.standard_normal((300, 24)).astype(np.float32))
    q = jnp.asarray(rng.standard_normal((17, 24)).astype(np.float32))
    d_hi, i_hi = brute_force_knn([x], q, 8)
    d_df, i_df = brute_force_knn([x], q, 8, precision="default")
    if jax.default_backend() == "cpu":
        # exact-equality is a CPU-only property: on TPU 'default' is
        # genuinely single-pass bf16 and near ties may reorder
        np.testing.assert_array_equal(np.asarray(i_hi), np.asarray(i_df))
    else:
        assert i_df.shape == i_hi.shape
    d_ip, i_ip = brute_force_knn(
        [x], q, 8, metric=D.InnerProduct, precision="default")
    assert d_ip.shape == (17, 8)


class TestRerank:
    """bf16 stage-1 + exact f32 re-rank mode (brute_force_knn
    rerank_ratio; VERDICT r4 item 8)."""

    def test_rerank_matches_exact(self):
        rs = np.random.RandomState(11)
        x = jnp.asarray(rs.randn(3000, 32), jnp.float32)
        q = jnp.asarray(rs.randn(64, 32), jnp.float32)
        d_ref, i_ref = brute_force_knn([x], q, 10)
        d_rr, i_rr = brute_force_knn([x], q, 10, rerank_ratio=4)
        # distances must agree to f32 (re-ranked distances are exact);
        # id disagreements only at genuine distance ties
        np.testing.assert_allclose(np.asarray(d_rr), np.asarray(d_ref),
                                   rtol=1e-4, atol=1e-4)
        recall = np.mean([len(set(np.asarray(i_rr)[r]) &
                              set(np.asarray(i_ref)[r])) / 10
                          for r in range(64)])
        assert recall >= 0.99, recall

    def test_rerank_multi_partition_translations(self):
        rs = np.random.RandomState(12)
        parts = [jnp.asarray(rs.randn(500, 16), jnp.float32)
                 for _ in range(3)]
        q = jnp.asarray(rs.randn(16, 16), jnp.float32)
        d_ref, i_ref = brute_force_knn(parts, q, 8)
        d_rr, i_rr = brute_force_knn(parts, q, 8, rerank_ratio=4)
        np.testing.assert_allclose(np.asarray(d_rr), np.asarray(d_ref),
                                   rtol=1e-4, atol=1e-4)
        # global ids in range
        assert int(jnp.max(i_rr)) < 1500 and int(jnp.min(i_rr)) >= 0

    def test_rerank_rejected_off_l2(self):
        rs = np.random.RandomState(13)
        x = jnp.asarray(rs.randn(100, 8), jnp.float32)
        q = jnp.asarray(rs.randn(4, 8), jnp.float32)
        with pytest.raises(Exception):
            brute_force_knn([x], q, 4, metric=D.InnerProduct,
                            rerank_ratio=4)


@pytest.mark.parametrize("n,nq,d,k", [
    (300, 17, 13, 5),         # sub-tile, odd sizes (ragged pow2 pad)
    (3000, 33, 128, 100),     # multi index tile, north-star k
    (2500, 24, 64, 10),
])
@pytest.mark.slow
def test_fused_knn_twophase_exact(rng, n, nq, d, k):
    """No-carry two-phase kernel (r5): per-tile select + XLA merge must
    match the naive reference exactly (interpret mode on CPU)."""
    from raft_tpu.ops.knn_tile import fused_knn_twophase

    index = rng.standard_normal((n, d)).astype(np.float32)
    queries = rng.standard_normal((nq, d)).astype(np.float32)
    dist, idx = fused_knn_twophase(jnp.asarray(index),
                                   jnp.asarray(queries), k)
    ref_d, _ = naive_knn(index, queries, k)
    np.testing.assert_allclose(np.asarray(dist), ref_d, rtol=1e-4,
                               atol=1e-4)
    full = ((queries[:, None, :] - index[None, :, :]) ** 2).sum(-1)
    chosen = np.take_along_axis(full, np.asarray(idx), axis=1)
    np.testing.assert_allclose(chosen, ref_d, rtol=1e-4, atol=1e-4)
    assert (np.asarray(idx) >= 0).all() and (np.asarray(idx) < n).all()


def test_fused_knn_twophase_k_cap(rng):
    from raft_tpu.ops.knn_tile import fused_knn_twophase

    x = jnp.asarray(rng.standard_normal((400, 8)).astype(np.float32))
    q = jnp.asarray(rng.standard_normal((4, 8)).astype(np.float32))
    with pytest.raises(Exception):
        fused_knn_twophase(x, q, 129)


def test_fused_knn_twophase_merge_pinned_to_topk(rng, monkeypatch):
    """A process-wide select_impl pin (e.g. approx95) must NOT reach the
    twophase phase-2 merge: the merge is part of the kernel's exactness
    contract and defaults to an explicit impl="topk" pin.  The pallas
    phase is stubbed (its per-build API skew is irrelevant here) — the
    assertion is purely about which impl the merge select_k receives."""
    import importlib

    from raft_tpu import config
    from raft_tpu.ops import knn_tile

    # the module, not the same-named function spatial/__init__ re-exports
    sk_mod = importlib.import_module("raft_tpu.spatial.select_k")

    captured = {}
    real_select_k = sk_mod.select_k

    def spy(keys, k, select_min=True, values=None, impl=None):
        captured["impl"] = impl
        return real_select_k(keys, k, select_min=select_min,
                             values=values, impl="topk")

    def fake_pallas_call(kern, **kw):
        def run(*operands):
            return [jnp.zeros(s.shape, s.dtype) for s in kw["out_shape"]]
        return run

    monkeypatch.setattr(sk_mod, "select_k", spy)
    monkeypatch.setattr(knn_tile.pl, "pallas_call", fake_pallas_call)
    monkeypatch.setattr(knn_tile.pltpu, "CompilerParams",
                        lambda **kw: None, raising=False)

    x = jnp.asarray(rng.standard_normal((300, 8)).astype(np.float32))
    q = jnp.asarray(rng.standard_normal((5, 8)).astype(np.float32))
    with config.override(select_impl="approx95"):
        knn_tile.fused_knn_twophase(x, q, 3)
    assert captured["impl"] == "topk"
    # and the explicit-arg escape hatch still reaches the merge
    knn_tile.fused_knn_twophase(x, q, 3, merge_select_impl="approx")
    assert captured["impl"] == "approx"
