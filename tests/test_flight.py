"""Flight recorder + request-scoped tracing (raft_tpu.core.flight;
docs/OBSERVABILITY.md "Flight recorder & request tracing").

The lifecycle invariant under test everywhere: every ADMITTED request
yields exactly ONE terminal event (resolved/expired/failed) on a
gapless, monotonically-timestamped timeline — across the plain path,
deadline expiry, requeue-once over a breaker trip, hedged dispatch,
recovery, and the out-of-core ANN path.  Plus: the ring-buffer memory
bound holds under 16-thread sustained load with zero post-warmup
compiles, breaker trips capture black-box dumps containing the
tripping batch's events, SLO burn math is exact under a fake clock,
and the trace_report renderings round-trip.
"""

import json
import os
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

import jax.numpy as jnp

from raft_tpu import config
from raft_tpu.comms import faults
from raft_tpu.core import flight
from raft_tpu.core.flight import (
    Exemplars,
    FlightRecorder,
    SLOTracker,
    TERMINAL_KINDS,
)
from raft_tpu.core.metrics import default_registry
from raft_tpu.core.profiler import compile_cache_stats
from raft_tpu.serve import (
    ANNService,
    CircuitBreaker,
    KNNService,
    RecoveryManager,
    inject_replica,
    inject_worker,
)
from raft_tpu.spatial import ann

pytestmark = pytest.mark.serve

SEED = int(os.environ.get("RAFT_TPU_SERVE_SEED", "1234"))
REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


class FakeClock:
    def __init__(self, t=0.0):
        self.t = float(t)

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt


@pytest.fixture(autouse=True)
def _flight_isolation():
    """Each test starts from an empty recorder with recording ON and
    leaves it that way (flight state is process-global)."""
    flight.set_enabled(True)
    flight.reset()
    yield
    flight.set_enabled(True)
    flight.reset()


@pytest.fixture
def rng():
    return np.random.default_rng(SEED)


@pytest.fixture
def index(rng):
    return jnp.asarray(rng.standard_normal((300, 16)), jnp.float32)


def _total_misses():
    return sum(s["misses"] for fn in compile_cache_stats().values()
               for s in fn.values())


def _assert_wellformed(trace, expect_terminal=None):
    """The per-trace invariants: non-empty, starts at admission, ends
    terminal, exactly one terminal, timestamps monotonic (gapless in
    the sense that every recorded step is present and ordered)."""
    assert trace is not None
    kinds = trace.kinds()
    assert kinds, "empty timeline"
    assert kinds[0] == "admitted"
    terminals = [k for k in kinds if k in TERMINAL_KINDS]
    assert len(terminals) == 1, "want exactly one terminal: %r" % kinds
    assert kinds[-1] == terminals[0]
    if expect_terminal is not None:
        assert terminals[0] == expect_terminal
    ts = [ev.ts for ev in trace.events]
    assert ts == sorted(ts), "timeline not monotonic"
    assert trace.dropped == 0
    return kinds


def _step(svc, fut, timeout=20.0):
    t0 = time.monotonic()
    while not fut.done():
        svc.worker.run_once()
        if time.monotonic() - t0 > timeout:
            raise AssertionError("future did not resolve")
        time.sleep(0.001)


# ---------------------------------------------------------------------- #
# recorder primitives
# ---------------------------------------------------------------------- #
class TestRecorder:
    def test_ring_bound_and_order(self):
        rec = FlightRecorder(capacity=16)
        for i in range(100):
            rec.record("tick", service="s", i=i)
        assert len(rec) == 16
        assert rec.capacity == 16
        evs = rec.events()
        assert [e.attrs["i"] for e in evs] == list(range(84, 100))
        ts = [e.ts for e in evs]
        assert ts == sorted(ts)

    def test_filters(self):
        rec = FlightRecorder(capacity=32)
        rec.record("a", service="one")
        rec.record("b", service="two")
        rec.record("a", service="two")
        assert [e.kind for e in rec.events(service="two")] == ["b", "a"]
        assert len(rec.events(kind="a")) == 2
        assert len(rec.events(last=1)) == 1

    def test_trace_ids_unique_and_increasing(self):
        rec = FlightRecorder(capacity=8)
        ids = [rec.new_trace("s").trace_id for _ in range(5)]
        assert ids == sorted(ids) and len(set(ids)) == 5

    def test_blackbox_snapshot_and_dump(self, tmp_path):
        rec = FlightRecorder(capacity=8)
        for i in range(12):
            rec.record("tick", service="s", i=i)
        box = rec.blackbox("unit_test", service="s", last=4)
        assert box["reason"] == "unit_test"
        assert [e["i"] for e in box["events"]] == [8, 9, 10, 11]
        assert rec.blackbox_summaries()[0]["n_events"] == 4
        path = tmp_path / "dump.json"
        rec.dump_to(str(path))
        data = json.loads(path.read_text())
        assert data["capacity"] == 8
        assert len(data["events"]) == 8
        assert data["blackboxes"][0]["reason"] == "unit_test"

    def test_disabled_is_noop(self):
        rec = FlightRecorder(capacity=8)
        flight.set_enabled(False)
        assert rec.new_trace("s") is None
        assert rec.record("tick") is None
        assert len(rec) == 0

    def test_capacity_knob(self):
        with config.override(flight_events="7"):
            rec = FlightRecorder()
        assert rec.capacity == 7

    def test_per_trace_cap_counts_drops(self):
        rec = FlightRecorder(capacity=8)
        tr = rec.new_trace("s")
        for _ in range(flight.TRACE_MAX_EVENTS + 5):
            rec.record("tick", trace=tr)
        assert len(tr.events) == flight.TRACE_MAX_EVENTS
        assert tr.dropped == 5


# ---------------------------------------------------------------------- #
# SLO + exemplars
# ---------------------------------------------------------------------- #
class TestSLO:
    def test_hit_ratio_and_burn_windows(self):
        clock = FakeClock(1000.0)
        slo = SLOTracker("svc", target_s=0.1, objective=0.9,
                         windows_s=(10.0, 100.0), clock=clock)
        # 8 old hits, then 2 recent misses inside the short window
        for _ in range(8):
            slo.observe("t", 0.05)
        clock.advance(50.0)
        assert not slo.observe("t", 0.5)          # over target
        assert not slo.observe("t", 0.05, deadline_ok=False)
        snap = slo.snapshot()
        st = snap["tenants"]["t"]
        assert st["total"] == 10 and st["misses"] == 2
        assert st["hit_ratio"] == pytest.approx(0.8)
        # short window holds only the 2 misses -> miss rate 1.0,
        # budget 0.1 -> burn 10; long window: 2/10 / 0.1 = 2
        assert st["burn"]["10s"] == pytest.approx(10.0)
        assert st["burn"]["100s"] == pytest.approx(2.0)
        fam = default_registry().get("raft_tpu_serve_slo_burn_rate")
        series = {tuple(sorted(lbl.items())): s.value
                  for lbl, s in fam.series()}
        assert series[(("service", "svc"), ("tenant", "t"),
                       ("window", "10s"))] == pytest.approx(10.0)
        misses = default_registry().get(
            "raft_tpu_serve_slo_misses_total")
        # scoped to THIS tracker's service: the family is process-
        # global and other suites (e.g. the fleet router's tracker)
        # legitimately mint their own series
        assert sum(s.value for lbl, s in misses.series()
                   if lbl.get("service") == "svc") == 2

    def test_deadline_only_mode(self):
        slo = SLOTracker("svc", target_s=0.0, objective=0.99,
                         windows_s=(60.0,), clock=FakeClock())
        assert slo.observe(None, 99.0)            # no target: a hit
        assert not slo.observe(None, 0.01, deadline_ok=False)

    def test_exemplars_keep_slowest(self):
        ex = Exemplars(k=3)
        for i, lat in enumerate([0.01, 0.5, 0.02, 0.9, 0.03, 0.4]):
            ex.observe(lat, trace_id=i)
        snap = ex.snapshot()
        assert [e["trace_id"] for e in snap] == [3, 1, 5]
        assert snap[0]["latency_ms"] == pytest.approx(900.0)


# ---------------------------------------------------------------------- #
# lifecycle through the serve pipeline
# ---------------------------------------------------------------------- #
class TestLifecycle:
    def test_plain_resolution_timeline(self, index, rng):
        clock = FakeClock()
        svc = KNNService(index, k=5, max_batch_rows=32,
                         bucket_rungs=(8, 32), max_wait_ms=1.0,
                         start=False, clock=clock)
        try:
            q = jnp.asarray(rng.standard_normal((4, 16)), jnp.float32)
            fut = svc.submit(q)
            clock.advance(0.01)
            assert svc.worker.run_once()
            fut.result(timeout=0)
            kinds = _assert_wellformed(fut.trace(), "resolved")
            assert kinds == ["admitted", "batch_formed",
                             "execute_launch", "execute_ready",
                             "resolved"]
            tl = fut.trace().timeline()
            admitted = tl[0]
            assert admitted["rows"] == 4 and admitted["depth"] == 1
            formed = tl[1]
            assert formed["rung"] == 8 and formed["riders"] == 1
            assert "batch" in formed
            ready = tl[3]
            assert "exec_s" in ready and "block_s" in ready
            assert tl[-1]["latency_s"] >= 0.0
            # SLO fed: one hit for the default tenant
            st = svc.stats()
            assert st["slo"]["tenants"]["default"]["total"] == 1
            assert st["exemplars"][0]["trace_id"] == \
                fut.trace().trace_id
        finally:
            svc.close()

    def test_deadline_expiry_terminal(self, index, rng):
        clock = FakeClock()
        svc = KNNService(index, k=5, max_batch_rows=32,
                         bucket_rungs=(8, 32), max_wait_ms=1.0,
                         start=False, clock=clock)
        try:
            q = jnp.asarray(rng.standard_normal((2, 16)), jnp.float32)
            fut = svc.submit(q, timeout=0.5)
            clock.advance(1.0)          # past deadline AND the window
            svc.worker.run_once()
            assert fut.exception(timeout=0) is not None
            kinds = _assert_wellformed(fut.trace(), "expired")
            assert "batch_formed" not in kinds  # expired pre-batch
            tl = fut.trace().timeline()
            assert tl[-1]["reason"] == "deadline"
            assert svc.stats()["slo"]["tenants"]["default"][
                "misses"] == 1
        finally:
            svc.close()

    def test_close_expiry_terminal(self, index, rng):
        clock = FakeClock()
        svc = KNNService(index, k=5, max_batch_rows=32,
                         bucket_rungs=(8, 32), max_wait_ms=1e6,
                         start=False, clock=clock)
        q = jnp.asarray(rng.standard_normal((2, 16)), jnp.float32)
        fut = svc.submit(q)
        svc.close(drain=False)
        assert fut.exception(timeout=0) is not None
        _assert_wellformed(fut.trace(), "expired")
        assert fut.trace().timeline()[-1]["reason"] == "close"

    def test_requeue_once_then_failed_and_blackbox(self, index, rng):
        """Breaker trip path: first failure requeues (non-terminal
        `requeued`), the second strike is the one terminal `failed`;
        the trip captures a black box holding the tripping batch's
        events."""
        clock = FakeClock()
        breaker = CircuitBreaker("flightknn", failure_threshold=1,
                                 cooldown_s=0.2, clock=clock)
        svc = KNNService(index, k=5, max_batch_rows=32,
                         bucket_rungs=(8, 32), max_wait_ms=1.0,
                         start=False, clock=clock, breaker=breaker,
                         name="flightknn")
        try:
            q = jnp.asarray(rng.standard_normal((2, 16)), jnp.float32)
            with inject_worker(svc.worker,
                               faults.FailNth(1, persistent=True)):
                fut = svc.submit(q)
                clock.advance(0.01)
                svc.worker.run_once()       # fails -> trip -> requeue
                assert not fut.done()
                assert "requeued" in fut.trace().kinds()
                clock.advance(0.5)          # past cooldown: half-open
                svc.worker.run_once()       # second strike -> failed
                assert fut.exception(timeout=0) is not None
            kinds = _assert_wellformed(fut.trace(), "failed")
            assert kinds.count("requeued") == 1
            assert kinds.count("batch_formed") == 2
            # the trip's black box contains this batch's events
            boxes = [b for b in
                     flight.default_recorder().blackboxes()
                     if b["reason"] == "breaker_trip"
                     and b["service"] == "flightknn"]
            assert boxes
            box_kinds = [e["kind"] for e in boxes[0]["events"]
                         if e.get("service") == "flightknn"]
            assert "batch_formed" in box_kinds
            assert "execute_launch" in box_kinds
            # breaker transitions are in the ordered stream
            sys_kinds = [e.kind for e in
                         flight.default_recorder().events(
                             service="flightknn")]
            assert "breaker_open" in sys_kinds
        finally:
            svc.close()

    def test_hedge_path_timeline(self, index, rng):
        svc = KNNService(index, k=5, replicas=2, hedge_ms=60.0,
                         max_batch_rows=32, bucket_rungs=(8, 32),
                         max_wait_ms=0.5)
        try:
            svc.warmup()
            q = jnp.asarray(rng.standard_normal((4, 16)), jnp.float32)
            with inject_replica(svc, 0, faults.Delay(0.8)):
                futs = [svc.submit(jnp.copy(q)) for _ in range(4)]
                for f in futs:
                    f.result(timeout=60)
            time.sleep(1.0)   # abandoned losers wake and bail
            hedged = [f for f in futs
                      if "hedge" in f.trace().kinds()]
            assert hedged, "no hedge event reached any trace"
            for f in futs:
                kinds = _assert_wellformed(f.trace(), "resolved")
                assert "replica_dispatch" in kinds
            tl = hedged[0].trace().timeline()
            hedge_ev = next(e for e in tl if e["kind"] == "hedge")
            assert {"primary", "hedge", "threshold_s"} <= set(hedge_ev)
            assert any(e["kind"] == "hedge_win" for e in tl)
        finally:
            svc.close()

    def test_recovery_events_and_survival(self, index, rng):
        svc = KNNService(index, k=5, max_batch_rows=32,
                         bucket_rungs=(8, 32), max_wait_ms=0.5)
        try:
            svc.warmup()
            manager = RecoveryManager(services=[svc])
            manager.recover()
            sys_kinds = [e.kind
                         for e in flight.default_recorder().events()]
            for k in ("recovery_begin", "recovery_pause",
                      "recovery_warmup", "recovery_readmit",
                      "recovery_done"):
                assert k in sys_kinds, k
            boxes = flight.default_recorder().blackboxes()
            assert any(b["reason"] == "recovery" for b in boxes)
            # traffic still resolves cleanly post-recovery
            q = jnp.asarray(rng.standard_normal((4, 16)), jnp.float32)
            fut = svc.submit(q)
            fut.result(timeout=30)
            _assert_wellformed(fut.trace(), "resolved")
        finally:
            svc.close()

    def test_ooc_path_timeline_and_events(self, rng):
        X = jnp.asarray(rng.standard_normal((2500, 24)), jnp.float32)
        idx = ann.ivf_flat_build(
            X, ann.IVFFlatParams(nlist=24, nprobe=6), seed=SEED)
        store_bytes = int(np.asarray(idx.slot_vecs).nbytes)
        svc = ANNService(idx, k=10, ooc=True,
                         device_budget_bytes=max(1, store_bytes // 3),
                         max_batch_rows=32, bucket_rungs=(8, 32),
                         max_wait_ms=1.0, nprobe_ladder=(4, 8),
                         delta_cap=64, compact_rows=0, start=False)
        try:
            q = jnp.asarray(rng.standard_normal((4, 24)), jnp.float32)
            fut = svc.submit(q)
            _step(svc, fut)
            _assert_wellformed(fut.trace(), "resolved")
            # compaction lands in the same ordered stream
            svc.insert(np.arange(8) + 10_000,
                       rng.standard_normal((8, 24)).astype(np.float32))
            assert svc.compact()
            kinds = [e.kind for e in
                     flight.default_recorder().events(
                         service=svc.name)]
            assert "compaction" in kinds
        finally:
            svc.close()

    def test_shed_records_system_event(self, index, rng):
        svc = KNNService(index, k=5, max_batch_rows=32,
                         bucket_rungs=(8, 32), max_wait_ms=1e6,
                         queue_cap=1, start=False, clock=FakeClock())
        try:
            q = jnp.asarray(rng.standard_normal((2, 16)), jnp.float32)
            svc.submit(q)
            with pytest.raises(Exception):
                svc.submit(q)
            sheds = flight.default_recorder().events(kind="shed")
            assert sheds and sheds[-1].attrs["reason"] == "overload"
            assert sheds[-1].trace_id is None
        finally:
            svc.close(drain=False)

    def test_disabled_recording_end_to_end(self, index, rng):
        flight.set_enabled(False)
        svc = KNNService(index, k=5, max_batch_rows=32,
                         bucket_rungs=(8, 32), max_wait_ms=1.0)
        try:
            q = jnp.asarray(rng.standard_normal((4, 16)), jnp.float32)
            fut = svc.submit(q)
            fut.result(timeout=30)
            assert fut.trace() is None
            assert len(flight.default_recorder()) == 0
        finally:
            svc.close()


# ---------------------------------------------------------------------- #
# sustained concurrent load: bound + exactly-once + zero compiles
# ---------------------------------------------------------------------- #
class TestSustainedLoad:
    def test_16_threads_bounded_ring_zero_compiles(self, index, rng):
        svc = KNNService(index, k=5, max_batch_rows=64,
                         bucket_rungs=(8, 16, 64), max_wait_ms=0.5)
        try:
            svc.warmup()
            m0 = _total_misses()
            pool = [jnp.asarray(rng.standard_normal((2, 16)),
                                jnp.float32) for _ in range(8)]
            futs = []
            lock = threading.Lock()

            def client(tid):
                mine = []
                for i in range(25):
                    f = svc.submit(jnp.copy(pool[(tid + i) % 8]))
                    f.result(timeout=60)
                    mine.append(f)
                with lock:
                    futs.extend(mine)

            threads = [threading.Thread(target=client, args=(t,))
                       for t in range(16)]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=120)
                assert not t.is_alive()
            assert len(futs) == 16 * 25
            rec = flight.default_recorder()
            assert len(rec) <= rec.capacity
            for f in futs:
                _assert_wellformed(f.trace(), "resolved")
            assert _total_misses() == m0
            snap = svc.stats()["slo"]["tenants"]["default"]
            assert snap["total"] > 0
        finally:
            svc.close()


# ---------------------------------------------------------------------- #
# snapshot + renderings + lint self-tests
# ---------------------------------------------------------------------- #
class TestSurfaces:
    def test_metrics_snapshot_flight_section(self, index, rng):
        from raft_tpu.session import metrics_snapshot

        svc = KNNService(index, k=5, max_batch_rows=32,
                         bucket_rungs=(8, 32), max_wait_ms=1.0)
        try:
            q = jnp.asarray(rng.standard_normal((4, 16)), jnp.float32)
            svc.submit(q).result(timeout=30)
            svc.stats()   # publishes SLO gauges
        finally:
            svc.close()
        fl = metrics_snapshot()["flight"]
        assert fl["enabled"] is True
        assert 0 < fl["events"] <= fl["capacity"]
        assert svc.name in fl["slo"]
        assert svc.name in fl["exemplars"]

    def test_trace_report_renderings(self, index, rng, tmp_path):
        sys.path.insert(0, REPO)
        from tools.trace_report import (
            load_events,
            render_waterfall,
            to_chrome_trace,
            trace_ids,
        )

        svc = KNNService(index, k=5, max_batch_rows=32,
                         bucket_rungs=(8, 32), max_wait_ms=1.0)
        try:
            q = jnp.asarray(rng.standard_normal((4, 16)), jnp.float32)
            fut = svc.submit(q)
            fut.result(timeout=30)
        finally:
            svc.close()
        timeline = fut.trace().timeline()
        water = render_waterfall(timeline)
        for kind in ("admitted", "execute_ready", "resolved"):
            assert kind in water
        chrome = to_chrome_trace(timeline)
        phases = {e["ph"] for e in chrome}
        assert "X" in phases and "i" in phases
        names = {e["name"] for e in chrome}
        assert {"queue", "execute", "request"} <= names
        # dump -> load round trip
        path = tmp_path / "dump.json"
        flight.default_recorder().dump_to(str(path))
        events = load_events(json.loads(path.read_text()))
        assert fut.trace().trace_id in trace_ids(events)
        json.dumps(chrome)   # valid JSON payload

    def test_loadgen_slow_trace_capture(self, index):
        sys.path.insert(0, REPO)
        from tools.loadgen import run_load

        svc = KNNService(index, k=5, max_batch_rows=32,
                         bucket_rungs=(8, 32), max_wait_ms=0.5)
        try:
            svc.warmup()
            rep = run_load(svc, mode="closed", duration=1.0,
                           concurrency=2, rows=2, trace_k=2)
        finally:
            svc.close()
        slow = rep["slow_traces"]
        assert 1 <= len(slow) <= 2
        assert slow[0]["latency_ms"] >= slow[-1]["latency_ms"]
        assert slow[0]["timeline"][0]["kind"] == "admitted"
        assert slow[0]["timeline"][-1]["kind"] == "resolved"

    def test_style_check_metric_lint_selftest(self):
        out = subprocess.run(
            [sys.executable, os.path.join(REPO, "ci",
                                          "style_check.py"),
             "--selftest"],
            capture_output=True, text=True)
        assert out.returncode == 0, out.stderr

    def test_health_check_surfaces_blackboxes(self):
        flight.default_recorder().record("tick", service="s")
        flight.default_recorder().blackbox("unit", service="s")
        # session-free surface: the summaries feed health_check
        summaries = flight.default_recorder().blackbox_summaries()
        assert summaries[0]["reason"] == "unit"
