"""MST / connect_components / single_linkage tests vs scipy ground truth.

Mirrors cpp/test/mst.cu (known graphs + weight-sum checks) and
cpp/test/sparse/linkage.cu (end-to-end labels vs expected clusters).
"""

import numpy as np
import pytest
import scipy.cluster.hierarchy as sch
import scipy.sparse as sp
import scipy.sparse.csgraph as csg

from raft_tpu.sparse import CSR
from raft_tpu.sparse.hierarchy import single_linkage
from raft_tpu.sparse.linkage import connect_components, cross_color_nn
from raft_tpu.sparse.mst import mst, mst_weight


def random_sym_graph(rng, n, density=0.3):
    d = rng.random((n, n)) * (rng.random((n, n)) < density)
    d = np.triu(d, 1)
    d = d + d.T
    return d.astype(np.float32)


def ref_mst_weight(adj):
    return csg.minimum_spanning_tree(sp.csr_matrix(adj)).sum()


class TestMST:
    @pytest.mark.parametrize("seed", [0, 1, 2])
    @pytest.mark.parametrize("n", [10, 40])
    def test_weight_matches_scipy(self, seed, n):
        rng = np.random.default_rng(seed)
        adj = random_sym_graph(rng, n, density=0.5)
        ncomp, _ = csg.connected_components(sp.csr_matrix(adj), directed=False)
        g, colors = mst(CSR.from_dense(adj))
        assert int(g.n_edges) == n - ncomp
        np.testing.assert_allclose(float(mst_weight(g)),
                                   float(ref_mst_weight(adj)), rtol=1e-5)
        assert len(np.unique(np.asarray(colors))) == ncomp

    def test_known_graph(self):
        # classic 4-node diamond: MST = {0-1 (1), 1-2 (2), 1-3 (3)}
        adj = np.zeros((4, 4), np.float32)
        edges = [(0, 1, 1.0), (0, 2, 4.0), (1, 2, 2.0), (1, 3, 3.0),
                 (2, 3, 5.0)]
        for i, j, w in edges:
            adj[i, j] = adj[j, i] = w
        g, colors = mst(CSR.from_dense(adj))
        assert int(g.n_edges) == 3
        assert float(mst_weight(g)) == 6.0
        assert len(np.unique(np.asarray(colors))) == 1

    def test_forest_restart_with_colors(self):
        # two disconnected pairs; restart with extra bridging edge
        adj = np.zeros((4, 4), np.float32)
        adj[0, 1] = adj[1, 0] = 1.0
        adj[2, 3] = adj[3, 2] = 1.0
        g, colors = mst(CSR.from_dense(adj))
        assert int(g.n_edges) == 2
        assert len(np.unique(np.asarray(colors))) == 2
        bridge = np.zeros((4, 4), np.float32)
        bridge[1, 2] = bridge[2, 1] = 5.0
        g2, colors2 = mst(CSR.from_dense(bridge), colors=colors)
        assert int(g2.n_edges) == 1
        assert len(np.unique(np.asarray(colors2))) == 1


class TestConnectComponents:
    def test_cross_color_nn(self):
        X = np.array([[0.0, 0], [1, 0], [10, 0], [11, 0]], np.float32)
        colors = np.array([0, 0, 1, 1], np.int32)
        d, j = cross_color_nn(X, colors)
        np.testing.assert_allclose(np.asarray(d), [10, 9, 9, 10], rtol=1e-5)
        np.testing.assert_array_equal(np.asarray(j), [2, 2, 1, 1])

    def test_connects_components(self):
        rng = np.random.default_rng(3)
        X = np.concatenate([rng.random((10, 2)),
                            rng.random((10, 2)) + 5]).astype(np.float32)
        colors = np.array([0] * 10 + [1] * 10, np.int32)
        fix = connect_components(X, colors)
        dense = np.asarray(fix.to_dense())
        # symmetric cross edges only
        np.testing.assert_allclose(dense, dense.T)
        assert (dense[:10, :10] == 0).all() and (dense[10:, 10:] == 0).all()
        assert (dense > 0).sum() >= 2


class TestSingleLinkage:
    @pytest.mark.parametrize("linkage", ["knn", "pairwise"])
    def test_matches_scipy_blobs(self, linkage):
        rng = np.random.default_rng(11)
        X = np.concatenate([
            rng.normal(0, 0.3, (20, 3)),
            rng.normal(4, 0.3, (25, 3)),
            rng.normal((8, 0, 0), 0.3, (15, 3)),
        ]).astype(np.float32)
        res = single_linkage(X, n_clusters=3, linkage=linkage)
        Z = sch.linkage(X, method="single")
        ref = sch.fcluster(Z, t=3, criterion="maxclust")
        # identical partitions modulo label permutation
        for lab in np.unique(res.labels):
            members = ref[res.labels == lab]
            assert (members == members[0]).all()
        assert len(np.unique(res.labels)) == 3
        # dendrogram deltas match scipy's merge heights
        # f32 device distances vs scipy f64
        np.testing.assert_allclose(res.deltas, Z[:, 2], rtol=1e-3, atol=1e-4)

    def test_n_clusters_one(self):
        rng = np.random.default_rng(5)
        X = rng.random((12, 2)).astype(np.float32)
        res = single_linkage(X, n_clusters=1)
        assert (res.labels == 0).all()

    def test_sizes_and_children_shape(self):
        rng = np.random.default_rng(6)
        X = rng.random((16, 2)).astype(np.float32)
        res = single_linkage(X, n_clusters=2)
        assert res.children.shape == (15, 2)
        assert res.sizes[-1] == 16
