"""Native host runtime tests: C++ results must equal the Python fallbacks.

The toolchain is part of the image, so these tests require the native
layer to load (a silent fallback would mask build regressions).
"""

import numpy as np
import pytest

from raft_tpu.core import native


@pytest.fixture(scope="module", autouse=True)
def require_native():
    assert native.native_available(), "native host runtime failed to build/load"
    assert native.native_version().startswith("raft_tpu_host")


class TestDendrogram:
    def test_matches_python(self):
        rng = np.random.default_rng(0)
        m = 40
        # random spanning tree edges
        src = np.arange(1, m)
        dst = np.asarray([rng.integers(0, i) for i in range(1, m)])
        w = rng.random(m - 1)

        nat = native.build_dendrogram(src, dst, w, m)
        assert nat is not None
        children, delta, sizes = nat

        # python reference (force fallback by calling the internals)
        from raft_tpu.sparse.hierarchy import _UnionFind
        order = np.argsort(w, kind="stable")
        s, d, ww = src[order], dst[order], w[order]
        uf = _UnionFind(m)
        ref_children = np.zeros((m - 1, 2), np.int64)
        ref_sizes = np.zeros(m - 1, np.int64)
        for i in range(m - 1):
            aa, bb = uf.find(int(s[i])), uf.find(int(d[i]))
            ref_children[i] = (aa, bb)
            ref_sizes[i] = uf.size[aa] + uf.size[bb]
            uf.union(aa, bb)
        np.testing.assert_array_equal(children, ref_children)
        np.testing.assert_allclose(delta, ww)
        np.testing.assert_array_equal(sizes, ref_sizes)

    def test_extract_matches_python(self):
        rng = np.random.default_rng(1)
        m = 30
        src = np.arange(1, m)
        dst = np.asarray([rng.integers(0, i) for i in range(1, m)])
        w = rng.random(m - 1)
        children, _, _ = native.build_dendrogram(src, dst, w, m)
        for k in [2, 3, 7]:
            nat = native.extract_clusters(children, k, m)
            # python path: replicate inline (avoid the native short-circuit)
            parent = np.full(2 * m - 1, -1, np.int64)
            for i in range(m - k):
                nid = m + i
                parent[children[i, 0]] = nid
                parent[children[i, 1]] = nid

            def find(x):
                while parent[x] != -1:
                    x = parent[x]
                return x

            roots = np.array([find(i) for i in range(m)])
            _, ref = np.unique(roots, return_inverse=True)
            np.testing.assert_array_equal(nat, ref)


class TestPacking:
    def test_build_lists(self):
        rng = np.random.default_rng(2)
        labels = rng.integers(0, 7, 100)
        table, ml = native.build_lists(labels, 7)
        assert table.shape == (7, ml)
        # every row id appears exactly once, in its own list
        flat = table[table >= 0]
        assert sorted(flat) == list(range(100))
        for l in range(7):
            members = table[l][table[l] >= 0]
            assert (labels[members] == l).all()

    def test_pack_groups(self):
        rng = np.random.default_rng(3)
        m, L = 50, 5
        owner = rng.integers(0, L, m)
        dist = rng.random(m)
        gmax = int(np.bincount(owner, minlength=L).max())
        groups, radius = native.pack_groups(owner, dist, L, gmax)
        for l in range(L):
            members = groups[l][groups[l] >= 0]
            assert (owner[members] == l).all()
            # descending distance order
            dd = dist[members]
            assert (np.diff(dd) <= 1e-12).all()
            if len(members):
                np.testing.assert_allclose(radius[l], dist[owner == l].max())


class TestArena:
    def test_alloc_stats(self):
        import ctypes
        from raft_tpu.core.native import _load
        lib = _load()
        before_total, before_use = native.arena_stats()
        p = lib.rt_alloc(1000)
        assert p is not None and p % 64 == 0  # 64-byte aligned
        total, in_use = native.arena_stats()
        assert in_use >= before_use + 1024  # pow2 size class
        lib.rt_free(ctypes.c_void_p(p))
        _, after = native.arena_stats()
        assert after == before_use


class TestIntegration:
    def test_single_linkage_uses_native(self):
        # end-to-end single_linkage gives identical labels with native on
        rng = np.random.default_rng(4)
        X = np.concatenate([rng.normal(0, 0.3, (15, 2)),
                            rng.normal(5, 0.3, (15, 2))]).astype(np.float32)
        from raft_tpu.sparse.hierarchy import single_linkage
        res = single_linkage(X, n_clusters=2)
        assert (res.labels[:15] == res.labels[0]).all()
        assert (res.labels[15:] == res.labels[15]).all()
        assert res.labels[0] != res.labels[15]
