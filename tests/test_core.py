"""Core runtime tests (reference: cpp/test/handle.cpp, test/integer_utils.cpp,
test/pow2_utils.cu, test/nvtx.cpp)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from raft_tpu import Handle, RaftError, expects, fail
from raft_tpu.core import tracing, utils
from raft_tpu.core.handle import stream_syncer


class TestErrors:
    def test_expects_pass(self):
        expects(True, "should not raise")

    def test_expects_fail(self):
        with pytest.raises(RaftError, match="bad value 42"):
            expects(False, "bad value %d", 42)

    def test_fail(self):
        with pytest.raises(RaftError, match="always fails"):
            fail("always fails")

    def test_stack_trace_collected(self):
        try:
            fail("boom")
        except RaftError as e:
            assert "Obtained stack trace" in str(e)
            assert e.raw_message == "boom"


class TestHandle:
    def test_default_device(self):
        h = Handle()
        assert h.get_device() in jax.devices()

    def test_stream_pool(self):
        h = Handle(n_streams=4)
        assert h.is_stream_pool_initialized()
        assert h.get_stream_pool_size() == 4
        assert h.get_stream_from_stream_pool(1) is not h.get_stream_from_stream_pool(2)
        # wraps around
        assert h.get_stream_from_stream_pool(5) is h.get_stream_from_stream_pool(1)

    def test_no_pool_raises(self):
        h = Handle()
        assert not h.is_stream_pool_initialized()
        with pytest.raises(RaftError):
            h.get_stream_from_stream_pool(0)
        # next_usable falls back to main stream
        assert h.get_next_usable_stream(3) is h.get_stream()

    def test_stream_sync(self):
        h = Handle(n_streams=2)
        s = h.get_stream()
        x = jnp.ones((128, 128)) @ jnp.ones((128, 128))
        s.record(x)
        h.sync_stream()
        h.sync_stream_pool()

    def test_comms_not_initialized(self):
        h = Handle()
        assert not h.comms_initialized()
        with pytest.raises(RaftError):
            h.get_comms()

    def test_subcomm(self):
        h = Handle()
        sentinel = object()
        h.set_comms(sentinel)
        assert h.get_comms() is sentinel
        h.set_subcomm("rows", sentinel)
        assert h.get_subcomm("rows") is sentinel
        with pytest.raises(RaftError):
            h.get_subcomm("cols")

    def test_device_properties(self):
        props = Handle().get_device_properties()
        assert "platform" in props and "device_kind" in props

    def test_stream_syncer(self):
        h = Handle(n_streams=1)
        with stream_syncer(h) as hh:
            assert hh is h


class TestUtils:
    def test_ceildiv(self):
        assert utils.ceildiv(10, 3) == 4
        assert utils.ceildiv(9, 3) == 3
        assert utils.ceildiv(1, 128) == 1

    def test_align(self):
        assert utils.align_to(100, 64) == 128
        assert utils.align_down(100, 64) == 64
        assert utils.round_up_safe(7, 7) == 7

    def test_pow2_predicates(self):
        assert utils.is_pow2(128)
        assert not utils.is_pow2(100)
        assert utils.log2(1024) == 10
        with pytest.raises(RaftError):
            utils.log2(0)

    def test_pow2_class(self):
        p = utils.Pow2(16)
        assert p.div(33) == 2
        assert p.mod(33) == 1
        assert p.round_up(33) == 48
        assert p.round_down(33) == 32
        assert p.is_aligned(48)
        with pytest.raises(RaftError):
            utils.Pow2(12)


class TestTracing:
    def test_annotate_runs(self):
        with tracing.annotate("test range %d", 7):
            x = jnp.arange(8).sum()
        assert int(x) == 28

    def test_push_pop(self):
        tracing.range_push("outer %s", "range")
        tracing.range_push("inner")
        tracing.range_pop()
        tracing.range_pop()
        # popping an empty stack is a no-op
        tracing.range_pop()

    def test_disable(self):
        tracing.set_enabled(False)
        try:
            with tracing.annotate("disabled"):
                pass
            tracing.range_push("disabled")
            tracing.range_pop()
        finally:
            tracing.set_enabled(True)
        assert tracing.is_enabled()


class TestTracingPopWhileDisabled:
    """Regression: pop must drain the stack even when tracing is disabled."""

    def test_push_disable_pop(self):
        from raft_tpu.core import tracing

        tracing.range_push("leaky")
        tracing.set_enabled(False)
        try:
            tracing.range_pop()
            assert len(tracing._range_stack()) == 0
        finally:
            tracing.set_enabled(True)


class TestDebugHooks:
    """Opt-in numeric sanitizers (SURVEY §5: debug_nans / checkify; the
    reference's analog is the lineinfo-for-memcheck build flag,
    cpp/CMakeLists.txt:45)."""

    def _poisoned(self):
        rng = np.random.default_rng(0)
        X = rng.standard_normal((64, 8)).astype(np.float32)
        X[13, 3] = np.nan
        return X

    def test_kmeans_catches_seeded_nan(self):
        from raft_tpu.core import debug
        from raft_tpu.spectral.kmeans import kmeans

        X = self._poisoned()
        kmeans(X, 4)  # disabled: silent (NaN propagates, no raise)
        debug.enable_debug_checks(True)
        try:
            with pytest.raises(debug.NumericError, match="observations"):
                kmeans(X, 4)
        finally:
            debug.enable_debug_checks(False)

    def test_lanczos_catches_seeded_nan(self):
        from raft_tpu.core import debug
        from raft_tpu.linalg.lanczos import compute_smallest_eigenvectors

        rng = np.random.default_rng(1)
        A = rng.standard_normal((32, 32)).astype(np.float32)
        A = A + A.T
        A[5, 7] = A[7, 5] = np.nan
        Aj = jnp.asarray(A)
        debug.enable_debug_checks(True)
        try:
            with pytest.raises(debug.NumericError, match="lanczos"):
                compute_smallest_eigenvectors(Aj, 32, 2)
        finally:
            debug.enable_debug_checks(False)

    def test_debug_nans_scope(self):
        from raft_tpu.core.debug import debug_nans

        with debug_nans():
            @jax.jit
            def f(x):
                return jnp.log(x)

            with pytest.raises(FloatingPointError):
                f(jnp.asarray(-1.0)).block_until_ready()
        assert not jax.config.jax_debug_nans

    def test_checkify_checks_wrapper(self):
        from raft_tpu.core.debug import checkify_checks

        def f(x):
            return jnp.sqrt(x) + 1.0

        g = checkify_checks(f)
        assert float(g(jnp.asarray(4.0))) == 3.0
        with pytest.raises(Exception, match="nan"):
            g(jnp.asarray(-1.0))

    def test_check_finite_skipped_under_trace(self):
        """The eager sanitizer must not break jittability of the public
        API (in-trace checking is checkify_checks's job)."""
        from raft_tpu.core import debug
        from raft_tpu.spectral.kmeans import kmeans

        debug.enable_debug_checks(True)
        try:
            out = jax.jit(lambda X: kmeans(X, 2).centroids)(
                jnp.asarray(np.random.default_rng(3)
                            .standard_normal((32, 4)), jnp.float32))
            assert out.shape == (2, 4)
        finally:
            debug.enable_debug_checks(False)
