"""raft_tpu.config: the one owner of the perf knobs (VERDICT r4 item 7).

Covers resolution order (override > configure > env alias > default),
whitelist validation (probe-only modes unreachable), the
consumed-at-trace-time warning, and that the four consumer sites
actually resolve through the module.
"""

import warnings

import pytest

from raft_tpu import config


@pytest.fixture(autouse=True)
def _reset_config(monkeypatch):
    monkeypatch.setattr(config, "_values", {})
    monkeypatch.setattr(config, "_consumed", {})
    for _, (env, _, _) in config._KNOBS.items():
        monkeypatch.delenv(env, raising=False)
    yield


def test_defaults():
    assert config.get("select_impl") == "topk"
    assert config.get("tile_merge") == "tile_topk"
    assert config.get("knn_tile_merge") == "merge"
    assert config.get("fused_knn_impl") is None


def test_env_alias(monkeypatch):
    monkeypatch.setenv("RAFT_TPU_SELECT_IMPL", "chunked")
    assert config.get("select_impl") == "chunked"


def test_configure_beats_env(monkeypatch):
    monkeypatch.setenv("RAFT_TPU_SELECT_IMPL", "chunked")
    config.configure(select_impl="approx")
    assert config.get("select_impl") == "approx"
    config.configure(select_impl=None)          # revert to env
    assert config.get("select_impl") == "chunked"


def test_override_innermost_wins():
    config.configure(tile_merge="direct")
    with config.override(tile_merge="tile_topk"):
        assert config.get("tile_merge") == "tile_topk"
        with config.override(tile_merge="direct"):
            assert config.get("tile_merge") == "direct"
        assert config.get("tile_merge") == "tile_topk"
    assert config.get("tile_merge") == "direct"


def test_override_none_reverts_to_env_default(monkeypatch):
    """override(knob=None) is a scoped revert (ADVICE r5): it must
    resolve to env/default inside the scope, not pin a literal None
    that shadows them."""
    monkeypatch.setenv("RAFT_TPU_SELECT_IMPL", "chunked")
    config.configure(select_impl="approx")
    with config.override(select_impl=None):
        # env wins inside the revert scope (configured value bypassed,
        # exactly like configure(select_impl=None))
        assert config.get("select_impl") == "chunked"
        assert config.describe()["select_impl"] == "chunked"
    assert config.get("select_impl") == "approx"     # scope popped
    monkeypatch.delenv("RAFT_TPU_SELECT_IMPL")
    with config.override(select_impl=None):
        # no env either: the built-in default, never a literal None
        assert config.get("select_impl") == "topk"
        assert config.describe()["select_impl"] == "topk"
    # inner None-revert under an outer pin reverts all the way down
    with config.override(tile_merge="direct"):
        with config.override(tile_merge=None):
            assert config.get("tile_merge") == "tile_topk"
        assert config.get("tile_merge") == "direct"


def test_unknown_knob_and_value_rejected():
    with pytest.raises(ValueError):
        config.configure(no_such_knob="x")
    with pytest.raises(ValueError):
        config.configure(select_impl="warp_heap")
    # the attribution probe must be unreachable from config
    with pytest.raises(ValueError):
        config.configure(knn_tile_merge="skip")
    with pytest.raises(ValueError):
        with config.override(knn_tile_merge="skip"):
            pass


def test_consumed_warning_fires_once_per_change():
    assert config.get("select_impl") == "topk"   # consume the default
    with pytest.warns(UserWarning, match="already consumed at trace"):
        config.configure(select_impl="chunked")
    # re-setting to an already-consumed value stays silent
    config.get("select_impl")
    with warnings.catch_warnings():
        warnings.simplefilter("error")
        config.configure(select_impl="chunked")


def test_describe_does_not_consume():
    d = config.describe()
    assert d["select_impl"] == "topk" and d["tile_merge"] == "tile_topk"
    assert config._consumed == {}


def test_consumer_sites_resolve_through_config():
    """The four historical env-read sites honor configure()."""
    import jax.numpy as jnp
    import numpy as np

    from raft_tpu.spatial.select_k import top_k_rows
    from raft_tpu.spatial.fused_l2_knn import fused_l2_knn

    keys = jnp.asarray(np.random.RandomState(0).randn(16, 512),
                       jnp.float32)
    v_ref, i_ref = top_k_rows(keys, 5, impl="topk")
    config.configure(select_impl="chunked")
    v, i = top_k_rows(keys, 5)
    np.testing.assert_allclose(np.asarray(v), np.asarray(v_ref), rtol=1e-6)

    x = jnp.asarray(np.random.RandomState(1).randn(256, 16), jnp.float32)
    q = jnp.asarray(np.random.RandomState(2).randn(8, 16), jnp.float32)
    d_ref, _ = fused_l2_knn(x, q, 4)
    with config.override(tile_merge="direct"):
        d, _ = fused_l2_knn(x, q, 4)
    np.testing.assert_allclose(np.asarray(d), np.asarray(d_ref),
                               rtol=1e-5, atol=1e-5)
