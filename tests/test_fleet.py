"""Fault-domain serving fleet (raft_tpu.fleet): router + multi-process
workers with crash-rejoin, drain choreography, and a chaos harness
(docs/FAULT_MODEL.md "Fleet fault domains").

Covers: the wire protocol's typed-error round trip and HTTP status
taxonomy, rendezvous placement stability under roster churn, router-
side top-k merge, seeded frame-fault and chaos-schedule determinism,
worker-label metric relabeling, the sentinel's fleet rules
(``worker_dead``/``rejoin_lag``) and per-(service, rung) latency
watches, and — against live worker PROCESSES — fleet formation over
ephemeral ports, fan-out/merge search, single-owner inserts, the
crash-restart rejoin under live ingestion (kill -9 mid-WAL-append:
zero acked-row loss, exactly-one terminal flight event per admitted
request, byte-identical answers vs an unkilled control fleet), drain
choreography, hedged re-dispatch on a replicated fleet, and the
``tools/metrics_report.py`` fleet section.  ``./run_tests.sh --fleet``
runs this file alone; ``./stress.sh fleet N`` loops the loadgen chaos
scenario with rotating seeds.
"""

import itertools
import json
import threading
import time
import urllib.request

import numpy as np
import pytest

from raft_tpu import config
from raft_tpu.core import flight
from raft_tpu.core.error import (CommError, CommTimeoutError,
                                 LogicError, RaftError,
                                 ServiceOverloadError,
                                 ServiceUnavailableError)
from raft_tpu.core.metrics import default_registry
from raft_tpu.fleet import Fleet, Router, protocol
from raft_tpu.fleet.chaos import ChaosSchedule, FrameFaults
from raft_tpu.fleet.router import _relabel_metrics
from raft_tpu.fleet.worker import _synth
from raft_tpu.serve import AnomalySentinel

pytestmark = pytest.mark.fleet

ROWS, DIM, K, NLIST, SEED = 600, 8, 5, 8, 7
_uniq = itertools.count()

# rows earlier tests inserted into the shared module fleet — the
# crash-rejoin control comparison must account for them too (the
# control fleet has to hold the SAME delta set to answer identically)
_INSERTED = {}


def _name(prefix="fltsvc"):
    return "%s%d" % (prefix, next(_uniq))


class FakeClock:
    def __init__(self, t=100.0):
        self.t = t

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt


@pytest.fixture(autouse=True)
def _flight_isolation():
    yield
    flight.reset()


@pytest.fixture(scope="module")
def fleet(tmp_path_factory):
    """One live 2-worker SHARDED fleet shared by the process tests
    (worker spawn = a jax import each; reuse is the test budget)."""
    root = tmp_path_factory.mktemp("fleet")
    f = Fleet(2, root=str(root), index_rows=ROWS, dim=DIM, k=K,
              seed=SEED, clusters=4, nlist=NLIST,
              service_opts={"delta_cap": 4096})
    try:
        f.wait_ready(timeout=180.0)
        yield f
    finally:
        f.close()


# ---------------------------------------------------------------------- #
# wire protocol (no processes)
# ---------------------------------------------------------------------- #
class TestProtocol:
    def test_error_roundtrip_preserves_type_and_hints(self):
        e = ServiceOverloadError("full", 9, 10, tenant="t0",
                                 retry_after_s=0.25)
        status, payload = protocol.error_response(e)
        assert status == 429
        back = protocol.decode_error(payload)
        assert isinstance(back, ServiceOverloadError)
        assert back.retry_after_s == pytest.approx(0.25)
        assert back.queue_depth == 9 and back.queue_cap == 10

    def test_error_status_taxonomy(self):
        cases = (
            (ServiceUnavailableError("x", "svc", "recovering",
                                     retry_after_s=1.0), 503),
            (CommTimeoutError("late"), 504),
            (ValueError("caller bug"), 409),
            (RuntimeError("surprise"), 500),
        )
        for exc, want in cases:
            status, payload = protocol.error_response(exc)
            assert status == want, exc
            back = protocol.decode_error(payload)
            assert isinstance(back, RaftError)
        # caller bugs decode to LogicError: the router must NOT retry
        # them against other workers
        _, payload = protocol.error_response(ValueError("bad k"))
        assert isinstance(protocol.decode_error(payload), LogicError)

    def test_garbled_body_raises_typed_comm_error(self):
        def garbled(method, url, body, timeout):
            return 200, b"\xff\xfenot json"

        with pytest.raises(CommError):
            protocol.get_json("http://x/info", timeout=1.0,
                              transport=garbled)

    def test_rendezvous_stable_under_roster_growth(self):
        nodes = ["w0", "w1", "w2"]
        keys = [str(i) for i in range(500)]
        before = {k: protocol.rendezvous(k, nodes) for k in keys}
        after = {k: protocol.rendezvous(k, nodes + ["w3"])
                 for k in keys}
        moved = [k for k in keys if before[k] != after[k]]
        # HRW: only keys that now rank the NEW node first move
        assert all(after[k] == "w3" for k in moved)
        assert 0 < len(moved) < len(keys) // 2
        # deterministic and order-independent
        assert protocol.rendezvous_rank("k", ["b", "a"]) == \
            protocol.rendezvous_rank("k", ["a", "b"])
        with pytest.raises(ServiceUnavailableError):
            protocol.rendezvous("k", [])

    def test_merge_topk_orders_pads_and_drops_sentinels(self):
        parts = [
            ([[0.1, 0.4], [1.0, float("inf")]], [[3, 7], [2, -1]]),
            ([[0.2, 0.3], [0.5, 0.6]], [[11, 5], [8, 9]]),
        ]
        dists, ids = protocol.merge_topk(parts, 3)
        assert ids[0] == [3, 11, 5]
        assert dists[0] == pytest.approx([0.1, 0.2, 0.3])
        # -1/inf padding from a shard never surfaces as a result
        assert ids[1] == [8, 9, 2]
        d2, i2 = protocol.merge_topk(parts[:1], 3)
        assert i2[1] == [2, -1, -1]
        assert d2[1][1] == float("inf")


# ---------------------------------------------------------------------- #
# chaos primitives (no processes)
# ---------------------------------------------------------------------- #
class TestChaosPrimitives:
    def test_frame_faults_drop_before_send_and_garble_idempotent(self):
        sent = []

        def base(method, url, body, timeout):
            sent.append(url)
            return 200, b'{"ok": true}'

        ff = FrameFaults(3, base=base)
        # disarmed: transparent
        assert ff("GET", "http://w/search", None, 1.0)[1] == \
            b'{"ok": true}'
        ff.arm(drop_p=1.0, garble_p=0.0, duration_s=60.0)
        with pytest.raises(CommError):
            ff("POST", "http://w/insert", b"{}", 1.0)
        # the drop happened BEFORE the frame went out (duplicate-safe
        # for inserts: the row never reached the worker)
        assert sent == ["http://w/search"]
        ff.arm(drop_p=0.0, garble_p=1.0, duration_s=60.0)
        _, data = ff("POST", "http://w/search", b"{}", 1.0)
        assert data != b'{"ok": true}'
        # insert ACKS are never garbled: losing one would manufacture
        # a false double-insert failure, not test a real one
        _, data = ff("POST", "http://w/insert", b"{}", 1.0)
        assert data == b'{"ok": true}'
        assert ff.injected["drop"] == 1 and ff.injected["garble"] == 1

    def test_chaos_schedule_seed_deterministic(self):
        a = ChaosSchedule.from_seed(11, duration_s=10.0, n_workers=3)
        b = ChaosSchedule.from_seed(11, duration_s=10.0, n_workers=3)
        assert a.events == b.events
        assert a.events  # never an empty schedule
        for ev in a.events:
            assert 0.0 <= ev["at"] <= 10.0
            if ev["kind"] == "kill":
                assert ev["restart_after_s"] > 0.0
        c = ChaosSchedule.from_seed(12, duration_s=10.0, n_workers=3)
        assert c.events != a.events

    def test_relabel_metrics_injects_worker_and_dedups_meta(self):
        text = ("# HELP m demo\n# TYPE m counter\n"
                "m{service=\"a\"} 1\nm_plain 2\n\xff garbled {\n")
        seen = set()
        w0 = _relabel_metrics(text, "w0", seen)
        w1 = _relabel_metrics(text, "w1", seen)
        assert 'm{service="a",worker="w0"} 1' in w0
        assert 'm_plain{worker="w0"} 2' in w0
        assert any(ln.startswith("# HELP") for ln in w0)
        # second worker: HELP/TYPE already emitted once for the scrape
        assert not any(ln.startswith("#") for ln in w1)
        assert not any("garbled" in ln for ln in w0 + w1)


# ---------------------------------------------------------------------- #
# sentinel: fleet rules + per-rung latency watches (fake clock)
# ---------------------------------------------------------------------- #
class _FakeFleet:
    def __init__(self):
        self.stats = {"workers_total": 2, "workers_dead": 0,
                      "last_rejoin": None}

    def fleet_stats(self):
        return dict(self.stats)


class TestSentinelFleetRules:
    def _sentinel(self, services, clock, **knobs):
        with config.override(**{k: str(v) for k, v in knobs.items()}):
            return AnomalySentinel(lambda: services, interval_s=0.0,
                                   clock=clock)

    def test_worker_dead_trips_and_clears(self):
        clock = FakeClock()
        fake = _FakeFleet()
        sent = self._sentinel({"fleet": fake}, clock)
        sent.tick(force=True)
        assert not sent.degraded()
        fake.stats["workers_dead"] = 1
        clock.advance(1.0)
        sent.tick(force=True)
        active = {(a["rule"], a["service"]) for a in sent.active()}
        assert ("worker_dead", "fleet") in active
        fake.stats["workers_dead"] = 0
        clock.advance(1.0)
        sent.tick(force=True)
        assert not sent.degraded()

    def test_rejoin_lag_judged_per_replayed_record(self):
        clock = FakeClock()
        fake = _FakeFleet()
        sent = self._sentinel(
            {"fleet": fake}, clock,
            ops_sentinel_rejoin_ms_per_record=50)
        # 10 ms/record: healthy replay
        fake.stats["last_rejoin"] = {"replayed_records": 100,
                                     "restore_s": 1.0}
        sent.tick(force=True)
        assert not sent.degraded()
        # 200 ms/record: recovery outgrowing the journal
        fake.stats["last_rejoin"] = {"replayed_records": 50,
                                     "restore_s": 10.0, "age_s": 0.4}
        clock.advance(1.0)
        sent.tick(force=True)
        active = {(a["rule"], a["service"]) for a in sent.active()}
        assert ("rejoin_lag", "fleet") in active
        # the slow rejoin is an incident, not a latched state: once it
        # ages past ops_sentinel_rejoin_hold_s the breach clears even
        # though the stats still describe the same slow restore
        fake.stats["last_rejoin"]["age_s"] = 60.0
        clock.advance(1.0)
        sent.tick(force=True)
        assert not sent.degraded()

    def test_per_rung_latency_watch_catches_one_bucket(self):
        name = _name("rung")
        clock = FakeClock()
        sent = self._sentinel({name: object()}, clock,
                              ops_sentinel_min_samples=5,
                              ops_sentinel_latency_factor=3)
        exec_t = default_registry().timer(
            "raft_tpu_serve_exec_seconds",
            labels=("service",)).labels(service=name)
        rung_t = {r: default_registry().timer(
            "raft_tpu_serve_exec_rung_seconds",
            labels=("service", "rung")).labels(service=name, rung=r)
            for r in (8, 64)}
        sent.tick(force=True)
        for _ in range(2):
            for _ in range(5):
                exec_t.observe(0.002)
                rung_t[8].observe(0.001)
                rung_t[64].observe(0.003)
            clock.advance(1.0)
            sent.tick(force=True)
        assert not sent.degraded()
        # a regression confined to the small rung, diluted by healthy
        # big-rung traffic: the mixed service mean stays under its 3x
        # threshold while the rung watch sees a clean 10x
        for _ in range(3):
            exec_t.observe(0.010)
            rung_t[8].observe(0.010)
        for _ in range(9):
            exec_t.observe(0.003)
            rung_t[64].observe(0.003)
        clock.advance(1.0)
        sent.tick(force=True)
        active = {(a["rule"], a["service"]) for a in sent.active()}
        assert ("exec_latency", "%s:r8" % name) in active
        assert ("exec_latency", "%s:r64" % name) not in active
        # this is the satellite's point: the service-level mean alone
        # would have hidden the regression inside the healthy mix
        assert ("exec_latency", name) not in active
        w = sent.status()["watches"]
        assert "exec_latency/%s:r8" % name in w


# ---------------------------------------------------------------------- #
# live fleet: formation, fan-out, inserts, aggregation
# ---------------------------------------------------------------------- #
def _http_json(url, timeout=10.0):
    with urllib.request.urlopen(url, timeout=timeout) as resp:
        return resp.status, json.loads(resp.read().decode("utf-8"))


class TestFleetLive:
    def test_forms_on_ephemeral_ports(self, fleet):
        reg = fleet.router.registry()
        assert sorted(reg) == ["w0", "w1"]
        ports = set()
        for wid, pub in reg.items():
            assert pub["state"] == "active"
            # satellite: workers bind port 0 and report the ACTUAL
            # bound ports through the registration handshake
            assert pub["data_port"] > 0 and pub["ops_port"] > 0
            ports.update((pub["data_port"], pub["ops_port"]))
            status, info = _http_json(
                "http://127.0.0.1:%d/info" % pub["data_port"])
            assert status == 200 and info["worker_id"] == wid
        assert len(ports) == 4

    def test_search_fans_out_and_merges(self, fleet):
        data = _synth(ROWS, DIM, SEED, 4)
        picks = [3, 117, 240, 511]
        out = fleet.router.search([data[i].tolist() for i in picks])
        assert not out["degraded"]
        assert out["shards_total"] == 2
        assert sorted(out["shards_answered"]) == [0, 1]
        for want, row, drow in zip(picks, out["ids"],
                                   out["distances"]):
            assert len(row) == K
            # the exact row is its own nearest neighbor, under its
            # GLOBAL id (shard-local ids translated at the worker)
            assert row[0] == want
            assert drow[0] == pytest.approx(0.0, abs=1e-4)
            assert drow == sorted(drow)

    def test_insert_placed_acked_and_searchable(self, fleet):
        rng = np.random.default_rng(41)
        ids = list(range(50_000, 50_008))
        vecs = rng.standard_normal((8, DIM)).astype(np.float32)
        rep = fleet.router.insert(ids, [v.tolist() for v in vecs])
        assert rep["ok"] and sorted(rep["acked_ids"]) == ids
        assert not rep["errors"]
        for i, v in zip(ids, vecs):
            _INSERTED[i] = v
        out = fleet.router.search([v.tolist() for v in vecs])
        for want, row in zip(ids, out["ids"]):
            assert row[0] == want

    def test_insert_below_base_range_is_callers_bug(self, fleet):
        rep = fleet.router.insert(
            [1], [[0.0] * DIM])  # collides with base-row global ids
        assert not rep["ok"]
        assert rep["errors"]
        assert any(e.get("error") == "LogicError"
                   for e in rep["errors"])

    def test_admission_shed_is_typed_with_retry_hint(self, fleet):
        r = fleet.router
        with r._lock:
            saved, r._inflight = r._inflight, r._inflight_cap
        try:
            with pytest.raises(ServiceOverloadError) as ei:
                r.search([[0.0] * DIM])
            assert ei.value.retry_after_s > 0.0
        finally:
            with r._lock:
                r._inflight = saved

    def test_aggregated_scrape_and_health(self, fleet):
        text = fleet.router.fleet_metrics_text()
        for worker in ('worker="router"', 'worker="w0"',
                       'worker="w1"'):
            assert worker in text
        # one scrape surface: worker families appear once per worker,
        # HELP/TYPE once per family
        assert text.count("# TYPE raft_tpu_serve_requests_total") == 1
        ok, payload = fleet.router.fleet_health()
        assert ok and payload["ok"]
        assert set(payload["workers"]) == {"w0", "w1"}
        # over HTTP, both spellings
        status, body = _http_json(fleet.router.url + "/fleet/healthz")
        assert status == 200 and body["ok"]
        status, body = _http_json(fleet.router.url + "/debug/snapshot")
        assert status == 200
        assert body["fleet"]["mode"] == "sharded"
        assert set(body["fleet"]["workers"]) == {"w0", "w1"}
        assert "p99_search_ms" in body["fleet"]["rollup"]

    def test_metrics_report_renders_fleet_section(self, fleet):
        from tools.metrics_report import render_report

        snap = fleet.router.fleet_snapshot()
        text = render_report(snap)
        assert "== fleet (router aggregate" in text
        assert "w0" in text and "w1" in text
        assert "rollup:" in text and "p99_search" in text

    def test_sentinel_rules_watch_the_router(self, fleet):
        fleet.router.sentinel.tick(force=True)
        watches = fleet.router.sentinel.status()["watches"]
        assert "worker_dead/fleet" in watches


# ---------------------------------------------------------------------- #
# the robustness headline: crash-restart rejoin under live ingestion
# ---------------------------------------------------------------------- #
class TestCrashRejoin:
    def test_kill9_mid_ingestion_zero_acked_loss(self, fleet,
                                                 tmp_path_factory):
        router = fleet.router
        rng = np.random.default_rng(17)
        acked = {}
        attempted = {}
        lock = threading.Lock()
        stop = threading.Event()

        def inserter():
            base = 100_000
            n = 0
            while not stop.is_set():
                ids = list(range(base + n, base + n + 4))
                vecs = rng.standard_normal((4, DIM)).astype(
                    np.float32)
                with lock:
                    for j, i in enumerate(ids):
                        attempted[i] = vecs[j]
                try:
                    rep = router.insert(ids,
                                        [v.tolist() for v in vecs],
                                        timeout_s=6.0)
                except RaftError:
                    time.sleep(0.02)
                    continue
                ok_ids = set(rep["acked_ids"])
                with lock:
                    for j, i in enumerate(ids):
                        if i in ok_ids:
                            acked[i] = vecs[j]
                n += 4
                time.sleep(0.01)

        t = threading.Thread(target=inserter, daemon=True)
        t.start()
        time.sleep(1.0)          # WAL-appends in flight...
        fleet.kill("w1")         # ...SIGKILL: no goodbye, no snapshot
        # degraded, not fail-closed: the survivor keeps answering
        # (flagged) and health says ok+degraded during the outage
        deadline = time.monotonic() + 20.0
        saw_degraded_answer = saw_degraded_health = False
        data = _synth(ROWS, DIM, SEED, 4)
        while time.monotonic() < deadline and not (
                saw_degraded_answer and saw_degraded_health):
            ok, payload = router.fleet_health()
            if ok and payload["degraded"]:
                saw_degraded_health = True
            try:
                out = router.search([data[3].tolist()],
                                    timeout_s=3.0)
                if out["degraded"]:
                    saw_degraded_answer = True
            except RaftError:
                pass
            time.sleep(0.1)
        assert saw_degraded_health and saw_degraded_answer
        time.sleep(0.5)          # keep ingesting against the survivor
        gen_before = router.registry()["w1"]["generation"]
        fleet.restart("w1")
        # wait for the REJOIN, not merely an active state: the restart
        # can land before the lease eviction, during which w1 still
        # reads "active" under its old (stale) registration
        deadline = time.monotonic() + 120.0
        while time.monotonic() < deadline:
            pub = router.registry()["w1"]
            if (pub["state"] == "active"
                    and pub["generation"] > gen_before):
                break
            time.sleep(0.1)
        assert router.registry()["w1"]["generation"] > gen_before
        assert router.active_workers() == ["w0", "w1"]
        stop.set()
        t.join(timeout=30.0)
        assert acked, "scenario needs acked inserts to mean anything"

        # rejoin was typed and flight-recorded, restore came from the
        # persist dir (snapshot + WAL replay)
        rejoins = flight.default_recorder().events(kind="fleet_rejoin")
        assert rejoins and rejoins[-1].attrs["worker"] == "w1"
        restore = router.registry()["w1"]["restore"]
        assert restore.get("restored") is True
        # health heals once the sentinel observes the rejoin
        deadline = time.monotonic() + 15.0
        while time.monotonic() < deadline:
            ok, payload = router.fleet_health()
            if ok and not payload["degraded"]:
                break
            time.sleep(0.2)
        assert ok and not payload["degraded"]

        # ZERO acked-row loss: every acknowledged id answers from the
        # healed fleet under its exact vector.  (Attempted-but-unacked
        # rows MAY exist — the ack raced the kill — so presence is
        # checked over the attempted set and acked must be a subset.
        # Rows earlier tests landed in this shared fleet are part of
        # the delta too: the control fleet must hold them as well.)
        present = {}
        items = sorted({**attempted, **_INSERTED}.items())
        for off in range(0, len(items), 32):
            chunk = items[off:off + 32]
            out = router.search([v.tolist() for _, v in chunk],
                                timeout_s=15.0)
            assert not out["degraded"]
            for (i, v), row in zip(chunk, out["ids"]):
                if row[0] == i:
                    present[i] = v
        lost = sorted(set(acked) - set(present))
        assert not lost, "acked rows lost across kill -9: %r" % lost

        # exactly-one terminal flight event per admitted request
        rec = flight.default_recorder()
        admitted = [e.attrs["rid"]
                    for e in rec.events(kind="fleet_admitted")]
        terminals = {}
        for kind in ("fleet_resolved", "fleet_failed",
                     "fleet_expired"):
            for e in rec.events(kind=kind):
                rid = e.attrs["rid"]
                terminals[rid] = terminals.get(rid, 0) + 1
        assert admitted
        for rid in admitted:
            assert terminals.get(rid, 0) == 1, rid

        # byte-identical vs an unkilled CONTROL fleet holding the same
        # rows: same base build (same seed), same present set
        root = tmp_path_factory.mktemp("control")
        control = Fleet(2, root=str(root), index_rows=ROWS, dim=DIM,
                        k=K, seed=SEED, clusters=4, nlist=NLIST,
                        service_opts={"delta_cap": 4096})
        try:
            control.wait_ready(timeout=180.0)
            citems = sorted(present.items())
            for off in range(0, len(citems), 32):
                chunk = citems[off:off + 32]
                rep = control.router.insert(
                    [i for i, _ in chunk],
                    [v.tolist() for _, v in chunk], timeout_s=15.0)
                assert rep["ok"]
            queries = ([data[i].tolist() for i in (3, 117, 240)]
                       + [v.tolist()
                          for _, v in citems[:8]])
            got = router.search(queries, timeout_s=15.0)
            want = control.router.search(queries, timeout_s=15.0)
            assert not got["degraded"] and not want["degraded"]
            assert got["ids"] == want["ids"]
            assert got["distances"] == want["distances"]
        finally:
            control.close()


class TestDrainChoreography:
    def test_drain_restart_preserves_rows_and_rejoins(self, fleet):
        router = fleet.router
        rng = np.random.default_rng(53)
        ids = list(range(200_000, 200_006))
        vecs = rng.standard_normal((6, DIM)).astype(np.float32)
        rep = router.insert(ids, [v.tolist() for v in vecs])
        assert rep["ok"]
        gen0 = router.registry()["w0"]["generation"]
        fleet.drain_restart("w0", timeout=120.0)
        assert router.active_workers() == ["w0", "w1"]
        assert router.registry()["w0"]["generation"] == gen0 + 1
        drains = flight.default_recorder().events(kind="fleet_drain")
        assert any(e.attrs["worker"] == "w0" for e in drains)
        # quiesce → snapshot → handoff: nothing durable was lost
        out = router.search([v.tolist() for v in vecs],
                            timeout_s=15.0)
        for want, row in zip(ids, out["ids"]):
            assert row[0] == want


# ---------------------------------------------------------------------- #
# replicated fleet: rendezvous placement + hedged re-dispatch
# ---------------------------------------------------------------------- #
class TestReplicatedHedge:
    @pytest.fixture(scope="class")
    def repl(self, tmp_path_factory):
        root = tmp_path_factory.mktemp("repl")
        router = Router(mode="replicated", shard_count=1,
                        hedge_ms=60.0, timeout_s=10.0)
        f = Fleet(2, root=str(root), index_rows=300, dim=DIM, k=3,
                  mode="replicated", seed=3, clusters=0, nlist=8,
                  router=router)
        try:
            f.wait_ready(timeout=180.0)
            yield f
        finally:
            f.close()

    def test_replicated_is_query_only(self, repl):
        with pytest.raises(LogicError):
            repl.router.insert([400], [[0.0] * DIM])

    def test_hedge_fires_when_primary_straggles(self, repl):
        router = repl.router
        data = _synth(300, DIM, 3, 0)
        tenant = "hedget"
        primary = protocol.rendezvous_rank(
            tenant, router.active_workers())[0]
        port = router.registry()[primary]["data_port"]

        def _total(name):
            snap = default_registry().snapshot().get(name, {})
            return sum(int(s["value"])
                       for s in snap.get("series", []))

        hedges0 = _total("raft_tpu_fleet_hedges_total")
        # hang the primary for less than the lease timeout: only the
        # hedge can save the request's latency
        protocol.post_json("http://127.0.0.1:%d/chaos" % port,
                           {"fault": "hang", "duration_s": 1.0},
                           timeout=5.0)
        out = router.search([data[5].tolist()], tenant=tenant,
                            timeout_s=8.0)
        assert out["ids"][0][0] == 5
        assert out["hedged"]
        assert _total("raft_tpu_fleet_hedges_total") == hedges0 + 1
        # let the hang expire so teardown sees a healthy fleet
        time.sleep(1.2)

    def test_hedged_request_joins_with_one_terminal(self, repl):
        """Exactly-one-terminal across the process boundary on the
        HEDGED path: the joined trace for a hedged request has one
        router terminal, a ``fleet_hedge`` span, and validates clean
        (the loser's late events cannot manufacture a second
        terminal)."""
        router = repl.router
        data = _synth(300, DIM, 3, 0)
        tenant = "hedgej"
        primary = protocol.rendezvous_rank(
            tenant, router.active_workers())[0]
        port = router.registry()[primary]["data_port"]
        protocol.post_json("http://127.0.0.1:%d/chaos" % port,
                           {"fault": "hang", "duration_s": 1.0},
                           timeout=5.0)
        rid = "flt-hedge-join"
        out = router.search([data[5].tolist()], tenant=tenant,
                            timeout_s=8.0, request_id=rid)
        assert out["hedged"]
        time.sleep(1.3)  # hang expires; loser's tail events settle
        status, joined = router.fleet_trace(rid)
        assert status == 200
        router_kinds = [e["kind"] for e in joined["spans"]
                        if e["proc"] == "router"]
        assert router_kinds.count("fleet_resolved") == 1
        assert "fleet_hedge" in router_kinds
        assert sum(1 for k in router_kinds
                   if k in ("fleet_failed", "fleet_expired")) == 0
        from raft_tpu.fleet import tracing
        assert not [p for p in tracing.validate(joined)
                    if "terminal" in p]


# ---------------------------------------------------------------------- #
# fleet tracing: context carrier + local index (no processes)
# ---------------------------------------------------------------------- #
class TestTraceCarrier:
    def test_trace_frame_parse_roundtrip(self):
        ctx = protocol.trace_frame("flt-00000007", "router", 12.5)
        parsed = protocol.parse_trace(ctx)
        assert parsed == {"id": "flt-00000007", "parent": "router",
                          "sent_at": 12.5}
        # bare-string legacy form still carries the id
        assert protocol.parse_trace("flt-9")["id"] == "flt-9"
        for junk in (None, 7, [], {}, {"parent": "x"}):
            assert protocol.parse_trace(junk) is None

    def test_post_json_mirrors_trace_header(self):
        seen = {}

        def transport(method, url, body, timeout, headers=None):
            seen["headers"] = headers
            return 200, b'{"ok": true}'

        ctx = protocol.trace_frame("flt-1", "router", 1.0)
        protocol.post_json("http://w/search", {"q": []}, timeout=1.0,
                           transport=transport, trace=ctx)
        hdr = seen["headers"][protocol.TRACE_HEADER]
        assert json.loads(hdr) == ctx

    def test_post_json_falls_back_for_legacy_transports(self):
        """An injected 4-arg transport (every pre-tracing test double,
        and FrameFaults before this PR) must keep working when a trace
        is attached — the body is the authoritative carrier."""
        calls = []

        def legacy(method, url, body, timeout):
            calls.append((method, url))
            return 200, b'{"ok": true}'

        rep = protocol.post_json(
            "http://w/search", {"q": []}, timeout=1.0,
            transport=legacy,
            trace=protocol.trace_frame("flt-2", "router", 0.0))
        assert rep == {"ok": True} and calls

    def test_frame_faults_forward_headers(self):
        got = {}

        def base(method, url, body, timeout, headers=None):
            got["headers"] = headers
            return 200, b'{"ok": true}'

        ff = FrameFaults(5, base=base)
        ff("POST", "http://w/search", b"{}", 1.0,
           headers={"X": "y"})
        assert got["headers"] == {"X": "y"}


class TestFleetTraceIndex:
    def test_trace_context_binds_and_tags_ring_events(self):
        rec = flight.default_recorder()
        ctx = protocol.parse_trace(
            protocol.trace_frame("flt-ctx-1", "router", 3.0))
        with flight.trace_context(ctx):
            tr = rec.new_trace("annx", "t0")
        assert tr.fleet["id"] == "flt-ctx-1"
        assert flight.current_trace_context() is None
        rec.record("admitted", service="annx", trace=tr)
        rec.record("batch_formed", service="annx", traces=[tr],
                   rung=8)
        rec.record("resolved", service="annx", trace=tr)
        ring = [e.to_dict() for e in rec.events(service="annx")]
        assert all(e.get("fleet") in ("flt-ctx-1", ["flt-ctx-1"])
                   for e in ring), ring
        # the per-fleet-id index holds the trace
        assert [t.trace_id for t in
                flight.fleet_traces("flt-ctx-1")] == [tr.trace_id]
        # to_dict round-trips the fleet slot
        assert tr.to_dict()["fleet"]["parent"] == "router"

    def test_no_context_means_no_tagging(self):
        rec = flight.default_recorder()
        tr = rec.new_trace("annx", "t0")
        assert tr.fleet is None
        rec.record("admitted", service="annx", trace=tr)
        ev = [e.to_dict() for e in rec.events(service="annx")][-1]
        assert "fleet" not in ev

    def test_index_survives_ring_wrap(self):
        """The fleet view reconstructs after the global ring wrapped:
        indexed traces keep their private event lists, so
        ``local_payload`` still has the full timeline."""
        from raft_tpu.core.flight import FlightRecorder
        rec = FlightRecorder(capacity=16)
        with flight.trace_context({"id": "flt-wrap", "parent":
                                   "router", "sent_at": 0.0}):
            tr = rec.new_trace("svc", None)
        rec.record("admitted", service="svc", trace=tr)
        rec.record("resolved", service="svc", trace=tr)
        for i in range(64):  # wrap the 16-slot ring with noise
            rec.record("compaction", service="other", i=i)
        assert not rec.events(service="svc")  # ring lost it
        traces = rec.fleet_traces("flt-wrap")
        assert len(traces) == 1
        kinds = [e["kind"] for e in traces[0].timeline()]
        assert kinds == ["admitted", "resolved"]

    def test_index_bounds_ids_fifo_and_traces_per_id(self):
        from raft_tpu.core.flight import (FLEET_TRACE_KEEP,
                                          FLEET_TRACES_PER_ID,
                                          FlightRecorder)
        rec = FlightRecorder(capacity=64)
        for i in range(FLEET_TRACE_KEEP + 3):
            with flight.trace_context({"id": "flt-%d" % i,
                                       "parent": "router",
                                       "sent_at": 0.0}):
                rec.new_trace("svc", None)
        ids = rec.fleet_trace_ids()
        assert len(ids) == FLEET_TRACE_KEEP
        assert "flt-0" not in ids and "flt-2" not in ids  # FIFO out
        assert "flt-%d" % (FLEET_TRACE_KEEP + 2) in ids
        # per-id cap: a retry storm cannot grow one id unboundedly
        for _ in range(FLEET_TRACES_PER_ID + 5):
            with flight.trace_context({"id": "flt-burst",
                                       "parent": "router",
                                       "sent_at": 0.0}):
                rec.new_trace("svc", None)
        assert len(rec.fleet_traces("flt-burst")) == \
            FLEET_TRACES_PER_ID


# ---------------------------------------------------------------------- #
# fleet tracing: clock-aligned join + validation (synthetic events)
# ---------------------------------------------------------------------- #
def _router_events(rid, t0=100.0, worker="w0", server_s=0.008,
                   terminal="fleet_resolved"):
    return [
        {"ts": t0, "kind": "fleet_admitted", "service": "fleet",
         "rid": rid},
        {"ts": t0 + 0.001, "kind": "fleet_rpc_send",
         "service": "fleet", "rid": rid, "worker": worker,
         "attempt": 0},
        {"ts": t0 + 0.012, "kind": "fleet_rpc_recv",
         "service": "fleet", "rid": rid, "worker": worker,
         "attempt": 0, "elapsed_s": 0.011, "server_s": server_s,
         "network_s": 0.011 - server_s},
        {"ts": t0 + 0.013, "kind": terminal, "service": "fleet",
         "rid": rid},
    ]


def _worker_payload(rid, wid, clock_t0, server_s=0.008):
    """A worker-half payload whose events sit on the WORKER clock."""
    events = [
        {"ts": clock_t0, "kind": "admitted", "service": "ann",
         "trace_id": 1},
        {"ts": clock_t0 + server_s * 0.5, "kind": "batch_formed",
         "service": "ann", "traces": [1]},
        {"ts": clock_t0 + server_s, "kind": "resolved",
         "service": "ann", "trace_id": 1},
    ]
    return {"fleet": rid, "worker_id": wid, "generation": 1,
            "now": clock_t0 + 1.0,
            "traces": [{"trace_id": 1, "service": "ann",
                        "tenant": None, "events": events}]}


class TestTracingJoin:
    def test_aligned_join_is_monotonic_and_gapless(self):
        from raft_tpu.fleet import tracing
        rid = "flt-j1"
        # worker clock runs 50 s behind the router; its span sits
        # inside the rpc bracket once shifted by +50
        payload = _worker_payload(rid, "w0", clock_t0=50.003)
        joined = tracing.join(
            rid, _router_events(rid),
            {"w0": {"offset_s": 50.0, "rtt_s": 0.002,
                    "payload": payload}})
        assert joined["terminal"] == "fleet_resolved"
        assert tracing.validate(joined) == []
        ts = [e["ts"] for e in joined["spans"]]
        assert ts == sorted(ts)
        procs = {e["proc"] for e in joined["spans"]}
        assert procs == {"router", "w0"}
        hop = joined["hops"]["w0"]
        assert hop["attempts"] == 1
        assert hop["network_s"] == pytest.approx(0.003)
        # the hop tiling is gapless: consecutive boundaries shared
        segs = tracing.hop_segments(joined)
        names = [s["name"] for s in segs]
        assert names == ["dispatch", "network_out", "worker",
                         "network_back", "merge_relay"]
        for a, b in zip(segs, segs[1:]):
            assert b["t0"] == pytest.approx(a["t1"])

    def test_misaligned_clock_is_flagged(self):
        from raft_tpu.fleet import tracing
        rid = "flt-j2"
        payload = _worker_payload(rid, "w0", clock_t0=50.003)
        # offset off by 80 ms >> tol (5 ms + rtt/2): the worker span
        # lands outside its rpc bracket and validate says so
        joined = tracing.join(
            rid, _router_events(rid),
            {"w0": {"offset_s": 50.08, "rtt_s": 0.002,
                    "payload": payload}})
        probs = tracing.validate(joined)
        assert any("clock alignment gap" in p for p in probs)

    def test_double_terminal_is_flagged(self):
        from raft_tpu.fleet import tracing
        rid = "flt-j3"
        evs = _router_events(rid)
        evs.append({"ts": evs[-1]["ts"] + 0.001,
                    "kind": "fleet_resolved", "service": "fleet",
                    "rid": rid})
        joined = tracing.join(rid, evs, {})
        assert any("terminal" in p for p in tracing.validate(joined))
        # and a worker-side duplicate terminal is caught per trace
        payload = _worker_payload(rid, "w0", clock_t0=100.003)
        payload["traces"][0]["events"].append(
            {"ts": 100.02, "kind": "resolved", "service": "ann",
             "trace_id": 1})
        joined = tracing.join(
            rid, _router_events(rid),
            {"w0": {"offset_s": 0.0, "rtt_s": 0.002,
                    "payload": payload}})
        assert any("2 terminals" in p for p in tracing.validate(joined))

    def test_partial_join_without_worker_payload(self):
        from raft_tpu.fleet import tracing
        rid = "flt-j4"
        joined = tracing.join(
            rid, _router_events(rid),
            {"w0": {"offset_s": 0.0, "rtt_s": 0.0, "payload": None}})
        assert joined["hops"]["w0"]["attempts"] == 1
        assert joined["align"]["w0"]["traces"] == 0
        # no worker events: nesting checks are vacuous, terminal holds
        assert tracing.validate(joined) == []


# ---------------------------------------------------------------------- #
# sentinel cross-hop rule: per-worker network baselines
# ---------------------------------------------------------------------- #
class TestSentinelFleetNetwork:
    def test_one_degraded_link_trips_its_own_watch(self):
        wa, wb = _name("netw"), _name("netw")
        clock = FakeClock()
        with config.override(ops_sentinel_min_samples="5",
                             ops_sentinel_latency_factor="3"):
            sent = AnomalySentinel(
                lambda: {"fleet": _FakeFleet()}, interval_s=0.0,
                clock=clock)
        timers = {w: default_registry().timer(
            "raft_tpu_fleet_network_seconds",
            labels=("worker",)).labels(worker=w) for w in (wa, wb)}
        sent.tick(force=True)
        for _ in range(2):
            for _ in range(5):
                timers[wa].observe(0.002)
                timers[wb].observe(0.002)
            clock.advance(1.0)
            sent.tick(force=True)
        watches = sent.status()["watches"]
        assert not watches["fleet_network/fleet:%s" % wa]["active"]
        # one link degrades 10x; the other stays healthy
        for _ in range(6):
            timers[wa].observe(0.020)
            timers[wb].observe(0.002)
        clock.advance(1.0)
        sent.tick(force=True)
        active = {(a["rule"], a["service"]) for a in sent.active()}
        assert ("fleet_network", "fleet:%s" % wa) in active
        assert ("fleet_network", "fleet:%s" % wb) not in active


# ---------------------------------------------------------------------- #
# prometheus worker-label escaping (regression: hostile worker names)
# ---------------------------------------------------------------------- #
class TestWorkerLabelEscaping:
    def test_hostile_worker_name_roundtrips(self):
        from raft_tpu.core.metrics import parse_prometheus
        hostile = 'w"0\\evil\nname'
        text = ("# HELP m demo\n# TYPE m counter\n"
                'm{service="a"} 1\nm_plain 2\n')
        out = _relabel_metrics(text, hostile, set())
        joined = "\n".join(out) + "\n"
        # every emitted line is still one line (the newline in the
        # name must have been escaped, not emitted)
        assert all("\n" not in ln for ln in out)
        parsed = parse_prometheus(joined)
        assert parsed["m"], joined
        for labels in parsed["m"]:
            assert dict(labels)["worker"] == hostile
        for labels in parsed["m_plain"]:
            assert dict(labels)["worker"] == hostile


# ---------------------------------------------------------------------- #
# live fleet: cross-process joined waterfall
# ---------------------------------------------------------------------- #
class TestFleetTracingLive:
    def test_joined_waterfall_monotonic_and_gapless(self, fleet):
        """The acceptance criterion: a live request's joined trace at
        ``/fleet/debug/trace/<id>`` is monotonic and gapless after
        clock alignment, with exactly one terminal per request."""
        from raft_tpu.fleet import tracing
        data = _synth(ROWS, DIM, SEED, 4)
        rid = "flt-live-join-1"
        out = fleet.router.search(
            [data[3].tolist(), data[7].tolist()], request_id=rid)
        assert not out["degraded"]
        status, joined = fleet.router.fleet_trace(rid)
        assert status == 200
        assert joined["terminal"] == "fleet_resolved"
        assert not joined["partial"]
        assert joined["problems"] == []
        # both shards contributed, each with worker-side spans tagged
        # by the propagated context
        assert set(joined["hops"]) == {"w0", "w1"}
        procs = {e["proc"] for e in joined["spans"]}
        assert procs == {"router", "w0", "w1"}
        for wid in ("w0", "w1"):
            kinds = [e["kind"] for e in joined["spans"]
                     if e["proc"] == wid]
            assert "admitted" in kinds and "resolved" in kinds
            assert kinds.count("resolved") == 1
        # per-process monotonic (validate already asserts; belt and
        # braces on the acceptance wording)
        for proc in procs:
            ts = [e["ts"] for e in joined["spans"]
                  if e["proc"] == proc]
            assert ts == sorted(ts)
        # hop tiling covers dispatch through merge with shared
        # boundaries per worker chain
        segs = tracing.hop_segments(joined)
        assert {s["name"] for s in segs} == {
            "dispatch", "network_out", "worker", "network_back",
            "merge_relay"}
        # the HTTP spelling returns the same join
        status, body = _http_json(
            fleet.router.url + "/fleet/debug/trace/" + rid)
        assert status == 200 and body["fleet"] == rid
        assert body["terminal"] == "fleet_resolved"
        # renderers accept the live payload
        from tools.trace_report import (fleet_to_chrome_trace,
                                        render_fleet_waterfall)
        text = render_fleet_waterfall(joined)
        assert rid in text and "network_out" in text
        chrome = fleet_to_chrome_trace(joined)
        assert any(e["ph"] == "X" and e["name"] == "fleet request"
                   for e in chrome)

    def test_unknown_id_is_404_not_500(self, fleet):
        status, payload = fleet.router.fleet_trace("flt-nope")
        assert status == 404
        assert "unknown fleet trace" in payload["message"]

    def test_clock_offsets_published_and_sane(self, fleet):
        # heartbeats have been flowing since fleet start: both
        # workers must have an offset estimate and a sub-second rtt
        deadline = time.monotonic() + 15.0
        while time.monotonic() < deadline:
            reg = fleet.router.registry()
            if all(r.get("clock_rtt_s", 0.0) > 0.0
                   for r in reg.values()):
                break
            time.sleep(0.2)
        reg = fleet.router.registry()
        for wid, pub in reg.items():
            assert pub["clock_rtt_s"] > 0.0, (wid, pub)
            assert pub["clock_rtt_s"] < 1.0
            # loopback offsets are small (same physical clock), but
            # the assertion is on the estimator's bound, not zero
            assert abs(pub["clock_offset_s"]) < 5.0

    def test_exactly_one_terminal_across_drain(self, fleet):
        """Exactly-one-terminal per fleet request while a worker
        drains and rejoins mid-traffic (the drain choreography hands
        requests off; none may double-terminate or vanish)."""
        router = fleet.router
        data = _synth(ROWS, DIM, SEED, 4)
        rids, stop = [], threading.Event()
        errs = []

        def client():
            i = 0
            while not stop.is_set():
                rid = "flt-drain-%d" % i
                i += 1
                try:
                    router.search([data[i % ROWS].tolist()],
                                  timeout_s=8.0, request_id=rid)
                except RaftError:
                    pass
                except Exception as e:  # noqa: BLE001 — untyped = bug
                    errs.append(e)
                rids.append(rid)
                time.sleep(0.01)

        t = threading.Thread(target=client, daemon=True)
        t.start()
        time.sleep(0.3)
        fleet.drain_restart("w1", timeout=120.0)
        time.sleep(0.3)
        stop.set()
        t.join(timeout=30.0)
        assert not errs
        assert router.active_workers() == ["w0", "w1"]
        rec = flight.default_recorder()
        terminals = {}
        for kind in ("fleet_resolved", "fleet_failed",
                     "fleet_expired"):
            for e in rec.events(kind=kind):
                rid = e.attrs.get("rid")
                if rid is not None and rid.startswith("flt-drain-"):
                    terminals[rid] = terminals.get(rid, 0) + 1
        admitted = [e.attrs["rid"]
                    for e in rec.events(kind="fleet_admitted")
                    if e.attrs.get("rid", "").startswith("flt-drain-")]
        assert admitted
        for rid in admitted:
            assert terminals.get(rid, 0) == 1, rid
