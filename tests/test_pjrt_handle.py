"""C++ PJRT handle: build, load, and probe (SURVEY §7 step 1).

The reference's C++-consumable surface is ``raft::handle_t``
(handle.hpp:49); ours is ``raft_tpu::pjrt::Handle`` over the PJRT C API.
These tests prove the C++ path end-to-end where a plugin exists: dlopen,
GetPjrtApi, version negotiation, and error plumbing.  Client creation
(device bring-up) is env-gated — it would contend for the real
accelerator in CI.
"""

import json
import os
import subprocess
import sys

import pytest

from raft_tpu.core.pjrt import (
    default_plugin_path,
    pjrt_native_available,
    probe_api_version,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(scope="module", autouse=True)
def _require_toolchain():
    if not pjrt_native_available():
        pytest.skip("no C++ toolchain / PJRT library build failed")


def test_probe_bad_path_raises_with_dlopen_message():
    with pytest.raises(RuntimeError, match="dlopen failed"):
        probe_api_version("/nonexistent-plugin.so")


def test_probe_non_plugin_so_raises_no_symbol():
    # a real .so that is not a PJRT plugin: symbol resolution must fail
    # loudly, not crash
    import numpy as np

    core = os.path.join(os.path.dirname(np.__file__), "_core")
    cands = [os.path.join(core, f) for f in os.listdir(core)
             if f.endswith(".so")]
    if not cands:
        pytest.skip("no non-plugin .so available")
    with pytest.raises(RuntimeError, match="GetPjrtApi"):
        probe_api_version(cands[0])


def test_probe_real_plugin_reports_api_version():
    path = default_plugin_path()
    if path is None or not os.path.exists(path):
        pytest.skip("no PJRT plugin installed")
    # Probe in a killable child: a REAL plugin's Plugin_Initialize can
    # hang inside vendor init on a host with no matching accelerator
    # (observed: libtpu.so blocking forever — holding
    # /tmp/libtpu_lockfile — in a TPU-less container), and a native
    # call can't be interrupted in-process.  A hang must skip this
    # test, not stall the whole suite until the CI timeout.
    code = ("import json\n"
            "from raft_tpu.core.pjrt import probe_api_version\n"
            "print('PROBE ' + json.dumps(probe_api_version(%r)))\n"
            % path)
    try:
        proc = subprocess.run(
            [sys.executable, "-c", code], capture_output=True,
            text=True, timeout=60, cwd=REPO)
    except subprocess.TimeoutExpired:
        pytest.skip("PJRT plugin probe hung in vendor init "
                    "(no matching accelerator attached?)")
    if proc.returncode != 0:
        # same failure semantics as the in-process call
        raise RuntimeError(
            "probe failed: %s" % proc.stderr.strip()[-500:])
    line = [ln for ln in proc.stdout.splitlines()
            if ln.startswith("PROBE ")][-1]
    info = json.loads(line[len("PROBE "):])
    major, minor = info["api_version"]
    assert major == 0 and minor >= 40, info


def test_client_info_env_gated():
    if os.environ.get("RAFT_TPU_PJRT_CREATE_CLIENT") != "1":
        pytest.skip("device bring-up gated behind RAFT_TPU_PJRT_CREATE_CLIENT=1")
    from raft_tpu.core.pjrt import client_info

    info = client_info()
    assert info["devices"], info
