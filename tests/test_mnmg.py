"""MNMG brute-force kNN over the virtual 8-device mesh.

Reference: baseline config #5 — multi-node brute-force kNN via comms
(comms/comms.hpp:193 + spatial/knn/knn.hpp:55), tested the way the
reference tests comms-driven code: on a real (here: virtual) cluster.
"""

import numpy as np
import pytest
import jax
import jax.numpy as jnp
from jax.sharding import Mesh

from raft_tpu import Handle
from raft_tpu.comms.host_comms import HostComms, default_mesh
from raft_tpu.distance.distance_type import DistanceType as D
from raft_tpu.spatial import brute_force_knn, mnmg_knn


@pytest.fixture
def data(rng):
    index = rng.standard_normal((403, 24)).astype(np.float32)  # not % 8
    queries = rng.standard_normal((56, 24)).astype(np.float32)
    return jnp.asarray(index), jnp.asarray(queries)


def test_mnmg_matches_single_device(data):
    index, queries = data
    d_ref, i_ref = brute_force_knn([index], queries, 10)
    d_got, i_got = mnmg_knn(index, queries, 10)
    np.testing.assert_allclose(np.asarray(d_got), np.asarray(d_ref),
                               rtol=1e-4, atol=1e-4)
    np.testing.assert_array_equal(np.asarray(i_got), np.asarray(i_ref))


def test_mnmg_via_injected_handle_comms(data):
    """The reference idiom: primitives fetch comms from the handle
    (handle.get_comms(), handle.hpp:229)."""
    index, queries = data
    h = Handle()
    h.set_comms(HostComms(default_mesh()))
    d_got, i_got = mnmg_knn(index, queries, 7, handle=h)
    d_ref, i_ref = brute_force_knn([index], queries, 7)
    np.testing.assert_array_equal(np.asarray(i_got), np.asarray(i_ref))


def test_mnmg_2d_mesh_query_sharded(data):
    """2-D mesh: index over 'dp', queries over 'mp' (subcomm pattern,
    handle.hpp:237)."""
    index, queries = data
    devs = np.array(jax.devices()[:8]).reshape(2, 4)
    mesh = Mesh(devs, ("mp", "dp"))
    d_got, i_got = mnmg_knn(index, queries, 5, mesh=mesh, axis="dp",
                            query_axis="mp")
    d_ref, i_ref = brute_force_knn([index], queries, 5)
    np.testing.assert_allclose(np.asarray(d_got), np.asarray(d_ref),
                               rtol=1e-4, atol=1e-4)
    np.testing.assert_array_equal(np.asarray(i_got), np.asarray(i_ref))


@pytest.mark.parametrize("metric", [D.L2SqrtExpanded, D.InnerProduct])
def test_mnmg_metric_dispatch(data, metric):
    index, queries = data
    d_got, i_got = mnmg_knn(index, queries, 6, metric=metric)
    d_ref, i_ref = brute_force_knn([index], queries, 6, metric=metric)
    np.testing.assert_allclose(np.asarray(d_got), np.asarray(d_ref),
                               rtol=1e-4, atol=1e-4)
    np.testing.assert_array_equal(np.asarray(i_got), np.asarray(i_ref))


def test_mnmg_k_exceeds_shard_rows(rng):
    """k larger than a shard's row count: every shard contributes all its
    rows and the merge still finds the global top-k."""
    index = jnp.asarray(rng.standard_normal((40, 8)).astype(np.float32))
    queries = jnp.asarray(rng.standard_normal((12, 8)).astype(np.float32))
    d_got, i_got = mnmg_knn(index, queries, 9)  # shards hold 5 rows each
    d_ref, i_ref = brute_force_knn([index], queries, 9)
    np.testing.assert_array_equal(np.asarray(i_got), np.asarray(i_ref))


def test_mnmg_ring_merge_matches_allgather(data):
    """merge='ring' (ppermute running top-k) == merge='allgather' ==
    single device, at a ragged shard size."""
    index, queries = data
    d_ref, i_ref = brute_force_knn([index], queries, 10)
    d_ring, i_ring = mnmg_knn(index, queries, 10, merge="ring")
    np.testing.assert_allclose(np.asarray(d_ring), np.asarray(d_ref),
                               rtol=1e-4, atol=1e-4)
    np.testing.assert_array_equal(np.asarray(i_ring), np.asarray(i_ref))


def test_mnmg_ring_k_exceeds_shard_rows(rng):
    """Ring merge when k > rows-per-shard (running block narrower than
    k must pad, not truncate)."""
    index = jnp.asarray(rng.standard_normal((19, 8)).astype(np.float32))
    queries = jnp.asarray(rng.standard_normal((7, 8)).astype(np.float32))
    d_ref, i_ref = brute_force_knn([index], queries, 5)
    d_ring, i_ring = mnmg_knn(index, queries, 5, merge="ring")
    np.testing.assert_allclose(np.asarray(d_ring), np.asarray(d_ref),
                               rtol=1e-4, atol=1e-4)
    np.testing.assert_array_equal(np.asarray(i_ring), np.asarray(i_ref))


def test_mnmg_ring_2d_mesh(data):
    """Ring merge composes with query sharding on a 2-D mesh."""
    index, queries = data
    devs = np.array(jax.devices()[:8]).reshape(2, 4)
    mesh = Mesh(devs, ("qx", "ix"))
    d_ref, i_ref = brute_force_knn([index], queries, 10)
    d_got, i_got = mnmg_knn(index, queries, 10, mesh=mesh, axis="ix",
                            query_axis="qx", merge="ring")
    np.testing.assert_allclose(np.asarray(d_got), np.asarray(d_ref),
                               rtol=1e-4, atol=1e-4)
    np.testing.assert_array_equal(np.asarray(i_got), np.asarray(i_ref))


# ---------------------------------------------------------------------- #
# hierarchical merge (intra-group allgather + inter-group ring; the
# HiCCL decomposition applied to top-k candidates)
# ---------------------------------------------------------------------- #
@pytest.mark.parametrize("group_size", [1, 2, 4, 8, None])
def test_mnmg_hierarchical_merge(data, group_size):
    """Hierarchical merge == single device at every legal group size
    (1 = pure ring, 8 = pure intra-group allgather, None = auto)."""
    index, queries = data
    d_ref, i_ref = brute_force_knn([index], queries, 10)
    d_got, i_got = mnmg_knn(index, queries, 10, merge="hierarchical",
                            group_size=group_size)
    np.testing.assert_allclose(np.asarray(d_got), np.asarray(d_ref),
                               rtol=1e-4, atol=1e-4)
    np.testing.assert_array_equal(np.asarray(i_got), np.asarray(i_ref))


def test_mnmg_hierarchical_bad_group_size(data):
    from raft_tpu.core.error import RaftError

    index, queries = data
    with pytest.raises(RaftError):
        mnmg_knn(index, queries, 5, merge="hierarchical", group_size=3)


def test_mnmg_merge_knob_resolution(data):
    """merge=None resolves the mnmg_merge config knob."""
    import warnings

    from raft_tpu import config

    index, queries = data
    d_ref, i_ref = brute_force_knn([index], queries, 6)
    with warnings.catch_warnings():
        # the knob IS trace-consumed; the deliberate test override
        # triggers the (correct) staleness caveat
        warnings.simplefilter("ignore", UserWarning)
        with config.override(mnmg_merge="hierarchical"):
            _, i_got = mnmg_knn(index, queries, 6)
    np.testing.assert_array_equal(np.asarray(i_got), np.asarray(i_ref))
    with pytest.raises(Exception):
        mnmg_knn(index, queries, 6, merge="bogus")


def test_mnmg_presharded_index_and_donating_twin(data):
    """shard_knn_index commits resident shards once; mnmg_knn(n_rows=)
    reuses them, and donate_queries routes into the donating twin."""
    from raft_tpu.comms.host_comms import default_mesh
    from raft_tpu.spatial.mnmg_knn import shard_knn_index

    index, queries = data
    mesh = default_mesh()
    index_p, n = shard_knn_index(index, mesh, mesh.axis_names[0])
    assert index_p.shape[0] % 8 == 0 and n == index.shape[0]
    d_ref, i_ref = brute_force_knn([index], queries, 10)
    d_got, i_got = mnmg_knn(index_p, jnp.copy(queries), 10, mesh=mesh,
                            axis=mesh.axis_names[0], n_rows=n,
                            donate_queries=True, merge="hierarchical")
    np.testing.assert_array_equal(np.asarray(i_got), np.asarray(i_ref))


def test_resolve_group_size_auto_and_explicit():
    from raft_tpu.comms.host_comms import default_mesh
    from raft_tpu.spatial.mnmg_knn import resolve_group_size

    mesh = default_mesh()
    g = resolve_group_size(mesh, mesh.axis_names[0])
    assert 8 % g == 0  # auto picks a divisor
    assert resolve_group_size(mesh, mesh.axis_names[0], 4) == 4


def test_axis_host_group_size_single_process():
    """The virtual mesh is one process: no host structure -> None."""
    from raft_tpu.comms.host_comms import axis_host_group_size, \
        default_mesh

    mesh = default_mesh()
    assert axis_host_group_size(mesh, mesh.axis_names[0]) is None


# ---------------------------------------------------------------------- #
# slot-sharded IVF-Flat (the ANN serving shard)
# ---------------------------------------------------------------------- #
@pytest.fixture(scope="module")
def ivf_sharded():
    from raft_tpu.comms.host_comms import default_mesh
    from raft_tpu.spatial.ann import IVFFlatParams, ivf_flat_build
    from raft_tpu.spatial.mnmg_knn import shard_ivf_flat_index

    rng = np.random.default_rng(7)
    X = rng.standard_normal((1500, 16)).astype(np.float32)
    index = ivf_flat_build(jnp.asarray(X), IVFFlatParams(nlist=24,
                                                         nprobe=6))
    mesh = default_mesh()
    return X, index, shard_ivf_flat_index(index, mesh,
                                          mesh.axis_names[0])


@pytest.mark.parametrize("merge", ["allgather", "ring", "hierarchical"])
def test_mnmg_ivf_matches_single_device(ivf_sharded, rng, merge):
    """Slot-sharded IVF search == single-device ivf_flat_search at the
    same nprobe, per merge topology."""
    from raft_tpu.spatial.ann import ivf_flat_search
    from raft_tpu.spatial.mnmg_knn import mnmg_ivf_flat_search

    X, index, sharded = ivf_sharded
    q = jnp.asarray(rng.standard_normal((9, 16)).astype(np.float32))
    d_ref, i_ref = ivf_flat_search(index, q, 5, nprobe=6)
    d_got, i_got = mnmg_ivf_flat_search(sharded, q, 5, nprobe=6,
                                        merge=merge)
    np.testing.assert_array_equal(np.asarray(i_got), np.asarray(i_ref))
    np.testing.assert_allclose(np.asarray(d_got), np.asarray(d_ref),
                               rtol=1e-4, atol=1e-4)


def test_mnmg_ivf_full_probe_is_exact(ivf_sharded, rng):
    """nprobe=nlist scans everything: sharded ANN == brute force."""
    from raft_tpu.spatial.mnmg_knn import mnmg_ivf_flat_search

    X, index, sharded = ivf_sharded
    q = jnp.asarray(rng.standard_normal((6, 16)).astype(np.float32))
    _, i_ref = brute_force_knn([jnp.asarray(X)], q, 4)
    _, i_got = mnmg_ivf_flat_search(sharded, q, 4, nprobe=24)
    np.testing.assert_array_equal(np.asarray(i_got), np.asarray(i_ref))


@pytest.mark.parametrize("merge", ["allgather", "ring", "hierarchical"])
def test_mnmg_ivf_narrow_candidates_pad_to_k(rng, merge):
    """k wider than the whole gathered candidate set (tiny probed
    lists): every topology must pad with (inf, -1) like the
    single-device running select, not crash in the merge re-selection
    (regression: the allgather arm used to select_k(k) over a
    narrower gather)."""
    from raft_tpu.comms.host_comms import default_mesh
    from raft_tpu.spatial.ann import (IVFFlatParams, ivf_flat_build,
                                      ivf_flat_search)
    from raft_tpu.spatial.mnmg_knn import (mnmg_ivf_flat_search,
                                           shard_ivf_flat_index)

    X = rng.standard_normal((120, 8)).astype(np.float32)
    index = ivf_flat_build(jnp.asarray(X), IVFFlatParams(nlist=64,
                                                         nprobe=1))
    mesh = default_mesh()
    sharded = shard_ivf_flat_index(index, mesh, mesh.axis_names[0])
    q = jnp.asarray(rng.standard_normal((5, 8)).astype(np.float32))
    d_ref, i_ref = ivf_flat_search(index, q, 64, nprobe=1)
    d_got, i_got = mnmg_ivf_flat_search(sharded, q, 64, nprobe=1,
                                        merge=merge)
    assert d_got.shape == (5, 64) and i_got.shape == (5, 64)
    np.testing.assert_array_equal(np.asarray(i_got), np.asarray(i_ref))


def test_mnmg_ivf_delta_merge(ivf_sharded, rng):
    """The replicated delta segment merges into the sharded result
    stream (ids disjoint from the base index)."""
    from raft_tpu.spatial.mnmg_knn import mnmg_ivf_flat_search

    X, index, sharded = ivf_sharded
    q = jnp.asarray(rng.standard_normal((5, 16)).astype(np.float32))
    dv = rng.standard_normal((32, 16)).astype(np.float32)
    dids = np.arange(9000, 9032, dtype=np.int32)
    _, i_got = mnmg_ivf_flat_search(
        sharded, q, 4, nprobe=24,
        delta=(jnp.asarray(dv), jnp.asarray(dids)))
    _, i_ref = brute_force_knn(
        [jnp.concatenate([jnp.asarray(X), jnp.asarray(dv)])], q, 4)
    i_ref = np.asarray(i_ref)
    want = np.where(i_ref >= X.shape[0],
                    i_ref - X.shape[0] + 9000, i_ref)
    np.testing.assert_array_equal(np.asarray(i_got), want)
