"""MNMG brute-force kNN over the virtual 8-device mesh.

Reference: baseline config #5 — multi-node brute-force kNN via comms
(comms/comms.hpp:193 + spatial/knn/knn.hpp:55), tested the way the
reference tests comms-driven code: on a real (here: virtual) cluster.
"""

import numpy as np
import pytest
import jax
import jax.numpy as jnp
from jax.sharding import Mesh

from raft_tpu import Handle
from raft_tpu.comms.host_comms import HostComms, default_mesh
from raft_tpu.distance.distance_type import DistanceType as D
from raft_tpu.spatial import brute_force_knn, mnmg_knn


@pytest.fixture
def data(rng):
    index = rng.standard_normal((403, 24)).astype(np.float32)  # not % 8
    queries = rng.standard_normal((56, 24)).astype(np.float32)
    return jnp.asarray(index), jnp.asarray(queries)


def test_mnmg_matches_single_device(data):
    index, queries = data
    d_ref, i_ref = brute_force_knn([index], queries, 10)
    d_got, i_got = mnmg_knn(index, queries, 10)
    np.testing.assert_allclose(np.asarray(d_got), np.asarray(d_ref),
                               rtol=1e-4, atol=1e-4)
    np.testing.assert_array_equal(np.asarray(i_got), np.asarray(i_ref))


def test_mnmg_via_injected_handle_comms(data):
    """The reference idiom: primitives fetch comms from the handle
    (handle.get_comms(), handle.hpp:229)."""
    index, queries = data
    h = Handle()
    h.set_comms(HostComms(default_mesh()))
    d_got, i_got = mnmg_knn(index, queries, 7, handle=h)
    d_ref, i_ref = brute_force_knn([index], queries, 7)
    np.testing.assert_array_equal(np.asarray(i_got), np.asarray(i_ref))


def test_mnmg_2d_mesh_query_sharded(data):
    """2-D mesh: index over 'dp', queries over 'mp' (subcomm pattern,
    handle.hpp:237)."""
    index, queries = data
    devs = np.array(jax.devices()[:8]).reshape(2, 4)
    mesh = Mesh(devs, ("mp", "dp"))
    d_got, i_got = mnmg_knn(index, queries, 5, mesh=mesh, axis="dp",
                            query_axis="mp")
    d_ref, i_ref = brute_force_knn([index], queries, 5)
    np.testing.assert_allclose(np.asarray(d_got), np.asarray(d_ref),
                               rtol=1e-4, atol=1e-4)
    np.testing.assert_array_equal(np.asarray(i_got), np.asarray(i_ref))


@pytest.mark.parametrize("metric", [D.L2SqrtExpanded, D.InnerProduct])
def test_mnmg_metric_dispatch(data, metric):
    index, queries = data
    d_got, i_got = mnmg_knn(index, queries, 6, metric=metric)
    d_ref, i_ref = brute_force_knn([index], queries, 6, metric=metric)
    np.testing.assert_allclose(np.asarray(d_got), np.asarray(d_ref),
                               rtol=1e-4, atol=1e-4)
    np.testing.assert_array_equal(np.asarray(i_got), np.asarray(i_ref))


def test_mnmg_k_exceeds_shard_rows(rng):
    """k larger than a shard's row count: every shard contributes all its
    rows and the merge still finds the global top-k."""
    index = jnp.asarray(rng.standard_normal((40, 8)).astype(np.float32))
    queries = jnp.asarray(rng.standard_normal((12, 8)).astype(np.float32))
    d_got, i_got = mnmg_knn(index, queries, 9)  # shards hold 5 rows each
    d_ref, i_ref = brute_force_knn([index], queries, 9)
    np.testing.assert_array_equal(np.asarray(i_got), np.asarray(i_ref))


def test_mnmg_ring_merge_matches_allgather(data):
    """merge='ring' (ppermute running top-k) == merge='allgather' ==
    single device, at a ragged shard size."""
    index, queries = data
    d_ref, i_ref = brute_force_knn([index], queries, 10)
    d_ring, i_ring = mnmg_knn(index, queries, 10, merge="ring")
    np.testing.assert_allclose(np.asarray(d_ring), np.asarray(d_ref),
                               rtol=1e-4, atol=1e-4)
    np.testing.assert_array_equal(np.asarray(i_ring), np.asarray(i_ref))


def test_mnmg_ring_k_exceeds_shard_rows(rng):
    """Ring merge when k > rows-per-shard (running block narrower than
    k must pad, not truncate)."""
    index = jnp.asarray(rng.standard_normal((19, 8)).astype(np.float32))
    queries = jnp.asarray(rng.standard_normal((7, 8)).astype(np.float32))
    d_ref, i_ref = brute_force_knn([index], queries, 5)
    d_ring, i_ring = mnmg_knn(index, queries, 5, merge="ring")
    np.testing.assert_allclose(np.asarray(d_ring), np.asarray(d_ref),
                               rtol=1e-4, atol=1e-4)
    np.testing.assert_array_equal(np.asarray(i_ring), np.asarray(i_ref))


def test_mnmg_ring_2d_mesh(data):
    """Ring merge composes with query sharding on a 2-D mesh."""
    index, queries = data
    devs = np.array(jax.devices()[:8]).reshape(2, 4)
    mesh = Mesh(devs, ("qx", "ix"))
    d_ref, i_ref = brute_force_knn([index], queries, 10)
    d_got, i_got = mnmg_knn(index, queries, 10, mesh=mesh, axis="ix",
                            query_axis="qx", merge="ring")
    np.testing.assert_allclose(np.asarray(d_got), np.asarray(d_ref),
                               rtol=1e-4, atol=1e-4)
    np.testing.assert_array_equal(np.asarray(i_got), np.asarray(i_ref))
