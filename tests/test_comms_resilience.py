"""Fault-injected resilience suite: retry/backoff, abort latching,
watchdog timeouts, bootstrap retry, and mesh-shrink recovery — all
deterministic on the simulated CPU mesh.

The reference can only validate its failure contract (status_t,
sync_stream + ncclCommGetAsyncError, ncclCommAbort) against a live
cluster; here :mod:`raft_tpu.comms.faults` injects failures below the
retry/abort machinery so every path runs hardware-free.  Seeded faults
honor ``RAFT_TPU_FAULT_SEED`` so ``stress.sh faults`` can rotate seeds
across iterations.
"""

import os
import time

import numpy as np
import pytest
import jax
import jax.numpy as jnp

from raft_tpu.comms import (
    HostComms, RetryPolicy, Status, default_mesh, faults, selftest,
)
from raft_tpu.comms.faults import InjectedError
from raft_tpu.core import tracing
from raft_tpu.core.error import (
    CommAbortedError, CommError, CommTimeoutError, LogicError, RaftError,
)
from raft_tpu.core.handle import Handle, Stream
from raft_tpu.session import Comms, _sessions

pytestmark = pytest.mark.faults

SEED = int(os.environ.get("RAFT_TPU_FAULT_SEED", "1234"))


def fast_policy(**kw):
    """Policy with recorded (not slept) backoff so tests stay instant."""
    slept = []
    kw.setdefault("max_retries", 3)
    kw.setdefault("base_delay", 0.01)
    policy = RetryPolicy(sleep=slept.append, **kw)
    return policy, slept


# ---------------------------------------------------------------------- #
# RetryPolicy mechanics
# ---------------------------------------------------------------------- #
def test_backoff_schedule_deterministic():
    p = RetryPolicy(max_retries=4, base_delay=0.05, multiplier=2.0,
                    max_delay=0.3)
    assert p.schedule() == [0.05, 0.1, 0.2, 0.3]
    assert p.schedule() == p.schedule()


def test_retry_policy_does_not_retry_logic_errors():
    p, slept = fast_policy()
    calls = []

    def bad():
        calls.append(1)
        raise LogicError("malformed call")

    with pytest.raises(LogicError):
        p.call(bad)
    assert len(calls) == 1 and slept == []


def test_watchdog_timeout_raises_comm_timeout():
    p = RetryPolicy(max_retries=0, timeout=0.05)
    with pytest.raises(CommTimeoutError):
        p.call(lambda: time.sleep(3))


# ---------------------------------------------------------------------- #
# acceptance (a): transient verb failure is retried and succeeds
# ---------------------------------------------------------------------- #
def test_transient_allreduce_retries_then_succeeds():
    policy, slept = fast_policy(max_retries=3)
    comms = HostComms(default_mesh(), retry_policy=policy)
    size = comms.get_size()
    before = tracing.get_counter("comms.retry")
    with faults.inject(comms, faults.FailNth(1, verb="allreduce")) as log:
        out = comms.allreduce(jnp.ones((size, 1), jnp.float32))
    assert (np.asarray(out) == size).all()
    # first execution failed, retry hit the transport again
    assert [v for v, _ in log.calls] == ["allreduce", "allreduce"]
    assert len(log.injected) == 1 and log.injected[0].verb == "allreduce"
    assert slept == [policy.schedule()[0]]
    assert tracing.get_counter("comms.retry") == before + 1
    assert not comms.aborted  # transient + recovered: no latch


def test_watchdog_timeout_retried_then_succeeds():
    policy, _ = fast_policy(max_retries=2, timeout=0.25)
    comms = HostComms(default_mesh())
    size = comms.get_size()
    # warm the compile cache policy-free so the deadline only ever
    # measures the injected delay, never a cold compile
    comms.bcast(jnp.zeros((size, 1), jnp.float32))
    comms.retry_policy = policy
    before = tracing.get_counter("comms.timeout")
    before_inj = tracing.get_counter("comms.fault_injected")
    with faults.inject(comms,
                       faults.Delay(1.0, verb="bcast", times=1)) as log:
        out = comms.bcast(
            jnp.zeros((size, 1), jnp.float32).at[0, 0].set(5.0))
    assert (np.asarray(out) == 5.0).all()
    assert [v for v, _ in log.calls] == ["bcast", "bcast"]
    assert tracing.get_counter("comms.timeout") == before + 1
    # non-raising faults (delays) count as injections too
    assert tracing.get_counter("comms.fault_injected") == before_inj + 1


def test_abandoned_delayed_attempt_never_dispatches_late():
    """A Delay outliving the watchdog must NOT dispatch its program
    after waking: the late collective would race the retry's (or the
    next caller's) program and deadlock the CPU backend's shared
    rendezvous.  The abandoned runner bails at the fault seam instead
    (resilience marks the thread, Delay.apply checks the mark)."""
    comms = HostComms(default_mesh())
    size = comms.get_size()
    comms.allreduce(jnp.ones((size, 1), jnp.float32))   # warm compile
    executed = []
    real_execute = comms._execute

    def counting(key, fn, *args, **kwargs):
        executed.append(key[0])
        return real_execute(key, fn, *args, **kwargs)

    comms._execute = counting
    comms.retry_policy = RetryPolicy(max_retries=1, base_delay=0.0,
                                     timeout=0.1)
    with faults.inject(comms, faults.Delay(0.5, verb="allreduce",
                                           times=1)):
        out = comms.allreduce(jnp.ones((size, 1), jnp.float32))
        assert (np.asarray(out) == size).all()          # retry won
        assert executed == ["allreduce"]                # only the retry
        time.sleep(0.7)                                 # let attempt 1 wake
        # the abandoned attempt woke, saw the mark, and bailed without
        # reaching the transport
        assert executed == ["allreduce"]


def test_random_faults_recovered_by_retry_rotating_seed():
    """With seeded random failures, enough retries always win — run under
    stress.sh faults, which rotates RAFT_TPU_FAULT_SEED per iteration."""
    policy, _ = fast_policy(max_retries=8, base_delay=0.0)
    comms = HostComms(default_mesh(), retry_policy=policy)
    size = comms.get_size()
    x = jnp.arange(size, dtype=jnp.float32)[:, None]
    want = np.asarray(comms.allreduce(x))
    with faults.inject(comms, faults.RandomFail(0.25, seed=SEED)):
        for _ in range(10):
            assert (np.asarray(comms.allreduce(x)) == want).all()
    assert not comms.aborted


def test_random_fail_deterministic_per_seed():
    def pattern(seed):
        f = faults.RandomFail(0.5, seed=seed)
        out = []
        for i in range(32):
            try:
                f.apply(None, "allreduce", ("allreduce",), i + 1)
                out.append(False)
            except InjectedError:
                out.append(True)
        return out

    assert pattern(SEED) == pattern(SEED)
    assert pattern(SEED) != pattern(SEED + 1)


def test_delay_rank_scoping_matches_static_params():
    d = faults.Delay(0.0, verb="bcast", rank=3)
    assert d.matches("bcast", ("bcast", 3))
    assert not d.matches("bcast", ("bcast", 0))
    p2p = faults.Delay(0.0, rank=2)
    assert p2p.matches("p2p", ("p2p", ((0, 1), (2, 3))))
    assert not p2p.matches("p2p", ("p2p", ((0, 1),)))
    # Op statics are not ranks: Op.SUM == 0 must not match rank 0
    from raft_tpu.comms import Op

    assert not faults.Delay(0.0, rank=0).matches("allreduce",
                                                 ("allreduce", Op.SUM))


# ---------------------------------------------------------------------- #
# acceptance (b): injected abort latches; every verb fails fast
# ---------------------------------------------------------------------- #
def test_abort_latches_and_all_verbs_fail_fast():
    comms = HostComms(default_mesh())
    size = comms.get_size()
    x = jnp.ones((size, 1), jnp.float32)
    with faults.inject(comms, faults.Abort(verb="allreduce")) as log:
        with pytest.raises(CommAbortedError):
            comms.allreduce(x)
    assert comms.aborted
    # fail-fast: none of these reach the transport (no new executions)
    n_calls = len(log.calls)
    for verb in (lambda: comms.allreduce(x),
                 lambda: comms.bcast(x),
                 lambda: comms.allgather(x),
                 lambda: comms.barrier(),
                 lambda: comms.isend(x[0], rank=0, dest=1),
                 lambda: comms.irecv(rank=1, source=0),
                 lambda: comms.waitall()):
        with pytest.raises(CommAbortedError):
            verb()
    assert len(log.calls) == n_calls
    assert comms.sync_stream() == Status.ABORT


def test_abort_latch_survives_retry_policy():
    """An abort is non-retryable: the policy must not spin on it."""
    policy, slept = fast_policy(max_retries=5)
    comms = HostComms(default_mesh(), retry_policy=policy)
    size = comms.get_size()
    with faults.inject(comms, faults.Abort(verb="allreduce")) as log:
        with pytest.raises(CommAbortedError):
            comms.allreduce(jnp.ones((size, 1)))
    assert len(log.calls) == 1 and slept == []


def test_exhausted_timeouts_surface_as_comm_timeout_error():
    """Deadline expiries keep their subtype through the verb layer so
    callers can branch on CommTimeoutError specifically."""
    policy, _ = fast_policy(max_retries=1, timeout=0.05)
    comms = HostComms(default_mesh())
    size = comms.get_size()
    comms.allreduce(jnp.ones((size, 1)))  # warm the compile cache
    comms.retry_policy = policy  # deadline applies to warmed executions
    with faults.inject(comms, faults.Delay(1.0, verb="allreduce")):
        with pytest.raises(CommTimeoutError):
            comms.allreduce(jnp.ones((size, 1)))
    assert comms.aborted


def test_exhausted_retries_latch_abort():
    policy, slept = fast_policy(max_retries=2)
    comms = HostComms(default_mesh(), retry_policy=policy)
    size = comms.get_size()
    with faults.inject(comms,
                       faults.FailNth(1, verb="allreduce",
                                      persistent=True)) as log:
        with pytest.raises(CommError) as ei:
            comms.allreduce(jnp.ones((size, 1)))
    assert "after 3 attempts" in str(ei.value)
    assert len(log.calls) == 3 and len(slept) == 2
    assert comms.aborted
    with pytest.raises(CommAbortedError):
        comms.bcast(jnp.ones((size, 1)))


def test_malformed_call_neither_retried_nor_poisoning():
    """A deterministic caller bug (duplicate ppermute destination ->
    ValueError in trace) must propagate without burning retries or
    latching the communicator."""
    policy, slept = fast_policy(max_retries=4)
    comms = HostComms(default_mesh(), retry_policy=policy)
    size = comms.get_size()
    with pytest.raises((IndexError, TypeError, ValueError)):
        comms.device_sendrecv(jnp.ones((size, 1)), [(0, 1), (1, 1)])
    assert slept == []  # no retries on a deterministic error
    assert not comms.aborted
    out = comms.allreduce(jnp.ones((size, 1)))  # communicator still live
    assert (np.asarray(out) == size).all()


def test_handle_surfaces_aborted_comms():
    handle = Handle()
    comms = HostComms(default_mesh())
    handle.set_comms(comms)
    assert handle.get_comms() is comms
    comms.abort()
    with pytest.raises(CommAbortedError):
        handle.get_comms()


# ---------------------------------------------------------------------- #
# acceptance (c): recover() on a shrunk mesh passes the selftest battery
# ---------------------------------------------------------------------- #
def test_recover_on_shrunk_mesh_passes_selftests():
    with Comms(mesh=default_mesh()) as s:
        old = s.comms
        extra = Handle()
        s.register_handle(extra)
        with faults.inject(s.comms, faults.Abort(verb="allreduce")):
            with pytest.raises(CommAbortedError):
                s.comms.allreduce(jnp.ones((8, 1)))
        # health check reports the aborted communicator but live devices
        health = s.health_check()
        assert not health["ok"]
        assert not any(health["tests"].values())
        assert all(health["devices"].values())
        # shrink: rebuild on half the mesh (simulated surviving sub-mesh),
        # naming survivors by the int ids health_check reports
        before = tracing.get_counter("comms.recover")
        survivors = [d.id for d in list(old.mesh.devices.ravel())[:4]]
        assert all(isinstance(i, int) and health["devices"][i]
                   for i in survivors)
        fresh = s.recover(devices=survivors)
        assert tracing.get_counter("comms.recover") == before + 1
        assert fresh is not old and fresh.get_size() == 4
        assert not fresh.aborted
        # every registered handle got the rebuilt communicator
        assert s.handle.get_comms() is fresh
        assert extra.get_comms() is fresh
        results = selftest.run_all(fresh)
        assert results and all(results.values()), results


def test_recover_multiaxis_mesh_requires_explicit_mesh():
    """Automatic 1-D rebuild must refuse to flatten a multi-axis mesh;
    an explicit replacement mesh (with the comms axis) is accepted."""
    from jax.sharding import Mesh

    devs = np.array(jax.devices()[:8]).reshape(4, 2)
    with Comms(mesh=Mesh(devs, ("ranks", "aux"))) as s:
        s.comms.abort()
        with pytest.raises(LogicError, match="pass the replacement mesh"):
            s.recover()
        with pytest.raises(LogicError, match="not both"):
            s.recover(devices=list(devs.ravel()[:2]),
                      mesh=Mesh(devs[:2], ("ranks", "aux")))
        fresh = s.recover(mesh=Mesh(devs[:2], ("ranks", "aux")))
        assert fresh.get_size() == 2
        assert s.handle.get_comms() is fresh
        assert s.handle.mesh.axis_names == ("ranks", "aux")
        size = fresh.get_size()
        out = fresh.allreduce(jnp.ones((size, 1), jnp.float32))
        assert (np.asarray(out) == size).all()


def test_run_all_fails_closed_on_aborted_comms():
    comms = HostComms(default_mesh())
    comms.abort()
    results = selftest.run_all(comms)
    assert set(results) == {fn.__name__ for fn in selftest.ALL_TESTS}
    assert not any(results.values())


def test_health_check_leaves_user_p2p_queue_alone():
    """The battery's p2p tests wait on their own requests only: user
    work queued-but-not-waited must survive a health probe untouched."""
    with Comms(mesh=default_mesh()) as s:
        comms = s.comms
        pending_send = comms.isend(jnp.ones((2,)), rank=0, dest=1, tag=42)
        pending_recv = comms.irecv(rank=1, source=0, tag=42)
        health = s.health_check()
        assert health["ok"], health
        # user's requests still queued, unmatched by the battery
        assert pending_send in comms._requests
        assert pending_recv in comms._requests
        comms.waitall()  # and still completable afterwards
        assert (np.asarray(pending_recv.result) == 1.0).all()


# ---------------------------------------------------------------------- #
# bootstrap retry (session layer)
# ---------------------------------------------------------------------- #
def test_bootstrap_retry_honors_timeout(monkeypatch):
    attempts = []
    monkeypatch.setattr(jax.distributed, "initialize",
                        lambda **kw: (attempts.append(1), time.sleep(3)))
    policy, slept = fast_policy(max_retries=2, timeout=0.1)
    s = Comms(coordinator_address="127.0.0.1:1", num_processes=1,
              process_id=0, retry_policy=policy)
    t0 = time.monotonic()
    with pytest.raises(CommError) as ei:
        s.init()
    elapsed = time.monotonic() - t0
    assert isinstance(ei.value.__cause__, CommTimeoutError)
    assert "after 3 attempts" in str(ei.value)
    assert len(attempts) == 3 and len(slept) == 2
    assert elapsed < 2.0  # bounded by the watchdog, not the 3 s hang
    assert not s.initialized and s.sessionId not in _sessions


def test_bootstrap_transient_failures_then_success(monkeypatch):
    attempts = []

    def flaky(**kw):
        attempts.append(1)
        if len(attempts) < 3:
            raise RuntimeError("coordinator not up yet")

    monkeypatch.setattr(jax.distributed, "initialize", flaky)
    monkeypatch.setattr(jax.distributed, "shutdown", lambda: None)
    boot_policy, slept = fast_policy(max_retries=3)
    verb_policy = RetryPolicy(max_retries=1, retry_timeouts=False)
    s = Comms(coordinator_address="127.0.0.1:1", num_processes=1,
              process_id=0, retry_policy=verb_policy,
              bootstrap_retry_policy=boot_policy)
    s.init()
    try:
        assert s.initialized and len(attempts) == 3
        assert slept == boot_policy.schedule()[:2]
        # bootstrap and verbs run under their own policies
        assert s.comms.retry_policy is verb_policy
    finally:
        s.destroy()


def test_init_failure_after_bootstrap_releases_connection(monkeypatch):
    """If init() fails after a successful bootstrap, the owned
    distributed connection must be shut down — the context-manager
    __exit__ never runs when __enter__ raises."""
    import raft_tpu.session as sessmod

    shutdown_calls = []
    monkeypatch.setattr(jax.distributed, "initialize", lambda **kw: None)
    monkeypatch.setattr(jax.distributed, "shutdown",
                        lambda: shutdown_calls.append(1))
    monkeypatch.setattr(sessmod, "default_mesh",
                        lambda: (_ for _ in ()).throw(
                            RuntimeError("mesh construction exploded")))
    s = Comms(coordinator_address="127.0.0.1:1", num_processes=1,
              process_id=0)
    with pytest.raises(RuntimeError, match="mesh construction"):
        s.init()
    assert shutdown_calls == [1]
    assert not s.initialized and not s._owns_distributed
    assert s.sessionId not in _sessions


def test_recover_rejects_foreign_device_objects():
    class FakeDevice:
        id = 999

    with Comms(mesh=default_mesh()) as s:
        with pytest.raises(LogicError, match="not in the session mesh"):
            s.recover(devices=[FakeDevice()])


def test_failed_waitall_consumes_requests():
    """A stale unmatched request must not poison later waitall calls."""
    comms = HostComms(default_mesh())
    comms.isend(jnp.ones((1,)), rank=0, dest=1, tag=99)  # never matched
    with pytest.raises(LogicError):
        comms.waitall()
    assert comms._requests == []
    comms.isend(jnp.full((1,), 3.0), rank=0, dest=1, tag=5)
    r = comms.irecv(rank=1, source=0, tag=5)
    comms.waitall()  # unaffected by the earlier failure
    assert float(r.result[0]) == 3.0


def test_bootstrap_respects_preexisting_distributed(monkeypatch):
    """A distributed runtime the user brought up themselves is used but
    never owned: no re-initialize, and destroy() must not shut it down."""
    import raft_tpu.session as sessmod

    monkeypatch.setattr(sessmod, "_distributed_is_initialized", lambda: True)
    init_calls, shutdown_calls = [], []
    monkeypatch.setattr(jax.distributed, "initialize",
                        lambda **kw: init_calls.append(1))
    monkeypatch.setattr(jax.distributed, "shutdown",
                        lambda: shutdown_calls.append(1))
    s = Comms(coordinator_address="127.0.0.1:1", num_processes=1,
              process_id=0).init()
    assert init_calls == [] and not s._owns_distributed
    s.destroy()
    assert shutdown_calls == []


# ---------------------------------------------------------------------- #
# satellites: Stream.sync poisoning, get_type, destroy idempotence
# ---------------------------------------------------------------------- #
class _Poison:
    def block_until_ready(self):
        raise RuntimeError("simulated async dispatch failure")


def test_stream_sync_clears_pending_on_failure():
    st = Stream("s")
    st.record(_Poison())
    with pytest.raises(RaftError):
        st.sync()
    assert st._pending == []
    st.sync()  # poisoned work does not replay
    st.record(jnp.ones((2,)))
    st.sync()
    assert st._pending == []


def test_get_type_unsupported_dtype_is_logic_error():
    from raft_tpu.comms import get_type

    with pytest.raises(LogicError) as ei:
        get_type(jnp.float16)
    assert "float16" in str(ei.value)
    with pytest.raises(LogicError):
        get_type(np.dtype("complex64"))


def test_destroy_idempotent_and_registry_cleared_on_teardown_error():
    s = Comms(mesh=default_mesh()).init()
    sid = s.sessionId
    assert sid in _sessions
    s.destroy()
    assert sid not in _sessions and not s.initialized
    s.destroy()  # second destroy: no-op, no raise

    # teardown failure must still deregister (no shadowing of a later
    # session re-using the lookup path)
    s2 = Comms(mesh=default_mesh()).init()

    def boom():
        raise RuntimeError("teardown exploded")

    s2._teardown = boom
    with pytest.raises(RuntimeError):
        s2.destroy()
    assert s2.sessionId not in _sessions and not s2.initialized
    s2.destroy()  # idempotent even after a failed teardown
