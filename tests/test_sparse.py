"""Sparse formats/convert/op/linalg tests vs scipy.sparse naive references.

Mirrors the reference's parameterized naive-kernel pattern
(cpp/test/sparse/*.cu): every primitive is checked against a dense or
scipy.sparse ground truth.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
import scipy.sparse as sp

from raft_tpu.sparse import COO, CSR, convert, linalg, op


def random_dense(rng, m, n, density=0.3, with_dups=False):
    d = rng.random((m, n)) * (rng.random((m, n)) < density)
    return d.astype(np.float32)


@pytest.fixture
def rng():
    return np.random.default_rng(42)


class TestFormats:
    def test_coo_roundtrip(self, rng):
        d = random_dense(rng, 13, 9)
        coo = COO.from_dense(d, capacity=200)
        np.testing.assert_allclose(np.asarray(coo.to_dense()), d)
        c = coo.compact()
        assert c.capacity == int(coo.nnz)
        np.testing.assert_allclose(np.asarray(c.to_dense()), d)

    def test_csr_roundtrip(self, rng):
        d = random_dense(rng, 7, 11)
        csr = CSR.from_dense(d, capacity=150)
        np.testing.assert_allclose(np.asarray(csr.to_dense()), d)
        ref = sp.csr_matrix(d)
        nnz = int(csr.nnz)
        np.testing.assert_array_equal(np.asarray(csr.indptr), ref.indptr)
        np.testing.assert_array_equal(np.asarray(csr.indices)[:nnz], ref.indices)

    def test_row_ids(self, rng):
        d = random_dense(rng, 6, 6)
        csr = CSR.from_dense(d, capacity=50)
        ref = sp.coo_matrix(d)
        got = np.asarray(csr.row_ids())
        np.testing.assert_array_equal(got[: ref.nnz], ref.row)
        assert (got[ref.nnz:] == 6).all()

    def test_pytree(self, rng):
        d = random_dense(rng, 5, 5)
        coo = COO.from_dense(d, capacity=30)
        out = jax.jit(lambda c: c.to_dense())(coo)
        np.testing.assert_allclose(np.asarray(out), d)


class TestConvert:
    def test_coo_to_csr_unsorted(self, rng):
        d = random_dense(rng, 10, 8)
        coo = COO.from_dense(d, capacity=100)
        perm = rng.permutation(100)
        shuffled = COO(coo.rows[perm], coo.cols[perm], coo.vals[perm],
                       coo.shape, coo.nnz)
        csr = convert.coo_to_csr(shuffled)
        np.testing.assert_allclose(np.asarray(csr.to_dense()), d)
        ref = sp.csr_matrix(d)
        np.testing.assert_array_equal(np.asarray(csr.indptr), ref.indptr)

    def test_csr_to_coo(self, rng):
        d = random_dense(rng, 9, 4)
        csr = CSR.from_dense(d, capacity=60)
        coo = convert.csr_to_coo(csr)
        np.testing.assert_allclose(np.asarray(coo.to_dense()), d)

    def test_csr_to_dense(self, rng):
        d = random_dense(rng, 4, 4)
        csr = CSR.from_dense(d)
        np.testing.assert_allclose(np.asarray(convert.csr_to_dense(csr)), d)


class TestOp:
    def test_coo_sort(self, rng):
        d = random_dense(rng, 8, 8)
        coo = COO.from_dense(d, capacity=80)
        perm = rng.permutation(80)
        shuffled = COO(coo.rows[perm], coo.cols[perm], coo.vals[perm],
                       coo.shape, coo.nnz)
        s = op.coo_sort(shuffled)
        r = np.asarray(s.rows)
        c = np.asarray(s.cols)
        nnz = int(s.nnz)
        key = r[:nnz].astype(np.int64) * 9 + c[:nnz]
        assert (np.diff(key) >= 0).all()
        assert (r[nnz:] == 8).all()
        np.testing.assert_allclose(np.asarray(s.to_dense()), d)

    def test_sort_by_weight(self, rng):
        d = random_dense(rng, 8, 8)
        coo = COO.from_dense(d, capacity=80)
        s = op.coo_sort_by_weight(coo)
        v = np.asarray(s.vals)[: int(s.nnz)]
        assert (np.diff(v) >= 0).all()

    def test_max_duplicates(self, rng):
        rows = np.array([0, 0, 1, 1, 1, 2], np.int32)
        cols = np.array([1, 1, 0, 2, 2, 2], np.int32)
        vals = np.array([3.0, 5.0, 1.0, 7.0, 2.0, 4.0], np.float32)
        coo = COO(rows, cols, vals, (3, 3))
        out = op.max_duplicates(coo)
        assert int(out.nnz) == 4
        dense = np.asarray(out.to_dense())
        expect = np.zeros((3, 3), np.float32)
        expect[0, 1], expect[1, 0], expect[1, 2], expect[2, 2] = 5, 1, 7, 4
        np.testing.assert_allclose(dense, expect)

    def test_sum_duplicates(self):
        rows = np.array([0, 0, 2], np.int32)
        cols = np.array([1, 1, 0], np.int32)
        vals = np.array([3.0, 5.0, 1.0], np.float32)
        out = op.sum_duplicates(COO(rows, cols, vals, (3, 3)))
        assert int(out.nnz) == 2
        dense = np.asarray(out.to_dense())
        assert dense[0, 1] == 8.0 and dense[2, 0] == 1.0

    def test_remove_scalar(self, rng):
        d = random_dense(rng, 6, 6)
        d[d > 0.5] = 7.0
        coo = COO.from_dense(d, capacity=50)
        out = op.coo_remove_scalar(coo, 7.0)
        expect = d.copy()
        expect[expect == 7.0] = 0
        np.testing.assert_allclose(np.asarray(out.to_dense()), expect)
        assert int(out.nnz) == (expect != 0).sum()

    def test_remove_scalar_jit(self, rng):
        d = random_dense(rng, 6, 6)
        coo = COO.from_dense(d, capacity=50)
        out = jax.jit(lambda c: op.coo_remove_scalar(c, 0.0))(coo)
        np.testing.assert_allclose(np.asarray(out.to_dense()), d)

    def test_csr_row_slice(self, rng):
        d = random_dense(rng, 10, 5)
        csr = CSR.from_dense(d)
        sub = op.csr_row_slice(csr, 2, 7)
        np.testing.assert_allclose(np.asarray(sub.to_dense()), d[2:7])

    def test_csr_row_op(self, rng):
        d = random_dense(rng, 5, 5)
        csr = CSR.from_dense(d, capacity=30)
        out = op.csr_row_op(csr, lambda r, v: v * (r + 1))
        got = CSR(csr.indptr, csr.indices, out, csr.shape).to_dense()
        expect = d * (np.arange(5)[:, None] + 1)
        np.testing.assert_allclose(np.asarray(got), expect, rtol=1e-6)


class TestLinalg:
    def test_degree(self, rng):
        d = random_dense(rng, 8, 8)
        coo = COO.from_dense(d, capacity=70)
        np.testing.assert_array_equal(
            np.asarray(linalg.coo_degree(coo)), (d != 0).sum(1))
        csr = CSR.from_dense(d, capacity=70)
        np.testing.assert_array_equal(
            np.asarray(linalg.csr_degree(csr)), (d != 0).sum(1))

    def test_row_normalize_l1(self, rng):
        d = random_dense(rng, 6, 6)
        csr = CSR.from_dense(d, capacity=40)
        out = linalg.csr_row_normalize_l1(csr)
        dense = np.asarray(out.to_dense())
        sums = np.abs(d).sum(1, keepdims=True)
        expect = np.where(sums > 0, d / np.where(sums == 0, 1, sums), 0)
        np.testing.assert_allclose(dense, expect, rtol=1e-6)

    def test_row_normalize_max(self, rng):
        d = random_dense(rng, 6, 6)
        csr = CSR.from_dense(d, capacity=40)
        out = linalg.csr_row_normalize_max(csr)
        mx = d.max(1, keepdims=True)
        expect = np.where(mx > 0, d / np.where(mx == 0, 1, mx), 0)
        np.testing.assert_allclose(np.asarray(out.to_dense()), expect, rtol=1e-6)

    def test_csr_add(self, rng):
        da = random_dense(rng, 7, 7)
        db = random_dense(rng, 7, 7)
        c = linalg.csr_add(CSR.from_dense(da, capacity=40),
                           CSR.from_dense(db, capacity=40))
        np.testing.assert_allclose(np.asarray(c.to_dense()), da + db, rtol=1e-6)

    def test_transpose(self, rng):
        d = random_dense(rng, 6, 9)
        t = linalg.csr_transpose(CSR.from_dense(d, capacity=60))
        assert t.shape == (9, 6)
        np.testing.assert_allclose(np.asarray(t.to_dense()), d.T)

    def test_symmetrize_sum(self):
        d = np.zeros((4, 4), np.float32)
        d[0, 1], d[1, 0], d[2, 3] = 2.0, 3.0, 5.0
        out = linalg.coo_symmetrize(COO.from_dense(d, capacity=10))
        dense = np.asarray(out.to_dense())
        expect = d + d.T
        np.testing.assert_allclose(dense, expect)

    def test_symmetrize_knn(self):
        idx = np.array([[1, 2], [0, 2], [0, 1]], np.int32)
        dist = np.array([[1.0, 4.0], [2.0, 3.0], [4.0, 3.0]], np.float32)
        out = linalg.symmetrize_knn(idx, dist, 3)
        dense = np.asarray(out.to_dense())
        expect = np.zeros((3, 3), np.float32)
        expect[0, 1] = expect[1, 0] = 2.0  # max(1, 2)
        expect[0, 2] = expect[2, 0] = 4.0
        expect[1, 2] = expect[2, 1] = 3.0
        np.testing.assert_allclose(dense, expect)

    def test_spmv(self, rng):
        d = random_dense(rng, 12, 9)
        x = rng.random(9).astype(np.float32)
        got = linalg.csr_spmv(CSR.from_dense(d, capacity=80), x)
        np.testing.assert_allclose(np.asarray(got), d @ x, rtol=1e-5)

    def test_spmv_cumsum_impl(self, rng):
        """The prefix-sum SpMV formulation (RAFT_TPU_SPMV_IMPL=cumsum)
        must match the segment-sum default, including empty rows and a
        padded capacity tail."""
        d = random_dense(rng, 30, 17)
        d[5] = 0.0                      # empty row
        x = rng.random(17).astype(np.float32)
        c = CSR.from_dense(d, capacity=700)
        got = linalg.csr_spmv(c, x, impl="cumsum")
        np.testing.assert_allclose(np.asarray(got), d @ x, rtol=2e-5,
                                   atol=1e-6)

    def test_spmm(self, rng):
        d = random_dense(rng, 8, 8)
        x = rng.random((8, 3)).astype(np.float32)
        got = linalg.csr_spmm(CSR.from_dense(d, capacity=50), x)
        np.testing.assert_allclose(np.asarray(got), d @ x, rtol=1e-5)

    def test_weak_cc_two_components(self):
        # 0-1-2 chain and 3-4 pair
        d = np.zeros((5, 5), np.float32)
        for i, j in [(0, 1), (1, 2), (3, 4)]:
            d[i, j] = d[j, i] = 1.0
        labels = np.asarray(linalg.weak_cc(CSR.from_dense(d)))
        assert labels[0] == labels[1] == labels[2] == 1
        assert labels[3] == labels[4] == 4

    def test_weak_cc_random(self, rng):
        n = 30
        d = (rng.random((n, n)) < 0.08).astype(np.float32)
        d = np.maximum(d, d.T)
        np.fill_diagonal(d, 0)
        labels = np.asarray(linalg.weak_cc(CSR.from_dense(d, capacity=max(1, int(d.sum())))))
        n_comp, ref_labels = sp.csgraph.connected_components(
            sp.csr_matrix(d), directed=False)
        # same partition
        for comp in range(n_comp):
            ours = labels[ref_labels == comp]
            assert (ours == ours[0]).all()
        assert len(np.unique(labels)) == n_comp


class TestSortscanSpmv:
    """Gather-free SpMV (r5): gather_via_sortscan + the sortscan impl
    must match scipy and the other impls exactly."""

    def test_gather_via_sortscan_matches_fancy_index(self):
        from raft_tpu.sparse.linalg import gather_via_sortscan

        rng = np.random.default_rng(5)
        x = jnp.asarray(rng.random(257).astype(np.float32))
        for m in (1, 7, 1024):
            idx = jnp.asarray(rng.integers(0, 257, m).astype(np.int32))
            got = np.asarray(gather_via_sortscan(x, idx))
            np.testing.assert_allclose(got, np.asarray(x)[np.asarray(idx)],
                                       rtol=0, atol=0)
        # duplicate-heavy and boundary probes
        idx = jnp.asarray(np.array([0, 0, 256, 256, 128] * 50, np.int32))
        got = np.asarray(gather_via_sortscan(x, idx))
        np.testing.assert_allclose(got, np.asarray(x)[np.asarray(idx)])
        # out-of-range clamps (documented contract; no silent 0-fill)
        oob = jnp.asarray(np.array([-1, -5, 300, 257], np.int32))
        got = np.asarray(gather_via_sortscan(x, oob))
        exp = np.asarray(x)[np.clip(np.asarray(oob), 0, 256)]
        np.testing.assert_allclose(got, exp)

    def test_spmv_sortscan_matches_scipy_and_segment(self):
        import scipy.sparse as sp

        from raft_tpu.sparse.formats import CSR
        from raft_tpu.sparse.linalg import csr_spmv

        rng = np.random.default_rng(6)
        dense = (rng.random((60, 45)) * (rng.random((60, 45)) > 0.7)
                 ).astype(np.float32)
        A = CSR.from_dense(jnp.asarray(dense))
        x = jnp.asarray(rng.random(45).astype(np.float32))
        ref = sp.csr_matrix(dense) @ np.asarray(x)
        y_seg = csr_spmv(A, x, impl="segment")
        y_ss = csr_spmv(A, x, impl="sortscan")
        np.testing.assert_allclose(np.asarray(y_ss), ref, rtol=1e-5,
                                   atol=1e-5)
        np.testing.assert_allclose(np.asarray(y_ss), np.asarray(y_seg),
                                   rtol=1e-6, atol=1e-6)

    def test_spmv_sortscan_under_jit_and_config(self):
        from raft_tpu import config
        from raft_tpu.sparse.formats import CSR
        from raft_tpu.sparse.linalg import csr_spmv

        rng = np.random.default_rng(7)
        dense = (rng.random((32, 32)) * (rng.random((32, 32)) > 0.5)
                 ).astype(np.float32)
        A = CSR.from_dense(jnp.asarray(dense))
        x = jnp.asarray(rng.random(32).astype(np.float32))
        with config.override(spmv_impl="sortscan"):
            y = jax.jit(lambda a, v: csr_spmv(a, v))(A, x)
        np.testing.assert_allclose(np.asarray(y), dense @ np.asarray(x),
                                   rtol=1e-5, atol=1e-5)
