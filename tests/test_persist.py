"""Durable serving state (raft_tpu.persist; docs/PERSISTENCE.md):
snapshot round trips for every index kind (bitwise search identity),
the corruption matrix (manifest / array payload / WAL interior / WAL
torn tail), the insert acknowledge contract, crash-restart recovery
through ANNService(persist_dir=) including the delta-overflow fold,
integrity scrubbing with quarantine-and-rebuild, session health
integration, and the serialization style ban.

Deterministic throughout: services run threadless (``start=False``)
with injected fake clocks driving snapshot intervals; the one
concurrency scenario rides ``tools/loadgen.run_crash_restart`` (also
rotated by ``./stress.sh chaos``).
"""

import os

import numpy as np
import pytest

import jax.numpy as jnp

from raft_tpu.core.error import (
    DataCorruptionError,
    LogicError,
)
from raft_tpu.core.profiler import compile_cache_stats
from raft_tpu.persist import (
    WriteAheadLog,
    current_manifest,
    load_current,
    replay_wal,
    write_snapshot,
)
from raft_tpu.serve import ANNService
from raft_tpu.spatial import ann
from raft_tpu.spatial.ooc import OocIVFFlat, ivf_flat_to_ooc

pytestmark = [pytest.mark.persist, pytest.mark.serve]

SEED = int(os.environ.get("RAFT_TPU_SERVE_SEED", "1234"))


class FakeClock:
    def __init__(self, t=1000.0):
        self.t = t

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt


@pytest.fixture
def rng():
    return np.random.default_rng(SEED)


@pytest.fixture
def data(rng):
    return jnp.asarray(rng.standard_normal((900, 16)), jnp.float32)


@pytest.fixture
def flat_index(data):
    return ann.ivf_flat_build(
        data, ann.IVFFlatParams(nlist=8, nprobe=4), seed=SEED)


def _total_misses():
    return sum(s["misses"] for fn in compile_cache_stats().values()
               for s in fn.values())


def _search_pair(idx, q, k=5):
    out = ann.approx_knn_search(idx, q, k, nprobe=4)
    return np.asarray(out[0]), np.asarray(out[1])


def _flip_byte(path, offset):
    with open(path, "r+b") as f:
        f.seek(offset)
        b = f.read(1)
        f.seek(offset)
        f.write(bytes([b[0] ^ 0xFF]))


def make_svc(index, tmp=None, clock=None, **kw):
    kw.setdefault("max_batch_rows", 32)
    kw.setdefault("bucket_rungs", (8, 32))
    kw.setdefault("max_wait_ms", 1.0)
    kw.setdefault("nprobe_ladder", (4, 8))
    kw.setdefault("delta_cap", 64)
    kw.setdefault("compact_rows", 0)
    # donation off: the deterministic halves re-drive queries through
    # _snapshot_search(donate=False) directly, which must hit the same
    # (non-donating) executables warmup compiled
    kw.setdefault("donate", False)
    if tmp is not None:
        kw.setdefault("persist_dir", str(tmp))
    if clock is not None:
        kw["clock"] = clock
    return ANNService(index, k=5, start=False, **kw)


def _state_search(svc, q, nprobe=4):
    st = svc._ann_state
    delta = ((st.delta_vecs, st.delta_ids) if st.delta_rows else None)
    out = svc._snapshot_search(st, q, nprobe, delta, False)
    return np.asarray(out[0]).copy(), np.asarray(out[1]).copy()


# --------------------------------------------------------------------- #
# snapshot round trips
# --------------------------------------------------------------------- #
class TestSnapshotRoundTrip:
    def test_flat_bitwise(self, flat_index, rng, tmp_path):
        write_snapshot(str(tmp_path), flat_index, seq=1, wal_seq=0)
        idx2, dv, di, manifest = load_current(str(tmp_path))
        assert manifest["kind"] == "IVFFlatIndex"
        assert dv is None and di is None
        q = jnp.asarray(rng.standard_normal((6, 16)), jnp.float32)
        d1, i1 = _search_pair(flat_index, q)
        d2, i2 = _search_pair(idx2, q)
        assert (d1 == d2).all() and (i1 == i2).all()

    def test_pq_with_refine(self, data, rng, tmp_path):
        idx = ann.ivf_pq_build(
            data, ann.IVFPQParams(nlist=8, nprobe=4, M=4,
                                  refine_ratio=2), seed=SEED)
        write_snapshot(str(tmp_path), idx, seq=1, wal_seq=0)
        idx2, _, _, manifest = load_current(str(tmp_path))
        assert manifest["kind"] == "IVFPQIndex"
        assert idx2.vectors is not None
        assert idx2.refine_ratio == 2
        q = jnp.asarray(rng.standard_normal((4, 16)), jnp.float32)
        d1, i1 = _search_pair(idx, q)
        d2, i2 = _search_pair(idx2, q)
        assert (d1 == d2).all() and (i1 == i2).all()

    def test_sq(self, data, rng, tmp_path):
        idx = ann.ivf_sq_build(
            data, ann.IVFSQParams(nlist=8, nprobe=4), seed=SEED)
        write_snapshot(str(tmp_path), idx, seq=1, wal_seq=0)
        idx2, _, _, manifest = load_current(str(tmp_path))
        assert manifest["kind"] == "IVFSQIndex"
        assert bool(idx2.encode_residual) == bool(idx.encode_residual)
        q = jnp.asarray(rng.standard_normal((4, 16)), jnp.float32)
        d1, i1 = _search_pair(idx, q)
        d2, i2 = _search_pair(idx2, q)
        assert (d1 == d2).all() and (i1 == i2).all()

    @pytest.mark.parametrize("mmap", [False, True])
    def test_ooc_store_stays_host(self, flat_index, rng, tmp_path,
                                  mmap):
        ooc = ivf_flat_to_ooc(flat_index)
        write_snapshot(str(tmp_path), ooc, seq=1, wal_seq=0)
        idx2, _, _, manifest = load_current(str(tmp_path),
                                            mmap_store=mmap)
        assert isinstance(idx2, OocIVFFlat)
        # the loader's contract: the bulk store never touches device
        assert isinstance(idx2.store, np.ndarray)
        if mmap:
            assert isinstance(idx2.store, np.memmap)
        assert (np.asarray(idx2.store) == np.asarray(ooc.store)).all()
        # per-slot chunking: chunk index IS a slot id
        store_entry = next(e for e in manifest["arrays"]
                           if e["name"] == "store")
        assert len(store_entry["crc32s"]) == ooc.n_slots

    def test_delta_rides_along(self, flat_index, rng, tmp_path):
        dvecs = rng.standard_normal((7, 16)).astype(np.float32)
        dids = np.arange(100, 107, dtype=np.int32)
        write_snapshot(str(tmp_path), flat_index, seq=3, wal_seq=9,
                       delta=(dvecs, dids))
        idx2, dv, di, manifest = load_current(str(tmp_path))
        assert manifest["delta_rows"] == 7
        assert manifest["wal_seq"] == 9
        assert (dv == dvecs).all() and (di == dids).all()

    def test_orphan_final_dir_from_crashed_flip_is_replaced(
            self, flat_index, tmp_path):
        # a crash BETWEEN a writer's directory rename and its CURRENT
        # flip leaves an orphan snapshot dir whose seq gets re-issued;
        # the next write must replace it, not fail rename(2) forever
        write_snapshot(str(tmp_path), flat_index, seq=1, wal_seq=0)
        orphan = tmp_path / "snapshots" / "snapshot-0000000002"
        orphan.mkdir()
        (orphan / "half-written.bin").write_bytes(b"junk")
        m = write_snapshot(str(tmp_path), flat_index, seq=2, wal_seq=0)
        assert m["seq"] == 2
        assert current_manifest(str(tmp_path))["seq"] == 2
        idx2, _, _, _ = load_current(str(tmp_path))
        assert idx2 is not None

    def test_restore_depth_skips_snapshot_covered_records(
            self, flat_index, rng, tmp_path):
        from raft_tpu.persist import PersistManager

        # a crash between write_snapshot and WAL truncation leaves
        # covered records (seq <= wal_seq) in the file: replay skips
        # them and the depth gauge must too
        wp = str(tmp_path / "wal.log")
        w = WriteAheadLog(wp, 16, np.float32, fsync="always")
        for i in range(3):
            w.append(np.arange(2 * i, 2 * i + 2),
                     rng.standard_normal((2, 16)).astype(np.float32))
        w.close()
        write_snapshot(str(tmp_path), flat_index, seq=1, wal_seq=2)
        mgr = PersistManager(str(tmp_path), service="t",
                             fsync="always", snapshot_interval_s=30.0,
                             scrub_chunks=0)
        restored = mgr.restore()
        assert len(restored.wal_records) == 1
        st = mgr.stats()
        assert st["replayed_records"] == 1
        assert st["wal_records"] == 1
        mgr.close()

    def test_supersede_sweeps_and_ignores_stray_tmp(self, flat_index,
                                                    tmp_path):
        write_snapshot(str(tmp_path), flat_index, seq=1, wal_seq=0)
        snaps = tmp_path / "snapshots"
        stray = snaps / ".tmp-snapshot-0000000099"
        stray.mkdir()
        (stray / "junk.bin").write_bytes(b"junk")
        write_snapshot(str(tmp_path), flat_index, seq=2, wal_seq=0)
        names = sorted(os.listdir(snaps))
        assert names == ["snapshot-0000000002"]
        assert current_manifest(str(tmp_path))["seq"] == 2


# --------------------------------------------------------------------- #
# corruption matrix
# --------------------------------------------------------------------- #
class TestCorruptionMatrix:
    def test_manifest_bitflip(self, flat_index, tmp_path):
        write_snapshot(str(tmp_path), flat_index, seq=1, wal_seq=0)
        mpath = (tmp_path / "snapshots" / "snapshot-0000000001"
                 / "MANIFEST.json")
        _flip_byte(str(mpath), 40)
        with pytest.raises(DataCorruptionError) as e:
            load_current(str(tmp_path))
        assert "MANIFEST.json" in str(e.value)

    def test_array_payload_bitflip(self, flat_index, tmp_path):
        write_snapshot(str(tmp_path), flat_index, seq=1, wal_seq=0)
        apath = (tmp_path / "snapshots" / "snapshot-0000000001"
                 / "slot_vecs.bin")
        _flip_byte(str(apath), 100)
        with pytest.raises(DataCorruptionError) as e:
            load_current(str(tmp_path))
        err = e.value
        assert err.path.endswith("slot_vecs.bin")
        assert err.offset == 0          # chunk-granular offset
        assert err.expected_crc is not None
        assert err.actual_crc is not None
        assert err.expected_crc != err.actual_crc

    def test_current_pointer_garbage(self, flat_index, tmp_path):
        write_snapshot(str(tmp_path), flat_index, seq=1, wal_seq=0)
        (tmp_path / "CURRENT").write_text("what even is this\n")
        with pytest.raises(DataCorruptionError):
            load_current(str(tmp_path))

    def test_version_mismatch(self, flat_index, tmp_path):
        import json
        import zlib

        write_snapshot(str(tmp_path), flat_index, seq=1, wal_seq=0)
        mpath = (tmp_path / "snapshots" / "snapshot-0000000001"
                 / "MANIFEST.json")
        doc = json.loads(mpath.read_bytes())
        doc["version"] = 999
        raw = json.dumps(doc).encode()
        mpath.write_bytes(raw)
        (tmp_path / "CURRENT").write_text(
            "snapshot-0000000001 %d\n" % (zlib.crc32(raw) & 0xFFFFFFFF))
        with pytest.raises(DataCorruptionError) as e:
            load_current(str(tmp_path))
        assert "version" in str(e.value)

    def test_wal_roundtrip_and_min_seq(self, rng, tmp_path):
        wp = str(tmp_path / "wal.log")
        w = WriteAheadLog(wp, 16, np.float32, fsync="always")
        v1 = rng.standard_normal((3, 16)).astype(np.float32)
        v2 = rng.standard_normal((2, 16)).astype(np.float32)
        assert w.append(np.arange(3), v1) == 1
        assert w.append(np.arange(3, 5), v2) == 2
        w.close()
        recs, info = replay_wal(wp)
        assert [s for s, _, _ in recs] == [1, 2]
        assert (recs[0][2] == v1).all() and (recs[1][2] == v2).all()
        assert info["total_records"] == 2 and not info["torn"]
        recs, info = replay_wal(wp, min_seq=1)
        assert [s for s, _, _ in recs] == [2]
        assert info["last_seq"] == 2

    def test_wal_torn_tail_tolerated(self, rng, tmp_path):
        wp = str(tmp_path / "wal.log")
        w = WriteAheadLog(wp, 8, np.float32, fsync="always")
        w.append(np.arange(2), rng.standard_normal((2, 8)).astype(
            np.float32))
        w.append(np.arange(2, 4), rng.standard_normal((2, 8)).astype(
            np.float32))
        w.close()
        os.truncate(wp, os.path.getsize(wp) - 5)   # tear the tail
        recs, info = replay_wal(wp)
        assert info["torn"]
        assert [s for s, _, _ in recs] == [1]
        # truncating to valid_end + re-opening appends cleanly
        os.truncate(wp, info["valid_end"])
        w2 = WriteAheadLog(wp, 8, np.float32, fsync="always",
                           start_seq=info["last_seq"])
        assert w2.append(np.arange(4, 6), rng.standard_normal(
            (2, 8)).astype(np.float32)) == 2
        w2.close()
        recs, info = replay_wal(wp)
        assert [s for s, _, _ in recs] == [1, 2] and not info["torn"]

    def test_wal_interior_bitflip_raises(self, rng, tmp_path):
        wp = str(tmp_path / "wal.log")
        w = WriteAheadLog(wp, 8, np.float32, fsync="always")
        w.append(np.arange(2), rng.standard_normal((2, 8)).astype(
            np.float32))
        end_first = w.tell()
        w.append(np.arange(2, 4), rng.standard_normal((2, 8)).astype(
            np.float32))
        w.close()
        # flip a payload byte INSIDE the first record (interior)
        _flip_byte(wp, end_first - 3)
        with pytest.raises(DataCorruptionError) as e:
            replay_wal(wp)
        err = e.value
        assert err.path == wp and err.offset is not None
        assert err.expected_crc != err.actual_crc

    def test_wal_bad_magic_raises(self, rng, tmp_path):
        wp = str(tmp_path / "wal.log")
        w = WriteAheadLog(wp, 8, np.float32, fsync="always")
        rec_start = w.tell()
        w.append(np.arange(2), rng.standard_normal((2, 8)).astype(
            np.float32))
        w.append(np.arange(2, 4), rng.standard_normal((2, 8)).astype(
            np.float32))
        w.close()
        _flip_byte(wp, rec_start)       # magic of record 1
        with pytest.raises(DataCorruptionError) as e:
            replay_wal(wp)
        assert "magic" in str(e.value)

    def test_wal_header_length_bitflip_is_corruption(self, rng,
                                                     tmp_path):
        # a flipped rows field must NOT reclassify as a torn tail and
        # silently drop the record — the header CRC catches it
        wp = str(tmp_path / "wal.log")
        w = WriteAheadLog(wp, 8, np.float32, fsync="always")
        rec_start = w.tell()
        w.append(np.arange(2), rng.standard_normal((2, 8)).astype(
            np.float32))
        w.close()
        _flip_byte(wp, rec_start + 12)  # rows u32 inside the header
        with pytest.raises(DataCorruptionError):
            replay_wal(wp)

    def test_wal_truncate_through(self, rng, tmp_path):
        wp = str(tmp_path / "wal.log")
        w = WriteAheadLog(wp, 8, np.float32, fsync="always")
        for i in range(4):
            w.append(np.arange(2 * i, 2 * i + 2),
                     rng.standard_normal((2, 8)).astype(np.float32))
        assert w.truncate_through(2) == 2
        w.close()
        recs, info = replay_wal(wp)
        assert [s for s, _, _ in recs] == [3, 4]

    def test_wal_bad_fsync_policy(self, tmp_path):
        with pytest.raises(LogicError):
            WriteAheadLog(str(tmp_path / "w.log"), 8, np.float32,
                          fsync="sometimes")

    def test_error_fields(self):
        e = DataCorruptionError("boom", "/x/y.bin", offset=64,
                                expected_crc=1, actual_crc=2)
        assert e.path == "/x/y.bin" and e.offset == 64
        assert "0x00000001" in str(e) and "@ byte 64" in str(e)


# --------------------------------------------------------------------- #
# ANNService integration
# --------------------------------------------------------------------- #
class TestServicePersistence:
    def test_insert_journaled_before_ack(self, flat_index, rng,
                                         tmp_path):
        svc = make_svc(flat_index, tmp_path)
        try:
            svc.insert(np.arange(1000, 1004),
                       rng.standard_normal((4, 16)).astype(np.float32))
            ps = svc.stats()["persist"]
            assert ps["wal_records"] == 1
            assert ps["wal_seq"] == 1
            assert svc._ann_state.wal_seq == 1
        finally:
            svc.close()

    def test_wal_failure_fails_insert_without_state_change(
            self, flat_index, rng, tmp_path):
        svc = make_svc(flat_index, tmp_path)
        try:
            def boom(ids, vecs):
                raise OSError("disk gone")

            svc._persist.wal_append = boom
            with pytest.raises(OSError):
                svc.insert(np.arange(1000, 1004),
                           rng.standard_normal((4, 16)).astype(
                               np.float32))
            # NOT acknowledged, NOT applied
            assert svc.delta_rows == 0
            assert svc._delta_count == 0
        finally:
            svc.close(snapshot=False)

    def test_interval_snapshot_truncates_wal(self, flat_index, rng,
                                             tmp_path):
        clock = FakeClock()
        svc = make_svc(flat_index, tmp_path, clock=clock,
                       snapshot_interval_s=10.0)
        try:
            svc.insert(np.arange(1000, 1008),
                       rng.standard_normal((8, 16)).astype(np.float32))
            svc.worker.run_maintenance()
            ps = svc.stats()["persist"]
            assert ps["snapshot_seq"] == 1     # bootstrap only
            assert ps["wal_records"] == 1 and ps["dirty"]
            clock.advance(11.0)
            svc.worker.run_maintenance()
            ps = svc.stats()["persist"]
            assert ps["snapshot_seq"] == 2
            assert ps["wal_records"] == 0 and not ps["dirty"]
            # the snapshot carries the delta rows the WAL dropped
            assert current_manifest(str(tmp_path))["delta_rows"] == 8
        finally:
            svc.close(snapshot=False)

    def test_crash_restart_bitwise_and_no_loss(self, flat_index, rng,
                                               tmp_path):
        svc = make_svc(flat_index, tmp_path)
        new_ids = np.arange(2000, 2012)
        svc.insert(new_ids,
                   rng.standard_normal((12, 16)).astype(np.float32))
        q = jnp.asarray(rng.standard_normal((6, 16)), jnp.float32)
        ref = _state_search(svc, q)
        svc.close(snapshot=False)           # simulated process death
        svc2 = make_svc(None, tmp_path)     # rebuild from dir alone
        try:
            ps = svc2.stats()["persist"]
            assert ps["replayed_records"] == 1
            got = _state_search(svc2, q)
            assert (got[0] == ref[0]).all() and (got[1] == ref[1]).all()
            _, gt_ids = svc2.ground_truth_store()
            assert set(int(x) for x in new_ids) <= set(
                int(x) for x in gt_ids)
        finally:
            svc2.close()

    def test_restored_service_zero_post_warmup_compiles(
            self, flat_index, rng, tmp_path):
        svc = make_svc(flat_index, tmp_path)
        svc.insert(np.arange(3000, 3004),
                   rng.standard_normal((4, 16)).astype(np.float32))
        svc.close(snapshot=False)
        svc2 = make_svc(None, tmp_path)
        try:
            svc2.warmup()
            # a bucket-rung shape: dispatch always pads to one, and
            # warmup only ever warms the rungs
            q = jnp.asarray(rng.standard_normal((8, 16)), jnp.float32)
            m0 = _total_misses()
            for cell in (4, 8):
                _state_search(svc2, q, nprobe=cell)
            assert _total_misses() - m0 == 0
        finally:
            svc2.close()

    def test_clean_close_leaves_empty_wal(self, flat_index, rng,
                                          tmp_path):
        svc = make_svc(flat_index, tmp_path)
        svc.insert(np.arange(4000, 4006),
                   rng.standard_normal((6, 16)).astype(np.float32))
        svc.close()                         # final snapshot
        svc2 = make_svc(None, tmp_path)
        try:
            ps = svc2.stats()["persist"]
            assert ps["replayed_records"] == 0
            assert ps["wal_records"] == 0
            assert svc2.delta_rows == 6     # via the snapshot instead
        finally:
            svc2.close()

    def test_restore_overflow_folds_into_index(self, flat_index, rng,
                                               tmp_path):
        svc = make_svc(flat_index, tmp_path, delta_cap=32,
                       snapshot_interval_s=1e9)
        ids_a = np.arange(5000, 5032)
        svc.insert(ids_a,
                   rng.standard_normal((32, 16)).astype(np.float32))
        svc.compact()       # delta -> index; WAL keeps the record
        ids_b = np.arange(6000, 6020)
        svc.insert(ids_b,
                   rng.standard_normal((20, 16)).astype(np.float32))
        q = jnp.asarray(rng.standard_normal((6, 16)), jnp.float32)
        ref = _state_search(svc, q)
        svc.close(snapshot=False)
        svc2 = make_svc(None, tmp_path, delta_cap=32)
        try:
            # replay had to fold record A into the index to make room
            assert svc2.stats()["persist"]["replayed_records"] == 2
            assert svc2.delta_rows == 20
            got = _state_search(svc2, q)
            assert (got[0] == ref[0]).all() and (got[1] == ref[1]).all()
            _, gt_ids = svc2.ground_truth_store()
            have = set(int(x) for x in gt_ids)
            assert set(int(x) for x in ids_a) <= have
            assert set(int(x) for x in ids_b) <= have
        finally:
            svc2.close()

    def test_snapshot_delta_exceeding_cap_raises(self, flat_index,
                                                 rng, tmp_path):
        svc = make_svc(flat_index, tmp_path, delta_cap=64)
        svc.insert(np.arange(7000, 7040),
                   rng.standard_normal((40, 16)).astype(np.float32))
        svc.close()     # snapshot holds 40 delta rows
        with pytest.raises(LogicError):
            make_svc(None, tmp_path, delta_cap=16)

    def test_dim_mismatch_restore_raises(self, flat_index, rng,
                                         tmp_path):
        svc = make_svc(flat_index, tmp_path)
        svc.close()
        other = ann.ivf_flat_build(
            jnp.asarray(rng.standard_normal((300, 8)), jnp.float32),
            ann.IVFFlatParams(nlist=4, nprobe=2), seed=SEED)
        with pytest.raises(LogicError):
            make_svc(other, tmp_path)

    def test_persist_knobs_require_persist_dir(self, flat_index):
        with pytest.raises(LogicError):
            make_svc(flat_index, None, persist_fsync="always")

    def test_bad_fsync_policy_at_construction(self, flat_index,
                                              tmp_path):
        with pytest.raises(LogicError):
            make_svc(flat_index, tmp_path, persist_fsync="sometimes")

    def test_index_none_without_state_raises(self, tmp_path):
        with pytest.raises(LogicError):
            make_svc(None, tmp_path)


# --------------------------------------------------------------------- #
# scrubbing
# --------------------------------------------------------------------- #
class TestScrubbing:
    def _ooc_svc(self, flat_index, tmp_path, **kw):
        store_b = int(np.asarray(flat_index.slot_vecs).nbytes)
        return make_svc(flat_index, tmp_path, ooc=True,
                        device_budget_bytes=max(store_b // 2, 4096),
                        scrub_chunks=10_000,
                        snapshot_interval_s=1e9, **kw)

    def test_poisoned_slot_quarantined_and_rebuilt(self, flat_index,
                                                   rng, tmp_path):
        from raft_tpu.core import flight

        svc = self._ooc_svc(flat_index, tmp_path)
        try:
            store = svc._ooc.store
            orig = store[2].copy()
            store[2] = 123.0                      # poison
            boxes0 = len(flight.default_recorder().blackboxes())
            svc.worker.run_maintenance()          # one full scrub cycle
            ps = svc.stats()["persist"]
            assert ps["last_scrub"]["errors"] >= 1
            assert ps["last_scrub"]["rebuilt"] == 1
            assert ps["last_scrub"]["last_error"]["repaired"]
            # repaired damage does NOT latch corruption
            assert not ps["corruption_detected"]
            assert (store[2] == orig).all()
            assert len(flight.default_recorder().blackboxes()) \
                > boxes0
        finally:
            svc.close(snapshot=False)

    def test_snapshot_file_corruption_detected(self, flat_index,
                                               tmp_path):
        svc = make_svc(flat_index, tmp_path, scrub_chunks=10_000)
        try:
            name = "snapshot-%010d" % svc._persist.snapshot_seq
            apath = os.path.join(str(tmp_path), "snapshots", name,
                                 "slot_vecs.bin")
            _flip_byte(apath, 10)
            svc.worker.run_maintenance()
            ps = svc.stats()["persist"]
            assert ps["corruption_detected"]
            assert ps["last_scrub"]["last_error"]["where"] \
                == "snapshot-file"
        finally:
            svc.close(snapshot=False)

    def test_session_health_fails_on_corruption(self, flat_index,
                                                tmp_path):
        from raft_tpu.session import Session

        with Session() as session:
            svc = session.serve(kind="ann", index=flat_index, k=5,
                                persist_dir=str(tmp_path),
                                scrub_chunks=10_000,
                                max_batch_rows=32,
                                bucket_rungs=(8, 32), delta_cap=64,
                                compact_rows=0, nprobe_ladder=(4, 8))
            assert session.health_check()["ok"]
            name = "snapshot-%010d" % svc._persist.snapshot_seq
            _flip_byte(os.path.join(str(tmp_path), "snapshots", name,
                                    "slot_vecs.bin"), 10)
            svc.worker.run_maintenance()
            report = session.health_check()
            assert not report["ok"]
            assert report["services"][svc.name]["persist"][
                "corruption_detected"]

    def test_scrub_disabled(self, flat_index, tmp_path):
        svc = make_svc(flat_index, tmp_path, scrub_chunks=0)
        try:
            svc.worker.run_maintenance()
            assert svc.stats()["persist"]["last_scrub"]["checked"] == 0
        finally:
            svc.close(snapshot=False)


# --------------------------------------------------------------------- #
# ooc restore + chaos + style ban
# --------------------------------------------------------------------- #
class TestEndToEnd:
    def test_ooc_crash_restart_mmap(self, flat_index, rng, tmp_path):
        store_b = int(np.asarray(flat_index.slot_vecs).nbytes)
        kw = dict(ooc=True, device_budget_bytes=max(store_b // 2,
                                                    4096))
        svc = make_svc(flat_index, tmp_path, **kw)
        svc.insert(np.arange(8000, 8008),
                   rng.standard_normal((8, 16)).astype(np.float32))
        q = jnp.asarray(rng.standard_normal((4, 16)), jnp.float32)
        ref = _state_search(svc, q)
        svc.close(snapshot=False)
        svc2 = make_svc(None, tmp_path, persist_mmap=True, **kw)
        try:
            assert isinstance(svc2._ooc, OocIVFFlat)
            assert isinstance(svc2._ooc.store, np.ndarray)
            got = _state_search(svc2, q)
            assert (got[0] == ref[0]).all() and (got[1] == ref[1]).all()
        finally:
            svc2.close(snapshot=False)

    def test_loadgen_crash_restart_scenario(self, tmp_path):
        from tools.loadgen import run_crash_restart

        report = run_crash_restart(
            str(tmp_path), index_rows=2500, dim=16, k=5, seed=SEED,
            duration=1.5, concurrency=2, rows=4, nlist=16, clusters=8)
        assert report["crash_ok"], report
        assert report["no_insert_loss"]
        assert report["bit_identical"]
        assert report["wal_replayed_records"] > 0
        assert report["post_restore_compiles"] == 0

    def test_serialization_ban_selftest(self):
        import importlib.util

        spec = importlib.util.spec_from_file_location(
            "style_check", os.path.join(
                os.path.dirname(os.path.dirname(
                    os.path.abspath(__file__))),
                "ci", "style_check.py"))
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        assert mod._selftest_persist_io() == 0
