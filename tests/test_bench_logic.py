"""Pure-logic tests for bench.py's reporting machinery.

The bench is the round's perf evidence; its headline assembly,
device-peak detection, and honest-status notes must not regress.  Only
the JAX-free functions are under test here — the ones bench.py's parent
process (which never imports JAX by design) relies on.  The test
*session* still has JAX loaded via conftest.py.
"""

import bench


class TestChipPeakFlops:
    def test_v5e_from_device_kind(self):
        peak, gen = bench.chip_peak_flops("TPU v5 lite", "tpu")
        assert gen == "v5e" and peak == bench.TPU_PEAK_BF16["v5e"]

    def test_v5p(self):
        peak, gen = bench.chip_peak_flops("TPU v5p", "tpu")
        assert gen == "v5p"

    def test_v6_trillium_maps_to_v6e_not_v5e(self):
        _, gen = bench.chip_peak_flops("TPU v6 lite", "tpu")
        assert gen == "v6e"

    def test_cpu_unrecognized(self):
        peak, gen = bench.chip_peak_flops("", "cpu")
        assert peak is None and gen is None

    def test_env_hint_only_for_non_cpu(self, monkeypatch):
        monkeypatch.setenv("PALLAS_AXON_TPU_GEN", "v5e")
        peak, gen = bench.chip_peak_flops("mystery-accel", "tpu")
        assert gen == "v5e(env)"
        peak, gen = bench.chip_peak_flops("", "cpu")
        assert peak is None


class TestAssemble:
    def test_accelerator_knn_beats_everything(self):
        tpu = {"knn_1m": {"qps": 5000.0, "n_index": 1_000_000},
               "pairwise_2k": {"gpairs_per_sec": 10.0,
                               "shape": [2048, 2048, 128]}}
        cpu = {"knn_100k": {"qps": 900.0, "n_index": 100_000}}
        out = bench.assemble(tpu, cpu)
        assert out["metric"] == "knn_qps_1M_128d_k100"
        assert out["value"] == 5000.0
        assert out["vs_baseline"] == round(5000.0 / 20000.0, 4)
        assert out["detail"]["cpu_fallback"] == cpu

    def test_pallas_rung_supersedes_when_faster(self):
        tpu = {"knn_1m": {"qps": 5000.0, "n_index": 1_000_000},
               "knn_1m_pallas": {"qps": 7000.0, "n_index": 1_000_000}}
        out = bench.assemble(tpu, {})
        assert out["value"] == 7000.0

    def test_pselect_rung_can_carry_the_100k_headline(self):
        tpu = {"knn_100k": {"qps": 9_000.0, "n_index": 100_000},
               "knn_100k_pselect": {"qps": 12_000.0, "n_index": 100_000}}
        out = bench.assemble(tpu, {})
        assert out["value"] == 12_000.0

    def test_100k_rung_scales_vs_baseline_by_index_size(self):
        tpu = {"knn_100k": {"qps": 10_000.0, "n_index": 100_000}}
        out = bench.assemble(tpu, {})
        assert out["metric"] == "knn_qps_100k_128d_k100"
        # 10k QPS at 100k index = 1k QPS-equivalent at 1M
        assert out["vs_baseline"] == round(10_000.0 * 0.1 / 20000.0, 4)

    def test_pairwise_fallback_normalizes_dim(self):
        tpu = {"pairwise_1k": {"gpairs_per_sec": 100.0,
                               "shape": [1024, 1024, 64]}}
        out = bench.assemble(tpu, {})
        assert out["unit"] == "Gpairs/s"
        # d=64 halves the FLOP-equivalent rate vs the d=128 constant
        assert out["vs_baseline"] == round(100.0 * 0.5 / 50.0, 4)

    def test_cpu_fallback_when_no_accelerator_rung(self):
        cpu = {"knn_100k": {"qps": 999.0, "n_index": 100_000}}
        out = bench.assemble(None, cpu)
        assert out["metric"].endswith("_cpu_fallback")

    def test_cpu_pairwise_fallback_when_no_knn_rung_fit(self):
        """A short budget can bank CPU pairwise but not CPU kNN; the
        report must carry the pairwise number, not a flat zero."""
        cpu = {"pairwise_1k": {"gpairs_per_sec": 0.25,
                               "shape": [1024, 1024, 64]}}
        out = bench.assemble(None, cpu)
        assert out["metric"] == "pairwise_l2_gpairs_1024x64_cpu_fallback"
        assert out["value"] == 0.25
        # r5: CPU-vs-A100 is suppressed as cross-hardware noise; the
        # note says so explicitly (r4 verdict item 5)
        assert out["vs_baseline"] == 0.0
        assert "suppressed" in out["vs_baseline_note"]

    def test_cpu_fallback_headline_notes_suppression(self):
        cpu = {"knn_100k": {"qps": 100.0, "n_index": 100_000}}
        out = bench.assemble(None, cpu)
        assert out["metric"].endswith("_cpu_fallback")
        assert out["vs_baseline"] == 0.0
        assert "vs_baseline_note" in out

    def test_accelerator_headline_keeps_vs_baseline(self):
        tpu = {"knn_1m": {"qps": 5000.0, "n_index": 1_000_000}}
        out = bench.assemble(tpu, {})
        assert out["vs_baseline"] > 0
        assert "vs_baseline_note" not in out

    def test_zero_when_nothing_banked(self):
        out = bench.assemble({}, {})
        assert out["value"] == 0.0 and out["vs_baseline"] == 0.0


class _FakeProc:
    def __init__(self, rc):
        self._rc = rc

    def poll(self):
        return self._rc


class _FakeChild:
    def __init__(self, rc=None, state=None, stderr_tail="", t_spawn=None):
        import time

        self.proc = _FakeProc(rc)
        self.state = state or {}
        self.stderr_tail = stderr_tail
        self.t_spawn = t_spawn or time.time()


class TestTpuAttemptNote:
    def test_child_died_before_init(self):
        note = bench._tpu_attempt_note(_FakeChild(rc=1), deadline=0)
        assert note["status"] == "child_died_rc=1_before_init"

    def test_killed_at_deadline_during_init(self):
        import time

        child = _FakeChild(rc=None, state={
            "init_log": [{"t": 1.0, "event": "backend_init_start"}]})
        note = bench._tpu_attempt_note(child, deadline=time.time() - 5)
        assert note["status"] == "killed_at_deadline_during_backend_init"
        assert note["stuck_after"] == "backend_init_start"

    def test_init_ok_but_no_rung(self):
        child = _FakeChild(rc=None, state={
            "init": {"is_tpu": True},
            "errors": {"knn_100k": "Traceback..."}})
        note = bench._tpu_attempt_note(child, deadline=0)
        assert note["status"] == "init_ok_but_no_accelerator_rung_completed"
        assert "errors" in note

    def test_non_accelerator_backend(self):
        child = _FakeChild(rc=None, state={"init": {"is_tpu": False}})
        note = bench._tpu_attempt_note(child, deadline=0)
        assert note["status"] == "init_on_non_accelerator_backend"

    def test_stderr_tail_preserved(self):
        note = bench._tpu_attempt_note(
            _FakeChild(rc=2, stderr_tail="boom"), deadline=0)
        assert note["stderr_tail"] == "boom"

    def test_stalled_attempt_note_shape(self):
        """The stall watchdog relabels the note and keeps the init log
        (the evidence that distinguishes 'hung after devices_ready'
        from 'never connected')."""
        import time

        child = _FakeChild(rc=None, state={
            "init_log": [{"t": 0.2, "event": "devices_ready"}]})
        note = bench._tpu_attempt_note(child, deadline=time.time() + 999)
        # parent_main overrides status for stalled kills; the raw note
        # must still carry where the child was stuck
        note["status"] = "killed_stalled_no_progress"
        assert note["stuck_after"] == "devices_ready"
        assert note["init_log"]


class TestInitRetry:
    """The TPU child must survive a flapping endpoint: UNAVAILABLE at
    t=0 with budget remaining retries instead of dying (r4 observed the
    endpoint down for ~25 min then healthy within one budget)."""

    def test_retries_until_devices_answer(self, monkeypatch):
        import time as _time

        import jax

        calls = {"n": 0}
        real_devices = jax.devices

        def flaky_devices():
            calls["n"] += 1
            if calls["n"] < 3:
                raise RuntimeError("UNAVAILABLE: backend setup error")
            return real_devices()

        monkeypatch.setattr(jax, "devices", flaky_devices)
        monkeypatch.setattr(_time, "sleep", lambda s: None)
        monkeypatch.setenv("RAFT_TPU_BENCH_DEADLINE",
                           repr(_time.time() + 600))
        monkeypatch.setenv("RAFT_TPU_BENCH_CPU", "1")
        out = bench._rung_init()
        # two failures + the successful third call (later init steps may
        # consult jax.devices again)
        assert calls["n"] >= 3
        assert out["platform"] == "cpu"

    def test_gives_up_near_deadline(self, monkeypatch):
        import time as _time

        import jax

        def dead_devices():
            raise RuntimeError("UNAVAILABLE: backend setup error")

        monkeypatch.setattr(jax, "devices", dead_devices)
        monkeypatch.setenv("RAFT_TPU_BENCH_DEADLINE",
                           repr(_time.time() + 60))  # < 120 s margin
        monkeypatch.setenv("RAFT_TPU_BENCH_CPU", "1")
        import pytest

        with pytest.raises(RuntimeError, match="UNAVAILABLE"):
            bench._rung_init()


class TestPartitionAttemptStates:
    """Cross-attempt rung banking must partition by the backend that
    measured each attempt (r4 review: a wedged-endpoint respawn that
    falls back to CPU must not relabel TPU rungs or smuggle CPU rungs
    under accelerator names)."""

    def test_tpu_then_cpu_fallback_attempt(self):
        tpu_attempt = {"init": {"is_tpu": True},
                       "linalg_bundle": {"gpairs_per_sec": 0,
                                         "gemm_tflops": 95.0,
                                         "qps": None}}
        cpu_attempt = {"init": {"is_tpu": False},
                       "knn_100k": {"qps": 500.0}}
        accel, fb, is_accel = bench._partition_attempt_states(
            [tpu_attempt, cpu_attempt])
        assert is_accel
        assert "linalg_bundle" in accel and "knn_100k" not in accel
        assert accel["init"]["is_tpu"]           # later init didn't clobber
        assert fb["knn_100k"]["qps"] == 500.0

    def test_cpu_then_tpu_attempt(self):
        cpu_attempt = {"init": {"is_tpu": False},
                       "knn_100k": {"qps": 500.0}}
        tpu_attempt = {"init": {"is_tpu": True},
                       "knn_100k": {"qps": 5000.0}}
        accel, fb, is_accel = bench._partition_attempt_states(
            [cpu_attempt, tpu_attempt])
        assert is_accel
        assert accel["knn_100k"]["qps"] == 5000.0
        assert fb["knn_100k"]["qps"] == 500.0

    def test_all_cpu(self):
        accel, fb, is_accel = bench._partition_attempt_states(
            [{"init": {"is_tpu": False}, "knn_100k": {"qps": 10.0}}])
        assert not is_accel and not accel
        assert fb["knn_100k"]["qps"] == 10.0


class TestEnvPins:
    def test_set_and_restore(self, monkeypatch):
        import os
        monkeypatch.setenv("RAFT_TPU_SELECT_IMPL", "chunked")
        monkeypatch.delenv("RAFT_TPU_TILE_MERGE", raising=False)
        with bench._env_pins({"RAFT_TPU_SELECT_IMPL": "pallas",
                              "RAFT_TPU_TILE_MERGE": "direct",
                              "RAFT_TPU_FUSED_KNN_IMPL": None}):
            assert os.environ["RAFT_TPU_SELECT_IMPL"] == "pallas"
            assert os.environ["RAFT_TPU_TILE_MERGE"] == "direct"
            assert "RAFT_TPU_FUSED_KNN_IMPL" not in os.environ
        assert os.environ["RAFT_TPU_SELECT_IMPL"] == "chunked"
        assert "RAFT_TPU_TILE_MERGE" not in os.environ

    def test_restores_on_exception(self, monkeypatch):
        import os
        monkeypatch.setenv("RAFT_TPU_SELECT_IMPL", "topk")
        try:
            with bench._env_pins({"RAFT_TPU_SELECT_IMPL": "approx"}):
                raise RuntimeError("boom")
        except RuntimeError:
            pass
        assert os.environ["RAFT_TPU_SELECT_IMPL"] == "topk"


class TestTwophase1mGate:
    """knn_1m_twophase runs ONLY after the two-phase kernel proves
    correct and fastest at 100k (r5); wrong-but-fast or unvalidated
    states must skip."""

    def test_skips_when_not_validated(self):
        out = bench._bench_knn_twophase_1m(
            {"pallas_check": {"twophase_qps_100k": 9999.0,
                              "xla_qps_100k": 1.0}})
        assert out["status"] == "skipped_twophase_not_validated"

    def test_skips_when_not_faster(self):
        out = bench._bench_knn_twophase_1m(
            {"pallas_check": {"twophase_dist_close": True,
                              "twophase_idx_match": True,
                              "twophase_qps_100k": 10.0,
                              "xla_qps_100k": 20.0,
                              "pallas_qps_100k": 1.0}})
        assert out["status"] == "skipped_twophase_not_faster"

    def test_skips_on_missing_check(self):
        out = bench._bench_knn_twophase_1m({})
        assert out["status"].startswith("skipped")

    def test_assemble_prefers_best_1m_rung(self):
        tpu = {"knn_1m": {"qps": 100.0, "n_index": 1_000_000},
               "knn_1m_twophase": {"qps": 250.0, "n_index": 1_000_000}}
        out = bench.assemble(tpu, {})
        assert out["value"] == 250.0
