"""Handle threading across the primitive surface (handle.hpp:49 parity).

The reference passes ``handle_t&`` to *every* primitive; round 3 only
threaded knn/ann/pairwise/spectral/hierarchy.  ``takes_handle``
(core/handle.py) extends the contract across linalg/matrix/stats/
sparse-op: each call with ``handle=`` must record its outputs on the
handle's main stream so ``sync_stream`` covers them.
"""

import numpy as np
import jax.numpy as jnp
import pytest

from raft_tpu import Handle
from raft_tpu.sparse.formats import COO


def _recorded(handle, call):
    before = len(handle.get_stream()._pending)
    out = call(handle)
    assert len(handle.get_stream()._pending) > before, call
    handle.sync_stream()
    return out


CASES = {
    "linalg.gemm": lambda h: __import__("raft_tpu.linalg", fromlist=["gemm"])
    .gemm(jnp.ones((4, 3)), jnp.ones((3, 5)), handle=h),
    "linalg.eig_dc": lambda h: __import__("raft_tpu.linalg", fromlist=["x"])
    .eig_dc(jnp.eye(4), handle=h),
    "linalg.row_norm": lambda h: __import__("raft_tpu.linalg", fromlist=["x"])
    .row_norm(jnp.ones((4, 3)), handle=h),
    "linalg.svd_qr": lambda h: __import__("raft_tpu.linalg", fromlist=["x"])
    .svd_qr(jnp.ones((4, 3)), handle=h),
    "linalg.transpose": lambda h: __import__("raft_tpu.linalg", fromlist=["x"])
    .transpose(jnp.ones((4, 3)), handle=h),
    "linalg.add": lambda h: __import__("raft_tpu.linalg", fromlist=["x"])
    .add(jnp.ones(3), jnp.ones(3), handle=h),
    "matrix.slice": lambda h: __import__("raft_tpu.matrix", fromlist=["x"])
    .slice_matrix(jnp.ones((6, 6)), 1, 1, 3, 3, handle=h),
    "matrix.math.power": lambda h: __import__("raft_tpu.matrix", fromlist=["x"])
    .power(jnp.ones((2, 2)), handle=h),
    "stats.mean": lambda h: __import__("raft_tpu.stats", fromlist=["x"])
    .mean(jnp.ones((4, 3)), handle=h),
    "sparse.coo_sort": lambda h: __import__(
        "raft_tpu.sparse.op", fromlist=["x"]).coo_sort(
        COO(jnp.asarray([1, 0], jnp.int32), jnp.asarray([0, 1], jnp.int32),
            jnp.asarray([1.0, 2.0], jnp.float32), shape=(2, 2)), handle=h),
}


@pytest.mark.parametrize("name", sorted(CASES))
def test_records_on_handle(name):
    h = Handle()
    _recorded(h, CASES[name])


def test_sync_stream_clears_pending():
    from raft_tpu.linalg import gemm

    h = Handle()
    gemm(jnp.ones((4, 3)), jnp.ones((3, 5)), handle=h)
    h.sync_stream()
    assert not h.get_stream()._pending


def test_decorated_result_unchanged():
    from raft_tpu.linalg import gemm

    h = Handle()
    a = jnp.asarray(np.arange(12, dtype=np.float32).reshape(4, 3))
    b = jnp.asarray(np.arange(15, dtype=np.float32).reshape(3, 5))
    np.testing.assert_allclose(np.asarray(gemm(a, b, handle=h)),
                               np.asarray(a) @ np.asarray(b), rtol=1e-6)
