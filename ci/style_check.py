"""Style/hygiene checks (reference ci/checks/style.sh: flake8 +
clang-format + include_checker; no linter is baked into this image, so
the equivalent checks are implemented with the stdlib).

Checks, per Python source file:
- parses (ast) — the flake8 E9 class;
- no tabs in indentation, no trailing whitespace, newline at EOF;
- line length <= 100 (``MAX_LEN``; wider than flake8's 88 to match the
  reference's .clang-format 100-column limit);
- no `from raft_tpu.… import *` (include hygiene: the reference's
  include_checker.py bans quote-style drift; the analog here is
  wildcard imports, which hide the dependency surface);
- no ad-hoc wall-clock timing inside ``raft_tpu/``
  (``time.time()`` / ``time.perf_counter()`` / ``time.monotonic()``):
  primitive timing must go through the profiler/metrics API
  (docs/OBSERVABILITY.md) so every number lands in the registry and
  the snapshot artifacts.  The metrics/profiler modules themselves are
  allowlisted (they ARE the timing implementation); ``time.sleep`` is
  not timing and stays legal.  bench.py / tools / tests are outside
  the library and free to time however they like.
- no raw ``threading.Thread(`` inside ``raft_tpu/`` outside
  ``raft_tpu/serve/`` and the resilience/profiler allowlist:
  daemon-thread hygiene (naming, lifecycle, drain-on-close) lives in
  one place — the serve worker (docs/SERVING.md) — plus the comms
  watchdog that predates it.  New background work should go through a
  :class:`raft_tpu.serve.scheduler.ServeWorker` or the resilience
  watchdog, not ad-hoc threads that nothing drains at teardown.
- no ``np.asarray(`` / ``np.array(`` inside ``raft_tpu/comms/`` hot
  paths: a payload bounced through host numpy silently re-introduces
  the host staging the zero-copy p2p path removed
  (docs/ZERO_COPY.md) — device arrays must stay device arrays end to
  end.  ``selftest.py`` / ``faults.py`` are allowlisted (test batteries
  read results on host by design), and a line carrying a
  ``comms-host-ok`` marker comment is exempt (device *handles* like
  mesh construction, and the deliberately-counted ``staging="host"``
  baseline).
- no direct ``jax.jit`` inside ``raft_tpu/spatial/mnmg_knn.py``: every
  SPMD program the sharded serving layer dispatches must compile
  through :func:`raft_tpu.core.profiler.profiled_jit` (and donating
  twins), or serve ``warmup()``'s zero-steady-state-compiles proof and
  loadgen's ``post_warmup_compiles`` check are blind to sharded
  compiles (docs/SERVING.md "Sharded serving").  A deliberate
  exception carries an ``mnmg-jit-ok`` marker comment on the line.
- no ``jax.device_put`` inside the out-of-core tier's path
  (``raft_tpu/spatial/ooc.py`` / ``raft_tpu/mr/tile_pool.py``): the
  tier exists so the full index NEVER lands on device
  (docs/ZERO_COPY.md §6) — a whole-store ``device_put`` silently
  un-does it.  The per-tile stream and the budget-bounded hot-set
  materialization are the only legitimate transfer sites; each carries
  an ``ooc-resident-ok`` marker comment (mirrors the comms
  ``np.asarray`` ban).
- no silent ``except Exception`` inside ``raft_tpu/serve/``: a serving
  failure must go SOMEWHERE a rider or an operator can see it — the
  handler must relay to rider futures (``_set_exception``), feed the
  metrics registry (``.inc`` / ``.observe`` / ``.record_failure``), or
  re-``raise``; an audited silent path carries a ``serve-exc-ok``
  marker comment on the ``except`` line (docs/FAULT_MODEL.md "Serving
  failure model" — the self-healing story dies the day a failure is
  swallowed invisibly).
- every ``ServiceOverloadError(...)`` raised inside ``raft_tpu/serve/``
  must carry an explicit ``retry_after_s=`` keyword: the overload/
  unavailable taxonomy promises callers a uniform back-off hint
  (docs/SERVING.md "Traffic shaping"), and a bare
  ``ServiceOverloadError(msg, depth, cap)`` silently hands back the
  0.0 default — a shed site with genuinely no estimate marks the line
  ``shed-hint-ok``.
- no jax reachable from the ops plane
  (``raft_tpu/serve/opsplane.py`` / ``sentinel.py``): every ops HTTP
  handler and sentinel rule reads host-side snapshots only — a jax
  call on a scrape path could compile, block the worker loop, or
  perturb the zero-post-warmup-compiles invariant
  (docs/OBSERVABILITY.md "Ops plane").  Total ban per module
  (imports, from-imports, any ``jax`` name use); a deliberate
  exception marks its line ``ops-jax-ok``.  (The ops server's daemon
  threads are legal by construction: the module lives in
  ``raft_tpu/serve/``, the raw-``threading.Thread`` ban's allowlisted
  home.)
- metric docs drift: every ``raft_tpu_*`` metric name registered in
  ``raft_tpu/`` (a string literal inside a
  counter/gauge/timer/labeled registry call) must appear in
  ``docs/OBSERVABILITY.md`` — the naming table is the operator's
  contract and it must not rot as instrumentation grows.  A
  deliberately undocumented name (e.g. a test-only probe) carries a
  ``metric-doc-ok`` marker comment on the line.  ``--selftest`` runs
  the lint's own fixtures (detection, marker escape, documented-name
  pass).

Exit code 0 when clean; prints one line per violation otherwise.
"""

import ast
import os
import re
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
MAX_LEN = 100
ROOTS = ("raft_tpu", "tests", "docs", "ci", "tools")
EXTRA = ("bench.py", "__graft_entry__.py")

# ad-hoc timing ban (raft_tpu/ only)
TIMING_ATTRS = ("time", "perf_counter", "perf_counter_ns", "monotonic",
                "monotonic_ns", "process_time")
TIMING_ALLOWLIST = (
    os.path.join("raft_tpu", "core", "metrics.py"),
    os.path.join("raft_tpu", "core", "profiler.py"),
)

# raw-Thread ban (raft_tpu/ only): serve/ owns worker threads, and
# fleet/ owns the router's lease/chaos/harness threads; the resilience
# watchdog and the timing allowlist predate it
THREAD_DIR_ALLOWLIST = (os.path.join("raft_tpu", "serve") + os.sep,
                        os.path.join("raft_tpu", "fleet") + os.sep)
THREAD_ALLOWLIST = TIMING_ALLOWLIST + (
    os.path.join("raft_tpu", "comms", "resilience.py"),
)

# host-numpy payload ban (raft_tpu/comms/ only): the zero-copy p2p
# path's guarantee is that payloads never bounce through host numpy
# (docs/ZERO_COPY.md); selftest/faults read results on host by design,
# and a `comms-host-ok` marker comment exempts a line (device handles,
# the counted staging="host" baseline)
COMMS_NP_DIR = os.path.join("raft_tpu", "comms") + os.sep
COMMS_NP_ALLOWLIST = (
    os.path.join("raft_tpu", "comms", "selftest.py"),
    os.path.join("raft_tpu", "comms", "faults.py"),
)
COMMS_NP_ATTRS = ("asarray", "array")
COMMS_NP_MARKER = "comms-host-ok"

# direct-jax.jit ban (raft_tpu/spatial/mnmg_knn.py only): sharded SPMD
# programs compile through profiled_jit so the serving layer's compile
# accounting sees them (docs/SERVING.md); `mnmg-jit-ok` marks a
# deliberate exception
MNMG_JIT_FILES = (os.path.join("raft_tpu", "spatial", "mnmg_knn.py"),)
MNMG_JIT_MARKER = "mnmg-jit-ok"

# whole-index device_put ban (the out-of-core tier's search path:
# raft_tpu/spatial/ooc.py + raft_tpu/mr/tile_pool.py): the tier's
# guarantee is that the full slot store NEVER lands on device — the
# only legitimate transfer sites are the pool's per-tile put and the
# budget-bounded hot-set materialization, each marked
# `ooc-resident-ok` (mirrors the comms np.asarray ban)
OOC_PUT_FILES = (os.path.join("raft_tpu", "spatial", "ooc.py"),
                 os.path.join("raft_tpu", "mr", "tile_pool.py"))
OOC_PUT_MARKER = "ooc-resident-ok"

# serve except-Exception audit (raft_tpu/serve/ only): a broad handler
# must relay, count, or re-raise — see module doc
SERVE_EXC_DIR = os.path.join("raft_tpu", "serve") + os.sep
SERVE_EXC_MARKER = "serve-exc-ok"
SERVE_EXC_RELAY_ATTRS = ("_set_exception", "inc", "observe",
                         "record_failure", "_fail_batch")

# shed-hint audit (raft_tpu/serve/ only): every ServiceOverloadError a
# shed site constructs must carry the retry_after_s back-off hint; a
# site with genuinely no estimate marks the line `shed-hint-ok`
SERVE_SHED_DIR = SERVE_EXC_DIR
SERVE_SHED_MARKER = "shed-hint-ok"
SERVE_SHED_NAME = "ServiceOverloadError"
SERVE_SHED_HINT_KW = "retry_after_s"

# metric docs-drift lint (raft_tpu/ only): a raft_tpu_* name literal
# inside a registry call (function name containing one of the hints)
# must appear in docs/OBSERVABILITY.md; `metric-doc-ok` marks a
# deliberately undocumented name
METRIC_DOC = os.path.join("docs", "OBSERVABILITY.md")
METRIC_DOC_MARKER = "metric-doc-ok"
METRIC_NAME_RE = re.compile(r"^raft_tpu_[a-z0-9_]+$")
METRIC_CALL_HINTS = ("counter", "gauge", "timer", "labeled")

# serialization ban (raft_tpu/ wide): persisted state goes through the
# checksummed manifest path (raft_tpu/persist, docs/PERSISTENCE.md) —
# never pickle (arbitrary code execution on load, zero integrity
# checking) and never numpy's .npy containers (``np.save`` /
# ``np.load(allow_pickle=True)`` can embed pickles and bypass the
# manifest CRCs entirely).  Plain ``np.load`` without allow_pickle
# stays legal (it cannot execute code).  A deliberate site marks its
# line `persist-io-ok` — the persist module's raw-array writer is the
# intended serializer and needs no marker (it uses tobytes/frombuffer).
PERSIST_IO_MARKER = "persist-io-ok"
PICKLE_MODULES = ("pickle", "cPickle", "_pickle", "dill", "cloudpickle")
NP_SAVE_ATTRS = ("save", "savez", "savez_compressed")

# ops-plane jax ban (raft_tpu/serve/opsplane.py + sentinel.py): every
# ops HTTP handler and sentinel rule reads host-side snapshots ONLY —
# a jax call reachable from a scrape could compile, block the worker
# loop, or perturb the zero-post-warmup-compiles invariant
# (docs/OBSERVABILITY.md "Ops plane").  The ban is total for these
# modules: no `import jax`, no `from jax import ...`, no `jax.`
# attribute use.  A deliberate exception marks its line `ops-jax-ok`.
OPS_JAX_FILES = (os.path.join("raft_tpu", "serve", "opsplane.py"),
                 os.path.join("raft_tpu", "serve", "sentinel.py"),
                 # the fleet router aggregates worker scrapes and must
                 # never compile: same ban as the ops handlers
                 os.path.join("raft_tpu", "fleet", "router.py"),
                 # fleet debug/trace aggregation: the cross-process
                 # join (worker /debug/trace payloads + clock
                 # alignment) runs inside router and worker HTTP
                 # handlers — a jax call here could compile or block
                 # the serving loop mid-scrape
                 os.path.join("raft_tpu", "fleet", "tracing.py"),
                 os.path.join("raft_tpu", "fleet", "protocol.py"))
OPS_JAX_MARKER = "ops-jax-ok"

# tuning-registry drift lint: every config._KNOBS entry with a non-None
# choices whitelist is a registry-owned impl knob and MUST have a
# register(...) entry in raft_tpu/core/tuning.py (the sweep's search
# space and the consumers' validation would otherwise skew from the
# config surface); `tune-reg-ok` on the _KNOBS entry line escapes.
# Companion per-file rule: no consumer in raft_tpu/ may carry a local
# tuple/list literal equal to a registry-owned knob's candidate set —
# the registry is the ONE owner (consumers re-export via
# tuning.candidates(knob)); `tune-reg-ok` marks a deliberate copy.
TUNE_REG_MARKER = "tune-reg-ok"
TUNE_CONFIG = os.path.join("raft_tpu", "config.py")
TUNE_REGISTRY = os.path.join("raft_tpu", "core", "tuning.py")
TUNE_EXEMPT = (TUNE_CONFIG, TUNE_REGISTRY)

# block-shape knob lint: tile shapes at fused-kernel call sites
# (block_q=, block_n=, ...) are REGISTRY integer-ladder knobs
# (knn_block_q/knn_block_n/nn_block_n, core/tuning.py) with legality
# predicates (lane/sublane multiples, VMEM fit) — a hand-written
# integer at a consumer call site bypasses both the predicates and the
# swept winners, which is exactly how the r5 hard-coded
# `min(tile_n, 1024)` rotted.  Scope: raft_tpu/ outside the
# kernel-owning ops/ modules (the kernels RESOLVE the knobs; their
# signature defaults are not call sites), plus tools/ and bench.py.
# tests/ pin geometry deliberately (lowering/export shape cases) and
# are exempt.  `block-shape-ok` marks a deliberate probe/attribution
# geometry.
BLOCK_KW_NAMES = ("block_q", "block_n", "block_m", "block_rows",
                  "block_w")
BLOCK_KW_MARKER = "block-shape-ok"
BLOCK_KW_OPS_DIR = os.path.join("raft_tpu", "ops") + os.sep

_metric_doc_text = None
_tune_sets_cache = None


def _knob_choice_entries(config_src=None):
    """[(knob, frozenset(choices), lineno, marked)] parsed statically
    from config.py's ``_KNOBS`` dict literal (choices = the non-None
    third tuple element).  ``config_src`` injects synthetic source for
    the self-tests; the real file is parsed once and cached."""
    global _tune_sets_cache
    if config_src is None:
        if _tune_sets_cache is not None:
            return _tune_sets_cache
        try:
            with open(os.path.join(REPO, TUNE_CONFIG),
                      encoding="utf-8") as f:
                config_src = f.read()
        except OSError:
            _tune_sets_cache = []
            return _tune_sets_cache
        out = _parse_knob_entries(config_src)
        _tune_sets_cache = out
        return out
    return _parse_knob_entries(config_src)


def _parse_knob_entries(src):
    out = []
    try:
        tree = ast.parse(src)
    except SyntaxError:
        return out
    lines = src.splitlines()
    for node in ast.walk(tree):
        # both the bare and the annotated (_KNOBS: Dict[...] = {...})
        # assignment forms
        if isinstance(node, ast.Assign):
            targets = node.targets
        elif isinstance(node, ast.AnnAssign):
            targets = [node.target]
        else:
            continue
        if not (any(isinstance(t, ast.Name) and t.id == "_KNOBS"
                    for t in targets)
                and isinstance(node.value, ast.Dict)):
            continue
        for key, val in zip(node.value.keys, node.value.values):
            if not (isinstance(key, ast.Constant)
                    and isinstance(key.value, str)
                    and isinstance(val, ast.Tuple)
                    and len(val.elts) >= 3):
                continue
            choices_node = val.elts[2]
            choices = None
            if isinstance(choices_node, ast.Tuple):
                cs = [e.value for e in choices_node.elts
                      if isinstance(e, ast.Constant)
                      and isinstance(e.value, str)]
                if cs:
                    choices = frozenset(cs)
            marked = TUNE_REG_MARKER in lines[key.lineno - 1]
            out.append((key.value, choices, key.lineno, marked))
    return out


def _registry_knob_names(tuning_src=None):
    """Knob-name string literals passed to ``register(...)`` in the
    candidate registry (2nd positional arg or ``knob=`` keyword)."""
    if tuning_src is None:
        try:
            with open(os.path.join(REPO, TUNE_REGISTRY),
                      encoding="utf-8") as f:
                tuning_src = f.read()
        except OSError:
            return set()
    names = set()
    try:
        tree = ast.parse(tuning_src)
    except SyntaxError:
        return names
    for node in ast.walk(tree):
        if not (isinstance(node, ast.Call)
                and ((isinstance(node.func, ast.Name)
                      and node.func.id == "register")
                     or (isinstance(node.func, ast.Attribute)
                         and node.func.attr == "register"))):
            continue
        if (len(node.args) >= 2
                and isinstance(node.args[1], ast.Constant)
                and isinstance(node.args[1].value, str)):
            names.add(node.args[1].value)
        for kw in node.keywords:
            if (kw.arg == "knob" and isinstance(kw.value, ast.Constant)
                    and isinstance(kw.value.value, str)):
                names.add(kw.value.value)
    return names


def check_tuning_registry(config_src=None, tuning_src=None):
    """Cross-file drift check (module-doc TUNE_REG block): choices
    knobs in config.py vs register() entries in core/tuning.py."""
    problems = []
    registered = _registry_knob_names(tuning_src)
    for knob, choices, lineno, marked in _knob_choice_entries(
            config_src):
        if choices and knob not in registered and not marked:
            problems.append(
                "%s:%d: knob %s has a choices whitelist but no "
                "candidate-registry entry in %s — register it (the "
                "sweep's search space and consumer validation must "
                "not skew from config), or mark the entry line "
                "`%s`" % (TUNE_CONFIG, lineno, knob, TUNE_REGISTRY,
                          TUNE_REG_MARKER))
    return problems


def _metric_doc(doc_text=None):
    """The observability doc's text (cached); ``doc_text`` injects a
    synthetic doc for the self-tests."""
    global _metric_doc_text
    if doc_text is not None:
        return doc_text
    if _metric_doc_text is None:
        try:
            with open(os.path.join(REPO, METRIC_DOC),
                      encoding="utf-8") as f:
                _metric_doc_text = f.read()
        except OSError:
            _metric_doc_text = ""
    return _metric_doc_text


def _metric_literals(tree):
    """(name, lineno) of every raft_tpu_* string literal passed into a
    registry-shaped call (counter/gauge/timer/_labeled and friends)."""
    out = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        fn = node.func
        fname = (fn.attr if isinstance(fn, ast.Attribute)
                 else getattr(fn, "id", ""))
        if not any(h in fname.lower() for h in METRIC_CALL_HINTS):
            continue
        for arg in list(node.args) + [kw.value for kw in node.keywords]:
            if (isinstance(arg, ast.Constant)
                    and isinstance(arg.value, str)
                    and METRIC_NAME_RE.match(arg.value)):
                out.append((arg.value, arg.lineno))
    return out


def _serve_handler_visible(handler):
    """Whether an ``except Exception`` handler relays (futures), counts
    (metrics), or re-raises — anything else is a silent swallow."""
    for sub in ast.walk(handler):
        if isinstance(sub, ast.Raise):
            return True
        if (isinstance(sub, ast.Call)
                and isinstance(sub.func, ast.Attribute)
                and sub.func.attr in SERVE_EXC_RELAY_ATTRS):
            return True
    return False


def check_file(path, doc_text=None, repo_root=None):
    """Lint one file.  ``doc_text`` injects a synthetic observability
    doc and ``repo_root`` a synthetic tree root — both exist so
    :func:`selftest` can run fixtures through THIS function (not a
    copy of its logic).  ``repo_root=None`` resolves the module's
    ``REPO`` at call time (tests monkeypatch it)."""
    problems = []
    rel = os.path.relpath(path, REPO if repo_root is None
                          else repo_root)
    with open(path, encoding="utf-8") as f:
        src = f.read()
    try:
        tree = ast.parse(src, filename=rel)
    except SyntaxError as e:
        return [f"{rel}:{e.lineno}: syntax error: {e.msg}"]
    if src and not src.endswith("\n"):
        problems.append(f"{rel}: missing newline at EOF")
    for i, line in enumerate(src.splitlines(), 1):
        if line != line.rstrip():
            problems.append(f"{rel}:{i}: trailing whitespace")
        if "\t" in line[: len(line) - len(line.lstrip())]:
            problems.append(f"{rel}:{i}: tab indentation")
        if len(line) > MAX_LEN:
            problems.append(f"{rel}:{i}: line too long ({len(line)})")
    in_lib = (rel.startswith("raft_tpu" + os.sep)
              and rel not in TIMING_ALLOWLIST)
    in_thread_scope = (rel.startswith("raft_tpu" + os.sep)
                       and not any(rel.startswith(d)
                                   for d in THREAD_DIR_ALLOWLIST)
                       and rel not in THREAD_ALLOWLIST)
    in_comms_np_scope = (rel.startswith(COMMS_NP_DIR)
                         and rel not in COMMS_NP_ALLOWLIST)
    in_serve_exc_scope = rel.startswith(SERVE_EXC_DIR)
    in_serial_scope = rel.startswith("raft_tpu" + os.sep)
    in_mnmg_jit_scope = rel in MNMG_JIT_FILES
    in_ooc_put_scope = rel in OOC_PUT_FILES
    in_ops_jax_scope = rel in OPS_JAX_FILES
    in_tune_scope = (rel.startswith("raft_tpu" + os.sep)
                     and rel not in TUNE_EXEMPT)
    in_block_scope = ((rel.startswith("raft_tpu" + os.sep)
                       and not rel.startswith(BLOCK_KW_OPS_DIR))
                      or rel.startswith("tools" + os.sep)
                      or rel == "bench.py")
    src_lines = src.splitlines()
    if in_tune_scope:
        owned = {choices: knob for knob, choices, _, _
                 in _knob_choice_entries()
                 if choices and len(choices) >= 2}
        for node in ast.walk(tree):
            if not isinstance(node, (ast.Tuple, ast.List)):
                continue
            vals = [e.value for e in node.elts
                    if isinstance(e, ast.Constant)
                    and isinstance(e.value, str)]
            if len(vals) != len(node.elts) or len(vals) < 2:
                continue
            knob = owned.get(frozenset(vals))
            if (knob is not None
                    and TUNE_REG_MARKER not in src_lines[node.lineno - 1]):
                problems.append(
                    f"{rel}:{node.lineno}: local candidate literal for "
                    f"registry-owned knob {knob} — consumers resolve/"
                    "validate through raft_tpu.core.tuning (re-export "
                    f"via tuning.candidates({knob!r})); mark a "
                    f"deliberate copy `{TUNE_REG_MARKER}`")
    if rel.startswith("raft_tpu" + os.sep):
        doc = _metric_doc(doc_text)
        for mname, lineno in _metric_literals(tree):
            # delimited match, not substring: an undocumented name
            # that is a prefix of a documented one (misses vs
            # misses_total) must still be flagged
            documented = re.search(
                r"(?<![A-Za-z0-9_])" + re.escape(mname)
                + r"(?![A-Za-z0-9_])", doc)
            if (not documented
                    and METRIC_DOC_MARKER not in src_lines[lineno - 1]):
                problems.append(
                    f"{rel}:{lineno}: metric {mname} is not documented "
                    f"in {METRIC_DOC} — add it to the naming table "
                    "(the operator contract must not rot; "
                    f"docs/OBSERVABILITY.md), or mark the line "
                    f"`{METRIC_DOC_MARKER}`")
    # aliases the time/threading modules are bound to ("import time",
    # "import time as t") — attribute-call matching must follow them or
    # the bans are trivially evaded
    time_aliases = {"time"}
    threading_aliases = {"threading"}
    numpy_aliases = {"numpy"}
    jax_aliases = {"jax"}
    for node in ast.walk(tree):
        if (isinstance(node, ast.ImportFrom) and node.module
                and node.module.startswith("raft_tpu")
                and any(a.name == "*" for a in node.names)):
            problems.append(f"{rel}:{node.lineno}: wildcard raft_tpu import")
        if in_block_scope and isinstance(node, ast.Call):
            for kw in node.keywords:
                if (kw.arg in BLOCK_KW_NAMES
                        and isinstance(kw.value, ast.Constant)
                        and isinstance(kw.value.value, int)
                        and not isinstance(kw.value.value, bool)
                        and BLOCK_KW_MARKER
                        not in src_lines[node.lineno - 1]
                        and BLOCK_KW_MARKER
                        not in src_lines[kw.value.lineno - 1]):
                    problems.append(
                        f"{rel}:{kw.value.lineno}: hand-written block "
                        f"shape {kw.arg}={kw.value.value} at a kernel "
                        "call site — tile shapes are registry "
                        "integer-ladder knobs (knn_block_q/knn_block_n/"
                        "nn_block_n; docs/TUNING.md \"Kernel "
                        "block-shape knobs\"): pass None and let the "
                        "kernel resolve the swept winner, or mark a "
                        "deliberate probe geometry "
                        f"`{BLOCK_KW_MARKER}`")
        if (in_serve_exc_scope and isinstance(node, ast.Call)
                and ((isinstance(node.func, ast.Name)
                      and node.func.id == SERVE_SHED_NAME)
                     or (isinstance(node.func, ast.Attribute)
                         and node.func.attr == SERVE_SHED_NAME))
                and not any(kw.arg == SERVE_SHED_HINT_KW
                            for kw in node.keywords)
                and SERVE_SHED_MARKER
                not in src_lines[node.lineno - 1]):
            problems.append(
                f"{rel}:{node.lineno}: {SERVE_SHED_NAME} without "
                f"{SERVE_SHED_HINT_KW}= — every shed must hand the "
                "caller a back-off hint (docs/SERVING.md); mark "
                f"hint-less sites `{SERVE_SHED_MARKER}`")
        if (in_serve_exc_scope and isinstance(node, ast.ExceptHandler)
                and (node.type is None
                     or (isinstance(node.type, ast.Name)
                         and node.type.id in ("Exception",
                                              "BaseException")))
                and SERVE_EXC_MARKER
                not in src_lines[node.lineno - 1]
                and not _serve_handler_visible(node)):
            problems.append(
                f"{rel}:{node.lineno}: silent except Exception in "
                "serve/ — relay to rider futures (_set_exception), "
                "count it (.inc/.observe/record_failure), re-raise, "
                f"or mark the audited line `{SERVE_EXC_MARKER}` "
                "(docs/FAULT_MODEL.md)")
        if in_serial_scope:
            if isinstance(node, ast.Import):
                for a in node.names:
                    if a.name == "numpy":
                        numpy_aliases.add(a.asname or "numpy")
                    if (a.name.split(".")[0] in PICKLE_MODULES
                            and PERSIST_IO_MARKER
                            not in src_lines[node.lineno - 1]):
                        problems.append(
                            f"{rel}:{node.lineno}: import of {a.name} "
                            "— persisted state goes through the "
                            "checksummed manifest path "
                            "(raft_tpu/persist, docs/PERSISTENCE.md), "
                            "never pickle; mark a deliberate site "
                            f"`{PERSIST_IO_MARKER}`")
            elif (isinstance(node, ast.ImportFrom) and node.module
                    and node.module.split(".")[0] in PICKLE_MODULES
                    and PERSIST_IO_MARKER
                    not in src_lines[node.lineno - 1]):
                problems.append(
                    f"{rel}:{node.lineno}: from-import of "
                    f"{node.module} — persisted state goes through "
                    "the checksummed manifest path "
                    "(raft_tpu/persist, docs/PERSISTENCE.md), never "
                    "pickle; mark a deliberate site "
                    f"`{PERSIST_IO_MARKER}`")
            elif (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and isinstance(node.func.value, ast.Name)
                    and node.func.value.id in numpy_aliases
                    and PERSIST_IO_MARKER
                    not in src_lines[node.lineno - 1]):
                if node.func.attr in NP_SAVE_ATTRS:
                    problems.append(
                        f"{rel}:{node.lineno}: np.{node.func.attr}() "
                        "— .npy/.npz containers bypass the "
                        "checksummed manifest path (raft_tpu/persist,"
                        " docs/PERSISTENCE.md); mark a deliberate "
                        f"site `{PERSIST_IO_MARKER}`")
                elif node.func.attr == "load" and any(
                        kw.arg == "allow_pickle"
                        and isinstance(kw.value, ast.Constant)
                        and kw.value.value is True
                        for kw in node.keywords):
                    problems.append(
                        f"{rel}:{node.lineno}: np.load(allow_pickle="
                        "True) — a pickle-bearing load can execute "
                        "code and bypasses the manifest CRCs "
                        "(docs/PERSISTENCE.md); mark a deliberate "
                        f"site `{PERSIST_IO_MARKER}`")
        if in_thread_scope:
            if isinstance(node, ast.Import):
                for a in node.names:
                    if a.name == "threading":
                        threading_aliases.add(a.asname or "threading")
            elif (isinstance(node, ast.ImportFrom)
                    and node.module == "threading"
                    and any(a.name == "Thread" for a in node.names)):
                problems.append(
                    f"{rel}:{node.lineno}: from-import of "
                    "threading.Thread — background work goes through "
                    "raft_tpu/serve (ServeWorker) or the resilience "
                    "watchdog (docs/SERVING.md)")
            elif (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr == "Thread"
                    and isinstance(node.func.value, ast.Name)
                    and node.func.value.id in threading_aliases):
                problems.append(
                    f"{rel}:{node.lineno}: raw threading.Thread() — "
                    "background work goes through raft_tpu/serve "
                    "(ServeWorker) or the resilience watchdog "
                    "(docs/SERVING.md)")
        if in_mnmg_jit_scope:
            if isinstance(node, ast.Import):
                for a in node.names:
                    if a.name == "jax":
                        jax_aliases.add(a.asname or "jax")
            elif (isinstance(node, ast.ImportFrom)
                    and node.module == "jax"
                    and any(a.name == "jit" for a in node.names)
                    and MNMG_JIT_MARKER
                    not in src_lines[node.lineno - 1]):
                problems.append(
                    f"{rel}:{node.lineno}: from-import of jax.jit — "
                    "sharded SPMD programs compile through "
                    "profiled_jit (docs/SERVING.md); mark deliberate "
                    f"exceptions `{MNMG_JIT_MARKER}`")
            elif (isinstance(node, ast.Attribute)
                    and node.attr == "jit"
                    and isinstance(node.value, ast.Name)
                    and node.value.id in jax_aliases
                    and MNMG_JIT_MARKER
                    not in src_lines[node.lineno - 1]):
                # Attribute (not Call) match: also catches the bare
                # `@jax.jit` decorator and `f = jax.jit` aliasing
                problems.append(
                    f"{rel}:{node.lineno}: direct jax.jit — sharded "
                    "SPMD programs compile through profiled_jit "
                    "(docs/SERVING.md); mark deliberate exceptions "
                    f"`{MNMG_JIT_MARKER}`")
        if in_ops_jax_scope:
            flagged = None
            if isinstance(node, ast.Import):
                if any(a.name == "jax" or a.name.startswith("jax.")
                       for a in node.names):
                    flagged = node.lineno
            elif (isinstance(node, ast.ImportFrom) and node.module
                    and node.module.split(".")[0] == "jax"):
                flagged = node.lineno
            elif (isinstance(node, ast.Name) and node.id == "jax"
                    and isinstance(node.ctx, ast.Load)):
                # bare-name use covers jax.<anything> attribute chains
                # AND aliasing (j = jax) — total ban, not a call list
                flagged = node.lineno
            if (flagged is not None
                    and OPS_JAX_MARKER
                    not in src_lines[flagged - 1]):
                problems.append(
                    f"{rel}:{flagged}: jax reachable from the ops "
                    "plane — handlers/sentinel rules read host-side "
                    "snapshots only; a scrape must never compile or "
                    "block the worker loop (docs/OBSERVABILITY.md "
                    f"\"Ops plane\"); mark a deliberate exception "
                    f"`{OPS_JAX_MARKER}`")
        if in_ooc_put_scope:
            if isinstance(node, ast.Import):
                for a in node.names:
                    if a.name == "jax":
                        jax_aliases.add(a.asname or "jax")
            elif (isinstance(node, ast.ImportFrom)
                    and node.module == "jax"
                    and any(a.name == "device_put" for a in node.names)
                    and OOC_PUT_MARKER
                    not in src_lines[node.lineno - 1]):
                problems.append(
                    f"{rel}:{node.lineno}: from-import of "
                    "jax.device_put in the out-of-core path — the full "
                    "index never lands on device (docs/ZERO_COPY.md "
                    "§6); mark the per-tile/hot-set transfer sites "
                    f"`{OOC_PUT_MARKER}`")
            elif (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr == "device_put"
                    and isinstance(node.func.value, ast.Name)
                    and node.func.value.id in jax_aliases
                    and OOC_PUT_MARKER
                    not in src_lines[node.lineno - 1]):
                problems.append(
                    f"{rel}:{node.lineno}: jax.device_put() in the "
                    "out-of-core path — the full index never lands on "
                    "device (docs/ZERO_COPY.md §6); mark the "
                    "per-tile/hot-set transfer sites "
                    f"`{OOC_PUT_MARKER}`")
        if in_comms_np_scope:
            if isinstance(node, ast.Import):
                for a in node.names:
                    if a.name == "numpy":
                        numpy_aliases.add(a.asname or "numpy")
            elif (isinstance(node, ast.ImportFrom)
                    and node.module == "numpy"
                    and any(a.name in COMMS_NP_ATTRS
                            for a in node.names)
                    and COMMS_NP_MARKER
                    not in src_lines[node.lineno - 1]):
                problems.append(
                    f"{rel}:{node.lineno}: from-import of numpy "
                    "array/asarray in comms — payloads stay on device "
                    "(docs/ZERO_COPY.md); mark device-handle uses "
                    f"with `{COMMS_NP_MARKER}`")
            elif (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr in COMMS_NP_ATTRS
                    and isinstance(node.func.value, ast.Name)
                    and node.func.value.id in numpy_aliases
                    and COMMS_NP_MARKER
                    not in src_lines[node.lineno - 1]):
                problems.append(
                    f"{rel}:{node.lineno}: np.{node.func.attr}() on a "
                    "comms hot path — payloads stay on device "
                    "(docs/ZERO_COPY.md); mark device-handle uses "
                    f"with `{COMMS_NP_MARKER}`")
        if not in_lib:
            continue
        if isinstance(node, ast.Import):
            for a in node.names:
                if a.name == "time":
                    time_aliases.add(a.asname or "time")
        elif isinstance(node, ast.ImportFrom) and node.module == "time":
            # importing the timing function itself IS the evasion
            for a in node.names:
                if a.name in TIMING_ATTRS:
                    problems.append(
                        f"{rel}:{node.lineno}: ad-hoc from-import of "
                        f"time.{a.name} — use the profiler/metrics API "
                        "(docs/OBSERVABILITY.md)")
        elif (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr in TIMING_ATTRS
                and isinstance(node.func.value, ast.Name)
                and node.func.value.id in time_aliases):
            problems.append(
                f"{rel}:{node.lineno}: ad-hoc time.{node.func.attr}() — "
                "use the profiler/metrics API (docs/OBSERVABILITY.md)")
    return problems


def selftest():
    """Executable fixtures for the metric docs-drift lint: an
    undocumented registered name is flagged, a documented one passes,
    the ``metric-doc-ok`` marker escapes, and a raft_tpu_* string
    outside a registry call (e.g. a thread-attribute name) is ignored.
    Returns the number of failed fixtures (0 = green)."""
    import tempfile

    doc = "| `raft_tpu_test_documented_total` | counter | fixture |\n"
    cases = [
        # (source, expect_flagged)
        ('reg.counter("raft_tpu_test_undocumented_total")\n', True),
        ('reg.counter("raft_tpu_test_documented_total")\n', False),
        ('reg.counter("raft_tpu_test_undocumented_total")'
         '  # metric-doc-ok: probe\n', False),
        ('getattr(t, "raft_tpu_test_undocumented_total", None)\n',
         False),
        ('_labeled("gauge", "raft_tpu_test_undocumented_total", "h",'
         ' "svc")\n', True),
        # a PREFIX of a documented name is still undocumented — the
        # substring-match hole the delimited regex closes
        ('reg.counter("raft_tpu_test_documented")\n', True),
    ]
    failures = 0
    with tempfile.TemporaryDirectory() as tmp:
        # the lint only fires under raft_tpu/ — stage the fixtures in
        # a synthetic repo root holding its own raft_tpu/ directory
        fixdir = os.path.join(tmp, "raft_tpu")
        os.makedirs(fixdir)
        for i, (src, expect) in enumerate(cases):
            path = os.path.join(fixdir, "fixture%d.py" % i)
            with open(path, "w", encoding="utf-8") as f:
                f.write(src)
            # the REAL check_file, pointed at the synthetic tree root
            # so the fixture is in scope exactly like a library file —
            # a copy of the lint logic here would let the real lint
            # regress while the selftest stayed green
            problems = [p for p in check_file(path, doc_text=doc,
                                              repo_root=tmp)
                        if "not documented" in p]
            flagged = bool(problems)
            if flagged != expect:
                failures += 1
                print("selftest fixture %d: expected flagged=%s, "
                      "got %r" % (i, expect, problems),
                      file=sys.stderr)
    print("metric-doc lint selftest: %d fixtures, %d failures"
          % (len(cases), failures), file=sys.stderr)
    failures += _selftest_tuning()
    failures += _selftest_persist_io()
    failures += _selftest_ops_jax()
    failures += _selftest_block_shape()
    return failures


def _selftest_ops_jax():
    """Executable fixtures for the ops-plane jax ban: imports,
    from-imports, attribute chains and aliasing are flagged inside the
    banned modules; the ``ops-jax-ok`` marker escapes; jax-free code
    and other serve modules pass."""
    import tempfile

    cases = [
        # (filename, source, expect_flagged)
        ("opsplane.py", "import jax\n", True),
        ("opsplane.py", "import jax.numpy as jnp\n", True),
        ("opsplane.py", "from jax import jit\n", True),
        ("opsplane.py", "from jax.sharding import Mesh\n", True),
        ("opsplane.py", "x = jax.devices()\n", True),
        ("opsplane.py", "j = jax\n", True),
        ("opsplane.py", "import jax  # ops-jax-ok: fixture\n", False),
        ("opsplane.py", "import json\nx = json.dumps({})\n", False),
        ("sentinel.py", "import jax\n", True),
        # the ban is scoped: the rest of serve/ may use jax freely
        ("scheduler.py", "import jax\n", False),
        # fleet debug/trace aggregation path (PR 17): the join and
        # the frame protocol are banned; worker.py is NOT (it hosts a
        # full jax ANNService — its trace handler delegates to
        # tracing.py, which is where the ban bites)
        (os.path.join("..", "fleet", "tracing.py"),
         "import jax\n", True),
        (os.path.join("..", "fleet", "tracing.py"),
         "from jax import numpy\n", True),
        (os.path.join("..", "fleet", "protocol.py"),
         "x = jax.device_count()\n", True),
        (os.path.join("..", "fleet", "tracing.py"),
         "import jax  # ops-jax-ok: fixture\n", False),
        (os.path.join("..", "fleet", "tracing.py"),
         "import json\nx = json.loads('{}')\n", False),
        (os.path.join("..", "fleet", "worker.py"),
         "import jax\n", False),
    ]
    failures = 0
    with tempfile.TemporaryDirectory() as tmp:
        fixdir = os.path.join(tmp, "raft_tpu", "serve")
        os.makedirs(fixdir)
        os.makedirs(os.path.join(tmp, "raft_tpu", "fleet"))
        for i, (fname, src, expect) in enumerate(cases):
            path = os.path.join(fixdir, fname)
            with open(path, "w", encoding="utf-8") as f:
                f.write(src)
            probs = [p for p in check_file(path, repo_root=tmp)
                     if "ops plane" in p]
            if bool(probs) != expect:
                failures += 1
                print("ops-jax fixture %d (%s): expected flagged=%s, "
                      "got %r" % (i, fname, expect, probs),
                      file=sys.stderr)
    print("ops-jax lint selftest: %d fixtures, %d failures"
          % (len(cases), failures), file=sys.stderr)
    return failures


def _selftest_block_shape():
    """Executable fixtures for the block-shape literal ban: integer
    literals for block kwargs are flagged in consumer scope, the
    ``block-shape-ok`` marker escapes, None/variable arguments pass,
    and the kernel-owning ops/ modules plus tests/ are out of scope."""
    import tempfile

    cases = [
        # (relpath, source, expect_flagged)
        (os.path.join("raft_tpu", "spatial", "f.py"),
         "d, i = fused_knn_tile(x, q, k, block_n=2048)\n", True),
        (os.path.join("raft_tpu", "spatial", "f.py"),
         "d, i = fused_knn_tile(x, q, k, block_q=64, block_n=bn)\n",
         True),
        (os.path.join("raft_tpu", "spatial", "f.py"),
         "d = fused_nn_tile(x, y,\n"
         "                  block_m=256)\n", True),
        (os.path.join("raft_tpu", "spatial", "f.py"),
         "d, i = fused_knn_tile(x, q, k, block_n=2048)"
         "  # block-shape-ok: fixture\n", False),
        (os.path.join("raft_tpu", "spatial", "f.py"),
         "d, i = fused_knn_tile(x, q, k, block_n=None)\n", False),
        (os.path.join("raft_tpu", "spatial", "f.py"),
         "d, i = fused_knn_tile(x, q, k, block_n=bn)\n", False),
        # the kernel modules own their ladders/defaults
        (os.path.join("raft_tpu", "ops", "f.py"),
         "d, i = helper(x, q, k, block_n=2048)\n", False),
        # tests pin geometry deliberately
        (os.path.join("tests", "f.py"),
         "d, i = fused_knn_tile(x, q, k, block_n=1024)\n", False),
        ("bench.py",
         "d, i = fused_knn_twophase(x, q, k, block_n=2048)\n", True),
        (os.path.join("tools", "f.py"),
         "d, i = fused_knn_tile(x, q, k, block_q=128)\n", True),
    ]
    failures = 0
    with tempfile.TemporaryDirectory() as tmp:
        for sub in (os.path.join("raft_tpu", "spatial"),
                    os.path.join("raft_tpu", "ops"), "tests", "tools"):
            os.makedirs(os.path.join(tmp, sub), exist_ok=True)
        for i, (relp, src, expect) in enumerate(cases):
            path = os.path.join(tmp, relp)
            with open(path, "w", encoding="utf-8") as f:
                f.write(src)
            probs = [p for p in check_file(path, repo_root=tmp)
                     if "hand-written block shape" in p]
            if bool(probs) != expect:
                failures += 1
                print("block-shape fixture %d (%s): expected "
                      "flagged=%s, got %r" % (i, relp, expect, probs),
                      file=sys.stderr)
    print("block-shape lint selftest: %d fixtures, %d failures"
          % (len(cases), failures), file=sys.stderr)
    return failures


def _selftest_persist_io():
    """Executable fixtures for the serialization ban: pickle imports
    and .npy-container writes are flagged, pickle-free numpy load
    passes, the ``persist-io-ok`` marker escapes."""
    import tempfile

    cases = [
        ("import pickle\n", True),
        ("import cloudpickle as cp\n", True),
        ("from pickle import loads\n", True),
        ("import pickle  # persist-io-ok: fixture\n", False),
        ("import numpy as np\nnp.save('x.npy', a)\n", True),
        ("import numpy as np\nnp.savez('x.npz', a=a)\n", True),
        ("import numpy as np\n"
         "np.load('x.npy', allow_pickle=True)\n", True),
        ("import numpy as np\nnp.load('x.npy')\n", False),
        ("import numpy as np\n"
         "np.save('x.npy', a)  # persist-io-ok: fixture\n", False),
    ]
    failures = 0
    with tempfile.TemporaryDirectory() as tmp:
        fixdir = os.path.join(tmp, "raft_tpu")
        os.makedirs(fixdir)
        for i, (src, expect) in enumerate(cases):
            path = os.path.join(fixdir, "serfix%d.py" % i)
            with open(path, "w", encoding="utf-8") as f:
                f.write(src)
            probs = [p for p in check_file(path, repo_root=tmp)
                     if PERSIST_IO_MARKER in p]
            if bool(probs) != expect:
                failures += 1
                print("persist-io fixture %d: expected flagged=%s, "
                      "got %r" % (i, expect, probs), file=sys.stderr)
    print("persist-io lint selftest: %d fixtures, %d failures"
          % (len(cases), failures), file=sys.stderr)
    return failures


def _selftest_tuning():
    """Executable fixtures for the tuning-registry lints: (a) a
    choices knob missing from the registry is flagged, registered/
    marked ones pass; (b) a consumer-local candidate literal is
    flagged, the marker escapes, an unrelated tuple passes."""
    import tempfile

    failures = 0
    # (a) cross-file drift, synthetic sources through the REAL checker
    cfg_missing = ('_KNOBS = {\n'
                   '    "lint_fixture_impl": ("E", "a", ("a", "b")),\n'
                   '}\n')
    cfg_marked = ('_KNOBS = {\n'
                  '    "lint_fixture_impl":'
                  '  # tune-reg-ok: fixture\n'
                  '        ("E", "a", ("a", "b")),\n'
                  '}\n')
    cfg_freeform = ('_KNOBS = {\n'
                    '    "lint_fixture_impl": ("E", "a", None),\n'
                    '}\n')
    reg_has = 'register("op", "lint_fixture_impl", ("a", "b"))\n'
    reg_empty = "\n"
    drift_cases = [
        (cfg_missing, reg_empty, True),
        (cfg_missing, reg_has, False),
        (cfg_marked, reg_empty, False),
        (cfg_freeform, reg_empty, False),
    ]
    for i, (cfg, regsrc, expect) in enumerate(drift_cases):
        got = bool(check_tuning_registry(config_src=cfg,
                                         tuning_src=regsrc))
        if got != expect:
            failures += 1
            print("tuning drift fixture %d: expected flagged=%s"
                  % (i, expect), file=sys.stderr)
    # (b) consumer-literal rule, fixture files against the REAL
    # config.py candidate sets (spmv_impl is registry-owned)
    lit_cases = [
        ('IMPLS = ("segment", "cumsum", "sortscan")\n', True),
        ('IMPLS = ("segment", "cumsum", "sortscan")'
         '  # tune-reg-ok: fixture\n', False),
        ('OTHER = ("alpha", "beta", "gamma")\n', False),
    ]
    with tempfile.TemporaryDirectory() as tmp:
        fixdir = os.path.join(tmp, "raft_tpu")
        os.makedirs(fixdir)
        for i, (srcf, expect) in enumerate(lit_cases):
            path = os.path.join(fixdir, "tunefix%d.py" % i)
            with open(path, "w", encoding="utf-8") as f:
                f.write(srcf)
            probs = [p for p in check_file(path, repo_root=tmp)
                     if "registry-owned" in p]
            if bool(probs) != expect:
                failures += 1
                print("tuning literal fixture %d: expected flagged=%s,"
                      " got %r" % (i, expect, probs), file=sys.stderr)
    print("tuning-registry lint selftest: %d fixtures, %d failures"
          % (len(drift_cases) + len(lit_cases), failures),
          file=sys.stderr)
    return failures


def main():
    if "--selftest" in sys.argv[1:]:
        return 1 if selftest() else 0
    files = list(EXTRA)
    for root in ROOTS:
        for dirpath, dirnames, filenames in os.walk(os.path.join(REPO, root)):
            dirnames[:] = [d for d in dirnames
                           if d not in ("__pycache__", "html")]
            files.extend(os.path.join(dirpath, f)
                         for f in filenames if f.endswith(".py"))
    problems = []
    for f in files:
        problems.extend(check_file(os.path.join(REPO, f)))
    # cross-file: config choices-knobs vs the candidate registry
    problems.extend(check_tuning_registry())
    for p in problems:
        print(p)
    print(f"checked {len(files)} files, {len(problems)} problems",
          file=sys.stderr)
    return 1 if problems else 0


if __name__ == "__main__":
    sys.exit(main())
