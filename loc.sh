#!/usr/bin/env bash
# Reproducible non-test source LoC count (the diagnostic VERDICT.md
# reports each round; recorded here so the number is re-derivable).
# Counts: the raft_tpu package, the C++ runtime, and the repo-root
# entry points (bench, graft entry).  Excludes tests/, docs/, and
# round artifacts.  Single wc over one concatenated stream — immune to
# xargs argument batching.
set -euo pipefail
cd "$(dirname "$0")"
{
  find raft_tpu cpp -type f \( -name '*.py' -o -name '*.cpp' -o -name '*.hpp' \
    -o -name '*.h' -o -name 'CMakeLists.txt' \) -print0 | xargs -0 cat
  cat bench.py __graft_entry__.py
} | wc -l
