#!/usr/bin/env bash
# Test runner pinning the simulated-mesh environment (the reference's CI
# analog, ci/gpu/build.sh:116).  tests/conftest.py forces the platform
# in-process (sitecustomize may pre-import jax against a real
# accelerator), so these env vars are belt-and-braces for subprocesses
# spawned by tests.
set -euo pipefail
cd "$(dirname "$0")"
export XLA_FLAGS="${XLA_FLAGS:-} --xla_force_host_platform_device_count=8"
export RAFT_TPU_TEST_PLATFORM="${RAFT_TPU_TEST_PLATFORM:-cpu}"
# --faults: only the comms fault-injection/resilience suite (the suite
# also runs as part of the default invocation; see stress.sh faults for
# the seed-rotating loop)
if [[ "${1:-}" == "--faults" ]]; then
    shift
    exec python -m pytest tests/ -q -m faults "$@"
fi
# --metrics: only the metrics/profiler/observability suite (also part
# of the default invocation)
if [[ "${1:-}" == "--metrics" ]]; then
    shift
    exec python -m pytest tests/test_metrics_profiler.py -q "$@"
fi
# --serve: only the serving-layer suite (also part of the default
# invocation; see stress.sh serve for the concurrency-shaking loop)
if [[ "${1:-}" == "--serve" ]]; then
    shift
    exec python -m pytest tests/ -q -m serve "$@"
fi
# --tuning: only the autotuner/candidate-registry suite (resolution
# ladder, table load/stale/corrupt, sweep smoke; also part of the
# default invocation)
if [[ "${1:-}" == "--tuning" ]]; then
    shift
    exec python -m pytest tests/ -q -m tuning "$@"
fi
# --persist: only the durability suite (snapshot/WAL round trips, the
# corruption matrix, crash-restart recovery, scrubbing; also part of
# the default invocation)
if [[ "${1:-}" == "--persist" ]]; then
    shift
    exec python -m pytest tests/ -q -m persist "$@"
fi
# --ops: only the ops-plane suite (embedded HTTP endpoint, program
# cost inventory, anomaly sentinel, scrape-under-traffic; also part
# of the default invocation)
if [[ "${1:-}" == "--ops" ]]; then
    shift
    exec python -m pytest tests/ -q -m ops "$@"
fi
# --fleet: only the multi-process fleet suite (router fan-out/merge,
# crash-restart rejoin under live ingestion, drain choreography,
# chaos harness; also part of the default invocation — see
# stress.sh fleet for the seed-rotating chaos loop)
if [[ "${1:-}" == "--fleet" ]]; then
    shift
    exec python -m pytest tests/ -q -m fleet "$@"
fi
exec python -m pytest tests/ -q "$@"
