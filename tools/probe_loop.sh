#!/usr/bin/env bash
# Probe the accelerator endpoint until it answers; one timestamped line
# per attempt.  Run detached; tail the log to see recovery.
LOG="${1:-/root/repo/.probe_r04.log}"
while true; do
  T=$(date +%H:%M:%S)
  OUT=$(timeout 45 python /root/repo/tools/tpu_probe.py 2>&1)
  RC=$?   # the probe's status, not a pipeline tail's
  OUT=$(printf '%s\n' "$OUT" | tail -1)
  echo "$T rc=$RC $OUT" >> "$LOG"
  if [ "$RC" -eq 0 ]; then
    echo "$T BACKEND UP" >> "$LOG"
  fi
  sleep 45
done
