#!/usr/bin/env bash
# Unattended recovery pipeline: wait for the accelerator endpoint, then
# run the measurement sequence in priority order, logging everything.
#
# Probe policy (r4 wedge forensics): the endpoint has two failure
# modes — connection-refused (the probe fails on its own in ~45 s;
# harmless to retry) and accepted-but-hung RPC (the probe hangs
# indefinitely; only a client kill frees our side, and kills-mid-RPC
# are the suspected cause of wedge persistence).  So each probe is
# allowed 15 min to finish or fail by itself; only a >15 min hang is
# abandoned, as the stall backstop.
#
# Priority on recovery: the full bench FIRST — its ladder banks the
# small rungs incrementally and already contains every open
# measurement question (pallas_check chained comparison, chunked vs
# topk selection, the 1M north star), so it extracts the most evidence
# per minute of endpoint health.  Tool scripts run after.
#
# Budget policy: the driver's round-end bench must find a free
# endpoint and a warm compile cache, never a colliding client.  Full
# budget only while the session has comfortable headroom (before
# ~13:00 local, this session runs 03:14-15:14); later recoveries get a short warm-the-top-rungs run;
# past 14:15 the pipeline stands down entirely.
cd /root/repo
LOG=.recovery.log
echo "=== pipeline start $(date +%H:%M:%S) ===" >> "$LOG"
while true; do
  NOW=$(date +%H%M)
  if [ "$NOW" -ge 1415 ] && [ "$NOW" -lt 2300 ]; then
    echo "$(date +%H:%M:%S) past 14:15 — stand down for the driver" >> "$LOG"
    exit 0
  fi
  timeout 900 python tools/tpu_probe.py >> "$LOG" 2>&1
  RC=$?   # capture IMMEDIATELY: both `if` compounds and $(date)
          # substitutions reset $? (two prior bugs here)
  [ "$RC" -eq 0 ] && break
  echo "$(date +%H:%M:%S) probe failed (rc=$RC); sleeping 120" >> "$LOG"
  sleep 120
done
echo "=== BACKEND UP $(date +%H:%M:%S) ===" >> "$LOG"

NOW=$(date +%H%M)
if [ "$NOW" -ge 1300 ] && [ "$NOW" -lt 2300 ]; then BUDGET=600; else BUDGET=2700; fi
echo "=== full bench (budget $BUDGET) ===" >> "$LOG"
RAFT_TPU_BENCH_BUDGET=$BUDGET python bench.py > .bench_r04_final.json \
  2> .bench_r04_final.err
echo "bench rc=$? at $(date +%H:%M:%S)" >> "$LOG"

# tool deadline pinned to the 14:15 stand-down wall clock (minus a
# 10-min drain) so a tool started late can never hold the endpoint
# into the driver's round-end window — tools honor
# RAFT_TPU_BENCH_DEADLINE via bench._time_chained and only setdefault
# their own
export RAFT_TPU_BENCH_DEADLINE=$(date -d "14:05" +%s)
NOW=$(date +%H%M)
if [ "$NOW" -lt 1345 ]; then
  echo "=== knn_kernel_sweep ===" >> "$LOG"
  python tools/knn_kernel_sweep.py > .knn_sweep.log 2>&1
  echo "knn_kernel_sweep rc=$? at $(date +%H:%M:%S)" >> "$LOG"
fi
NOW=$(date +%H%M)
if [ "$NOW" -lt 1345 ]; then
  echo "=== select_variants ===" >> "$LOG"
  python tools/select_variants.py > .select_variants.log 2>&1
  echo "select_variants rc=$? at $(date +%H:%M:%S)" >> "$LOG"
fi
echo "=== pipeline done $(date +%H:%M:%S) ===" >> "$LOG"
