#!/usr/bin/env bash
# Full unattended recovery pipeline: wait for the backend, then run the
# measurement sequence in priority order, logging everything.  Never
# kills a client mid-RPC; each stage runs to completion.
cd /root/repo
LOG=.recovery.log
echo "=== pipeline start $(date +%H:%M:%S) ===" >> "$LOG"
while true; do
  if python tools/tpu_probe.py >> "$LOG" 2>&1; then break; fi
  echo "$(date +%H:%M:%S) probe failed; sleeping 90" >> "$LOG"
  sleep 90
done
echo "=== BACKEND UP $(date +%H:%M:%S); steady_knn ===" >> "$LOG"
python tools/steady_knn.py > .steady_knn.log 2>&1
echo "steady_knn rc=$? at $(date +%H:%M:%S)" >> "$LOG"
echo "=== select_variants ===" >> "$LOG"
python tools/select_variants.py > .select_variants.log 2>&1
echo "select_variants rc=$? at $(date +%H:%M:%S)" >> "$LOG"
echo "=== full bench (warm cache for the driver) ===" >> "$LOG"
RAFT_TPU_BENCH_BUDGET=2700 python bench.py > .bench_r04_final.json \
  2> .bench_r04_final.err
echo "bench rc=$? at $(date +%H:%M:%S)" >> "$LOG"
echo "=== pipeline done ===" >> "$LOG"
