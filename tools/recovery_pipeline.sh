#!/usr/bin/env bash
# Full unattended recovery pipeline: wait for the backend, then run the
# measurement sequence in priority order, logging everything.  Never
# kills a client mid-RPC; each stage runs to completion.
cd /root/repo
LOG=.recovery.log
echo "=== pipeline start $(date +%H:%M:%S) ===" >> "$LOG"
while true; do
  if python tools/tpu_probe.py >> "$LOG" 2>&1; then break; fi
  echo "$(date +%H:%M:%S) probe failed; sleeping 90" >> "$LOG"
  sleep 90
done
echo "=== BACKEND UP $(date +%H:%M:%S); steady_knn ===" >> "$LOG"
python tools/steady_knn.py > .steady_knn.log 2>&1
echo "steady_knn rc=$? at $(date +%H:%M:%S)" >> "$LOG"
echo "=== select_variants ===" >> "$LOG"
python tools/select_variants.py > .select_variants.log 2>&1
echo "select_variants rc=$? at $(date +%H:%M:%S)" >> "$LOG"
echo "=== full bench (warm cache for the driver) ===" >> "$LOG"
# never collide with the driver's own round-end bench: full budget only
# while the session has comfortable headroom (driver takes over ~02:49);
# late recovery gets a short warm-the-top-rungs run instead
HOUR=$(date +%H)
BUDGET=2700
if [ "$HOUR" -ge 1 ] && [ "$HOUR" -lt 12 ]; then BUDGET=600; fi
RAFT_TPU_BENCH_BUDGET=$BUDGET python bench.py > .bench_r04_final.json \
  2> .bench_r04_final.err
echo "bench (budget $BUDGET) rc=$? at $(date +%H:%M:%S)" >> "$LOG"
echo "=== pipeline done ===" >> "$LOG"
