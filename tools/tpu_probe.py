"""Minimal TPU backend probe: timestamped init log to stdout."""
import time, sys
t0 = time.time()
def log(e):
    print(f"[{time.time()-t0:8.1f}s] {e}", flush=True)
log("start; importing jax")
import jax
log("jax imported")
import jax.numpy as jnp
devs = jax.devices()
log(f"devices: {[str(d) for d in devs]} platform={devs[0].platform} kind={devs[0].device_kind}")
x = jnp.ones((128, 128), jnp.float32)
v = float((x @ x)[0, 0])
log(f"first matmul done: {v}")
