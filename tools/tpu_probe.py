"""Minimal TPU backend probe: timestamped init log to stdout."""
import time, sys
t0 = time.time()
def log(e):
    print(f"[{time.time()-t0:8.1f}s] {e}", flush=True)
import os
REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
os.environ.setdefault("JAX_COMPILATION_CACHE_DIR",
                      os.path.join(REPO, ".jax_cache"))
log("start; importing jax")
import jax
log("jax imported")
import jax.numpy as jnp
from bench import _enable_compile_cache
_enable_compile_cache()
devs = jax.devices()
log(f"devices: {[str(d) for d in devs]} platform={devs[0].platform} kind={devs[0].device_kind}")
x = jnp.ones((128, 128), jnp.float32)
v = float((x @ x)[0, 0])
log(f"first matmul done: {v}")
