"""One-off steady-state timing of kNN path variants on the live backend.

Isolates where the time goes at the 100k x 4096 x 128 k=100 shape:
matmul-only scan (selection removed), lax.top_k vs approx_max_k
selection, tile size sweep, the compiled Pallas kernel, and bf16 MXU
passes.  Prints one line per variant; informs which impl the bench
ladder should default to.
"""

import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
os.environ.setdefault("JAX_COMPILATION_CACHE_DIR",
                      os.path.join(REPO, ".jax_cache"))

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
from jax import lax  # noqa: E402


def timeit(fn, *args, iters=3):
    out = jax.block_until_ready(fn(*args))  # compile + warm
    t0 = time.perf_counter()
    for _ in range(iters):
        out = jax.block_until_ready(fn(*args))
    dt = (time.perf_counter() - t0) / iters
    return dt, out


def main():
    dev = jax.devices()[0]
    print(f"backend: {dev.platform} ({dev.device_kind})", flush=True)
    n, nq, d, k = 100_000, 4096, 128, 100
    x = jax.random.normal(jax.random.PRNGKey(0), (n, d), jnp.float32)
    q = jax.random.normal(jax.random.PRNGKey(1), (nq, d), jnp.float32)

    def scan_variant(tile_n, select, prec="highest"):
        n_tiles = -(-n // tile_n)
        n_pad = n_tiles * tile_n
        x_p = jnp.pad(x, ((0, n_pad - n), (0, 0)))
        xn = (x_p * x_p).sum(1)

        @jax.jit
        def run(qq):
            qn = (qq * qq).sum(1)

            def step(carry, t):
                best_d, best_i = carry
                x_t = lax.dynamic_slice_in_dim(x_p, t * tile_n, tile_n, 0)
                xn_t = lax.dynamic_slice_in_dim(xn, t * tile_n, tile_n, 0)
                g = lax.dot_general(qq, x_t, (((1,), (1,)), ((), ())),
                                    precision=prec)
                dd = qn[:, None] + xn_t[None, :] - 2.0 * g
                valid = (t * tile_n + jnp.arange(tile_n)) < n
                dd = jnp.where(valid[None, :], dd, jnp.inf)
                if select == "none":
                    return (jnp.minimum(best_d, dd[:, :k]), best_i), None
                if select == "topk":
                    tv, ti = lax.top_k(-dd, k)
                elif select == "approx":
                    tv, ti = lax.approx_max_k(-dd, k, recall_target=0.95)
                elif select == "approx1":
                    tv, ti = lax.approx_max_k(-dd, k, recall_target=1.0)
                ti = (t * tile_n + ti).astype(jnp.int32)
                cd = jnp.concatenate([best_d, -tv], axis=1)
                ci = jnp.concatenate([best_i, ti], axis=1)
                mv, mp = lax.top_k(-cd, k)
                return (-mv, jnp.take_along_axis(ci, mp, axis=1)), None

            init = (jnp.full((nq, k), jnp.inf, jnp.float32),
                    jnp.zeros((nq, k), jnp.int32))
            (bd, bi), _ = lax.scan(step, init, jnp.arange(n_tiles))
            return bd, bi

        return run

    for name, fn in [
        ("matmul_only_t8k", scan_variant(8192, "none")),
        ("topk_t8k", scan_variant(8192, "topk")),
        ("approx95_t8k", scan_variant(8192, "approx")),
        ("approx100_t8k", scan_variant(8192, "approx1")),
        ("topk_t32k", scan_variant(32768, "topk")),
        ("approx95_t32k", scan_variant(32768, "approx")),
        ("approx95_t100k", scan_variant(100_000, "approx")),
        ("topk_t8k_bf16", scan_variant(8192, "topk", "default")),
    ]:
        try:
            dt, _ = timeit(fn, q)
            print(f"{name:18s} {dt*1e3:9.2f} ms/batch  {nq/dt:10,.0f} QPS",
                  flush=True)
        except Exception as e:
            print(f"{name:18s} FAILED {type(e).__name__}: {str(e)[:160]}",
                  flush=True)

    from raft_tpu.spatial.fused_l2_knn import fused_l2_knn

    for impl in ("xla", "pallas"):
        try:
            dt, _ = timeit(lambda qq, i=impl: fused_l2_knn(x, qq, k, impl=i),
                           q)
            print(f"fused_{impl:12s} {dt*1e3:9.2f} ms/batch  "
                  f"{nq/dt:10,.0f} QPS", flush=True)
        except Exception as e:
            print(f"fused_{impl:12s} FAILED {type(e).__name__}: "
                  f"{str(e)[:160]}", flush=True)


if __name__ == "__main__":
    main()
