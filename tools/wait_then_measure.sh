#!/usr/bin/env bash
# Gentle recovery watcher: probe the backend every 90 s (each probe is
# allowed to finish or fail on its own; no kills mid-RPC), and the
# moment one succeeds, run the steady-state kNN measurement.
LOG="${1:-/root/repo/.wait_measure.log}"
cd /root/repo
while true; do
  T=$(date +%H:%M:%S)
  if python tools/tpu_probe.py >> "$LOG" 2>&1; then
    echo "$T BACKEND UP — running steady_knn" >> "$LOG"
    python tools/steady_knn.py > .steady_knn.log 2>&1
    echo "$T steady_knn rc=$? done" >> "$LOG"
    break
  fi
  echo "$T probe failed; sleeping" >> "$LOG"
  sleep 90
done
