"""Part 2 of the timing cross-check: WHY do chained (100 ms) and wall
(1800 ms) disagree on the same 100k kNN call?

Hypotheses tested, all at nq=1024, n=100k, d=128, k=100, impl=xla:
  a. dead-code: chained keeps only sum(dists), so the index half of the
     selection (variadic sorts, gathers) is pruned -> wall-time a
     sum(dists)-only jit and compare;
  b. output-fetch: wall pays a (nq,k) device->host fetch per call ->
     wall-time with a device-resident scalar output;
  c. chained undercount: force BOTH outputs live in the chain.

    python tools/timing_xcheck2.py > .timing_xcheck2.log 2>&1
"""

import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
os.environ.setdefault("JAX_COMPILATION_CACHE_DIR",
                      os.path.join(REPO, ".jax_cache"))
os.environ.setdefault("RAFT_TPU_BENCH_DEADLINE", str(time.time() + 1800))

T0 = time.time()


def log(msg):
    print(f"[{time.time()-T0:7.1f}s] {msg}", flush=True)


def wall(fn, *args):
    import jax

    jax.block_until_ready(fn(*args))
    ts = []
    for _ in range(4):
        t0 = time.perf_counter()
        jax.block_until_ready(fn(*args))
        ts.append(time.perf_counter() - t0)
    return min(ts)


def main():
    import jax
    import jax.numpy as jnp

    from bench import _time_chained
    from raft_tpu.spatial.fused_l2_knn import fused_l2_knn

    dev = jax.devices()[0]
    log(f"backend: {dev.platform} ({dev.device_kind})")

    n, nq, d, k = 100_000, 1024, 128, 100
    x = jax.random.normal(jax.random.PRNGKey(0), (n, d), jnp.float32)
    q = jax.random.normal(jax.random.PRNGKey(1), (nq, d), jnp.float32)
    jax.block_until_ready((x, q))

    full = jax.jit(lambda qq: fused_l2_knn(x, qq, k, impl="xla"))
    dist_sum = jax.jit(
        lambda qq: fused_l2_knn(x, qq, k, impl="xla")[0].sum())
    both_sum = jax.jit(lambda qq: (
        fused_l2_knn(x, qq, k, impl="xla")[0].sum()
        + fused_l2_knn(x, qq, k, impl="xla")[1].sum()))

    dt = wall(full, q)
    log(f"wall full (d,i) out : {dt*1e3:9.1f} ms  {nq/dt:10,.0f} QPS")
    dt = wall(dist_sum, q)
    log(f"wall sum(d) only    : {dt*1e3:9.1f} ms  {nq/dt:10,.0f} QPS")
    dt = wall(both_sum, q)
    log(f"wall sum(d)+sum(i)  : {dt*1e3:9.1f} ms  {nq/dt:10,.0f} QPS")

    def step_d(qq):
        return fused_l2_knn(x, qq, k, impl="xla")[0]

    def step_di(qq):
        dd, ii = fused_l2_knn(x, qq, k, impl="xla")
        return dd + ii.astype(dd.dtype)

    dt = _time_chained(step_d, q, 2)
    log(f"chained d-only      : {dt*1e3:9.1f} ms  {nq/dt:10,.0f} QPS")
    dt = _time_chained(step_di, q, 2)
    log(f"chained d+i live    : {dt*1e3:9.1f} ms  {nq/dt:10,.0f} QPS")


if __name__ == "__main__":
    main()
