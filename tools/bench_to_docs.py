"""Render a bench JSON report as markdown table rows for BASELINE.md /
README.  Reads the report path given as argv[1] (default
.bench_r04_final.json) and prints the rows; doc edits stay a human
decision.
"""

import json
import sys


def fmt_rung(name, r):
    if not isinstance(r, dict):
        return None
    dev = r.get("device", "?")
    mfu = r.get("mfu") or {}
    mfu_s = ""
    if mfu.get("mfu") is not None:
        mfu_s = f", mfu {mfu['mfu']:.3f}" if isinstance(
            mfu.get("mfu"), float) else ""
    if "qps" in r:
        extra = ""
        if "recall_at_k_vs_exact" in r:
            extra = f", recall {r['recall_at_k_vs_exact']}"
        if "recall_at_10_vs_exact" in r:
            extra = f", recall@10 {r['recall_at_10_vs_exact']}"
        return (f"| {name} | {r['qps']:,.0f} QPS"
                f" ({r.get('seconds_per_batch', '?')} s/batch{extra}{mfu_s})"
                f" | {dev} |")
    if "gpairs_per_sec" in r:
        return (f"| {name} | {r['gpairs_per_sec']} Gpairs/s"
                f" ({r.get('metric', '')}{mfu_s}) | {dev} |")
    if "gemm_tflops" in r:
        return f"| {name} | {r['gemm_tflops']} TFLOP/s{mfu_s} | {dev} |"
    if "seconds_incl_compile" in r:
        return (f"| {name} | {r['seconds_incl_compile']} s incl compile"
                f" | {dev} |")
    if "seconds" in r:
        return f"| {name} | {r['seconds']} s steady | {dev} |"
    return None


def main():
    path = sys.argv[1] if len(sys.argv) > 1 else ".bench_r04_final.json"
    rep = json.load(open(path))
    print(f"headline: {rep['metric']} = {rep['value']} {rep['unit']}"
          f" (vs_baseline {rep['vs_baseline']})\n")
    print("| rung | result | device |\n|---|---|---|")
    det = rep.get("detail", {})
    for name, r in det.items():
        if name in ("init_log", "cpu_fallback", "errors", "skipped",
                    "fallback"):
            continue
        row = fmt_rung(name, r)
        if row:
            print(row)
    if "cpu_fallback" in det:
        print("\nCPU fallback child:")
        for name, r in det["cpu_fallback"].items():
            row = fmt_rung(name, r)
            if row:
                print(row)


if __name__ == "__main__":
    main()
