#!/usr/bin/env python
"""Render raft_tpu flight-recorder traces (docs/OBSERVABILITY.md
"Flight recorder & request tracing").

Two renderings of the same event stream:

- **Chrome trace-event JSON** (``--chrome out.json``): the format
  chrome://tracing and Perfetto (https://ui.perfetto.dev) open
  directly.  Request brackets (queue wait = admitted→batch_formed,
  execute = execute_launch→execute_ready, total = admitted→terminal)
  become complete ("X") slices, one track per trace_id; everything
  else (hedges, requeues, breaker transitions, compactions) becomes
  instant events; system events without a trace_id land on a
  per-service ``system`` track.
- **Terminal waterfall** (``--trace-id N``): one request's timeline as
  an offset-annotated bar chart — the "why was THIS request slow"
  screen (``tools/loadgen.py --trace`` prints the same rendering for
  the slowest requests of a run).
- **Fleet waterfall**: a ``/fleet/debug/trace/<id>`` joined payload
  (docs/OBSERVABILITY.md "Fleet tracing") renders as ONE
  clock-aligned cross-process waterfall — router hops and worker
  timelines on a shared router-clock axis, plus the gapless hop
  tiling (dispatch → network out → worker → network back → merge).
  ``--chrome`` exports the same join as Perfetto tracks, one process
  track per hop.  The payload shape is auto-detected.

Input is any flight dump JSON: ``FlightRecorder.dump_to()`` output
(``{"events": [...], "blackboxes": [...]}``), a single black-box dump
(``{"reason", "events"}``), a bare event list, or a fleet-join
payload (``{"fleet", "spans", ...}``).  Events are dicts with at
least ``ts`` (monotonic seconds) and ``kind``; see the event
vocabulary table in docs/OBSERVABILITY.md.

Usage:
    python tools/trace_report.py dump.json                # summary
    python tools/trace_report.py dump.json --trace-id 17  # waterfall
    python tools/trace_report.py dump.json --chrome trace.json
    python tools/trace_report.py joined.json              # fleet view

Importable: :func:`to_chrome_trace`, :func:`render_waterfall`,
:func:`render_fleet_waterfall`, :func:`fleet_to_chrome_trace`,
:func:`trace_ids` (loadgen and tests reuse them).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Dict, List, Optional

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# bracket pairs rendered as complete slices: name -> (open kind, close
# kind); "total" additionally closes on any terminal kind
BRACKETS = {
    "queue": ("admitted", "batch_formed"),
    "execute": ("execute_launch", "execute_ready"),
}
TERMINALS = ("resolved", "expired", "failed")


def load_events(obj) -> List[dict]:
    """Events out of any flight dump shape (module doc)."""
    if isinstance(obj, list):
        return list(obj)
    if isinstance(obj, dict):
        if "events" in obj:
            return list(obj["events"])
        if "ring" in obj:
            return list(obj["ring"])
    raise SystemExit("unrecognized flight dump shape (want a list of "
                     "events, or a dict with 'events')")


def event_trace_ids(ev: dict) -> List[int]:
    """The trace ids an event belongs to: its own ``trace_id``, or —
    for a shared batch-level ring event — the rider list the recorder
    stamped as ``traces`` (empty = a system event)."""
    tid = ev.get("trace_id")
    if tid is not None:
        return [int(tid)]
    return [int(t) for t in ev.get("traces", ())]


def trace_ids(events: List[dict]) -> List[int]:
    """Distinct trace ids present, admission order."""
    seen: Dict[int, None] = {}
    for ev in events:
        for tid in event_trace_ids(ev):
            seen.setdefault(tid, None)
    return list(seen)


def _by_trace(events: List[dict]) -> Dict[int, List[dict]]:
    out: Dict[int, List[dict]] = {}
    for ev in events:
        for tid in event_trace_ids(ev):
            out.setdefault(tid, []).append(ev)
    return out


def to_chrome_trace(events: List[dict]) -> List[dict]:
    """Chrome trace-event JSON objects (the ``traceEvents`` array;
    Perfetto accepts the bare array too).  Timestamps are microseconds
    relative to the earliest event."""
    if not events:
        return []
    t0 = min(float(ev["ts"]) for ev in events)

    def us(ts: float) -> float:
        return round((float(ts) - t0) * 1e6, 1)

    out: List[dict] = []
    for tid, evs in sorted(_by_trace(events).items()):
        svc = next((e.get("service") for e in evs
                    if e.get("service")), "serve")
        track = "trace %d" % tid
        opens: Dict[str, float] = {}
        first_ts = float(evs[0]["ts"])
        for ev in evs:
            kind = ev["kind"]
            for name, (ko, kc) in BRACKETS.items():
                if kind == ko:
                    opens[name] = float(ev["ts"])
                elif kind == kc and name in opens:
                    start = opens.pop(name)
                    out.append({"name": name, "ph": "X", "pid": svc,
                                "tid": track, "ts": us(start),
                                "dur": round(
                                    (float(ev["ts"]) - start) * 1e6,
                                    1)})
            args = {k: v for k, v in ev.items()
                    if k not in ("ts", "kind")}
            out.append({"name": kind, "ph": "i", "s": "t", "pid": svc,
                        "tid": track, "ts": us(ev["ts"]), "args": args})
            if kind in TERMINALS:
                out.append({"name": "request", "ph": "X", "pid": svc,
                            "tid": track, "ts": us(first_ts),
                            "dur": round(
                                (float(ev["ts"]) - first_ts) * 1e6, 1),
                            "args": {"terminal": kind}})
    for ev in events:
        if not event_trace_ids(ev):
            svc = ev.get("service") or "system"
            args = {k: v for k, v in ev.items()
                    if k not in ("ts", "kind")}
            out.append({"name": ev["kind"], "ph": "i", "s": "g",
                        "pid": svc, "tid": "system",
                        "ts": us(ev["ts"]), "args": args})
    out.sort(key=lambda e: e["ts"])
    return out


def render_waterfall(timeline: List[dict], width: int = 48) -> str:
    """One trace's timeline as a terminal waterfall: per event, the
    offset from admission, a position marker scaled over the request's
    total duration, the kind, and the load-bearing attrs."""
    if not timeline:
        return "(empty trace)"
    t0 = float(timeline[0]["ts"])
    t1 = float(timeline[-1]["ts"])
    span = max(t1 - t0, 1e-9)
    head = timeline[0]
    lines = ["trace %s  service=%s tenant=%s  total=%.3fms"
             % (head.get("trace_id", "?"), head.get("service", "?"),
                head.get("tenant", "?"), span * 1e3)]
    for ev in timeline:
        off = float(ev["ts"]) - t0
        pos = min(width - 1, int(round(off / span * (width - 1))))
        bar = "·" * pos + "█"
        attrs = {k: v for k, v in ev.items()
                 if k not in ("ts", "kind", "service", "tenant",
                              "trace_id", "traces") and v is not None}
        attr_s = " ".join("%s=%s" % kv for kv in sorted(attrs.items()))
        lines.append("  %9.3fms  %-*s %-16s %s"
                     % (off * 1e3, width + 1, bar, ev["kind"], attr_s))
    return "\n".join(lines)


def is_fleet_join(obj) -> bool:
    """True when ``obj`` is a ``/fleet/debug/trace/<id>`` joined
    payload rather than a flat flight dump."""
    return (isinstance(obj, dict) and "fleet" in obj
            and "spans" in obj)


def _bar(t0: float, t1: float, lo: float, span: float,
         width: int) -> str:
    """A ``[t0, t1]`` extent as a fixed-width bar over ``[lo,
    lo+span]``."""
    p0 = min(width - 1, max(0, int((t0 - lo) / span * (width - 1))))
    p1 = min(width - 1, max(p0, int((t1 - lo) / span * (width - 1))))
    return "·" * p0 + "█" * (p1 - p0 + 1) + "·" * (width - 1 - p1)


def render_fleet_waterfall(joined: dict, width: int = 48) -> str:
    """A joined fleet trace as one terminal waterfall: alignment
    header, per-hop summary, the gapless hop tiling, then every span
    (router clock, process-labelled)."""
    from raft_tpu.fleet import tracing

    spans = list(joined.get("spans") or ())
    if not spans:
        return ("fleet trace %s: no spans (expired from the ring, or "
                "never admitted)" % joined.get("fleet"))
    lo = min(float(e["ts"]) for e in spans)
    hi = max(float(e["ts"]) for e in spans)
    span = max(hi - lo, 1e-9)
    lines = ["fleet trace %s  terminal=%s  total=%.3fms  workers=%d%s"
             % (joined.get("fleet"), joined.get("terminal"),
                span * 1e3, len(joined.get("hops") or ()),
                "  [PARTIAL]" if joined.get("partial") else "")]
    for wid, a in sorted((joined.get("align") or {}).items()):
        lines.append("  align %-8s offset=%+.3fms rtt=%.3fms "
                     "traces=%s gen=%s"
                     % (wid, a.get("offset_s", 0.0) * 1e3,
                        a.get("rtt_s", 0.0) * 1e3,
                        a.get("traces"), a.get("generation")))
    for wid, hop in sorted((joined.get("hops") or {}).items()):
        lines.append("  hop   %-8s attempts=%d network=%.3fms "
                     "server=%.3fms"
                     % (wid, hop.get("attempts", 0),
                        hop.get("network_s", 0.0) * 1e3,
                        hop.get("server_s", 0.0) * 1e3))
    segs = tracing.hop_segments(joined)
    if segs:
        lines.append("  -- hop tiling (gapless boundaries) --")
        for seg in segs:
            lines.append(
                "  %9.3fms  %s %-8s %-12s %.3fms"
                % ((seg["t0"] - lo) * 1e3,
                   _bar(seg["t0"], seg["t1"], lo, span, width),
                   seg["proc"], seg["name"],
                   (seg["t1"] - seg["t0"]) * 1e3))
    lines.append("  -- spans (router clock) --")
    for ev in spans:
        off = float(ev["ts"]) - lo
        pos = min(width - 1, int(round(off / span * (width - 1))))
        bar = "·" * pos + "█"
        attrs = {k: v for k, v in ev.items()
                 if k not in ("ts", "kind", "service", "tenant",
                              "trace_id", "traces", "proc")
                 and v is not None}
        attr_s = " ".join("%s=%s" % kv for kv in sorted(attrs.items()))
        lines.append("  %9.3fms  %-*s %-8s %-16s %s"
                     % (off * 1e3, width + 1, bar,
                        ev.get("proc", "?"), ev["kind"], attr_s))
    for prob in joined.get("problems") or ():
        lines.append("  !! %s" % prob)
    return "\n".join(lines)


def fleet_to_chrome_trace(joined: dict) -> List[dict]:
    """A joined fleet trace as Chrome trace-event JSON: one Perfetto
    process track per hop (router + each worker), the gapless hop
    tiling as complete slices, every span as an instant event, and
    the request total on the router track."""
    from raft_tpu.fleet import tracing

    spans = list(joined.get("spans") or ())
    if not spans:
        return []
    lo = min(float(e["ts"]) for e in spans)

    def us(ts: float) -> float:
        return round((float(ts) - lo) * 1e6, 1)

    out: List[dict] = []
    for seg in tracing.hop_segments(joined):
        out.append({"name": seg["name"], "ph": "X",
                    "pid": seg["proc"], "tid": "hops",
                    "ts": us(seg["t0"]),
                    "dur": round((seg["t1"] - seg["t0"]) * 1e6, 1)})
    admitted = None
    terminal_ts = None
    for ev in spans:
        proc = ev.get("proc", "?")
        track = ("trace %s" % ev["trace_id"]
                 if ev.get("trace_id") is not None else "events")
        args = {k: v for k, v in ev.items()
                if k not in ("ts", "kind", "proc")}
        out.append({"name": ev["kind"], "ph": "i", "s": "t",
                    "pid": proc, "tid": track, "ts": us(ev["ts"]),
                    "args": args})
        if proc == "router":
            if ev["kind"] == "fleet_admitted":
                admitted = float(ev["ts"])
            elif ev["kind"] == joined.get("terminal"):
                terminal_ts = float(ev["ts"])
    if admitted is not None and terminal_ts is not None:
        out.append({"name": "fleet request", "ph": "X",
                    "pid": "router", "tid": "hops",
                    "ts": us(admitted),
                    "dur": round((terminal_ts - admitted) * 1e6, 1),
                    "args": {"fleet": joined.get("fleet"),
                             "terminal": joined.get("terminal")}})
    out.sort(key=lambda e: e["ts"])
    return out


def summarize(events: List[dict]) -> str:
    """Per-trace one-liners plus the system-event tail — the index a
    postmortem starts from."""
    lines = []
    traces = _by_trace(events)
    if traces:
        lines.append("== traces (%d) ==" % len(traces))
        for tid, evs in sorted(traces.items()):
            term = next((e["kind"] for e in reversed(evs)
                         if e["kind"] in TERMINALS), "in-flight")
            dur = (float(evs[-1]["ts"]) - float(evs[0]["ts"])) * 1e3
            lines.append(
                "  trace %-8d %-10s %-9s %8.3fms  %d events"
                % (tid, evs[0].get("service", "?"), term, dur,
                   len(evs)))
    system = [e for e in events if not event_trace_ids(e)]
    if system:
        lines.append("== system events (%d) ==" % len(system))
        for ev in system[-40:]:
            attrs = {k: v for k, v in ev.items()
                     if k not in ("ts", "kind", "service")}
            lines.append("  %14.6f  %-18s %-10s %s"
                         % (float(ev["ts"]), ev["kind"],
                            ev.get("service", "-"),
                            " ".join("%s=%s" % kv
                                     for kv in sorted(attrs.items()))))
    return "\n".join(lines) if lines else "(no events)"


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("dump", help="flight dump JSON "
                                 "(FlightRecorder.dump_to / black-box "
                                 "file / bare event list)")
    ap.add_argument("--trace-id", type=int, default=None,
                    help="render one trace's terminal waterfall")
    ap.add_argument("--chrome", metavar="OUT.json", default=None,
                    help="write Chrome trace-event JSON "
                         "(chrome://tracing / Perfetto)")
    args = ap.parse_args(argv)

    with open(args.dump, encoding="utf-8") as f:
        obj = json.load(f)

    if is_fleet_join(obj):
        if args.chrome:
            chrome = fleet_to_chrome_trace(obj)
            with open(args.chrome, "w", encoding="utf-8") as f:
                json.dump({"traceEvents": chrome}, f, indent=2,
                          sort_keys=True)
                f.write("\n")
            print("wrote %d chrome events to %s"
                  % (len(chrome), args.chrome))
            return 0
        print(render_fleet_waterfall(obj))
        return 0

    events = load_events(obj)

    if args.chrome:
        chrome = to_chrome_trace(events)
        with open(args.chrome, "w", encoding="utf-8") as f:
            json.dump({"traceEvents": chrome}, f, indent=2,
                      sort_keys=True)
            f.write("\n")
        print("wrote %d chrome events to %s"
              % (len(chrome), args.chrome))
        if args.trace_id is None:
            return 0
    if args.trace_id is not None:
        timeline = [e for e in events
                    if args.trace_id in event_trace_ids(e)]
        if not timeline:
            print("trace %d not in the dump (have: %s)"
                  % (args.trace_id,
                     ", ".join(map(str, trace_ids(events)[:20]))),
                  file=sys.stderr)
            return 1
        print(render_waterfall(timeline))
        return 0
    print(summarize(events))
    return 0


if __name__ == "__main__":
    sys.exit(main())
