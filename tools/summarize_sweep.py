"""Summarize a knn_kernel_sweep log: rank configs, print markdown.

    python tools/summarize_sweep.py .knn_sweep.log

Hardware-free (pure parsing).  One row per config with QPS and the
ratio to the xla_scan baseline; errors listed at the bottom so a
partially-complete sweep still summarizes.
"""

import json
import sys


def main(path):
    rows, errors, base = [], [], None
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line.startswith("{"):
                continue
            try:
                rec = json.loads(line)
            except ValueError:
                continue
            cfg = rec.get("config")
            if not cfg or cfg == "init":
                continue
            if "error" in rec:
                errors.append((cfg, rec["error"][-120:]))
                continue
            qps = rec.get("qps")
            if qps is None:
                continue
            rows.append((cfg, qps, rec.get("seconds_per_batch")))
            if cfg == "xla_scan":
                base = qps
    rows.sort(key=lambda r: -r[1])
    print("| config | QPS | s/batch | vs xla_scan |")
    print("|---|---|---|---|")
    for cfg, qps, spb in rows:
        vs = f"{qps / base:.2f}x" if base else "-"
        print(f"| {cfg} | {qps:,.0f} | {spb} | {vs} |")
    if errors:
        print("\nerrors:")
        for cfg, err in errors:
            print(f"- {cfg}: {err}")
    if rows:
        print(f"\nwinner: {rows[0][0]} ({rows[0][1]:,.0f} QPS)")


if __name__ == "__main__":
    main(sys.argv[1] if len(sys.argv) > 1 else ".knn_sweep.log")
